"""Packet-tiled Pallas round engine — lossless at scales the monolithic
kernel cannot compile.

The monolithic round kernel (:mod:`qba_tpu.ops.round_kernel`) holds the
whole ``[max_l, n_pk, size_l]`` mailbox in VMEM, which stops compiling at
the lossless slot bound for large configs (33 parties: n_pk = 2048;
reference scale sizeL = 1000) — those configs previously ran either lossy
(slot-bound overflow) or on the ~26x-slower XLA fallback (docs/PERF.md).
The reference's own mailbox buffering is unbounded (``tfg.py:337-348`` —
the Iprobe drain accepts arbitrarily many packets per round), so lossless
execution at scale is a capability gap this engine closes.

Design — two phases per round, over a *compacted packet pool*:

* **Pool layout.**  Instead of the dense ``[sender, slot]`` mailbox, the
  round's packets live compacted at the front of a capacity-``n_pool``
  pool (``n_pool = n_lieutenants * slots`` — the same lossless bound),
  in (sender, slot) lexicographic order with a per-trial ``n_sent``
  count.  Compaction preserves the engine's packet processing order
  (docs/DIVERGENCES.md D5), so verdicts stay bit-identical to the XLA
  engine; each pool entry carries its mailbox ``cell`` id
  (``sender * slots + slot``) so the per-cell attack draws
  (:func:`qba_tpu.adversary.sample_attacks_round`) keep their identity
  and the randomness matches every other engine bit for bit.

* **Phase 1 — verdict kernel (Pallas).**  A 1-D grid over packet blocks
  of ``blk`` packets streams the pool through VMEM.  Each step computes
  the full acceptance verdict for its block against every receiver
  (the same flag algebra as the monolithic kernel) and updates the
  accepted-sets ``vi`` in a revisited output block — TPU grid steps
  execute in order, so carrying ``vi`` across blocks reproduces the
  sequential first-candidate-per-order dedup (``v not in Vi``,
  ``tfg.py:294``) exactly.  Blocks at or past ``n_sent`` skip all
  compute (the pool is compacted, so occupancy concentrates in the
  leading blocks — at 33 parties a round typically fills <2 of 8
  blocks).

* **Phase 2 — rebuild (XLA).**  Slot allocation, overflow detection and
  next-round pool construction are gathers and small top-k/scatter ops
  — bandwidth-bound, no tiny-reduction pathology — so they stay in XLA:
  per receiver the accepted packets' pool indices come from one
  ``top_k``; destination offsets from an exclusive cumsum of accept
  counts; one scatter of at most ``n_lieutenants * slots`` indices
  builds the source map; everything else is a batched gather + the same
  keep/append row algebra as the monolithic kernel's tail.

Value-presence tests use per-position bit-plane masks (``ceil(w/32)``
int32 planes), exact for ``w <= 64`` — covering the 33-party north star
(w = 64) without the ``O(max_l)`` row loops.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from qba_tpu.adversary import (
    CLEAR_L_BIT,
    CLEAR_P_BIT,
    DROP_BIT,
    FORGE_BIT,
    FORGE_P_BIT,
)
from qba_tpu.config import QBAConfig
from qba_tpu.core.types import SENTINEL
from qba_tpu.diagnostics import (
    QBADemotionWarning,
    QBAProbeWarning,
    warn_and_record,
)
from qba_tpu.ops.round_kernel import CompilerParams, _lane_group
from qba_tpu.ops.verdict_algebra import (
    AllReceiverVerdict,
    VerdictAlgebra,
    accept_first_per_value,
    accept_first_per_value_all,
    accept_first_per_value_group,
    all_receiver_supported,
    make_receiver_tables,
)


def _gdt(cfg: QBAConfig):
    """The kernels' exact-integer matmul dtype for this config."""
    return (
        jnp.bfloat16 if cfg.size_l <= 256 and cfg.w <= 256
        else jnp.float32
    )


def _prec(dt):
    """Matmul precision making an integer-valued dot EXACT for values
    beyond bf16's 256-integer range.

    An f32 *dtype* does NOT buy f32 *precision*: with JAX's default
    matmul precision XLA may lower an f32 dot through single-pass bf16
    (observed on BOTH the TPU and CPU backends, and lowering-dependent —
    the same program batched differently flipped between exact and
    lossy), silently rounding integer operands > 256 to even.  Round-5
    root cause of the rebuild kernel's wrong-draw bug: the meta gather's
    cell ids (< n_pool, odd values > 256) came back decremented.  Every
    dot whose operands can exceed 256 must therefore pass
    ``Precision.HIGHEST``; bf16-operand dots with proven <= 256 values
    are exact by construction and keep the fast path.

    The "proven" part is machine-checked: ``qba-tpu lint``'s KI-3 pass
    interval-bounds every dot operand on every traced build path — the
    one-hot gathers below lint clean by structure, and removing a
    HIGHEST from a wide-operand dot (e.g. the meta gather) fails CI
    (qba_tpu/analysis/dots.py, docs/ANALYSIS.md)."""
    return jax.lax.Precision.HIGHEST if dt == jnp.float32 else None


def _verdict_block_accepts(
    *,
    variant: str,
    blk: int,
    n_rv: int,
    n_cells: int,
    slots: int,
    max_l: int,
    size_l: int,
    w: int,
    gdt,
    grp: int,
    seg_l: int,
    r0_list: list[int],
    r_off,
    r_idx,
    vals,
    lens,
    p_i32,
    meta,
    vi,
    honest_col,
    att_t,
    rv_t,
    late_t,
    tables,
    use_fp: bool = False,
):
    """The acceptance-verdict algebra for ONE packet block, as a pure
    value-level function: ``(acc [blk, n_rv] i32, new_vi [n_rv, w] i32)``
    from the block's loaded pool fields and the receivers' current
    accepted sets ``vi``.

    Shared by :func:`build_verdict_kernel` (one call per grid step,
    ``vi`` carried through the revisited ``ovi`` block) and
    :func:`build_fused_round_kernel` (a static sub-block loop at grid
    step 0, ``vi`` carried through the same revisited block) — ONE
    implementation, so the fused path is bit-identical by construction.

    ``vals`` is the block's ``max_l`` row list (each ``[blk, size_l]``
    int32), ``meta`` the packed ``[blk, 4]`` column, ``honest_col`` /
    ``att_t`` / ``rv_t`` / ``late_t`` the full cell-space draw operands
    (``n_cells`` columns — the helper selects the block's rows by cell
    id), and ``tables`` the variant's receiver tables (the
    ``(e, lip, lioob)`` lane-pack for the group family, the
    :func:`make_receiver_tables` tuple for ``"allrecv"``).  The
    group-serial accept chain accumulates into value-level row/column
    masks instead of per-receiver ref stores (no dynamic-update-slice;
    Mosaic-safe), which is bit-identical: receivers' vi rows are
    disjoint and each receiver is visited once."""
    idx_col = jax.lax.broadcasted_iota(jnp.int32, (blk, 1), 0)
    cnt_col = meta[:, META_COUNT : META_COUNT + 1]
    v_col = meta[:, META_V : META_V + 1]
    cell_col = meta[:, META_CELL : META_CELL + 1]
    sender_col = cell_col // slots  # [blk, 1]
    sent = meta[:, META_SENT : META_SENT + 1] != 0  # [blk, 1]

    # ---- Draw selection: cell-ordered -> this block's rows -----------
    # One-hot over mailbox cell ids (exact: ids < n_cells; values
    # <= 15 / < w / 0-1 are gdt-exact), like the rebuild kernel.  The
    # draw tables arrive receiver-major [n_rv, n_cells] — pad-free, and
    # the MXU contracts the rhs's dim 1 directly (an NT matmul).
    iota_cells = jax.lax.broadcasted_iota(jnp.int32, (blk, n_cells), 1)
    oh_cell = jnp.where(iota_cells == cell_col, 1.0, 0.0).astype(gdt)

    def cell_mm(tbl_t):  # [n_rv, n_cells] -> [blk, n_rv]
        return jax.lax.dot_general(
            oh_cell, tbl_t.astype(gdt),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(gdt),
        )

    def cell_col_mm(tbl):  # [n_cells, 1] column -> [blk, 1]
        return jax.lax.dot_general(
            oh_cell, tbl.astype(gdt),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(gdt),
        )

    biz = cell_col_mm(honest_col).astype(jnp.int32) == 0

    # ---- All-receiver flag algebra -----------------------------------
    act_all = cell_mm(att_t).astype(jnp.int32)  # [blk, n_rv]
    rv_all = cell_mm(rv_t).astype(jnp.int32)
    late_all = cell_mm(late_t).astype(jnp.int32)
    # Global receiver ids (r_off = 0 single-device): sender_col is a
    # global sender index, so self-delivery must compare against global
    # receiver ids too.
    lane_recv = (
        jax.lax.broadcasted_iota(jnp.int32, (blk, n_rv), 1) + r_off
    )
    dropped_all = biz & ((act_all & DROP_BIT) != 0)
    v2_all = jnp.where(biz & ((act_all & FORGE_BIT) != 0),
                       rv_all, v_col)
    clearp_all = biz & ((act_all & CLEAR_P_BIT) != 0)
    clearl_all = biz & ((act_all & CLEAR_L_BIT) != 0)
    # forge-P (strategy="split" only): statically gated so every other
    # strategy's jaxpr — and the reference bit-identity pin — is
    # untouched.
    forgep_all = (
        biz & ((act_all & FORGE_P_BIT) != 0) if use_fp else None
    )
    delivered_all = (
        ~dropped_all & (late_all == 0) & sent
        & (sender_col != lane_recv)
    )
    count_eff_all = jnp.where(clearl_all, 0, cnt_col)

    if variant == "allrecv":
        # All receivers in one batched pass (docs/PERF.md round 5).
        ar = AllReceiverVerdict(
            n_p=blk, n_rv=n_rv, max_l=max_l, size_l=size_l,
            w=w, gdt=gdt, vals=vals, lens=lens,
            count=cnt_col, p_i32=p_i32,
            tables=tuple(tables),
            r_idx=r_idx,
        )
        ok_all = ar.flags(
            v2_all, clearp_all, clearl_all, count_eff_all,
            delivered_all, forgep_all,
        )
        return accept_first_per_value_all(
            ok_all, v2_all, vi, idx_col, blk, n_rv, w
        )

    e_vals, lip_vals, lioob_vals = tables
    # The shared per-group acceptance flag algebra
    # (ops/verdict_algebra.py — one implementation for both Pallas
    # kernels).
    va = VerdictAlgebra(
        n_p=blk, grp=grp, seg_l=seg_l, max_l=max_l,
        size_l=size_l, w=w, gdt=gdt,
        vals=vals, lens=lens, count=cnt_col,
        p_i32=p_i32,
        e_vals=e_vals, lip_vals=lip_vals,
        lioob_vals=lioob_vals, r_idx=r_idx,
    )
    if variant == "group":
        # Round 6 — block-parallel first-accept reduction: the
        # lane-group loop still produces the ok flags (its MXU batching
        # over grp receivers is the win the round-4 pass bought), but
        # the dedup is ONE segmented first-index reduction over all
        # receivers instead of a per-receiver chain (docs/PERF.md
        # round 6).  The cross-block vi carry stays with the caller.
        ok_parts = []
        next_col = 0
        for gi, r0 in enumerate(r0_list):
            sl = slice(r0, r0 + grp)
            ok_g, _dup_g, _olen_g = va.group(
                gi, v2_all[:, sl], clearp_all[:, sl],
                clearl_all[:, sl], count_eff_all[:, sl],
                delivered_all[:, sl],
                None if forgep_all is None else forgep_all[:, sl],
            )
            # int32 before slicing/concatenating: Mosaic rejects i1
            # tpu.concatenate and i1 lane relayouts.
            ok_i = jnp.where(ok_g, 1, 0)
            # Tail-group overlap: keep only the columns not already
            # covered (the recomputed flags are identical either way).
            ok_parts.append(ok_i[:, next_col - r0 :])
            next_col = r0 + grp
        ok_all = (
            jnp.concatenate(ok_parts, axis=1)
            if len(ok_parts) > 1 else ok_parts[0]
        )
        return accept_first_per_value_all(
            ok_all != 0, v2_all, vi, idx_col, blk, n_rv, w,
        )

    # variant == "group-serial": the pre-round-6 accept chain,
    # accumulated into value-level masks (each receiver's row/column is
    # written exactly once; rows are disjoint, so the running vi carry
    # matches the ref-store version bit for bit).
    acc_out = jnp.zeros((blk, n_rv), jnp.int32)
    vi_cur = vi
    row_ids = jax.lax.broadcasted_iota(jnp.int32, (n_rv, w), 0)
    col_ids = jax.lax.broadcasted_iota(jnp.int32, (blk, n_rv), 1)
    done: set[int] = set()
    for gi, r0 in enumerate(r0_list):
        sl = slice(r0, r0 + grp)
        ok_g, _dup_g, _olen_g = va.group(
            gi, v2_all[:, sl], clearp_all[:, sl],
            clearl_all[:, sl], count_eff_all[:, sl],
            delivered_all[:, sl],
            None if forgep_all is None else forgep_all[:, sl],
        )
        if grp > 1 and grp * w <= 512:
            # Group-batched dedup: one [blk, grp*w]-lane pass for the
            # whole lane group (receivers' vi rows are disjoint).
            acc_cols, new_rows = accept_first_per_value_group(
                r0, grp, ok_g, v2_all[:, sl], vi_cur,
                idx_col, blk, w,
            )
            for j in range(grp):
                recv = r0 + j
                if recv in done:
                    continue
                done.add(recv)
                vi_cur = jnp.where(
                    row_ids == recv,
                    jnp.broadcast_to(
                        new_rows[j].astype(jnp.int32), (n_rv, w)
                    ),
                    vi_cur,
                )
                acc_out = jnp.where(
                    col_ids == recv,
                    jnp.broadcast_to(
                        acc_cols[j].astype(jnp.int32), (blk, n_rv)
                    ),
                    acc_out,
                )
            continue
        for j in range(grp):
            recv = r0 + j
            if recv in done:  # tail-group overlap: already done
                continue
            done.add(recv)
            acc1, new_vi1 = accept_first_per_value(
                ok_g[:, j : j + 1],
                v2_all[:, recv : recv + 1],
                vi_cur[recv : recv + 1, :], idx_col, blk, w,
            )
            vi_cur = jnp.where(
                row_ids == recv,
                jnp.broadcast_to(new_vi1.astype(jnp.int32), (n_rv, w)),
                vi_cur,
            )
            acc_out = jnp.where(
                col_ids == recv,
                jnp.broadcast_to(acc1.astype(jnp.int32), (blk, n_rv)),
                acc_out,
            )
    return acc_out, vi_cur


def build_verdict_kernel(
    cfg: QBAConfig,
    blk: int,
    *,
    interpret: bool = False,
    n_recv: int | None = None,
    out_vma: frozenset | None = None,
    variant: str = "group",
):
    """Compile phase 1: the blocked acceptance-verdict kernel.

    Returns ``verdict(round_idx, vals, lens, count, p, v, sent, cell,
    li, vi, honest_cells, attack, rand_v, late) -> (acc, vi')`` where
    the pool operands are ``[.., n_pool, ..]`` in compacted packet
    order, ``cell`` is each packet's mailbox cell id, the draw operands
    stay **mailbox-cell-ordered** ``[n_cells, n_rv]`` (the kernel
    selects each block's rows with a one-hot MXU matmul against the
    cell ids — XLA-side pool-order gathers processed every pool row
    each round; in-kernel selection is paid only by live blocks), and
    ``acc`` is the int32 ``[n_pool, n_lieutenants]`` acceptance matrix.
    jit/vmap-safe (vmap over trials prepends the Pallas grid).

    A block skips all verdict compute when its ``sent`` flags are all
    zero — the pool is compacted, so occupancy concentrates in the
    leading blocks and trailing blocks cost only their DMA.  (The skip
    reads the block's own data rather than an ``n_sent`` scalar: a
    per-trial scalar operand cannot be batched into SMEM under vmap.)

    ``variant`` selects the verdict formulation (all bit-identical;
    :func:`resolve_verdict_variant` picks):

    * ``"group"`` — lane-group flag algebra + the round-6
      block-parallel first-accept reduction: one
      :func:`accept_first_per_value_all` pass dedups every receiver at
      once, with no per-receiver chain through ``ovi_ref``.  The
      default; covers every config, including the ones the round-4
      group-batched dedup excludes (``grp == 1`` and
      ``grp * w > 512``).
    * ``"group-serial"`` — the pre-round-6 accept path (group-batched
      dedup inside the ``grp * w <= 512`` window, serial per-receiver
      chains elsewhere).  Kept as the TPU compile fallback and as the
      in-repo reference the parallel reduction is pinned against.
    * ``"allrecv"`` — all-receiver flag algebra (docs/PERF.md round 5),
      gated by :func:`all_receiver_supported`.

    ``n_recv`` builds the party-sharded variant for
    :mod:`qba_tpu.parallel.spmd` (mirroring the monolithic kernel's
    ``build_round_step(n_recv=...)``): the kernel drains a contiguous
    block of ``n_recv`` receivers against the FULL gathered pool —
    which is then per-device compacted (contiguous live prefix per
    ``tp`` segment), preserving the global (sender, slot) packet order
    D5 needs, with dead inter-segment capacity skipped by the same
    block-skip test.  ``step`` gains a runtime ``recv_off`` operand
    (every device runs one program under shard_map), the
    receiver-indexed operands hold only the local block's rows/columns,
    and ``out_vma`` declares the mesh axes the outputs vary over
    (required under shard_map's replication checker).
    """
    n_rv_glob, slots, max_l = cfg.n_lieutenants, cfg.slots, cfg.max_l
    size_l, w = cfg.size_l, cfg.w
    n_pool = n_rv_glob * slots  # the GLOBAL pool capacity / cell space
    local = n_recv is not None
    n_rv = n_recv if local else n_rv_glob  # receivers this kernel drains
    if n_pool % blk:
        raise ValueError(f"blk={blk} must divide n_pool={n_pool}")
    n_blocks = n_pool // blk
    gdt = _gdt(cfg)
    if variant not in ("group", "group-serial", "allrecv"):
        raise ValueError(f"unknown verdict variant {variant!r}")
    if variant == "allrecv" and not all_receiver_supported(size_l, w):
        raise ValueError(
            f"allrecv variant unsupported at size_l={size_l}, w={w}"
        )

    # Receiver lane-packing plan (see round_kernel.py's kernel v4): grp
    # receivers side by side fill the VPU's 128 lanes when size_l is
    # narrow; the last group re-covers the tail when grp doesn't divide
    # n_rv (the member loop skips already-processed receivers).
    grp = _lane_group(size_l, n_rv)
    seg_l = grp * size_l
    r0_list = list(range(0, n_rv - grp + 1, grp))
    if n_rv % grp:
        r0_list.append(n_rv - grp)
    e_np = np.zeros((grp, seg_l), np.float32)
    for j in range(grp):
        e_np[j, j * size_l : (j + 1) * size_l] = 1.0

    def kernel(round_ref, *refs):
        def scalar_read(ref):
            # Interpret mode under shard_map's replication checker: a
            # full load + squeeze avoids the literal-index dynamic_slice
            # (see round_kernel.py).  Mosaic keeps the SMEM read.
            if interpret:
                return ref[:].reshape(())
            return ref[0]

        if local:
            off_ref, *refs = refs
            r_off = scalar_read(off_ref)  # block's first receiver
        else:
            r_off = 0
        if variant == "allrecv":
            (
                vals_ref, lens_ref, p_ref, meta_ref, vi_ref, honest_ref,
                act_ref, rv_ref, late_ref, t1_ref, t2_ref, tob_ref,
                tlh_ref, tlh2_ref,
                acc_ref, ovi_ref,
            ) = refs
        else:
            (
                vals_ref, lens_ref, p_ref, meta_ref, vi_ref, honest_ref,
                act_ref, rv_ref, late_ref, e_ref, lip_ref, lioob_ref,
                acc_ref, ovi_ref,
            ) = refs

        r_idx = scalar_read(round_ref)
        blk_id = pl.program_id(0)

        @pl.when(blk_id == 0)
        def _init_vi():
            ovi_ref[:] = vi_ref[:]

        # Block-skip: all-empty blocks (zero sent flags — the pool is
        # compacted, per device segment in the sharded case) skip all
        # verdict compute.
        block_live = (
            jnp.sum(meta_ref[:, META_SENT : META_SENT + 1]) > 0
        )

        @pl.when(jnp.logical_not(block_live))
        def _skip():
            acc_ref[:] = jnp.zeros((blk, n_rv), jnp.int32)

        @pl.when(block_live)
        def _verdict():
            # The whole per-block verdict lives in the shared pure
            # helper (one implementation with the fused round kernel —
            # see _verdict_block_accepts); this kernel supplies the
            # cross-block vi carry through the revisited ovi block.
            tables = (
                (t1_ref[:], t2_ref[:], tob_ref[:], tlh_ref[:],
                 tlh2_ref[:])
                if variant == "allrecv"
                else (e_ref[:], lip_ref[:], lioob_ref[:])
            )
            acc, new_vi = _verdict_block_accepts(
                variant=variant, blk=blk, n_rv=n_rv, n_cells=n_pool,
                slots=slots, max_l=max_l, size_l=size_l, w=w, gdt=gdt,
                grp=grp, seg_l=seg_l, r0_list=r0_list,
                r_off=r_off, r_idx=r_idx,
                vals=[
                    vals_ref[r].astype(jnp.int32) for r in range(max_l)
                ],
                lens=lens_ref[:],
                p_i32=p_ref[:].astype(jnp.int32),
                meta=meta_ref[:],
                vi=ovi_ref[:],
                honest_col=honest_ref[:],
                att_t=act_ref[:], rv_t=rv_ref[:], late_t=late_ref[:],
                tables=tables,
                use_fp=cfg.strategy == "split",
            )
            ovi_ref[:] = new_vi
            acc_ref[:] = acc

    grid = (n_blocks,)

    def blkmap(i):
        return (i, 0)

    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # round_idx
    ] + (
        [pl.BlockSpec(memory_space=pltpu.SMEM)] if local else []  # recv_off
    ) + [
        pl.BlockSpec((max_l, blk, size_l), lambda i: (0, i, 0)),  # vals
        pl.BlockSpec((blk, max_l), blkmap),  # lens
        pl.BlockSpec((blk, size_l), blkmap),  # p
        pl.BlockSpec((blk, 4), blkmap),  # meta (count, v, sent, cell)
        pl.BlockSpec((n_rv, w), lambda i: (0, 0)),  # vi
        pl.BlockSpec((n_pool, 1), lambda i: (0, 0)),  # honest_cells
        pl.BlockSpec((n_rv, n_pool), lambda i: (0, 0)),  # attack^T
        pl.BlockSpec((n_rv, n_pool), lambda i: (0, 0)),  # rand_v^T
        pl.BlockSpec((n_rv, n_pool), lambda i: (0, 0)),  # late^T
    ] + (
        [
            pl.BlockSpec((size_l, n_rv), lambda i: (0, 0)),  # t_li1
            pl.BlockSpec((size_l, n_rv), lambda i: (0, 0)),  # t_li2
            pl.BlockSpec((size_l, n_rv), lambda i: (0, 0)),  # t_oob
            pl.BlockSpec((size_l, w * n_rv), lambda i: (0, 0)),  # t_lh
            pl.BlockSpec((w * size_l, n_rv), lambda i: (0, 0)),  # t_lh2
        ]
        if variant == "allrecv"
        else [
            pl.BlockSpec((grp, seg_l), lambda i: (0, 0)),  # e_mat
            pl.BlockSpec((len(r0_list), seg_l), lambda i: (0, 0)),  # lip
            pl.BlockSpec((len(r0_list), seg_l), lambda i: (0, 0)),  # lioob
        ]
    )
    out_specs = (
        pl.BlockSpec((blk, n_rv), blkmap),  # acc
        pl.BlockSpec((n_rv, w), lambda i: (0, 0)),  # ovi (revisited)
    )

    from qba_tpu.ops.round_kernel import promote_vma, vma_struct

    call = pl.pallas_call(
        kernel,
        grid=grid,
        out_shape=(
            vma_struct(out_vma, (n_pool, n_rv)),
            vma_struct(out_vma, (n_rv, w)),
        ),
        in_specs=in_specs,
        out_specs=out_specs,
        # vi donates into ovi: the round step is a lax.scan body, and an
        # un-aliased carry costs a copy per round (see the monolithic
        # kernel's aliasing note).  Safe: vi_ref is copied into the
        # revisited ovi block at grid step 0 and only ovi is read after.
        # Machine-checked: KI-5 `qba-tpu lint --effects` chases every
        # scan carry to an aliased kernel output (checks scan-carry /
        # alias-consistency); editing this dict breaks the lint, not
        # just this comment.
        input_output_aliases={(2 if local else 1) + 4: 1},
        compiler_params=CompilerParams(
            # See build_rebuild_kernel: large vmap batches multi-buffer
            # operands past the compiler's ~16 MB default scoped cap.
            vmem_limit_bytes=100 * 2**20,
        ),
        interpret=interpret,
    )

    def _pv(x):
        return promote_vma(out_vma, x)

    def _tail(li):
        if variant == "allrecv":
            # ``li`` is the prebuilt table tuple from
            # :func:`make_verdict_tables` (round-invariant — built once
            # outside the scan).
            return tuple(li)
        li_pack = jnp.stack(
            [li[r0 : r0 + grp].reshape(-1) for r0 in r0_list]
        )
        li_oob_pack = ((li_pack > w) | (li_pack < 0)).astype(jnp.int32)
        return jnp.asarray(e_np), li_pack, li_oob_pack

    if local:

        def verdict(round_idx, recv_off, vals, lens, p, meta, li, vi,
                    honest_pk, attack, rand_v, late):
            # Pool operands are GLOBAL; li/vi/draw columns are the local
            # receiver block's; recv_off is its first receiver.  The
            # cell-major draws transpose to the kernel's pad-free
            # receiver-major layout here (XLA fuses the transpose into
            # the sampling producer).
            args = (
                jnp.asarray([round_idx], jnp.int32),
                jnp.asarray(recv_off, jnp.int32).reshape(1),
                vals, lens, p, meta, vi, honest_pk,
                attack.T, rand_v.T, late.T, *_tail(li),
            )
            return call(*map(_pv, args))

    else:

        def verdict(round_idx, vals, lens, p, meta, li, vi,
                    honest_pk, attack, rand_v, late):
            # li itself is consumed host-side (the lane-packed lip/lioob
            # or all-receiver tables carry its data); the kernel takes
            # only the tables.
            return call(
                jnp.asarray([round_idx], jnp.int32),
                vals, lens, p, meta, vi, honest_pk,
                attack.T, rand_v.T, late.T, *_tail(li),
            )

    return verdict


def pool_vals_dtype(cfg: QBAConfig):
    """Element dtype of the pool's position-expanded tensors (``vals``,
    ``p``): bfloat16 when every stored value is bf16-exact (integers of
    magnitude <= 256: protocol values < w, SENTINEL = -1) — a 2x cut in
    the rebuild kernel's resident VMEM and in per-round HBM traffic at
    scale, and the MXU gathers consume it without conversion.  (int8
    would halve it again, but this TPU target rejects i8 vector
    compares.)"""
    return jnp.bfloat16 if cfg.w <= 256 else jnp.int32


def make_verdict_tables(cfg: QBAConfig, li):
    """Receiver tables for the all-receiver verdict variant
    (:func:`qba_tpu.ops.verdict_algebra.make_receiver_tables`) — built
    ONCE per trial, outside the round scan (li is round-invariant), and
    passed to the kernel in place of ``li``."""
    return make_receiver_tables(li, cfg.size_l, cfg.w, _gdt(cfg))


def honest_cells(honest, cfg: QBAConfig):
    """Per-cell sender-honesty column ``[n_cells, 1]`` from the
    rank-indexed honesty mask (cells are static per trial: the cell's
    sender lieutenant is ``cell // slots``, rank ``+ 2``).  The tiled
    analog of :func:`qba_tpu.ops.round_kernel.honest_packets` — shared
    by the single-device and party-sharded callers."""
    n_cells = cfg.n_lieutenants * cfg.slots
    return honest[
        jnp.arange(n_cells) // cfg.slots + 2
    ].astype(jnp.int32)[:, None]


# Lanes of the pool's packed per-packet meta column (ONE [cap, 4] int32
# tensor instead of four [cap, 1] columns: a narrow minor dim pads to a
# full 128-lane tile either way, so four separate columns cost 4x the
# HBM/DMA of one packed tensor — ~4 MB/trial/round at the 33-party
# scale, in BOTH kernels' operands and the rebuild's outputs).
META_COUNT, META_V, META_SENT, META_CELL = 0, 1, 2, 3


def empty_pool(cfg: QBAConfig, n_recv: int | None = None):
    """The compacted packet pool: ``(vals, lens, p, meta)`` with
    ``meta[:, META_*] = (count, v, sent, cell)``, capacity
    ``n_lieutenants * slots`` (the lossless bound — each receiver
    accepts at most ``slots <= w`` packets per round).  ``n_recv``
    sizes a party-sharded LOCAL pool (capacity ``n_recv * slots`` —
    one device's senders)."""
    n_rv = n_recv if n_recv is not None else cfg.n_lieutenants
    slots, max_l, s = cfg.slots, cfg.max_l, cfg.size_l
    cap = n_rv * slots
    vdt = pool_vals_dtype(cfg)
    return (
        jnp.full((max_l, cap, s), SENTINEL, vdt),
        jnp.zeros((cap, max_l), jnp.int32),
        jnp.zeros((cap, s), vdt),
        jnp.zeros((cap, 4), jnp.int32),
    )


def pool_from_step3a(cfg: QBAConfig, out_cells, *, start=None,
                     n_recv: int | None = None):
    """Compact step 3a's per-lieutenant broadcast (slot 0 of each sender
    row, ``tfg.py:185-196``) into the pool.

    Party-sharded callers pass their receiver-block rows plus
    ``start`` (the block's first GLOBAL receiver, traced) and
    ``n_recv``: the result is the device's LOCAL pool — locally
    compacted, carrying GLOBAL cell ids, so the per-round ``tp``
    all_gather concatenates segments in global (sender, slot) order.
    """
    o_vals, o_lens, o_count, o_p, o_v, o_sent = out_cells
    n_rv = n_recv if n_recv is not None else cfg.n_lieutenants
    slots = cfg.slots
    cap = n_rv * slots
    base = 0 if start is None else start
    sent0 = o_sent[:, 0]  # bool[n_rv]
    offs = jnp.cumsum(sent0.astype(jnp.int32)) - sent0.astype(jnp.int32)
    dst = jnp.where(sent0, offs, cap)
    pool = empty_pool(cfg, n_recv)

    def scat(tgt, src):  # scatter rows of src[n_rv, ...] to dst positions
        return tgt.at[dst].set(src, mode="drop")

    vdt = pool_vals_dtype(cfg)
    vals_p = pool[0].transpose(1, 0, 2).at[dst].set(
        o_vals[:, 0].astype(vdt), mode="drop"
    ).transpose(1, 0, 2)
    cell_ids = (base + jnp.arange(n_rv, dtype=jnp.int32)) * slots
    meta_rows = jnp.stack(
        [
            o_count[:, 0],
            o_v[:, 0],
            jnp.ones((n_rv,), jnp.int32),
            cell_ids,
        ],
        axis=1,
    )
    return (
        vals_p,
        scat(pool[1], o_lens[:, 0]),
        scat(pool[2], o_p[:, 0].astype(vdt)),
        scat(pool[3], meta_rows),
    )


def rebuild_pool(cfg: QBAConfig, round_idx, pool, li, acc,
                 attack_pool, rand_v_pool, honest_pool, *, start=None,
                 n_recv: int | None = None):
    """Phase 2 (XLA): slot allocation + next-round pool construction.

    Mirrors the monolithic kernel's rebuild tail (``tfg.py:298-299`` slot
    allocation, ``lieu_receive``'s evidence append) over the compacted
    pool.  Returns ``(pool', overflow)``.

    Party-sharded callers pass ``n_recv`` + ``start``: ``pool`` is then
    the FULL gathered pool, ``li``/``acc`` and the per-receiver draw
    columns hold only the local receiver block, and the result is the
    device's LOCAL pool (capacity ``n_recv * slots``, global cell ids).
    """
    n_rv_glob, slots, max_l, s = (
        cfg.n_lieutenants, cfg.slots, cfg.max_l, cfg.size_l,
    )
    n_pool = n_rv_glob * slots  # gathered/global pool capacity
    n_rv = n_recv if n_recv is not None else n_rv_glob
    n_out = n_rv * slots  # this block's output pool capacity
    base = 0 if start is None else start
    vals, lens, p, meta = pool
    count = meta[:, META_COUNT : META_COUNT + 1]
    v = meta[:, META_V : META_V + 1]
    biz = honest_pool == 0  # [n_pool, 1]
    clear_p = biz & ((attack_pool & CLEAR_P_BIT) != 0)  # [n_pool, n_rv]
    clear_l = biz & ((attack_pool & CLEAR_L_BIT) != 0)
    v2 = jnp.where(biz & ((attack_pool & FORGE_BIT) != 0),
                   rand_v_pool, v)
    # forge-P (strategy="split"): statically gated, None elsewhere.
    forge_p = (
        biz & ((attack_pool & FORGE_P_BIT) != 0)
        if cfg.strategy == "split" else None
    )

    rebroadcast = (acc != 0) & (round_idx <= cfg.n_dishonest)
    # Per-receiver slot index (draw identity for the next round) and the
    # slot-bound overflow flag (lossless slots=w never overflows: a
    # receiver accepts each order value at most once per round).
    slot_r = (jnp.cumsum(rebroadcast.astype(jnp.int32), axis=0)
              - rebroadcast)  # [n_pool, n_rv]
    write = rebroadcast & (slot_r < slots)
    overflow = jnp.any(rebroadcast & ~write)

    # Source map: per receiver, the accepted packets' pool indices in
    # packet order — one descending top_k of -index over the write mask.
    big = n_pool + 1
    score = jnp.where(write, -jnp.arange(n_pool)[:, None], -big)
    top = jax.lax.top_k(score.T, slots)[0]  # [n_rv, slots], descending
    src_r = -top  # ascending pool index; `big` marks empty slots
    has_r = src_r < n_pool  # [n_rv, slots]

    # Compacted destination: receiver-major (sender, slot) order —
    # compaction preserves D5 packet order (per device block in the
    # party-sharded case; segments concatenate in global order).
    k_r = jnp.sum(write.astype(jnp.int32), axis=0)  # [n_rv]
    offs = jnp.cumsum(k_r) - k_r  # exclusive
    dst = jnp.where(
        has_r, offs[:, None] + jnp.arange(slots)[None, :], n_out
    )  # [n_rv, slots]
    dst_f = dst.reshape(-1)
    src_f = jnp.minimum(src_r.reshape(-1), n_pool - 1)

    # src_pool[d] = pool index feeding compacted position d.
    src_pool = jnp.full((n_out,), n_pool, jnp.int32).at[dst_f].set(
        src_f.astype(jnp.int32), mode="drop"
    )
    new_sent = (src_pool < n_pool).astype(jnp.int32)[:, None]
    srcc = jnp.minimum(src_pool, n_pool - 1)
    # cell id = sender(=accepting receiver) * slots + per-receiver slot
    # — GLOBAL receiver index (base + local).
    cell_f = (
        (base + jnp.arange(n_rv, dtype=jnp.int32))[:, None] * slots
        + jnp.arange(slots, dtype=jnp.int32)[None, :]
    ).reshape(-1)
    new_cell = jnp.zeros((n_out,), jnp.int32).at[dst_f].set(
        cell_f, mode="drop"
    )[:, None]
    recv_c = jnp.clip(new_cell[:, 0] // slots - base, 0, n_rv - 1)

    # Gather source fields + the (src, recv) corruption flags.
    vals_g = jnp.take(vals, srcc, axis=1)  # [max_l, n_pool, s]
    lens_g = jnp.take(lens, srcc, axis=0)
    cnt_g = jnp.take(count, srcc, axis=0)  # [n_pool, 1]
    p_g = jnp.take(p, srcc, axis=0)  # [n_pool, s]
    clearp_c = clear_p[srcc, recv_c][:, None]
    clearl_c = clear_l[srcc, recv_c][:, None]
    v2_c = v2[srcc, recv_c][:, None]
    li_c = jnp.take(li, recv_c, axis=0)  # [n_pool, s]

    # The keep/append row algebra — identical to the monolithic kernel's
    # tail (lieu_receive's L.add of the own sub-list, tfg.py:291).
    p2 = (p_g != 0) & ~clearp_c
    if forge_p is not None:
        # Forged-full P survives the rebuild (forgery wins over clear);
        # own_len = sum(p2) then yields size_l automatically.
        p2 = forge_p[srcc, recv_c][:, None] | p2
    own = jnp.where(p2, li_c, SENTINEL)
    own_len = jnp.sum(p2.astype(jnp.int32), axis=1, keepdims=True)
    cnt_eff = jnp.where(clearl_c, 0, cnt_g)
    valid_raw = jnp.arange(max_l)[None, :] < cnt_g  # [n_pool, max_l]
    row_eq = jnp.all(
        vals_g.transpose(1, 0, 2) == own[:, None, :], axis=-1
    )  # [n_pool, max_l]
    dup = jnp.any(valid_raw & row_eq, axis=-1, keepdims=True) & ~clearl_c
    new_cnt = jnp.where(dup, cnt_eff, jnp.minimum(cnt_eff + 1, max_l))

    has = new_sent != 0  # [n_pool, 1]
    iota_l = jnp.arange(max_l)[None, :]
    keep_row = iota_l < cnt_eff  # clear_l zeroes cnt_eff
    new_row = ~dup & (iota_l == cnt_eff)
    o_lens = jnp.where(
        has,
        jnp.where(new_row, own_len,
                  jnp.where(keep_row, lens_g, 0)),
        0,
    )
    iota_r = jnp.arange(max_l)[:, None, None]
    keep3 = iota_r < cnt_eff[None, :, :]
    new3 = (~dup & (iota_r == cnt_eff[None]))
    o_vals = jnp.where(
        has[None],
        jnp.where(new3, own[None], jnp.where(keep3, vals_g, SENTINEL)),
        SENTINEL,
    )
    vdt = pool_vals_dtype(cfg)
    o_count = jnp.where(has, new_cnt, 0)
    o_p = jnp.where(has, p2, False).astype(vdt)
    o_v = jnp.where(has, v2_c, 0)
    o_meta = jnp.concatenate([o_count, o_v, new_sent, new_cell], axis=1)
    return (o_vals.astype(vdt), o_lens, o_p, o_meta), overflow


def build_rebuild_kernel(
    cfg: QBAConfig,
    blk_d: int,
    *,
    interpret: bool = False,
    n_recv: int | None = None,
    out_vma: frozenset | None = None,
):
    """Compile phase 2 as a Pallas kernel — the fast path; the XLA
    :func:`rebuild_pool` is the fallback when this shape doesn't compile.

    Why a kernel: XLA lowers the rebuild's pool-sized dynamic gathers,
    scatter and top_k to serial-ish loops (measured ~40-100 ms per round
    batch each at the 33-party scale — together ~6x the verdict kernel
    itself).  Here every gather is a one-hot MXU matmul and the slot
    allocation is an in-kernel prefix sum, so the round's rebuild is
    ~free next to the verdict pass.

    Layout: 1-D grid over destination blocks of ``blk_d`` compacted pool
    positions.  The source pool stays resident in VMEM across steps
    (constant index maps — fetched once); destination blocks whose base
    is past the round's total accept count skip all compute.  Step 0
    computes the slot allocation into scratch:

    * ``accT`` (the acceptance matrix, receiver-major ``[n_rv, n_pool]``,
      transposed once in XLA) -> per-receiver exclusive prefix counts
      along lanes (Hillis-Steele shifts), clamped write masks, and the
      per-receiver accept counts/offsets ``k_r`` / ``offs`` (lane-axis
      prefix over ``n_rv`` lanes).
    * the slot-bound overflow flag (``tfg.py:298-299``; lossless
      ``slots=w`` never overflows).

    Every later step builds its receiver one-hot from ``offs``/``k_r``,
    forms the dst-block gather matrix ``G^T [blk_d, n_pool]`` from the
    scratch write/slot tables, and MXU-gathers every pool field plus the
    (cell, receiver) corruption draws, then applies the same keep/append
    row algebra as :func:`rebuild_pool`.

    Returns ``rebuild(round_idx, vals, lens, count, p, v, cell, li, acc,
    accT, attack, rand_v, honest_cells) -> (o_vals, o_lens, o_count,
    o_p, o_v, o_sent, o_cell, overflow)`` with ``attack``/``rand_v``
    mailbox-cell-ordered ``[n_cells, n_rv]`` (NOT pool-gathered) and
    ``honest_cells`` the per-cell sender honesty column.

    ``n_recv`` builds the party-sharded variant (see
    :func:`build_verdict_kernel`): the source pool is the FULL gathered
    pool, the receiver-indexed operands hold the local block only, the
    destination pool has capacity ``n_recv * slots``, output cell ids
    are global (``recv_off`` runtime operand), and ``out_vma`` declares
    the outputs' mesh axes for shard_map's replication checker.
    """
    n_rv_glob, slots, max_l = cfg.n_lieutenants, cfg.slots, cfg.max_l
    size_l, w = cfg.size_l, cfg.w
    n_pool = n_rv_glob * slots  # gathered/global source pool capacity
    local = n_recv is not None
    n_rv = n_recv if local else n_rv_glob
    n_out = n_rv * slots  # this block's destination pool capacity
    n_dis = cfg.n_dishonest
    if n_out % blk_d:
        raise ValueError(f"blk_d={blk_d} must divide n_out={n_out}")
    n_blocks = n_out // blk_d
    gdt = jnp.bfloat16 if size_l <= 256 and w <= 256 else jnp.float32
    vdt = pool_vals_dtype(cfg)

    def kernel(round_ref, *refs):
        def scalar_read(ref):
            if interpret:
                return ref[:].reshape(())
            return ref[0]

        if local:
            off_ref, *refs = refs
            r_off = scalar_read(off_ref)  # block's first GLOBAL receiver
        else:
            r_off = 0
        (
            vals_ref, lens_ref, p_ref, meta_ref,
            li_ref, acc_ref, accT_ref, att_ref, rv_ref, hon_ref,
            ovals_ref, olens_ref, op_ref, ometa_ref, ovf_ref,
            wT_scr, sT_scr, lane_scr,
        ) = refs

        r_idx = scalar_read(round_ref)
        bd = pl.program_id(0) * blk_d

        @pl.when(pl.program_id(0) == 0)
        def _prep():
            # Write mask + slot allocation, receiver-major.
            writeT = (accT_ref[:] != 0) & (r_idx <= n_dis)  # [n_rv, n_pool]
            w_i = jnp.where(writeT, 1, 0)
            # Inclusive prefix along lanes (Hillis-Steele, log2 steps).
            x = w_i
            k = 1
            while k < n_pool:
                x = x + jnp.pad(x, ((0, 0), (k, 0)))[:, :n_pool]
                k *= 2
            slotT = x - w_i  # exclusive prefix = outgoing slot index
            write_m = writeT & (slotT < slots)
            ovf_ref[:] = jnp.where(
                jnp.any(writeT & ~write_m), 1, 0
            ).reshape(1, 1)
            wT_scr[:] = jnp.where(write_m, 1, 0)
            sT_scr[:] = jnp.minimum(slotT, slots)
            # Per-receiver accept counts (lane-oriented, from the
            # packet-major acc), their exclusive lane prefix (dst
            # offsets), and the round's total accept count.
            write0 = (acc_ref[:] != 0) & (r_idx <= n_dis)  # [n_pool, n_rv]
            k_lane = jnp.minimum(
                jnp.sum(jnp.where(write0, 1, 0), axis=0, keepdims=True),
                slots,
            )  # [1, n_rv]
            x = k_lane
            k = 1
            while k < n_rv:
                x = x + jnp.pad(x, ((0, 0), (k, 0)))[:, :n_rv]
                k *= 2
            offs = x - k_lane  # [1, n_rv] exclusive
            lane_scr[0:1, :] = offs
            lane_scr[1:2, :] = k_lane

        offs = lane_scr[0:1, :]  # [1, n_rv]
        k_lane = lane_scr[1:2, :]
        total = jnp.sum(k_lane)

        def zero_outputs():
            ovals_ref[:] = jnp.full(
                (max_l, blk_d, size_l), SENTINEL, vdt
            )
            olens_ref[:] = jnp.zeros((blk_d, max_l), jnp.int32)
            op_ref[:] = jnp.zeros((blk_d, size_l), vdt)
            ometa_ref[:] = jnp.zeros((blk_d, 4), jnp.int32)

        @pl.when(bd >= total)
        def _skip():
            zero_outputs()

        @pl.when(bd < total)
        def _build():
            d_col = bd + jax.lax.broadcasted_iota(
                jnp.int32, (blk_d, 1), 0
            )  # global dst position
            live = d_col < total  # [blk_d, 1]
            # Receiver one-hot: offs[r] <= d < offs[r] + k_r.
            offs_b = jnp.broadcast_to(offs, (blk_d, n_rv))
            k_b = jnp.broadcast_to(k_lane, (blk_d, n_rv))
            onehot = (offs_b <= d_col) & (d_col < offs_b + k_b)
            oh_i = jnp.where(onehot, 1, 0)
            iota_rv = jax.lax.broadcasted_iota(
                jnp.int32, (blk_d, n_rv), 1
            )
            r_j = jnp.sum(oh_i * iota_rv, axis=1, keepdims=True)
            slot_lane = d_col - jnp.sum(
                oh_i * jnp.broadcast_to(offs, (blk_d, n_rv)),
                axis=1, keepdims=True,
            )  # [blk_d, 1]
            oh_f = jnp.where(onehot, 1.0, 0.0).astype(gdt)

            def oh_mm(tbl, dt=gdt):  # [n_rv, X] -> [blk_d, X] via MXU
                return jax.lax.dot_general(
                    oh_f.astype(dt), tbl.astype(dt),
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=_prec(dt),
                )

            w_sel = oh_mm(wT_scr[:]) > 0.5  # [blk_d, n_pool]
            s_sel = oh_mm(sT_scr[:]).astype(jnp.int32)
            g_t = w_sel & (s_sel == slot_lane)  # broadcast over lanes
            g_f = jnp.where(g_t, 1.0, 0.0)

            def gmm(field, dt=gdt):  # [n_pool, X] -> [blk_d, X]
                return jax.lax.dot_general(
                    g_f.astype(dt), field.astype(dt),
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=_prec(dt),
                )

            rows_g = [
                gmm(vals_ref[r]).astype(jnp.int32) for r in range(max_l)
            ]
            lens_g = gmm(lens_ref[:]).astype(jnp.int32)  # [blk_d, max_l]
            p_g = gmm(p_ref[:]).astype(jnp.int32)  # [blk_d, size_l]
            # One gather for all packed per-packet columns; f32 operands
            # AND Precision.HIGHEST (via _prec) because cell ids reach
            # n_pool-1 > 256 — an f32 dot at default precision may
            # lower through bf16 and round odd cell ids to even (the
            # round-5 wrong-draw bug; see _prec).
            meta_g = gmm(meta_ref[:], jnp.float32).astype(jnp.int32)
            cnt_g = meta_g[:, META_COUNT : META_COUNT + 1]
            v_g = meta_g[:, META_V : META_V + 1]
            cell_g = meta_g[:, META_CELL : META_CELL + 1]

            # (cell, receiver) corruption draws: one-hot over cell ids
            # (values < n_pool, f32-exact), then lane-select receiver.
            # Draw tables are receiver-major [n_rv, n_cells] (pad-free;
            # the MXU contracts the rhs's dim 1 — see the verdict
            # kernel's layout note); the honesty column stays cell-major.
            iota_cells = jax.lax.broadcasted_iota(
                jnp.int32, (blk_d, n_pool), 1
            )
            oh_cell = jnp.where(
                iota_cells == cell_g, 1.0, 0.0
            ).astype(gdt)

            def cell_mm(tbl_t, dt=gdt):  # [n_rv, n_cells] -> [blk_d, n_rv]
                return jax.lax.dot_general(
                    oh_cell.astype(dt), tbl_t.astype(dt),
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=_prec(dt),
                )

            def cell_col_mm(tbl, dt=gdt):  # [n_cells, 1] -> [blk_d, 1]
                return jax.lax.dot_general(
                    oh_cell.astype(dt), tbl.astype(dt),
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=_prec(dt),
                )

            att_rows = cell_mm(att_ref[:])  # [blk_d, n_rv] f32
            rv_rows = cell_mm(rv_ref[:])
            att_c = jnp.sum(
                att_rows * oh_f.astype(jnp.float32), axis=1, keepdims=True
            ).astype(jnp.int32)
            rv_c = jnp.sum(
                rv_rows * oh_f.astype(jnp.float32), axis=1, keepdims=True
            ).astype(jnp.int32)
            hon_c = cell_col_mm(hon_ref[:]).astype(jnp.int32)  # [blk_d, 1]

            biz = hon_c == 0
            clearp_c = biz & ((att_c & CLEAR_P_BIT) != 0)
            clearl_c = biz & ((att_c & CLEAR_L_BIT) != 0)
            v2_c = jnp.where(biz & ((att_c & FORGE_BIT) != 0), rv_c, v_g)
            li_row = oh_mm(li_ref[:]).astype(jnp.int32)  # [blk_d, size_l]

            # Keep/append row algebra — mirrors rebuild_pool /
            # lieu_receive's L.add (tfg.py:291).
            p2 = (p_g != 0) & ~clearp_c
            if cfg.strategy == "split":
                # forge-P: the fabricated all-True mask survives the
                # rebuild (statically gated; see rebuild_pool).
                p2 = (biz & ((att_c & FORGE_P_BIT) != 0)) | p2
            own = jnp.where(p2, li_row, SENTINEL)
            own_len = jnp.sum(jnp.where(p2, 1, 0), axis=1, keepdims=True)
            cnt_eff = jnp.where(clearl_c, 0, cnt_g)
            dup = jnp.zeros((blk_d, 1), jnp.bool_)
            for r in range(max_l):
                mism = jnp.sum(
                    jnp.where(rows_g[r] != own, 1, 0),
                    axis=1, keepdims=True,
                )
                dup |= (cnt_g > r) & (mism == 0)
            dup &= ~clearl_c
            new_cnt = jnp.where(
                dup, cnt_eff, jnp.minimum(cnt_eff + 1, max_l)
            )

            has = live
            iota_l = jax.lax.broadcasted_iota(jnp.int32, (blk_d, max_l), 1)
            keep_row = iota_l < cnt_eff
            new_row = ~dup & (iota_l == cnt_eff)
            olens_ref[:] = jnp.where(
                has,
                jnp.where(new_row, own_len, jnp.where(keep_row, lens_g, 0)),
                0,
            )
            for r in range(max_l):
                keep = ~clearl_c & (r < cnt_eff)
                is_new = ~dup & (r == cnt_eff)
                row = jnp.where(
                    is_new, own, jnp.where(keep, rows_g[r], SENTINEL)
                )
                ovals_ref[r] = jnp.where(has, row, SENTINEL).astype(vdt)
            op_ref[:] = jnp.where(has & p2, 1.0, 0.0).astype(vdt)
            # Packed next-round meta: count, v, sent, and the GLOBAL
            # cell id (the accepting receiver's global index).
            ometa_ref[:] = jnp.where(
                has,
                jnp.concatenate(
                    [
                        new_cnt,
                        v2_c,
                        jnp.ones((blk_d, 1), jnp.int32),
                        (r_off + r_j) * slots + slot_lane,
                    ],
                    axis=1,
                ),
                0,
            )

    full = lambda i: (0, 0)  # noqa: E731 — constant index map (resident)
    full3 = lambda i: (0, 0, 0)  # noqa: E731

    def dmap(i):
        return (i, 0)

    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # round_idx
    ] + (
        [pl.BlockSpec(memory_space=pltpu.SMEM)] if local else []  # recv_off
    ) + [
        pl.BlockSpec((max_l, n_pool, size_l), full3),  # vals
        pl.BlockSpec((n_pool, max_l), full),  # lens
        pl.BlockSpec((n_pool, size_l), full),  # p
        pl.BlockSpec((n_pool, 4), full),  # meta (count, v, sent, cell)
        pl.BlockSpec((n_rv, size_l), full),  # li
        pl.BlockSpec((n_pool, n_rv), full),  # acc
        pl.BlockSpec((n_rv, n_pool), full),  # accT
        pl.BlockSpec((n_rv, n_pool), full),  # attack^T (receiver-major)
        pl.BlockSpec((n_rv, n_pool), full),  # rand_v^T (receiver-major)
        pl.BlockSpec((n_pool, 1), full),  # honest_cells
    ]
    out_specs = (
        pl.BlockSpec((max_l, blk_d, size_l), lambda i: (0, i, 0)),  # vals
        pl.BlockSpec((blk_d, max_l), dmap),  # lens
        pl.BlockSpec((blk_d, size_l), dmap),  # p
        pl.BlockSpec((blk_d, 4), dmap),  # meta
        pl.BlockSpec((1, 1), lambda i: (0, 0)),  # overflow
    )
    from qba_tpu.ops.round_kernel import promote_vma, vma_struct

    def oshp(*dims, dt=jnp.int32):
        return vma_struct(out_vma, dims, dt)

    call = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        out_shape=(
            oshp(max_l, n_out, size_l, dt=vdt),
            oshp(n_out, max_l),
            oshp(n_out, size_l, dt=vdt),
            oshp(n_out, 4),
            oshp(1, 1),
        ),
        in_specs=in_specs,
        out_specs=out_specs,
        # The pool donates into the next-round pool (scan carry):
        # vals/lens/p/meta -> ovals/olens/op/ometa.  Without the aliases
        # XLA rebuilds the carry with a full pool copy per round
        # (measured ~83 ms of a 480 ms 250-trial north-star batch) and
        # keeps two resident pool generations in HBM.  Safe: the source
        # operands have constant index maps — fetched to VMEM before the
        # first destination block writes back — and the caller never
        # reuses the donated arrays after this call.  The party-sharded
        # variant cannot alias (gathered global pool in, local pool
        # out — different shapes).  Machine-checked: KI-5
        # `qba-tpu lint --effects` (scan-carry / alias-consistency).
        input_output_aliases=(
            {} if local else {1: 0, 2: 1, 3: 2, 4: 3}
        ),
        scratch_shapes=[
            pltpu.VMEM((n_rv, n_pool), jnp.int32),  # wT
            pltpu.VMEM((n_rv, n_pool), jnp.int32),  # sT (clamped slots)
            pltpu.VMEM((8, n_rv), jnp.int32),  # offs / k_r rows
        ],
        compiler_params=CompilerParams(
            # The resident full-pool operands get multi-buffered at large
            # vmap batches; raise the compiler's scoped-vmem cap (default
            # ~16 MB) toward the physical VMEM so that's allowed.
            vmem_limit_bytes=100 * 2**20,
        ),
        interpret=interpret,
    )

    def _pv(x):
        return promote_vma(out_vma, x)

    if local:

        def rebuild(round_idx, recv_off, vals, lens, p, meta,
                    li, acc, attack, rand_v, honest_cells):
            args = (
                jnp.asarray([round_idx], jnp.int32),
                jnp.asarray(recv_off, jnp.int32).reshape(1),
                vals, lens, p, meta, li, acc,
                acc.T, attack.T, rand_v.T, honest_cells,
            )
            out = call(*map(_pv, args))
            return out[:4], out[4][0, 0] > 0

    else:

        def rebuild(round_idx, vals, lens, p, meta, li, acc,
                    attack, rand_v, honest_cells):
            out = call(
                jnp.asarray([round_idx], jnp.int32),
                vals, lens, p, meta, li, acc,
                acc.T, attack.T, rand_v.T, honest_cells,
            )
            return out[:4], out[4][0, 0] > 0

    return rebuild


def build_fused_round_kernel(
    cfg: QBAConfig,
    blk_d: int,
    blk_v: int,
    *,
    interpret: bool = False,
    n_recv: int | None = None,
    out_vma: frozenset | None = None,
    variant: str = "group",
    trial_pack: int = 1,
):
    """Compile the FUSED round kernel: verdict + rebuild in ONE
    ``pallas_call`` per round (docs/PERF.md round 7).

    The two-kernel path makes the compacted pool take a full HBM round
    trip between the verdict and rebuild launches every round and
    materializes the ``acc`` acceptance matrix (plus its XLA-side
    transpose) in HBM.  Here the pool is loaded once per round: every
    input is resident (constant index maps — fetched once across the
    grid), grid step 0 runs the verdict as a static loop over ``blk_v``
    packet sub-blocks (the same block-skip + cross-block ``vi`` carry
    as :func:`build_verdict_kernel`, through the revisited ``ovi``
    output block and an ``acc`` VMEM scratch), computes the slot
    allocation packet-major (sublane-axis Hillis-Steele prefix — no
    XLA-side ``acc.T`` operand), and every grid step writes one
    ``blk_d`` destination block of the successor pool exactly like
    :func:`build_rebuild_kernel`.  ``acc``/``accT`` never touch HBM and
    the launch count per round drops from 2 to 1.

    The verdict math is :func:`_verdict_block_accepts` — the SAME
    helper the two-kernel verdict runs — so the fused path is
    bit-identical by construction (pinned by
    tests/test_round_kernel_fused.py).

    ``trial_pack = k > 1`` folds ``k`` trials into one grid: every
    trial-varying operand/output/scratch gains a leading ``k`` axis and
    the kernel loops the ``k`` trials per grid step.  Small configs
    (the headline 11p/64) are ~3/4 bound by fixed per-grid-step
    overhead (docs/PERF.md round 5); packing amortizes that overhead
    ``k``-fold.  Trials are independent — the packed loop touches only
    slice ``t`` of every trial-varying ref — so packing preserves bit
    identity trial by trial.

    ``n_recv`` builds the party-sharded variant (gathered global pool
    in, local destination pool out — no pool aliasing; global cell ids
    via the ``recv_off`` operand).  Trial packing is a single-device
    batching tool and is not supported together with ``n_recv``.

    Returns ``fused(round_idx, vals, lens, p, meta, li, li_arg, vi,
    honest_cells, attack, rand_v, late) -> ((o_vals, o_lens, o_p,
    o_meta), vi', overflow)`` with draws mailbox-cell-ordered
    ``[n_cells, n_rv]`` (``[k, n_cells, n_rv]`` packed) and ``li_arg``
    the verdict-table argument (:func:`make_verdict_tables` output for
    ``"allrecv"``, ``li`` itself for the group family).  The local
    variant takes ``recv_off`` after ``round_idx``.
    """
    n_rv_glob, slots, max_l = cfg.n_lieutenants, cfg.slots, cfg.max_l
    size_l, w = cfg.size_l, cfg.w
    n_pool = n_rv_glob * slots  # gathered/global source pool capacity
    local = n_recv is not None
    n_rv = n_recv if local else n_rv_glob
    n_out = n_rv * slots
    n_dis = cfg.n_dishonest
    kk = trial_pack
    packed = kk > 1
    if packed and local:
        raise ValueError("trial packing is single-device only")
    if kk < 1:
        raise ValueError(f"trial_pack={kk} must be >= 1")
    if n_out % blk_d:
        raise ValueError(f"blk_d={blk_d} must divide n_out={n_out}")
    if n_pool % blk_v:
        raise ValueError(f"blk_v={blk_v} must divide n_pool={n_pool}")
    n_blocks = n_out // blk_d
    gdt = _gdt(cfg)
    vdt = pool_vals_dtype(cfg)
    if variant not in ("group", "group-serial", "allrecv"):
        raise ValueError(f"unknown verdict variant {variant!r}")
    if variant == "allrecv" and not all_receiver_supported(size_l, w):
        raise ValueError(
            f"allrecv variant unsupported at size_l={size_l}, w={w}"
        )

    # Receiver lane-packing plan — identical to build_verdict_kernel.
    grp = _lane_group(size_l, n_rv)
    seg_l = grp * size_l
    r0_list = list(range(0, n_rv - grp + 1, grp))
    if n_rv % grp:
        r0_list.append(n_rv - grp)
    e_np = np.zeros((grp, seg_l), np.float32)
    for j in range(grp):
        e_np[j, j * size_l : (j + 1) * size_l] = 1.0

    def kernel(round_ref, *refs):
        def scalar_read(ref):
            if interpret:
                return ref[:].reshape(())
            return ref[0]

        if local:
            off_ref, *refs = refs
            r_off = scalar_read(off_ref)  # block's first GLOBAL receiver
        else:
            r_off = 0
        if variant == "allrecv":
            (
                vals_ref, lens_ref, p_ref, meta_ref, li_ref, vi_ref,
                hon_ref, att_ref, rv_ref, late_ref,
                t1_ref, t2_ref, tob_ref, tlh_ref, tlh2_ref,
                ovals_ref, olens_ref, op_ref, ometa_ref, ovf_ref,
                ovi_ref,
                acc_scr, w_scr, s_scr, lane_scr,
            ) = refs
        else:
            (
                vals_ref, lens_ref, p_ref, meta_ref, li_ref, vi_ref,
                hon_ref, att_ref, rv_ref, late_ref,
                e_ref, lip_ref, lioob_ref,
                ovals_ref, olens_ref, op_ref, ometa_ref, ovf_ref,
                ovi_ref,
                acc_scr, w_scr, s_scr, lane_scr,
            ) = refs

        r_idx = scalar_read(round_ref)
        bd = pl.program_id(0) * blk_d

        def T(ref, t):  # full per-trial view of a trial-varying ref
            return ref[t] if packed else ref[:]

        @pl.when(pl.program_id(0) == 0)
        def _phase_a():
            # --- Verdict: static sub-block loop, vi carried through the
            # revisited ovi block (TPU grid step 0 runs once; the loop
            # order reproduces the two-kernel path's grid order).
            for t in range(kk):
                if packed:
                    ovi_ref[t] = vi_ref[t]
                else:
                    ovi_ref[:] = vi_ref[:]
                if variant == "allrecv":
                    tables_t = (
                        T(t1_ref, t), T(t2_ref, t), T(tob_ref, t),
                        T(tlh_ref, t), T(tlh2_ref, t),
                    )
                else:
                    # e is trial-invariant; lip/lioob vary per trial.
                    tables_t = (
                        e_ref[:], T(lip_ref, t), T(lioob_ref, t),
                    )
                for b0 in range(0, n_pool, blk_v):
                    sl = slice(b0, b0 + blk_v)
                    meta_blk = (
                        meta_ref[t, sl] if packed else meta_ref[sl]
                    )
                    live = jnp.sum(
                        meta_blk[:, META_SENT : META_SENT + 1]
                    ) > 0

                    @pl.when(live)
                    def _do(t=t, sl=sl, meta_blk=meta_blk,
                            tables_t=tables_t):
                        acc, new_vi = _verdict_block_accepts(
                            variant=variant, blk=blk_v, n_rv=n_rv,
                            n_cells=n_pool, slots=slots, max_l=max_l,
                            size_l=size_l, w=w, gdt=gdt, grp=grp,
                            seg_l=seg_l, r0_list=r0_list,
                            r_off=r_off, r_idx=r_idx,
                            vals=[
                                (
                                    vals_ref[r, t, sl] if packed
                                    else vals_ref[r, sl]
                                ).astype(jnp.int32)
                                for r in range(max_l)
                            ],
                            lens=(
                                lens_ref[t, sl] if packed
                                else lens_ref[sl]
                            ),
                            p_i32=(
                                p_ref[t, sl] if packed else p_ref[sl]
                            ).astype(jnp.int32),
                            meta=meta_blk,
                            vi=T(ovi_ref, t),
                            honest_col=T(hon_ref, t),
                            att_t=T(att_ref, t), rv_t=T(rv_ref, t),
                            late_t=T(late_ref, t),
                            tables=tables_t,
                            use_fp=cfg.strategy == "split",
                        )
                        if packed:
                            acc_scr[t, sl] = acc
                            ovi_ref[t] = new_vi
                        else:
                            acc_scr[sl] = acc
                            ovi_ref[:] = new_vi

                    @pl.when(jnp.logical_not(live))
                    def _skip_blk(t=t, sl=sl):
                        zeros = jnp.zeros((blk_v, n_rv), jnp.int32)
                        if packed:
                            acc_scr[t, sl] = zeros
                        else:
                            acc_scr[sl] = zeros

            # --- Slot allocation, packet-major (no accT operand: the
            # per-receiver prefix runs along SUBLANES over the acc
            # scratch — same Hillis-Steele shift-add, padded on axis 0).
            for t in range(kk):
                acc_t = T(acc_scr, t)  # [n_pool, n_rv]
                write0 = (acc_t != 0) & (r_idx <= n_dis)
                w_i = jnp.where(write0, 1, 0)
                x = w_i
                k = 1
                while k < n_pool:
                    x = x + jnp.pad(x, ((k, 0), (0, 0)))[:n_pool, :]
                    k *= 2
                slot0 = x - w_i  # exclusive prefix = outgoing slot
                write_m = write0 & (slot0 < slots)
                ovf_val = jnp.where(
                    jnp.any(write0 & ~write_m), 1, 0
                ).reshape(1, 1)
                if packed:
                    ovf_ref[t : t + 1, :] = ovf_val
                    w_scr[t] = jnp.where(write_m, 1, 0)
                    s_scr[t] = jnp.minimum(slot0, slots)
                else:
                    ovf_ref[:] = ovf_val
                    w_scr[:] = jnp.where(write_m, 1, 0)
                    s_scr[:] = jnp.minimum(slot0, slots)
                k_lane = jnp.minimum(
                    jnp.sum(w_i, axis=0, keepdims=True), slots
                )  # [1, n_rv]
                x = k_lane
                k = 1
                while k < n_rv:
                    x = x + jnp.pad(x, ((0, 0), (k, 0)))[:, :n_rv]
                    k *= 2
                offs = x - k_lane  # [1, n_rv] exclusive
                if packed:
                    lane_scr[t, 0:1, :] = offs
                    lane_scr[t, 1:2, :] = k_lane
                else:
                    lane_scr[0:1, :] = offs
                    lane_scr[1:2, :] = k_lane

        # --- Phase B: one destination block per grid step — the same
        # build as build_rebuild_kernel._build, with the write/slot
        # tables read packet-major from scratch (NT matmuls).
        for t in range(kk):
            offs = lane_scr[t, 0:1, :] if packed else lane_scr[0:1, :]
            k_lane = lane_scr[t, 1:2, :] if packed else lane_scr[1:2, :]
            total = jnp.sum(k_lane)

            def zero_outputs(t=t):
                empty = jnp.full((blk_d, size_l), SENTINEL, vdt)
                if packed:
                    for r in range(max_l):
                        ovals_ref[r, t] = empty
                    olens_ref[t] = jnp.zeros((blk_d, max_l), jnp.int32)
                    op_ref[t] = jnp.zeros((blk_d, size_l), vdt)
                    ometa_ref[t] = jnp.zeros((blk_d, 4), jnp.int32)
                else:
                    ovals_ref[:] = jnp.full(
                        (max_l, blk_d, size_l), SENTINEL, vdt
                    )
                    olens_ref[:] = jnp.zeros((blk_d, max_l), jnp.int32)
                    op_ref[:] = jnp.zeros((blk_d, size_l), vdt)
                    ometa_ref[:] = jnp.zeros((blk_d, 4), jnp.int32)

            @pl.when(bd >= total)
            def _skip(zero_outputs=zero_outputs):
                zero_outputs()

            @pl.when(bd < total)
            def _build(t=t, offs=offs, k_lane=k_lane, total=total):
                d_col = bd + jax.lax.broadcasted_iota(
                    jnp.int32, (blk_d, 1), 0
                )  # global dst position
                live = d_col < total  # [blk_d, 1]
                offs_b = jnp.broadcast_to(offs, (blk_d, n_rv))
                k_b = jnp.broadcast_to(k_lane, (blk_d, n_rv))
                onehot = (offs_b <= d_col) & (d_col < offs_b + k_b)
                oh_i = jnp.where(onehot, 1, 0)
                iota_rv = jax.lax.broadcasted_iota(
                    jnp.int32, (blk_d, n_rv), 1
                )
                r_j = jnp.sum(oh_i * iota_rv, axis=1, keepdims=True)
                slot_lane = d_col - jnp.sum(
                    oh_i * offs_b, axis=1, keepdims=True
                )  # [blk_d, 1]
                oh_f = jnp.where(onehot, 1.0, 0.0).astype(gdt)

                def oh_mm(tbl, dt=gdt):  # [n_rv, X] -> [blk_d, X]
                    return jax.lax.dot_general(
                        oh_f.astype(dt), tbl.astype(dt),
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                        precision=_prec(dt),
                    )

                def oh_mm_t(tbl, dt=gdt):  # packet-major [n_pool, n_rv]
                    return jax.lax.dot_general(
                        oh_f.astype(dt), tbl.astype(dt),
                        (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                        precision=_prec(dt),
                    )

                w_sel = oh_mm_t(T(w_scr, t)) > 0.5  # [blk_d, n_pool]
                s_sel = oh_mm_t(T(s_scr, t)).astype(jnp.int32)
                g_t = w_sel & (s_sel == slot_lane)
                g_f = jnp.where(g_t, 1.0, 0.0)

                def gmm(field, dt=gdt):  # [n_pool, X] -> [blk_d, X]
                    return jax.lax.dot_general(
                        g_f.astype(dt), field.astype(dt),
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                        precision=_prec(dt),
                    )

                rows_g = [
                    gmm(
                        vals_ref[r, t] if packed else vals_ref[r]
                    ).astype(jnp.int32)
                    for r in range(max_l)
                ]
                lens_g = gmm(T(lens_ref, t)).astype(jnp.int32)
                p_g = gmm(T(p_ref, t)).astype(jnp.int32)
                # f32 + Precision.HIGHEST: cell ids reach n_pool-1 > 256
                # (see _prec — the round-5 wrong-draw bug).
                meta_g = gmm(T(meta_ref, t), jnp.float32).astype(
                    jnp.int32
                )
                cnt_g = meta_g[:, META_COUNT : META_COUNT + 1]
                v_g = meta_g[:, META_V : META_V + 1]
                cell_g = meta_g[:, META_CELL : META_CELL + 1]

                iota_cells = jax.lax.broadcasted_iota(
                    jnp.int32, (blk_d, n_pool), 1
                )
                oh_cell = jnp.where(
                    iota_cells == cell_g, 1.0, 0.0
                ).astype(gdt)

                def cell_mm(tbl_t, dt=gdt):  # [n_rv, n_cells]
                    return jax.lax.dot_general(
                        oh_cell.astype(dt), tbl_t.astype(dt),
                        (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                        precision=_prec(dt),
                    )

                def cell_col_mm(tbl, dt=gdt):  # [n_cells, 1]
                    return jax.lax.dot_general(
                        oh_cell.astype(dt), tbl.astype(dt),
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                        precision=_prec(dt),
                    )

                att_rows = cell_mm(T(att_ref, t))  # [blk_d, n_rv] f32
                rv_rows = cell_mm(T(rv_ref, t))
                att_c = jnp.sum(
                    att_rows * oh_f.astype(jnp.float32),
                    axis=1, keepdims=True,
                ).astype(jnp.int32)
                rv_c = jnp.sum(
                    rv_rows * oh_f.astype(jnp.float32),
                    axis=1, keepdims=True,
                ).astype(jnp.int32)
                hon_c = cell_col_mm(T(hon_ref, t)).astype(jnp.int32)

                biz = hon_c == 0
                clearp_c = biz & ((att_c & CLEAR_P_BIT) != 0)
                clearl_c = biz & ((att_c & CLEAR_L_BIT) != 0)
                v2_c = jnp.where(
                    biz & ((att_c & FORGE_BIT) != 0), rv_c, v_g
                )
                li_row = oh_mm(T(li_ref, t)).astype(jnp.int32)

                # Keep/append row algebra — mirrors rebuild_pool.
                p2 = (p_g != 0) & ~clearp_c
                if cfg.strategy == "split":
                    # forge-P: statically gated (see rebuild_pool).
                    p2 = (biz & ((att_c & FORGE_P_BIT) != 0)) | p2
                own = jnp.where(p2, li_row, SENTINEL)
                own_len = jnp.sum(
                    jnp.where(p2, 1, 0), axis=1, keepdims=True
                )
                cnt_eff = jnp.where(clearl_c, 0, cnt_g)
                dup = jnp.zeros((blk_d, 1), jnp.bool_)
                for r in range(max_l):
                    mism = jnp.sum(
                        jnp.where(rows_g[r] != own, 1, 0),
                        axis=1, keepdims=True,
                    )
                    dup |= (cnt_g > r) & (mism == 0)
                dup &= ~clearl_c
                new_cnt = jnp.where(
                    dup, cnt_eff, jnp.minimum(cnt_eff + 1, max_l)
                )

                has = live
                iota_l = jax.lax.broadcasted_iota(
                    jnp.int32, (blk_d, max_l), 1
                )
                keep_row = iota_l < cnt_eff
                new_row = ~dup & (iota_l == cnt_eff)
                olens_val = jnp.where(
                    has,
                    jnp.where(
                        new_row, own_len,
                        jnp.where(keep_row, lens_g, 0),
                    ),
                    0,
                )
                if packed:
                    olens_ref[t] = olens_val
                else:
                    olens_ref[:] = olens_val
                for r in range(max_l):
                    keep = ~clearl_c & (r < cnt_eff)
                    is_new = ~dup & (r == cnt_eff)
                    row = jnp.where(
                        is_new, own,
                        jnp.where(keep, rows_g[r], SENTINEL),
                    )
                    row = jnp.where(has, row, SENTINEL).astype(vdt)
                    if packed:
                        ovals_ref[r, t] = row
                    else:
                        ovals_ref[r] = row
                op_val = jnp.where(has & p2, 1.0, 0.0).astype(vdt)
                ometa_val = jnp.where(
                    has,
                    jnp.concatenate(
                        [
                            new_cnt,
                            v2_c,
                            jnp.ones((blk_d, 1), jnp.int32),
                            (r_off + r_j) * slots + slot_lane,
                        ],
                        axis=1,
                    ),
                    0,
                )
                if packed:
                    op_ref[t] = op_val
                    ometa_ref[t] = ometa_val
                else:
                    op_ref[:] = op_val
                    ometa_ref[:] = ometa_val

    full = lambda i: (0, 0)  # noqa: E731 — constant map (resident)
    full3 = lambda i: (0, 0, 0)  # noqa: E731
    full4 = lambda i: (0, 0, 0, 0)  # noqa: E731

    def kdim(*dims):  # prepend the trial-pack axis when packed
        return (kk,) + dims if packed else dims

    def kmap(f2, f3):
        return f3 if packed else f2

    if variant == "allrecv":
        table_specs = [
            pl.BlockSpec(kdim(size_l, n_rv), kmap(full, full3)),
            pl.BlockSpec(kdim(size_l, n_rv), kmap(full, full3)),
            pl.BlockSpec(kdim(size_l, n_rv), kmap(full, full3)),
            pl.BlockSpec(kdim(size_l, w * n_rv), kmap(full, full3)),
            pl.BlockSpec(kdim(w * size_l, n_rv), kmap(full, full3)),
        ]
    else:
        table_specs = [
            pl.BlockSpec((grp, seg_l), full),  # e (trial-invariant)
            pl.BlockSpec(
                kdim(len(r0_list), seg_l), kmap(full, full3)
            ),  # lip
            pl.BlockSpec(
                kdim(len(r0_list), seg_l), kmap(full, full3)
            ),  # lioob
        ]

    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # round_idx
    ] + (
        [pl.BlockSpec(memory_space=pltpu.SMEM)] if local else []
    ) + [
        pl.BlockSpec(
            ((max_l,) + kdim(n_pool, size_l)),
            kmap(full3, full4),
        ),  # vals
        pl.BlockSpec(kdim(n_pool, max_l), kmap(full, full3)),  # lens
        pl.BlockSpec(kdim(n_pool, size_l), kmap(full, full3)),  # p
        pl.BlockSpec(kdim(n_pool, 4), kmap(full, full3)),  # meta
        pl.BlockSpec(kdim(n_rv, size_l), kmap(full, full3)),  # li
        pl.BlockSpec(kdim(n_rv, w), kmap(full, full3)),  # vi
        pl.BlockSpec(kdim(n_pool, 1), kmap(full, full3)),  # honest
        pl.BlockSpec(kdim(n_rv, n_pool), kmap(full, full3)),  # attack^T
        pl.BlockSpec(kdim(n_rv, n_pool), kmap(full, full3)),  # rand_v^T
        pl.BlockSpec(kdim(n_rv, n_pool), kmap(full, full3)),  # late^T
    ] + table_specs

    if packed:
        out_specs = (
            pl.BlockSpec(
                (max_l, kk, blk_d, size_l), lambda i: (0, 0, i, 0)
            ),
            pl.BlockSpec((kk, blk_d, max_l), lambda i: (0, i, 0)),
            pl.BlockSpec((kk, blk_d, size_l), lambda i: (0, i, 0)),
            pl.BlockSpec((kk, blk_d, 4), lambda i: (0, i, 0)),
            pl.BlockSpec((kk, 1), full),  # overflow
            pl.BlockSpec((kk, n_rv, w), full3),  # ovi (revisited)
        )
    else:
        out_specs = (
            pl.BlockSpec((max_l, blk_d, size_l), lambda i: (0, i, 0)),
            pl.BlockSpec((blk_d, max_l), lambda i: (i, 0)),
            pl.BlockSpec((blk_d, size_l), lambda i: (i, 0)),
            pl.BlockSpec((blk_d, 4), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), full),  # overflow
            pl.BlockSpec((n_rv, w), full),  # ovi (revisited)
        )

    from qba_tpu.ops.round_kernel import promote_vma, vma_struct

    def oshp(*dims, dt=jnp.int32):
        return vma_struct(out_vma, dims, dt)

    call = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        out_shape=(
            oshp(max_l, *kdim(n_out, size_l), dt=vdt),
            oshp(*kdim(n_out, max_l)),
            oshp(*kdim(n_out, size_l), dt=vdt),
            oshp(*kdim(n_out, 4)),
            oshp(*((kk, 1) if packed else (1, 1))),
            oshp(*kdim(n_rv, w)),
        ),
        in_specs=in_specs,
        out_specs=out_specs,
        # The pool donates into the successor pool and vi into ovi (scan
        # carries — see build_rebuild_kernel / build_verdict_kernel's
        # aliasing notes; same safety argument: constant-index-map
        # sources are fetched before the first destination write-back).
        # The party-sharded variant can alias only vi (the pools have
        # different shapes).  Machine-checked: KI-5
        # `qba-tpu lint --effects` (scan-carry / alias-consistency).
        input_output_aliases=(
            {7: 5} if local else {1: 0, 2: 1, 3: 2, 4: 3, 6: 5}
        ),
        scratch_shapes=[
            pltpu.VMEM(kdim(n_pool, n_rv), jnp.int32),  # acc
            pltpu.VMEM(kdim(n_pool, n_rv), jnp.int32),  # write mask
            pltpu.VMEM(kdim(n_pool, n_rv), jnp.int32),  # clamped slots
            pltpu.VMEM(kdim(8, n_rv), jnp.int32),  # offs / k_r rows
        ],
        compiler_params=CompilerParams(
            vmem_limit_bytes=100 * 2**20,
        ),
        interpret=interpret,
    )

    def _pv(x):
        return promote_vma(out_vma, x)

    def _tail(li_arg):
        if variant == "allrecv":
            return tuple(li_arg)
        if packed:
            li_pack = jnp.stack(
                [
                    li_arg[:, r0 : r0 + grp].reshape(kk, -1)
                    for r0 in r0_list
                ],
                axis=1,
            )  # [kk, len(r0_list), seg_l]
        else:
            li_pack = jnp.stack(
                [li_arg[r0 : r0 + grp].reshape(-1) for r0 in r0_list]
            )
        li_oob_pack = ((li_pack > w) | (li_pack < 0)).astype(jnp.int32)
        return jnp.asarray(e_np), li_pack, li_oob_pack

    def _t(x):  # receiver-major draw layout (per trial when packed)
        return jnp.swapaxes(x, -1, -2)

    if local:

        def fused(round_idx, recv_off, vals, lens, p, meta, li, li_arg,
                  vi, honest_pk, attack, rand_v, late):
            args = (
                jnp.asarray([round_idx], jnp.int32),
                jnp.asarray(recv_off, jnp.int32).reshape(1),
                vals, lens, p, meta, li, vi, honest_pk,
                _t(attack), _t(rand_v), _t(late), *_tail(li_arg),
            )
            out = call(*map(_pv, args))
            return out[:4], out[5], out[4][0, 0] > 0

    else:

        def fused(round_idx, vals, lens, p, meta, li, li_arg, vi,
                  honest_pk, attack, rand_v, late):
            out = call(
                jnp.asarray([round_idx], jnp.int32),
                vals, lens, p, meta, li, vi, honest_pk,
                _t(attack), _t(rand_v), _t(late), *_tail(li_arg),
            )
            if packed:
                return out[:4], out[5], out[4][:, 0] > 0
            return out[:4], out[5], out[4][0, 0] > 0

    return fused


# ---------------------------------------------------------------------------
# Engine selection: block-size planning + compile probe.
#
# Probe verdicts persist on disk (per config shape x jax version x device
# kind): a failed remote-tunnel compile costs ~2 minutes, and Mosaic's
# scoped-vmem accounting cannot be predicted from outside (see
# round_kernel.py's pre-filter note), so the first process on a machine
# pays for the search once and every later process reads the answer.

from qba_tpu.ops.round_kernel import (  # noqa: E402 — probe cache
    _probe_disk_get,
    _probe_disk_key,
    _probe_disk_put,
)

# KI-2 contract on the three budgets below: every candidate block the
# planner screens against a budget must also satisfy it in the static
# re-derivation the lint performs (qba_tpu/analysis/memory.py) — edits
# to an estimate or budget that let an over-budget plan through fail
# `qba-tpu lint` before the TPU compile probe ever sees it.
_TILED_PREFILTER_BYTES = 48 * 2**20
_MAX_PROBE_CANDIDATES = 4


def _block_estimate(cfg: QBAConfig, blk: int,
                    n_recv: int | None = None,
                    variant: str | None = None) -> int:
    """Loose VMEM estimate for one verdict block (same spirit as
    round_kernel.fits_kernel — a screen before the authoritative compile
    probe, not a guarantee).  ``n_recv`` estimates the party-sharded
    local-receiver variant (smaller flag tiles and lane groups);
    ``variant`` None is a conservative over-approximation covering
    every verdict variant."""
    n_rv = n_recv if n_recv is not None else cfg.n_lieutenants
    tile = 4 * blk * cfg.size_l
    est = tile * (2 * cfg.max_l + 10)
    grp = _lane_group(cfg.size_l, n_rv)
    if grp > 1:
        est += tile * grp * (cfg.max_l + 6)
        if variant != "group" and grp * cfg.w <= 512:
            # Group-batched dedup intermediates (~7 [blk, grp*w] int32
            # tiles — see accept_first_per_value_group); only the
            # serial-accept variant runs this pass.
            est += 4 * blk * grp * cfg.w * 7
    if variant in (None, "group"):
        # Block-parallel accept intermediates (~5 [blk, n_rv, w] int32
        # tiles — see accept_first_per_value_all, the round-6 default
        # accept path for the group variant).
        est += 4 * blk * n_rv * cfg.w * 5
    est += 4 * blk * n_rv * 6  # flag algebra tiles
    est = int(est * (1.0 + cfg.max_l / 4.0))
    if (
        variant not in ("group", "group-serial")
        and n_recv is None
        and all_receiver_supported(cfg.size_l, cfg.w)
    ):
        # The all-receiver variant's distinct big intermediates: the
        # [blk, w*n_rv] count/pack tensors, the [blk, n_rv, w] accept
        # pass, and the [blk, 32*n_planes*size_l] PB planes.  With
        # variant unknown (None) this is a conservative max; a resolved
        # "group" variant prunes with the group estimate only.
        w = cfg.w
        est_ar = (
            4 * blk * cfg.size_l * (2 * cfg.max_l + 8)
            + 4 * blk * w * n_rv * 7
            + 2 * blk * 32 * ((w + 31) // 32) * cfg.size_l * 3
        )
        est = max(est, int(est_ar * (1.0 + cfg.max_l / 4.0)))
    return est


def _preferred_block(cfg: QBAConfig) -> int:
    """Measured sweet spot for the packet-block size.

    Round-4 HONEST sweeps (after the chunked-timing erratum,
    docs/PERF.md) at 1000/256-trial single batches: the 33-party north
    star peaks at blk=128 (8 932 rounds/s vs 8 579 at 64 and 7 143 at
    512) and the reference-scale 11p/sizeL=1000 at blk=80 (11 190 vs
    9 712 at 8).  A flat preferred value of 96 makes the log2-distance
    ordering pick the measured winner in both sweeps — finer blocks
    skip dead pool capacity, coarser blocks amortize the per-grid-step
    fixed cost; ~100 packets balances the two at both scales.
    Two-point calibrated (same caveat as the auto engine flip point):
    configs far from these two scales get the nearest candidate, with
    measured stakes of ~5-20% across the swept range."""
    return 96


def _order_candidates(cands: list[int], preferred: int) -> list[int]:
    import math

    return sorted(
        cands, key=lambda b: abs(math.log2(b) - math.log2(preferred))
    )


def block_candidates(cfg: QBAConfig, n_recv: int | None = None,
                     variant: str | None = None) -> list[int]:
    """Candidate block sizes: divisors of the pool capacity, multiples
    of 8 where possible, within the VMEM pre-filter, ordered by
    closeness to the measured sweet spot (:func:`_preferred_block`) and
    capped at ``_MAX_PROBE_CANDIDATES`` (each failed remote compile
    probe costs minutes; the disk cache makes even that a one-time
    cost).  Blocks always tile the GLOBAL pool — ``n_recv`` only
    affects the VMEM estimate of the local-receiver variant."""
    n_pool = cfg.n_lieutenants * cfg.slots
    divs = [d for d in range(n_pool, 0, -1) if n_pool % d == 0]
    cands = [d for d in divs if d % 8 == 0] or divs
    ok = [b for b in cands if _block_estimate(cfg, b, n_recv, variant)
          <= _TILED_PREFILTER_BYTES]
    return _order_candidates(ok, _preferred_block(cfg))[
        :_MAX_PROBE_CANDIDATES
    ]


def _rebuild_estimate(cfg: QBAConfig, blk_d: int,
                      n_recv: int | None = None) -> int:
    """Loose per-step VMEM estimate for the rebuild kernel: resident
    pool operands (double-buffered under vmap) + the f32
    ``[blk_d, n_pool]`` gather intermediates + gathered rows/outputs.
    ``n_recv`` estimates the party-sharded variant, whose
    receiver-indexed operands and scratch shrink with the block."""
    slots, max_l, s = cfg.slots, cfg.max_l, cfg.size_l
    n_pool = cfg.n_lieutenants * slots  # source pool stays global
    n_rv = n_recv if n_recv is not None else cfg.n_lieutenants
    vb = 2 if cfg.w <= 256 else 4
    resident = (
        vb * max_l * n_pool * s  # vals
        + vb * n_pool * s  # p
        + 4 * n_pool * max_l  # lens
        + 6 * 4 * n_pool  # meta/honest cols (128-lane tile floor)
        + 4 * 4 * n_pool * n_rv  # acc/accT/attack/rand_v operands
        + 2 * 4 * n_pool * n_rv  # wT/sT scratch
    )
    step = (
        3 * 4 * blk_d * n_pool  # G^T, w_sel, s_sel (f32)
        + 2 * blk_d * n_pool  # oh_cell
        + 4 * max_l * blk_d * s  # rows_g (i32)
        + 2 * (vb * max_l * blk_d * s + 4 * blk_d * (max_l + s + 8))
    )
    return 2 * resident + step


_REBUILD_BUDGET = 24 * 2**20


def rebuild_candidates(cfg: QBAConfig, n_recv: int | None = None) -> list[int]:
    """Candidate destination block sizes for the rebuild kernel — same
    sweet-spot ordering as :func:`block_candidates` (dead destination
    blocks skip like dead packet blocks).  The destination pool is
    LOCAL in the party-sharded variant: blocks divide
    ``n_recv * slots``."""
    n_rv = n_recv if n_recv is not None else cfg.n_lieutenants
    n_out = n_rv * cfg.slots
    divs = [d for d in range(n_out, 0, -1) if n_out % d == 0]
    cands = [d for d in divs if d % 8 == 0] or divs
    ok = [b for b in cands
          if _rebuild_estimate(cfg, b, n_recv) <= _REBUILD_BUDGET]
    return _order_candidates(ok, _preferred_block(cfg))[
        :_MAX_PROBE_CANDIDATES
    ]


_TILED_PROBE_CACHE: dict[tuple, int | None] = {}
_REBUILD_PROBE_CACHE: dict[tuple, int | None] = {}
_FUSED_PROBE_CACHE: dict[tuple, int | None] = {}
_MEGA_PROBE_CACHE: dict[tuple, int | None] = {}

# Resolver memo (PR 2 satellite): every resolve_* entry point caches
# its verdict per (config shape, backend, n_recv, explicit overrides).
# The compile-probe caches above already make the probe itself a
# one-time cost, but a sweep over many same-shape chunks still paid the
# candidate enumeration + cache plumbing on EVERY measure_batch call —
# and, off-TPU, re-ran the estimate arithmetic per call.  PROBE_STATS
# makes the caching observable (tests assert same-shape re-resolution
# adds hits, not misses or probes, and that evictions are counted).
PROBE_STATS: dict[str, int] = {
    "compile_probes": 0,
    "resolve_hits": 0,
    "resolve_misses": 0,
    "resolve_evictions": 0,
}

# LRU-bounded: one-shot CLI runs never approach the cap, but a
# long-lived serving process (qba_tpu/serve) sees unbounded mixed-shape
# traffic, and an unbounded memo is a slow leak.  The cap is generous —
# an entry is a small tuple -> scalar pair, so thousands cost ~nothing;
# the bound exists so the worst case is recomputation (a re-probe at
# most), never growth.  Hits refresh recency; evictions land in
# PROBE_STATS["resolve_evictions"] and the `qba-tpu serve --cache-stats`
# readout.
from collections import OrderedDict as _OrderedDict  # noqa: E402

_RESOLVE_CACHE: "_OrderedDict[tuple, object]" = _OrderedDict()
_RESOLVE_CACHE_CAP = int(os.environ.get("QBA_RESOLVE_CACHE_CAP", "4096"))


def set_resolve_cache_cap(cap: int) -> int:
    """Set the resolver-memo LRU capacity (entries); returns the old
    cap.  ``cap < 1`` is rejected — a zero-capacity memo would turn
    every resolution into a miss and, on TPU, a fresh compile probe."""
    global _RESOLVE_CACHE_CAP
    if cap < 1:
        raise ValueError(f"resolve cache cap must be >= 1; got {cap}")
    old, _RESOLVE_CACHE_CAP = _RESOLVE_CACHE_CAP, cap
    while len(_RESOLVE_CACHE) > _RESOLVE_CACHE_CAP:
        _RESOLVE_CACHE.popitem(last=False)
        PROBE_STATS["resolve_evictions"] += 1
    return old


def resolve_cache_info() -> dict:
    """Observable state of the resolver memo + probe caches (the
    ``qba-tpu serve --cache-stats`` readout)."""
    return {
        "resolve_cache": {
            "size": len(_RESOLVE_CACHE),
            "cap": _RESOLVE_CACHE_CAP,
            "evictions": PROBE_STATS["resolve_evictions"],
        },
        "probe_caches": {
            "tiled": len(_TILED_PROBE_CACHE),
            "rebuild": len(_REBUILD_PROBE_CACHE),
            "fused": len(_FUSED_PROBE_CACHE),
            "mega": len(_MEGA_PROBE_CACHE),
            "variant": len(_VARIANT_CACHE),
        },
        "probe_stats": dict(PROBE_STATS),
    }


def clear_resolve_caches() -> None:
    """Reset the in-process resolver memo and probe counters (tests;
    the disk probe cache and the per-kernel probe caches are separate
    and keep their one-time-cost semantics)."""
    _RESOLVE_CACHE.clear()
    for k in PROBE_STATS:
        PROBE_STATS[k] = 0


def _memo(key: tuple, compute):
    if key in _RESOLVE_CACHE:
        PROBE_STATS["resolve_hits"] += 1
        _RESOLVE_CACHE.move_to_end(key)
        return _RESOLVE_CACHE[key]
    PROBE_STATS["resolve_misses"] += 1
    val = compute()
    # compute() may itself memoize (resolve_fused_block resolves the
    # verdict block first), so insert after it returns and re-check the
    # bound against the final size.
    _RESOLVE_CACHE[key] = val
    _RESOLVE_CACHE.move_to_end(key)
    while len(_RESOLVE_CACHE) > _RESOLVE_CACHE_CAP:
        _RESOLVE_CACHE.popitem(last=False)
        PROBE_STATS["resolve_evictions"] += 1
    return val


# ---------------------------------------------------------------------------
# Warm-start seam (qba_tpu/serve): the resolver memo and the in-process
# probe/variant caches, exported as one JSON-able artifact and restored
# into a fresh process.  A server boot that imports a saved state
# resolves every covered shape with ZERO new probes or misses
# (tests/test_serve.py pins this via PROBE_STATS).  Keys are tuples of
# primitives (one nested shape tuple); JSON round-trips them as nested
# lists, restored tuple-for-tuple below.

RESOLVER_STATE_SCHEMA = "qba-tpu/resolver-state/v1"


def _key_from_json(k):
    return tuple(_key_from_json(x) if isinstance(x, list) else x for x in k)


def export_resolver_state() -> dict:
    """JSON-able snapshot of every in-process resolution verdict: the
    resolver memo plus the compile-probe and variant caches.  Values
    are scalars (block sizes, pack factors, variant names, booleans,
    None); the import side rejects a state recorded by a different jax
    version or backend — a probe verdict is only valid where it was
    probed (same discipline as the disk probe cache key)."""
    return {
        "schema": RESOLVER_STATE_SCHEMA,
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "resolve": [[list(k), v] for k, v in _RESOLVE_CACHE.items()],
        "variant": [[list(k), v] for k, v in _VARIANT_CACHE.items()],
        "probe": {
            "tiled": [[list(k), v] for k, v in _TILED_PROBE_CACHE.items()],
            "rebuild": [
                [list(k), v] for k, v in _REBUILD_PROBE_CACHE.items()
            ],
            "fused": [[list(k), v] for k, v in _FUSED_PROBE_CACHE.items()],
            "mega": [[list(k), v] for k, v in _MEGA_PROBE_CACHE.items()],
        },
    }


def import_resolver_state(state: dict) -> int:
    """Restore a :func:`export_resolver_state` snapshot; returns the
    number of entries restored (0 for a stale/mismatched state).
    Restoring does NOT touch PROBE_STATS — imported verdicts are not
    hits, misses, or probes; they are the reason none of those happen.
    Entries merge under the LRU discipline (the cap still holds)."""
    if (
        state.get("schema") != RESOLVER_STATE_SCHEMA
        or state.get("jax_version") != jax.__version__
        or state.get("backend") != jax.default_backend()
    ):
        return 0
    n = 0
    for k, v in state.get("resolve", []):
        _RESOLVE_CACHE[_key_from_json(k)] = v
        _RESOLVE_CACHE.move_to_end(_key_from_json(k))
        n += 1
    while len(_RESOLVE_CACHE) > _RESOLVE_CACHE_CAP:
        _RESOLVE_CACHE.popitem(last=False)
        PROBE_STATS["resolve_evictions"] += 1
    for cache, entries in (
        (_VARIANT_CACHE, state.get("variant", [])),
        (_TILED_PROBE_CACHE, state.get("probe", {}).get("tiled", [])),
        (_REBUILD_PROBE_CACHE, state.get("probe", {}).get("rebuild", [])),
        (_FUSED_PROBE_CACHE, state.get("probe", {}).get("fused", [])),
        # Absent in pre-megakernel snapshots — .get keeps schema v1.
        (_MEGA_PROBE_CACHE, state.get("probe", {}).get("mega", [])),
    ):
        for k, v in entries:
            cache[_key_from_json(k)] = v
            n += 1
    return n


def _resolve_key(kind: str, cfg: QBAConfig, n_recv=None,
                 extra: tuple = ()) -> tuple:
    # tiled_block / trial_pack are explicit overrides the resolvers
    # honor; n_dishonest bounds the round count some estimates read.
    return (
        kind, _shape_key(cfg), cfg.n_dishonest, cfg.tiled_block,
        getattr(cfg, "trial_pack", None), jax.default_backend(), n_recv,
    ) + tuple(extra)


def _shape_key(cfg: QBAConfig) -> tuple:
    return (cfg.n_lieutenants, cfg.slots, cfg.max_l, cfg.size_l, cfg.w)


def _probe_plan(kernel_name, cfg, candidates, compile_one, cache,
                fallback_desc, extra: str = ""):
    """Shared cached compile-probe search: first candidate block size
    that compiles wins.  Memory cache per process, disk cache per
    machine (see the module note above); ``compile_one(blk)`` must
    raise on compile failure and never execute anything.  ``extra``
    distinguishes kernel variants of the same config shape (the
    party-sharded ``n_recv`` builds)."""
    key = _shape_key(cfg) + (extra,)
    if key in cache:
        return cache[key]
    dkey = _probe_disk_key(kernel_name, cfg, extra=extra)
    hit = _probe_disk_get(dkey)
    if hit is not None:
        blk = None if hit < 0 else hit
        cache[key] = blk
        return blk
    from qba_tpu.ops.round_kernel import probe_error_transient

    chosen: int | None = None
    last_err: Exception | None = None
    transient_seen = False
    transient_abandoned = False  # a candidate given up on a transient
    for blk in candidates:
        this_transient = False
        for _attempt in range(2):  # retry once on transient tunnel errors
            try:
                compile_one(blk)
                chosen = blk
                break
            except Exception as e:  # compile failures only (no execution)
                last_err = e
                this_transient = probe_error_transient(e)
                if this_transient:
                    transient_seen = True
                    continue  # a helper crash is not a shape verdict
                break  # deterministic (VMEM/lowering) -> next candidate
        if chosen is not None:
            break
        if this_transient:
            # This candidate's FINAL error was transient: its verdict is
            # unknown, so any later candidate's win is provisional.
            transient_abandoned = True
    if chosen is None and last_err is not None:
        warn_and_record(
            f"{kernel_name} kernel compile probe failed for every block "
            f"candidate at (n_parties={cfg.n_parties}, "
            f"size_l={cfg.size_l}, slots={cfg.slots}); "
            f"{fallback_desc}: {last_err!r:.500}",
            QBAProbeWarning,
            site="ops.round_kernel_tiled._probe_plan",
            stacklevel=3,
            reason="all_block_candidates_failed",
            kernel=kernel_name,
            n_parties=cfg.n_parties,
            size_l=cfg.size_l,
            slots=cfg.slots,
            error=repr(last_err)[:500],
        )
    if chosen is not None or not transient_seen:
        # Cache only real verdicts in-process: a failure born from a
        # transient tunnel error would pin this shape to a slower
        # engine — for the process lifetime via the memory cache, for
        # every later process via the disk cache (observed — see
        # round_kernel.probe_error_transient).  The cost of not caching
        # is a re-probe on the next call: the desired retry.
        cache[key] = chosen
        if not transient_abandoned:
            # Disk-persist only verdicts whose losing candidates all
            # failed *deterministically*: a candidate abandoned on a
            # transient tunnel error has an unknown verdict, and a
            # later (slower) candidate's win must not pin this shape
            # machine-wide — keep it in-process only so the next
            # process re-probes the abandoned candidate (ADVICE r4).
            # Deterministic earlier failures (VMEM OOM, lowering) are
            # real shape verdicts and persist as before, even when a
            # transient blip happened elsewhere in the search.
            _probe_disk_put(dkey, -1 if chosen is None else chosen)
    return chosen


def _probe_shapes(cfg: QBAConfig):
    """Batched ShapeDtypeStruct factory for the probes.  Probing under a
    small vmap matters: batching prepends a grid dimension, and Pallas
    double-buffers even constant-index-map operands across batch
    elements — an unbatched probe under-counts VMEM by ~2x (observed:
    batch 2 compiles, batch 256 OOMs at identical per-step shapes until
    the vmem cap is raised; see build_rebuild_kernel)."""
    i32 = jnp.int32
    vdt = pool_vals_dtype(cfg)

    def shp(*dims, dt=i32):
        return jax.ShapeDtypeStruct((2,) + dims, dt)

    return shp, i32, vdt


_LANE = 128  # v5e minor-dim tile width (the padding model's constant)


def _pad(x: int, m: int) -> int:
    return -(-x // m) * m


def pool_bytes(cfg: QBAConfig, trials: int = 1,
               n_recv: int | None = None) -> dict:
    """Logical vs TPU-padded resident bytes of the carried pool — the
    planning view of the HBM ceiling (VERDICT r3 item 2).

    ``n_recv`` narrows the receiver axis to a per-device shard (tp-way
    party sharding carries ``n_recv = n_lieutenants // tp`` receivers
    per device), which is the per-device resident pool the sharded
    KI-2 model budgets against.

    Padding model (observed on v5e): the minor dim tiles to 128 lanes
    (so ``size_l=64`` doubles ``vals``/``p`` and any narrow column pays
    the full 128-lane tile), the second-minor to 8 sublanes (16 for
    bf16's packed tiling).  The round-4 meta packing collapsed four
    [cap, 1] columns into one [cap, 4] tensor — identical logical
    bytes, 4x less padded — and kernel donation removed the second
    resident pool generation the scan carry used to keep."""
    n_rv, slots, max_l, s = (
        n_recv if n_recv is not None else cfg.n_lieutenants,
        cfg.slots, cfg.max_l, cfg.size_l,
    )
    cap = n_rv * slots
    vb = 2 if pool_vals_dtype(cfg) == jnp.bfloat16 else 4
    pad, lane = _pad, _LANE
    logical = (
        vb * max_l * cap * s  # vals
        + 4 * cap * max_l  # lens
        + vb * cap * s  # p
        + 4 * cap * 4  # meta
    )
    padded = (
        vb * max_l * pad(cap, 16 if vb == 2 else 8) * pad(s, lane)
        + 4 * pad(cap, 8) * pad(max_l, lane)
        + vb * pad(cap, 16 if vb == 2 else 8) * pad(s, lane)
        + 4 * pad(cap, 8) * pad(4, lane)
    )
    return {
        "logical_bytes": logical * trials,
        "padded_bytes": padded * trials,
        "pad_ratio": round(padded / logical, 2),
    }


def roofline_model(cfg: QBAConfig, trials: int = 1) -> dict:
    """Analytic per-batch HBM traffic UPPER BOUND for the tiled round
    loop (VERDICT r4 item 2) — the stream-everything model: per round,
    the verdict kernel's BlockSpec prefetch pulls the padded pool +
    draw tables + li/vi once, and the rebuild kernel reads the pool and
    writes its donated successor.  Real traffic is at most this (the
    scheduler may elide dead-block lanes; nothing forces it to), so the
    implied bandwidth `bytes / device_seconds` is an upper bound on
    achieved HBM bandwidth — useful to show the engine is NOT
    bandwidth-bound (docs/PERF.md round 5: live-lane compute dominates
    at the north star), not to claim a utilization figure.
    """
    pool_term = 3 * pool_bytes(cfg)["padded_bytes"]  # verdict r + rebuild r/w
    n_rv, slots = cfg.n_lieutenants, cfg.slots
    cap = n_rv * slots
    pad, lane = _pad, _LANE
    # Per-trial per-round operand bytes beyond the pool itself.
    draws = 3 * 4 * pad(cap, 8) * pad(n_rv, lane)  # att/rv/late i32
    li_vi = 4 * pad(n_rv, 8) * (pad(cfg.size_l, lane) + pad(cfg.w, lane))
    honest = 4 * pad(cap, 8) * lane  # [cap, 1] column pays a full tile
    acc = 4 * pad(cap, 8) * lane  # verdict->rebuild handoff
    per_round = pool_term + draws + li_vi + honest + acc
    return {
        "per_round_per_trial_bytes": per_round,
        "batch_bytes_upper_bound": per_round * cfg.n_rounds * trials,
        "pool_share": round(pool_term / per_round, 3),
    }


_VARIANT_CACHE: dict[tuple, bool] = {}


def _probe_verdict_compile(cfg: QBAConfig, blk_probe: int, variant: str,
                           n_recv: int | None = None) -> None:
    """Data-free compile probe of one verdict-kernel build (raises on
    failure, never executes).  Shared by the variant resolvers; on
    success the caller may seed the block plan with ``blk_probe``."""
    PROBE_STATS["compile_probes"] += 1
    shp, i32, vdt = _probe_shapes(cfg)
    n_pool = cfg.n_lieutenants * cfg.slots
    n_rv = n_recv if n_recv is not None else cfg.n_lieutenants
    local = n_recv is not None
    s, w, gdt = cfg.size_l, cfg.w, _gdt(cfg)
    if variant == "allrecv":
        li_shape = (
            shp(s, n_rv, dt=jnp.float32), shp(s, n_rv, dt=jnp.float32),
            shp(s, n_rv, dt=jnp.float32), shp(s, w * n_rv, dt=gdt),
            shp(w * s, n_rv, dt=gdt),
        )
    else:
        li_shape = shp(n_rv, s)
    verdict = build_verdict_kernel(
        cfg, blk_probe, n_recv=n_recv, variant=variant
    )
    off = (jax.ShapeDtypeStruct((), i32),) if local else ()
    in_axes = (None,) * (1 + len(off)) + (0,) * 10
    jax.jit(jax.vmap(verdict, in_axes=in_axes)).lower(
        jax.ShapeDtypeStruct((), i32),
        *off,
        shp(cfg.max_l, n_pool, s, dt=vdt),
        shp(n_pool, cfg.max_l),
        shp(n_pool, s, dt=vdt), shp(n_pool, 4),
        li_shape, shp(n_rv, w), shp(n_pool, 1),
        shp(n_pool, n_rv), shp(n_pool, n_rv), shp(n_pool, n_rv),
    ).compile()


def _seed_block_plan(cfg: QBAConfig, blk_probe: int, extra: str) -> None:
    """Seed the block plan with a just-compiled candidate so
    tiled_kernel_plan does not pay the same ~2-minute remote compile a
    second time (it probes the same first candidate)."""
    plan_key = _shape_key(cfg) + (extra,)
    _TILED_PROBE_CACHE.setdefault(plan_key, blk_probe)
    _probe_disk_put(
        _probe_disk_key("tiled-verdict", cfg, extra=extra), blk_probe
    )


def _resolve_group_accept(cfg: QBAConfig,
                          n_recv: int | None = None) -> str:
    """Accept-path resolution within the group family: ``"group"`` (the
    round-6 block-parallel first-accept reduction) when that kernel
    compiles, demoting to ``"group-serial"`` (the pre-round-6
    per-receiver accept chain, which has compiled at every supported
    shape since round 3) on a deterministic compile failure.  Off-TPU
    there is no real compile to probe: the parallel path is the static
    default, so the CPU equivalence suites exercise the same math the
    TPU runs."""
    if jax.default_backend() != "tpu":
        return "group"
    # Probe at the block size the engine will actually run with — an
    # explicit tiled_block bypasses the block-plan probe entirely, so a
    # variant verdict from a different block would not transfer.
    n_pool = cfg.n_lieutenants * cfg.slots
    if cfg.tiled_block is not None and n_pool % cfg.tiled_block == 0:
        blk_probe = cfg.tiled_block
    else:
        cands = block_candidates(cfg, n_recv, "group")
        if not cands:
            return "group-serial"
        blk_probe = cands[0]
    key = _shape_key(cfg) + ("accept", n_recv, blk_probe)
    if key in _VARIANT_CACHE:
        return "group" if _VARIANT_CACHE[key] else "group-serial"
    dkey = _probe_disk_key(
        "tiled-verdict-accept", cfg,
        extra=f"blk{blk_probe}"
        + (f"recv{n_recv}" if n_recv is not None else ""),
    )
    hit = _probe_disk_get(dkey)
    if hit is not None:
        _VARIANT_CACHE[key] = hit > 0
        return "group" if hit > 0 else "group-serial"
    from qba_tpu.ops.round_kernel import probe_error_transient

    err: Exception | None = None
    try:
        _probe_verdict_compile(cfg, blk_probe, "group", n_recv)
        if cfg.tiled_block is None:
            _seed_block_plan(
                cfg, blk_probe,
                (f"recv{n_recv}" if n_recv is not None else ""),
            )
    except Exception as e:
        if probe_error_transient(e):
            # Unknown verdict — do not cache; take the proven serial
            # path for this process only (observable, mirroring the
            # _probe_plan fallback message — ADVICE r5 item 2).
            warn_and_record(
                "tiled-verdict accept-path compile probe hit a "
                f"transient error at (n_parties={cfg.n_parties}, "
                f"size_l={cfg.size_l}, slots={cfg.slots}); falling back "
                "to the serial accept chain ('group-serial') for this "
                f"process without caching: {e!r:.500}",
                QBAProbeWarning,
                site="ops.round_kernel_tiled._resolve_group_accept",
                stacklevel=3,
                reason="transient_probe_error",
                variant_from="group",
                variant_to="group-serial",
                n_parties=cfg.n_parties,
                size_l=cfg.size_l,
                slots=cfg.slots,
                error=repr(e)[:500],
            )
            return "group-serial"
        err = e
    ok = err is None
    _VARIANT_CACHE[key] = ok
    _probe_disk_put(dkey, 1 if ok else 0)
    if not ok:
        warn_and_record(
            "tiled-verdict parallel accept reduction failed to compile "
            f"at (n_parties={cfg.n_parties}, size_l={cfg.size_l}, "
            f"slots={cfg.slots}, blk={blk_probe}); demoting to the "
            f"serial accept chain ('group-serial'): {err!r:.500}",
            QBADemotionWarning,
            site="ops.round_kernel_tiled._resolve_group_accept",
            stacklevel=3,
            variant_from="group",
            variant_to="group-serial",
            n_parties=cfg.n_parties,
            size_l=cfg.size_l,
            slots=cfg.slots,
            blk=blk_probe,
            error=repr(err)[:500],
        )
    return "group" if ok else "group-serial"


def _resolve_verdict_variant_impl(cfg: QBAConfig,
                                  n_recv: int | None = None) -> str:
    """Which verdict-kernel variant this config runs: ``"allrecv"``
    (all receivers batched per block — docs/PERF.md round 5) where the
    exactness gate holds and the kernel compiles, else the group family
    — ``"group"`` (lane-group flag algebra + the round-6 block-parallel
    first-accept reduction) when it compiles, ``"group-serial"`` (the
    pre-round-6 accept chain) as the compile fallback.  On TPU the
    verdicts are cached compile probes (same machinery as the
    block-size plans); off-TPU (interpret mode) the static gates alone
    decide, so the CPU equivalence suites exercise the same math the
    TPU runs.  The party-sharded engine (``n_recv``) stays in the group
    family."""
    if n_recv is not None or not all_receiver_supported(cfg.size_l, cfg.w):
        return _resolve_group_accept(cfg, n_recv)
    if jax.default_backend() != "tpu":
        return "allrecv"
    # Probe at the block size the engine will actually run with (see
    # _resolve_group_accept).
    n_pool = cfg.n_lieutenants * cfg.slots
    if cfg.tiled_block is not None and n_pool % cfg.tiled_block == 0:
        blk_probe = cfg.tiled_block
    else:
        cands = block_candidates(cfg, variant="allrecv")
        if not cands:
            return _resolve_group_accept(cfg)
        blk_probe = cands[0]
    key = _shape_key(cfg) + (blk_probe,)
    if key in _VARIANT_CACHE:
        return (
            "allrecv" if _VARIANT_CACHE[key]
            else _resolve_group_accept(cfg)
        )
    dkey = _probe_disk_key(
        "tiled-verdict-variant", cfg, extra=f"blk{blk_probe}"
    )
    hit = _probe_disk_get(dkey)
    if hit is not None:
        _VARIANT_CACHE[key] = hit > 0
        return "allrecv" if hit > 0 else _resolve_group_accept(cfg)
    from qba_tpu.ops.round_kernel import probe_error_transient

    try:
        _probe_verdict_compile(cfg, blk_probe, "allrecv")
        ok = True
        if cfg.tiled_block is None:
            _seed_block_plan(cfg, blk_probe, "+allrecv")
    except Exception as e:
        if probe_error_transient(e):
            # Unknown verdict — do not cache.  Warn so variant flapping
            # across processes is observable (ADVICE r5 item 2; mirrors
            # the _probe_plan fallback message), then resolve within
            # the group family for this process.
            warn_and_record(
                "tiled-verdict variant compile probe hit a transient "
                f"error at (n_parties={cfg.n_parties}, "
                f"size_l={cfg.size_l}, slots={cfg.slots}); falling back "
                "to the group variant for this process without caching "
                f"(the variant may flap across runs): {e!r:.500}",
                QBAProbeWarning,
                site="ops.round_kernel_tiled._resolve_verdict_variant",
                stacklevel=2,
                reason="transient_probe_error",
                variant_from="allrecv",
                variant_to="group",
                n_parties=cfg.n_parties,
                size_l=cfg.size_l,
                slots=cfg.slots,
                error=repr(e)[:500],
            )
            return _resolve_group_accept(cfg)
        ok = False
    _VARIANT_CACHE[key] = ok
    _probe_disk_put(dkey, 1 if ok else 0)
    return "allrecv" if ok else _resolve_group_accept(cfg)


def resolve_verdict_variant(cfg: QBAConfig,
                            n_recv: int | None = None) -> str:
    """Memoized :func:`_resolve_verdict_variant_impl` — the verdict per
    (config shape, backend, ``n_recv``) is computed once per process;
    same-shape sweeps skip the probe path entirely (PROBE_STATS counts
    the hits)."""
    return _memo(
        _resolve_key("variant", cfg, n_recv),
        lambda: _resolve_verdict_variant_impl(cfg, n_recv),
    )


def tiled_kernel_plan(cfg: QBAConfig, n_recv: int | None = None,
                      variant: str | None = None) -> int | None:
    """The verdict-kernel block size the tiled engine will use for this
    config, or None if no candidate compiles.  Like
    round_kernel.kernel_compiles, the authoritative gate is a cached
    data-free compile probe per shape — Mosaic's scoped-vmem use cannot
    be modeled reliably from outside.  ``n_recv`` probes the
    party-sharded local-receiver variant; ``variant`` defaults to
    :func:`resolve_verdict_variant`'s pick."""
    local = n_recv is not None

    if variant is None:
        variant = resolve_verdict_variant(cfg, n_recv)

    def compile_one(blk):
        _probe_verdict_compile(cfg, blk, variant, n_recv)

    return _probe_plan(
        "tiled-verdict", cfg, block_candidates(cfg, n_recv, variant),
        compile_one,
        _TILED_PROBE_CACHE, "falling back to the XLA round engine",
        extra=(f"recv{n_recv}" if local else "")
        + {"allrecv": "+allrecv", "group-serial": "+accser"}.get(
            variant, ""
        ),
    )


def rebuild_kernel_plan(cfg: QBAConfig, n_recv: int | None = None) -> int | None:
    """Destination block size for the Pallas rebuild kernel, or None if
    no candidate compiles (the XLA :func:`rebuild_pool` then takes
    over).  ``n_recv`` probes the party-sharded variant."""
    shp, i32, vdt = _probe_shapes(cfg)
    slots = cfg.slots
    n_pool = cfg.n_lieutenants * slots
    n_rv = n_recv if n_recv is not None else cfg.n_lieutenants
    local = n_recv is not None

    def compile_one(blk_d):
        PROBE_STATS["compile_probes"] += 1
        rebuild = build_rebuild_kernel(cfg, blk_d, n_recv=n_recv)
        off = (jax.ShapeDtypeStruct((), i32),) if local else ()
        in_axes = (None,) * (1 + len(off)) + (0,) * 9
        jax.jit(jax.vmap(rebuild, in_axes=in_axes)).lower(
            jax.ShapeDtypeStruct((), i32),
            *off,
            shp(cfg.max_l, n_pool, cfg.size_l, dt=vdt),
            shp(n_pool, cfg.max_l),
            shp(n_pool, cfg.size_l, dt=vdt), shp(n_pool, 4),
            shp(n_rv, cfg.size_l), shp(n_pool, n_rv),
            shp(n_pool, n_rv), shp(n_pool, n_rv), shp(n_pool, 1),
        ).compile()

    return _probe_plan(
        "tiled-rebuild", cfg, rebuild_candidates(cfg, n_recv), compile_one,
        _REBUILD_PROBE_CACHE, "using the XLA rebuild fallback",
        extra=f"recv{n_recv}" if local else "",
    )


def _resolve_rebuild_block_impl(cfg: QBAConfig,
                                n_recv: int | None = None) -> int | None:
    """Block size the tiled engine's rebuild kernel runs with, or None
    to use the XLA rebuild fallback.

    An explicit ``tiled_block`` is sized for the *verdict* kernel (whose
    per-block footprint shrinks with the block); the rebuild kernel's
    G^T/one-hot intermediates grow as ``blk_d * n_pool``, so the
    explicit value is honored only where its estimate fits (and, in the
    party-sharded case, divides the LOCAL destination pool) — otherwise
    the probe picks, keeping the XLA fallback reachable instead of
    failing at trial-compile time."""
    n_rv = n_recv if n_recv is not None else cfg.n_lieutenants
    n_out = n_rv * cfg.slots
    if cfg.tiled_block is not None and n_out % cfg.tiled_block == 0:
        if (
            jax.default_backend() != "tpu"
            or _rebuild_estimate(cfg, cfg.tiled_block, n_recv)
            <= _REBUILD_BUDGET
        ):
            return cfg.tiled_block
    if jax.default_backend() == "tpu":
        return rebuild_kernel_plan(cfg, n_recv)
    cands = rebuild_candidates(cfg, n_recv)
    return cands[0] if cands else n_out


def resolve_rebuild_block(cfg: QBAConfig,
                          n_recv: int | None = None) -> int | None:
    """Memoized :func:`_resolve_rebuild_block_impl` (see
    :func:`resolve_verdict_variant`)."""
    return _memo(
        _resolve_key("rebuild", cfg, n_recv),
        lambda: _resolve_rebuild_block_impl(cfg, n_recv),
    )


def _resolve_tiled_block_impl(cfg: QBAConfig,
                              n_recv: int | None = None) -> int:
    """The block size the tiled engine runs with: the config's explicit
    ``tiled_block`` when set (tests force small blocks to exercise the
    multi-block path off-TPU), else the probe's pick on TPU, else the
    largest pre-filter candidate (interpret mode has no real compile to
    probe)."""
    if cfg.tiled_block is not None:
        return cfg.tiled_block
    if jax.default_backend() == "tpu":
        blk = tiled_kernel_plan(cfg, n_recv)
        if blk is not None:
            return blk
    # Pass the resolved variant so the VMEM estimate matches the kernel
    # the engine actually builds (ADVICE r5 item 4 — a variant=None
    # estimate over-approximates across all variants and can pick a
    # different block than the probed plan would).
    cands = block_candidates(cfg, n_recv, resolve_verdict_variant(cfg, n_recv))
    return cands[0] if cands else cfg.n_lieutenants * cfg.slots


def resolve_tiled_block(cfg: QBAConfig, n_recv: int | None = None) -> int:
    """Memoized :func:`_resolve_tiled_block_impl` (see
    :func:`resolve_verdict_variant`)."""
    return _memo(
        _resolve_key("tiled", cfg, n_recv),
        lambda: _resolve_tiled_block_impl(cfg, n_recv),
    )


# ---------------------------------------------------------------------------
# Fused round kernel: planning + compile probe (docs/PERF.md round 7).

_FUSED_BUDGET = 32 * 2**20


def _fused_estimate(cfg: QBAConfig, blk_d: int, blk_v: int,
                    n_recv: int | None = None,
                    trial_pack: int = 1) -> int:
    """Loose per-step VMEM estimate for the fused round kernel: the
    rebuild kernel's resident + destination-step terms, the acc/write/
    slot scratch (packet-major, ``3 x [n_pool, n_rv]`` int32), and the
    verdict sub-block's intermediates at ``blk_v`` — all scaled by the
    trial-pack factor except the verdict/build step terms' peak, which
    the static per-trial loop serializes (one trial's intermediates
    live at a time; Mosaic may still overlap two, hence the 2x)."""
    n_rv = n_recv if n_recv is not None else cfg.n_lieutenants
    n_pool = cfg.n_lieutenants * cfg.slots
    resident = _rebuild_estimate(cfg, blk_d, n_recv)
    scratch = 3 * 4 * n_pool * n_rv + 4 * 8 * n_rv
    step_v = _block_estimate(cfg, blk_v, n_recv, "group")
    return trial_pack * (resident + scratch) + 2 * step_v


def fused_candidates(cfg: QBAConfig, n_recv: int | None = None,
                     blk_v: int | None = None,
                     trial_pack: int = 1) -> list[int]:
    """Candidate destination block sizes for the fused kernel — the
    rebuild kernel's candidate rule under the fused VMEM estimate."""
    if blk_v is None:
        blk_v = resolve_tiled_block(cfg, n_recv)
    n_rv = n_recv if n_recv is not None else cfg.n_lieutenants
    n_out = n_rv * cfg.slots
    divs = [d for d in range(n_out, 0, -1) if n_out % d == 0]
    cands = [d for d in divs if d % 8 == 0] or divs
    ok = [
        b for b in cands
        if _fused_estimate(cfg, b, blk_v, n_recv, trial_pack)
        <= _FUSED_BUDGET
    ]
    return _order_candidates(ok, _preferred_block(cfg))[
        :_MAX_PROBE_CANDIDATES
    ]


def _probe_fused_compile(cfg: QBAConfig, blk_d: int, blk_v: int,
                         variant: str, n_recv: int | None = None,
                         trial_pack: int = 1) -> None:
    """Data-free compile probe of one fused-round-kernel build (raises
    on failure, never executes)."""
    PROBE_STATS["compile_probes"] += 1
    shp, i32, vdt = _probe_shapes(cfg)
    n_pool = cfg.n_lieutenants * cfg.slots
    n_rv = n_recv if n_recv is not None else cfg.n_lieutenants
    local = n_recv is not None
    s, w, gdt = cfg.size_l, cfg.w, _gdt(cfg)
    kd = (trial_pack,) if trial_pack > 1 else ()

    def kshp(*dims, dt=i32):
        return shp(*(kd + dims), dt=dt)

    if variant == "allrecv":
        li_arg = (
            kshp(s, n_rv, dt=jnp.float32), kshp(s, n_rv, dt=jnp.float32),
            kshp(s, n_rv, dt=jnp.float32), kshp(s, w * n_rv, dt=gdt),
            kshp(w * s, n_rv, dt=gdt),
        )
    else:
        li_arg = kshp(n_rv, s)
    fused = build_fused_round_kernel(
        cfg, blk_d, blk_v, n_recv=n_recv, variant=variant,
        trial_pack=trial_pack,
    )
    off = (jax.ShapeDtypeStruct((), i32),) if local else ()
    in_axes = (None,) * (1 + len(off)) + (0,) * 11
    jax.jit(jax.vmap(fused, in_axes=in_axes)).lower(
        jax.ShapeDtypeStruct((), i32),
        *off,
        jax.ShapeDtypeStruct((2, cfg.max_l) + kd + (n_pool, s), vdt),
        kshp(n_pool, cfg.max_l),
        kshp(n_pool, s, dt=vdt), kshp(n_pool, 4),
        kshp(n_rv, s), li_arg, kshp(n_rv, w), kshp(n_pool, 1),
        kshp(n_pool, n_rv), kshp(n_pool, n_rv), kshp(n_pool, n_rv),
    ).compile()


def fused_kernel_plan(cfg: QBAConfig, n_recv: int | None = None,
                      variant: str | None = None,
                      trial_pack: int = 1) -> int | None:
    """Destination block size for the fused round kernel, or None if no
    candidate compiles (the two-kernel tiled path then takes over —
    the fused engine's demotion target)."""
    local = n_recv is not None
    if variant is None:
        variant = resolve_verdict_variant(cfg, n_recv)
    blk_v = resolve_tiled_block(cfg, n_recv)

    def compile_one(blk_d):
        _probe_fused_compile(
            cfg, blk_d, blk_v, variant, n_recv, trial_pack
        )

    return _probe_plan(
        "tiled-fused", cfg,
        fused_candidates(cfg, n_recv, blk_v, trial_pack), compile_one,
        _FUSED_PROBE_CACHE, "falling back to the two-kernel tiled path",
        extra=(f"recv{n_recv}" if local else "")
        + {"allrecv": "+allrecv", "group-serial": "+accser"}.get(
            variant, ""
        )
        + (f"+pack{trial_pack}" if trial_pack > 1 else "")
        + f"+v{blk_v}",
    )


def _resolve_fused_block_impl(cfg: QBAConfig,
                              n_recv: int | None = None,
                              trial_pack: int = 1) -> int | None:
    """Destination block size the fused engine runs with, or None to
    demote to the two-kernel tiled path.  An explicit ``tiled_block``
    is honored where it divides the destination pool and fits the fused
    estimate (same discipline as :func:`resolve_rebuild_block`)."""
    n_rv = n_recv if n_recv is not None else cfg.n_lieutenants
    n_out = n_rv * cfg.slots
    blk_v = resolve_tiled_block(cfg, n_recv)
    if cfg.tiled_block is not None and n_out % cfg.tiled_block == 0:
        if (
            jax.default_backend() != "tpu"
            or _fused_estimate(
                cfg, cfg.tiled_block, blk_v, n_recv, trial_pack
            ) <= _FUSED_BUDGET
        ):
            return cfg.tiled_block
    if jax.default_backend() == "tpu":
        return fused_kernel_plan(cfg, n_recv, trial_pack=trial_pack)
    cands = fused_candidates(cfg, n_recv, blk_v, trial_pack)
    return cands[0] if cands else n_out


def resolve_fused_block(cfg: QBAConfig, n_recv: int | None = None,
                        trial_pack: int = 1) -> int | None:
    """Memoized :func:`_resolve_fused_block_impl` (see
    :func:`resolve_verdict_variant`)."""
    return _memo(
        _resolve_key("fused", cfg, n_recv, (trial_pack,)),
        lambda: _resolve_fused_block_impl(cfg, n_recv, trial_pack),
    )


def _resolve_trial_pack_impl(cfg: QBAConfig) -> int:
    """The fused engine's trial-pack factor ``k``: the config's
    explicit ``trial_pack`` when set (tests force ``k > 1`` off-TPU),
    else — on TPU, for configs whose whole packed working set is small
    (the per-grid-step fixed overhead the packing amortizes dominates
    exactly there, docs/PERF.md round 5) — the largest of 8/4/2 whose
    fused kernel fits the estimate and compiles; 1 otherwise."""
    if cfg.trial_pack is not None:
        return cfg.trial_pack
    if jax.default_backend() != "tpu":
        return 1
    blk_v = resolve_tiled_block(cfg)
    for k in (8, 4, 2):
        cands = fused_candidates(cfg, None, blk_v, k)
        if not cands:
            continue
        if fused_kernel_plan(cfg, trial_pack=k) is not None:
            return k
    return 1


def resolve_trial_pack(cfg: QBAConfig) -> int:
    """Memoized :func:`_resolve_trial_pack_impl` (see
    :func:`resolve_verdict_variant`)."""
    return _memo(
        _resolve_key("pack", cfg),
        lambda: _resolve_trial_pack_impl(cfg),
    )


# ---------------------------------------------------------------------------
# Trial megakernel: planning + compile probe (docs/PERF.md round 8).
# The kernel itself lives in ops/trial_megakernel.py (it imports the
# verdict helper from this module); the planner lives here with the
# other resolvers so the serve warm-start artifact covers it.

_MEGA_BUDGET = 64 * 2**20

# Reserve held back from the megakernel budget when the launch also
# carries the in-VMEM GF(2) generation prologue or the in-kernel ring
# exchange: both phases materialize transients the loose estimates
# below do not itemize (the sweep's per-step one-hot selects, the
# in-flight DMA slot plus the deposit window), so a plan that fits
# only by consuming the last budget bytes demotes instead (KI-2).
_MEGA_RESERVE = 8 * 2**20


def _mega_gen_bytes(cfg: QBAConfig, trial_pack: int = 1) -> int:
    """VMEM the gen-fused prologue adds to the megakernel launch: the
    static packed tableaux of both circuit families, the per-shot
    broadcast tableau planes the measurement sweep carries (the
    dominant term — 2 planes x B shots x 2T rows x W words), the
    per-shot phase/coin/flip operands, and the decoded-operand
    scratch."""
    from qba_tpu.gf2.bitops import n_words

    t2 = 2 * cfg.total_qubits
    wds = n_words(cfg.total_qubits)
    b = trial_pack * cfg.size_l
    tables = 4 * t2 * wds * 4
    planes = 2 * b * t2 * wds * 4
    vectors = b * (3 * t2 + 2 * cfg.total_qubits + 1) * 4
    decoded = 4 * trial_pack * (
        4 * cfg.n_lieutenants * cfg.size_l + cfg.size_l * (
            cfg.n_parties + 1
        )
    )
    return tables + planes + vectors + decoded


def _mega_estimate(cfg: QBAConfig, blk_d: int, blk_v: int,
                   trial_pack: int = 1, gen: bool = False) -> int:
    """Loose VMEM estimate for the one-launch trial kernel: the fused
    round kernel's per-step terms plus what the in-kernel loop keeps
    resident for the whole launch — BOTH pool halves (ping-pong A/B
    scratch), the round-stacked draw slabs, and the entry-decode
    one-hot intermediates.  ``gen=True`` adds the in-VMEM generation
    terms (:func:`_mega_gen_bytes`)."""
    n_rv = cfg.n_lieutenants
    n_pool = n_rv * cfg.slots
    s, max_l = cfg.size_l, cfg.max_l
    vb = jnp.dtype(pool_vals_dtype(cfg)).itemsize
    pool = (
        vb * max_l * n_pool * s + 4 * n_pool * max_l
        + vb * n_pool * s + 4 * n_pool * 4
    )
    draws = 3 * 4 * cfg.n_rounds * n_rv * n_pool
    decode = 4 * n_pool * n_rv + 4 * n_pool * max(s, cfg.w)
    return (
        _fused_estimate(cfg, blk_d, blk_v, None, trial_pack)
        + trial_pack * (2 * pool + draws + decode)
        + (_mega_gen_bytes(cfg, trial_pack) if gen else 0)
    )


def _mega_budget(gen: bool = False) -> int:
    """Effective megakernel budget — the gen-fused launch gives up
    :data:`_MEGA_RESERVE` for the prologue's unpriced transients."""
    return _MEGA_BUDGET - (_MEGA_RESERVE if gen else 0)


def mega_candidates(cfg: QBAConfig, blk_v: int | None = None,
                    trial_pack: int = 1, gen: bool = False) -> list[int]:
    """Candidate destination block sizes for the trial megakernel —
    the fused kernel's candidate rule under the megakernel estimate."""
    if blk_v is None:
        blk_v = resolve_tiled_block(cfg)
    n_pool = cfg.n_lieutenants * cfg.slots
    divs = [d for d in range(n_pool, 0, -1) if n_pool % d == 0]
    cands = [d for d in divs if d % 8 == 0] or divs
    ok = [
        b for b in cands
        if _mega_estimate(cfg, b, blk_v, trial_pack, gen)
        <= _mega_budget(gen)
    ]
    return _order_candidates(ok, _preferred_block(cfg))[
        :_MAX_PROBE_CANDIDATES
    ]


def _probe_mega_compile(cfg: QBAConfig, blk_d: int, blk_v: int,
                        variant: str, trial_pack: int = 1,
                        gen: bool = False) -> None:
    """Data-free compile probe of one trial-megakernel build (raises on
    failure, never executes)."""
    # Deferred import: the megakernel module imports this module's
    # verdict helper at its top level.
    from qba_tpu.ops.trial_megakernel import build_trial_megakernel

    PROBE_STATS["compile_probes"] += 1
    shp, i32, vdt = _probe_shapes(cfg)
    n_pool = cfg.n_lieutenants * cfg.slots
    n_rv = cfg.n_lieutenants
    s, w, gdt = cfg.size_l, cfg.w, _gdt(cfg)
    kd = (trial_pack,) if trial_pack > 1 else ()

    def kshp(*dims, dt=i32):
        return shp(*(kd + dims), dt=dt)

    if variant == "allrecv":
        li_arg = (
            kshp(s, n_rv, dt=jnp.float32), kshp(s, n_rv, dt=jnp.float32),
            kshp(s, n_rv, dt=jnp.float32), kshp(s, w * n_rv, dt=gdt),
            kshp(w * s, n_rv, dt=gdt),
        )
    else:
        li_arg = kshp(n_rv, s)
    mega = build_trial_megakernel(
        cfg, blk_d, blk_v, variant=variant, trial_pack=trial_pack,
        gen=gen,
    )
    draws = (
        shp(*((cfg.n_rounds,) + kd + (n_pool, n_rv))),
        shp(*((cfg.n_rounds,) + kd + (n_pool, n_rv))),
        shp(*((cfg.n_rounds,) + kd + (n_pool, n_rv))),
    )
    if gen:
        t = cfg.total_qubits
        gen_ops = (
            kshp(s), kshp(s, t), kshp(s, 2 * t), kshp(s, 2 * t),
            kshp(s, t),
        )
        jax.jit(jax.vmap(mega)).lower(
            gen_ops, kshp(n_rv), kshp(n_pool, 1), *draws,
        ).compile()
    else:
        jax.jit(jax.vmap(mega)).lower(
            kshp(n_rv, s), kshp(n_rv, s), li_arg, kshp(n_rv),
            kshp(n_pool, 1), *draws,
        ).compile()


def mega_kernel_plan(cfg: QBAConfig, variant: str | None = None,
                     trial_pack: int = 1, gen: bool = False) -> int | None:
    """Destination block size for the trial megakernel, or None if no
    candidate compiles (the fused per-round engine then takes over —
    the megakernel's demotion target; a gen-fused plan instead demotes
    to host-side generation, keeping the megakernel)."""
    if variant is None:
        variant = resolve_verdict_variant(cfg)
    blk_v = resolve_tiled_block(cfg)

    def compile_one(blk_d):
        _probe_mega_compile(cfg, blk_d, blk_v, variant, trial_pack, gen)

    return _probe_plan(
        "trial-mega", cfg,
        mega_candidates(cfg, blk_v, trial_pack, gen), compile_one,
        _MEGA_PROBE_CACHE,
        "falling back to host-side list generation" if gen
        else "falling back to the fused per-round engine",
        extra={"allrecv": "+allrecv", "group-serial": "+accser"}.get(
            variant, ""
        )
        + (f"+pack{trial_pack}" if trial_pack > 1 else "")
        + ("+gen" if gen else "")
        + f"+v{blk_v}",
    )


def _resolve_mega_block_impl(
    cfg: QBAConfig, trial_pack: int = 1, gen: bool = False
) -> tuple[int, int] | None:
    """``(blk_d, blk_v)`` the megakernel engine runs with, or None to
    demote to the fused per-round engine.  An explicit ``tiled_block``
    is honored where it divides the pool and fits the megakernel
    estimate (same discipline as :func:`resolve_fused_block`); off-TPU
    the estimate alone decides, so an over-budget shape demotes
    honestly instead of compiling an interpret-mode kernel no TPU plan
    would admit."""
    n_pool = cfg.n_lieutenants * cfg.slots
    blk_v = resolve_tiled_block(cfg)
    if cfg.tiled_block is not None and n_pool % cfg.tiled_block == 0:
        if (
            jax.default_backend() != "tpu"
            or _mega_estimate(cfg, cfg.tiled_block, blk_v, trial_pack, gen)
            <= _mega_budget(gen)
        ):
            return (cfg.tiled_block, blk_v)
    if jax.default_backend() == "tpu":
        blk_d = mega_kernel_plan(cfg, trial_pack=trial_pack, gen=gen)
        return None if blk_d is None else (blk_d, blk_v)
    cands = mega_candidates(cfg, blk_v, trial_pack, gen)
    return (cands[0], blk_v) if cands else None


def _resolve_mega_gen_impl(cfg: QBAConfig, trial_pack: int = 1) -> str:
    """``"gf2"`` when step-1 generation runs inside the megakernel's
    launch, ``"host"`` otherwise.  The fused path exists only for the
    stabilizer sampler; ``mega_gen`` forces either side, and ``"auto"``
    fuses exactly when a gen-fused plan (estimate + probe) is
    admitted.  A forced ``"gf2"`` that cannot be honored still
    resolves ``"host"`` here — the engine records the demotion loudly
    at dispatch."""
    if cfg.mega_gen == "host" or cfg.qsim_path != "stabilizer":
        return "host"
    plan = _memo(
        _resolve_key("mega", cfg, None, (trial_pack, True)),
        lambda: _resolve_mega_block_impl(cfg, trial_pack, gen=True),
    )
    return "host" if plan is None else "gf2"


def resolve_mega_gen(cfg: QBAConfig, trial_pack: int = 1) -> str:
    """Memoized :func:`_resolve_mega_gen_impl` (see
    :func:`resolve_verdict_variant`)."""
    return _memo(
        _resolve_key(
            "megagen", cfg, None,
            (trial_pack, cfg.mega_gen, cfg.qsim_path),
        ),
        lambda: _resolve_mega_gen_impl(cfg, trial_pack),
    )


def resolve_mega_block(
    cfg: QBAConfig, trial_pack: int = 1
) -> tuple[int, int] | None:
    """Memoized :func:`_resolve_mega_block_impl` (see
    :func:`resolve_verdict_variant`) — planned for the generation mode
    :func:`resolve_mega_gen` settles on, so one resolver call answers
    both "which blocks" and "which launch shape"."""
    gen = resolve_mega_gen(cfg, trial_pack) == "gf2"
    return _memo(
        _resolve_key("mega", cfg, None, (trial_pack, gen)),
        lambda: _resolve_mega_block_impl(cfg, trial_pack, gen=gen),
    )


def _sharded_mega_estimate(cfg: QBAConfig, blk_d: int, blk_v: int,
                           n_tp: int) -> int:
    """Loose VMEM estimate for the party-sharded megakernel on one tp
    shard: the fused kernel's per-step terms at the local receiver
    count, ONE global pool half (the assembled A side every shard
    reads), the local B half plus the double-buffered ring transient
    (two comm slots of the local segment), and the shard's slice of
    the round-stacked draw slabs."""
    n_rv = cfg.n_lieutenants
    n_local = n_rv // n_tp
    n_pool = n_rv * cfg.slots
    s, max_l = cfg.size_l, cfg.max_l
    vb = jnp.dtype(pool_vals_dtype(cfg)).itemsize
    pool = (
        vb * max_l * n_pool * s + 4 * n_pool * max_l
        + vb * n_pool * s + 4 * n_pool * 4
    )
    local = pool // n_tp
    draws = 3 * 4 * cfg.n_rounds * n_local * n_pool
    decode = 4 * n_pool * n_local + 4 * n_pool * max(s, cfg.w)
    return (
        _fused_estimate(cfg, blk_d, blk_v, n_local, 1)
        + pool + 3 * local + draws + decode
    )


def sharded_mega_candidates(cfg: QBAConfig, n_tp: int,
                            blk_v: int | None = None) -> list[int]:
    """Candidate destination block sizes for the party-sharded trial
    megakernel — divisors of the LOCAL destination rows screened
    against the reserved megakernel budget (the in-kernel ring's
    in-flight DMA transients draw on the same :data:`_MEGA_RESERVE`
    the gen prologue does)."""
    n_rv = cfg.n_lieutenants
    if n_tp < 2 or n_rv % n_tp != 0:
        return []
    n_local = n_rv // n_tp
    loc_rows = n_local * cfg.slots
    if blk_v is None:
        blk_v = resolve_tiled_block(cfg, n_local)
    divs = [d for d in range(loc_rows, 0, -1) if loc_rows % d == 0]
    cands = [d for d in divs if d % 8 == 0] or divs
    ok = [
        b for b in cands
        if _sharded_mega_estimate(cfg, b, blk_v, n_tp)
        <= _mega_budget(gen=True)
    ]
    return _order_candidates(ok, _preferred_block(cfg))


def _sharded_mega_plan_impl(
    cfg: QBAConfig, n_tp: int
) -> tuple[int, int] | None:
    """``(blk_d, blk_v)`` for the party-sharded trial megakernel at
    ``n_tp`` shards, or None to demote to the fused per-round engine
    under the tp mesh.  Estimate-gated only — the in-kernel ring uses
    remote DMA under shard_map, which has no single-device compile
    probe; a dispatch failure on real hardware degrades loudly through
    :func:`qba_tpu.parallel.spmd.run_trials_spmd`'s fallback (same
    contract as the ring shuffle itself)."""
    n_rv = cfg.n_lieutenants
    if n_rv % n_tp != 0:
        return None
    n_local = n_rv // n_tp
    # The sharded engine always resolves in the GROUP family at the
    # local receiver count (allrecv is the global-batch formulation;
    # _resolve_verdict_variant_impl with n_recv set never returns it),
    # so the verdict block is the per-shard tiled plan.
    blk_v = resolve_tiled_block(cfg, n_local)
    n_pool = n_rv * cfg.slots
    if n_pool % blk_v != 0:
        return None
    ordered = sharded_mega_candidates(cfg, n_tp, blk_v)
    return (ordered[0], blk_v) if ordered else None


def sharded_mega_plan(cfg: QBAConfig, n_tp: int) -> tuple[int, int] | None:
    """Memoized :func:`_sharded_mega_plan_impl` (see
    :func:`resolve_verdict_variant`)."""
    return _memo(
        _resolve_key(
            "megash", cfg, cfg.n_lieutenants // n_tp
            if n_tp and cfg.n_lieutenants % n_tp == 0 else None,
            (n_tp,),
        ),
        lambda: _sharded_mega_plan_impl(cfg, n_tp),
    )
