"""Pallas TPU kernels for the framework's hot ops.

The reference's compute-dominant path is serial circuit simulation on an
external native engine (``tfg.py:76-84``, SURVEY §3.2).  Here the dense
validation engine gets a fused Pallas kernel: one kernel executes the
*entire* circuit with the statevector resident in VMEM
(:mod:`qba_tpu.ops.fused_circuit`), instead of one HBM round-trip per
gate.
"""

from qba_tpu.ops.fused_circuit import build_fused_circuit_run

__all__ = ["build_fused_circuit_run"]
