"""Remote-DMA ring all-gather kernel for the party-sharded engine.

The TPU transport behind ``tp_comms="ring"``
(:mod:`qba_tpu.parallel.ring` holds the schedule contract and the
off-TPU ``ppermute`` twin): one ``pallas_call`` per round moves every
device's pool shard around the tp ring as ``tp - 1`` asynchronous
neighbor hops (``pltpu.make_async_remote_copy``), double-buffered
through a 2-slot VMEM scratch so hop ``k+1``'s send can overlap hop
``k``'s consumption.  Only ``min(2, tp - 1)`` remote shards are ever
resident next to the local pool — the comms term the sharded KI-2
budget model prices (:func:`qba_tpu.analysis.memory.comms_buffer_bytes`)
— where the ``all_gather`` path transiently materializes all
``tp - 1`` remote shards at once.

Hop schedule (the neighbor-ring pattern of SNIPPETS.md [1]/[2] and the
accelerator guide): at step ``k`` every device forwards the shard it
received at step ``k - 1`` (its own at ``k = 0``) to the right
neighbor ``(my + 1) % tp`` and deposits the shard arriving from the
left — which originated at device ``(my - k - 1) % tp`` — at that
owner's global offset.  The assembled output is the shards
concatenated in tp order, i.e. bit-identical to
``jax.lax.all_gather(x, "tp", tiled=True)``.

This module is TPU-only by construction: remote DMA has no interpret
path across an emulated CPU mesh, so :mod:`qba_tpu.parallel.spmd`
builds it only when ``jax.default_backend() == "tpu"`` and CPU tests
exercise the ``ppermute`` twin instead.  A dispatch-time failure under
``tp_comms="auto"`` demotes to the ``all_gather`` escape hatch with a
recorded warning (``run_trials_spmd``), never silently.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from qba_tpu.ops.round_kernel import CompilerParams, vma_struct


def _ring_kernel_body(
    local_ref, out_ref, comm_ref, send_sem, recv_sem,
    *, n_tp: int, axis_name: str, mesh_axes: tuple[str, ...],
):
    """One device's side of the ring: barrier with both neighbors (their
    comm slots must exist before anyone starts a remote write), then
    ``n_tp - 1`` double-buffered hops."""
    my_tp = jax.lax.axis_index(axis_name)
    chunk = local_ref.shape[0]

    def coords(tp_idx):
        # Mesh-coordinate device id: every non-tp axis keeps this
        # device's own index (the ring never leaves its tp row).
        return tuple(
            tp_idx if a == axis_name else jax.lax.axis_index(a)
            for a in mesh_axes
        )

    right = jax.lax.rem(my_tp + 1, n_tp)
    left = jax.lax.rem(my_tp + n_tp - 1, n_tp)

    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(
        barrier, inc=1, device_id=coords(left),
        device_id_type=pltpu.DeviceIdType.MESH,
    )
    pltpu.semaphore_signal(
        barrier, inc=1, device_id=coords(right),
        device_id_type=pltpu.DeviceIdType.MESH,
    )
    pltpu.semaphore_wait(barrier, 2)

    # Own shard: straight into the output at this device's offset, and
    # into the first send slot.
    out_ref[pl.ds(my_tp * chunk, chunk)] = local_ref[...]
    comm_ref[0] = local_ref[...]

    for step in range(n_tp - 1):
        send_slot = step % 2
        recv_slot = (step + 1) % 2
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_ref.at[send_slot],
            dst_ref=comm_ref.at[recv_slot],
            send_sem=send_sem.at[send_slot],
            recv_sem=recv_sem.at[recv_slot],
            device_id=coords(right),
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        rdma.start()
        rdma.wait()
        # The shard now in recv_slot originated step+1 hops to the left.
        src_dev = jax.lax.rem(my_tp + n_tp - step - 1, n_tp)
        out_ref[pl.ds(src_dev * chunk, chunk)] = comm_ref[recv_slot]


def build_ring_gather(
    n_tp: int,
    *,
    axis_name: str = "tp",
    mesh_axes: tuple[str, ...] = ("dp", "tp"),
    out_vma: frozenset | None = None,
    collective_id: int = 0,
):
    """Build ``gather(x, axis=0)``: the remote-DMA ring equivalent of
    ``jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)``.

    ``out_vma`` follows the KI-1 threading contract
    (:mod:`qba_tpu.analysis.vma`): the gathered output varies over the
    mesh axes it names (value-replicated over tp, but only the psum
    recombination downstream *proves* replication to the checker).
    Booleans ride as int32 (remote DMA moves word-aligned planes) and
    are cast back on arrival.  One launch gathers one array; the spmd
    round body calls it per pool leaf, so the KI-5 launch model counts
    ``leaves x n_rounds`` ring launches per trial
    (:func:`qba_tpu.analysis.launches.spmd_launches_per_trial`).
    """
    if n_tp < 1:
        raise ValueError(f"n_tp must be >= 1, got {n_tp}")
    if axis_name not in mesh_axes:
        raise ValueError(
            f"axis_name {axis_name!r} not in mesh_axes {mesh_axes!r}"
        )

    def gather(x: jax.Array, axis: int = 0) -> jax.Array:
        if n_tp == 1:
            return x
        moved = jnp.moveaxis(x, axis, 0)
        was_bool = moved.dtype == jnp.bool_
        work = moved.astype(jnp.int32) if was_bool else moved
        chunk = work.shape[0]
        out_dims = (n_tp * chunk,) + work.shape[1:]
        ring = pl.pallas_call(
            lambda lr, orf, cr, ss, rs: _ring_kernel_body(
                lr, orf, cr, ss, rs,
                n_tp=n_tp, axis_name=axis_name, mesh_axes=mesh_axes,
            ),
            # No grid and no explicit block specs: the shard and the
            # gathered output are whole-array VMEM residents (the
            # kernel stores into out_ref directly; shard sizes are
            # MB-scale at every planned shape — the KI-2 plan audit
            # prices them via comms_buffer_bytes).
            out_shape=vma_struct(out_vma, out_dims, work.dtype),
            scratch_shapes=[
                pltpu.VMEM((2, chunk) + work.shape[1:], work.dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
            compiler_params=CompilerParams(
                has_side_effects=True, collective_id=collective_id,
            ),
        )
        out = ring(work)
        if was_bool:
            out = out != 0
        return jnp.moveaxis(out, 0, axis)

    return gather
