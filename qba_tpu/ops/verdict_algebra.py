"""The shared per-packet acceptance flag algebra of the Pallas round
kernels.

Both TPU kernels — the fused monolithic kernel
(:mod:`qba_tpu.ops.round_kernel`) and the packet-tiled verdict kernel
(:mod:`qba_tpu.ops.round_kernel_tiled`) — evaluate the same batched form
of ``lieu_receive``'s consistency verdict (``tfg.py:289-300``,
executable spec: :func:`qba_tpu.core.consistent.consistent_after_append`)
over lane-packed receiver groups.  This module holds that algebra ONCE:
the kernels keep their own layouts, scheduling, and rebuild phases, but
the flag math a spec change must touch lives here — previously it
existed as three hand-synchronized copies (the XLA engine's batched form
remains in :mod:`qba_tpu.rounds.engine`; the kernels' two copies are
unified here), and the ``appended`` guard of round 3 had to be applied
to each one separately.

Conventions (see round_kernel.py's layout notes): packets fill sublanes
(``n_p`` of them — the whole mailbox or one tile block), list positions
fill lanes, receivers are lane-packed ``grp`` per tile with per-segment
reductions as exact bf16/f32 MXU matmuls against a segment one-hot.
Value-presence tests use per-position bit-plane masks (``ceil(w/32)``
int32 planes, exact for all queried values < w) when ``w <= 64``; wider
order spaces fall back to per-row loops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from qba_tpu.core.types import SENTINEL


class VerdictAlgebra:
    """Per-kernel-invocation instance: precomputes the receiver-
    independent raw-packet facts and lane tiles from loaded values, then
    evaluates per-receiver-group verdicts.

    Args (values, already loaded from refs — all int32 unless noted):
      vals: list of ``max_l`` evidence-row tiles ``[n_p, size_l]``.
      lens: ``[n_p, max_l]``; count: ``[n_p, 1]``.
      p_i32: ``[n_p, size_l]`` P-mask as 0/1 int32.
      e_vals: segment one-hot ``[grp, seg_l]`` (ignored when grp == 1).
      lip_vals / lioob_vals: lane-packed receiver lists / out-of-bound
        flags ``[n_groups, seg_l]``.
      r_idx: the round index (traced scalar).
    """

    def __init__(self, *, n_p, grp, seg_l, max_l, size_l, w, gdt,
                 vals, lens, count, p_i32, e_vals, lip_vals, lioob_vals,
                 r_idx):
        self.n_p, self.grp, self.seg_l = n_p, grp, seg_l
        self.max_l, self.size_l, self.w, self.gdt = max_l, size_l, w, gdt
        self.lip_vals, self.lioob_vals = lip_vals, lioob_vals
        self.r_idx = r_idx
        self.lens, self.count = lens, count
        self.len0 = lens[:, 0:1]
        self.vals = vals
        in_t = [vals[r] != SENTINEL for r in range(max_l)]
        self.valid = [count > r for r in range(max_l)]

        # ---- Receiver-independent raw-packet facts (tfg.py:87-98) ----
        false_col = jnp.zeros((n_p, 1), jnp.bool_)
        oob = false_col
        lens_bad = false_col
        cells_coll = false_col
        for r in range(max_l):
            row_bad = jnp.any(
                in_t[r] & ((vals[r] > w) | (vals[r] < 0)),
                axis=1, keepdims=True,
            )
            oob |= self.valid[r] & row_bad
            lens_bad |= self.valid[r] & (lens[:, r : r + 1] != self.len0)
            for s in range(r + 1, max_l):
                hit = jnp.any(
                    in_t[r] & in_t[s] & (vals[r] == vals[s]),
                    axis=1, keepdims=True,
                )
                cells_coll |= self.valid[s] & hit
        self.oob, self.lens_bad, self.cells_coll = oob, lens_bad, cells_coll

        # Value-presence bit planes: bit (x & 31) of plane x >> 5 set at
        # (packet, position) iff some valid evidence row holds value x
        # there.  Exact for queries < w (mailbox v < w, forged v <
        # n_parties+1 <= w, li values < w); stored out-of-range garbage
        # cannot alias a query (distinct (plane, bit) per value).
        self.n_planes = (w + 31) // 32
        self.use_bitmask = w <= 64
        if self.use_bitmask:
            pm = [jnp.zeros((n_p, size_l), jnp.int32)
                  for _ in range(self.n_planes)]
            for r in range(max_l):
                for p_i in range(self.n_planes):
                    lo, hi = 32 * p_i, 32 * (p_i + 1)
                    in_pl = (vals[r] >= lo) & (vals[r] < hi)
                    pm[p_i] |= jnp.where(
                        self.valid[r] & in_t[r] & in_pl,
                        jnp.left_shift(jnp.int32(1), vals[r] & 31),
                        0,
                    )

        # ---- Lane-packed tiles: grp copies of the packet tables ------
        if grp > 1:
            self._e_mat = e_vals.astype(gdt)
        self.vals_t = [
            jnp.concatenate([vals[r]] * grp, axis=1) for r in range(max_l)
        ]
        self.p_tile = jnp.concatenate([p_i32] * grp, axis=1) != 0
        if self.use_bitmask:
            self.pm_t = [jnp.concatenate([pm[p_i]] * grp, axis=1)
                         for p_i in range(self.n_planes)]
        else:
            self.in_t_t = [self.vals_t[r] != SENTINEL
                           for r in range(max_l)]

    # The two segment primitives; everything downstream is ONE algebra
    # over them.  grp == 1 degenerates both to plain broadcast / axis
    # reduction (Mosaic cannot lower a 1-wide-output matmul, and there
    # is nothing to pack anyway).
    def _as_gdt(self, x):
        # Mosaic rejects the i1 vector relayout an astype from bool can
        # pick (bitcast_vreg i1->i32 on narrow tiles); a select against
        # float constants lowers cleanly.
        if x.dtype == jnp.bool_:
            return jnp.where(x, 1.0, 0.0).astype(self.gdt)
        return x.astype(self.gdt)

    def expand(self, cols):
        """[n_p, grp] per-receiver columns -> [n_p, seg_l] lanes."""
        if self.grp == 1:
            return jnp.broadcast_to(
                self._as_gdt(cols).astype(jnp.float32),
                (self.n_p, self.seg_l),
            )
        return jax.lax.dot_general(
            self._as_gdt(cols), self._e_mat,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def seg_reduce(self, lanes):
        """[n_p, seg_l] lanes -> [n_p, grp] per-segment counts."""
        if self.grp == 1:
            return jnp.sum(
                self._as_gdt(lanes).astype(jnp.float32),
                axis=1, keepdims=True,
            )
        return jax.lax.dot_general(
            self._as_gdt(lanes), self._e_mat,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def _plane_bit(self, q_lanes):
        """Presence bit of query value ``q_lanes`` (< w) at each
        (packet, position): select the plane by q >> 5, shift by
        q & 31."""
        sel = self.pm_t[0]
        for p_i in range(1, self.n_planes):
            sel = jnp.where((q_lanes >> 5) == p_i, self.pm_t[p_i], sel)
        return (jnp.right_shift(sel, q_lanes & 31) & 1) != 0

    def group(self, gi, v2_g, clearp_g, clearl_g, count_eff_g,
              delivered_g):
        """One receiver group's verdicts (group ``gi`` = the ``gi``-th
        contiguous receiver slice): returns ``(ok_g, dup_g,
        own_len_g)``, each ``[n_p, grp]`` (``own_len_g`` int32); the
        arguments are the group's per-receiver columns ``[n_p, grp]``
        (post-corruption order value, clear-P/clear-L flags, effective
        evidence count, delivery mask).

        Mirrors ``consistent_after_append``'s decomposition, including
        the round-3 ``appended`` fullness guard (reducible to ``~dup``
        under the config invariant ``max_l >= n_rounds + 1``, kept so
        the kernels stay on the spec if the bound is ever raised)."""
        max_l, n_p, grp = self.max_l, self.n_p, self.grp
        v2_lanes = self.expand(v2_g).astype(jnp.int32)
        clearp_lanes = self.expand(clearp_g) != 0
        p2_lanes = self.p_tile & ~clearp_lanes
        li_row = self.lip_vals[gi : gi + 1, :]
        li_bc = jnp.broadcast_to(li_row, (n_p, self.seg_l))
        own_lanes = jnp.where(p2_lanes, li_bc, SENTINEL)

        dup_g = jnp.zeros((n_p, grp), jnp.bool_)
        for r in range(max_l):
            mism = self.seg_reduce(self.vals_t[r] != own_lanes)
            dup_g |= self.valid[r] & (mism == 0)
        dup_g &= ~clearl_g
        own_len_g = self.seg_reduce(p2_lanes).astype(jnp.int32)

        bad_own_pos = p2_lanes & (
            (li_bc == v2_lanes)
            | (self.lioob_vals[gi : gi + 1, :] != 0)
        )
        if self.use_bitmask:
            cont_g = self.seg_reduce(self._plane_bit(v2_lanes)) > 0
            own_coll_g = (
                self.seg_reduce(p2_lanes & self._plane_bit(li_bc)) > 0
            )
            bad_own_g = self.seg_reduce(bad_own_pos) > 0
            cont_or_oob = ~clearl_g & (cont_g | self.oob)
        else:
            contains_g = jnp.zeros((n_p, grp), jnp.bool_)
            own_coll_g = jnp.zeros((n_p, grp), jnp.bool_)
            for r in range(max_l):
                contains_g |= self.valid[r] & (
                    self.seg_reduce(
                        self.in_t_t[r] & (self.vals_t[r] == v2_lanes)
                    )
                    > 0
                )
                own_coll_g |= self.valid[r] & (
                    self.seg_reduce(
                        p2_lanes
                        & self.in_t_t[r]
                        & (self.vals_t[r] == own_lanes)
                    )
                    > 0
                )
            bad_own_g = self.seg_reduce(bad_own_pos) > 0
            cont_or_oob = ~clearl_g & (self.oob | contains_g)

        appended_g = ~dup_g & (count_eff_g < max_l)
        cond2 = ~(cont_or_oob | (appended_g & bad_own_g))
        new_count_g = jnp.where(appended_g, count_eff_g + 1, count_eff_g)
        cond1 = (clearl_g | ~self.lens_bad) & (
            ~appended_g | (count_eff_g == 0) | (own_len_g == self.len0)
        )
        cond3 = (clearl_g | ~self.cells_coll) & (
            ~appended_g | ~(~clearl_g & own_coll_g)
        )
        ok_g = (
            delivered_g & cond1 & cond2 & cond3
            & (new_count_g == self.r_idx + 1)
        )
        return ok_g, dup_g, own_len_g


def accept_first_per_value_group(r0, grp, ok_g, v2_g, ovi_ref,
                                 idx_col, n_p, w):
    """Group-batched :func:`accept_first_per_value`: the ``grp``
    receivers of one lane group processed in a single
    ``[n_p, grp*w]``-lane pass instead of a serial per-receiver chain
    (the receiver loop was the verdict kernels' compute floor on live
    blocks — ~8 small ops per receiver with a scheduling dependency
    through the shared vi ref).  Receivers' vi rows are disjoint, so
    batching cannot reorder anything observable; the cross-block
    sequential carry is untouched.

    Reads rows ``r0 .. r0+grp`` of ``ovi_ref`` and returns
    ``(acc_cols, new_rows)`` WITHOUT storing — two python lists of
    ``grp`` int32 arrays each: ``acc_cols[j]`` is receiver ``r0+j``'s
    acceptance column ``[n_p, 1]`` and ``new_rows[j]`` its updated vi
    row ``[1, w]`` (0/1 int32, directly storable into the refs).  The
    caller stores per receiver so tail-group overlap can skip
    already-updated rows (the update is not idempotent for acc).
    Requires ``grp * w`` lanes per tile."""
    seg = grp * w
    iota_lane = jax.lax.broadcasted_iota(jnp.int32, (n_p, seg), 1)
    lane_val = iota_lane % w
    # Per-lane v2/ok of the lane's segment (static where-chain over the
    # small grp).
    v2_lane = jnp.broadcast_to(v2_g[:, 0:1], (n_p, seg))
    ok_lane = jnp.broadcast_to(
        jnp.where(ok_g[:, 0:1], 1, 0), (n_p, seg)
    )
    for j in range(1, grp):
        in_seg = iota_lane >= j * w
        v2_lane = jnp.where(in_seg, v2_g[:, j : j + 1], v2_lane)
        ok_lane = jnp.where(
            in_seg, jnp.where(ok_g[:, j : j + 1], 1, 0), ok_lane
        )
    onehot = lane_val == v2_lane  # exactly one lane per (packet, segment)
    vi_flat = jnp.concatenate(
        [ovi_ref[r0 + j : r0 + j + 1, :] for j in range(grp)], axis=1
    )  # [1, seg]
    cand_lane = onehot & (ok_lane != 0) & (vi_flat == 0)
    masked_idx = jnp.where(cand_lane, idx_col, n_p)
    first = jnp.min(masked_idx, axis=0, keepdims=True)  # [1, seg]
    acc_lane = jnp.where(cand_lane & (first == idx_col), 1, 0)
    # Per-receiver columns: each (packet, segment) has at most one lane
    # set, so a lane max over the segment is the indicator.
    acc_cols = [
        jnp.max(acc_lane[:, j * w : (j + 1) * w], axis=1, keepdims=True)
        for j in range(grp)
    ]
    any_acc = jnp.max(acc_lane, axis=0, keepdims=True)
    new_flat = jnp.where((vi_flat != 0) | (any_acc != 0), 1, 0)  # [1, seg]
    new_rows = [new_flat[:, j * w : (j + 1) * w] for j in range(grp)]
    return acc_cols, new_rows


def accept_first_per_value(ok, v2, vi_row, idx_col, n_p, w):
    """First-candidate-per-order dedup against Vi (``tfg.py:294``) for
    one receiver: among packets with ``ok`` carrying the same order
    value, the lowest index wins, and values already in ``vi_row`` are
    excluded.  Returns ``(acc [n_p, 1] bool, new_vi_row [1, w] bool)``.
    NOT idempotent at the caller (the vi update must land exactly once
    per receiver)."""
    iota_w = jax.lax.broadcasted_iota(jnp.int32, (n_p, w), 1)
    onehot = v2 == iota_w  # [n_p, w]
    in_vi = jnp.any(onehot & (vi_row != 0), axis=1, keepdims=True)
    cand = ok & ~in_vi
    masked_idx = jnp.where(onehot & cand, idx_col, n_p)
    first = jnp.min(masked_idx, axis=0, keepdims=True)  # [1, w]
    first_b = jnp.min(
        jnp.where(onehot, jnp.broadcast_to(first, (n_p, w)), n_p),
        axis=1, keepdims=True,
    )
    acc = cand & (first_b == idx_col)
    new_vi = (vi_row != 0) | jnp.any(acc & onehot, axis=0, keepdims=True)
    return acc, new_vi
