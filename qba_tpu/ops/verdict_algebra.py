"""The shared per-packet acceptance flag algebra of the Pallas round
kernels.

Both TPU kernels — the fused monolithic kernel
(:mod:`qba_tpu.ops.round_kernel`) and the packet-tiled verdict kernel
(:mod:`qba_tpu.ops.round_kernel_tiled`) — evaluate the same batched form
of ``lieu_receive``'s consistency verdict (``tfg.py:289-300``,
executable spec: :func:`qba_tpu.core.consistent.consistent_after_append`)
over lane-packed receiver groups.  This module holds that algebra ONCE:
the kernels keep their own layouts, scheduling, and rebuild phases, but
the flag math a spec change must touch lives here — previously it
existed as three hand-synchronized copies (the XLA engine's batched form
remains in :mod:`qba_tpu.rounds.engine`; the kernels' two copies are
unified here), and the ``appended`` guard of round 3 had to be applied
to each one separately.

Conventions (see round_kernel.py's layout notes): packets fill sublanes
(``n_p`` of them — the whole mailbox or one tile block), list positions
fill lanes, receivers are lane-packed ``grp`` per tile with per-segment
reductions as exact bf16/f32 MXU matmuls against a segment one-hot.
Value-presence tests use per-position bit-plane masks (``ceil(w/32)``
int32 planes, exact for all queried values < w) when ``w <= 64``; wider
order spaces fall back to per-row loops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from qba_tpu.core.types import SENTINEL


def _exact_prec(dt):
    """``Precision.HIGHEST`` for f32-dtype integer dots: with default
    matmul precision XLA may lower an f32 dot through single-pass bf16
    (backend- and lowering-dependent), rounding integer operands > 256.
    bf16-operand dots whose values are proven <= 256 are exact by
    construction and keep the fast path.  (Round-5 root cause of the
    rebuild kernel's wrong-draw bug — see round_kernel_tiled._prec.)
    The proof obligation is machine-checked: ``qba-tpu lint``'s KI-3
    pass interval-bounds every dot operand on every traced build path
    (qba_tpu/analysis/dots.py, docs/ANALYSIS.md)."""
    return jax.lax.Precision.HIGHEST if dt == jnp.float32 else None


class VerdictAlgebra:
    """Per-kernel-invocation instance: precomputes the receiver-
    independent raw-packet facts and lane tiles from loaded values, then
    evaluates per-receiver-group verdicts.

    Args (values, already loaded from refs — all int32 unless noted):
      vals: list of ``max_l`` evidence-row tiles ``[n_p, size_l]``.
      lens: ``[n_p, max_l]``; count: ``[n_p, 1]``.
      p_i32: ``[n_p, size_l]`` P-mask as 0/1 int32.
      e_vals: segment one-hot ``[grp, seg_l]`` (ignored when grp == 1).
      lip_vals / lioob_vals: lane-packed receiver lists / out-of-bound
        flags ``[n_groups, seg_l]``.
      r_idx: the round index (traced scalar).
    """

    def __init__(self, *, n_p, grp, seg_l, max_l, size_l, w, gdt,
                 vals, lens, count, p_i32, e_vals, lip_vals, lioob_vals,
                 r_idx):
        self.n_p, self.grp, self.seg_l = n_p, grp, seg_l
        self.max_l, self.size_l, self.w, self.gdt = max_l, size_l, w, gdt
        self.lip_vals, self.lioob_vals = lip_vals, lioob_vals
        self.r_idx = r_idx
        self.lens, self.count = lens, count
        self.len0 = lens[:, 0:1]
        self.vals = vals
        in_t = [vals[r] != SENTINEL for r in range(max_l)]
        self.valid = [count > r for r in range(max_l)]

        # ---- Receiver-independent raw-packet facts (tfg.py:87-98) ----
        false_col = jnp.zeros((n_p, 1), jnp.bool_)
        oob = false_col
        lens_bad = false_col
        cells_coll = false_col
        for r in range(max_l):
            row_bad = jnp.any(
                in_t[r] & ((vals[r] > w) | (vals[r] < 0)),
                axis=1, keepdims=True,
            )
            oob |= self.valid[r] & row_bad
            lens_bad |= self.valid[r] & (lens[:, r : r + 1] != self.len0)
            for s in range(r + 1, max_l):
                hit = jnp.any(
                    in_t[r] & in_t[s] & (vals[r] == vals[s]),
                    axis=1, keepdims=True,
                )
                cells_coll |= self.valid[s] & hit
        self.oob, self.lens_bad, self.cells_coll = oob, lens_bad, cells_coll

        # Value-presence bit planes: bit (x & 31) of plane x >> 5 set at
        # (packet, position) iff some valid evidence row holds value x
        # there.  Exact for queries < w (mailbox v < w, forged v <
        # n_parties+1 <= w, li values < w); stored out-of-range garbage
        # cannot alias a query (distinct (plane, bit) per value).
        self.n_planes = (w + 31) // 32
        self.use_bitmask = w <= 64
        if self.use_bitmask:
            pm = [jnp.zeros((n_p, size_l), jnp.int32)
                  for _ in range(self.n_planes)]
            for r in range(max_l):
                for p_i in range(self.n_planes):
                    lo, hi = 32 * p_i, 32 * (p_i + 1)
                    in_pl = (vals[r] >= lo) & (vals[r] < hi)
                    pm[p_i] |= jnp.where(
                        self.valid[r] & in_t[r] & in_pl,
                        jnp.left_shift(jnp.int32(1), vals[r] & 31),
                        0,
                    )

        # ---- Lane-packed tiles: grp copies of the packet tables ------
        if grp > 1:
            self._e_mat = e_vals.astype(gdt)
        self.vals_t = [
            jnp.concatenate([vals[r]] * grp, axis=1) for r in range(max_l)
        ]
        self.p_tile = jnp.concatenate([p_i32] * grp, axis=1) != 0
        if self.use_bitmask:
            self.pm_t = [jnp.concatenate([pm[p_i]] * grp, axis=1)
                         for p_i in range(self.n_planes)]
        else:
            self.in_t_t = [self.vals_t[r] != SENTINEL
                           for r in range(max_l)]

    # The two segment primitives; everything downstream is ONE algebra
    # over them.  grp == 1 degenerates both to plain broadcast / axis
    # reduction (Mosaic cannot lower a 1-wide-output matmul, and there
    # is nothing to pack anyway).
    def _as_gdt(self, x):
        # Mosaic rejects the i1 vector relayout an astype from bool can
        # pick (bitcast_vreg i1->i32 on narrow tiles); a select against
        # float constants lowers cleanly.
        if x.dtype == jnp.bool_:
            return jnp.where(x, 1.0, 0.0).astype(self.gdt)
        return x.astype(self.gdt)

    def expand(self, cols):
        """[n_p, grp] per-receiver columns -> [n_p, seg_l] lanes."""
        if self.grp == 1:
            return jnp.broadcast_to(
                self._as_gdt(cols).astype(jnp.float32),
                (self.n_p, self.seg_l),
            )
        return jax.lax.dot_general(
            self._as_gdt(cols), self._e_mat,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_exact_prec(self.gdt),
        )

    def seg_reduce(self, lanes):
        """[n_p, seg_l] lanes -> [n_p, grp] per-segment counts."""
        if self.grp == 1:
            return jnp.sum(
                self._as_gdt(lanes).astype(jnp.float32),
                axis=1, keepdims=True,
            )
        return jax.lax.dot_general(
            self._as_gdt(lanes), self._e_mat,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_exact_prec(self.gdt),
        )

    def _plane_bit(self, q_lanes):
        """Presence bit of query value ``q_lanes`` (< w) at each
        (packet, position): select the plane by q >> 5, shift by
        q & 31."""
        sel = self.pm_t[0]
        for p_i in range(1, self.n_planes):
            sel = jnp.where((q_lanes >> 5) == p_i, self.pm_t[p_i], sel)
        return (jnp.right_shift(sel, q_lanes & 31) & 1) != 0

    def group(self, gi, v2_g, clearp_g, clearl_g, count_eff_g,
              delivered_g, forgep_g=None):
        """One receiver group's verdicts (group ``gi`` = the ``gi``-th
        contiguous receiver slice): returns ``(ok_g, dup_g,
        own_len_g)``, each ``[n_p, grp]`` (``own_len_g`` int32); the
        arguments are the group's per-receiver columns ``[n_p, grp]``
        (post-corruption order value, clear-P/clear-L flags, effective
        evidence count, delivery mask, and — under strategy="split" —
        the forge-P flag: the packet arrives claiming a MAXIMAL
        presence mask, so the effective P is all-True regardless of the
        raw mask; forgery wins over clear-P).

        Mirrors ``consistent_after_append``'s decomposition, including
        the round-3 ``appended`` fullness guard (reducible to ``~dup``
        under the config invariant ``max_l >= n_rounds + 1``, kept so
        the kernels stay on the spec if the bound is ever raised)."""
        max_l, n_p, grp = self.max_l, self.n_p, self.grp
        v2_lanes = self.expand(v2_g).astype(jnp.int32)
        clearp_lanes = self.expand(clearp_g) != 0
        p2_lanes = self.p_tile & ~clearp_lanes
        if forgep_g is not None:
            # Every downstream term (own row, dup identity, own_len,
            # bad_own, own collision) flows from the effective mask.
            p2_lanes = (self.expand(forgep_g) != 0) | p2_lanes
        li_row = self.lip_vals[gi : gi + 1, :]
        li_bc = jnp.broadcast_to(li_row, (n_p, self.seg_l))
        own_lanes = jnp.where(p2_lanes, li_bc, SENTINEL)

        dup_g = jnp.zeros((n_p, grp), jnp.bool_)
        for r in range(max_l):
            mism = self.seg_reduce(self.vals_t[r] != own_lanes)
            dup_g |= self.valid[r] & (mism == 0)
        dup_g &= ~clearl_g
        own_len_g = self.seg_reduce(p2_lanes).astype(jnp.int32)

        bad_own_pos = p2_lanes & (
            (li_bc == v2_lanes)
            | (self.lioob_vals[gi : gi + 1, :] != 0)
        )
        if self.use_bitmask:
            cont_g = self.seg_reduce(self._plane_bit(v2_lanes)) > 0
            own_coll_g = (
                self.seg_reduce(p2_lanes & self._plane_bit(li_bc)) > 0
            )
            bad_own_g = self.seg_reduce(bad_own_pos) > 0
            cont_or_oob = ~clearl_g & (cont_g | self.oob)
        else:
            contains_g = jnp.zeros((n_p, grp), jnp.bool_)
            own_coll_g = jnp.zeros((n_p, grp), jnp.bool_)
            for r in range(max_l):
                contains_g |= self.valid[r] & (
                    self.seg_reduce(
                        self.in_t_t[r] & (self.vals_t[r] == v2_lanes)
                    )
                    > 0
                )
                own_coll_g |= self.valid[r] & (
                    self.seg_reduce(
                        p2_lanes
                        & self.in_t_t[r]
                        & (self.vals_t[r] == own_lanes)
                    )
                    > 0
                )
            bad_own_g = self.seg_reduce(bad_own_pos) > 0
            cont_or_oob = ~clearl_g & (self.oob | contains_g)

        appended_g = ~dup_g & (count_eff_g < max_l)
        cond2 = ~(cont_or_oob | (appended_g & bad_own_g))
        new_count_g = jnp.where(appended_g, count_eff_g + 1, count_eff_g)
        cond1 = (clearl_g | ~self.lens_bad) & (
            ~appended_g | (count_eff_g == 0) | (own_len_g == self.len0)
        )
        cond3 = (clearl_g | ~self.cells_coll) & (
            ~appended_g | ~(~clearl_g & own_coll_g)
        )
        ok_g = (
            delivered_g & cond1 & cond2 & cond3
            & (new_count_g == self.r_idx + 1)
        )
        return ok_g, dup_g, own_len_g


def _or_fold_lanes(x):
    """Bitwise-OR reduction over the lane axis: ``[n_p, n] -> [n_p, 1]``
    by halving folds (handles odd widths; Mosaic has no or-reduce)."""
    n = x.shape[1]
    while n > 1:
        h = n // 2
        lo = x[:, :h] | x[:, h : 2 * h]
        x = jnp.concatenate([lo, x[:, 2 * h :]], axis=1) if n % 2 else lo
        n = h + (n % 2)
    return x


class AllReceiverVerdict:
    """The verdict flag algebra for ALL receivers of a block in one
    batched pass — no per-receiver-group loop (docs/PERF.md round 5:
    the group loop's serial chains were the tiled verdict kernel's
    measured compute floor at the north-star scale).

    Same inputs/invariants as :class:`VerdictAlgebra`, but every
    receiver-dependent term is an ``[n_p, n_rv]`` 2-D op fed by MXU
    contractions against per-receiver tables built once per trial
    (:func:`make_receiver_tables`):

    * duplicate-of-own-row via the exact integer identity
      ``sum_pos (v - own)^2 == 0`` (the XLA engine's MXU form,
      rounds/engine.py) — ``max_l`` matmuls against ``(li+1)`` /
      ``(li^2-1)`` tables instead of ``n_groups * max_l`` segment
      reductions;
    * evidence-contains-v2 via position-folded presence bit planes
      (one bit-select per receiver column, no lane expansion);
    * own-row collision via a ``(value, position)`` one-hot
      contraction: ``PB[n_p, w*size_l] @ Lh2[w*size_l, n_rv]`` where
      ``PB`` masks the packet's presence planes by P;
    * ``v2 == li`` on a P position via ``P @ Lh`` counts packed into
      16-bit presence planes by a second (config-constant) matmul —
      f32-exact (powers of two, sums < 2^16).

    Exactness gate (:func:`all_receiver_supported`): ``w <= 64`` (bit
    planes) and ``size_l * (w+1)^2 < 2^24`` (the dup identity in f32).
    """

    def __init__(self, *, n_p, n_rv, max_l, size_l, w, gdt,
                 vals, lens, count, p_i32, tables, r_idx):
        self.n_p, self.n_rv = n_p, n_rv
        self.max_l, self.size_l, self.w, self.gdt = max_l, size_l, w, gdt
        self.r_idx = r_idx
        self.lens, self.count = lens, count
        self.len0 = lens[:, 0:1]
        self.vals = vals
        (self.t_li1, self.t_li2, self.t_oob, self.t_lh,
         self.t_lh2) = tables
        in_t = [vals[r] != SENTINEL for r in range(max_l)]
        self.valid = [count > r for r in range(max_l)]
        self.p_i32 = p_i32  # 0/1
        self.p_b = p_i32 != 0
        self.p_f32 = p_i32.astype(jnp.float32)

        # ---- Receiver-independent raw-packet facts (tfg.py:87-98) ----
        false_col = jnp.zeros((n_p, 1), jnp.bool_)
        oob = false_col
        lens_bad = false_col
        cells_coll = false_col
        for r in range(max_l):
            row_bad = jnp.any(
                in_t[r] & ((vals[r] > w) | (vals[r] < 0)),
                axis=1, keepdims=True,
            )
            oob |= self.valid[r] & row_bad
            lens_bad |= self.valid[r] & (lens[:, r : r + 1] != self.len0)
            for s in range(r + 1, max_l):
                hit = jnp.any(
                    in_t[r] & in_t[s] & (vals[r] == vals[s]),
                    axis=1, keepdims=True,
                )
                cells_coll |= self.valid[s] & hit
        self.oob, self.lens_bad, self.cells_coll = oob, lens_bad, cells_coll

        # Value-presence bit planes (same construction as VerdictAlgebra).
        self.n_planes = (w + 31) // 32
        pm = [jnp.zeros((n_p, size_l), jnp.int32)
              for _ in range(self.n_planes)]
        for r in range(max_l):
            for p_i in range(self.n_planes):
                lo, hi = 32 * p_i, 32 * (p_i + 1)
                in_pl = (vals[r] >= lo) & (vals[r] < hi)
                pm[p_i] |= jnp.where(
                    self.valid[r] & in_t[r] & in_pl,
                    jnp.left_shift(jnp.int32(1), vals[r] & 31),
                    0,
                )
        self.pm = pm
        # Position-folded planes: bit q set iff value q appears at ANY
        # position of a valid row — the whole `contains` test becomes a
        # per-receiver bit select.
        self.pm_any = [_or_fold_lanes(p) for p in pm]

    def _mm(self, lhs_f32, tbl):
        """[n_p, K] f32 @ [K, n_rv] table -> [n_p, n_rv] f32 — always
        Precision.HIGHEST: t_li2 carries li^2-1 values beyond bf16's
        256-integer range, and the dup identity needs exact zero."""
        return jax.lax.dot_general(
            lhs_f32, tbl,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )

    def _select_bit(self, planes_cols, q, bits_per_plane):
        """Per-receiver bit select: ``planes_cols`` is a list of
        ``[n_p, n_rv]`` int32 plane columns; ``q`` ``[n_p, n_rv]`` the
        query value.  Returns bool presence of bit ``q % bits`` in
        plane ``q // bits``."""
        shift_bits = bits_per_plane.bit_length() - 1  # 32 -> 5, 16 -> 4
        sel = planes_cols[0]
        for j in range(1, len(planes_cols)):
            sel = jnp.where((q >> shift_bits) == j, planes_cols[j], sel)
        return (
            jnp.right_shift(sel, q & (bits_per_plane - 1)) & 1
        ) != 0

    def flags(self, v2_all, clearp_all, clearl_all, count_eff_all,
              delivered_all, forgep_all=None):
        """All receivers' verdicts in one pass: returns ``ok_all``
        ``[n_p, n_rv]`` bool — the batched equivalent of running
        :meth:`VerdictAlgebra.group` over every lane group.

        ``forgep_all`` (strategy="split" only; ``None`` keeps the
        historical path untouched) marks deliveries whose P mask is
        FORGED to all-True.  The P-factored MXU identities blend in
        their full-mask counterparts — which are receiver-table column
        sums or one extra unmasked contraction, not new per-group
        loops — selected per (packet, receiver) by the flag."""
        n_p, n_rv, max_l = self.n_p, self.n_rv, self.max_l
        size_l, w = self.size_l, self.w
        notcp = jnp.where(clearp_all, 0.0, 1.0)  # (1 - cp) [n_p, n_rv]
        fp = (
            None if forgep_all is None
            else jnp.where(forgep_all, 1.0, 0.0)  # [n_p, n_rv]
        )

        # ---- dup: evidence row == own row, via the integer identity.
        # own = p2*(li+1) - 1; mism_r = ssq_v - 2*cross + ssq_own with
        #   cross  = (1-cp) * [p*v]@(li+1) - sum_v
        #   ssq_own = (1-cp) * [p]@(li^2-1) + size_l
        # (rounds/engine.py's MXU dup form, here per block).  Under
        # forge-P the effective mask is all-True: the masked
        # contractions are replaced by their full-mask forms
        # (column sums of t_li2; one unmasked vals @ t_li1 per row).
        m2 = self._mm(self.p_f32, self.t_li2)  # [n_p, n_rv]
        ssq_own = notcp * m2 + float(size_l)
        if fp is not None:
            m2_full = jnp.sum(self.t_li2, axis=0, keepdims=True)
            ssq_own = (
                fp * (m2_full + float(size_l)) + (1.0 - fp) * ssq_own
            )
        dup_all = jnp.zeros((n_p, n_rv), jnp.bool_)
        for r in range(max_l):
            pv = jnp.where(self.p_b, self.vals[r], 0).astype(jnp.float32)
            m1 = self._mm(pv, self.t_li1)
            s_v = jnp.sum(self.vals[r], axis=1, keepdims=True)
            ssq_v = jnp.sum(
                self.vals[r] * self.vals[r], axis=1, keepdims=True
            )
            cross = notcp * m1 - s_v.astype(jnp.float32)
            if fp is not None:
                m1_full = self._mm(
                    self.vals[r].astype(jnp.float32), self.t_li1
                )
                cross = (
                    fp * (m1_full - s_v.astype(jnp.float32))
                    + (1.0 - fp) * cross
                )
            mism = ssq_v.astype(jnp.float32) - 2.0 * cross + ssq_own
            dup_all |= self.valid[r] & (mism == 0.0)
        dup_all &= ~clearl_all
        own_len_f = notcp * jnp.sum(self.p_f32, axis=1, keepdims=True)
        if fp is not None:
            own_len_f = fp * float(size_l) + (1.0 - fp) * own_len_f
        own_len_all = own_len_f.astype(jnp.int32)

        # ---- contains: v2 present anywhere in a valid row (bit select
        # on the position-folded planes).
        any_cols = [
            jnp.broadcast_to(a, (n_p, n_rv)) for a in self.pm_any
        ]
        cont_all = self._select_bit(any_cols, v2_all, 32)
        cont_or_oob = ~clearl_all & (cont_all | self.oob)

        # ---- own-row collision: exists pos in P with li present in the
        # evidence there.  PB[(q, pos)] = P & bit q of the presence
        # plane at pos; contract against the per-receiver li one-hot.
        pb_planes = []
        bit_planes = []  # un-P-masked — the forge-P full-mask variant
        for p_i in range(self.n_planes):
            reps = min(32, w - 32 * p_i)  # only q < w has Lh2 rows
            # Concatenate int32 vectors only — tpu.concatenate on i1
            # picks an unlowerable vreg bitcast relayout.
            tiled = jnp.concatenate([self.pm[p_i]] * reps, axis=1)
            p_rep = jnp.concatenate([self.p_i32] * reps, axis=1)
            q_in_tile = (
                jax.lax.broadcasted_iota(
                    jnp.int32, (n_p, reps * size_l), 1
                )
                // size_l
            )
            bits_i = jnp.right_shift(tiled, q_in_tile) & 1  # 0/1 int32
            pb_planes.append(bits_i & p_rep)
            if fp is not None:
                bit_planes.append(bits_i)
        pb_i = (
            jnp.concatenate(pb_planes, axis=1)
            if len(pb_planes) > 1 else pb_planes[0]
        )  # [n_p, w*size_l] 0/1 int32
        pb = jnp.where(pb_i != 0, 1.0, 0.0).astype(self.gdt)
        own_coll_cnt = jax.lax.dot_general(
            pb, self.t_lh2.astype(self.gdt),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        own_coll_all = (notcp * own_coll_cnt) > 0.0
        if fp is not None:
            bits_all_i = (
                jnp.concatenate(bit_planes, axis=1)
                if len(bit_planes) > 1 else bit_planes[0]
            )
            pb_full = jnp.where(bits_all_i != 0, 1.0, 0.0).astype(
                self.gdt
            )
            own_coll_full = jax.lax.dot_general(
                pb_full, self.t_lh2.astype(self.gdt),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            own_coll_all = jnp.where(
                forgep_all, own_coll_full > 0.0, own_coll_all
            )

        # ---- bad_own: a P position whose li equals v2 or is oob.
        oob_cnt = self._mm(self.p_f32, self.t_oob)
        # counts of P positions with li == q, all (q, receiver) pairs.
        cq = jax.lax.dot_general(
            self.p_f32.astype(self.gdt), self.t_lh.astype(self.gdt),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [n_p, w * n_rv], ordered q-major
        pres = jnp.where(cq > 0.0, 1.0, 0.0).astype(self.gdt)
        # 16-bit packing matrix, built in-kernel from iota (it is
        # config-constant — an operand would be force-broadcast per
        # trial under the trials vmap): row q*n_rv+r contributes
        # 1 << (q % 16) to plane-major column (q // 16)*n_rv + r.
        n_half = -(-self.w // 16)
        row_i = jax.lax.broadcasted_iota(
            jnp.int32, (self.w * n_rv, n_half * n_rv), 0
        )
        col_i = jax.lax.broadcasted_iota(
            jnp.int32, (self.w * n_rv, n_half * n_rv), 1
        )
        rq, rr = row_i // n_rv, row_i % n_rv
        t_pack = jnp.where(
            (rq // 16 == col_i // n_rv) & (rr == col_i % n_rv),
            jnp.left_shift(jnp.int32(1), rq % 16),
            0,
        ).astype(self.gdt)
        packed = jax.lax.dot_general(
            pres, t_pack,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            # KI-3: t_pack carries 1 << (q % 16) up to 2^15 — far past
            # bf16's 256-integer range — and the gdt here can be f32,
            # whose DEFAULT precision may still lower through
            # single-pass bf16.  Exact today only because powers of two
            # survive bf16 rounding; pin the precision so the packing
            # stays exact if the plane width ever changes.
            precision=jax.lax.Precision.HIGHEST,
        ).astype(jnp.int32)  # [n_p, n_half * n_rv], plane-major
        half_cols = [
            packed[:, j * n_rv : (j + 1) * n_rv] for j in range(n_half)
        ]
        li_eq_v2 = self._select_bit(half_cols, v2_all, 16)
        bad_own_all = ~clearp_all & ((oob_cnt > 0.0) | li_eq_v2)
        if fp is not None:
            # Full-mask bad_own: every own-list position is claimed, so
            # "some P position has li == v2 / li oob" degenerates to
            # per-receiver column sums of the tables — no new matmul.
            # Presence of value q anywhere in receiver r's list is the
            # column sum of t_lh (positions with li == q), bit-packed by
            # constant shifts into the same 16-bit plane select.
            oob_full = jnp.sum(self.t_oob, axis=0, keepdims=True)
            cq_full = jnp.sum(
                self.t_lh.astype(jnp.float32), axis=0, keepdims=True
            )  # [1, w * n_rv], q-major
            pres_full = jnp.where(cq_full > 0.0, 1, 0)  # int32
            full_planes = []
            for j in range(n_half):
                acc = jnp.zeros((1, n_rv), jnp.int32)
                for qq in range(min(16, self.w - 16 * j)):
                    q = 16 * j + qq
                    acc = acc | jnp.left_shift(
                        pres_full[:, q * n_rv : (q + 1) * n_rv], qq
                    )
                full_planes.append(jnp.broadcast_to(acc, (n_p, n_rv)))
            li_eq_v2_full = self._select_bit(full_planes, v2_all, 16)
            bad_own_all = jnp.where(
                forgep_all,
                (oob_full > 0.0) | li_eq_v2_full,
                bad_own_all,
            )

        # ---- the shared condition algebra (consistent_after_append).
        appended_all = ~dup_all & (count_eff_all < max_l)
        cond2 = ~(cont_or_oob | (appended_all & bad_own_all))
        new_count_all = jnp.where(
            appended_all, count_eff_all + 1, count_eff_all
        )
        cond1 = (clearl_all | ~self.lens_bad) & (
            ~appended_all
            | (count_eff_all == 0)
            | (own_len_all == self.len0)
        )
        cond3 = (clearl_all | ~self.cells_coll) & (
            ~appended_all | ~(~clearl_all & own_coll_all)
        )
        return (
            delivered_all & cond1 & cond2 & cond3
            & (new_count_all == self.r_idx + 1)
        )


def all_receiver_supported(size_l: int, w: int) -> bool:
    """Static exactness gate for :class:`AllReceiverVerdict`: bit
    planes need ``w <= 64``; the f32 dup identity needs
    ``size_l * (w+1)^2 < 2^24`` (values live in [-1, w])."""
    return w <= 64 and size_l * (w + 1) * (w + 1) < 2**24


def make_receiver_tables(li, size_l: int, w: int, gdt):
    """Per-trial receiver tables for :class:`AllReceiverVerdict` (built
    ONCE outside the round scan — li is round-invariant):

    * ``t_li1`` f32 ``[size_l, n_rv]`` = ``(li+1)^T``; ``t_li2`` =
      ``(li^2-1)^T`` — the dup identity's contraction tables;
    * ``t_oob`` f32 ``[size_l, n_rv]`` = own-value out-of-bound flags;
    * ``t_lh`` ``[size_l, w*n_rv]`` one-hot ``li[r, pos] == q``,
      columns q-major — P-masked per-value counts;
    * ``t_lh2`` ``[w*size_l, n_rv]`` the same one-hot with ``(q, pos)``
      rows — the own-collision contraction.
    """
    li_f = li.astype(jnp.float32)
    t_li1 = (li_f + 1.0).T
    t_li2 = (li_f * li_f - 1.0).T
    t_oob = jnp.where((li > w) | (li < 0), 1.0, 0.0).T
    oh = (li[:, :, None] == jnp.arange(w)[None, None, :])  # [n_rv, s, w]
    t_lh = (
        oh.transpose(1, 2, 0).reshape(size_l, w * li.shape[0]).astype(gdt)
    )
    t_lh2 = (
        oh.transpose(2, 1, 0).reshape(w * size_l, li.shape[0]).astype(gdt)
    )
    return t_li1, t_li2, t_oob, t_lh, t_lh2


def accept_first_per_value_all(ok_all, v2_all, vi, idx_col, n_p, n_rv, w):
    """All-receiver first-candidate-per-order dedup (``tfg.py:294``):
    the batched form of :func:`accept_first_per_value` — receivers' vi
    rows are disjoint, so one ``[n_p, n_rv, w]`` pass computes every
    receiver's column with no serial chain.  ``vi`` is the CURRENT
    ``[n_rv, w]`` int32 accepted-set matrix (read once by the caller);
    returns ``(acc [n_p, n_rv] int32, new_vi [n_rv, w] int32)``.  The
    cross-block sequential carry stays with the caller's revisited
    output block (the carry is irreducible in the sense that later
    blocks' candidates depend on earlier blocks' accepted values — see
    the dependency repro in tests/test_verdict_algebra.py — but it IS
    associative: this per-block first-index + the caller's vi merge is
    exactly the associative combine, and TPU grid steps run in order
    anyway, so the carry costs O(n_rv*w) elementwise work per block).

    Since round 6 this is the accept path of BOTH verdict-kernel
    variants ("group" assembles ok_all from the lane-group flag passes;
    "allrecv" from the all-receiver algebra) and of the monolithic
    kernel — exact for any ``w`` (no dots, pure compare/min/max), so no
    KI-3 precision concern."""
    iota_w = jax.lax.broadcasted_iota(jnp.int32, (n_p, n_rv, w), 2)
    onehot = v2_all[:, :, None] == iota_w  # [n_p, n_rv, w]
    # Minor-dim insertion on an i1 vector is not lowerable (Mosaic:
    # "Insertion of minor dim that is not a no-op only supported for
    # 32-bit types") — expand ok as int32, compare back to bool.
    ok_i = jnp.where(ok_all, 1, 0)
    cand = onehot & (ok_i[:, :, None] != 0) & (vi[None, :, :] == 0)
    masked_idx = jnp.where(cand, idx_col[:, :, None], n_p)
    first = jnp.min(masked_idx, axis=0)  # [n_rv, w]
    acc_i = jnp.where(
        cand & (first[None, :, :] == idx_col[:, :, None]), 1, 0
    )  # int32 throughout — i1 reduces pick unlowerable vreg bitcasts
    acc = jnp.max(acc_i, axis=2)
    # [n_p, n_rv] — at most one lane per (packet, receiver)
    new_vi = jnp.where(
        (vi != 0) | (jnp.max(acc_i, axis=0) != 0), 1, 0
    )
    return acc, new_vi


def accept_first_per_value_group(r0, grp, ok_g, v2_g, ovi_ref,
                                 idx_col, n_p, w):
    """Group-batched :func:`accept_first_per_value`: the ``grp``
    receivers of one lane group processed in a single
    ``[n_p, grp*w]``-lane pass instead of a serial per-receiver chain
    (the receiver loop was the verdict kernels' compute floor on live
    blocks — ~8 small ops per receiver with a scheduling dependency
    through the shared vi ref).  Receivers' vi rows are disjoint, so
    batching cannot reorder anything observable; the cross-block
    sequential carry is untouched.

    Reads rows ``r0 .. r0+grp`` of ``ovi_ref`` and returns
    ``(acc_cols, new_rows)`` WITHOUT storing — two python lists of
    ``grp`` int32 arrays each: ``acc_cols[j]`` is receiver ``r0+j``'s
    acceptance column ``[n_p, 1]`` and ``new_rows[j]`` its updated vi
    row ``[1, w]`` (0/1 int32, directly storable into the refs).  The
    caller stores per receiver so tail-group overlap can skip
    already-updated rows (the update is not idempotent for acc).
    Requires ``grp * w`` lanes per tile."""
    seg = grp * w
    iota_lane = jax.lax.broadcasted_iota(jnp.int32, (n_p, seg), 1)
    lane_val = iota_lane % w
    # Per-lane v2/ok of the lane's segment (static where-chain over the
    # small grp).
    v2_lane = jnp.broadcast_to(v2_g[:, 0:1], (n_p, seg))
    ok_lane = jnp.broadcast_to(
        jnp.where(ok_g[:, 0:1], 1, 0), (n_p, seg)
    )
    for j in range(1, grp):
        in_seg = iota_lane >= j * w
        v2_lane = jnp.where(in_seg, v2_g[:, j : j + 1], v2_lane)
        ok_lane = jnp.where(
            in_seg, jnp.where(ok_g[:, j : j + 1], 1, 0), ok_lane
        )
    onehot = lane_val == v2_lane  # exactly one lane per (packet, segment)
    vi_flat = jnp.concatenate(
        [ovi_ref[r0 + j : r0 + j + 1, :] for j in range(grp)], axis=1
    )  # [1, seg]
    cand_lane = onehot & (ok_lane != 0) & (vi_flat == 0)
    masked_idx = jnp.where(cand_lane, idx_col, n_p)
    first = jnp.min(masked_idx, axis=0, keepdims=True)  # [1, seg]
    acc_lane = jnp.where(cand_lane & (first == idx_col), 1, 0)
    # Per-receiver columns: each (packet, segment) has at most one lane
    # set, so a lane max over the segment is the indicator.
    acc_cols = [
        jnp.max(acc_lane[:, j * w : (j + 1) * w], axis=1, keepdims=True)
        for j in range(grp)
    ]
    any_acc = jnp.max(acc_lane, axis=0, keepdims=True)
    new_flat = jnp.where((vi_flat != 0) | (any_acc != 0), 1, 0)  # [1, seg]
    new_rows = [new_flat[:, j * w : (j + 1) * w] for j in range(grp)]
    return acc_cols, new_rows


def accept_first_per_value(ok, v2, vi_row, idx_col, n_p, w):
    """First-candidate-per-order dedup against Vi (``tfg.py:294``) for
    one receiver: among packets with ``ok`` carrying the same order
    value, the lowest index wins, and values already in ``vi_row`` are
    excluded.  Returns ``(acc [n_p, 1] bool, new_vi_row [1, w] bool)``.
    NOT idempotent at the caller (the vi update must land exactly once
    per receiver)."""
    iota_w = jax.lax.broadcasted_iota(jnp.int32, (n_p, w), 1)
    onehot = v2 == iota_w  # [n_p, w]
    in_vi = jnp.any(onehot & (vi_row != 0), axis=1, keepdims=True)
    cand = ok & ~in_vi
    masked_idx = jnp.where(onehot & cand, idx_col, n_p)
    first = jnp.min(masked_idx, axis=0, keepdims=True)  # [1, w]
    first_b = jnp.min(
        jnp.where(onehot, jnp.broadcast_to(first, (n_p, w)), n_p),
        axis=1, keepdims=True,
    )
    acc = cand & (first_b == idx_col)
    new_vi = (vi_row != 0) | jnp.any(acc & onehot, axis=0, keepdims=True)
    return acc, new_vi
