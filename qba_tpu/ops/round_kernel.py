"""Pallas TPU kernel for the voting-round hot loop.

One kernel invocation executes a FULL protocol round for one trial — all
receivers' inbox drains (``tfg.py:337-348`` + ``lieu_receive``,
``tfg.py:289-300``) — with the round's entire mailbox resident in VMEM
(~205 KB at the headline config).

Why a kernel: the XLA formulation of the per-(receiver, packet) verdict is
a batch of tiny ``[max_l, size_l]`` reductions whose tiles occupy ~30% of
the VPU and whose loop fusions ran at a few Gop/s (three ~70 ms fusions
per batch at nParties=11, sizeL=64, 1000 trials).  Here the layout is
chosen for the hardware: packets fill the sublane dimension (``n_pk`` of
them) and list positions fill lanes, so every verdict reduction is a
dense ``[n_pk, size_l]`` tile op and the whole round is one fused program.

Semantics are bit-identical to the XLA path
(:func:`qba_tpu.rounds.engine.receiver_round`) — enforced by the
equivalence tests in tests/test_round_kernel.py and by the three-way
backend differentials.

Layout conventions (per trial; ``vmap`` over trials prepends the grid):

* ``vals``  — int32 ``[max_l, n_pk, size_l]`` (row-major outer so each
  evidence row is one clean 2-D tile)
* ``lens``  — int32 ``[n_pk, max_l]``
* per-packet scalars (``count``, ``v``, ``sent``, honesty, draws) —
  int32 ``[n_pk, 1]`` columns or ``[n_lieu, n_pk]`` row-sliced per
  receiver; all flags stay 2-D end to end
* bools travel as int32 0/1 (predicate relayouts are avoided entirely)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from qba_tpu.adversary import (
    CLEAR_L_BIT,
    CLEAR_P_BIT,
    DROP_BIT,
    FORGE_BIT,
)
from qba_tpu.config import QBAConfig
from qba_tpu.core.types import SENTINEL


def _cumsum_exclusive(col: jnp.ndarray, n: int) -> jnp.ndarray:
    """Exclusive prefix sum along the sublane axis of an ``[n, 1]`` int32
    column — one strictly-lower-triangular MXU matmul (a log2(n) chain of
    shifted adds costs ~2 log2(n) vector relayouts per call; the matmul is
    one op and exact for the small integer counts involved).

    bf16 operands: both operands are 0/1 flags (exact in bf16) and the
    MXU accumulates in f32, so the result is bit-exact while running at
    the MXU's fast path.
    """
    iota_r = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
    iota_c = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
    tri = (iota_c < iota_r).astype(jnp.bfloat16)  # strictly lower triangular
    return jax.lax.dot_general(
        tri,
        col.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)


def build_round_step(cfg: QBAConfig, *, interpret: bool = False):
    """Compile one synchronous voting round for one trial.

    Returns ``step(round_idx, vals, lens, count, p, v, sent, li, vi,
    honest_pk, attack, rand_v, late) -> (ovals, olens, ocount, op,
    ov, osent, ovi, overflow)`` — jit/vmap-safe (vmap over trials becomes
    the Pallas grid).  ``attack`` is the effective edit bitmask from
    :func:`qba_tpu.adversary.sample_attacks_round` (bit0 drop, bit1
    forge-v, bit2 clear-P, bit3 clear-L) — scope semantics are folded in
    before the kernel, so the kernel algebra is scope-agnostic.
    """
    n_s, slots, max_l = cfg.n_lieutenants, cfg.slots, cfg.max_l
    size_l, w = cfg.size_l, cfg.w
    n_pk = n_s * slots
    n_dis = cfg.n_dishonest
    # Matmul operand dtype: bf16 is exact for integers of magnitude
    # <= 256; larger list lengths / order ranges fall back to f32.
    gdt = jnp.bfloat16 if size_l <= 256 and w <= 256 else jnp.float32

    def kernel(
        round_ref,  # SMEM [1]
        vals_ref,  # [max_l, n_pk, size_l]
        lens_ref,  # [n_pk, max_l]
        count_ref,  # [n_pk, 1]
        p_ref,  # [n_pk, size_l]
        v_ref,  # [n_pk, 1]
        sent_ref,  # [n_pk, 1]
        li_ref,  # [n_lieu, size_l]
        vi_ref,  # [n_lieu, w]
        honest_ref,  # [n_pk, 1]
        act_ref,  # [n_pk, n_lieu] edit bitmasks (packet-major)
        rv_ref,
        late_ref,
        ovals_ref,
        olens_ref,
        ocount_ref,
        op_ref,
        ov_ref,
        osent_ref,
        ovi_ref,
        oovf_ref,  # [1, 1]
        acc_scr,  # scratch [n_pk, n_lieu] i32 — per-receiver accept cols
        dup_scr,  # scratch [n_pk, n_lieu] i32
        olen_scr,  # scratch [n_pk, n_lieu] i32
        g_scr,  # scratch [n_pk, n_pk] gdt — global one-hot gather matrix
    ):
        r_idx = round_ref[0]
        idx_col = jax.lax.broadcasted_iota(jnp.int32, (n_pk, 1), 0)
        sender_col = idx_col // slots

        vals = [vals_ref[r] for r in range(max_l)]  # each [n_pk, size_l]
        in_t = [vals[r] != SENTINEL for r in range(max_l)]
        lens = lens_ref[:]  # [n_pk, max_l]
        count = count_ref[:]  # [n_pk, 1]
        p_in = p_ref[:] != 0  # [n_pk, size_l]
        v_in = v_ref[:]  # [n_pk, 1]
        sent = sent_ref[:] != 0  # [n_pk, 1]
        biz = honest_ref[:] == 0  # [n_pk, 1]
        valid = [count > r for r in range(max_l)]  # each [n_pk, 1]
        len0 = lens[:, 0:1]  # [n_pk, 1]

        # ---- Receiver-independent raw-mailbox facts ----------------------
        false_col = jnp.zeros((n_pk, 1), jnp.bool_)
        oob = false_col
        lens_bad = false_col
        cells_coll = false_col
        for r in range(max_l):
            row_bad = jnp.any(
                in_t[r] & ((vals[r] > w) | (vals[r] < 0)), axis=1, keepdims=True
            )
            oob |= valid[r] & row_bad
            lens_bad |= valid[r] & (lens[:, r : r + 1] != len0)
            for s in range(r + 1, max_l):
                hit = jnp.any(
                    in_t[r] & in_t[s] & (vals[r] == vals[s]),
                    axis=1,
                    keepdims=True,
                )
                cells_coll |= valid[s] & hit

        # Per-position value-presence bitmask (w <= 32 only): bit x of
        # ``pm[pk, j]`` is set iff some valid evidence row holds value x at
        # position j.  Turns the per-receiver contains-v2 / own-collision
        # row loops (O(max_l) [n_pk, size_l] reductions each) into single
        # vector shifts against this shared table — the receiver unroll is
        # the kernel's hot loop, so receiver-independent precompute is
        # nearly free by comparison.
        use_bitmask = w <= 32
        if use_bitmask:
            pm = jnp.zeros((n_pk, size_l), jnp.int32)
            for r in range(max_l):
                in_range = (vals[r] >= 0) & (vals[r] <= 31)
                pm |= jnp.where(
                    valid[r] & in_t[r] & in_range,
                    jnp.left_shift(jnp.int32(1), vals[r] & 31),
                    0,
                )
        # Own-row out-of-range check factored out of the receiver loop:
        # under p2 the own row is exactly the receiver's list, so
        # ``own > w | own < 0`` reduces to this per-lieutenant table.
        li_all = li_ref[:]  # [n_lieu, size_l]
        li_oob_all = (li_all > w) | (li_all < 0)

        ovi_ref[:] = vi_ref[:]
        # No zero-init of the other outputs: the batched rebuild at the
        # bottom stores every row of every output exactly once.

        # ---- All-receiver flag algebra: one [n_pk, n_lieu] op each -------
        # The draws are packet-major, so every per-receiver corruption
        # flag is computed for all receivers in one tile op; the unrolled
        # receiver loop below consumes relayout-free lane slices.
        act_all = act_ref[:]  # [n_pk, n_lieu]
        rv_all = rv_ref[:]
        late_all = late_ref[:]
        lane_recv = jax.lax.broadcasted_iota(jnp.int32, (n_pk, n_s), 1)
        dropped_all = biz & ((act_all & DROP_BIT) != 0)
        v2_all = jnp.where(biz & ((act_all & FORGE_BIT) != 0), rv_all, v_in)
        clearp_all = biz & ((act_all & CLEAR_P_BIT) != 0)
        clearl_all = biz & ((act_all & CLEAR_L_BIT) != 0)
        delivered_all = (
            ~dropped_all & (late_all == 0) & sent & (sender_col != lane_recv)
        )
        count_eff_all = jnp.where(clearl_all, 0, count)

        for recv in range(n_s):  # Loop A: verdicts + acceptance + vi
            v2 = v2_all[:, recv : recv + 1]  # [n_pk, 1]
            clear_p = clearp_all[:, recv : recv + 1]
            clear_l = clearl_all[:, recv : recv + 1]
            delivered = delivered_all[:, recv : recv + 1]
            count_eff = count_eff_all[:, recv : recv + 1]
            li_row = li_ref[recv : recv + 1, :]  # [1, size_l]

            p2 = p_in & ~clear_p  # [n_pk, size_l]
            own = jnp.where(
                p2, jnp.broadcast_to(li_row, (n_pk, size_l)), SENTINEL
            )
            own_len = jnp.sum(p2.astype(jnp.int32), axis=1, keepdims=True)

            dup = false_col
            for r in range(max_l):
                same = ~jnp.any(vals[r] != own, axis=1, keepdims=True)
                dup |= valid[r] & same
            dup &= ~clear_l

            if use_bitmask:
                # Arithmetic shift is fine: only bit 0 is read after it.
                # contains_v2 and bad_own share one fused [n_pk, size_l]
                # reduction below (any(A)|any(B) == any(A|B)).
                contains_v2_pos = (jnp.right_shift(pm, v2) & 1) != 0
                own_coll = jnp.any(
                    p2 & ((jnp.right_shift(pm, li_row) & 1) != 0),
                    axis=1,
                    keepdims=True,
                )
            else:
                contains_v2 = false_col
                own_coll = false_col
                for r in range(max_l):
                    contains_v2 |= valid[r] & jnp.any(
                        in_t[r] & (vals[r] == v2), axis=1, keepdims=True
                    )
                    own_coll |= valid[r] & jnp.any(
                        p2 & in_t[r] & (vals[r] == own), axis=1, keepdims=True
                    )

            # The min() clamp never fires (mailbox counts <= max_l-1 by
            # the rebroadcast bound) — see the matching note in
            # rounds/engine.py before changing max_l's derivation.
            new_count = jnp.where(
                dup, count_eff, jnp.minimum(count_eff + 1, max_l)
            )

            cond1 = (clear_l | ~lens_bad) & (
                (count_eff == 0) | (own_len == len0)
            )
            bad_own_pos = p2 & (
                (li_row == v2) | li_oob_all[recv : recv + 1, :]
            )
            if use_bitmask:
                bad2 = jnp.any(
                    (~clear_l & contains_v2_pos) | bad_own_pos,
                    axis=1,
                    keepdims=True,
                )
                cond2 = ~(bad2 | (~clear_l & oob))
            else:
                bad_own = jnp.any(bad_own_pos, axis=1, keepdims=True)
                cond2 = ~((~clear_l & (oob | contains_v2)) | bad_own)
            cond3 = (clear_l | ~cells_coll) & (dup | ~(~clear_l & own_coll))
            ok = delivered & cond1 & cond2 & cond3 & (new_count == r_idx + 1)

            # ---- dedup: first candidate per order value (tfg.py:294) -----
            vi_row = ovi_ref[recv : recv + 1, :]  # [1, w]
            iota_w = jax.lax.broadcasted_iota(jnp.int32, (n_pk, w), 1)
            onehot = v2 == iota_w  # [n_pk, w]
            in_vi = jnp.any(
                onehot & (vi_row != 0), axis=1, keepdims=True
            )  # [n_pk, 1]
            cand = ok & ~in_vi
            masked_idx = jnp.where(onehot & cand, idx_col, n_pk)
            first = jnp.min(masked_idx, axis=0, keepdims=True)  # [1, w]
            first_b = jnp.min(
                jnp.where(onehot, jnp.broadcast_to(first, (n_pk, w)), n_pk),
                axis=1,
                keepdims=True,
            )  # [n_pk, 1]
            acc = cand & (first_b == idx_col)

            new_vi = (vi_row != 0) | jnp.any(acc & onehot, axis=0, keepdims=True)
            ovi_ref[recv : recv + 1, :] = new_vi.astype(jnp.int32)

            # Stash this receiver's per-packet columns for the batched
            # rebuild below.
            acc_scr[:, recv : recv + 1] = acc.astype(jnp.int32)
            dup_scr[:, recv : recv + 1] = dup.astype(jnp.int32)
            olen_scr[:, recv : recv + 1] = own_len

        # ---- Batched slot allocation (tfg.py:298-299), all receivers -----
        # One triangular MXU matmul computes every receiver's exclusive
        # prefix count at once (the per-receiver version was n_s matmuls).
        acc_all = acc_scr[:] != 0  # [n_pk, n_lieu]
        dup_all = dup_scr[:] != 0
        olen_all = olen_scr[:]
        rebroadcast_all = acc_all & (r_idx <= n_dis)
        slot_all = _cumsum_exclusive(rebroadcast_all.astype(jnp.int32), n_pk)
        write_all = rebroadcast_all & (slot_all < slots)
        oovf_ref[:] = (
            jnp.any(rebroadcast_all & ~write_all)
            .astype(jnp.int32)
            .reshape(1, 1)
        )
        new_count_all = jnp.where(
            dup_all, count_eff_all, jnp.minimum(count_eff_all + 1, max_l)
        )

        # Loop B: assemble the global one-hot gather matrix column block by
        # column block — G[pk, c] = 1 iff packet pk feeds output cell c
        # (injective: each cell has at most one source).
        iota_s = jax.lax.broadcasted_iota(jnp.int32, (n_pk, slots), 1)
        for recv in range(n_s):
            g_r = write_all[:, recv : recv + 1] & (
                slot_all[:, recv : recv + 1] == iota_s
            )
            g_scr[:, recv * slots : (recv + 1) * slots] = g_r.astype(gdt)

        # ---- Batched rebuild: one full-width MXU matmul per field --------
        # out[c] = field[src(c), recv(c)].  Receiver-independent fields
        # (evidence rows, lens, P) gather directly with G^T; receiver-
        # dependent [n_pk, n_lieu] fields gather to [c, n_lieu] and a lane
        # select against recv(c) picks the right column.  This replaces
        # ~12 small [slots, n_pk] matmuls per receiver with ~16 full-width
        # ones total.  bf16 operands are exact when every gathered value
        # is an integer of magnitude <= 256 (vals < w, lengths <= size_l,
        # G is 0/1); larger configs fall back to f32 (see gdt).
        big_g = g_scr[:]
        row_c = jax.lax.broadcasted_iota(jnp.int32, (n_pk, n_s), 0)
        recv_onehot = (lane_recv == row_c // slots).astype(jnp.float32)

        def gmat(x):  # [n_pk(src), X] -> f32 [n_pk(c), X]
            return jax.lax.dot_general(
                big_g,
                x.astype(gdt),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        def gsel(field_all):  # [n_pk(src), n_lieu] -> int32 [n_pk(c), 1]
            gd = gmat(field_all)  # [c, n_lieu] f32
            return jnp.sum(gd * recv_onehot, axis=1, keepdims=True).astype(
                jnp.int32
            )

        has = gsel(jnp.ones((n_pk, n_s), jnp.int32)) != 0  # [c, 1]
        v2_g = gsel(v2_all)
        cnt_g = gsel(count_eff_all)
        dup_g = gsel(dup_all.astype(jnp.int32))
        clr_g = gsel(clearl_all.astype(jnp.int32))
        clrp_g = gsel(clearp_all.astype(jnp.int32))
        olen_g = gsel(olen_all)
        ncnt_g = gsel(new_count_all)

        pin_g = gmat(p_in).astype(jnp.int32)  # [c, size_l]
        lens_g = gmat(lens).astype(jnp.int32)  # [c, max_l]
        rows_g = [gmat(vals[r]).astype(jnp.int32) for r in range(max_l)]
        # li_exp[c] = li[recv(c)] — the receiver's own list, re-expanded
        # instead of gathered (own rows never need the source packet).
        li_exp = jax.lax.dot_general(
            recv_onehot.astype(gdt),
            li_all.astype(gdt),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(jnp.int32)
        p2_g = (pin_g != 0) & (clrp_g == 0)
        own_g = jnp.where(p2_g, li_exp, SENTINEL)

        iota_l = jax.lax.broadcasted_iota(jnp.int32, (n_pk, max_l), 1)
        keep_row = (clr_g == 0) & (iota_l < cnt_g)
        new_row = (dup_g == 0) & (iota_l == cnt_g)
        olens_ref[:] = jnp.where(
            has,
            jnp.where(new_row, olen_g, jnp.where(keep_row, lens_g, 0)),
            0,
        )
        for r in range(max_l):
            keep = (clr_g == 0) & (r < cnt_g)  # [c, 1]
            is_new = (dup_g == 0) & (r == cnt_g)
            row = jnp.where(
                is_new, own_g, jnp.where(keep, rows_g[r], SENTINEL)
            )
            ovals_ref[r] = jnp.where(has, row, SENTINEL)
        ocount_ref[:] = jnp.where(has, ncnt_g, 0)
        op_ref[:] = jnp.where(has, p2_g.astype(jnp.int32), 0)
        ov_ref[:] = jnp.where(has, v2_g, 0)
        osent_ref[:] = has.astype(jnp.int32)

    out_shapes = (
        jax.ShapeDtypeStruct((max_l, n_pk, size_l), jnp.int32),  # vals
        jax.ShapeDtypeStruct((n_pk, max_l), jnp.int32),  # lens
        jax.ShapeDtypeStruct((n_pk, 1), jnp.int32),  # count
        jax.ShapeDtypeStruct((n_pk, size_l), jnp.int32),  # p
        jax.ShapeDtypeStruct((n_pk, 1), jnp.int32),  # v
        jax.ShapeDtypeStruct((n_pk, 1), jnp.int32),  # sent
        jax.ShapeDtypeStruct((n_s, w), jnp.int32),  # vi
        jax.ShapeDtypeStruct((1, 1), jnp.int32),  # overflow
    )

    # The mailbox + vi inputs are donated into the corresponding outputs:
    # the round step is a lax.scan body, and without aliasing XLA inserts
    # a full mailbox copy per round to rebuild the carry (~7% of the round
    # loop at the headline config).  Safe because the kernel loads every
    # aliased ref into values before its first output store (vals/lens/
    # count/p/v/sent are read exactly once at the top; vi is copied into
    # ovi and only ovi is read after).
    call = pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [pl.BlockSpec(memory_space=pltpu.VMEM)] * 12,
        out_specs=tuple(
            pl.BlockSpec(memory_space=pltpu.VMEM) for _ in out_shapes
        ),
        input_output_aliases={1: 0, 2: 1, 3: 2, 4: 3, 5: 4, 6: 5, 8: 6},
        scratch_shapes=[
            pltpu.VMEM((n_pk, n_s), jnp.int32),  # acc_scr
            pltpu.VMEM((n_pk, n_s), jnp.int32),  # dup_scr
            pltpu.VMEM((n_pk, n_s), jnp.int32),  # olen_scr
            pltpu.VMEM((n_pk, n_pk), gdt),  # g_scr
        ],
        interpret=interpret,
    )

    def step(round_idx, vals, lens, count, p, v, sent, li, vi, honest_pk,
             attack, rand_v, late):
        # Draws arrive packet-major [n_pk, n_lieu] straight from
        # sample_attacks_round — no transpose anywhere on the path.
        return call(
            jnp.asarray([round_idx], jnp.int32),
            vals, lens, count, p, v, sent, li, vi, honest_pk,
            attack, rand_v, late,
        )

    return step


# Scoped VMEM available to a kernel instance (v5e exposes 16 MB; leave
# headroom for Mosaic's own scratch).
_VMEM_BUDGET_BYTES = 10 * 2**20


def fits_kernel(cfg: QBAConfig) -> bool:
    """Whether the round kernel's per-trial working set fits in VMEM.

    The kernel holds the mailbox (in + out) plus ~a dozen
    ``[n_pk, size_l]``-sized intermediates per receiver iteration.  At
    the reference's sizeL=1000 with 5 traitors that is ~20 MB — over the
    16 MB scoped-vmem limit (observed compile failure) — so ``auto``
    engine selection falls back to the XLA path for such configs.
    """
    n_pk = cfg.n_lieutenants * cfg.slots
    tile = 4 * n_pk * cfg.size_l
    # Tile count: mailbox in + out refs (2*max_l), loaded row values and
    # their in-tuple masks (2*max_l), and ~a dozen [n_pk, size_l]
    # intermediates (p_in/p2/own/op plus fusion temporaries).
    est = tile * (4 * cfg.max_l + 12)
    # Plus the [n_pk, n_pk] working set of the batched rebuild: the
    # triangular prefix-sum operand (f32/bf16) and the one-hot gather
    # scratch.
    est += n_pk * n_pk * 8
    return est <= _VMEM_BUDGET_BYTES
