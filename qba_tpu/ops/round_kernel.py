"""Pallas TPU kernel for the voting-round hot loop.

One kernel invocation executes a FULL protocol round for one trial — all
receivers' inbox drains (``tfg.py:337-348`` + ``lieu_receive``,
``tfg.py:289-300``) — with the round's entire mailbox resident in VMEM
(~205 KB at the headline config).

Why a kernel: the XLA formulation of the per-(receiver, packet) verdict is
a batch of tiny ``[max_l, size_l]`` reductions whose tiles occupy ~30% of
the VPU and whose loop fusions ran at a few Gop/s (three ~70 ms fusions
per batch at nParties=11, sizeL=64, 1000 trials).  Here the layout is
chosen for the hardware: packets fill the sublane dimension (``n_pk`` of
them) and list positions fill lanes, so every verdict reduction is a
dense ``[n_pk, size_l]`` tile op and the whole round is one fused program.

Semantics are bit-identical to the XLA path
(:func:`qba_tpu.rounds.engine.receiver_round`) — enforced by the
equivalence tests in tests/test_round_kernel.py and by the three-way
backend differentials.

Layout conventions (per trial; ``vmap`` over trials prepends the grid):

* ``vals``  — int32 ``[max_l, n_pk, size_l]`` (row-major outer so each
  evidence row is one clean 2-D tile)
* ``lens``  — int32 ``[n_pk, max_l]``
* per-packet scalars (``count``, ``v``, ``sent``, honesty, draws) —
  int32 ``[n_pk, 1]`` columns or ``[n_lieu, n_pk]`` row-sliced per
  receiver; all flags stay 2-D end to end
* bools travel as int32 0/1 (predicate relayouts are avoided entirely)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from qba_tpu.adversary import (
    CLEAR_L_BIT,
    CLEAR_P_BIT,
    DROP_BIT,
    FORGE_BIT,
    FORGE_P_BIT,
)
from qba_tpu.config import QBAConfig
from qba_tpu.core.types import SENTINEL
from qba_tpu.diagnostics import QBAProbeWarning, warn_and_record
from qba_tpu.ops.verdict_algebra import (
    VerdictAlgebra,
    _exact_prec,
    accept_first_per_value_all,
)

# Compiler-params compat: older jax builds name the Pallas-TPU params
# class ``TPUCompilerParams``; newer ones ``CompilerParams``.
CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)


def _cumsum_exclusive(col: jnp.ndarray, n: int) -> jnp.ndarray:
    """Exclusive prefix sum along the sublane axis of an ``[n, 1]`` int32
    column — one strictly-lower-triangular MXU matmul (a log2(n) chain of
    shifted adds costs ~2 log2(n) vector relayouts per call; the matmul is
    one op and exact for the small integer counts involved).

    bf16 operands: both operands are 0/1 flags (exact in bf16) and the
    MXU accumulates in f32, so the result is bit-exact while running at
    the MXU's fast path.
    """
    iota_r = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
    iota_c = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
    tri = (iota_c < iota_r).astype(jnp.bfloat16)  # strictly lower triangular
    return jax.lax.dot_general(
        tri,
        col.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)


def _lane_group(size_l: int, n_recv: int) -> int:
    """Receivers packed side by side per lane tile (kernel v4): fill the
    VPU's 128 lanes when size_l is narrow; 1 when a single receiver's
    positions already span a full tile.  Shared by the kernel builder and
    the fits_kernel VMEM estimate so they cannot drift."""
    return max(1, min(128 // size_l, n_recv))


def pack_mailbox(mb, n_rows: int, max_l: int, size_l: int):
    """Mailbox pytree -> the kernel's operand layout (shared by the
    single-device and party-sharded callers so the layout contract lives
    in exactly one place next to the kernel that defines it)."""
    return (
        mb.vals.reshape(n_rows, max_l, size_l).transpose(1, 0, 2),
        mb.lens.reshape(n_rows, max_l),
        mb.count.reshape(n_rows, 1),
        mb.p_mask.reshape(n_rows, size_l).astype(jnp.int32),
        mb.v.reshape(n_rows, 1),
        mb.sent.reshape(n_rows, 1).astype(jnp.int32),
    )


def honest_packets(honest, cfg: QBAConfig):
    """Per-packet sender-honesty column [n_pk, 1] from the rank-indexed
    honesty mask (the kernel's honest_pk operand)."""
    n_pk = cfg.n_lieutenants * cfg.slots
    senders = jnp.arange(n_pk) // cfg.slots
    return honest[senders + 2].astype(jnp.int32)[:, None]


# Shared vma plumbing for every Pallas kernel builder that can run
# under shard_map's replication checker (this module's monolithic round
# step and both tiled kernels import these — ONE copy of the promotion
# rule, not three hand-synchronized closures).

def _detect_vma_support() -> bool:
    """Whether this jax build has the varying-manual-axes machinery
    (``ShapeDtypeStruct(..., vma=...)`` / ``lax.pcast``).  Older builds
    predate it — their shard_map replication checker (``check_rep``)
    has its own pallas rules, so the declarations below degrade to
    no-ops rather than crashing every party-sharded kernel build."""
    try:
        jax.ShapeDtypeStruct((1,), jnp.int32, vma=frozenset())
        return True
    except TypeError:
        return False


_HAVE_VMA = _detect_vma_support()


def promote_vma(out_vma, x):
    """Promote ``x`` to carry every axis in ``out_vma``: under the
    replication checker every pallas operand must match the declared
    vma; constants and replicated values get pcast explicitly.
    No-op when ``out_vma`` is None (checker off) or the build has no
    vma machinery."""
    if out_vma is None or not _HAVE_VMA:
        return x
    have = getattr(jax.typeof(x), "vma", frozenset())
    need = tuple(a for a in out_vma if a not in have)
    return jax.lax.pcast(x, need, to="varying") if need else x


def vma_struct(out_vma, dims, dt=jnp.int32):
    """``ShapeDtypeStruct`` carrying the declared output vma (pallas
    outputs must state which mesh axes they vary over under the
    replication checker).

    Contract (KI-1): every kernel builder must route its ``out_vma``
    argument through this helper and :func:`promote_vma` — the lint's
    threading audit injects a sentinel at each builder and requires it
    to arrive here (qba_tpu/analysis/vma.py, docs/ANALYSIS.md)."""
    if out_vma is None or not _HAVE_VMA:
        return jax.ShapeDtypeStruct(dims, dt)
    return jax.ShapeDtypeStruct(dims, dt, vma=out_vma)


def build_round_step(
    cfg: QBAConfig,
    *,
    interpret: bool = False,
    n_recv: int | None = None,
    out_vma: frozenset | None = None,
):
    """Compile one synchronous voting round for one trial.

    Returns ``step(round_idx, vals, lens, count, p, v, sent, li, vi,
    honest_pk, attack, rand_v, late) -> (ovals, olens, ocount, op,
    ov, osent, ovi, overflow)`` — jit/vmap-safe (vmap over trials becomes
    the Pallas grid).  ``attack`` is the effective edit bitmask from
    :func:`qba_tpu.adversary.sample_attacks_round` (bit0 drop, bit1
    forge-v, bit2 clear-P, bit3 clear-L) — scope semantics are folded in
    before the kernel, so the kernel algebra is scope-agnostic.

    ``n_recv`` builds the party-sharded variant for
    :mod:`qba_tpu.parallel.spmd`: the kernel drains the inbox of a
    contiguous block of ``n_recv`` receivers against the FULL gathered
    mailbox, taking the block's first receiver index as an extra
    *runtime* operand (every device runs the same program under
    shard_map, so the offset cannot be compile-time).  ``step`` then has
    signature ``step(round_idx, recv_off, vals..., li_local, vi_local,
    honest_pk, attack_local, rand_v_local, late_local)`` with the
    receiver-indexed operands holding only the local block's rows /
    columns, and returns the local block's outgoing mailbox cells + vi.
    """
    n_s, slots, max_l = cfg.n_lieutenants, cfg.slots, cfg.max_l
    size_l, w = cfg.size_l, cfg.w
    n_pk = n_s * slots
    n_dis = cfg.n_dishonest
    local = n_recv is not None
    n_rv = n_recv if local else n_s  # receivers this kernel drains
    n_c = n_rv * slots  # outgoing mailbox cells produced
    # Matmul operand dtype: bf16 is exact for integers of magnitude
    # <= 256; larger list lengths / order ranges fall back to f32.
    gdt = jnp.bfloat16 if size_l <= 256 and w <= 256 else jnp.float32

    # ---- Receiver lane-packing plan (kernel v4) ---------------------------
    # A [n_pk, size_l] tile occupies only size_l of the VPU's 128 lanes;
    # at the headline size_l=64 every per-receiver verdict op ran at half
    # width.  Pack grp receivers side by side in the lane dimension
    # (seg_l = grp * size_l lanes) and process them together: elementwise
    # verdict work runs at full lane occupancy and the per-segment
    # reductions become one small MXU matmul against the segment one-hot
    # E [grp, seg_l] (0/1 operands, f32 accumulate — exact).  Groups are
    # contiguous receiver slices; when grp does not divide n_lieu the last
    # group re-covers the tail (overlap recomputes identical values; the
    # member loop below skips already-processed receivers so the
    # non-idempotent vi update runs exactly once per receiver).
    grp = _lane_group(size_l, n_rv)
    seg_l = grp * size_l
    r0_list = list(range(0, n_rv - grp + 1, grp))
    if n_rv % grp:
        r0_list.append(n_rv - grp)
    e_np = np.zeros((grp, seg_l), np.float32)
    for j in range(grp):
        e_np[j, j * size_l : (j + 1) * size_l] = 1.0

    def kernel(round_ref, *refs):
        def scalar_read(ref):
            # In interpret mode under shard_map's replication checker,
            # ``ref[0]`` stages a dynamic_slice whose literal index lacks
            # the operand's vma; a full load + squeeze avoids the slice.
            # Mosaic (the real TPU path) keeps the canonical SMEM read.
            if interpret:
                return ref[:].reshape(())
            return ref[0]

        if local:
            (
                off_ref,
                vals_ref, lens_ref, count_ref, p_ref, v_ref, sent_ref,
                li_ref, vi_ref, honest_ref, act_ref, rv_ref, late_ref,
                e_ref, lip_ref, lioob_ref,
                ovals_ref, olens_ref, ocount_ref, op_ref, ov_ref,
                osent_ref, ovi_ref, oovf_ref,
                acc_scr, dup_scr, olen_scr, g_scr,
            ) = refs
            r_off = scalar_read(off_ref)  # block's first receiver (runtime)
        else:
            (
                vals_ref, lens_ref, count_ref, p_ref, v_ref, sent_ref,
                li_ref, vi_ref, honest_ref, act_ref, rv_ref, late_ref,
                e_ref, lip_ref, lioob_ref,
                ovals_ref, olens_ref, ocount_ref, op_ref, ov_ref,
                osent_ref, ovi_ref, oovf_ref,
                acc_scr, dup_scr, olen_scr, g_scr,
            ) = refs
            r_off = 0
        r_idx = scalar_read(round_ref)
        idx_col = jax.lax.broadcasted_iota(jnp.int32, (n_pk, 1), 0)
        sender_col = idx_col // slots

        vals = [vals_ref[r] for r in range(max_l)]  # each [n_pk, size_l]
        in_t = [vals[r] != SENTINEL for r in range(max_l)]
        lens = lens_ref[:]  # [n_pk, max_l]
        count = count_ref[:]  # [n_pk, 1]
        p_in = p_ref[:] != 0  # [n_pk, size_l]
        v_in = v_ref[:]  # [n_pk, 1]
        sent = sent_ref[:] != 0  # [n_pk, 1]
        biz = honest_ref[:] == 0  # [n_pk, 1]
        valid = [count > r for r in range(max_l)]  # each [n_pk, 1]
        len0 = lens[:, 0:1]  # [n_pk, 1]

        li_all = li_ref[:]  # [n_lieu, size_l] (rebuild's li_exp below)

        ovi_ref[:] = vi_ref[:]
        # No zero-init of the other outputs: the batched rebuild at the
        # bottom stores every row of every output exactly once.

        # ---- All-receiver flag algebra: one [n_pk, n_lieu] op each -------
        # The draws are packet-major, so every per-receiver corruption
        # flag is computed for all receivers in one tile op; the unrolled
        # receiver loop below consumes relayout-free lane slices.
        act_all = act_ref[:]  # [n_pk, n_rv]
        rv_all = rv_ref[:]
        late_all = late_ref[:]
        lane_recv = (
            jax.lax.broadcasted_iota(jnp.int32, (n_pk, n_rv), 1) + r_off
        )
        dropped_all = biz & ((act_all & DROP_BIT) != 0)
        v2_all = jnp.where(biz & ((act_all & FORGE_BIT) != 0), rv_all, v_in)
        clearp_all = biz & ((act_all & CLEAR_P_BIT) != 0)
        clearl_all = biz & ((act_all & CLEAR_L_BIT) != 0)
        # Forge-P (strategy="split" only — statically gated so every
        # other strategy's traced kernel, and the reference bit-identity
        # pin, are byte-for-byte unchanged).
        forgep_all = (
            biz & ((act_all & FORGE_P_BIT) != 0)
            if cfg.strategy == "split"
            else None
        )
        delivered_all = (
            ~dropped_all & (late_all == 0) & sent & (sender_col != lane_recv)
        )
        count_eff_all = jnp.where(clearl_all, 0, count)

        # ---- Loop A: the shared per-group acceptance flag algebra ------
        # (ops/verdict_algebra.py — one implementation for both Pallas
        # kernels; lane-packs grp receivers per tile, value-presence as
        # bit planes for w <= 64, per-row loops beyond.)
        va = VerdictAlgebra(
            n_p=n_pk, grp=grp, seg_l=seg_l, max_l=max_l,
            size_l=size_l, w=w, gdt=gdt,
            vals=vals, lens=lens, count=count, p_i32=p_ref[:],
            e_vals=e_ref[:], lip_vals=lip_ref[:],
            lioob_vals=lioob_ref[:], r_idx=r_idx,
        )
        done: set[int] = set()
        ok_parts = []
        next_col = 0
        for gi, r0 in enumerate(r0_list):
            sl = slice(r0, r0 + grp)
            ok_g, dup_g, own_len_g = va.group(
                gi, v2_all[:, sl], clearp_all[:, sl], clearl_all[:, sl],
                count_eff_all[:, sl], delivered_all[:, sl],
                None if forgep_all is None else forgep_all[:, sl],
            )
            # int32 before slicing/concatenating (Mosaic rejects i1
            # tpu.concatenate); tail-group overlap keeps only the not-
            # yet-covered columns (the recomputed flags are identical).
            ok_i = jnp.where(ok_g, 1, 0)
            ok_parts.append(ok_i[:, next_col - r0 :])
            next_col = r0 + grp
            for j in range(grp):
                recv = r0 + j
                if recv in done:  # tail-group overlap: already done
                    continue
                done.add(recv)
                dup_scr[:, recv : recv + 1] = dup_g[:, j : j + 1].astype(
                    jnp.int32
                )
                olen_scr[:, recv : recv + 1] = own_len_g[:, j : j + 1]
        ok_all = (
            jnp.concatenate(ok_parts, axis=1)
            if len(ok_parts) > 1 else ok_parts[0]
        )
        # Round 6 — parallel first-accept reduction (mirrors the tiled
        # kernel's "group" variant): one segmented first-index pass
        # dedups every receiver at once, replacing the per-receiver
        # accept chain through ovi_ref that the round-5 roofline named
        # as the dominant serial term.  Receivers' vi rows are disjoint,
        # so batching is observationally identical to the sequential
        # drain (tfg.py:294).
        acc_all_i, new_vi = accept_first_per_value_all(
            ok_all != 0, v2_all, ovi_ref[:], idx_col, n_pk, n_rv, w
        )
        ovi_ref[:] = new_vi
        acc_scr[:] = acc_all_i

        # ---- Batched slot allocation (tfg.py:298-299), all receivers -----
        # One triangular MXU matmul computes every receiver's exclusive
        # prefix count at once (the per-receiver version was n_s matmuls).
        acc_all = acc_scr[:] != 0  # [n_pk, n_rv]
        dup_all = dup_scr[:] != 0
        olen_all = olen_scr[:]
        rebroadcast_all = acc_all & (r_idx <= n_dis)
        slot_all = _cumsum_exclusive(rebroadcast_all.astype(jnp.int32), n_pk)
        write_all = rebroadcast_all & (slot_all < slots)
        oovf_ref[:] = (
            jnp.any(rebroadcast_all & ~write_all)
            .astype(jnp.int32)
            .reshape(1, 1)
        )
        new_count_all = jnp.where(
            dup_all, count_eff_all, jnp.minimum(count_eff_all + 1, max_l)
        )

        # Loop B: assemble the global one-hot gather matrix column block by
        # column block — G[pk, c] = 1 iff packet pk feeds output cell c
        # (injective: each cell has at most one source).
        iota_s = jax.lax.broadcasted_iota(jnp.int32, (n_pk, slots), 1)
        for recv in range(n_rv):
            g_r = write_all[:, recv : recv + 1] & (
                slot_all[:, recv : recv + 1] == iota_s
            )
            g_scr[:, recv * slots : (recv + 1) * slots] = g_r.astype(gdt)

        # ---- Batched rebuild: one full-width MXU matmul per field --------
        # out[c] = field[src(c), recv(c)].  Receiver-independent fields
        # (evidence rows, lens, P) gather directly with G^T; receiver-
        # dependent [n_pk, n_lieu] fields gather to [c, n_lieu] and a lane
        # select against recv(c) picks the right column.  This replaces
        # ~12 small [slots, n_pk] matmuls per receiver with ~16 full-width
        # ones total.  bf16 operands are exact when every gathered value
        # is an integer of magnitude <= 256 (vals < w, lengths <= size_l,
        # G is 0/1); larger configs fall back to f32 (see gdt).
        big_g = g_scr[:]
        row_c = jax.lax.broadcasted_iota(jnp.int32, (n_c, n_rv), 0)
        lane_rv_c = jax.lax.broadcasted_iota(jnp.int32, (n_c, n_rv), 1)
        recv_onehot = (lane_rv_c == row_c // slots).astype(jnp.float32)

        def gmat(x):  # [n_pk(src), X] -> f32 [n_pk(c), X]
            return jax.lax.dot_general(
                big_g,
                x.astype(gdt),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_exact_prec(gdt),
            )

        def gsel(field_all):  # [n_pk(src), n_lieu] -> int32 [n_pk(c), 1]
            gd = gmat(field_all)  # [c, n_lieu] f32
            return jnp.sum(gd * recv_onehot, axis=1, keepdims=True).astype(
                jnp.int32
            )

        has = gsel(jnp.ones((n_pk, n_rv), jnp.int32)) != 0  # [c, 1]
        v2_g = gsel(v2_all)
        cnt_g = gsel(count_eff_all)
        dup_g = gsel(dup_all.astype(jnp.int32))
        clr_g = gsel(clearl_all.astype(jnp.int32))
        clrp_g = gsel(clearp_all.astype(jnp.int32))
        olen_g = gsel(olen_all)
        ncnt_g = gsel(new_count_all)

        pin_g = gmat(p_in).astype(jnp.int32)  # [c, size_l]
        lens_g = gmat(lens).astype(jnp.int32)  # [c, max_l]
        rows_g = [gmat(vals[r]).astype(jnp.int32) for r in range(max_l)]
        # li_exp[c] = li[recv(c)] — the receiver's own list, re-expanded
        # instead of gathered (own rows never need the source packet).
        li_exp = jax.lax.dot_general(
            recv_onehot.astype(gdt),
            li_all.astype(gdt),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_exact_prec(gdt),
        ).astype(jnp.int32)
        p2_g = (pin_g != 0) & (clrp_g == 0)
        if forgep_all is not None:
            # Forged-full P survives the rebuild: the rebroadcast packet
            # carries the fabricated all-True mask (forgery wins).
            p2_g = (gsel(forgep_all.astype(jnp.int32)) != 0) | p2_g
        own_g = jnp.where(p2_g, li_exp, SENTINEL)

        iota_l = jax.lax.broadcasted_iota(jnp.int32, (n_c, max_l), 1)
        keep_row = (clr_g == 0) & (iota_l < cnt_g)
        new_row = (dup_g == 0) & (iota_l == cnt_g)
        olens_ref[:] = jnp.where(
            has,
            jnp.where(new_row, olen_g, jnp.where(keep_row, lens_g, 0)),
            0,
        )
        for r in range(max_l):
            keep = (clr_g == 0) & (r < cnt_g)  # [c, 1]
            is_new = (dup_g == 0) & (r == cnt_g)
            row = jnp.where(
                is_new, own_g, jnp.where(keep, rows_g[r], SENTINEL)
            )
            ovals_ref[r] = jnp.where(has, row, SENTINEL)
        ocount_ref[:] = jnp.where(has, ncnt_g, 0)
        op_ref[:] = jnp.where(has, p2_g.astype(jnp.int32), 0)
        ov_ref[:] = jnp.where(has, v2_g, 0)
        osent_ref[:] = has.astype(jnp.int32)

    # Inside shard_map with its replication checker on, pallas outputs
    # must declare which mesh axes they vary over (out_vma; the
    # party-sharded spmd engine passes its mesh axes).
    def oshp(*dims):
        return vma_struct(out_vma, dims)

    out_shapes = (
        oshp(max_l, n_c, size_l),  # vals
        oshp(n_c, max_l),  # lens
        oshp(n_c, 1),  # count
        oshp(n_c, size_l),  # p
        oshp(n_c, 1),  # v
        oshp(n_c, 1),  # sent
        oshp(n_rv, w),  # vi
        oshp(1, 1),  # overflow
    )

    # The mailbox + vi inputs are donated into the corresponding outputs:
    # the round step is a lax.scan body, and without aliasing XLA inserts
    # a full mailbox copy per round to rebuild the carry (~7% of the round
    # loop at the headline config).  Safe because the kernel loads every
    # aliased ref into values before its first output store (vals/lens/
    # count/p/v/sent are read exactly once at the top; vi is copied into
    # ovi and only ovi is read after).  Machine-checked: KI-5
    # `qba-tpu lint --effects` chases every scan carry to an aliased
    # kernel output (scan-carry / alias-consistency checks).
    n_vmem_in = 15
    n_smem_in = 2 if local else 1  # round_idx [+ recv offset]
    # The local variant cannot alias the global mailbox inputs into its
    # block-local outputs (shapes differ); vi still aliases.
    if local:
        aliases = {n_smem_in + 7: 6}  # vi -> ovi
    else:
        aliases = {1: 0, 2: 1, 3: 2, 4: 3, 5: 4, 6: 5, 8: 6}
    call = pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * n_smem_in
        + [pl.BlockSpec(memory_space=pltpu.VMEM)] * n_vmem_in,
        out_specs=tuple(
            pl.BlockSpec(memory_space=pltpu.VMEM) for _ in out_shapes
        ),
        input_output_aliases=aliases,
        scratch_shapes=[
            pltpu.VMEM((n_pk, n_rv), jnp.int32),  # acc_scr
            pltpu.VMEM((n_pk, n_rv), jnp.int32),  # dup_scr
            pltpu.VMEM((n_pk, n_rv), jnp.int32),  # olen_scr
            pltpu.VMEM((n_pk, n_c), gdt),  # g_scr
        ],
        compiler_params=CompilerParams(
            # Raise Mosaic's ~16 MB default scoped-vmem cap toward the
            # physical VMEM: large vmap batches multi-buffer operands
            # (see round_kernel_tiled.py), and configs like the
            # reference's sizeL=1000 at the lossless slot bound compile
            # comfortably under the real limit.
            vmem_limit_bytes=100 * 2**20,
        ),
        interpret=interpret,
    )

    def _pv(x):
        return promote_vma(out_vma, x)

    def _tail(li):
        # Lane-packed receiver tables (cheap XLA reshapes outside the
        # kernel; per trial under vmap like li itself).
        li_pack = jnp.stack(
            [li[r0 : r0 + grp].reshape(-1) for r0 in r0_list]
        )  # [n_groups, seg_l]
        li_oob_pack = ((li_pack > w) | (li_pack < 0)).astype(jnp.int32)
        return jnp.asarray(e_np), li_pack, li_oob_pack

    if local:

        def step(round_idx, recv_off, vals, lens, count, p, v, sent, li,
                 vi, honest_pk, attack, rand_v, late):
            # Mailbox operands are GLOBAL; li/vi/draw columns are the
            # local receiver block's; recv_off is its first receiver.
            args = (
                jnp.asarray([round_idx], jnp.int32),
                jnp.asarray(recv_off, jnp.int32).reshape(1),
                vals, lens, count, p, v, sent, li, vi, honest_pk,
                attack, rand_v, late, *_tail(li),
            )
            return call(*map(_pv, args))

    else:

        def step(round_idx, vals, lens, count, p, v, sent, li, vi,
                 honest_pk, attack, rand_v, late):
            # Draws arrive packet-major [n_pk, n_lieu] straight from
            # sample_attacks_round — no transpose anywhere on the path.
            return call(
                jnp.asarray([round_idx], jnp.int32),
                vals, lens, count, p, v, sent, li, vi, honest_pk,
                attack, rand_v, late, *_tail(li),
            )

    return step


# ---------------------------------------------------------------------------
# Probe disk cache — shared by every kernel probe in ops/ (the tiled
# engine imports these).  Probe verdicts persist per (kernel, config
# shape, jax version, device kind): a failed remote-tunnel compile costs
# ~2 minutes and Mosaic's scoped-vmem accounting cannot be predicted
# from outside, so the first process on a machine pays for the search
# once and every later process reads the answer.  TPU-only (the CPU test
# suite exercises the probe failure paths deterministically).

import json as _json
import os as _os


def _probe_cache_path() -> str:
    return _os.environ.get(
        "QBA_PROBE_CACHE",
        _os.path.join(
            _os.path.expanduser("~"), ".cache", "qba_tpu", "probes.json"
        ),
    )


_PROBE_VERSION = 8  # bump when kernel structure/compiler params change
# v6: tiled kernels take the meta-packed pool (count/v/sent/cell in one
# [cap, 4] tensor) + donation; block ordering recalibrated on honest
# timings (docs/PERF.md round 4 erratum).
# v7: Precision.HIGHEST on exactness-critical dots (KI-3 — changes the
# kernels' scoped-vmem footprint, so v6 block plans are stale) + the
# all-receiver verdict variant.
# v8: parallel first-accept reduction in both kernels and the
# group/group-serial accept-path split (v7 "group" block plans
# measured a different kernel body; the new [blk, n_rv, w] accept
# intermediates change the scoped-vmem footprint).


def _probe_disk_key(kernel: str, cfg: QBAConfig, extra: str = "") -> str:
    dev = jax.devices()[0].device_kind if jax.devices() else "?"
    return (
        f"{kernel}v{_PROBE_VERSION}:{cfg.n_lieutenants}:{cfg.slots}:"
        f"{cfg.max_l}:{cfg.size_l}:{cfg.w}:{extra}:{jax.__version__}:{dev}"
    )


def _probe_disk_get(key: str):
    if jax.default_backend() != "tpu":  # disk cache is for real-TPU probes
        return None
    try:
        with open(_probe_cache_path()) as f:
            return _json.load(f).get(key)
    except Exception:
        return None


try:  # POSIX file locking for the probe cache; absent -> lock-free write
    import fcntl as _fcntl
except ImportError:  # pragma: no cover - non-POSIX host
    _fcntl = None


def _probe_disk_put(key: str, value) -> None:
    if jax.default_backend() != "tpu":
        return
    path = _probe_cache_path()
    try:
        _os.makedirs(_os.path.dirname(path), exist_ok=True)
        # Serialize the read-modify-write across processes: without the
        # lock two concurrent probes each read, add their own key, and
        # the second replace drops the first writer's verdict (a lost
        # verdict re-probes later — a failed remote compile costs ~2
        # minutes).  Lock acquisition is itself best-effort (flock can
        # fail on e.g. NFS): the write must still happen unlocked then,
        # and the per-pid tmp name keeps it from interleaving.
        with open(path + ".lock", "w") as lock_f:
            if _fcntl is not None:
                try:
                    _fcntl.flock(lock_f, _fcntl.LOCK_EX)
                except OSError:  # pragma: no cover - odd filesystems
                    pass
            try:
                with open(path) as f:
                    data = _json.load(f)
            except Exception:
                data = {}
            data[key] = value
            tmp = f"{path}.{_os.getpid()}.tmp"
            with open(tmp, "w") as f:
                _json.dump(data, f)
            _os.replace(tmp, path)
    except Exception:
        pass  # cache is best-effort


# Transient-error classification for compile probes: a remote-tunnel
# helper crash (HTTP 500 / dead subprocess / deadline) is NOT a verdict
# about the kernel shape — caching it as "does not compile" silently
# pins a config to a slower engine forever (observed: a flaky helper
# crash cached tiled-verdict=-1 for the north-star shape, dropping auto
# to the XLA engine which then OOM'd at the new single-batch size).
# Transient failures retry once and are never persisted to disk.
_TRANSIENT_ERR_MARKERS = (
    "remote_compile",
    "HTTP 5",
    "subprocess exit",
    "DEADLINE",
    "UNAVAILABLE",
    "Connection",
)


def probe_error_transient(e: Exception) -> bool:
    s = repr(e)
    # A remote-tunnel wrapper (HTTP 500 / helper exit 1) around a REAL
    # compiler verdict is deterministic: the Mosaic error text rides
    # inside the message (round 5 — previously such failures re-probed
    # every process).
    if "Mosaic failed to compile" in s:
        return False
    return any(m in s for m in _TRANSIENT_ERR_MARKERS)


# Pre-filter bound for the compile probe.  The real gate is a one-time
# compile attempt (kernel_compiles below): Mosaic's scoped-vmem use is
# hard to model — observed actual/estimate ratios range from ~0.8x
# (nParties=11, sizeL=1000, slots=16: est 25.8 MB, OOM at ~20 MB) to
# ~3.7x (nParties=33, sizeL=64, slots=8: est 6.8 MB, OOM at 25.45 MB) —
# so the estimate only screens out hopeless configs before paying for a
# doomed compile.
_VMEM_PREFILTER_BYTES = 128 * 2**20


def fits_kernel(cfg: QBAConfig, n_recv: int | None = None) -> bool:
    """Loose VMEM pre-filter for the round kernel.

    True means "plausibly fits — worth a compile probe", not "fits":
    the authoritative check is :func:`kernel_compiles`, which attempts
    the compile once per config shape and caches the outcome.  False
    configs (e.g. the reference's sizeL=1000 at the default lossless
    slot bound) skip the probe and go straight to the XLA engine.
    ``n_recv`` estimates the party-sharded local-block variant, whose
    working set shrinks with the block (smaller grp tiles, an
    ``[n_pk, n_recv*slots]`` gather scratch).
    """
    n_rv = n_recv if n_recv is not None else cfg.n_lieutenants
    n_pk = cfg.n_lieutenants * cfg.slots
    tile = 4 * n_pk * cfg.size_l
    # Tile count: mailbox in + out refs (2*max_l), loaded row values and
    # their in-tuple masks (2*max_l), and ~a dozen [n_pk, size_l]
    # intermediates (p_in/p2/own/op plus fusion temporaries).
    est = tile * (4 * cfg.max_l + 12)
    # Lane-packed receiver tables (kernel v4): grp copies of the packet
    # tables plus ~6 [n_pk, grp*size_l] group intermediates.
    grp = _lane_group(cfg.size_l, n_rv)
    if grp > 1:
        est += tile * grp * (cfg.max_l + 6)
    # Plus the working set of the batched rebuild: the triangular
    # prefix-sum operand (f32/bf16, [n_pk, n_pk]) and the one-hot gather
    # scratch ([n_pk, n_recv*slots]).
    est += n_pk * n_pk * 4 + n_pk * n_rv * cfg.slots * 4
    # Mosaic stack scaling with the unrolled row loops (worst observed
    # ratio; see the pre-filter note above).
    est = int(est * (1.0 + cfg.max_l / 4.0))
    return est <= _VMEM_PREFILTER_BYTES


# Probe outcomes per kernel shape — a compile attempt is seconds on a
# remote tunnel, so pay it once per (process, config shape).
_PROBE_CACHE: dict[tuple, bool] = {}


def kernel_compiles(cfg: QBAConfig, n_recv: int | None = None) -> bool:
    """Whether the round kernel actually compiles for this config.

    Attempts a real (abstract-shape, data-free) compile of one round
    step and caches the verdict.  This is the authoritative ``auto``
    engine gate: Mosaic's scoped-vmem accounting cannot be predicted
    reliably from the outside (see the pre-filter note), and a failed
    probe here is exactly the failure the fallback must avoid at
    run-trial compile time.  ``n_recv`` probes the party-sharded
    local-block variant instead (see :func:`build_round_step`).
    """
    key = (cfg.n_lieutenants, cfg.slots, cfg.max_l, cfg.size_l, cfg.w,
           n_recv)
    hit = _PROBE_CACHE.get(key)
    if hit is not None:
        return hit
    dkey = _probe_disk_key("fused", cfg, extra=f"recv{n_recv}")
    disk = _probe_disk_get(dkey)
    if disk is not None:
        _PROBE_CACHE[key] = bool(disk)
        return bool(disk)
    if not fits_kernel(cfg, n_recv):
        # As loud as a failed probe: the estimate is unreliable (see the
        # pre-filter note), so a misclassified config silently taking a
        # slower engine would be invisible to the operator otherwise.
        fallback = (
            "the spmd engine will fall back to vectorized XLA"
            if n_recv is not None
            else "the auto engine will try the packet-tiled kernel, then XLA"
        )
        warn_and_record(
            "fused round kernel VMEM pre-filter rejected "
            f"(n_parties={cfg.n_parties}, size_l={cfg.size_l}, "
            f"slots={cfg.slots}) without a compile probe; " + fallback,
            QBAProbeWarning,
            site="ops.round_kernel.kernel_compiles",
            stacklevel=2,
            reason="vmem_prefilter",
            n_parties=cfg.n_parties,
            size_l=cfg.size_l,
            slots=cfg.slots,
            n_recv=n_recv,
        )
        _PROBE_CACHE[key] = False
        return False
    n_pk = cfg.n_lieutenants * cfg.slots
    n_s, max_l, s, w = cfg.n_lieutenants, cfg.max_l, cfg.size_l, cfg.w
    n_rv = n_recv if n_recv is not None else n_s
    i32 = jnp.int32

    def shp(*dims):
        return jax.ShapeDtypeStruct(dims, i32)

    def compile_probe():
        step = build_round_step(cfg, n_recv=n_recv)
        n_in = 12  # operands after the round-idx scalar
        off = ()
        in_axes = (None,) + (0,) * n_in
        if n_recv is not None:
            off = (jax.ShapeDtypeStruct((), i32),)
            in_axes = (None, None) + (0,) * n_in

        def bshp(*dims):
            # Probe under a small vmap: batching multi-buffers operands
            # (see round_kernel_tiled.py's probe note).
            return jax.ShapeDtypeStruct((2,) + dims, i32)

        jax.jit(jax.vmap(step, in_axes=in_axes)).lower(
            jax.ShapeDtypeStruct((), i32),  # round_idx
            *off,  # recv block offset (local variant)
            bshp(max_l, n_pk, s), bshp(n_pk, max_l), bshp(n_pk, 1),
            bshp(n_pk, s), bshp(n_pk, 1), bshp(n_pk, 1),  # vals..sent
            bshp(n_rv, s), bshp(n_rv, w), bshp(n_pk, 1),  # li, vi, honest
            bshp(n_pk, n_rv), bshp(n_pk, n_rv), bshp(n_pk, n_rv),  # draws
        ).compile()

    ok, transient = False, False
    try:
        compile_probe()
        ok = True
    except Exception as e:  # compile failures only reach here (no execution)
        if probe_error_transient(e):
            transient = True
            try:  # one retry: helper crashes are not shape verdicts
                compile_probe()
                ok, transient = True, False
            except Exception as e2:
                e = e2
        if not ok:
            # Loud on purpose: a genuine VMEM overflow and a transient
            # tunnel/infrastructure error both land here, and the
            # fallback costs up to ~26x (docs/PERF.md) — the operator
            # should see why.
            warn_and_record(
                "round kernel compile probe failed for "
                f"(n_parties={cfg.n_parties}, size_l={cfg.size_l}, "
                f"slots={cfg.slots}); falling back to the XLA round "
                f"engine for this config: {e!r:.500}",
                QBAProbeWarning,
                site="ops.round_kernel.kernel_compiles",
                stacklevel=2,
                reason="compile_probe_failed",
                n_parties=cfg.n_parties,
                size_l=cfg.size_l,
                slots=cfg.slots,
                n_recv=n_recv,
                error=repr(e)[:500],
            )
    if ok or not transient:
        # Never cache transient failures — not even in-process: a flaky
        # tunnel minute must not pin this config to the slow engine for
        # the process lifetime.  The cost is a re-probe on the next
        # call, which is exactly the desired retry.
        _PROBE_CACHE[key] = ok
        _probe_disk_put(dkey, int(ok))
    return ok
