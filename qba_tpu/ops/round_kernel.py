"""Pallas TPU kernel for the voting-round hot loop.

One kernel invocation executes a FULL protocol round for one trial — all
receivers' inbox drains (``tfg.py:337-348`` + ``lieu_receive``,
``tfg.py:289-300``) — with the round's entire mailbox resident in VMEM
(~205 KB at the headline config).

Why a kernel: the XLA formulation of the per-(receiver, packet) verdict is
a batch of tiny ``[max_l, size_l]`` reductions whose tiles occupy ~30% of
the VPU and whose loop fusions ran at a few Gop/s (three ~70 ms fusions
per batch at nParties=11, sizeL=64, 1000 trials).  Here the layout is
chosen for the hardware: packets fill the sublane dimension (``n_pk`` of
them) and list positions fill lanes, so every verdict reduction is a
dense ``[n_pk, size_l]`` tile op and the whole round is one fused program.

Semantics are bit-identical to the XLA path
(:func:`qba_tpu.rounds.engine.receiver_round`) — enforced by the
equivalence tests in tests/test_round_kernel.py and by the three-way
backend differentials.

Layout conventions (per trial; ``vmap`` over trials prepends the grid):

* ``vals``  — int32 ``[max_l, n_pk, size_l]`` (row-major outer so each
  evidence row is one clean 2-D tile)
* ``lens``  — int32 ``[n_pk, max_l]``
* per-packet scalars (``count``, ``v``, ``sent``, honesty, draws) —
  int32 ``[n_pk, 1]`` columns or ``[n_lieu, n_pk]`` row-sliced per
  receiver; all flags stay 2-D end to end
* bools travel as int32 0/1 (predicate relayouts are avoided entirely)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from qba_tpu.config import QBAConfig
from qba_tpu.core.types import SENTINEL


def _cumsum_exclusive(col: jnp.ndarray, n: int) -> jnp.ndarray:
    """Exclusive prefix sum along the sublane axis of an ``[n, 1]`` int32
    column — one strictly-lower-triangular MXU matmul (a log2(n) chain of
    shifted adds costs ~2 log2(n) vector relayouts per call; the matmul is
    one op and exact for the small integer counts involved)."""
    iota_r = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
    iota_c = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
    tri = (iota_c < iota_r).astype(jnp.float32)  # strictly lower triangular
    return jax.lax.dot_general(
        tri,
        col.astype(jnp.float32),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)


def build_round_step(cfg: QBAConfig, *, interpret: bool = False):
    """Compile one synchronous voting round for one trial.

    Returns ``step(round_idx, vals, lens, count, p, v, sent, li, vi,
    honest_pk, action, coin, rand_v, late) -> (ovals, olens, ocount, op,
    ov, osent, ovi, overflow)`` — jit/vmap-safe (vmap over trials becomes
    the Pallas grid).
    """
    n_s, slots, max_l = cfg.n_lieutenants, cfg.slots, cfg.max_l
    size_l, w = cfg.size_l, cfg.w
    n_pk = n_s * slots
    n_dis = cfg.n_dishonest

    def kernel(
        round_ref,  # SMEM [1]
        vals_ref,  # [max_l, n_pk, size_l]
        lens_ref,  # [n_pk, max_l]
        count_ref,  # [n_pk, 1]
        p_ref,  # [n_pk, size_l]
        v_ref,  # [n_pk, 1]
        sent_ref,  # [n_pk, 1]
        li_ref,  # [n_lieu, size_l]
        vi_ref,  # [n_lieu, w]
        honest_ref,  # [n_pk, 1]
        act_ref,  # [n_lieu, n_pk]
        coin_ref,
        rv_ref,
        late_ref,
        ovals_ref,
        olens_ref,
        ocount_ref,
        op_ref,
        ov_ref,
        osent_ref,
        ovi_ref,
        oovf_ref,  # [1, 1]
    ):
        r_idx = round_ref[0]
        idx_col = jax.lax.broadcasted_iota(jnp.int32, (n_pk, 1), 0)
        sender_col = idx_col // slots

        vals = [vals_ref[r] for r in range(max_l)]  # each [n_pk, size_l]
        in_t = [vals[r] != SENTINEL for r in range(max_l)]
        lens = lens_ref[:]  # [n_pk, max_l]
        count = count_ref[:]  # [n_pk, 1]
        p_in = p_ref[:] != 0  # [n_pk, size_l]
        v_in = v_ref[:]  # [n_pk, 1]
        sent = sent_ref[:] != 0  # [n_pk, 1]
        biz = honest_ref[:] == 0  # [n_pk, 1]
        valid = [count > r for r in range(max_l)]  # each [n_pk, 1]
        len0 = lens[:, 0:1]  # [n_pk, 1]

        # ---- Receiver-independent raw-mailbox facts ----------------------
        false_col = jnp.zeros((n_pk, 1), jnp.bool_)
        oob = false_col
        lens_bad = false_col
        cells_coll = false_col
        for r in range(max_l):
            row_bad = jnp.any(
                in_t[r] & ((vals[r] > w) | (vals[r] < 0)), axis=1, keepdims=True
            )
            oob |= valid[r] & row_bad
            lens_bad |= valid[r] & (lens[:, r : r + 1] != len0)
            for s in range(r + 1, max_l):
                hit = jnp.any(
                    in_t[r] & in_t[s] & (vals[r] == vals[s]),
                    axis=1,
                    keepdims=True,
                )
                cells_coll |= valid[s] & hit

        ovf = jnp.zeros((1, 1), jnp.int32)
        ovi_ref[:] = vi_ref[:]
        olens_ref[:] = jnp.zeros((n_pk, max_l), jnp.int32)
        ocount_ref[:] = jnp.zeros((n_pk, 1), jnp.int32)
        op_ref[:] = jnp.zeros((n_pk, size_l), jnp.int32)
        ov_ref[:] = jnp.zeros((n_pk, 1), jnp.int32)
        osent_ref[:] = jnp.zeros((n_pk, 1), jnp.int32)
        for r in range(max_l):
            ovals_ref[r] = jnp.full((n_pk, size_l), SENTINEL, jnp.int32)

        for recv in range(n_s):  # static unroll over receivers
            act = act_ref[recv : recv + 1, :].reshape(n_pk, 1)
            coin = coin_ref[recv : recv + 1, :].reshape(n_pk, 1)
            rv = rv_ref[recv : recv + 1, :].reshape(n_pk, 1)
            late = late_ref[recv : recv + 1, :].reshape(n_pk, 1)
            li_row = li_ref[recv : recv + 1, :]  # [1, size_l]

            dropped = biz & (act == 0) & (coin == 0)
            v2 = jnp.where(biz & (act == 1), rv, v_in)  # [n_pk, 1]
            clear_p = biz & (act == 2)
            clear_l = biz & (act == 3)
            delivered = (
                ~dropped & (late == 0) & sent & (sender_col != recv)
            )  # [n_pk, 1]

            p2 = p_in & ~clear_p  # [n_pk, size_l]
            own = jnp.where(
                p2, jnp.broadcast_to(li_row, (n_pk, size_l)), SENTINEL
            )
            own_len = jnp.sum(p2.astype(jnp.int32), axis=1, keepdims=True)

            dup = false_col
            contains_v2 = false_col
            own_coll = false_col
            for r in range(max_l):
                same = ~jnp.any(vals[r] != own, axis=1, keepdims=True)
                dup |= valid[r] & same
                contains_v2 |= valid[r] & jnp.any(
                    in_t[r] & (vals[r] == v2), axis=1, keepdims=True
                )
                own_coll |= valid[r] & jnp.any(
                    p2 & in_t[r] & (vals[r] == own), axis=1, keepdims=True
                )
            dup &= ~clear_l

            count_eff = jnp.where(clear_l, 0, count)
            new_count = jnp.where(
                dup, count_eff, jnp.minimum(count_eff + 1, max_l)
            )

            cond1 = (clear_l | ~lens_bad) & (
                (count_eff == 0) | (own_len == len0)
            )
            bad_own = jnp.any(
                p2 & ((own == v2) | (own > w) | (own < 0)),
                axis=1,
                keepdims=True,
            )
            cond2 = ~((~clear_l & (oob | contains_v2)) | bad_own)
            cond3 = (clear_l | ~cells_coll) & (dup | ~(~clear_l & own_coll))
            ok = delivered & cond1 & cond2 & cond3 & (new_count == r_idx + 1)

            # ---- dedup: first candidate per order value (tfg.py:294) -----
            vi_row = ovi_ref[recv : recv + 1, :]  # [1, w]
            iota_w = jax.lax.broadcasted_iota(jnp.int32, (n_pk, w), 1)
            onehot = v2 == iota_w  # [n_pk, w]
            in_vi = jnp.any(
                onehot & (vi_row != 0), axis=1, keepdims=True
            )  # [n_pk, 1]
            cand = ok & ~in_vi
            masked_idx = jnp.where(onehot & cand, idx_col, n_pk)
            first = jnp.min(masked_idx, axis=0, keepdims=True)  # [1, w]
            first_b = jnp.min(
                jnp.where(onehot, jnp.broadcast_to(first, (n_pk, w)), n_pk),
                axis=1,
                keepdims=True,
            )  # [n_pk, 1]
            acc = cand & (first_b == idx_col)

            new_vi = (vi_row != 0) | jnp.any(acc & onehot, axis=0, keepdims=True)
            ovi_ref[recv : recv + 1, :] = new_vi.astype(jnp.int32)

            # ---- slot allocation + rebroadcast (tfg.py:298-299) ----------
            rebroadcast = acc & (r_idx <= n_dis)
            slot_col = _cumsum_exclusive(rebroadcast.astype(jnp.int32), n_pk)
            write = rebroadcast & (slot_col < slots)
            ovf += jnp.any(rebroadcast & ~write).astype(jnp.int32).reshape(1, 1)

            # ---- rebuild written packets into this receiver's row --------
            # Slot assignment is injective, so the slot <- packet gather is
            # a one-hot matrix; every rebuild field is an MXU matmul
            # G[slots, n_pk] @ data[n_pk, X] (exact: all values < 2^24) and
            # every store is static — no dynamic slicing anywhere.  (An
            # XLA-side rebuild via dynamic gathers and a fused single wide
            # matmul were both measured slower than these per-field
            # gathers.)
            iota_s = jax.lax.broadcasted_iota(jnp.int32, (n_pk, slots), 1)
            g = (write & (slot_col == iota_s)).astype(jnp.float32)

            def gat(x):  # [n_pk, X] -> one-hot gather [slots, X]
                return jax.lax.dot_general(
                    g,
                    x.astype(jnp.float32),
                    (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ).astype(jnp.int32)

            has = gat(jnp.ones((n_pk, 1), jnp.int32)) > 0  # [slots, 1]
            p2_g = gat(p2)  # [slots, size_l]
            own_g = gat(own)
            rows_g = [gat(vals[r]) for r in range(max_l)]
            v2_g = gat(v2)  # [slots, 1]
            cnt_g = gat(count_eff)
            dup_g = gat(dup)
            clr_g = gat(clear_l)
            olen_g = gat(own_len)
            ncnt_g = gat(new_count)
            lens_g = gat(lens)  # [slots, max_l]

            base = recv * slots
            iota_l = jax.lax.broadcasted_iota(jnp.int32, (slots, max_l), 1)
            keep_row = (clr_g == 0) & (iota_l < cnt_g)
            new_row = (dup_g == 0) & (iota_l == cnt_g)
            olens_ref[base : base + slots, :] = jnp.where(
                has,
                jnp.where(new_row, olen_g, jnp.where(keep_row, lens_g, 0)),
                0,
            )
            for r in range(max_l):
                keep = (clr_g == 0) & (r < cnt_g)  # [slots, 1]
                is_new = (dup_g == 0) & (r == cnt_g)
                row = jnp.where(
                    is_new, own_g, jnp.where(keep, rows_g[r], SENTINEL)
                )
                ovals_ref[r, base : base + slots, :] = jnp.where(
                    has, row, SENTINEL
                )
            ocount_ref[base : base + slots, :] = jnp.where(has, ncnt_g, 0)
            op_ref[base : base + slots, :] = jnp.where(has, p2_g, 0)
            ov_ref[base : base + slots, :] = jnp.where(has, v2_g, 0)
            osent_ref[base : base + slots, :] = has.astype(jnp.int32)

        oovf_ref[:] = ovf

    out_shapes = (
        jax.ShapeDtypeStruct((max_l, n_pk, size_l), jnp.int32),  # vals
        jax.ShapeDtypeStruct((n_pk, max_l), jnp.int32),  # lens
        jax.ShapeDtypeStruct((n_pk, 1), jnp.int32),  # count
        jax.ShapeDtypeStruct((n_pk, size_l), jnp.int32),  # p
        jax.ShapeDtypeStruct((n_pk, 1), jnp.int32),  # v
        jax.ShapeDtypeStruct((n_pk, 1), jnp.int32),  # sent
        jax.ShapeDtypeStruct((n_s, w), jnp.int32),  # vi
        jax.ShapeDtypeStruct((1, 1), jnp.int32),  # overflow
    )

    call = pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [pl.BlockSpec(memory_space=pltpu.VMEM)] * 13,
        out_specs=tuple(
            pl.BlockSpec(memory_space=pltpu.VMEM) for _ in out_shapes
        ),
        interpret=interpret,
    )

    def step(round_idx, vals, lens, count, p, v, sent, li, vi, honest_pk,
             action, coin, rand_v, late):
        return call(
            jnp.asarray([round_idx], jnp.int32),
            vals, lens, count, p, v, sent, li, vi, honest_pk,
            action, coin, rand_v, late,
        )

    return step


# Scoped VMEM available to a kernel instance (v5e exposes 16 MB; leave
# headroom for Mosaic's own scratch).
_VMEM_BUDGET_BYTES = 10 * 2**20


def fits_kernel(cfg: QBAConfig) -> bool:
    """Whether the round kernel's per-trial working set fits in VMEM.

    The kernel holds the mailbox (in + out) plus ~a dozen
    ``[n_pk, size_l]``-sized intermediates per receiver iteration.  At
    the reference's sizeL=1000 with 5 traitors that is ~20 MB — over the
    16 MB scoped-vmem limit (observed compile failure) — so ``auto``
    engine selection falls back to the XLA path for such configs.
    """
    n_pk = cfg.n_lieutenants * cfg.slots
    tile = 4 * n_pk * cfg.size_l
    # Tile count: mailbox in + out refs (2*max_l), loaded row values and
    # their in-tuple masks (2*max_l), and ~a dozen [n_pk, size_l]
    # intermediates (p_in/p2/own/op plus fusion temporaries).
    est = tile * (4 * cfg.max_l + 12)
    return est <= _VMEM_BUDGET_BYTES
