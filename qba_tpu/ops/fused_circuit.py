"""Fused whole-circuit statevector execution — one Pallas kernel per circuit.

The dense validation engine (:mod:`qba_tpu.qsim.statevector`) applies one
gate at a time; under XLA each gate is a statevector-sized HBM round-trip.
This kernel executes the *entire* circuit in a single ``pallas_call`` with
the state resident in VMEM — the TPU-native answer to the reference's
serial per-gate native-engine calls (``tfg.py:76-80``, SURVEY §3.2).

Design (see ``/opt/skills/guides/pallas_guide.md``):

* **Layout** — the flat statevector (qubit 0 = the most significant index
  bit, matching :mod:`qba_tpu.qsim.statevector`) is viewed as
  ``[rows, lanes]`` with ``lanes = 2**min(n, 7)``: the last ``min(n, 7)``
  qubits live in the 128-wide lane dimension, the rest in the sublane/row
  dimension.
* **Lane-qubit gates** (including lane-qubit controls) are ``L x L``
  matmuls on the MXU: the controlled gate restricted to the lane subspace
  is precomputed as a dense matrix, so ``state @ M.T`` applies it to every
  row at once.
* **Row-qubit gates** are sublane butterflies on the VPU: the partner
  amplitude ``state[r ^ 2**rbs]`` is two static rolls selected by the
  target bit; controls become iota bit-masks.  H/X/XPOW keep their
  add-only fast paths; every other 2x2 gate uses the generic coefficient
  form ``new = c_s * state + c_p * partner`` with per-target-bit matrix
  entries.
* **Real fast path** — when every gate in the circuit is real-valued
  (H, X/CNOT, Z/CZ, RY, parameterized X**b — all the protocol circuits
  use, ``tfg.py:17-39``) and the initial state is |0..0>, the state
  stays ``float32``: half the memory and FLOPs of the complex engine.
  Circuits with complex gates (Y, S, T, RX, RZ, P) run the same kernel
  on a dual (real, imag) float32 state pair — complex64 results without
  complex arithmetic inside the kernel.
* **Data-dependent encodings** — the reference rebuilds the Q-correlated
  circuit per list position with fresh ``rands`` (``tfg.py:30-37``); here
  the permutation bits arrive as an int32 param vector in SMEM, so ONE
  compiled kernel serves every position and trial under ``vmap``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_INV_SQRT2 = float(1.0 / np.sqrt(2.0))


@dataclasses.dataclass(frozen=True)
class _LaneOp:
    """Gate whose target sits in the lane dimension -> MXU matmul."""

    mat_idx: int  # index into the stacked [K, L, L] matrices
    param: int | None  # param index for X**b, None for fixed gates
    row_ctrl_shifts: tuple[int, ...]  # row-qubit controls (iota bit tests)
    has_imag: bool  # the lane matrix has a nonzero imaginary part


@dataclasses.dataclass(frozen=True)
class _RowOp:
    """Gate whose target sits in the row dimension -> sublane butterfly."""

    kind: str  # "H" | "X" | "XPOW" | "GEN"
    rbs: int  # target bit shift within the row index
    param: int | None
    row_ctrl_shifts: tuple[int, ...]
    lane_ctrl_shifts: tuple[int, ...]
    # 2x2 matrix entries for the generic coefficient form (kind "GEN"),
    # as (real, imag) python floats baked into the kernel.
    g2: tuple[tuple[complex, ...], ...] | None = None


def _lane_matrix(
    gate2: np.ndarray, t_shift: int, ctrl_shifts: tuple[int, ...], lanes: int
) -> np.ndarray:
    """Dense ``[L, L]`` matrix of ``gate2`` on lane-bit ``t_shift``,
    controlled on lane bits ``ctrl_shifts`` (identity elsewhere)."""
    mat = np.zeros((lanes, lanes), dtype=np.complex64)
    for col in range(lanes):
        if all((col >> c) & 1 for c in ctrl_shifts):
            in_bit = (col >> t_shift) & 1
            for out_bit in (0, 1):
                row = (col & ~(1 << t_shift)) | (out_bit << t_shift)
                mat[row, col] = gate2[out_bit, in_bit]
        else:
            mat[col, col] = 1.0
    return mat


def build_fused_circuit_run(
    n_qubits: int, ops, n_params: int, *, interpret: bool = False
):
    """Compile a static op list into ``run(params) -> statevector[2**n]``.

    ``ops`` is a sequence of :class:`qba_tpu.qsim.circuit.Op`; the returned
    function is jit/vmap-safe.  The result dtype is float32 for all-real
    circuits and complex64 when any gate is complex (see module docs).
    """
    from qba_tpu.qsim.statevector import gate_matrix

    lane_bits = min(n_qubits, 7)
    lanes = 1 << lane_bits
    n_rows = 1 << (n_qubits - lane_bits)

    def bit_shift(q: int) -> tuple[bool, int]:
        """(is_lane, shift): flat-index bit position of qubit ``q`` split
        into the lane / row sub-index (qubit 0 = MSB of the flat index)."""
        flat = n_qubits - 1 - q
        if flat < lane_bits:
            return True, flat
        return False, flat - lane_bits

    plan: list[_LaneOp | _RowOp] = []
    mats0: list[np.ndarray] = []  # complex64 [L, L]
    mats_d: list[np.ndarray] = []  # real XPOW deltas, complex64 for stacking
    for op in ops:
        t_lane, t_shift = bit_shift(op.target)
        lane_cs = tuple(
            s for c in op.controls for is_l, s in (bit_shift(c),) if is_l
        )
        row_cs = tuple(
            s for c in op.controls for is_l, s in (bit_shift(c),) if not is_l
        )
        if op.kind == "XPOW":
            g2 = None  # runtime-parameterized; handled specially below
        else:
            g2 = gate_matrix(op.kind, op.angle)
        if t_lane:
            if op.kind == "XPOW":
                base = gate_matrix("X")
                full = _lane_matrix(base, t_shift, lane_cs, lanes)
                mats0.append(np.eye(lanes, dtype=np.complex64))
                mats_d.append(full - np.eye(lanes, dtype=np.complex64))
            else:
                full = _lane_matrix(g2, t_shift, lane_cs, lanes)
                mats0.append(full)
                mats_d.append(np.zeros((lanes, lanes), np.complex64))
            has_imag = bool(
                np.any(mats0[-1].imag) or np.any(mats_d[-1].imag)
            )
            plan.append(_LaneOp(len(mats0) - 1, op.param, row_cs, has_imag))
        else:
            if op.kind in ("H", "X", "XPOW"):
                plan.append(
                    _RowOp(op.kind, t_shift, op.param, row_cs, lane_cs)
                )
            else:
                entries = tuple(
                    tuple(complex(g2[i, j]) for j in (0, 1)) for i in (0, 1)
                )
                plan.append(
                    _RowOp("GEN", t_shift, None, row_cs, lane_cs, entries)
                )

    def _op_is_real(op) -> bool:
        if isinstance(op, _LaneOp):
            return not op.has_imag
        if op.kind == "GEN":
            return all(e.imag == 0.0 for row in op.g2 for e in row)
        return True  # H / X / XPOW

    is_real = all(_op_is_real(op) for op in plan)

    # Stacked constants (>=1 entry so the kernel signature is static).
    m0 = np.stack(mats0) if mats0 else np.eye(lanes, dtype=np.complex64)[None]
    md = (
        np.stack(mats_d)
        if mats_d
        else np.zeros((1, lanes, lanes), np.complex64)
    )
    m0r, m0i = m0.real.astype(np.float32), m0.imag.astype(np.float32)
    mdr = md.real.astype(np.float32)  # XPOW deltas are always real
    n_params = max(n_params, 1)

    def kernel(params_ref, m0r_ref, *rest):
        # The all-zero imaginary matrix stack is only an input on the
        # complex path — the real fast path never reads it, so shipping
        # it would be pure VMEM/bandwidth waste on the protocol circuits.
        if is_real:
            (mdr_ref, *out_refs) = rest
            m0i_ref = None
        else:
            (m0i_ref, mdr_ref, *out_refs) = rest
        row_iota = jax.lax.broadcasted_iota(jnp.int32, (n_rows, lanes), 0)
        lane_iota = jax.lax.broadcasted_iota(jnp.int32, (n_rows, lanes), 1)

        def ctrl_mask(row_cs, lane_cs):
            mask = jnp.ones((n_rows, lanes), dtype=jnp.bool_)
            for c in row_cs:
                mask &= ((row_iota >> c) & 1) == 1
            for c in lane_cs:
                mask &= ((lane_iota >> c) & 1) == 1
            return mask

        # |0...0>: real amplitude 1 at index 0, imag identically 0.
        x = jnp.where((row_iota == 0) & (lane_iota == 0), 1.0, 0.0).astype(
            jnp.float32
        )
        y = None if is_real else jnp.zeros((n_rows, lanes), jnp.float32)

        def masked(op, new_x, new_y, old_x, old_y, lane_ctrls=()):
            cs = op.row_ctrl_shifts, lane_ctrls
            if not (cs[0] or cs[1]):
                return new_x, new_y
            mask = ctrl_mask(*cs)
            out_x = jnp.where(mask, new_x, old_x)
            out_y = (
                None if old_y is None else jnp.where(mask, new_y, old_y)
            )
            return out_x, out_y

        for op in plan:  # static unroll: the circuit IS the kernel
            if isinstance(op, _LaneOp):
                ar = m0r_ref[op.mat_idx]
                if op.param is not None:
                    b = params_ref[op.param].astype(jnp.float32)
                    ar = ar + b * mdr_ref[op.mat_idx]
                if is_real:
                    new_x = jnp.dot(
                        x, ar.T, preferred_element_type=jnp.float32
                    )
                    new_y = None
                else:
                    ai = m0i_ref[op.mat_idx]
                    new_x = jnp.dot(
                        x, ar.T, preferred_element_type=jnp.float32
                    ) - jnp.dot(y, ai.T, preferred_element_type=jnp.float32)
                    new_y = jnp.dot(
                        y, ar.T, preferred_element_type=jnp.float32
                    ) + jnp.dot(x, ai.T, preferred_element_type=jnp.float32)
                x, y = masked(op, new_x, new_y, x, y)
            else:
                stride = 1 << op.rbs
                # partner[r] = state[r ^ stride]: two static rolls selected
                # by the target bit (no dynamic gathers on TPU).
                bit = ((row_iota >> op.rbs) & 1) == 1

                def roll_partner(s):
                    up = jnp.concatenate([s[stride:], s[:stride]], axis=0)
                    down = jnp.concatenate([s[-stride:], s[:-stride]], axis=0)
                    return jnp.where(bit, down, up)

                px = roll_partner(x)
                py = None if y is None else roll_partner(y)
                if op.kind == "H":
                    new_x = (
                        jnp.where(bit, px - x, x + px) * _INV_SQRT2
                    )
                    new_y = (
                        None
                        if y is None
                        else jnp.where(bit, py - y, y + py) * _INV_SQRT2
                    )
                elif op.kind == "X":
                    new_x, new_y = px, py
                elif op.kind == "XPOW":
                    flip = params_ref[op.param] != 0
                    new_x = jnp.where(flip, px, x)
                    new_y = None if y is None else jnp.where(flip, py, y)
                else:  # GEN: new = c_s * state + c_p * partner
                    (m00, m01), (m10, m11) = op.g2
                    csr = jnp.where(bit, m11.real, m00.real)
                    cpr = jnp.where(bit, m10.real, m01.real)
                    if is_real:
                        new_x = csr * x + cpr * px
                        new_y = None
                    else:
                        csi = jnp.where(bit, m11.imag, m00.imag)
                        cpi = jnp.where(bit, m10.imag, m01.imag)
                        new_x = csr * x - csi * y + cpr * px - cpi * py
                        new_y = csi * x + csr * y + cpi * px + cpr * py
                x, y = masked(op, new_x, new_y, x, y, op.lane_ctrl_shifts)

        out_refs[0][:] = x
        if not is_real:
            out_refs[1][:] = y

    n_out = 1 if is_real else 2
    n_in = 3 if is_real else 4  # params + m0r [+ m0i] + mdr
    call = pl.pallas_call(
        kernel,
        out_shape=tuple(
            jax.ShapeDtypeStruct((n_rows, lanes), jnp.float32)
            for _ in range(n_out)
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [pl.BlockSpec(memory_space=pltpu.VMEM)] * (n_in - 1),
        out_specs=tuple(
            pl.BlockSpec(memory_space=pltpu.VMEM) for _ in range(n_out)
        ),
        interpret=interpret,
    )

    def run(params: jnp.ndarray | None = None) -> jnp.ndarray:
        if params is None:
            params = jnp.zeros((n_params,), dtype=jnp.int32)
        params = jnp.asarray(params, dtype=jnp.int32)
        if is_real:
            out = call(params, m0r, mdr)
            return out[0].reshape(-1)
        out = call(params, m0r, m0i, mdr)
        return jax.lax.complex(out[0], out[1]).reshape(-1)

    return run
