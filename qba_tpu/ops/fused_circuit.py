"""Fused whole-circuit statevector execution — one Pallas kernel per circuit.

The dense validation engine (:mod:`qba_tpu.qsim.statevector`) applies one
gate at a time; under XLA each gate is a statevector-sized HBM round-trip.
This kernel executes the *entire* circuit in a single ``pallas_call`` with
the state resident in VMEM — the TPU-native answer to the reference's
serial per-gate native-engine calls (``tfg.py:76-80``, SURVEY §3.2).

Design (see ``/opt/skills/guides/pallas_guide.md``):

* **Layout** — the flat statevector (qubit 0 = the most significant index
  bit, matching :mod:`qba_tpu.qsim.statevector`) is viewed as
  ``[rows, lanes]`` with ``lanes = 2**min(n, 7)``: the last ``min(n, 7)``
  qubits live in the 128-wide lane dimension, the rest in the sublane/row
  dimension.
* **Lane-qubit gates** (including lane-qubit controls) are ``L x L``
  matmuls on the MXU: the controlled gate restricted to the lane subspace
  is precomputed as a dense matrix, so ``state @ M.T`` applies it to every
  row at once.
* **Row-qubit gates** are sublane butterflies on the VPU: the partner
  amplitude ``state[r ^ 2**rbs]`` is two static rolls selected by the
  target bit; controls become iota bit-masks.
* **Real arithmetic** — every gate the protocol circuits use (H, X/CNOT,
  parameterized X**b; ``tfg.py:17-39``) is real-valued and the initial
  state is |0..0>, so the state is ``float32``, not complex: half the
  memory and FLOPs of the complex engine.
* **Data-dependent encodings** — the reference rebuilds the Q-correlated
  circuit per list position with fresh ``rands`` (``tfg.py:30-37``); here
  the permutation bits arrive as an int32 param vector in SMEM, so ONE
  compiled kernel serves every position and trial under ``vmap``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_INV_SQRT2 = float(1.0 / np.sqrt(2.0))

_H2 = np.asarray([[1.0, 1.0], [1.0, -1.0]], dtype=np.float32) * np.float32(
    _INV_SQRT2
)
_X2 = np.asarray([[0.0, 1.0], [1.0, 0.0]], dtype=np.float32)


@dataclasses.dataclass(frozen=True)
class _LaneOp:
    """Gate whose target sits in the lane dimension -> MXU matmul."""

    mat_idx: int  # index into the stacked [K, L, L] matrices
    param: int | None  # param index for X**b, None for fixed gates
    row_ctrl_shifts: tuple[int, ...]  # row-qubit controls (iota bit tests)


@dataclasses.dataclass(frozen=True)
class _RowOp:
    """Gate whose target sits in the row dimension -> sublane butterfly."""

    kind: str  # "H" | "X" | "XPOW"
    rbs: int  # target bit shift within the row index
    param: int | None
    row_ctrl_shifts: tuple[int, ...]
    lane_ctrl_shifts: tuple[int, ...]


def _lane_matrix(
    gate2: np.ndarray, t_shift: int, ctrl_shifts: tuple[int, ...], lanes: int
) -> np.ndarray:
    """Dense ``[L, L]`` matrix of ``gate2`` on lane-bit ``t_shift``,
    controlled on lane bits ``ctrl_shifts`` (identity elsewhere)."""
    mat = np.zeros((lanes, lanes), dtype=np.float32)
    for col in range(lanes):
        if all((col >> c) & 1 for c in ctrl_shifts):
            in_bit = (col >> t_shift) & 1
            for out_bit in (0, 1):
                row = (col & ~(1 << t_shift)) | (out_bit << t_shift)
                mat[row, col] = gate2[out_bit, in_bit]
        else:
            mat[col, col] = 1.0
    return mat


def build_fused_circuit_run(
    n_qubits: int, ops, n_params: int, *, interpret: bool = False
):
    """Compile a static op list into ``run(params) -> float32[2**n]``.

    ``ops`` is a sequence of :class:`qba_tpu.qsim.circuit.Op`; the returned
    function is jit/vmap-safe and returns the final (real) statevector.
    """
    lane_bits = min(n_qubits, 7)
    lanes = 1 << lane_bits
    n_rows = 1 << (n_qubits - lane_bits)

    def bit_shift(q: int) -> tuple[bool, int]:
        """(is_lane, shift): flat-index bit position of qubit ``q`` split
        into the lane / row sub-index (qubit 0 = MSB of the flat index)."""
        flat = n_qubits - 1 - q
        if flat < lane_bits:
            return True, flat
        return False, flat - lane_bits

    plan: list[_LaneOp | _RowOp] = []
    mats0: list[np.ndarray] = []
    mats_d: list[np.ndarray] = []
    for op in ops:
        t_lane, t_shift = bit_shift(op.target)
        lane_cs = tuple(
            s for c in op.controls for is_l, s in (bit_shift(c),) if is_l
        )
        row_cs = tuple(
            s for c in op.controls for is_l, s in (bit_shift(c),) if not is_l
        )
        if t_lane:
            gate2 = _H2 if op.kind == "H" else _X2
            full = _lane_matrix(gate2, t_shift, lane_cs, lanes)
            if op.kind == "XPOW":
                mats0.append(np.eye(lanes, dtype=np.float32))
                mats_d.append(full - np.eye(lanes, dtype=np.float32))
            else:
                mats0.append(full)
                mats_d.append(np.zeros((lanes, lanes), dtype=np.float32))
            plan.append(_LaneOp(len(mats0) - 1, op.param, row_cs))
        else:
            plan.append(_RowOp(op.kind, t_shift, op.param, row_cs, lane_cs))

    # Stacked constants (>=1 entry so the kernel signature is static).
    m0 = np.stack(mats0) if mats0 else np.eye(lanes, dtype=np.float32)[None]
    md = np.stack(mats_d) if mats_d else np.zeros((1, lanes, lanes), np.float32)
    n_params = max(n_params, 1)

    def kernel(params_ref, m0_ref, md_ref, out_ref):
        row_iota = jax.lax.broadcasted_iota(jnp.int32, (n_rows, lanes), 0)
        lane_iota = jax.lax.broadcasted_iota(jnp.int32, (n_rows, lanes), 1)

        def ctrl_mask(row_cs, lane_cs):
            mask = jnp.ones((n_rows, lanes), dtype=jnp.bool_)
            for c in row_cs:
                mask &= ((row_iota >> c) & 1) == 1
            for c in lane_cs:
                mask &= ((lane_iota >> c) & 1) == 1
            return mask

        # |0...0>
        state = jnp.where(
            (row_iota == 0) & (lane_iota == 0), 1.0, 0.0
        ).astype(jnp.float32)

        for op in plan:  # static unroll: the circuit IS the kernel
            if isinstance(op, _LaneOp):
                mat = m0_ref[op.mat_idx]
                if op.param is not None:
                    b = params_ref[op.param].astype(jnp.float32)
                    mat = mat + b * md_ref[op.mat_idx]
                new = jnp.dot(state, mat.T, preferred_element_type=jnp.float32)
                if op.row_ctrl_shifts:
                    state = jnp.where(ctrl_mask(op.row_ctrl_shifts, ()), new, state)
                else:
                    state = new
            else:
                stride = 1 << op.rbs
                # partner[r] = state[r ^ stride]: two static rolls selected
                # by the target bit (no dynamic gathers on TPU).
                bit = ((row_iota >> op.rbs) & 1) == 1
                up = jnp.concatenate([state[stride:], state[:stride]], axis=0)
                down = jnp.concatenate([state[-stride:], state[:-stride]], axis=0)
                partner = jnp.where(bit, down, up)
                if op.kind == "H":
                    new = jnp.where(bit, partner - state, state + partner) * _INV_SQRT2
                elif op.kind == "X":
                    new = partner
                else:  # XPOW
                    flip = params_ref[op.param] != 0
                    new = jnp.where(flip, partner, state)
                if op.row_ctrl_shifts or op.lane_ctrl_shifts:
                    mask = ctrl_mask(op.row_ctrl_shifts, op.lane_ctrl_shifts)
                    state = jnp.where(mask, new, state)
                else:
                    state = new

        out_ref[:] = state

    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n_rows, lanes), jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )

    def run(params: jnp.ndarray | None = None) -> jnp.ndarray:
        if params is None:
            params = jnp.zeros((n_params,), dtype=jnp.int32)
        params = jnp.asarray(params, dtype=jnp.int32)
        return call(params, m0, md).reshape(-1)

    return run
