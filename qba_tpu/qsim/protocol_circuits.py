"""The protocol's two circuit families on the dense engine.

Builds the reference's gates/circuits (``notQCorrelated`` ``tfg.py:15-22``,
``qCorrelated`` ``tfg.py:25-40``, assemblers ``tfg.py:43-65``) and the
dense-path list generation (``generacionListas``, ``tfg.py:68-84``) —
``vmap``-batched over list positions instead of the reference's serial
per-position loop.

Qubit layout: ``(nParties+1)`` groups of ``nQubits``; group 0 is the QSD's
extra copy, group 1 the commander's particles (``tfg.py:142-158``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from qba_tpu.config import QBAConfig
from qba_tpu.core.decode import measure_to_ints
from qba_tpu.qsim.circuit import Circuit, Gate


def not_q_correlated(n_parties: int, n_qubits: int) -> Gate:
    """H on every qubit of groups 1..nParties, then CNOT copying group 1
    onto group 0 (``tfg.py:15-22``)."""
    size = (n_parties + 1) * n_qubits
    gate = Gate(size, "not Q-Correlated")
    for i in range(n_qubits, size):
        gate.add_operation("H", targets=i)
    for i in range(n_qubits):
        gate.add_operation("X", targets=i, controls=i + n_qubits)
    return gate


def q_correlated(n_parties: int, n_qubits: int) -> Gate:
    """H on group 0; X-encode a permutation value into each party group
    (as parameterized XPOW ops reading the permutation's bits at runtime —
    the reference bakes fresh ``rands`` into a new circuit per position,
    ``tfg.py:25-40``); CNOT group 0 onto every other group."""
    size = (n_parties + 1) * n_qubits
    gate = Gate(size, "Q-Correlated")
    for i in range(n_qubits):
        gate.add_operation("H", targets=i)
    for i in range(1, n_parties + 1):
        for j in range(n_qubits):
            # param vector layout: bit j (big-endian) of rands[i-1]
            gate.add_operation(
                "XPOW", targets=i * n_qubits + j, param=(i - 1) * n_qubits + j
            )
    for i in range(n_qubits, size):
        gate.add_operation("X", targets=i, controls=i % n_qubits)
    return gate


def gen_q_corr_circuit(n_parties: int, n_qubits: int) -> Circuit:
    """``genQCorrCircuit`` (``tfg.py:43-52``)."""
    size = (n_parties + 1) * n_qubits
    return Circuit(size, "Q-Correlated Circuit").add_operation(
        q_correlated(n_parties, n_qubits)
    )


def gen_nq_corr_circuit(n_parties: int, n_qubits: int) -> Circuit:
    """``genNQCorrCircuit`` (``tfg.py:56-65``)."""
    size = (n_parties + 1) * n_qubits
    return Circuit(size, "Not Q-Correlated Circuit").add_operation(
        not_q_correlated(n_parties, n_qubits)
    )


def _perm_bits(perm: jnp.ndarray, n_qubits: int) -> jnp.ndarray:
    """Big-endian bits of each permutation entry: [n] -> [n * n_qubits]."""
    shifts = jnp.arange(n_qubits - 1, -1, -1, dtype=jnp.int32)
    return ((perm[:, None] >> shifts) & 1).reshape(-1).astype(jnp.int32)


def generate_lists_dense(cfg: QBAConfig, key: jax.Array, impl: str = "xla"):
    """Dense-path ``generacionListas`` (``tfg.py:68-84``), one Born sample
    per list position, all positions batched with ``vmap``.

    ``impl`` selects the circuit executor (:meth:`Circuit.compile`):
    ``"xla"``, ``"pallas"``, ``"pallas_interpret"``, ``"auto"`` (the
    fused Pallas kernel on TPU, interpreter mode elsewhere), or
    ``"stabilizer"`` (the Clifford tableau — the only executor that
    runs the joint circuits at the reference's real party counts; the
    dense impls cap at ~20 qubits).

    Returns ``(lists, qcorr)``: int32 ``[n_parties+1, size_l]`` decoded
    order values per party (row 0 = QSD extra copy, row 1 = commander),
    and the ground-truth Q-correlated position mask ``[size_l]``.
    """
    n, nq = cfg.n_parties, cfg.n_qubits
    if impl == "auto":
        # Resolve against the actual joint circuit: past the dense cap
        # a Clifford op list hands off to the stabilizer engine
        # (recorded via warn_and_record inside resolve_auto_impl)
        # instead of building a guaranteed-OOM statevector — and the
        # stabilizer resolution takes the *batched* GF(2) path, not a
        # per-position tableau vmap.
        impl = gen_q_corr_circuit(n, nq).resolve_auto_impl()
        if impl == "stabilizer":
            return generate_lists_stabilizer(cfg, key)
    # Imperfect resources (cfg.p_depolarize / cfg.p_measure_flip) apply
    # per position off that position's measurement key — compile() owns
    # the channel (classical reduction on the dense engines).
    run_q = gen_q_corr_circuit(n, nq).compile(
        impl, cfg.p_depolarize, cfg.p_measure_flip
    )
    run_nq = gen_nq_corr_circuit(n, nq).compile(
        impl, cfg.p_depolarize, cfg.p_measure_flip
    )

    k_qcorr, k_perm, k_meas = jax.random.split(key, 3)
    qcorr = jax.random.bernoulli(k_qcorr, 0.5, (cfg.size_l,))

    def one_position(k_p, k_m, is_q):
        perm = jax.random.permutation(k_p, jnp.arange(1, n + 1, dtype=jnp.int32))
        params = _perm_bits(perm, nq)
        # Both branches cost one small statevector each at validation sizes;
        # select keeps the program branch-free under vmap.
        bits_q = run_q(k_m, params)
        bits_nq = run_nq(k_m)
        return jnp.where(is_q, bits_q, bits_nq)

    perm_keys = jax.random.split(k_perm, cfg.size_l)
    meas_keys = jax.random.split(k_meas, cfg.size_l)
    bits = jax.vmap(one_position)(perm_keys, meas_keys, qcorr)  # [size_l, total_qubits]

    # Regroup to the reference's raw layout: party i's bits across positions
    # (tfg.py:81-82), then decode (tfg.py:128-129).
    per_party = bits.reshape(cfg.size_l, n + 1, nq).transpose(1, 0, 2)
    lists = measure_to_ints(per_party.reshape(n + 1, -1), cfg.size_l, nq)
    return lists, qcorr


def stabilizer_gen_tables(cfg: QBAConfig):
    """Static packed tableaux of both protocol circuit families —
    the compile-time half of the megakernel's in-VMEM generation.

    Returns ``(x0w_q, z0w_q, x0w_nq, z0w_nq)``, each a numpy
    ``[2*total, W]`` uint32 array: the evolved symplectic rows of the
    Q-correlated / not-Q-correlated circuits, packed exactly as
    :func:`qba_tpu.gf2.symplectic.build_gf2_sample_core` packs them.
    Pure host numpy per config shape; the megakernel takes them as
    VMEM inputs and broadcasts per shot.
    """
    import numpy as np

    from qba_tpu.gf2.bitops import pack_bits
    from qba_tpu.gf2.symplectic import compile_symplectic

    n, nq = cfg.n_parties, cfg.n_qubits
    total = (n + 1) * nq
    circ_q = gen_q_corr_circuit(n, nq)
    circ_nq = gen_nq_corr_circuit(n, nq)
    prog_q = compile_symplectic(total, tuple(circ_q.ops), circ_q.n_params)
    prog_nq = compile_symplectic(total, tuple(circ_nq.ops), 0)
    # The tables are config-constant: force eager packing so tracing a
    # gen-fused trial (launch/effects audits run under make_jaxpr) does
    # not turn these kernel-build-time constants into tracers.
    with jax.ensure_compile_time_eval():
        return tuple(
            np.asarray(pack_bits(jnp.asarray(m)))
            for m in (prog_q.x, prog_q.z, prog_nq.x, prog_nq.z)
        )


def stabilizer_gen_operands(cfg: QBAConfig, key: jax.Array):
    """Per-trial generation operands for the megakernel's in-VMEM
    GF(2) sweep — everything of :func:`generate_lists_stabilizer`
    EXCEPT the measurement sweep and the decode, under the *identical*
    key tree, so the in-kernel sweep (sharing
    :func:`~qba_tpu.gf2.symplectic.gf2_measure_sweep`) reproduces the
    host path bit for bit.

    ``key`` is the SAME ``k_lists`` subkey ``setup_trial`` feeds
    ``generate_lists_for``.  Returns ``(qcorr, coins, r_q, r_nq,
    mflip)``:

    * ``qcorr``  bool ``[size_l]`` — the position-correlation mask;
    * ``coins``  int32 ``[size_l, total]`` — the measurement coins
      (``_draw_coins`` off the per-position meas keys, shared by both
      branches exactly as the host path shares them);
    * ``r_q``    int32 ``[size_l, 2*total]`` — Q-correlated phases:
      ``r0 ^ params @ L^T`` (the permutation encoding) with any
      depolarizing phase parity already folded in;
    * ``r_nq``   int32 ``[size_l, 2*total]`` — not-Q-correlated
      phases, noise likewise folded;
    * ``mflip``  int32 ``[size_l, total]`` — readout flips (all
      zeros when noiseless; both branches share the draw, so the
      post-sweep XOR commutes with the qcorr select).

    Noise uses :func:`qba_tpu.qsim.noise.noise_draws` off the same
    meas keys as the host path; the sweep itself stays PRNG-free.
    """
    from qba_tpu.gf2.linalg import gf2_matmul
    from qba_tpu.gf2.symplectic import _draw_coins, compile_symplectic

    n, nq = cfg.n_parties, cfg.n_qubits
    total = (n + 1) * nq
    circ_q = gen_q_corr_circuit(n, nq)
    circ_nq = gen_nq_corr_circuit(n, nq)
    prog_q = compile_symplectic(total, tuple(circ_q.ops), circ_q.n_params)
    prog_nq = compile_symplectic(total, tuple(circ_nq.ops), 0)
    r0_q = jnp.asarray(prog_q.r, jnp.int32)    # [2T]
    r0_nq = jnp.asarray(prog_nq.r, jnp.int32)
    lt_q = jnp.asarray(prog_q.l.T, jnp.int32)  # [P, 2T]

    k_qcorr, k_perm, k_meas = jax.random.split(key, 3)
    qcorr = jax.random.bernoulli(k_qcorr, 0.5, (cfg.size_l,))

    perm_keys = jax.random.split(k_perm, cfg.size_l)
    meas_keys = jax.random.split(k_meas, cfg.size_l)
    perms = jax.vmap(
        lambda k: jax.random.permutation(k, jnp.arange(1, n + 1, dtype=jnp.int32))
    )(perm_keys)
    params = jax.vmap(_perm_bits, in_axes=(0, None))(perms, nq)
    coins = _draw_coins(meas_keys, total)      # [size_l, T]

    b = cfg.size_l
    r_q = r0_q[None, :] ^ gf2_matmul(params & 1, lt_q)  # [size_l, 2T]
    r_nq = jnp.broadcast_to(r0_nq[None, :], (b, 2 * total))
    noisy = cfg.p_depolarize > 0.0 or cfg.p_measure_flip > 0.0
    if not noisy:
        return qcorr, coins, r_q, r_nq, jnp.zeros((b, total), jnp.int32)
    from qba_tpu.qsim.noise import noise_draws

    bx, bz, mflip = jax.vmap(
        lambda k: noise_draws(k, total, cfg.p_depolarize, cfg.p_measure_flip)
    )(meas_keys)
    noise_q = gf2_matmul(bx, jnp.asarray(prog_q.z.T, jnp.int32)) ^ gf2_matmul(
        bz, jnp.asarray(prog_q.x.T, jnp.int32)
    )
    noise_nq = gf2_matmul(bx, jnp.asarray(prog_nq.z.T, jnp.int32)) ^ gf2_matmul(
        bz, jnp.asarray(prog_nq.x.T, jnp.int32)
    )
    return qcorr, coins, r_q ^ noise_q, r_nq ^ noise_nq, mflip


def generate_lists_stabilizer(cfg: QBAConfig, key: jax.Array):
    """``generacionListas`` on the batched GF(2) symplectic engine — the
    primary resource path at reference scale (ROADMAP item 5).

    Both circuit families compile once into aggregate symplectic
    transforms (:mod:`qba_tpu.gf2.symplectic`), then the whole
    ``size_l`` position batch runs as a handful of batched GF(2)
    matmuls + one masked measurement sweep — no per-position circuit
    execution, no per-op column edits.  This is what makes 65-party
    (462-qubit), 129-party (1040-qubit) and 257-party (2322-qubit)
    scenarios runnable end to end.

    Key-tree and coin-draw discipline exactly mirror
    :func:`generate_lists_dense`: ``(k_qcorr, k_perm, k_meas)`` split,
    per-position permutation and measurement subkeys, both branches
    sharing the position's measurement key — so the outputs are
    **bit-identical** to ``generate_lists_dense(cfg, key,
    impl="stabilizer")`` (the per-position tableau reference) for the
    same key, at any party count where both can run.

    Returns ``(lists, qcorr)`` with the same layout as
    :func:`generate_lists_dense`.
    """
    from qba_tpu.gf2 import build_gf2_tableau_run_batch

    n, nq = cfg.n_parties, cfg.n_qubits
    total = (n + 1) * nq
    circ_q = gen_q_corr_circuit(n, nq)
    circ_nq = gen_nq_corr_circuit(n, nq)
    # Noise rides each position's measurement key (tableau-phase
    # injection — keeps the program Clifford; see qsim/noise.py), so
    # both stabilizer engines stay bit-identical under noise too.
    run_q = build_gf2_tableau_run_batch(
        total, tuple(circ_q.ops), circ_q.n_params,
        cfg.p_depolarize, cfg.p_measure_flip,
    )
    run_nq = build_gf2_tableau_run_batch(
        total, tuple(circ_nq.ops), 0,
        cfg.p_depolarize, cfg.p_measure_flip,
    )

    k_qcorr, k_perm, k_meas = jax.random.split(key, 3)
    qcorr = jax.random.bernoulli(k_qcorr, 0.5, (cfg.size_l,))

    perm_keys = jax.random.split(k_perm, cfg.size_l)
    meas_keys = jax.random.split(k_meas, cfg.size_l)
    perms = jax.vmap(
        lambda k: jax.random.permutation(k, jnp.arange(1, n + 1, dtype=jnp.int32))
    )(perm_keys)
    params = jax.vmap(_perm_bits, in_axes=(0, None))(perms, nq)  # [size_l, n*nq]

    # Both branches over the whole batch, sharing the per-position
    # measurement keys (same coins as the reference's shared k_m);
    # select keeps the program branch-free.
    bits_q = run_q(meas_keys, params)  # [size_l, total]
    bits_nq = run_nq(meas_keys)
    bits = jnp.where(qcorr[:, None], bits_q, bits_nq)

    per_party = bits.reshape(cfg.size_l, n + 1, nq).transpose(1, 0, 2)
    lists = measure_to_ints(per_party.reshape(n + 1, -1), cfg.size_l, nq)
    return lists, qcorr
