"""The protocol's two circuit families on the dense engine.

Builds the reference's gates/circuits (``notQCorrelated`` ``tfg.py:15-22``,
``qCorrelated`` ``tfg.py:25-40``, assemblers ``tfg.py:43-65``) and the
dense-path list generation (``generacionListas``, ``tfg.py:68-84``) —
``vmap``-batched over list positions instead of the reference's serial
per-position loop.

Qubit layout: ``(nParties+1)`` groups of ``nQubits``; group 0 is the QSD's
extra copy, group 1 the commander's particles (``tfg.py:142-158``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from qba_tpu.config import QBAConfig
from qba_tpu.core.decode import measure_to_ints
from qba_tpu.qsim.circuit import Circuit, Gate


def not_q_correlated(n_parties: int, n_qubits: int) -> Gate:
    """H on every qubit of groups 1..nParties, then CNOT copying group 1
    onto group 0 (``tfg.py:15-22``)."""
    size = (n_parties + 1) * n_qubits
    gate = Gate(size, "not Q-Correlated")
    for i in range(n_qubits, size):
        gate.add_operation("H", targets=i)
    for i in range(n_qubits):
        gate.add_operation("X", targets=i, controls=i + n_qubits)
    return gate


def q_correlated(n_parties: int, n_qubits: int) -> Gate:
    """H on group 0; X-encode a permutation value into each party group
    (as parameterized XPOW ops reading the permutation's bits at runtime —
    the reference bakes fresh ``rands`` into a new circuit per position,
    ``tfg.py:25-40``); CNOT group 0 onto every other group."""
    size = (n_parties + 1) * n_qubits
    gate = Gate(size, "Q-Correlated")
    for i in range(n_qubits):
        gate.add_operation("H", targets=i)
    for i in range(1, n_parties + 1):
        for j in range(n_qubits):
            # param vector layout: bit j (big-endian) of rands[i-1]
            gate.add_operation(
                "XPOW", targets=i * n_qubits + j, param=(i - 1) * n_qubits + j
            )
    for i in range(n_qubits, size):
        gate.add_operation("X", targets=i, controls=i % n_qubits)
    return gate


def gen_q_corr_circuit(n_parties: int, n_qubits: int) -> Circuit:
    """``genQCorrCircuit`` (``tfg.py:43-52``)."""
    size = (n_parties + 1) * n_qubits
    return Circuit(size, "Q-Correlated Circuit").add_operation(
        q_correlated(n_parties, n_qubits)
    )


def gen_nq_corr_circuit(n_parties: int, n_qubits: int) -> Circuit:
    """``genNQCorrCircuit`` (``tfg.py:56-65``)."""
    size = (n_parties + 1) * n_qubits
    return Circuit(size, "Not Q-Correlated Circuit").add_operation(
        not_q_correlated(n_parties, n_qubits)
    )


def _perm_bits(perm: jnp.ndarray, n_qubits: int) -> jnp.ndarray:
    """Big-endian bits of each permutation entry: [n] -> [n * n_qubits]."""
    shifts = jnp.arange(n_qubits - 1, -1, -1, dtype=jnp.int32)
    return ((perm[:, None] >> shifts) & 1).reshape(-1).astype(jnp.int32)


def generate_lists_dense(cfg: QBAConfig, key: jax.Array, impl: str = "xla"):
    """Dense-path ``generacionListas`` (``tfg.py:68-84``), one Born sample
    per list position, all positions batched with ``vmap``.

    ``impl`` selects the circuit executor (:meth:`Circuit.compile`):
    ``"xla"``, ``"pallas"``, ``"pallas_interpret"``, ``"auto"`` (the
    fused Pallas kernel on TPU, interpreter mode elsewhere), or
    ``"stabilizer"`` (the Clifford tableau — the only executor that
    runs the joint circuits at the reference's real party counts; the
    dense impls cap at ~20 qubits).

    Returns ``(lists, qcorr)``: int32 ``[n_parties+1, size_l]`` decoded
    order values per party (row 0 = QSD extra copy, row 1 = commander),
    and the ground-truth Q-correlated position mask ``[size_l]``.
    """
    n, nq = cfg.n_parties, cfg.n_qubits
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "pallas_interpret"
    run_q = gen_q_corr_circuit(n, nq).compile(impl)
    run_nq = gen_nq_corr_circuit(n, nq).compile(impl)

    k_qcorr, k_perm, k_meas = jax.random.split(key, 3)
    qcorr = jax.random.bernoulli(k_qcorr, 0.5, (cfg.size_l,))

    def one_position(k_p, k_m, is_q):
        perm = jax.random.permutation(k_p, jnp.arange(1, n + 1, dtype=jnp.int32))
        params = _perm_bits(perm, nq)
        # Both branches cost one small statevector each at validation sizes;
        # select keeps the program branch-free under vmap.
        bits_q = run_q(k_m, params)
        bits_nq = run_nq(k_m)
        return jnp.where(is_q, bits_q, bits_nq)

    perm_keys = jax.random.split(k_perm, cfg.size_l)
    meas_keys = jax.random.split(k_meas, cfg.size_l)
    bits = jax.vmap(one_position)(perm_keys, meas_keys, qcorr)  # [size_l, total_qubits]

    # Regroup to the reference's raw layout: party i's bits across positions
    # (tfg.py:81-82), then decode (tfg.py:128-129).
    per_party = bits.reshape(cfg.size_l, n + 1, nq).transpose(1, 0, 2)
    lists = measure_to_ints(per_party.reshape(n + 1, -1), cfg.size_l, nq)
    return lists, qcorr
