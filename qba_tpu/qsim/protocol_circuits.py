"""The protocol's two circuit families on the dense engine.

Builds the reference's gates/circuits (``notQCorrelated`` ``tfg.py:15-22``,
``qCorrelated`` ``tfg.py:25-40``, assemblers ``tfg.py:43-65``) and the
dense-path list generation (``generacionListas``, ``tfg.py:68-84``) —
``vmap``-batched over list positions instead of the reference's serial
per-position loop.

Qubit layout: ``(nParties+1)`` groups of ``nQubits``; group 0 is the QSD's
extra copy, group 1 the commander's particles (``tfg.py:142-158``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from qba_tpu.config import QBAConfig
from qba_tpu.core.decode import measure_to_ints
from qba_tpu.qsim.circuit import Circuit, Gate


def not_q_correlated(n_parties: int, n_qubits: int) -> Gate:
    """H on every qubit of groups 1..nParties, then CNOT copying group 1
    onto group 0 (``tfg.py:15-22``)."""
    size = (n_parties + 1) * n_qubits
    gate = Gate(size, "not Q-Correlated")
    for i in range(n_qubits, size):
        gate.add_operation("H", targets=i)
    for i in range(n_qubits):
        gate.add_operation("X", targets=i, controls=i + n_qubits)
    return gate


def q_correlated(n_parties: int, n_qubits: int) -> Gate:
    """H on group 0; X-encode a permutation value into each party group
    (as parameterized XPOW ops reading the permutation's bits at runtime —
    the reference bakes fresh ``rands`` into a new circuit per position,
    ``tfg.py:25-40``); CNOT group 0 onto every other group."""
    size = (n_parties + 1) * n_qubits
    gate = Gate(size, "Q-Correlated")
    for i in range(n_qubits):
        gate.add_operation("H", targets=i)
    for i in range(1, n_parties + 1):
        for j in range(n_qubits):
            # param vector layout: bit j (big-endian) of rands[i-1]
            gate.add_operation(
                "XPOW", targets=i * n_qubits + j, param=(i - 1) * n_qubits + j
            )
    for i in range(n_qubits, size):
        gate.add_operation("X", targets=i, controls=i % n_qubits)
    return gate


def gen_q_corr_circuit(n_parties: int, n_qubits: int) -> Circuit:
    """``genQCorrCircuit`` (``tfg.py:43-52``)."""
    size = (n_parties + 1) * n_qubits
    return Circuit(size, "Q-Correlated Circuit").add_operation(
        q_correlated(n_parties, n_qubits)
    )


def gen_nq_corr_circuit(n_parties: int, n_qubits: int) -> Circuit:
    """``genNQCorrCircuit`` (``tfg.py:56-65``)."""
    size = (n_parties + 1) * n_qubits
    return Circuit(size, "Not Q-Correlated Circuit").add_operation(
        not_q_correlated(n_parties, n_qubits)
    )


def _perm_bits(perm: jnp.ndarray, n_qubits: int) -> jnp.ndarray:
    """Big-endian bits of each permutation entry: [n] -> [n * n_qubits]."""
    shifts = jnp.arange(n_qubits - 1, -1, -1, dtype=jnp.int32)
    return ((perm[:, None] >> shifts) & 1).reshape(-1).astype(jnp.int32)


def generate_lists_dense(cfg: QBAConfig, key: jax.Array, impl: str = "xla"):
    """Dense-path ``generacionListas`` (``tfg.py:68-84``), one Born sample
    per list position, all positions batched with ``vmap``.

    ``impl`` selects the circuit executor (:meth:`Circuit.compile`):
    ``"xla"``, ``"pallas"``, ``"pallas_interpret"``, ``"auto"`` (the
    fused Pallas kernel on TPU, interpreter mode elsewhere), or
    ``"stabilizer"`` (the Clifford tableau — the only executor that
    runs the joint circuits at the reference's real party counts; the
    dense impls cap at ~20 qubits).

    Returns ``(lists, qcorr)``: int32 ``[n_parties+1, size_l]`` decoded
    order values per party (row 0 = QSD extra copy, row 1 = commander),
    and the ground-truth Q-correlated position mask ``[size_l]``.
    """
    n, nq = cfg.n_parties, cfg.n_qubits
    if impl == "auto":
        # Resolve against the actual joint circuit: past the dense cap
        # a Clifford op list hands off to the stabilizer engine
        # (recorded via warn_and_record inside resolve_auto_impl)
        # instead of building a guaranteed-OOM statevector — and the
        # stabilizer resolution takes the *batched* GF(2) path, not a
        # per-position tableau vmap.
        impl = gen_q_corr_circuit(n, nq).resolve_auto_impl()
        if impl == "stabilizer":
            return generate_lists_stabilizer(cfg, key)
    # Imperfect resources (cfg.p_depolarize / cfg.p_measure_flip) apply
    # per position off that position's measurement key — compile() owns
    # the channel (classical reduction on the dense engines).
    run_q = gen_q_corr_circuit(n, nq).compile(
        impl, cfg.p_depolarize, cfg.p_measure_flip
    )
    run_nq = gen_nq_corr_circuit(n, nq).compile(
        impl, cfg.p_depolarize, cfg.p_measure_flip
    )

    k_qcorr, k_perm, k_meas = jax.random.split(key, 3)
    qcorr = jax.random.bernoulli(k_qcorr, 0.5, (cfg.size_l,))

    def one_position(k_p, k_m, is_q):
        perm = jax.random.permutation(k_p, jnp.arange(1, n + 1, dtype=jnp.int32))
        params = _perm_bits(perm, nq)
        # Both branches cost one small statevector each at validation sizes;
        # select keeps the program branch-free under vmap.
        bits_q = run_q(k_m, params)
        bits_nq = run_nq(k_m)
        return jnp.where(is_q, bits_q, bits_nq)

    perm_keys = jax.random.split(k_perm, cfg.size_l)
    meas_keys = jax.random.split(k_meas, cfg.size_l)
    bits = jax.vmap(one_position)(perm_keys, meas_keys, qcorr)  # [size_l, total_qubits]

    # Regroup to the reference's raw layout: party i's bits across positions
    # (tfg.py:81-82), then decode (tfg.py:128-129).
    per_party = bits.reshape(cfg.size_l, n + 1, nq).transpose(1, 0, 2)
    lists = measure_to_ints(per_party.reshape(n + 1, -1), cfg.size_l, nq)
    return lists, qcorr


def generate_lists_stabilizer(cfg: QBAConfig, key: jax.Array):
    """``generacionListas`` on the batched GF(2) symplectic engine — the
    primary resource path at reference scale (ROADMAP item 5).

    Both circuit families compile once into aggregate symplectic
    transforms (:mod:`qba_tpu.gf2.symplectic`), then the whole
    ``size_l`` position batch runs as a handful of batched GF(2)
    matmuls + one masked measurement sweep — no per-position circuit
    execution, no per-op column edits.  This is what makes 65-party
    (462-qubit), 129-party (1040-qubit) and 257-party (2322-qubit)
    scenarios runnable end to end.

    Key-tree and coin-draw discipline exactly mirror
    :func:`generate_lists_dense`: ``(k_qcorr, k_perm, k_meas)`` split,
    per-position permutation and measurement subkeys, both branches
    sharing the position's measurement key — so the outputs are
    **bit-identical** to ``generate_lists_dense(cfg, key,
    impl="stabilizer")`` (the per-position tableau reference) for the
    same key, at any party count where both can run.

    Returns ``(lists, qcorr)`` with the same layout as
    :func:`generate_lists_dense`.
    """
    from qba_tpu.gf2 import build_gf2_tableau_run_batch

    n, nq = cfg.n_parties, cfg.n_qubits
    total = (n + 1) * nq
    circ_q = gen_q_corr_circuit(n, nq)
    circ_nq = gen_nq_corr_circuit(n, nq)
    # Noise rides each position's measurement key (tableau-phase
    # injection — keeps the program Clifford; see qsim/noise.py), so
    # both stabilizer engines stay bit-identical under noise too.
    run_q = build_gf2_tableau_run_batch(
        total, tuple(circ_q.ops), circ_q.n_params,
        cfg.p_depolarize, cfg.p_measure_flip,
    )
    run_nq = build_gf2_tableau_run_batch(
        total, tuple(circ_nq.ops), 0,
        cfg.p_depolarize, cfg.p_measure_flip,
    )

    k_qcorr, k_perm, k_meas = jax.random.split(key, 3)
    qcorr = jax.random.bernoulli(k_qcorr, 0.5, (cfg.size_l,))

    perm_keys = jax.random.split(k_perm, cfg.size_l)
    meas_keys = jax.random.split(k_meas, cfg.size_l)
    perms = jax.vmap(
        lambda k: jax.random.permutation(k, jnp.arange(1, n + 1, dtype=jnp.int32))
    )(perm_keys)
    params = jax.vmap(_perm_bits, in_axes=(0, None))(perms, nq)  # [size_l, n*nq]

    # Both branches over the whole batch, sharing the per-position
    # measurement keys (same coins as the reference's shared k_m);
    # select keeps the program branch-free.
    bits_q = run_q(meas_keys, params)  # [size_l, total]
    bits_nq = run_nq(meas_keys)
    bits = jnp.where(qcorr[:, None], bits_q, bits_nq)

    per_party = bits.reshape(cfg.size_l, n + 1, nq).transpose(1, 0, 2)
    lists = measure_to_ints(per_party.reshape(n + 1, -1), cfg.size_l, nq)
    return lists, qcorr
