"""Imperfect quantum resources: depolarizing + readout-flip channels.

The reference assumes noiseless Clifford circuits and perfect
measurement (``tfg.py:15-84``).  This module adds the two standard
imperfections as *channels on the terminal measurement*:

* **Depolarizing** (``cfg.p_depolarize``): independently per qubit,
  with probability ``p`` a uniformly random Pauli (X, Y or Z) is
  applied immediately before measurement.
* **Measurement flip** (``cfg.p_measure_flip``): independently per
  qubit, the classical readout bit is flipped with probability ``q``.

Because every protocol circuit ends in a full computational-basis
measurement, the depolarizing channel has an exact classical
reduction: an X or Y error on qubit ``j`` flips outcome bit ``j``
(``P(X-component) = 2p/3``), a Z error is invisible.  The dense
statevector and factorized-sampler paths therefore apply
:func:`classical_flips` to the measured bits — *exactly* the channel,
not an approximation.  The stabilizer paths instead inject the drawn
Pauli into the tableau phase vector (:mod:`qba_tpu.qsim.stabilizer`,
:mod:`qba_tpu.gf2.symplectic`) — a phase-only edit, so the tableau
stays Clifford and the KI-3 / gf2 lint surface is untouched; the two
stabilizer engines share :func:`noise_draws` and remain bit-identical
to each other, while dense-vs-stabilizer equality under noise is
distributional (pinned statistically in tests/test_noise.py).

Draw discipline (shared by every path): the noise stream forks off the
*measurement* key via ``fold_in(key, _NOISE_TAG)`` with a fresh tag, so
zero-noise runs consume exactly the byte-identical key tree as before —
``p_depolarize = p_measure_flip = 0.0`` is bit-identical to current
outputs on every engine, and the noise branches are statically gated on
the Python floats (never traced at zero).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Fresh fold_in tag for the noise stream (disjoint from the adversary
# tags in qba_tpu.adversary.model and every split already in use).
_NOISE_TAG = 0x401E


def noise_draws(
    key: jax.Array,
    n: int,
    p_depolarize: float,
    p_measure_flip: float,
):
    """One shot's channel draws: ``(bx, bz, mflip)`` int32 ``[n]``.

    ``bx``/``bz`` are the X/Z components of the drawn Pauli (X -> (1,0),
    Y -> (1,1), Z -> (0,1), identity -> (0,0)); ``mflip`` the readout
    flips.  Both stabilizer engines consume these identically (their
    bit-identity contract extends to noisy runs)."""
    k_noise = jax.random.fold_in(key, _NOISE_TAG)
    kn_p, kn_k, kn_f = jax.random.split(k_noise, 3)
    pauli = jax.random.bernoulli(kn_p, p_depolarize, (n,))
    kind = jax.random.randint(kn_k, (n,), 0, 3, dtype=jnp.int32)
    bx = (pauli & (kind != 2)).astype(jnp.int32)  # X or Y
    bz = (pauli & (kind != 0)).astype(jnp.int32)  # Y or Z
    mflip = jax.random.bernoulli(
        kn_f, p_measure_flip, (n,)
    ).astype(jnp.int32)
    return bx, bz, mflip


def classical_flips(
    key: jax.Array,
    n: int,
    p_depolarize: float,
    p_measure_flip: float,
) -> jnp.ndarray:
    """The exact classical reduction for a terminal measurement:
    int32 ``[n]`` of outcome-bit flips (``bx ^ mflip`` — X/Y errors
    flip the readout, Z errors are invisible)."""
    bx, _bz, mflip = noise_draws(key, n, p_depolarize, p_measure_flip)
    return bx ^ mflip


def classical_flips_shots(
    key: jax.Array,
    shots: int,
    n: int,
    p_depolarize: float,
    p_measure_flip: float,
) -> jnp.ndarray:
    """Batched classical reduction for a multi-shot dense run: int32
    ``[shots, n]`` of outcome-bit flips, one independent channel per
    shot, drawn off the run key's noise fork (the dense engine prepares
    the state once and Born-samples the batch, so there is no per-shot
    subkey to fold into)."""
    k_noise = jax.random.fold_in(key, _NOISE_TAG)
    kn_p, kn_k, kn_f = jax.random.split(k_noise, 3)
    full = (shots, n)
    pauli = jax.random.bernoulli(kn_p, p_depolarize, full)
    kind = jax.random.randint(kn_k, full, 0, 3, dtype=jnp.int32)
    bx = (pauli & (kind != 2)).astype(jnp.int32)
    mflip = jax.random.bernoulli(
        kn_f, p_measure_flip, full
    ).astype(jnp.int32)
    return bx ^ mflip


def classical_flip_ints(
    key: jax.Array,
    shape: tuple[int, ...],
    n_qubits: int,
    p_depolarize: float,
    p_measure_flip: float,
) -> jnp.ndarray:
    """Batched classical flips packed as big-endian ``n_qubits``-bit
    integers: int32 ``[*shape]`` in ``[0, 2**n_qubits)`` — the XOR mask
    for decoded order values (the factorized sampler's layout, one
    independent channel per (group, position) qubit block)."""
    k_noise = jax.random.fold_in(key, _NOISE_TAG)
    kn_p, kn_k, kn_f = jax.random.split(k_noise, 3)
    full = (*shape, n_qubits)
    pauli = jax.random.bernoulli(kn_p, p_depolarize, full)
    kind = jax.random.randint(kn_k, full, 0, 3, dtype=jnp.int32)
    bx = (pauli & (kind != 2)).astype(jnp.int32)
    mflip = jax.random.bernoulli(
        kn_f, p_measure_flip, full
    ).astype(jnp.int32)
    flips = bx ^ mflip  # [*shape, n_qubits] 0/1, big-endian bit order
    shifts = jnp.arange(n_qubits - 1, -1, -1, dtype=jnp.int32)
    return jnp.sum(flips << shifts, axis=-1).astype(jnp.int32)
