"""Circuit and gate builder over the dense statevector kernels.

Covers the qsimov API surface the reference exercises (``tfg.py:17-21,
27-39,46-52,59-65,76-80``): named multi-qubit gates assembled from
primitive operations (``QGate`` + ``add_operation``), circuits that apply
gates and measure every qubit (``QCircuit`` + ``MEASURE``), and an executor
(``Drewom().execute``) returning measurement bits.

Idiomatic differences from qsimov: a :class:`Circuit` is a *static*
op list compiled once into a single jitted statevector program —
re-executing or ``vmap``-ing it costs no retracing; data-dependent gates
are expressed as parameterized ``XPOW`` ops reading a runtime param vector
instead of rebuilding the circuit per sample (the reference rebuilds the
Q-correlated circuit per list position, ``tfg.py:72-74``).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from qba_tpu.qsim import statevector as sv


# Fixed gates (no angle), rotation families (static angle), and the
# runtime-parameterized XPOW (the only data-dependent gate the protocol
# needs, tfg.py:30-37).  Controlled variants of all of them come from
# ``controls`` — CNOT = X + control, CZ = Z + control.
FIXED_GATES = ("H", "X", "Y", "Z", "S", "T")
ROTATION_GATES = ("RX", "RY", "RZ", "P")


@dataclasses.dataclass(frozen=True)
class Op:
    """One primitive operation (static description)."""

    kind: str  # one of FIXED_GATES | ROTATION_GATES | "XPOW"
    target: int
    controls: tuple[int, ...] = ()
    param: int | None = None  # index into the runtime param vector (XPOW)
    angle: float | None = None  # static angle (rotation gates only)


@dataclasses.dataclass
class Gate:
    """A named composite gate — the ``QGate`` equivalent."""

    n_qubits: int
    name: str = ""
    ops: list[Op] = dataclasses.field(default_factory=list)

    def add_operation(
        self,
        kind: str,
        *,
        targets: int,
        controls: int | tuple[int, ...] | None = None,
        param: int | None = None,
        angle: float | None = None,
    ) -> "Gate":
        if kind not in (*FIXED_GATES, *ROTATION_GATES, "XPOW"):
            raise ValueError(f"unsupported gate kind {kind!r}")
        if kind == "XPOW" and param is None:
            raise ValueError("XPOW requires a param index")
        if kind in ROTATION_GATES and angle is None:
            raise ValueError(f"{kind} requires an angle")
        if kind not in ROTATION_GATES and angle is not None:
            raise ValueError(f"{kind} takes no angle")
        ctrls: tuple[int, ...]
        if controls is None:
            ctrls = ()
        elif isinstance(controls, int):
            ctrls = (controls,)
        else:
            ctrls = tuple(controls)
        for q in (targets, *ctrls):
            if not 0 <= q < self.n_qubits:
                raise ValueError(f"qubit {q} out of range for {self.n_qubits}-qubit gate")
        if targets in ctrls:
            raise ValueError("target cannot also be a control")
        self.ops.append(Op(kind, targets, ctrls, param, angle))
        return self


@dataclasses.dataclass
class Circuit:
    """A ``QCircuit`` equivalent: gates + implicit full measurement."""

    n_qubits: int
    name: str = ""
    ops: list[Op] = dataclasses.field(default_factory=list)

    def add_operation(self, gate: Gate) -> "Circuit":
        if gate.n_qubits != self.n_qubits:
            raise ValueError(
                f"gate is {gate.n_qubits}-qubit, circuit is {self.n_qubits}-qubit"
            )
        self.ops.extend(gate.ops)
        return self

    @property
    def n_params(self) -> int:
        return max((op.param + 1 for op in self.ops if op.param is not None), default=0)

    def resolve_auto_impl(self) -> str:
        """Resolve ``impl="auto"`` to a concrete executor.

        At or under :data:`~qba_tpu.config.DENSE_QUBIT_CAP` qubits the
        dense fused kernel wins (Pallas on TPU, interpreter elsewhere).
        Past the cap a statevector cannot exist — 2**n amplitudes — so
        a Clifford op list hands off to the stabilizer tableau engine
        instead of building a guaranteed-OOM dense program; the handoff
        is recorded (``warn_and_record``) so run manifests capture the
        engine decision.  Non-Clifford past the cap is infeasible on
        every engine and raises.
        """
        from qba_tpu.config import DENSE_QUBIT_CAP

        if self.n_qubits <= DENSE_QUBIT_CAP:
            return "pallas" if jax.default_backend() == "tpu" else "pallas_interpret"
        from qba_tpu.qsim.stabilizer import is_clifford_ops

        if is_clifford_ops(self.ops):
            from qba_tpu.diagnostics import QBADemotionWarning, warn_and_record

            warn_and_record(
                f"{self.n_qubits}-qubit circuit exceeds the dense cap "
                f"({DENSE_QUBIT_CAP}); op list is Clifford — routing "
                "impl='auto' to the stabilizer tableau engine",
                QBADemotionWarning,
                site="qsim.circuit.resolve_auto_impl",
                engine_from="pallas",
                engine_to="stabilizer",
                reason="dense_qubit_cap",
                n_qubits=self.n_qubits,
                dense_qubit_cap=DENSE_QUBIT_CAP,
            )
            return "stabilizer"
        raise ValueError(
            f"{self.n_qubits}-qubit circuit exceeds the dense cap "
            f"({DENSE_QUBIT_CAP} qubits) and is outside the stabilizer "
            "engine's Clifford gate set — no executor can run it"
        )

    def compile_state(self, impl: str = "xla"):
        """Build ``state(params=None) -> final flat statevector [2**n]``.

        Contract shared by every impl: ``params=None`` means all-zero
        params (every X**b acts as identity), and the result is the flat
        amplitude vector in the same index order.  Dtypes differ — the
        engines are deliberately distinct:

        * ``"xla"`` — per-gate axis algebra, complex64.
        * ``"pallas"`` — the fused single-kernel executor
          (:func:`qba_tpu.ops.build_fused_circuit_run`): float32 when
          every gate in the circuit is real-valued (the protocol
          circuits; half the memory and FLOPs), complex64 via a dual
          real/imag state otherwise.
        * ``"pallas_interpret"`` — same kernel in interpreter mode (runs
          on any backend; used by the CPU test suite).
        """
        ops = tuple(self.ops)
        n = self.n_qubits
        n_params = self.n_params
        if impl == "stabilizer":
            raise ValueError(
                "the stabilizer engine has no statevector (that is the "
                "point: it runs circuits whose 2**n amplitudes cannot "
                "exist); use compile()/compile_shots(impl='stabilizer')"
            )
        if impl in ("pallas", "pallas_interpret"):
            from qba_tpu.ops import build_fused_circuit_run

            return build_fused_circuit_run(
                n, ops, n_params, interpret=impl == "pallas_interpret"
            )
        if impl != "xla":
            raise ValueError(f"unknown circuit impl {impl!r}")

        def state_fn(params: jnp.ndarray | None = None) -> jnp.ndarray:
            if params is None:
                params = jnp.zeros((max(n_params, 1),), dtype=jnp.int32)
            state = sv.init_state(n)
            for op in ops:
                if op.kind == "XPOW":
                    mat = sv.xpow_matrix(params[op.param])
                else:
                    mat = sv.gate_matrix(op.kind, op.angle)
                if op.controls:
                    state = sv.apply_controlled_1q(state, mat, op.target, op.controls)
                else:
                    state = sv.apply_1q(state, mat, op.target)
            return state.reshape(-1)

        return state_fn

    def compile(
        self,
        impl: str = "xla",
        p_depolarize: float = 0.0,
        p_measure_flip: float = 0.0,
    ):
        """Build ``run(key, params=None) -> int32 bits[n_qubits]``.

        The returned function is pure and jit/vmap-safe; measurement of
        every qubit (the reference's per-qubit MEASURE ops,
        ``tfg.py:49-51``) is one Born sample over the final state.

        ``impl="stabilizer"`` routes Clifford circuits to the tableau
        engine (:mod:`qba_tpu.qsim.stabilizer`) — identical contract,
        no qubit-count cap (the reference's 48-qubit 11-party joint
        circuit, ``tfg.py:76-80``, runs through here).
        ``impl="auto"`` picks per :meth:`resolve_auto_impl` — past the
        dense cap, Clifford circuits hand off to the stabilizer engine
        rather than OOM.

        Nonzero noise applies the channels of :mod:`qba_tpu.qsim.noise`
        — the dense path via the exact classical reduction on the
        measured bits, the stabilizer path via tableau-phase injection.
        """
        n = self.n_qubits
        if impl == "auto":
            impl = self.resolve_auto_impl()
        if impl == "stabilizer":
            from qba_tpu.qsim.stabilizer import build_tableau_run

            return build_tableau_run(
                n, tuple(self.ops), self.n_params,
                p_depolarize, p_measure_flip,
            )
        state_fn = self.compile_state(impl)
        noisy = p_depolarize > 0.0 or p_measure_flip > 0.0

        def run(key: jax.Array, params: jnp.ndarray | None = None) -> jnp.ndarray:
            state = state_fn(params)
            bits = sv.measure_all(state.reshape((2,) * n), key)
            if noisy:
                from qba_tpu.qsim.noise import classical_flips

                bits = bits ^ classical_flips(
                    key, n, p_depolarize, p_measure_flip
                )
            return bits

        return run

    def compile_shots(
        self,
        impl: str = "xla",
        p_depolarize: float = 0.0,
        p_measure_flip: float = 0.0,
    ):
        """Build ``run(key, shots, params=None) -> int32 bits[shots, n]``.

        Multi-shot batching: the statevector is prepared ONCE and only
        the Born sampling batches over shots (``shots`` must be static
        under jit).  On ``impl="stabilizer"`` the whole shot batch runs
        on the batched GF(2) engine (:mod:`qba_tpu.gf2.symplectic`):
        the static op list is compiled once into an aggregate
        symplectic transform and all shots advance together through a
        masked measurement sweep — bit-identical to the per-shot
        tableau (:func:`~qba_tpu.qsim.stabilizer.build_tableau_run_shots`,
        the differential reference) under identical keys.
        ``impl="auto"`` resolves per :meth:`resolve_auto_impl`.
        Noise follows the same split as :meth:`compile` (classical
        reduction on dense bits, phase injection on the GF(2) engine).
        """
        n = self.n_qubits
        if impl == "auto":
            impl = self.resolve_auto_impl()
        if impl == "stabilizer":
            from qba_tpu.gf2 import build_gf2_tableau_run_shots

            return build_gf2_tableau_run_shots(
                n, tuple(self.ops), self.n_params,
                p_depolarize, p_measure_flip,
            )
        state_fn = self.compile_state(impl)
        noisy = p_depolarize > 0.0 or p_measure_flip > 0.0

        def run(
            key: jax.Array, shots: int, params: jnp.ndarray | None = None
        ) -> jnp.ndarray:
            state = state_fn(params)
            bits = sv.measure_shots(state.reshape((2,) * n), key, shots)
            if noisy:
                from qba_tpu.qsim.noise import classical_flips_shots

                bits = bits ^ classical_flips_shots(
                    key, shots, n, p_depolarize, p_measure_flip
                )
            return bits

        return run
