"""Dense statevector kernels.

The state of an ``n``-qubit register is a complex array of shape
``(2,) * n`` — qubit ``q`` is axis ``q``, matching the reference's qubit
indexing where qubit 0 is the most significant measurement bit
(``tfg.py:81-82`` slices group ``i`` as bits ``i*nQubits..``).  Gate
application is axis algebra (tensordot + moveaxis), which XLA lowers to
fused transposes/matmuls; measurement is Born sampling over the flattened
amplitudes.

All functions are pure and jit/vmap-safe with static qubit indices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Single-qubit gate matrices — host (numpy) constants on purpose: a
# module-level ``jnp`` constant would be materialized on the default
# device at import time, and complex64 eager ops are unimplemented on
# some TPU runtimes (the axon tunnel), poisoning the async queue for the
# whole process.  As numpy values they are baked into jitted programs as
# literals and only touch the device inside compiled (validation-path)
# code.
_SQRT2 = np.sqrt(2.0).astype(np.float32)
H = np.asarray([[1.0, 1.0], [1.0, -1.0]], dtype=np.complex64) / _SQRT2
X = np.asarray([[0.0, 1.0], [1.0, 0.0]], dtype=np.complex64)
I2 = np.eye(2, dtype=np.complex64)

GATES = {"H": H, "X": X, "I": I2}


def init_state(n: int) -> jnp.ndarray:
    """|0...0> on ``n`` qubits."""
    state = jnp.zeros((2,) * n, dtype=jnp.complex64)
    return state.reshape(-1).at[0].set(1.0).reshape((2,) * n)


def apply_1q(state: jnp.ndarray, mat: jnp.ndarray, target: int) -> jnp.ndarray:
    """Apply a 2x2 ``mat`` to qubit ``target``."""
    moved = jnp.moveaxis(state, target, 0)
    out = jnp.tensordot(mat, moved, axes=([1], [0]))
    return jnp.moveaxis(out, 0, target)


def apply_controlled_1q(
    state: jnp.ndarray, mat: jnp.ndarray, target: int, controls: tuple[int, ...]
) -> jnp.ndarray:
    """Apply ``mat`` to ``target`` where all ``controls`` qubits are |1>."""
    if not controls:
        return apply_1q(state, mat, target)
    n = state.ndim
    ctrls = sorted(controls)
    # Move controls to the leading axes, target to the axis right after.
    rest = [q for q in range(n) if q not in ctrls and q != target]
    perm = ctrls + [target] + rest
    moved = jnp.transpose(state, perm)
    sub = moved[(1,) * len(ctrls)]  # controls all |1>, target is axis 0
    sub = jnp.tensordot(mat, sub, axes=([1], [0]))
    moved = moved.at[(1,) * len(ctrls)].set(sub)
    return jnp.transpose(moved, _inverse_permutation(perm))


def _inverse_permutation(perm: list[int]) -> list[int]:
    inv = [0] * len(perm)
    for i, p in enumerate(perm):
        inv[p] = i
    return inv


def measure_all(state: jnp.ndarray, key: jax.Array) -> jnp.ndarray:
    """Sample a computational-basis outcome for every qubit.

    Returns int32 bits ``[n]`` with qubit ``q`` at index ``q`` — the layout
    the reference's result slicing expects (``tfg.py:81-82``).
    """
    n = state.ndim
    probs = jnp.abs(state.reshape(-1)) ** 2
    idx = jax.random.categorical(key, jnp.log(probs))
    shifts = jnp.arange(n - 1, -1, -1, dtype=jnp.int32)
    return ((idx >> shifts) & 1).astype(jnp.int32)


def xpow_matrix(bit: jnp.ndarray) -> jnp.ndarray:
    """``X**bit`` for a traced 0/1 ``bit`` — I when 0, X when 1.

    Lets data-dependent X encodings (the reference regenerates the
    Q-correlated circuit per position with fresh ``rands``,
    ``tfg.py:30-37``) live inside one compiled program instead of
    rebuilding circuits.
    """
    b = jnp.asarray(bit, dtype=jnp.complex64)
    return I2 * (1 - b) + X * b
