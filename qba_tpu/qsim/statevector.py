"""Dense statevector kernels.

The state of an ``n``-qubit register is a complex array of shape
``(2,) * n`` — qubit ``q`` is axis ``q``, matching the reference's qubit
indexing where qubit 0 is the most significant measurement bit
(``tfg.py:81-82`` slices group ``i`` as bits ``i*nQubits..``).  Gate
application is axis algebra (tensordot + moveaxis), which XLA lowers to
fused transposes/matmuls; measurement is Born sampling over the flattened
amplitudes.

All functions are pure and jit/vmap-safe with static qubit indices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Single-qubit gate matrices — host (numpy) constants on purpose: a
# module-level ``jnp`` constant would be materialized on the default
# device at import time, and complex64 eager ops are unimplemented on
# some TPU runtimes (the axon tunnel), poisoning the async queue for the
# whole process.  As numpy values they are baked into jitted programs as
# literals and only touch the device inside compiled (validation-path)
# code.
_SQRT2 = np.sqrt(2.0).astype(np.float32)
H = np.asarray([[1.0, 1.0], [1.0, -1.0]], dtype=np.complex64) / _SQRT2
X = np.asarray([[0.0, 1.0], [1.0, 0.0]], dtype=np.complex64)
Y = np.asarray([[0.0, -1.0j], [1.0j, 0.0]], dtype=np.complex64)
Z = np.asarray([[1.0, 0.0], [0.0, -1.0]], dtype=np.complex64)
S = np.asarray([[1.0, 0.0], [0.0, 1.0j]], dtype=np.complex64)
T = np.asarray(
    [[1.0, 0.0], [0.0, np.exp(0.25j * np.pi)]], dtype=np.complex64
)
I2 = np.eye(2, dtype=np.complex64)

GATES = {"H": H, "X": X, "Y": Y, "Z": Z, "S": S, "T": T, "I": I2}

# Parameterized single-qubit families (static angle -> constant matrix).
_ROTATIONS = {
    "RX": lambda t: np.asarray(
        [
            [np.cos(t / 2), -1j * np.sin(t / 2)],
            [-1j * np.sin(t / 2), np.cos(t / 2)],
        ],
        dtype=np.complex64,
    ),
    "RY": lambda t: np.asarray(
        [
            [np.cos(t / 2), -np.sin(t / 2)],
            [np.sin(t / 2), np.cos(t / 2)],
        ],
        dtype=np.complex64,
    ),
    "RZ": lambda t: np.asarray(
        [[np.exp(-0.5j * t), 0.0], [0.0, np.exp(0.5j * t)]],
        dtype=np.complex64,
    ),
    "P": lambda t: np.asarray(
        [[1.0, 0.0], [0.0, np.exp(1j * t)]], dtype=np.complex64
    ),
}


def gate_matrix(kind: str, angle: float | None = None) -> np.ndarray:
    """Static 2x2 matrix for a gate kind.

    Fixed gates (H/X/Y/Z/S/T) take no angle; rotation families
    (RX/RY/RZ/P) require one.  CZ/CNOT/any controlled gate are expressed
    as the base gate plus ``controls`` at the circuit layer.  Runtime
    data-dependent gates stay with the XPOW param mechanism
    (``tfg.py:30-37``), which this function deliberately excludes.
    """
    if kind in GATES:
        if angle is not None:
            raise ValueError(f"gate {kind!r} takes no angle")
        return GATES[kind]
    if kind in _ROTATIONS:
        if angle is None:
            raise ValueError(f"gate {kind!r} requires an angle")
        return _ROTATIONS[kind](float(angle))
    raise ValueError(f"unknown gate kind {kind!r}")


def init_state(n: int) -> jnp.ndarray:
    """|0...0> on ``n`` qubits."""
    state = jnp.zeros((2,) * n, dtype=jnp.complex64)
    return state.reshape(-1).at[0].set(1.0).reshape((2,) * n)


def apply_1q(state: jnp.ndarray, mat: jnp.ndarray, target: int) -> jnp.ndarray:
    """Apply a 2x2 ``mat`` to qubit ``target``."""
    moved = jnp.moveaxis(state, target, 0)
    out = jnp.tensordot(mat, moved, axes=([1], [0]),
                        precision=jax.lax.Precision.HIGHEST)
    return jnp.moveaxis(out, 0, target)


def apply_controlled_1q(
    state: jnp.ndarray, mat: jnp.ndarray, target: int, controls: tuple[int, ...]
) -> jnp.ndarray:
    """Apply ``mat`` to ``target`` where all ``controls`` qubits are |1>."""
    if not controls:
        return apply_1q(state, mat, target)
    n = state.ndim
    ctrls = sorted(controls)
    # Move controls to the leading axes, target to the axis right after.
    rest = [q for q in range(n) if q not in ctrls and q != target]
    perm = ctrls + [target] + rest
    moved = jnp.transpose(state, perm)
    sub = moved[(1,) * len(ctrls)]  # controls all |1>, target is axis 0
    sub = jnp.tensordot(mat, sub, axes=([1], [0]),
                        precision=jax.lax.Precision.HIGHEST)
    moved = moved.at[(1,) * len(ctrls)].set(sub)
    return jnp.transpose(moved, _inverse_permutation(perm))


def _inverse_permutation(perm: list[int]) -> list[int]:
    inv = [0] * len(perm)
    for i, p in enumerate(perm):
        inv[p] = i
    return inv


def measure_all(state: jnp.ndarray, key: jax.Array) -> jnp.ndarray:
    """Sample a computational-basis outcome for every qubit.

    Returns int32 bits ``[n]`` with qubit ``q`` at index ``q`` — the layout
    the reference's result slicing expects (``tfg.py:81-82``).
    """
    n = state.ndim
    probs = jnp.abs(state.reshape(-1)) ** 2
    idx = jax.random.categorical(key, jnp.log(probs))
    shifts = jnp.arange(n - 1, -1, -1, dtype=jnp.int32)
    return ((idx >> shifts) & 1).astype(jnp.int32)


def measure_shots(state: jnp.ndarray, key: jax.Array, shots: int) -> jnp.ndarray:
    """``shots`` independent computational-basis samples from ONE state.

    Returns int32 bits ``[shots, n]``.  The state is prepared once and
    only the Born sampling batches — the multi-shot analog of qsimov's
    repeated ``Drewom`` executions without re-simulating the circuit.
    """
    n = state.ndim
    probs = jnp.abs(state.reshape(-1)) ** 2
    idx = jax.random.categorical(key, jnp.log(probs), shape=(shots,))
    shifts = jnp.arange(n - 1, -1, -1, dtype=jnp.int32)
    return ((idx[:, None] >> shifts[None, :]) & 1).astype(jnp.int32)


def xpow_matrix(bit: jnp.ndarray) -> jnp.ndarray:
    """``X**bit`` for a traced 0/1 ``bit`` — I when 0, X when 1.

    Lets data-dependent X encodings (the reference regenerates the
    Q-correlated circuit per position with fresh ``rands``,
    ``tfg.py:30-37``) live inside one compiled program instead of
    rebuilding circuits.
    """
    b = jnp.asarray(bit, dtype=jnp.complex64)
    return I2 * (1 - b) + X * b
