"""Vectorized stabilizer-tableau executor — the reference-scale circuit path.

The reference *executes* the joint ``(nParties+1)*nQubits``-qubit circuit
per list position through qsimov (``tfg.py:76-80``), demonstrably at 48
qubits for its 11-party demo (``logs tests/log_11.txt``).  The dense
statevector engine (:mod:`qba_tpu.qsim.statevector`) caps at ~20 qubits,
so until round 5 that scale was covered only by the factorized
closed-form sampler.  The protocol circuits are pure Clifford — H, X,
CNOT and the classically-parameterized ``X**b`` (``tfg.py:15-40``) — so
a stabilizer tableau (Aaronson & Gottesman, quant-ph/0406196) simulates
them *exactly* in O(n^2) space and polynomial time at any party count:
this module runs the reference's actual 48-qubit (and 204-qubit
33-party) constructions through the circuit API.

TPU-first design — this is NOT a port of the serial CHP algorithm:

* **XZ normal form, not CHP's Y-literal form.**  Each tableau row
  stores a Pauli as ``(-1)^r prod_j X^x_j Z^z_j``.  Under the gate set
  the protocol needs (H, X, Y, Z, CNOT, CZ, ``X**b``) this set is
  closed with phases in ±1 — multiplying two rows costs one GF(2)
  cross-parity ``parity(z_h . x_p)`` instead of CHP's mod-4
  ``i``-exponent bookkeeping (the ``g`` function).  The S/T gates,
  whose conjugations leave the form (``S: X -> iXZ``), are rejected
  with a pointer to the dense engine; the protocol never uses them.
* **Measurements are matmuls, not rowsum loops.**  The deterministic
  branch of a computational-basis measurement multiplies the selected
  (mutually commuting) stabilizer rows in one shot: the product's sign
  exponent is ``sum_i s_i r_i + sum_{a<b} (z_a . x_b)  (mod 2)`` — the
  strict upper triangle of one ``[n, n]`` integer matmul over the
  selected rows, which XLA tiles onto the MXU.  The random branch is
  one masked rank-1 GF(2) update of the whole tableau.  CHP's serial
  per-row rowsum never appears.
* **One compiled program.**  The circuit's op list is static (traced
  once); data-dependent X gates read a runtime param vector (``XPOW``,
  same mechanism as the dense path); the per-qubit measurement sweep is
  a ``lax.fori_loop``; everything jit/vmaps over list positions.

Row convention: rows ``0..n-1`` are destabilizers (initially ``X_i``),
rows ``n..2n-1`` stabilizers (initially ``Z_i``) — destabilizer phases
never influence outcomes (the deterministic branch multiplies stabilizer
rows only) but are carried for tableau validity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Gates whose conjugation action keeps rows inside {± prod X^x Z^z}.
# Derivations (per target qubit a, control c; updates act on every row):
#   H(a):      X<->Z            => r ^= x_a & z_a ; swap x_a, z_a
#   X(a):      Z -> -Z          => r ^= z_a
#   Y(a):      X -> -X, Z -> -Z => r ^= x_a ^ z_a
#   Z(a):      X -> -X          => r ^= x_a
#   CNOT(c,a): X_c -> X_c X_a, Z_a -> Z_c Z_a  => x_a ^= x_c ; z_c ^= z_a
#              (sign-free in XZ form: the reordering crosses commuting
#              factors only — unlike CHP's Y-literal rule)
#   CZ(c,a):   X_c -> X_c Z_a, X_a -> X_a Z_c  => z_a ^= x_c ; z_c ^= x_a ;
#              r ^= x_c & x_a   (one Z crosses one X on the same qubit)
#   X**b(a):   classically-controlled X        => r ^= b & z_a
CLIFFORD_FIXED = ("H", "X", "Y", "Z")


def is_clifford_ops(ops) -> bool:
    """True iff every op is representable by this engine (used by the
    ``Drewom`` auto engine chooser) — the same predicate
    :func:`_validate_ops` enforces, so the chooser and the engine can
    never disagree about the gate surface."""
    try:
        _validate_ops(ops)
    except ValueError:
        return False
    return True


def _apply_ops(ops, x, z, r, params):
    """Conjugate the whole tableau through the static op list.

    ``x``/``z``: int32 ``[2n, n]`` GF(2) matrices, ``r``: int32 ``[2n]``.
    Column indices are static (baked from the op list); only XPOW reads
    the traced ``params`` vector.
    """
    for op in ops:
        a = op.target
        if op.kind == "XPOW":
            b = params[op.param]
            r = r ^ (b & z[:, a])
        elif op.controls:
            (c,) = op.controls
            if op.kind == "X":  # CNOT control c -> target a
                x = x.at[:, a].set(x[:, a] ^ x[:, c])
                z = z.at[:, c].set(z[:, c] ^ z[:, a])
            else:  # CZ (symmetric in (c, a))
                r = r ^ (x[:, c] & x[:, a])
                zc = z[:, c] ^ x[:, a]
                z = z.at[:, a].set(z[:, a] ^ x[:, c])
                z = z.at[:, c].set(zc)
        elif op.kind == "H":
            r = r ^ (x[:, a] & z[:, a])
            xa = x[:, a]
            x = x.at[:, a].set(z[:, a])
            z = z.at[:, a].set(xa)
        elif op.kind == "X":
            r = r ^ z[:, a]
        elif op.kind == "Y":
            r = r ^ x[:, a] ^ z[:, a]
        else:  # "Z"
            r = r ^ x[:, a]
    return x, z, r


def _validate_ops(ops) -> None:
    for op in ops:
        if op.kind == "XPOW":
            if op.controls:
                raise ValueError("controlled XPOW is not supported")
            continue
        if op.kind not in CLIFFORD_FIXED:
            raise ValueError(
                f"gate {op.kind!r} is outside this engine's Clifford set "
                "(S/T/rotations change the XZ normal form); use the dense "
                "statevector engine for non-Clifford circuits"
            )
        if len(op.controls) > 1:
            raise ValueError(
                "multi-controlled gates are not Clifford; use the dense "
                "engine"
            )
        if op.controls and op.kind not in ("X", "Z"):
            raise ValueError(
                f"controlled-{op.kind} is not supported on the stabilizer "
                "engine (only CNOT/CZ); use the dense engine"
            )


def build_tableau_run(
    n: int,
    ops,
    n_params: int,
    p_depolarize: float = 0.0,
    p_measure_flip: float = 0.0,
):
    """Build ``run(key, params=None) -> int32 bits[n]`` on the tableau
    engine — same contract as :meth:`Circuit.compile`'s other impls:
    one computational-basis sample of every qubit, qubit ``q`` at index
    ``q`` (``tfg.py:81-82``'s slicing layout).

    The per-qubit measurement sweep consumes one pre-drawn uniform bit
    per qubit (used only when that qubit's outcome is random), so the
    whole program is a fixed-shape ``fori_loop`` — jit/vmap-safe.

    Nonzero ``p_depolarize``/``p_measure_flip`` inject the channels of
    :mod:`qba_tpu.qsim.noise`: the drawn Pauli conjugates the evolved
    tableau — a pure phase edit (``X(a): r ^= z_a``, ``Z(a): r ^= x_a``,
    Y both), so the tableau stays Clifford — and readout flips XOR the
    output bits.  Statically gated: at zero the traced program (and the
    key stream) is byte-identical to the noiseless build.
    """
    ops = tuple(ops)
    _validate_ops(ops)
    rows2n = jnp.arange(2 * n, dtype=jnp.int32)
    noisy = p_depolarize > 0.0 or p_measure_flip > 0.0

    def run(key: jax.Array, params: jnp.ndarray | None = None) -> jnp.ndarray:
        if params is None:
            params = jnp.zeros((max(n_params, 1),), dtype=jnp.int32)
        # |0..0>: destabilizers X_i, stabilizers Z_i, all phases +.
        eye = jnp.eye(n, dtype=jnp.int32)
        zero = jnp.zeros((n, n), dtype=jnp.int32)
        x = jnp.concatenate([eye, zero], axis=0)
        z = jnp.concatenate([zero, eye], axis=0)
        r = jnp.zeros((2 * n,), dtype=jnp.int32)

        x, z, r = _apply_ops(ops, x, z, r, params)

        mflip = None
        if noisy:
            from qba_tpu.qsim.noise import noise_draws

            bx, bz, mflip = noise_draws(
                key, n, p_depolarize, p_measure_flip
            )
            # Pauli conjugation of every row: phase-only in XZ form.
            r = r ^ ((z @ bx + x @ bz) & 1)

        rnds = (jax.random.bits(key, (n,), jnp.uint32) & 1).astype(jnp.int32)

        def measure_one(a, carry):
            x, z, r, out = carry
            xa = jnp.take(x, a, axis=1)  # [2n] — column a
            has_stab = jnp.any(xa[n:] == 1)

            def random_branch(x, z, r):
                # Some stabilizer anticommutes with Z_a: outcome is a
                # fresh coin; the tableau collapses onto it.
                p = n + jnp.argmax(xa[n:])  # first such stabilizer row
                xp = jnp.take(x, p, axis=0)  # [n]
                zp = jnp.take(z, p, axis=0)
                rp = jnp.take(r, p, axis=0)
                # Every other row with x_a = 1 absorbs row p (GF(2)
                # rank-1 update); its sign picks up the cross parity
                # z_h . x_p of the Z-past-X reorder.
                mask = xa * jnp.where(rows2n == p, 0, 1)  # [2n] 0/1
                cross = (z @ xp) & 1  # [2n]
                r = r ^ (mask & (rp ^ cross))
                x = x ^ (mask[:, None] * xp[None, :])
                z = z ^ (mask[:, None] * zp[None, :])
                # Row p retires to the destabilizer bank; the new
                # stabilizer is (+/-) Z_a with the coin as its sign.
                e_a = (jnp.arange(n, dtype=jnp.int32) == a).astype(jnp.int32)
                rnd = rnds[a]
                is_dst = (rows2n == p - n)[:, None]
                is_p = (rows2n == p)[:, None]
                x = jnp.where(is_dst, xp[None, :], x)
                x = jnp.where(is_p, 0, x)
                z = jnp.where(is_dst, zp[None, :], z)
                z = jnp.where(is_p, e_a[None, :], z)
                r = jnp.where(rows2n == p - n, rp, r)
                r = jnp.where(rows2n == p, rnd, r)
                return x, z, r, rnd

            def det_branch(x, z, r):
                # Z_a is in the stabilizer group: the outcome is the
                # sign of prod_{i: destab_i has x_a=1} stab_i.  Those
                # rows commute pairwise, so the product's sign exponent
                # is  sum_i s_i r_i  +  sum_{a<b} (z_{k_a} . x_{k_b})
                # (mod 2) — the strict upper triangle of one [n, n]
                # matmul over the selected rows (MXU-shaped), not a
                # serial rowsum accumulation.
                s = xa[:n]  # [n] 0/1 selectors
                xs = s[:, None] * x[n:]
                zs = s[:, None] * z[n:]
                m = zs @ xs.T  # [n, n] cross counts
                upper = jnp.sum(jnp.triu(m, k=1))
                outcome = (jnp.sum(s * r[n:]) + upper) & 1
                return x, z, r, outcome

            x, z, r, bit = jax.lax.cond(
                has_stab, random_branch, det_branch, x, z, r
            )
            out = out.at[a].set(bit)
            return x, z, r, out

        out0 = jnp.zeros((n,), dtype=jnp.int32)
        _, _, _, out = jax.lax.fori_loop(
            0, n, measure_one, (x, z, r, out0)
        )
        if mflip is not None:
            out = out ^ mflip
        return out

    return run


def build_tableau_run_shots(
    n: int,
    ops,
    n_params: int,
    p_depolarize: float = 0.0,
    p_measure_flip: float = 0.0,
):
    """``run(key, shots, params=None) -> int32 bits[shots, n]``.

    Unlike the dense engine (state prepared once, Born sampling
    batched), measurement collapses a tableau — each shot is an
    independent vmapped tableau run.  Tableau prep is O(n^2) per shot,
    which is the cheap part at any scale this engine targets.
    """
    run1 = build_tableau_run(n, ops, n_params, p_depolarize, p_measure_flip)

    def run(
        key: jax.Array, shots: int, params: jnp.ndarray | None = None
    ) -> jnp.ndarray:
        keys = jax.random.split(key, shots)
        if params is None:
            return jax.vmap(lambda k: run1(k))(keys)
        return jax.vmap(lambda k: run1(k, params))(keys)

    return run
