"""qsimov-shaped API shim — drop-in call signatures for reference users.

The reference drives its quantum engine through qsimov's object API:
``qs.QGate(size, 0, name)`` + ``add_operation("H"/"X", targets=,
controls=)`` (``tfg.py:17-21,27-39``), ``qs.QCircuit(size, size, name)``
+ ``add_operation(gate)`` / ``add_operation("MEASURE", targets=i,
outputs=i)`` (``tfg.py:46-52,59-65``), and ``qs.Drewom().execute(circ)[0]
-> list[int]`` (``tfg.py:76-80``).  This module provides the same three
names with the same call shapes so that reference-style construction code
ports verbatim, executing on the framework's compiled statevector engine.

Migration notes (idiomatic differences, not API differences):

* Execution is jitted; :class:`Drewom` caches compiled programs keyed by
  circuit *structure*, so re-executing the same circuit costs no
  recompilation.  Code that rebuilds a structurally different circuit per
  sample (the reference's per-position Q-correlated rebuild with fresh X
  placements, ``tfg.py:72-74``) recompiles per structure — for hot loops
  use the parameterized circuits in
  :mod:`qba_tpu.qsim.protocol_circuits`, which bake the data dependence
  into a runtime param vector instead.
* Randomness is explicit: ``Drewom(seed=...)`` owns a threefry key and
  advances it per ``execute`` call (the reference relies on qsimov's
  hidden global RNG).
"""

from __future__ import annotations

import jax

from qba_tpu.config import DENSE_QUBIT_CAP
from qba_tpu.qsim.circuit import Circuit, Gate


class QGate:
    """qsimov-shaped composite gate: ``QGate(size, ancilla, name)``."""

    def __init__(self, size: int, ancilla: int = 0, name: str = ""):
        if ancilla:
            raise ValueError("ancilla qubits are not supported (the "
                             "reference always passes 0, tfg.py:17,27)")
        self._gate = Gate(size, name)

    @property
    def name(self) -> str:
        return self._gate.name

    def add_operation(
        self, kind, *, targets, controls=None, outputs=None, angle=None
    ):
        if outputs is not None:
            raise ValueError("outputs= only applies to MEASURE ops on a "
                             "QCircuit")
        self._gate.add_operation(
            kind, targets=targets, controls=controls, angle=angle
        )
        return self


class QCircuit:
    """qsimov-shaped circuit: ``QCircuit(size, measured, name)``.

    ``add_operation`` accepts a :class:`QGate`, a primitive gate name, or
    ``"MEASURE"`` with ``targets=``/``outputs=`` (the reference measures
    every qubit with ``outputs=i``, ``tfg.py:49-51``).
    """

    def __init__(self, size: int, measured: int = 0, name: str = ""):
        self._circ = Circuit(size, name)
        # outputs slot -> measured qubit; populated by MEASURE ops.
        self._outputs: dict[int, int] = {}

    @property
    def name(self) -> str:
        return self._circ.name

    @property
    def n_qubits(self) -> int:
        return self._circ.n_qubits

    def add_operation(
        self, op, *, targets=None, controls=None, outputs=None, angle=None
    ):
        if op == "MEASURE":
            if targets is None:
                raise ValueError("MEASURE requires targets=")
            slot = targets if outputs is None else outputs
            if slot in self._outputs:
                raise ValueError(f"output slot {slot} measured twice")
            self._outputs[slot] = targets
            return self
        # Measurement here is one final Born sample (the only pattern the
        # reference uses: all MEASUREs last, tfg.py:49-51); a gate after a
        # MEASURE would need mid-circuit collapse semantics — reject it
        # rather than silently reorder.
        if self._outputs:
            raise ValueError(
                "gates after MEASURE are not supported (measurement is a "
                "single final Born sample; add all gates first)"
            )
        if isinstance(op, QGate):
            self._circ.add_operation(op._gate)
            return self
        if targets is None:
            raise ValueError(f"gate {op!r} requires targets=")
        self._circ.add_operation(
            Gate(self._circ.n_qubits).add_operation(
                op, targets=targets, controls=controls, angle=angle
            )
        )
        return self

    def _measure_order(self) -> tuple[int, ...]:
        """Measured qubits in output-slot order; default = all qubits
        (the only pattern the reference uses)."""
        if not self._outputs:
            return tuple(range(self._circ.n_qubits))
        return tuple(q for _, q in sorted(self._outputs.items()))

    def _structure(self):
        # Compiled program depends only on the ops — the output-slot
        # ordering is applied host-side, so it stays out of the cache key.
        return (self._circ.n_qubits, tuple(self._circ.ops))


class Drewom:
    """qsimov-shaped executor: ``Drewom().execute(circuit)`` returns a
    list of shot results, each the measured bits in output-slot order —
    ``execute(circ)[0]`` is the reference's usage (``tfg.py:76-80``).

    ``engine`` selects the simulator: ``"auto"`` (default) runs the
    dense statevector up to 20 qubits and switches to the stabilizer
    tableau (:mod:`qba_tpu.qsim.stabilizer`) beyond — so the
    reference's 48-qubit 11-party joint circuit executes through the
    same three-line call it uses with qsimov.  ``"dense"`` /
    ``"stabilizer"`` force one engine (the stabilizer engine rejects
    non-Clifford gates with a ValueError).
    """

    def __init__(self, seed: int = 0, engine: str = "auto"):
        if engine not in ("auto", "dense", "stabilizer"):
            raise ValueError(f"unknown Drewom engine {engine!r}")
        self._key = jax.random.key(seed)
        self._engine = engine
        self._programs: dict = {}

    def _impl_for(self, circuit: QCircuit) -> str:
        if self._engine == "dense":
            return "xla"
        if self._engine == "stabilizer":
            return "stabilizer"
        if circuit.n_qubits <= DENSE_QUBIT_CAP:
            return "xla"
        from qba_tpu.qsim.stabilizer import is_clifford_ops

        if is_clifford_ops(circuit._circ.ops):
            return "stabilizer"
        raise ValueError(
            f"{circuit.n_qubits}-qubit circuit outside the stabilizer "
            "engine's gate set (S/T/rotations/multi-control change the "
            f"XZ normal form), and the dense engine caps at "
            f"{DENSE_QUBIT_CAP} qubits"
        )

    def execute(self, circuit: QCircuit, shots: int = 1) -> list[list[int]]:
        if not isinstance(circuit, QCircuit):
            raise TypeError("Drewom.execute expects a QCircuit")
        impl = self._impl_for(circuit)
        struct = (impl,) + circuit._structure()
        run = self._programs.get(struct)
        if run is None:
            # Multi-shot batching: dense prepares the state once and
            # batches only the Born sampling; stabilizer vmaps whole
            # tableau runs (compile_shots on either impl).
            run = jax.jit(
                circuit._circ.compile_shots(impl), static_argnums=1
            )
            self._programs[struct] = run
        self._key, k = jax.random.split(self._key)
        # One dispatch + one host transfer for all shots.
        bits = jax.device_get(run(k, shots))
        order = list(circuit._measure_order())
        return [[int(b) for b in row[order]] for row in bits]
