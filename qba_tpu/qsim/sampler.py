"""Factorized closed-form sampler — the production quantum path.

The reference's joint circuits are Clifford with a product/low-rank
structure whose measurement distribution has an exact closed form
(SURVEY §2.6, derived from ``tfg.py:15-40``):

* not-Q-correlated position: groups 1..nParties i.i.d. uniform on
  ``[0, w)``; group 0 equals group 1 (the CNOT copy acts on |0> targets).
* Q-correlated position: ``r ~ U[0, w)`` from the group-0 Hadamards; group
  ``i`` measures ``r XOR rands[i-1]`` where ``rands`` is a fresh uniform
  permutation of ``1..nParties`` — pairwise distinct across parties and
  never equal to ``r``.

Sampling that distribution directly is exactly equivalent to simulating
and measuring the circuits — but costs O(nParties * sizeL) instead of
O(2^((nParties+1) nQubits)) per position, so it scales to any party count
(the reference's 48-qubit joint circuits at nParties=11 are far beyond any
dense engine).  Equivalence is cross-validated statistically against the
dense path in tests/test_qsim.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from qba_tpu.config import QBAConfig


def generate_lists(cfg: QBAConfig, key: jax.Array):
    """Sample all parties' lists for one trial.

    Returns ``(lists, qcorr)``: int32 ``[n_parties+1, size_l]`` (row 0 =
    QSD extra copy, row 1 = commander, matching the send order of
    ``tfg.py:142-149``) and the Q-correlated position mask ``[size_l]``
    (``tfg.py:69``).
    """
    n, w, s = cfg.n_parties, cfg.w, cfg.size_l
    # Value-range invariant (ADVICE r4): every list value this sampler
    # emits must lie in [0, w).  The XLA engine's popcount-collision and
    # MXU dup identities (rounds/engine.py) are exact ONLY on that
    # range, and this sampler is where evidence values are born.  The
    # XOR path below stays closed under [0, w) iff w is a power of two
    # (it is, by construction: w = 2**n_qubits) AND every perm value
    # fits in n_qubits bits (perms <= n_parties < 2**n_qubits = w).
    if w & (w - 1) != 0 or n >= w:  # survives -O, unlike assert
        raise ValueError(
            f"sampler range invariant broken: w={w} must be a power of "
            f"two > n_parties={n}; engine verdict identities assume "
            "vals in [0, w)"
        )
    k_qcorr, k_r, k_perm, k_u = jax.random.split(key, 4)

    qcorr = jax.random.bernoulli(k_qcorr, 0.5, (s,))

    # Q-correlated: r per position, fresh permutation per position.
    # The permutation is the argsort of n i.i.d. uint32 draws — the same
    # sort-based construction jax.random.permutation uses internally, but
    # as ONE batched draw + sort for all positions instead of a
    # per-position key-split + shuffle chain (which dominated the setup
    # phase under vmap over trials: size_l * trials threefry derivations).
    # Tie probability per position is ~n^2 / 2^33 (< 2^-25 at n=33) with
    # deterministic resolution — a uniformity bias orders of magnitude
    # below statistical detectability.
    r = jax.random.randint(k_r, (s,), 0, w, dtype=jnp.int32)
    noise = jax.random.bits(k_perm, (s, n), jnp.uint32)
    perms = jnp.argsort(noise, axis=-1).astype(jnp.int32) + 1  # [s, n] of 1..n
    rows_q = jnp.concatenate([r[None, :], r[None, :] ^ perms.T], axis=0)

    # Not-Q-correlated: groups 1..n i.i.d. uniform; group 0 copies group 1.
    u = jax.random.randint(k_u, (n, s), 0, w, dtype=jnp.int32)
    rows_nq = jnp.concatenate([u[0:1], u], axis=0)

    lists = jnp.where(qcorr[None, :], rows_q, rows_nq)
    if cfg.p_depolarize > 0.0 or cfg.p_measure_flip > 0.0:
        # Imperfect resources (qsim/noise.py): the exact classical
        # reduction of per-qubit depolarizing + readout flip on a
        # terminal measurement — one independent channel per
        # (group, position) qubit block, XORed into the decoded values
        # (closed under [0, w): flip ints < 2**n_qubits = w).  The
        # noise stream forks off a fresh fold_in tag, so the zero-noise
        # draws above are byte-identical to the noiseless sampler —
        # and the branch is statically gated (never traced at zero).
        from qba_tpu.qsim.noise import classical_flip_ints

        lists = lists ^ classical_flip_ints(
            key, (n + 1, s), cfg.n_qubits,
            cfg.p_depolarize, cfg.p_measure_flip,
        )
    return lists, qcorr
