"""TPU quantum engine — replaces the reference's external qsimov/doki stack.

The reference simulates every list position with a joint
``(nParties+1)*nQubits``-qubit circuit through the native qsimov engine
(``tfg.py:4,43-84``).  Here (SURVEY §7.2):

* :mod:`qba_tpu.qsim.statevector` — dense statevector kernels in
  ``jax.numpy``: gate application by axis algebra, measurement by Born
  sampling.  General path, feasible to ~20 qubits; used for validation.
* :mod:`qba_tpu.qsim.circuit` — a circuit/gate builder covering the qsimov
  API surface the reference uses (H, X, controlled-X, full measurement),
  compiled to one jitted statevector program.
* :mod:`qba_tpu.qsim.protocol_circuits` — the protocol's two circuit
  families (``notQCorrelated``/``qCorrelated``, ``tfg.py:15-65``) on the
  dense engine.
* :mod:`qba_tpu.qsim.sampler` — the factorized closed-form sampler
  (SURVEY §2.6): the exact output distribution of those Clifford circuits,
  sampled directly; scales to any ``nParties`` and is the production path.
* :mod:`qba_tpu.qsim.stabilizer` — vectorized Clifford-tableau executor:
  runs the *actual* joint circuits at the reference's real scale (48
  qubits at 11 parties, ``tfg.py:76-80``; 204 at 33) where no
  statevector can exist — the circuit-API path for ``qsim_path=
  "stabilizer"`` and ``Drewom``'s beyond-20-qubit auto engine.
"""

from qba_tpu.qsim.circuit import Circuit, Gate
from qba_tpu.qsim.sampler import generate_lists
from qba_tpu.qsim.protocol_circuits import (
    generate_lists_dense,
    generate_lists_stabilizer,
    not_q_correlated,
    q_correlated,
)


def generate_lists_for(cfg, key):
    """Dispatch list generation on ``cfg.qsim_path`` — the single chooser
    shared by all three protocol backends (jax / local / native), so the
    key tree stays identical across them.

    ``"stabilizer"`` takes the batched GF(2) symplectic path
    (:func:`~qba_tpu.qsim.protocol_circuits.generate_lists_stabilizer`)
    — bit-identical to the per-position tableau reference under the
    same key, and the only path that reaches 65/129/257-party scale.
    """
    if cfg.qsim_path == "factorized":
        return generate_lists(cfg, key)
    if cfg.qsim_path == "stabilizer":
        return generate_lists_stabilizer(cfg, key)
    if cfg.qsim_path == "dense_pallas":
        impl = "auto"
    else:
        impl = "xla"
    return generate_lists_dense(cfg, key, impl)


from qba_tpu.qsim.compat import Drewom, QCircuit, QGate

__all__ = [
    "Circuit",
    "Drewom",
    "Gate",
    "QCircuit",
    "QGate",
    "generate_lists",
    "generate_lists_dense",
    "generate_lists_for",
    "generate_lists_stabilizer",
    "not_q_correlated",
    "q_correlated",
]
