"""Aggregate symplectic compilation + the batched stabilizer sampler.

The per-shot tableau engine (:mod:`qba_tpu.qsim.stabilizer`) walks the
op list column by column for every shot.  But conjugation by a Clifford
circuit is *linear* on Pauli (x|z) vectors over GF(2): the whole static
op list collapses, once at build time, into

* a ``2n x 2n`` symplectic matrix ``M`` (row ``i`` of the evolved
  tableau = row ``i`` of ``M``, because the initial tableau IS the
  identity),
* a phase vector ``r0[2n]`` (the quadratic phase form evaluated on the
  identity rows), and
* a param-linear phase matrix ``L[2n, P]`` — each ``X**b`` op
  contributes ``r ^= b & z_a(current)``, and the *current* ``z_a`` is a
  known linear functional of the initial row at compile time.

Circuit application for a whole ``(trials x size_l)`` shot batch is
then a handful of batched GF(2) matmuls: the per-position phases are
``r = r0 ^ (params @ L^T mod 2)`` — one K-tiled MXU dot over the entire
batch (:func:`qba_tpu.gf2.linalg.gf2_matmul`) — and the packed rows of
``M`` are broadcast as the shared initial state.  Per-op ``.at[:, a]``
column edits never execute at runtime.

The measurement sweep stays a per-qubit ``fori_loop`` (measurement
collapse is inherently sequential in the qubit index) but runs the
whole shot batch per step with *masked* GF(2) updates — the per-shot
``lax.cond`` divergence of the reference engine becomes one
``has_stab`` select per step:

* random branch: pivot by batched argmax, cross parity by packed
  popcount, collapse by one batched rank-1 XOR update
  (:func:`~qba_tpu.gf2.linalg.rank1_update_packed`);
* deterministic branch: sign by the triangular-parity reduction
  (:func:`~qba_tpu.gf2.linalg.triangular_parity`) — O(n * W) per shot
  instead of the per-shot engine's ``[n, n]`` cross matmul.

Bit-identity with the per-shot engine under identical keys is a hard
contract (tests/test_gf2.py): the key tree (``split(key, shots)``), the
coin draws (``random.bits(key, (n,), uint32) & 1``), the pivot choice
(first anticommuting stabilizer), and the mod-2 algebra all match the
reference engine exactly.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from qba_tpu.gf2.bitops import (
    get_bit,
    mask_words,
    pack_bits,
    parity_words,
    unit_words,
)
from qba_tpu.gf2.linalg import gf2_matmul, rank1_update_packed, triangular_parity


@dataclasses.dataclass(frozen=True)
class SymplecticProgram:
    """One static Clifford op list, compiled (host-side, exact GF(2)
    arithmetic in numpy) to its aggregate action on the standard
    initial tableau."""

    n: int
    x: np.ndarray   # [2n, n] 0/1 — evolved X bits (rows of M, X half)
    z: np.ndarray   # [2n, n] 0/1 — evolved Z bits (rows of M, Z half)
    r: np.ndarray   # [2n] 0/1  — phases at params = 0
    l: np.ndarray   # [2n, P]  — phase coefficient of each runtime param


def compile_symplectic(n: int, ops, n_params: int) -> SymplecticProgram:
    """Fold the static op list into one symplectic transform + phase
    data by pushing the identity tableau through the gate rules of
    :mod:`qba_tpu.qsim.stabilizer` (same XZ-normal-form derivations) —
    with the XPOW phase contribution kept *symbolic* in the params:
    at the moment ``X**b(a)`` executes, ``r ^= b & z_a`` reads the
    current ``z`` column, which is a compile-time-known GF(2) vector,
    so the whole contribution is the linear form ``L @ params``."""
    from qba_tpu.qsim.stabilizer import _validate_ops

    ops = tuple(ops)
    _validate_ops(ops)
    x = np.concatenate(
        [np.eye(n, dtype=np.int32), np.zeros((n, n), np.int32)], axis=0
    )
    z = np.concatenate(
        [np.zeros((n, n), np.int32), np.eye(n, dtype=np.int32)], axis=0
    )
    r = np.zeros((2 * n,), np.int32)
    l = np.zeros((2 * n, max(n_params, 1)), np.int32)
    for op in ops:
        a = op.target
        if op.kind == "XPOW":
            l[:, op.param] ^= z[:, a]
        elif op.controls:
            (c,) = op.controls
            if op.kind == "X":  # CNOT c -> a
                x[:, a] ^= x[:, c]
                z[:, c] ^= z[:, a]
            else:  # CZ
                r ^= x[:, c] & x[:, a]
                zc = z[:, c] ^ x[:, a]
                z[:, a] ^= x[:, c]
                z[:, c] = zc
        elif op.kind == "H":
            r ^= x[:, a] & z[:, a]
            x[:, a], z[:, a] = z[:, a].copy(), x[:, a].copy()
        elif op.kind == "X":
            r ^= z[:, a]
        elif op.kind == "Y":
            r ^= x[:, a] ^ z[:, a]
        else:  # "Z"
            r ^= x[:, a]
    return SymplecticProgram(n=n, x=x, z=z, r=r, l=l)


def gf2_measure_sweep(
    n: int,
    xw: jnp.ndarray,
    zw: jnp.ndarray,
    r: jnp.ndarray,
    rnds: jnp.ndarray,
) -> jnp.ndarray:
    """The batched measurement sweep on an evolved packed tableau:
    ``(xw[B, 2n, W], zw[B, 2n, W], r[B, 2n], rnds[B, n]) -> bits[B, n]``.

    ``rnds`` are pre-drawn int32 {0, 1} coins (consumed only where the
    outcome is random); ``r`` carries the per-shot phases with any
    param/noise contribution already folded in.  This is THE sweep —
    shared verbatim by the host sampler core
    (:func:`build_gf2_sample_core`) and the trial megakernel's in-VMEM
    generation prologue (:mod:`qba_tpu.ops.trial_megakernel`), so the
    two generation paths are bit-identical *by construction*, not by
    test luck.

    Every step is written in the Pallas-safe subset — 2-D
    ``broadcasted_iota``, one-hot ``where``-selects instead of
    ``take``/``take_along_axis``/``argmax``, masked writes instead of
    ``.at[].set`` — in formulations value-identical to the gather
    originals:

    * pivot: ``min(where(stab_xa == 1, col, n))`` equals
      ``argmax(stab_xa)`` whenever a stabilizer anticommutes; rows
      without one get the out-of-range pivot ``2n``, whose every
      dependent value is discarded by the ``has_stab`` merge selects;
    * row gathers (``xp``/``zp``/``rp``, the coin, the measured-bit
      write): one-hot row masks summed/selected along the row axis —
      exact, since exactly one (or zero, discarded) row is selected.
    """
    b = rnds.shape[0]
    nw = xw.shape[-1]
    iota_n = jax.lax.broadcasted_iota(jnp.int32, (b, n), 1)
    rows2n = jax.lax.broadcasted_iota(jnp.int32, (b, 2 * n), 1)
    iota_w = jax.lax.broadcasted_iota(jnp.int32, (1, nw), 1)
    u0 = jnp.asarray(0, jnp.uint32)

    def measure_one(a, carry):
        xw, zw, r, out = carry
        # get_bit without the word gather: one-hot word select.
        wsel = iota_w == (a >> 5)                     # [1, W]
        shift = (a & 31).astype(jnp.uint32)
        word = jnp.sum(jnp.where(wsel[None], xw, u0), axis=-1)
        xa = ((word >> shift) & 1).astype(jnp.int32)  # [B, 2n]
        stab_xa = xa[:, n:]
        has_stab = jnp.any(stab_xa == 1, axis=1)      # [B]
        # -- random branch (masked; discarded where deterministic) --
        first = jnp.min(jnp.where(stab_xa == 1, iota_n, n), axis=1)
        p = n + first                                 # first pivot [B]
        sel = rows2n == p[:, None]                    # [B, 2n] one-hot
        xp = jnp.sum(jnp.where(sel[..., None], xw, u0), axis=1)
        zp = jnp.sum(jnp.where(sel[..., None], zw, u0), axis=1)
        rp = jnp.sum(jnp.where(sel, r, 0), axis=1)    # [B]
        # Cross parity z_h . x_p per row — packed popcount, no dot.
        cross = parity_words(zw & xp[:, None, :], axis=-1)  # [B, 2n]
        mask_o = xa * (1 - sel.astype(jnp.int32))     # [B, 2n]
        r_rand = r ^ (mask_o & (rp[:, None] ^ cross))
        x_rand = rank1_update_packed(xw, mask_o, xp)
        z_rand = rank1_update_packed(zw, mask_o, zp)
        # Row surgery: pivot retires to the destabilizer bank; the
        # new stabilizer is (+/-) Z_a signed by the coin.
        rnd = jnp.sum(jnp.where(iota_n == a, rnds, 0), axis=1)  # [B]
        e_a = jnp.where(wsel, jnp.asarray(1, jnp.uint32) << shift, u0)
        is_dst = rows2n == (p - n)[:, None]           # [B, 2n]
        is_p = sel
        x_rand = jnp.where(is_dst[..., None], xp[:, None, :], x_rand)
        x_rand = jnp.where(is_p[..., None], u0, x_rand)
        z_rand = jnp.where(is_dst[..., None], zp[:, None, :], z_rand)
        z_rand = jnp.where(is_p[..., None], e_a[None], z_rand)
        r_rand = jnp.where(is_dst, rp[:, None], r_rand)
        r_rand = jnp.where(is_p, rnd[:, None], r_rand)
        # -- deterministic branch (reads state, never writes) --
        s = xa[:, :n]                                 # [B, n]
        phase_par = jnp.sum(s * r[:, n:], axis=1) & 1
        sm = mask_words(s)[..., None]                 # [B, n, 1]
        tri = triangular_parity(sm & zw[:, n:, :], sm & xw[:, n:, :])
        det_out = phase_par ^ tri
        # -- merge: one select per step replaces per-shot cond --
        xw = jnp.where(has_stab[:, None, None], x_rand, xw)
        zw = jnp.where(has_stab[:, None, None], z_rand, zw)
        r = jnp.where(has_stab[:, None], r_rand, r)
        bit = jnp.where(has_stab, rnd, det_out)
        out = jnp.where(iota_n == a, bit[:, None], out)
        return xw, zw, r, out

    out0 = jnp.zeros((b, n), dtype=jnp.int32)
    _, _, _, out = jax.lax.fori_loop(
        0, n, measure_one, (xw, zw, r, out0)
    )
    return out


def build_gf2_sample_core(n: int, ops, n_params: int):
    """Build the pure batched sampler core:
    ``sample(rnds[B, n], params[B, P] | None) -> int32 bits[B, n]``.

    ``rnds`` are the pre-drawn measurement coins (one per qubit per
    shot, only consumed where the outcome is random — the same contract
    as the per-shot engine).  No PRNG inside: this is the callable the
    ``qba-tpu lint`` gf2 path traces (:mod:`qba_tpu.analysis.traces`),
    so every GF(2) dot it contains is interval-checked from BOOL seeds.
    """
    prog = compile_symplectic(n, ops, n_params)
    x0w = jnp.asarray(pack_bits(jnp.asarray(prog.x)))   # [2n, W]
    z0w = jnp.asarray(pack_bits(jnp.asarray(prog.z)))
    r0 = jnp.asarray(prog.r, jnp.int32)                 # [2n]
    lt = jnp.asarray(prog.l.T, jnp.int32)               # [P, 2n]

    def sample(
        rnds: jnp.ndarray,
        params: jnp.ndarray | None = None,
        phase_noise: jnp.ndarray | None = None,
    ):
        b = rnds.shape[0]
        rnds = rnds.astype(jnp.int32) & 1
        if params is not None and n_params > 0:
            # Circuit application, whole batch at once: phases are
            # r0 ^ (params @ L^T) — the batched K-tiled GF(2) matmul.
            phase = gf2_matmul(params.astype(jnp.int32) & 1, lt)  # [B, 2n]
            r = r0[None, :] ^ phase
        else:
            r = jnp.broadcast_to(r0[None, :], (b, 2 * n))
        if phase_noise is not None:
            # Depolarizing channel as a phase-only edit (the drawn Pauli
            # conjugates the evolved tableau — see qsim/noise.py): the
            # caller supplies [B, 2n] parities, precomputed against the
            # compiled rows, keeping this core PRNG-free for lint.
            r = r ^ phase_noise
        xw = jnp.broadcast_to(x0w[None], (b, 2 * n, x0w.shape[-1]))
        zw = jnp.broadcast_to(z0w[None], (b, 2 * n, z0w.shape[-1]))
        # One shared sweep (also the megakernel's in-VMEM prologue —
        # gen-fused bit-identity is by construction, not by test).
        return gf2_measure_sweep(n, xw, zw, r, rnds)

    return sample


def _draw_coins(keys: jax.Array, n: int) -> jnp.ndarray:
    """Per-shot coins, bit-identical to the per-shot engine's draw:
    ``(random.bits(key, (n,), uint32) & 1)`` vmapped over the keys."""
    bits = jax.vmap(lambda k: jax.random.bits(k, (n,), jnp.uint32))(keys)
    return (bits & 1).astype(jnp.int32)


def build_gf2_tableau_run_batch(
    n: int,
    ops,
    n_params: int,
    p_depolarize: float = 0.0,
    p_measure_flip: float = 0.0,
):
    """``run_batch(keys[B], params=None) -> int32 bits[B, n]``.

    ``keys`` is a batch of PRNG keys (one per shot/list position);
    ``params`` is ``None``, a shared ``[P]`` vector, or a per-shot
    ``[B, P]`` matrix.  This is the entry ``generate_lists_stabilizer``
    feeds per-position meas keys and per-position permutation bits.

    Nonzero noise draws the per-shot channels of
    :func:`qba_tpu.qsim.noise.noise_draws` from each shot's own key —
    the same draw the per-shot tableau engine makes, so the two
    stabilizer engines stay bit-identical under noise.  The Pauli lands
    as a batched phase parity against the compiled rows (two GF(2)
    matmuls), keeping the traced core Clifford-only and PRNG-free.
    """
    core = build_gf2_sample_core(n, ops, n_params)
    noisy = p_depolarize > 0.0 or p_measure_flip > 0.0
    if noisy:
        prog = compile_symplectic(n, ops, n_params)
        zt = jnp.asarray(prog.z.T, jnp.int32)  # [n, 2n]
        xt = jnp.asarray(prog.x.T, jnp.int32)

    def run_batch(keys: jax.Array, params: jnp.ndarray | None = None):
        rnds = _draw_coins(keys, n)
        if params is not None and params.ndim == 1:
            params = jnp.broadcast_to(
                params[None, :], (rnds.shape[0], params.shape[0])
            )
        if not noisy:
            return core(rnds, params)
        from qba_tpu.qsim.noise import noise_draws

        bx, bz, mflip = jax.vmap(
            lambda k: noise_draws(k, n, p_depolarize, p_measure_flip)
        )(keys)
        # Per-row phase parity of the drawn Pauli against the evolved
        # tableau: rows are shared across the batch (params only touch
        # phases), so  r ^= bx . z_row ^ bz . x_row  batches as matmuls.
        phase_noise = gf2_matmul(bx, zt) ^ gf2_matmul(bz, xt)  # [B, 2n]
        return core(rnds, params, phase_noise=phase_noise) ^ mflip

    return run_batch


def build_gf2_tableau_run_shots(
    n: int,
    ops,
    n_params: int,
    p_depolarize: float = 0.0,
    p_measure_flip: float = 0.0,
):
    """``run(key, shots, params=None) -> int32 bits[shots, n]`` — the
    :meth:`Circuit.compile_shots` contract on the batched GF(2) engine,
    key-tree-identical to the per-shot reference
    (:func:`qba_tpu.qsim.stabilizer.build_tableau_run_shots`): the key
    splits into ``shots`` subkeys and each shot's coins come from its
    own subkey."""
    run_batch = build_gf2_tableau_run_batch(
        n, ops, n_params, p_depolarize, p_measure_flip
    )

    def run(
        key: jax.Array, shots: int, params: jnp.ndarray | None = None
    ) -> jnp.ndarray:
        return run_batch(jax.random.split(key, shots), params)

    return run
