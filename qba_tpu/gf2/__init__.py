"""qba_tpu.gf2 — batched bit-packed GF(2) linear algebra + the
aggregate-symplectic stabilizer sampler.

This package is the scale engine for resource generation (ROADMAP
item 5): parity matmuls as KI-3-provable integer dots
(:mod:`~qba_tpu.gf2.linalg`), packed-word bit kernels
(:mod:`~qba_tpu.gf2.bitops`), and the compiled batched tableau sampler
(:mod:`~qba_tpu.gf2.symplectic`) that replaces per-op, per-shot column
edits with a handful of batched GF(2) matmuls plus a masked
measurement sweep over the whole ``(trials x size_l)`` shot batch.
"""

from qba_tpu.gf2.bitops import (
    WORD,
    get_bit,
    mask_words,
    n_words,
    pack_bits,
    parity_words,
    prefix_xor_exclusive,
    unit_words,
    unpack_bits,
)
from qba_tpu.gf2.linalg import (
    GF2_TILE_K,
    gf2_matmul,
    gf2_matvec,
    rank1_update_packed,
    triangular_parity,
)
from qba_tpu.gf2.symplectic import (
    SymplecticProgram,
    build_gf2_sample_core,
    build_gf2_tableau_run_batch,
    build_gf2_tableau_run_shots,
    compile_symplectic,
)

__all__ = [
    "WORD",
    "GF2_TILE_K",
    "SymplecticProgram",
    "build_gf2_sample_core",
    "build_gf2_tableau_run_batch",
    "build_gf2_tableau_run_shots",
    "compile_symplectic",
    "get_bit",
    "gf2_matmul",
    "gf2_matvec",
    "mask_words",
    "n_words",
    "pack_bits",
    "parity_words",
    "prefix_xor_exclusive",
    "rank1_update_packed",
    "triangular_parity",
    "unit_words",
    "unpack_bits",
]
