"""GF(2) linear algebra as KI-3-provable integer matmuls.

A parity matmul ``c = a @ b (mod 2)`` over 0/1 matrices is an ordinary
integer matmul followed by a mod-2 reduce — exactly the kernel class
the KI-3 lint (:mod:`qba_tpu.analysis.dots`) proves exact: the MXU
feeds default-precision ``dot_general`` through bf16 passes, and bf16
represents integers exactly up to 256.  Two facts keep every dot here
inside that envelope *by construction*:

* the operands are 0/1 (magnitude bound 1 — trivially bf16-exact), and
* the contraction is **K-tiled at** :data:`GF2_TILE_K` ``= 256``, so
  each tile's accumulated sum is at most 256 — bf16-exact even if a
  backend accumulated partials at operand precision — and each tile is
  reduced mod 2 before tiles are XOR-combined (the cross-tile combine
  is integer XOR on {0,1}, never a wide float sum).

No ``Precision.HIGHEST`` escape hatch and no ``qba-lint: exact-ok``
allowlist marker appears in this module: ``qba-tpu lint --engines gf2``
must prove every dot clean from the interval seeds alone (pinned by
tests/test_analysis.py).

The batched rank-1 update and the triangular-parity reduction operate
on the *packed* representation (:mod:`qba_tpu.gf2.bitops`) — they are
memory-bound XOR/popcount sweeps where a dense dot would inflate the
working set 32x (the measurement sweep calls them once per qubit).
"""

from __future__ import annotations

import jax.numpy as jnp

from qba_tpu.gf2.bitops import (
    mask_words,
    parity_words,
    prefix_xor_exclusive,
)

#: Max contraction length per dot tile: per-tile accumulations of 0/1
#: products stay <= 256, bf16's exact-integer ceiling (KI-3).
GF2_TILE_K = 256


def gf2_matmul(a: jnp.ndarray, b: jnp.ndarray, *, tile_k: int = GF2_TILE_K):
    """Parity matmul ``c[..., i, j] = XOR_k a[..., i, k] & b[..., k, j]``.

    ``a``/``b`` are 0/1 integer (or bool) arrays; leading batch axes
    broadcast as in ``jnp.matmul``.  Returns int32 in {0, 1}.

    Each K-tile is one default-precision f32 ``dot_general`` (MXU-
    shaped) whose accumulation is bounded by ``tile_k``; tiles reduce
    mod 2 independently and XOR-combine, so no intermediate ever
    leaves the bf16-exact integer range.
    """
    if a.shape[-1] != b.shape[-2]:
        raise ValueError(
            f"gf2_matmul: contraction mismatch {a.shape} @ {b.shape}"
        )
    if tile_k < 1 or tile_k > GF2_TILE_K:
        raise ValueError(
            f"tile_k={tile_k} must be in [1, {GF2_TILE_K}]: larger tiles "
            "let a per-tile accumulation exceed bf16's exact range"
        )
    k = a.shape[-1]
    af = (a.astype(jnp.int32) & 1).astype(jnp.float32)
    bf = (b.astype(jnp.int32) & 1).astype(jnp.float32)
    acc = None
    for k0 in range(0, k, tile_k):
        k1 = min(k0 + tile_k, k)
        part = jnp.matmul(
            af[..., :, k0:k1], bf[..., k0:k1, :],
            preferred_element_type=jnp.float32,
        )
        tile = part.astype(jnp.int32) & 1
        acc = tile if acc is None else acc ^ tile
    if acc is None:  # k == 0: empty contraction is the zero matrix
        shape = (*jnp.broadcast_shapes(a.shape[:-2], b.shape[:-2]),
                 a.shape[-2], b.shape[-1])
        return jnp.zeros(shape, jnp.int32)
    return acc


def gf2_matvec(m: jnp.ndarray, v: jnp.ndarray, *, tile_k: int = GF2_TILE_K):
    """Parity mat-vec ``[..., m, k] @ [..., k] -> [..., m]``."""
    return gf2_matmul(m, v[..., None], tile_k=tile_k)[..., 0]


def rank1_update_packed(
    m_words: jnp.ndarray, mask: jnp.ndarray, row_words: jnp.ndarray,
) -> jnp.ndarray:
    """Masked GF(2) rank-1 update on packed rows:
    ``m ^= outer(mask, row)``.

    ``m_words``: ``[..., R, W]`` uint32, ``mask``: ``[..., R]`` 0/1,
    ``row_words``: ``[..., W]`` uint32.  This is the tableau-collapse
    primitive: every row flagged by ``mask`` absorbs ``row`` in one
    vectorized XOR — the batched replacement for the per-shot
    ``lax.cond`` random-measurement branch.
    """
    mw = mask_words(mask)[..., None]
    return m_words ^ (mw & row_words[..., None, :])


def triangular_parity(
    z_words: jnp.ndarray, x_words: jnp.ndarray,
) -> jnp.ndarray:
    """Parity of the strict-upper-triangle cross sum
    ``sum_{a<b} z_a . x_b`` over rows (axis -2) of packed operands.

    Both inputs are ``[..., R, W]`` with non-selected rows already
    zero-masked.  Because parity distributes over addition, the
    ``[R, R]`` cross matrix of the unpacked formulation collapses to an
    exclusive prefix-XOR over rows followed by one AND + popcount
    parity — O(R * W) instead of O(R^2) — which is what makes the
    deterministic measurement branch batchable at n = 1040+ qubits.
    """
    prefix = prefix_xor_exclusive(z_words, axis=-2)
    return parity_words(prefix & x_words, axis=(-2, -1))
