"""Bit-packed GF(2) vectors: uint32 words, 32 bits per lane.

The packed layout is the memory side of the gf2 subsystem: a GF(2)
vector of ``n`` bits occupies ``ceil(n / 32)`` uint32 words (bit ``j``
lives in word ``j >> 5`` at position ``j & 31``, little-endian within
the word), so a 2n x n stabilizer tableau at 129 parties (n = 1040)
shrinks from 8.7 MB of int32 flags to 270 KB of words per shot — the
difference between a (trials x size_l) shot batch fitting in VMEM-class
working sets or not.

Everything here is elementwise/VPU work on integer dtypes (XOR, AND,
shifts, ``population_count``) — exact by construction, no dots, so the
KI-3 lint has nothing to prove on this layer.  The MXU-shaped parity
*matmuls* live in :mod:`qba_tpu.gf2.linalg`; this module supplies the
packing, single-column extraction, per-fiber parity, and the exclusive
prefix-XOR that powers the triangular-parity reduction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: Bits per packed word.
WORD = 32


def n_words(n_bits: int) -> int:
    """Words needed for ``n_bits`` GF(2) entries."""
    return -(-n_bits // WORD)


def pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """Pack 0/1 entries along the last axis: ``[..., n] -> [..., W]``.

    Accepts any integer/bool dtype; only the low bit of each entry is
    read.  Bit ``j`` of the input lands in word ``j // 32`` at position
    ``j % 32``.
    """
    n = bits.shape[-1]
    w = n_words(n)
    pad = w * WORD - n
    b = (bits.astype(jnp.uint32) & 1)
    if pad:
        b = jnp.pad(b, [(0, 0)] * (b.ndim - 1) + [(0, pad)])
    b = b.reshape(*b.shape[:-1], w, WORD)
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    return jnp.sum(b << shifts, axis=-1).astype(jnp.uint32)


def unpack_bits(words: jnp.ndarray, n_bits: int) -> jnp.ndarray:
    """Inverse of :func:`pack_bits`: ``[..., W] -> [..., n_bits]`` int32."""
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    bits = (words[..., None] >> shifts) & 1
    bits = bits.reshape(*words.shape[:-1], words.shape[-1] * WORD)
    return bits[..., :n_bits].astype(jnp.int32)


def get_bit(words: jnp.ndarray, j) -> jnp.ndarray:
    """Extract bit ``j`` (a traced scalar is fine) along the last axis:
    ``[..., W] -> [...]`` int32 in {0, 1}."""
    j = jnp.asarray(j, jnp.int32)
    word = jnp.take(words, j >> 5, axis=-1)
    return ((word >> (j & 31).astype(jnp.uint32)) & 1).astype(jnp.int32)


def unit_words(n_bits: int, j) -> jnp.ndarray:
    """Packed standard basis vector ``e_j``: ``[W]`` uint32 with only
    bit ``j`` set.  ``j`` may be traced."""
    j = jnp.asarray(j, jnp.int32)
    idx = jnp.arange(n_words(n_bits), dtype=jnp.int32)
    bit = jnp.asarray(1, jnp.uint32) << (j & 31).astype(jnp.uint32)
    return jnp.where(idx == (j >> 5), bit, jnp.asarray(0, jnp.uint32))


def parity_words(words: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Parity (XOR-reduce) of all bits along packed axis ``axis``:
    popcount each word, sum, take the low bit.  int32 in {0, 1}."""
    counts = jax.lax.population_count(words)
    return (jnp.sum(counts.astype(jnp.int32), axis=axis) & 1)


def mask_words(mask: jnp.ndarray) -> jnp.ndarray:
    """0/1 (or bool) mask -> all-ones/all-zeros uint32 word mask, for
    ANDing against packed rows (``mask & row`` per word)."""
    return jnp.where(
        mask.astype(jnp.int32) != 0,
        jnp.asarray(0xFFFFFFFF, jnp.uint32),
        jnp.asarray(0, jnp.uint32),
    )


def prefix_xor_exclusive(words: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Exclusive running XOR along ``axis``: output fiber ``i`` is the
    XOR of input fibers ``0..i-1`` (fiber 0 is all zeros).

    This is the packed form of the strict-lower-triangle accumulation:
    for selected tableau rows, ``prefix[b] & x[b]`` has the parity of
    ``sum_{a<b} z_a . x_b`` — the triangular-parity reduction of
    :func:`qba_tpu.gf2.linalg.triangular_parity` — without ever forming
    the ``[n, n]`` cross matrix the unpacked formulation needs.
    """
    inclusive = jax.lax.associative_scan(jnp.bitwise_xor, words, axis=axis)
    ax = axis % words.ndim
    pad = [(0, 0)] * words.ndim
    pad[ax] = (1, 0)
    shifted = jnp.pad(inclusive, pad)
    idx = [slice(None)] * words.ndim
    idx[ax] = slice(0, words.shape[ax])
    return shifted[tuple(idx)]
