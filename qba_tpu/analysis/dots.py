"""KI-3 exact-dot pass.

Mechanizes the KNOWN_ISSUES rule: *any dot whose integer operands can
exceed 256 must pass ``Precision.HIGHEST``*.  On TPU, default-precision
``dot_general`` feeds the MXU with bf16 passes regardless of the stored
dtype, and bf16 represents integers exactly only up to ``2**8 = 256`` —
beyond that, protocol ids (pool rows, cell ids, lieutenant ids) silently
round to even and the gather/permute matmuls return the wrong row.

The pass runs over the :class:`~qba_tpu.analysis.intervals.DotRecord`
list produced by interval interpretation of each traced build path and
flags every ``dot_general`` that is

* *default precision* (``precision=None`` or a ``DEFAULT`` pair), and
* has a floating operand (f32/bf16/f16 — integer dots run exactly in
  the VPU and are safe), that is
* **provably integer-valued** with magnitude bound above
  :data:`BF16_EXACT_MAX` — or integral but unbounded, which counts as a
  violation (the analysis must *prove* safety, not fail to disprove it).

Operands the analysis cannot prove integral (probabilities, averages)
are skipped: bf16 rounding of real-valued math is an accepted accuracy
trade handled by the engines' own tolerances, not a KI-3 bug.  Those
skips err toward false negatives and are counted in the report stats.

Annotating a proven-exact dot
-----------------------------

If a default-precision dot is genuinely safe for a reason outside the
interval domain (e.g. the integer values are multiples of 512 and thus
bf16-exact despite exceeding 256), mark the call site with a trailing
or preceding comment containing the marker ``qba-lint: exact-ok``
followed by the justification::

    out = one_hot @ table  # qba-lint: exact-ok (values are powers of 2)

The pass reads the flagged source line (and its two neighbours, for
wrapped calls) and demotes the finding to a note carrying the
justification.  See docs/ANALYSIS.md.
"""

from __future__ import annotations

import os
from typing import Iterable

import numpy as np

from qba_tpu.analysis.findings import Finding, Report
from qba_tpu.analysis.intervals import DotRecord

#: Largest integer magnitude bf16 represents exactly (8 significand bits).
BF16_EXACT_MAX = 256

ALLOW_MARKER = "qba-lint: exact-ok"

_FLOAT_DTYPES = ("float32", "bfloat16", "float16")


def _is_default_precision(precision) -> bool:
    if precision is None:
        return True
    parts = precision if isinstance(precision, (tuple, list)) else (precision,)
    return all(str(getattr(p, "name", p)).upper() == "DEFAULT" for p in parts)


def _allow_justification(where: str) -> str | None:
    """Return the ``qba-lint: exact-ok`` annotation near ``where`` if any."""
    if ":" not in where:
        return None
    fname, _, lineno_s = where.rpartition(":")
    try:
        lineno = int(lineno_s)
    except ValueError:
        return None
    if not os.path.isfile(fname):
        return None
    try:
        with open(fname, encoding="utf-8") as fh:
            lines = fh.readlines()
    except OSError:
        return None
    for i in range(max(0, lineno - 2), min(len(lines), lineno + 2)):
        if ALLOW_MARKER in lines[i]:
            return lines[i].split(ALLOW_MARKER, 1)[1].strip() or "annotated"
    return None


def check_dots(records: Iterable[DotRecord]) -> Report:
    report = Report()
    n_checked = n_exact = n_skipped = 0
    for rec in records:
        n_checked += 1
        eqn = rec.eqn
        if not _is_default_precision(eqn.params.get("precision")):
            n_exact += 1
            continue
        for side, ival, var in (
            ("lhs", rec.lhs, eqn.invars[0]),
            ("rhs", rec.rhs, eqn.invars[1]),
        ):
            dtype = np.dtype(var.aval.dtype)
            if dtype.name not in _FLOAT_DTYPES:
                continue
            if not ival.integral:
                n_skipped += 1
                continue
            if ival.bounded and ival.mag <= BF16_EXACT_MAX:
                continue
            bound = (
                f"magnitude bound {ival.mag:g}" if ival.bounded
                else "unbounded integer range"
            )
            justification = _allow_justification(rec.where)
            msg = (
                f"default-precision dot_general with integer-valued "
                f"{side} operand ({dtype.name}, {ival!r}): {bound} exceeds "
                f"bf16's exact range of {BF16_EXACT_MAX}; pass "
                f"precision=Precision.HIGHEST or prove the bound"
            )
            if justification is not None:
                report.notes.append(
                    f"allowlisted exact-dot at {rec.where or rec.path}: "
                    f"{justification}"
                )
                continue
            report.findings.append(Finding(
                ki="KI-3", check="exact-dot", path=rec.path,
                message=msg, where=rec.where,
            ))
    report.stats["dots_checked"] = n_checked
    report.stats["dots_explicit_precision"] = n_exact
    report.stats["dots_skipped_nonintegral"] = n_skipped
    return report
