"""Lint orchestrator: the entry point the CLI and CI gate call.

:func:`run_lint` traces every engine/kernel build path
(:mod:`qba_tpu.analysis.traces`), interval-interprets each jaxpr
(:mod:`qba_tpu.analysis.intervals`), and runs the invariant passes —
KI-3 exact-dot (:mod:`qba_tpu.analysis.dots`), KI-1 vma-threading
(:mod:`qba_tpu.analysis.vma`), KI-2 plan audit incl. sharded
per-device budgets (:mod:`qba_tpu.analysis.memory`), and, with
``effects=True`` (CLI ``--effects``), KI-5 donation/aliasing
(:mod:`qba_tpu.analysis.effects`) and KI-6 host-sync discipline
(:mod:`qba_tpu.analysis.transfers`); ``protocol=True`` (CLI
``--protocol``) adds the config-independent KI-10 file-queue
protocol model check (:mod:`qba_tpu.analysis.protocol`) — over a
small config matrix chosen to cover the planner's phase space:

* ``cheap``       — (17, 16, 4): every engine live, fused plan resolves,
  even lieutenant count so the 2-way sharded variants trace;
* ``north-star``  — (33, 64, 10): the BASELINE.md flagship; the fused
  kernel demotes on TPU and the pool meta bounds cross bf16's exact
  range, so the one-hot structure proofs carry real weight;
* ``f32-gdt``     — (11, 1000, 3): the reference paper's 11-party
  scale; size_l pushes the verdict kernel into its f32 gather dtype.
* ``stabilizer``  — (11, 16, 3) on ``qsim_path="stabilizer"`` with
  ``mega_gen="gf2"``: the batched GF(2) resource path; its parity dots
  (``qba_tpu/gf2``) must prove KI-3-clean with zero allowlist markers,
  the packed-tableau KI-2 entry fires, and the gen-fused megakernel
  audits (generation in-kernel, zero host scans) run on every lint.

One aggregated :class:`~qba_tpu.analysis.findings.Report` comes back:
empty findings means the tree upholds KI-1/KI-2/KI-3 by construction.
The whole run is pure CPU tracing/arithmetic — no TPU, no compile
probes (the KI-2 pass verifies that last claim against PROBE_STATS).
"""

from __future__ import annotations

from typing import Iterable, Sequence

from qba_tpu.analysis.findings import Report
from qba_tpu.config import QBAConfig

#: (label, config-kwargs) lint matrix; see the module docstring for why
#: each point is in it.
LINT_MATRIX = (
    ("cheap", dict(n_parties=17, size_l=16, n_dishonest=4)),
    ("north-star", dict(n_parties=33, size_l=64, n_dishonest=10)),
    ("f32-gdt", dict(n_parties=11, size_l=1000, n_dishonest=3)),
    ("stabilizer", dict(
        n_parties=11, size_l=16, n_dishonest=3, qsim_path="stabilizer",
        mega_gen="gf2",
    )),
    # split traces the forge-P flag algebra + full-mask MXU identities
    # that every other strategy statically gates OUT of its jaxpr — the
    # only matrix point where those dots exist to be interval-checked.
    ("split-strategy", dict(
        n_parties=17, size_l=16, n_dishonest=4, strategy="split",
    )),
)

ENGINE_CHOICES = (
    "xla", "pallas", "pallas_tiled", "pallas_fused", "pallas_mega",
    "spmd", "gf2",
)


def lint_configs() -> list[tuple[str, QBAConfig]]:
    """The built-in lint matrix, instantiated."""
    return [(label, QBAConfig(**kw)) for label, kw in LINT_MATRIX]


def saved_plan_configs(path: str) -> list[tuple[str, QBAConfig]]:
    """Lint matrix points for every shape recorded in a serve
    warm-start artifact (``plans.json``, :mod:`qba_tpu.serve.persist`).

    Plans restored from disk skip the live probe path entirely, so
    without this hook a server could dispatch on engine builds the KI
    gates never saw; ``qba-tpu lint --saved-plans`` closes that gap by
    re-tracing exactly the dispatched shapes."""
    from qba_tpu.serve.persist import saved_configs

    return [
        (
            f"plan:{cfg.n_parties}p-L{cfg.size_l}-d{cfg.n_dishonest}",
            cfg,
        )
        for cfg in saved_configs(path)
    ]


def _lint_config(
    label: str, cfg: QBAConfig, engines, sitewide: bool,
    effects: bool = False,
) -> Report:
    from qba_tpu.analysis.dots import check_dots
    from qba_tpu.analysis.intervals import IntervalInterpreter
    from qba_tpu.analysis.memory import check_gf2_memory, check_memory
    from qba_tpu.analysis.traces import trace_paths
    from qba_tpu.analysis.vma import check_vma

    engine_set = set(engines) if engines is not None else set(ENGINE_CHOICES)
    report = Report()
    paths, notes = trace_paths(cfg, engine_set)
    report.notes.extend(f"{label}: {n}" for n in notes)

    records = []
    unhandled: set[str] = set()
    for p in paths:
        interp = IntervalInterpreter(f"{label}:{p.name}")
        interp.run(p.closed_jaxpr, p.seeds)
        records.extend(interp.dots.values())
        unhandled |= interp.unhandled
    report.extend(check_dots(records))
    report.stats["paths_traced"] = len(paths)
    report.stats["unhandled_primitives"] = unhandled

    if "spmd" in engine_set:
        # The KI-1 call-site/policy audits are config-independent —
        # run them once per lint, not once per matrix point.
        report.extend(check_vma(cfg, sitewide=sitewide))
    if engine_set & {"pallas_tiled", "pallas_fused"}:
        report.extend(check_memory(cfg))
    if "gf2" in engine_set:
        report.extend(check_gf2_memory(cfg))
    if effects:
        from qba_tpu.analysis.effects import check_effects
        from qba_tpu.analysis.launches import (
            check_launches,
            check_spmd_launches,
        )
        from qba_tpu.analysis.transfers import check_jaxpr_transfers

        report.extend(check_effects(cfg, paths, engine_set))
        report.extend(check_launches(cfg, engine_set))
        if "spmd" in engine_set:
            report.extend(check_spmd_launches(cfg, engine_set))
        report.extend(check_jaxpr_transfers(paths))
    return report


def run_lint(
    configs: Sequence[tuple[str, QBAConfig]] | None = None,
    engines: Iterable[str] | None = None,
    effects: bool = False,
    protocol: bool = False,
) -> Report:
    """Run every lint pass over ``configs`` (default: the built-in
    matrix) restricted to ``engines`` (default: all build paths).
    ``effects=True`` adds the KI-5 donation/aliasing audit and the
    KI-6 host-sync discipline gate (per-config jaxpr passes plus the
    sitewide AST sweep, serve dispatch proof, and jit-donation audit).
    ``protocol=True`` adds the KI-10 file-queue protocol pass — the
    bounded model check, conformance sweep, and admission-purity proof
    (:mod:`qba_tpu.analysis.protocol`); it is config-independent and
    runs once per lint.
    Returns one aggregated report; ``report.ok`` is the CI gate."""
    from qba_tpu.analysis import tracecache

    if engines is not None:
        bad = set(engines) - set(ENGINE_CHOICES)
        if bad:
            raise ValueError(
                f"unknown lint engine(s) {sorted(bad)}; "
                f"choose from {ENGINE_CHOICES}"
            )
    tracecache.reset()
    report = Report()
    sitewide = True
    for label, cfg in configs if configs is not None else lint_configs():
        report.extend(
            _lint_config(label, cfg, engines, sitewide, effects=effects)
        )
        sitewide = False
    if effects:
        from qba_tpu.analysis.effects import check_jit_donation
        from qba_tpu.analysis.transfers import (
            check_device_loop,
            check_transfers,
        )

        report.extend(check_transfers())
        report.extend(check_jit_donation())
        # ROADMAP item 3: the device-resident targeted loop must stay a
        # single transfer-free dispatch (per-chunk readbacks eliminated,
        # not fenced) — proven from its traced jaxpr, sitewide.
        report.extend(check_device_loop())
    if protocol:
        from qba_tpu.analysis.protocol import check_protocol

        report.extend(check_protocol())
    cache = tracecache.stats()
    report.stats.update(cache)
    if cache["trace_cache_hits"]:
        report.notes.append(
            f"trace cache: {cache['trace_cache_hits']} hit(s) across "
            f"{cache['trace_cache_entries']} traced (config, engine) "
            "pair(s) — each hit is one full run_trial retrace saved"
        )
    return report
