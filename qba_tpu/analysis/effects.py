"""KI-5 donation/aliasing audit.

The round engines' throughput story rests on buffer donation: every
round-scan carry (``vi`` and the mailbox pool) must flow through a
kernel whose ``input_output_aliases`` hands the carried HBM buffer
back to the next iteration, otherwise each round allocates a fresh
pool generation and the KI-2 trial ceiling silently halves.  Until
this pass, that discipline lived in comments next to the alias dicts
(``ops/round_kernel_tiled.py``, ``ops/round_kernel.py``) — nothing
machine-checked that a claimed donation *actually aliases*, or that a
carry does not round-trip through a fresh allocation.  This pass
re-derives it from the jaxprs:

* **Alias consistency** — every ``(i, o)`` pair in a ``pallas_call``'s
  ``input_output_aliases`` must name an in-range input/output with
  identical shape *and* dtype (XLA rejects some of these at compile
  time, but only on TPU — CPU interpret tests would never see it).
* **Donation coverage** — a ``pallas_call`` claiming *no* aliases
  while some output exactly matches an input's shape+dtype is a missed
  donation candidate and is flagged; a deliberate miss is annotated
  ``# qba-lint: donate-ok (reason)`` at the call site (the party-
  sharded builders legitimately alias only ``vi`` — gathered global
  pool in, local pool out — and their alias dicts say so).
* **Scan-carry donation** — for each round engine, the full
  ``run_trial`` jaxpr is traced and every ``lax.scan`` whose body
  launches a kernel is audited: each carry output is chased backwards
  (through shape/dtype-preserving ops and ``pjit`` bodies) to its
  producer; a carry produced by a kernel output *without* an alias
  onto it round-trips through a fresh HBM allocation — finding.  The
  alias's source input must itself chase back to the scan carry state.
  Carries produced by plain XLA ops (the ``xla`` engine, counter
  state) are XLA's buffer-reuse problem and are counted, not flagged.
* **Top-level jit donation** — the dispatch jits
  (:mod:`qba_tpu.backends.jax_backend`, :mod:`qba_tpu.parallel.spmd`)
  are audited by AST: any ``donate_argnums`` claim must not overlap
  ``static_argnums`` (a donated static is dead machinery), and the
  deliberate zero-donation policy (keys are reused across repeat
  dispatches by bench/serve; state donation lives in the kernel
  aliases above) is recorded as a note so a future claim is a
  conscious change.

Findings are tagged ``KI-5`` (docs/KNOWN_ISSUES.md).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import warnings

from qba_tpu.analysis.findings import Finding, Report
from qba_tpu.analysis.intervals import source_location
from qba_tpu.config import QBAConfig

#: Call-site marker that demotes a donation-coverage finding to a note
#: carrying the justification (same grammar as ``qba-lint: exact-ok``
#: and ``qba-lint: sync-ok`` — docs/ANALYSIS.md).
DONATE_ALLOW_MARKER = "qba-lint: donate-ok"

#: Engines whose ``run_trial`` round scans the carry audit traces.
#: ``pallas_mega`` is deliberately NOT here: its round loop runs
#: inside the kernel, so there is no scan to audit — :func:`_audit_mega`
#: instead PROVES the scan is gone (exactly one ``pallas_call``, zero
#: kernel-launching scans in the whole trial jaxpr).
SCAN_ENGINES = ("xla", "pallas", "pallas_tiled", "pallas_fused")

#: Shape/dtype-preserving primitives the carry chase looks through —
#: they forward the same buffer-sized value, so donation "survives"
#: them (XLA fuses them into the consumer or aliases the copy).
_TRANSPARENT_PRIMS = frozenset({
    "convert_element_type", "copy", "copy_p", "reshape", "transpose",
    "squeeze", "expand_dims", "rev", "reduce_precision",
    "stop_gradient", "device_put", "optimization_barrier",
    "sharding_constraint",
})

_CALL_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "xla_call", "named_call",
    "custom_jvp_call", "custom_vjp_call", "remat", "checkpoint",
})


def annotation_at(where: str, marker: str) -> str | None:
    """Return the justification text if the source at ``where``
    ("file:line") carries ``# <marker> ...`` within one line of the
    location (wrapped calls), else None.  Shared reader for the
    ``qba-lint:`` annotation family."""
    path, _, lineno = where.rpartition(":")
    if not path:
        return None
    try:
        num = int(lineno)
        with open(path) as fh:
            lines = fh.readlines()
    except (ValueError, OSError):
        return None
    for i in range(max(0, num - 2), min(len(lines), num + 2)):
        if marker in lines[i]:
            return lines[i].split(marker, 1)[1].strip() or "annotated"
    return None


# ---------------------------------------------------------------------------
# Jaxpr plumbing.


def _as_jaxprs(v):
    if hasattr(v, "eqns"):  # Jaxpr
        yield v
    elif hasattr(v, "jaxpr"):  # ClosedJaxpr
        yield v.jaxpr
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _as_jaxprs(x)


def iter_eqns(jaxpr):
    """All equations of ``jaxpr``, descending into call/scan/cond
    sub-jaxprs (but not into Pallas kernel bodies — a kernel body
    cannot launch another kernel)."""
    for eqn in jaxpr.eqns:
        yield eqn
        if eqn.primitive.name == "pallas_call":
            continue
        for p in eqn.params.values():
            for sub in _as_jaxprs(p):
                yield from iter_eqns(sub)


def _aval_sig(var):
    aval = getattr(var, "aval", None)
    return (
        tuple(getattr(aval, "shape", ()) or ()),
        str(getattr(aval, "dtype", "")),
    )


def _producers(jaxpr):
    prods = {}
    for eqn in jaxpr.eqns:
        for j, v in enumerate(eqn.outvars):
            if type(v).__name__ != "DropVar":
                prods[v] = (eqn, j)
    return prods


@dataclasses.dataclass
class _Frame:
    """One level of the backward chase: a jaxpr, its producer map, and
    the call equation that entered it (None at the top)."""

    jaxpr: object
    prods: dict
    call_eqn: object


def _chase_back(var, frames):
    """Chase ``var`` backwards through shape-preserving ops and call
    bodies to its producing allocation.  Returns
    ``(kind, payload, out_idx, frames)`` with kind one of ``"invar"``
    (payload = top-frame input index), ``"pallas"`` (payload = the
    kernel eqn, out_idx = which kernel output), ``"literal"``,
    ``"const"`` or ``"opaque"`` (payload = the producing eqn)."""
    frames = list(frames)
    for _ in range(10_000):  # structural walk; cycles are impossible
        if type(var).__name__ == "Literal":
            return ("literal", None, None, frames)
        frame = frames[-1]
        invars = frame.jaxpr.invars
        for idx, iv in enumerate(invars):
            if iv is var:
                if len(frames) == 1:
                    return ("invar", idx, None, frames)
                call_eqn = frame.call_eqn
                off = len(call_eqn.invars) - len(invars)
                var = call_eqn.invars[off + idx]
                frames = frames[:-1]
                break
        else:
            ent = frame.prods.get(var)
            if ent is None:
                return ("const", None, None, frames)
            eqn, j = ent
            name = eqn.primitive.name
            if name in _TRANSPARENT_PRIMS:
                var = eqn.invars[0]
                continue
            if name == "pallas_call":
                return ("pallas", eqn, j, frames)
            sub = eqn.params.get("call_jaxpr") or (
                eqn.params.get("jaxpr")
                if name in _CALL_PRIMS else None
            )
            if sub is not None:
                inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                frames = frames + [_Frame(inner, _producers(inner), eqn)]
                var = inner.outvars[j]
                continue
            return ("opaque", eqn, j, frames)
        continue
    return ("opaque", None, None, frames)


# ---------------------------------------------------------------------------
# Pallas-call alias audit (per traced build path).


def _audit_pallas_eqn(eqn, path, report, stats) -> None:
    aliases = dict(eqn.params.get("input_output_aliases") or ())
    where = source_location(eqn)
    in_sigs = [_aval_sig(v) for v in eqn.invars]
    out_sigs = [_aval_sig(v) for v in eqn.outvars]
    stats["pallas_calls_audited"] += 1
    for i, o in aliases.items():
        stats["alias_pairs_checked"] += 1
        if not (0 <= i < len(in_sigs) and 0 <= o < len(out_sigs)):
            report.findings.append(Finding(
                ki="KI-5", check="alias-consistency", path=path,
                where=where,
                message=(
                    f"input_output_aliases {{{i}: {o}}} is out of range "
                    f"({len(in_sigs)} inputs, {len(out_sigs)} outputs)"
                ),
            ))
            continue
        if in_sigs[i] != out_sigs[o]:
            report.findings.append(Finding(
                ki="KI-5", check="alias-consistency", path=path,
                where=where,
                message=(
                    f"claimed donation {{{i}: {o}}} does not alias: "
                    f"input {in_sigs[i][0]}/{in_sigs[i][1]} vs output "
                    f"{out_sigs[o][0]}/{out_sigs[o][1]} — a donation "
                    "that changes shape or dtype is a fresh allocation "
                    "plus a copy, not a reuse"
                ),
            ))
    if not aliases:
        # A kernel that donates nothing while an output exactly matches
        # an un-aliased input is a missed in-place update: the output
        # is a fresh HBM buffer the input's could have carried.
        matches = [
            (i, o)
            for o, osig in enumerate(out_sigs)
            for i, isig in enumerate(in_sigs)
            if osig == isig and osig[0]
        ]
        if matches:
            justification = annotation_at(where, DONATE_ALLOW_MARKER)
            if justification is not None:
                report.notes.append(
                    f"{path}: allowlisted donation miss at {where}: "
                    f"{justification}"
                )
            else:
                i, o = matches[0]
                report.findings.append(Finding(
                    ki="KI-5", check="donation-miss", path=path,
                    where=where,
                    message=(
                        f"pallas_call declares no input_output_aliases "
                        f"but output {o} matches input {i} "
                        f"({out_sigs[o][0]}/{out_sigs[o][1]}) — donate "
                        "it, or annotate the call site with "
                        f"'# {DONATE_ALLOW_MARKER} (reason)'"
                    ),
                ))


def audit_pallas_calls(closed_jaxpr, path: str = "fixture") -> Report:
    """Alias-consistency + donation-coverage over every kernel launch
    in one jaxpr — the per-path half of :func:`check_effects`, exposed
    for fixture tests."""
    report = Report()
    stats = {"pallas_calls_audited": 0, "alias_pairs_checked": 0}
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name == "pallas_call":
            _audit_pallas_eqn(eqn, path, report, stats)
    report.stats.update(stats)
    return report


# ---------------------------------------------------------------------------
# Scan-carry donation audit.


def _contains_pallas(jaxpr) -> bool:
    return any(
        e.primitive.name == "pallas_call" for e in iter_eqns(jaxpr)
    )


def _find_scans(jaxpr):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            yield eqn
            continue
        if eqn.primitive.name == "pallas_call":
            continue
        for p in eqn.params.values():
            for sub in _as_jaxprs(p):
                yield from _find_scans(sub)


def audit_scan_carries(closed_jaxpr, path, report, stats) -> None:
    """Audit every kernel-launching ``scan`` in ``closed_jaxpr``: each
    carry must either pass through untouched, chase back to an aliased
    kernel output whose alias source is the carry state, or be a plain
    XLA value (counted as ``xla_carries`` — XLA owns that reuse)."""
    jaxpr = closed_jaxpr.jaxpr
    for scan_eqn in _find_scans(jaxpr):
        body = scan_eqn.params["jaxpr"]
        bj = body.jaxpr if hasattr(body, "jaxpr") else body
        if not _contains_pallas(bj):
            stats["scans_without_kernels"] += 1
            continue
        stats["kernel_scans_audited"] += 1
        nc = scan_eqn.params.get("num_consts", 0)
        nk = scan_eqn.params.get("num_carry", 0)
        frames0 = [_Frame(bj, _producers(bj), None)]
        for c in range(nk):
            stats["scan_carries_audited"] += 1
            kind, payload, j, frames = _chase_back(
                bj.outvars[c], frames0
            )
            if kind == "pallas":
                eqn = payload
                where = source_location(eqn)
                aliases = dict(
                    eqn.params.get("input_output_aliases") or ()
                )
                srcs = [i for i, o in aliases.items() if o == j]
                if not srcs:
                    report.findings.append(Finding(
                        ki="KI-5", check="scan-carry", path=path,
                        where=where,
                        message=(
                            f"scan carry {c} is kernel output {j} with "
                            "no alias onto it: every round allocates a "
                            "fresh HBM generation of this carry "
                            "(input_output_aliases must hand the "
                            "carried buffer back)"
                        ),
                    ))
                    continue
                k2, idx2, _, _ = _chase_back(
                    eqn.invars[srcs[0]], frames
                )
                if k2 == "invar" and nc <= idx2 < nc + nk:
                    stats["donated_carries"] += 1
                else:
                    report.findings.append(Finding(
                        ki="KI-5", check="scan-carry", path=path,
                        where=where,
                        message=(
                            f"scan carry {c} aliases kernel input "
                            f"{srcs[0]}, but that input does not "
                            "originate from the scan carry state "
                            f"(chased to {k2}) — the donated buffer is "
                            "not the carried one"
                        ),
                    ))
            elif kind == "invar" and payload is not None and (
                nc <= payload < nc + nk
            ):
                stats["passthrough_carries"] += 1
            else:
                stats["xla_carries"] += 1


def audit_scans(closed_jaxpr, path: str = "fixture") -> Report:
    """Scan-carry donation audit over one jaxpr — exposed for fixture
    tests; :func:`check_effects` drives the engine sweep."""
    report = Report()
    stats = {
        "kernel_scans_audited": 0,
        "scan_carries_audited": 0,
        "donated_carries": 0,
        "passthrough_carries": 0,
        "xla_carries": 0,
        "scans_without_kernels": 0,
    }
    audit_scan_carries(closed_jaxpr, path, report, stats)
    report.stats.update(stats)
    return report


def trace_trial_scan(cfg: QBAConfig, engine: str):
    """``jax.make_jaxpr`` of one full ``run_trial`` with the round
    engine forced, so the audit sees the scan exactly as dispatch
    builds it (plan resolution, demotions and all).  Memoized per
    (config, engine) for the lint run — the launch pins trace the
    same paths (:mod:`qba_tpu.analysis.tracecache`)."""
    from qba_tpu.analysis.tracecache import trial_jaxpr

    closed, _caught = trial_jaxpr(cfg, engine)
    return closed


def _audit_engine_scans(cfg, engines, report, stats) -> None:
    import jax

    for engine in SCAN_ENGINES:
        if engine not in engines:
            continue
        before = dict(stats)
        try:
            closed = trace_trial_scan(cfg, engine)
        except Exception as exc:  # demoted/unbuildable path -> note
            report.notes.append(
                f"effects/{engine}: scan audit skipped "
                f"({type(exc).__name__}: {exc})"
            )
            continue
        audit_scan_carries(closed, f"{engine}/run_trial", report, stats)
        donated = stats["donated_carries"] - before.get(
            "donated_carries", 0
        )
        audited = stats["scan_carries_audited"] - before.get(
            "scan_carries_audited", 0
        )
        if audited:
            report.notes.append(
                f"effects/{engine}: {donated}/{audited} round-scan "
                "carries kernel-donated"
            )
        else:
            report.notes.append(
                f"effects/{engine}: round scan is XLA-managed "
                "(no kernel launch in the body; donation is XLA "
                "buffer reuse)"
            )
    # The packed fused runner folds trials into the kernel grid; its
    # scan carries the packed pool and must donate it the same way.
    if "pallas_fused" in engines:
        try:
            from qba_tpu.rounds.engine import run_trials_fused_packed

            keys = jax.random.split(jax.random.key(0), 2)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                closed = jax.make_jaxpr(
                    lambda k: run_trials_fused_packed(cfg, k, 2)
                )(keys)
        except Exception as exc:
            report.notes.append(
                f"effects/fused_packed: scan audit skipped "
                f"({type(exc).__name__}: {exc})"
            )
        else:
            audit_scan_carries(
                closed, "fused_packed/run_trials", report, stats
            )


def _audit_mega(cfg, report, stats) -> None:
    """KI-5 for the scan-free megakernel engine: the donation story of
    ``pallas_mega`` is that there are NO round-scan carries at all —
    vi/pool state lives in VMEM scratch inside one launch.  The audit
    must prove that claim from the jaxpr, not silently skip a scan it
    cannot find: trace ``run_trial`` with the engine forced and assert
    (a) zero ``lax.scan``s whose body launches a kernel, and (b)
    exactly ONE ``pallas_call`` in the whole trial.  A recorded
    demotion (no plan / counters requested) is noted — the demoted
    path is one of the :data:`SCAN_ENGINES` and gets the ordinary
    carry audit on its own trace."""
    from qba_tpu.analysis.tracecache import trial_jaxpr
    from qba_tpu.diagnostics import QBADemotionWarning

    try:
        closed, caught = trial_jaxpr(cfg, "pallas_mega")
    except Exception as exc:
        report.findings.append(Finding(
            ki="KI-5", check="mega-one-launch", path="pallas_mega/run_trial",
            message=(
                f"megakernel trial trace failed ({type(exc).__name__}: "
                f"{exc}) — neither the one-launch proof nor a recorded "
                "demotion exists for this config"
            ),
        ))
        return
    demotions = [
        w for w in caught if issubclass(w.category, QBADemotionWarning)
    ]
    if demotions:
        report.notes.append(
            "effects/pallas_mega: recorded demotion at this config "
            f"({demotions[0].message}) — the demoted engine's scan is "
            "audited under its own trace"
        )
        stats["mega_demotions_recorded"] += 1
        return
    kernel_scans = sum(
        1 for s in _find_scans(closed.jaxpr)
        if _contains_pallas(
            s.params["jaxpr"].jaxpr
            if hasattr(s.params["jaxpr"], "jaxpr")
            else s.params["jaxpr"]
        )
    )
    launches = sum(
        1 for e in iter_eqns(closed.jaxpr)
        if e.primitive.name == "pallas_call"
    )
    stats["mega_launches_counted"] = launches
    if kernel_scans:
        report.findings.append(Finding(
            ki="KI-5", check="mega-one-launch",
            path="pallas_mega/run_trial",
            message=(
                f"megakernel trial still contains {kernel_scans} "
                "kernel-launching scan(s): the round loop has NOT moved "
                "in-kernel, and its carries escape the donation audit "
                "(SCAN_ENGINES does not trace pallas_mega)"
            ),
        ))
    if launches != 1:
        report.findings.append(Finding(
            ki="KI-5", check="mega-one-launch",
            path="pallas_mega/run_trial",
            message=(
                f"megakernel trial launches {launches} pallas_call(s), "
                "expected exactly 1 — the one-launch-per-trial contract "
                "(docs/PERF.md round 8) is broken"
            ),
        ))
    if not kernel_scans and launches == 1:
        report.notes.append(
            "effects/pallas_mega: round scan PROVEN eliminated — "
            "1 pallas_call, 0 kernel-launching scans in the full trial "
            "jaxpr (no host carries exist to donate)"
        )
    _audit_mega_gen(cfg, closed, report, stats)


def _audit_mega_gen(cfg, closed, report, stats) -> None:
    """Gen-fused extension of the KI-5 megakernel audit: when
    ``mega_gen`` resolves ``"gf2"``, step-1 resource generation claims
    to run in VMEM inside the same launch.  Prove it from the same
    trial jaxpr the one-launch check used — the host generation path
    evaluates its GF(2) measurement sweeps as ``lax.scan``s outside
    any kernel, so the gen-fused trace must carry ZERO host-side
    scans (the launch count alone stays 1 either way and cannot see
    the leak)."""
    from qba_tpu.analysis.launches import count_host_scans
    from qba_tpu.ops.round_kernel_tiled import resolve_mega_gen

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        gen = resolve_mega_gen(cfg)
    if gen != "gf2":
        return
    host_scans = count_host_scans(closed.jaxpr)
    stats["mega_gen_host_scans"] = host_scans
    if host_scans:
        report.findings.append(Finding(
            ki="KI-5", check="mega-gen-in-kernel",
            path="pallas_mega/run_trial",
            message=(
                f"mega_gen resolved 'gf2' but {host_scans} host-side "
                "scan(s) remain in the trial jaxpr — the generation "
                "sweep leaked back outside the one launch"
            ),
        ))
    else:
        report.notes.append(
            "effects/pallas_mega: generation PROVEN in-kernel — "
            "mega_gen='gf2', 0 host-side scans alongside the single "
            "launch (host generation would carry its measurement "
            "sweeps as scans)"
        )


# ---------------------------------------------------------------------------
# Top-level jit donation audit (AST).


def _jit_calls(tree):
    """Yield every ``jax.jit`` application in ``tree`` — direct
    decorator, ``jax.jit(...)`` call, or ``functools.partial(jax.jit,
    ...)`` — with the keyword dict that configures it."""
    def is_jax_jit(node):
        return (
            isinstance(node, ast.Attribute) and node.attr == "jit"
            and isinstance(node.value, ast.Name)
            and node.value.id == "jax"
        )

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if is_jax_jit(fn):
            yield node, {kw.arg: kw.value for kw in node.keywords}
        elif (
            (isinstance(fn, ast.Name) and fn.id == "partial")
            or (isinstance(fn, ast.Attribute) and fn.attr == "partial")
        ) and node.args and is_jax_jit(node.args[0]):
            yield node, {kw.arg: kw.value for kw in node.keywords}


def _int_set(node) -> set[int] | None:
    if node is None:
        return set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out = set()
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.add(e.value)
            else:
                return None
        return out
    return None


def check_jit_donation(source_paths=None) -> Report:
    """KI-5 over the top-level dispatch jits: ``donate_argnums``
    claims must be sound (no overlap with ``static_argnums``), and the
    zero-donation policy is recorded.  Zero jits found is itself a
    finding — the audit no longer matches the module layout."""
    report = Report()
    if source_paths is None:
        import qba_tpu.backends.jax_backend as jb
        import qba_tpu.parallel.spmd as spmd_mod
        import qba_tpu.sweep as sweep_mod

        # sweep.py carries the device-resident loop jits, whose
        # while-carry donation (KI-5) must stay sound.
        source_paths = [jb.__file__, spmd_mod.__file__, sweep_mod.__file__]
    jits = 0
    claims = 0
    path_jits: dict[str, int] = {}
    path_claims: dict[str, int] = {}
    for path in source_paths:
        with open(path) as fh:
            tree = ast.parse(fh.read(), filename=path)
        rel = os.path.basename(path)
        for call, kws in _jit_calls(tree):
            jits += 1
            path_jits[rel] = path_jits.get(rel, 0) + 1
            where = f"{path}:{call.lineno}"
            donate = kws.get("donate_argnums") or kws.get(
                "donate_argnames"
            )
            if donate is None:
                continue
            claims += 1
            path_claims[rel] = path_claims.get(rel, 0) + 1
            dset = _int_set(kws.get("donate_argnums"))
            sset = _int_set(kws.get("static_argnums"))
            if dset is None or sset is None:
                report.notes.append(
                    f"effects/jit: non-literal donate/static argnums "
                    f"at {where} — donation soundness not statically "
                    "checkable"
                )
                continue
            overlap = dset & sset
            if overlap:
                report.findings.append(Finding(
                    ki="KI-5", check="jit-donation", path=f"jit:{rel}",
                    where=where,
                    message=(
                        f"donate_argnums {sorted(overlap)} are also "
                        "static_argnums: a static argument has no "
                        "buffer to donate — the claim is dead "
                        "machinery"
                    ),
                ))
            else:
                report.notes.append(
                    f"effects/jit: donation claim {sorted(dset)} at "
                    f"{where}"
                )
    if jits == 0:
        report.findings.append(Finding(
            ki="KI-5", check="jit-donation", path="jit:*",
            message=(
                "found zero jax.jit applications in the dispatch "
                "modules — the donation audit no longer matches the "
                "module layout"
            ),
        ))
    else:
        # Per-module policy: the dispatch modules (jax_backend, spmd)
        # keep zero donation claims — trial keys are reused across
        # repeat dispatches by bench/serve and carry donation lives in
        # the kernel input_output_aliases.  The device-loop jits in
        # sweep.py are the recorded exception (each donates its
        # while-carry; claims noted above).
        zero_jits = sum(
            n for rel, n in path_jits.items()
            if path_claims.get(rel, 0) == 0
        )
        if zero_jits:
            report.notes.append(
                f"effects/jit: {zero_jits} dispatch jits, zero "
                "donate_argnums claims (policy: trial keys are reused "
                "across repeat dispatches by bench/serve; carry "
                "donation lives in the kernel input_output_aliases)"
            )
    report.stats["jits_audited"] = jits
    return report


# ---------------------------------------------------------------------------
# Entry point.


def check_effects(cfg: QBAConfig, paths, engines) -> Report:
    """Run the KI-5 audit for one lint config: alias consistency and
    donation coverage over every already-traced build path, plus the
    scan-carry audit over each engine's full ``run_trial`` jaxpr.
    ``paths`` is the :func:`qba_tpu.analysis.traces.trace_paths`
    output (re-used, not re-traced)."""
    report = Report()
    stats = {
        "pallas_calls_audited": 0,
        "alias_pairs_checked": 0,
        "kernel_scans_audited": 0,
        "scan_carries_audited": 0,
        "donated_carries": 0,
        "passthrough_carries": 0,
        "xla_carries": 0,
        "scans_without_kernels": 0,
        "mega_demotions_recorded": 0,
    }
    kernel_free_paths = []
    for p in paths:
        before = stats["pallas_calls_audited"]
        for eqn in iter_eqns(p.closed_jaxpr.jaxpr):
            if eqn.primitive.name == "pallas_call":
                _audit_pallas_eqn(eqn, p.name, report, stats)
        if stats["pallas_calls_audited"] == before:
            kernel_free_paths.append(p.name)
    if kernel_free_paths:
        report.notes.append(
            "effects: kernel-free build paths (donation is XLA buffer "
            f"reuse): {', '.join(sorted(kernel_free_paths))}"
        )
    _audit_engine_scans(cfg, set(engines), report, stats)
    if "pallas_mega" in set(engines):
        _audit_mega(cfg, report, stats)
    report.stats.update(stats)
    return report
