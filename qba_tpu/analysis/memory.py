"""KI-2 static VMEM/HBM plan audit.

KNOWN_ISSUES KI-2 is the memory discipline the tiled engines live by:
every kernel build goes through a VMEM *pre-filter* (a loose static
estimate screened against a per-kernel budget) before the
authoritative compile probe, and the resident pool's TPU-padded bytes
set the HBM trial ceiling.  Nothing at runtime re-checks that the
resolved plans actually satisfy their own budgets — an estimate edit,
a budget bump, or a planner change can silently ship a plan the
pre-filter would reject.  This pass re-derives everything statically:

* **Plan-vs-budget**: for each engine family (verdict / rebuild /
  fused, global and party-sharded), the resolved block size must
  divide its pool and its VMEM estimate must fit the budget it was
  screened against (``_TILED_PREFILTER_BYTES`` / ``_REBUILD_BUDGET`` /
  ``_FUSED_BUDGET``).  An explicit ``cfg.tiled_block`` override that
  busts the budget is flagged — off-TPU resolution honors it
  unchecked, so the lint is the only gate.
* **HBM trial ceiling**: the planning model
  ``floor((HBM - reserve) / (occupancy * padded_pool_bytes))`` with
  the v5e constants below; for the north-star config the prediction
  must stay inside the measured batch band (the model is calibrated
  against hardware sweeps — drifting out of band means the padding
  model or the occupancy factor no longer describes the machine).
* **Probe hygiene**: resolving plans off-TPU must never fire a compile
  probe (``PROBE_STATS`` delta) — interpret-mode planning is pure
  arithmetic by design.

Findings mean the *plan* is statically inconsistent with its own
budget model; notes carry the derived numbers (ceilings, roofline
shares) so the lint doubles as a capacity report.
"""

from __future__ import annotations

import jax

from qba_tpu.analysis.findings import Finding, Report
from qba_tpu.config import QBAConfig

#: v5e HBM planning constants (docs/PERF.md): usable HBM, runtime
#: reserve, and the occupancy factor covering the donated pool plus the
#: transient successor generation the rebuild writes.
HBM_BYTES = int(15.75 * 2**30)
HBM_RESERVE = int(1.5 * 2**30)
POOL_OCCUPANCY = 1.5

#: The north-star config and its measured max-trials band on v5e —
#: the calibration anchor for the ceiling model.
NORTH_STAR = (33, 64, 10)
NORTH_STAR_CEILING_BAND = (1088, 1151)

#: Mesh shapes (dp, tp) the lint predicts per-device budgets for by
#: default — the matrix the round-9 remote-DMA sharding landed behind.
#: dp replicates trials across data-parallel devices; tp shards the
#: receiver axis of the mailbox pool.  (1, 8) is the full-width shard
#: of this container's 8 devices — the shape the 65p over-budget
#: scenario runs on.
DEFAULT_MESH_SHAPES = ((2, 4), (1, 8))

#: Comms transports the sharded model prices (mirrors
#: ``qba_tpu.parallel.ring.TP_COMMS_CHOICES``; "ring" is the round-9
#: default the admission controller and the findings gate use).
TP_COMMS_MODELED = ("ring", "all_gather")


def trial_ceiling(cfg: QBAConfig, hbm_bytes: int = HBM_BYTES) -> int:
    """Predicted max concurrent trials before the pool exhausts HBM."""
    from qba_tpu.ops.round_kernel_tiled import pool_bytes

    per_trial = pool_bytes(cfg)["padded_bytes"]
    return int((hbm_bytes - HBM_RESERVE) // (POOL_OCCUPANCY * per_trial))


def sharded_pool_bytes(cfg: QBAConfig, tp: int) -> dict:
    """Per-device resident pool under tp-way party sharding: each
    device carries ``n_lieutenants // tp`` receivers' mailbox rows, so
    the padding model applies to the *shard's* cap, not the global one
    (narrow shards pay proportionally more padding — the pad_ratio in
    the result is the honest per-device number)."""
    from qba_tpu.ops.round_kernel_tiled import pool_bytes

    if tp < 1 or cfg.n_lieutenants % tp != 0:
        raise ValueError(
            f"tp={tp} does not divide n_lieutenants={cfg.n_lieutenants}"
        )
    return pool_bytes(cfg, n_recv=cfg.n_lieutenants // tp)


def comms_buffer_bytes(cfg: QBAConfig, tp: int, comms: str = "ring") -> int:
    """Per-trial comms transient resident NEXT to the local pool shard
    during a voting round's gather, in shard-padded bytes.

    * ``all_gather`` transiently materializes every remote shard at
      once: ``(tp - 1) x shard`` — the term that eats the linear-in-tp
      ceiling (the pre-round-9 KI-2 wall).
    * ``ring`` keeps only the double-buffered slot pair of the
      remote-DMA shuffle (:mod:`qba_tpu.ops.ring_shuffle`):
      ``min(2, tp - 1) x shard`` — constant in tp.

    Both are exactly 0 at tp=1 (no comms), which is what makes
    :func:`sharded_trial_ceiling` reduce to :func:`trial_ceiling`."""
    if comms not in TP_COMMS_MODELED:
        raise ValueError(
            f"unknown comms {comms!r}; expected one of {TP_COMMS_MODELED}"
        )
    if tp <= 1:
        return 0
    shard = sharded_pool_bytes(cfg, tp)["padded_bytes"]
    hops_resident = (tp - 1) if comms == "all_gather" else min(2, tp - 1)
    return hops_resident * shard


def sharded_trial_ceiling(
    cfg: QBAConfig, dp: int = 1, tp: int = 1,
    hbm_bytes: int = HBM_BYTES, comms: str = "ring",
) -> dict:
    """Per-device and whole-mesh trial ceilings for a (dp, tp) mesh.

    tp shards the receiver axis (each device holds a
    ``n_lieutenants // tp`` slice of the pool), dp replicates the
    tp-group over independent trials — so the per-device ceiling is
    set by the *sharded* pool bytes plus the comms transient
    (:func:`comms_buffer_bytes`) against one device's HBM, and the
    mesh ceiling is ``dp`` times that (trials never share state across
    dp replicas).  Under ``comms="ring"`` the per-trial footprint is
    ``occupancy x shard + 2 x shard`` — constant multiplier, so the
    ceiling scales ~linearly in tp; under ``all_gather`` the
    multiplier grows with tp and the scaling flattens.  (dp=1, tp=1)
    reduces exactly to :func:`trial_ceiling` for either comms."""
    per_device_pool = sharded_pool_bytes(cfg, tp)["padded_bytes"]
    comms_bytes = comms_buffer_bytes(cfg, tp, comms)
    footprint = POOL_OCCUPANCY * per_device_pool + comms_bytes
    per_device = int((hbm_bytes - HBM_RESERVE) // footprint)
    return {
        "dp": dp,
        "tp": tp,
        "comms": comms,
        "n_recv": cfg.n_lieutenants // tp,
        "per_device_pool_bytes": per_device_pool,
        "comms_buffer_bytes": comms_bytes,
        "per_device_trials": per_device,
        "mesh_trials": dp * per_device,
    }


def _audit_plans(cfg: QBAConfig, n_recv: int | None, report: Report,
                 prefix: str | None = None) -> None:
    from qba_tpu.ops.round_kernel_tiled import (
        _FUSED_BUDGET,
        _REBUILD_BUDGET,
        _TILED_PREFILTER_BYTES,
        _block_estimate,
        _fused_estimate,
        _rebuild_estimate,
        block_candidates,
        fused_candidates,
        rebuild_candidates,
        resolve_fused_block,
        resolve_rebuild_block,
        resolve_tiled_block,
        resolve_trial_pack,
        resolve_verdict_variant,
    )

    if prefix is None:
        prefix = "spmd/" if n_recv is not None else ""
    n_rv = n_recv if n_recv is not None else cfg.n_lieutenants
    n_pool = cfg.n_lieutenants * cfg.slots
    n_out = n_rv * cfg.slots
    shape = f"(n_parties={cfg.n_parties}, size_l={cfg.size_l})"

    def check(path, cands, pool, est_fn, budget, budget_name,
              resolved, demote_msg):
        # 1. Pre-filter self-consistency: every candidate the planner
        #    would hand the TPU compile probe must fit the budget it
        #    was screened against and tile the pool exactly.
        for b in cands:
            est = est_fn(b)
            if est > budget:
                report.findings.append(Finding(
                    ki="KI-2", check="vmem-plan", path=path,
                    message=(
                        f"candidate block {b} at {shape}: VMEM estimate "
                        f"{est / 2**20:.1f} MiB exceeds {budget_name} "
                        f"({budget / 2**20:.0f} MiB) — the candidate list "
                        "violates its own pre-filter"
                    ),
                ))
            if pool % b != 0:
                report.findings.append(Finding(
                    ki="KI-2", check="vmem-plan", path=path,
                    message=(
                        f"candidate block {b} does not divide its pool "
                        f"({pool}) at {shape}: the grid would drop or "
                        "double-visit packets"
                    ),
                ))
        if not cands:
            report.notes.append(f"{path}: {demote_msg} at {shape}")
        else:
            b0 = cands[0]
            report.notes.append(
                f"{path}: TPU plan probes block {b0} first, estimate "
                f"{est_fn(b0) / 2**20:.1f} MiB within {budget_name} "
                f"{budget / 2**20:.0f} MiB"
            )
        # 2. Whatever this backend resolved must still tile the pool
        #    (interpret mode skips the budget, never the grid math).
        if resolved is not None and pool % resolved != 0:
            report.findings.append(Finding(
                ki="KI-2", check="vmem-plan", path=path,
                message=(
                    f"resolved block {resolved} does not divide its pool "
                    f"({pool}) at {shape}"
                ),
            ))
        # 3. An explicit tiled_block override is honored unchecked
        #    off-TPU — flag it when it busts the TPU budget, because
        #    CPU tests would then exercise a plan the TPU rejects.
        if (
            cfg.tiled_block is not None and pool % cfg.tiled_block == 0
            and est_fn(cfg.tiled_block) > budget
        ):
            report.findings.append(Finding(
                ki="KI-2", check="vmem-plan", path=path,
                message=(
                    f"explicit tiled_block={cfg.tiled_block} at {shape}: "
                    f"VMEM estimate "
                    f"{est_fn(cfg.tiled_block) / 2**20:.1f} MiB exceeds "
                    f"{budget_name} ({budget / 2**20:.0f} MiB) — off-TPU "
                    "runs honor the override unchecked, so tests no "
                    "longer model a plan the TPU would accept"
                ),
            ))

    variant = resolve_verdict_variant(cfg, n_recv=n_recv)
    blk_v = resolve_tiled_block(cfg, n_recv=n_recv)
    check(
        f"{prefix}pallas_tiled/verdict",
        block_candidates(cfg, n_recv, variant), n_pool,
        lambda b: _block_estimate(cfg, b, n_recv, variant),
        _TILED_PREFILTER_BYTES, "_TILED_PREFILTER_BYTES",
        blk_v, "no verdict block fits; engine unavailable on TPU",
    )
    check(
        f"{prefix}pallas_tiled/rebuild",
        rebuild_candidates(cfg, n_recv), n_out,
        lambda b: _rebuild_estimate(cfg, b, n_recv),
        _REBUILD_BUDGET, "_REBUILD_BUDGET",
        resolve_rebuild_block(cfg, n_recv=n_recv),
        "demotes to the XLA rebuild on TPU",
    )
    pack = resolve_trial_pack(cfg) if n_recv is None else 1
    check(
        f"{prefix}pallas_fused/round",
        fused_candidates(cfg, n_recv, blk_v, pack), n_out,
        lambda b: _fused_estimate(cfg, b, blk_v, n_recv, pack),
        _FUSED_BUDGET, "_FUSED_BUDGET",
        resolve_fused_block(cfg, n_recv=n_recv, trial_pack=pack),
        "demotes to the two-kernel tiled path on TPU",
    )
    if n_recv is None:
        # The trial megakernel's whole-launch VMEM scratch budget is
        # the KI-2 entry that decides whether one trial's decode + all
        # rounds + reduce fit residency at once.  The gen-fused launch
        # additionally prices the in-VMEM GF(2) tableau working set
        # and gives up _MEGA_RESERVE for the prologue's unpriced
        # transients — audited against the reserved budget exactly as
        # the planner screens it.
        from qba_tpu.ops.round_kernel_tiled import (
            _mega_budget,
            _mega_estimate,
            _mega_gen_bytes,
            mega_candidates,
            resolve_mega_block,
            resolve_mega_gen,
        )

        gen = resolve_mega_gen(cfg, pack) == "gf2"
        mega_plan = resolve_mega_block(cfg, trial_pack=pack)
        check(
            "pallas_mega/trial" + ("+gen" if gen else ""),
            mega_candidates(cfg, blk_v, pack, gen=gen), n_pool,
            lambda b: _mega_estimate(cfg, b, blk_v, pack, gen=gen),
            _mega_budget(gen),
            "_mega_budget(gen=True)" if gen else "_MEGA_BUDGET",
            mega_plan[0] if mega_plan is not None else None,
            "demotes to the fused per-round engine on TPU"
            if not gen else "demotes to host-side generation on TPU",
        )
        if gen:
            report.notes.append(
                f"pallas_mega/trial+gen: in-VMEM generation prices "
                f"{_mega_gen_bytes(cfg, pack) / 2**20:.1f} MiB of "
                f"tableau working set at {shape}; the launch budget "
                "holds back the _MEGA_RESERVE guard for sweep "
                "transients"
            )
    else:
        # The party-sharded megakernel: per-shard launch residency
        # (one assembled global pool half + local halves + the
        # double-buffered in-kernel ring slots) against the RESERVED
        # budget — the in-flight remote-DMA transients draw on the
        # same guard the gen prologue does.
        from qba_tpu.ops.round_kernel_tiled import (
            _mega_budget,
            _sharded_mega_estimate,
            sharded_mega_candidates,
            sharded_mega_plan,
        )

        n_tp = cfg.n_lieutenants // n_recv
        loc_rows = n_recv * cfg.slots
        sh_plan = sharded_mega_plan(cfg, n_tp)
        check(
            f"{prefix}pallas_mega/trial",
            sharded_mega_candidates(cfg, n_tp, blk_v), loc_rows,
            lambda b: _sharded_mega_estimate(cfg, b, blk_v, n_tp),
            _mega_budget(gen=True), "_mega_budget(gen=True)",
            sh_plan[0] if sh_plan is not None else None,
            "demotes to the fused per-round engine under the tp mesh",
        )


def device_loop_carry_bytes(
    n_chunks: int, chunk_trials: int, n_cells: int = 1,
    per_trial_bits: bool = False,
) -> dict:
    """KI-2 footprint model of the device-resident sequential loop's
    while-carry (docs/STATS.md "Device-resident stopping",
    KNOWN_ISSUES "Device-loop while-carry residency").

    The carry is deliberately integer-thin — the engine's one-chunk
    working set (pool, mailbox, verdicts) is identical to what the
    host loop dispatches per chunk, so the device loop's *additional*
    residency is exactly what this model prices:

    * per cell: cumulative count + chunk cursor + done flag
      (scalars), per-chunk counts (``int32[n_chunks]``) and overflow
      flags (``bool[n_chunks]``) kept for the host's checkpoint-parity
      replay;
    * shared: the stop tables (``2 x int32[n_chunks+1]``) and, for the
      adaptive surface, the schedule/tier logs
      (``2 x int32[n_cells*n_chunks]``);
    * ``per_trial_bits``: the serve early-finish loop also carries the
      per-trial success bits (``bool[n_chunks*chunk_trials]``) and the
      request's key table (``uint32[2][n_chunks*chunk_trials]``).
    """
    per_cell = 4 + 4 + 1 + n_chunks * 4 + n_chunks * 1
    shared = 2 * (n_chunks + 1) * 4
    if n_cells > 1:
        shared += 2 * n_cells * n_chunks * 4  # sched + tier logs
    if per_trial_bits:
        per_cell += n_chunks * chunk_trials * (1 + 8)
    return {
        "n_chunks": n_chunks,
        "chunk_trials": chunk_trials,
        "n_cells": n_cells,
        "per_cell_bytes": per_cell,
        "shared_bytes": shared,
        "total_bytes": n_cells * per_cell + shared,
    }


def gf2_tableau_bytes(cfg: QBAConfig) -> dict:
    """Packed-tableau working set of the batched GF(2) sampler, per
    shot (one list position): x + z packed word planes ``[2n, W]``
    uint32, the phase vector, the coin vector, and the output bits.
    The 32x packing is the KI-2 story for this engine — at 129 parties
    (n = 1040 qubits) the packed planes are ~541 KiB/shot where int32
    flag planes would be ~16.5 MiB."""
    from qba_tpu.gf2 import n_words

    n = cfg.total_qubits
    w = n_words(n)
    planes = 2 * (2 * n) * w * 4       # x + z, uint32 words
    vectors = (2 * n + 2 * n) * 4      # phase r + the two where-branches
    per_shot = planes + vectors + 2 * n * 4
    return {
        "n_qubits": n,
        "words_per_row": w,
        "per_shot_bytes": per_shot,
        "per_position_unpacked_bytes": 2 * (2 * n) * n * 4,
    }


def gf2_shot_ceiling(cfg: QBAConfig, hbm_bytes: int = HBM_BYTES) -> int:
    """Predicted max concurrent shots (trials x size_l list positions)
    of the batched GF(2) sampler before the packed tableau batch
    exhausts HBM — same planning model as :func:`trial_ceiling`."""
    per_shot = gf2_tableau_bytes(cfg)["per_shot_bytes"]
    return int((hbm_bytes - HBM_RESERVE) // (POOL_OCCUPANCY * per_shot))


def check_gf2_memory(cfg: QBAConfig) -> Report:
    """KI-2 entry for the packed-tableau shapes of the gf2 engine."""
    report = Report()
    tb = gf2_tableau_bytes(cfg)
    shots = gf2_shot_ceiling(cfg)
    trials = shots // max(cfg.size_l, 1)
    report.notes.append(
        f"gf2-tableau: {tb['n_qubits']} qubits packed to "
        f"{tb['words_per_row']} words/row, "
        f"{tb['per_shot_bytes']} B/shot "
        f"({tb['per_position_unpacked_bytes']} B unpacked) -> "
        f"~{shots} concurrent shots, ~{trials} trials at "
        f"size_l={cfg.size_l} on v5e"
    )
    if trials < 1:
        report.findings.append(Finding(
            ki="KI-2", check="gf2-tableau", path="gf2/sampler",
            message=(
                f"packed tableau batch for one trial "
                f"({cfg.size_l} positions x {tb['per_shot_bytes']} "
                f"B/shot) cannot fit under the v5e model "
                f"({HBM_BYTES} B HBM, {HBM_RESERVE} B reserve, "
                f"occupancy {POOL_OCCUPANCY}) — shard list positions "
                "before dispatching this shape"
            ),
        ))
    return report


def check_memory(cfg: QBAConfig) -> Report:
    """Run the KI-2 audit for one config (global + 2-way sharded)."""
    from qba_tpu.ops.round_kernel_tiled import (
        PROBE_STATS,
        pool_bytes,
        roofline_model,
    )

    report = Report()
    probes_before = PROBE_STATS["compile_probes"]
    _audit_plans(cfg, None, report)
    if cfg.n_lieutenants % 2 == 0:
        _audit_plans(cfg, cfg.n_lieutenants // 2, report)

    pb = pool_bytes(cfg)
    ceiling = trial_ceiling(cfg)
    report.notes.append(
        f"hbm-ceiling: padded pool {pb['padded_bytes']} B/trial "
        f"(pad ratio {pb['pad_ratio']}) -> predicted max "
        f"~{ceiling} concurrent trials on v5e"
    )
    if ceiling < 1:
        report.findings.append(Finding(
            ki="KI-2", check="hbm-ceiling", path="pallas_tiled",
            message=(
                f"padded pool {pb['padded_bytes']} B/trial cannot fit a "
                f"single trial under the v5e model ({HBM_BYTES} B HBM, "
                f"{HBM_RESERVE} B reserve, occupancy {POOL_OCCUPANCY})"
            ),
        ))
    key = (cfg.n_parties, cfg.size_l, cfg.n_dishonest)
    if key == NORTH_STAR:
        lo, hi = NORTH_STAR_CEILING_BAND
        if not (lo <= ceiling <= hi):
            report.findings.append(Finding(
                ki="KI-2", check="hbm-ceiling", path="pallas_tiled",
                message=(
                    f"north-star trial-ceiling prediction {ceiling} left "
                    f"the measured v5e band [{lo}, {hi}]: the padding "
                    "model or occupancy factor no longer matches "
                    "hardware (recalibrate against a measured sweep "
                    "before trusting batch sizing)"
                ),
            ))
        else:
            report.notes.append(
                f"hbm-ceiling: north-star prediction {ceiling} inside "
                f"the measured band [{lo}, {hi}]"
            )
    rf = roofline_model(cfg)
    report.notes.append(
        f"roofline: {rf['per_round_per_trial_bytes']} B/round/trial "
        f"upper bound, pool share {rf['pool_share']}"
    )

    # Device-resident loop carry (ROADMAP item 3): the while-carry the
    # single-dispatch targeted paths keep resident across chunks, at a
    # representative 64-chunk budget.  The engine's per-chunk working
    # set is unchanged from the host loop; the carry is the delta.
    dl = device_loop_carry_bytes(64, cfg.trials)
    dl_serve = device_loop_carry_bytes(64, cfg.trials, per_trial_bits=True)
    report.notes.append(
        f"device-loop-carry: {dl['total_bytes']} B resident across a "
        f"64-chunk targeted sweep (serve early-finish with per-trial "
        f"bits + key table: {dl_serve['total_bytes']} B) — negligible "
        "next to the per-trial pool; the chunk working set is the host "
        "loop's own"
    )
    if dl_serve["total_bytes"] > HBM_BYTES - HBM_RESERVE:
        report.findings.append(Finding(
            ki="KI-2", check="device-loop-carry", path="sweep/device",
            message=(
                f"device-loop carry {dl_serve['total_bytes']} B at a "
                "64-chunk budget no longer fits the v5e model — the "
                "carry has stopped being integer-thin"
            ),
        ))

    # Sharded per-device budgets (ROADMAP item 1): for each default
    # mesh shape, re-run the plan audit at the per-device receiver
    # shard and predict the per-device / mesh trial ceilings.
    meshes_checked = 0
    for dp, tp in DEFAULT_MESH_SHAPES:
        if cfg.n_lieutenants % tp != 0:
            report.notes.append(
                f"sharded-hbm: mesh (dp={dp}, tp={tp}) skipped — tp "
                f"does not divide n_lieutenants={cfg.n_lieutenants}"
            )
            continue
        meshes_checked += 1
        if cfg.n_lieutenants // tp != cfg.n_lieutenants // 2:
            _audit_plans(cfg, cfg.n_lieutenants // tp, report,
                         prefix=f"spmd[tp={tp}]/")
        sc = sharded_trial_ceiling(cfg, dp=dp, tp=tp, comms="ring")
        sc_ag = sharded_trial_ceiling(cfg, dp=dp, tp=tp,
                                      comms="all_gather")
        report.notes.append(
            f"sharded-hbm[dp={dp},tp={tp}]: per-device pool "
            f"{sc['per_device_pool_bytes']} B/trial "
            f"(n_recv={sc['n_recv']}, ring comms "
            f"+{sc['comms_buffer_bytes']} B) -> "
            f"~{sc['per_device_trials']} trials/device, "
            f"~{sc['mesh_trials']} mesh trials on v5e "
            f"(all_gather comms would cap at "
            f"~{sc_ag['per_device_trials']} trials/device)"
        )
        if sc["per_device_trials"] < 1:
            report.findings.append(Finding(
                ki="KI-2", check="sharded-hbm",
                path=f"spmd[dp={dp},tp={tp}]",
                message=(
                    f"per-device pool {sc['per_device_pool_bytes']} "
                    f"B/trial at n_recv={sc['n_recv']} (+ ring comms "
                    f"{sc['comms_buffer_bytes']} B) cannot fit a "
                    f"single trial per device under the v5e model "
                    f"({HBM_BYTES} B HBM, {HBM_RESERVE} B reserve, "
                    f"occupancy {POOL_OCCUPANCY}) — this mesh shape "
                    "is oversharded for the shape's mailbox pool"
                ),
            ))
    report.stats["sharded_meshes_checked"] = meshes_checked

    probes_fired = PROBE_STATS["compile_probes"] - probes_before
    if jax.default_backend() != "tpu" and probes_fired > 0:
        report.findings.append(Finding(
            ki="KI-2", check="probe-hygiene", path="pallas_tiled",
            message=(
                f"{probes_fired} compile probe(s) fired while resolving "
                "plans off-TPU: interpret-mode planning must be pure "
                "arithmetic (PROBE_STATS)"
            ),
        ))
    report.stats["memory_probes_fired"] = probes_fired
    return report
