"""KI-11 — campaign completeness over an atlas store.

The atlas's value is the claim "this is the whole cube": every cell of
the enumerated campaign either carries a certified record meeting its
target or an explicit refusal/truncation finding.  A silent gap — a
cell that was enumerated but never certified, refused, or even
admitted — converts the phase diagram from evidence into anecdote, and
nothing at run time notices: the driver exits, the store looks
plausible, the renderer happily draws the cells that exist.

So completeness is a *lint gate* (docs/KNOWN_ISSUES.md KI-11):

* the store carries a campaign ledger, the ledger belongs to the spec
  it claims, and **re-enumerating the spec's cube** yields exactly the
  ledger's cell set (the cube is re-derived, never trusted);
* every cell is terminal — ``certified`` or ``refused`` — and its
  store record exists, validates, agrees with the ledger, and is
  filed under the content address its own config hashes to;
* certified records certify honestly: a resolving stop decision and a
  CI with both endpoints (the KI-8 rule, applied to the atlas);
  refused records carry their evidence (``refusal.reason``);
* frontier steering held: per rendered slice, the widest frontier
  cell's CI is no wider than the widest interior cell's — frontier
  cells are the ones the escalation policy promises to tighten first.

Orphan records (cells in the store but not this campaign's ledger) are
notes, not findings — independently produced stores merging into one
directory is the design, and each campaign's completeness is judged
against its own cube.
"""

from __future__ import annotations

from typing import Any

from qba_tpu.analysis.findings import Finding, Report
from qba_tpu.atlas.steer import is_frontier
from qba_tpu.atlas.store import (
    AtlasStore,
    cell_key,
    validate_cell_record,
)

_PASS = "campaign-completeness"


def _finding(check: str, message: str, where: str = "") -> Finding:
    return Finding(
        ki="KI-11", check=check, path="atlas/store", message=message,
        where=where,
    )


def check_atlas_store(store_dir: str) -> Report:
    """Prove one atlas store complete against its campaign ledger;
    every violated invariant is a KI-11 finding."""
    report = Report()
    store = AtlasStore(store_dir)
    try:
        ledger = store.load_ledger()
    except ValueError as e:
        report.add([_finding("ledger-schema", str(e), store.ledger_path)])
        return report
    if ledger is None:
        report.add([
            _finding(
                "ledger-missing",
                "no campaign ledger: completeness is unprovable — a "
                "store without a ledger is a collection, not an atlas",
                store.ledger_path,
            )
        ])
        return report
    target = (ledger.get("campaign") or {}).get("target")
    cells: dict[str, Any] = ledger.get("cells") or {}

    # --- the cube is re-derived, never trusted -----------------------
    spec_json = ledger.get("campaign")
    enumerated: list[str] | None = None
    if isinstance(spec_json, dict):
        try:
            from qba_tpu.atlas.cube import CampaignSpec, enumerate_cells

            spec = CampaignSpec.from_json(spec_json)
            if spec.campaign_key() != ledger.get("campaign_key"):
                report.add([
                    _finding(
                        "campaign-key",
                        f"ledger campaign_key {ledger.get('campaign_key')!r}"
                        f" != spec hash {spec.campaign_key()!r}",
                        store.ledger_path,
                    )
                ])
            enumerated = [c.key for c in enumerate_cells(spec)]
        except (TypeError, ValueError) as e:
            report.add([
                _finding(
                    "campaign-spec",
                    f"ledger campaign spec does not re-enumerate: {e}",
                    store.ledger_path,
                )
            ])
    else:
        report.add([
            _finding(
                "campaign-spec", "ledger carries no campaign spec",
                store.ledger_path,
            )
        ])
    if enumerated is not None:
        missing = [k for k in enumerated if k not in cells]
        extra = [k for k in cells if k not in set(enumerated)]
        for k in missing:
            report.add([
                _finding(
                    _PASS,
                    f"enumerated cell {k} is absent from the ledger — "
                    "a silent gap in the cube",
                    store.ledger_path,
                )
            ])
        for k in extra:
            report.add([
                _finding(
                    _PASS,
                    f"ledger cell {k} is not produced by the campaign "
                    "spec's enumeration — ledger and spec disagree",
                    store.ledger_path,
                )
            ])

    # --- every cell terminal, every record honest --------------------
    n_certified = n_refused = 0
    for key, entry in sorted(cells.items()):
        status = entry.get("status")
        if status not in ("certified", "refused"):
            report.add([
                _finding(
                    _PASS,
                    f"cell {key} ({entry.get('coords')}) is {status!r}: "
                    "neither certified to its target nor explicitly "
                    "refused — the campaign did not finish",
                    store.ledger_path,
                )
            ])
            continue
        rec = store.load_cell(key)
        path = store.cell_path(key)
        if rec is None:
            report.add([
                _finding(
                    "record-missing",
                    f"ledger says {key} is {status} but the store has "
                    "no readable record for it",
                    path,
                )
            ])
            continue
        try:
            validate_cell_record(rec)
        except ValueError as e:
            report.add([_finding("record-invalid", str(e), path)])
            continue
        if rec["status"] != status:
            report.add([
                _finding(
                    "ledger-record-drift",
                    f"ledger calls {key} {status!r} but its record says "
                    f"{rec['status']!r}",
                    path,
                )
            ])
        if rec["status"] == "certified":
            n_certified += 1
            if target is not None and rec.get("target") != target:
                from qba_tpu.atlas.store import record_satisfies

                if not record_satisfies(rec, target):
                    report.add([
                        _finding(
                            "target-mismatch",
                            f"cell {key} certified at {rec.get('target')!r}"
                            f" which does not satisfy the campaign target "
                            f"{target!r}",
                            path,
                        )
                    ])
        else:
            n_refused += 1

    # --- orphans: legitimate (merged stores), but say so -------------
    ledger_keys = set(cells)
    orphans = [
        rec["cell_key"]
        for _name, rec in store.iter_cells()
        if rec.get("cell_key") not in ledger_keys
    ]
    if orphans:
        report.notes.append(
            f"{len(orphans)} store cell(s) outside this campaign's ledger "
            f"(merged store?): {orphans[:4]}"
        )

    # --- filename <-> content address --------------------------------
    for name, rec in store.iter_cells():
        ck = rec.get("cell_key")
        cfg = rec.get("config")
        if isinstance(cfg, dict) and ck is not None:
            want = cell_key(cfg)
            if ck != want or not name.startswith(f"cell-{ck}"):
                report.add([
                    _finding(
                        "content-address",
                        f"{name}: filed key {ck!r} vs config hash "
                        f"{want!r} — record and address disagree",
                        store.cells_dir,
                    )
                ])

    # --- frontier steering held on the rendered slices ---------------
    if target:
        slices: dict[tuple, dict[str, list[float]]] = {}
        for _name, rec in store.iter_cells():
            if rec.get("cell_key") not in ledger_keys:
                continue
            ci = rec.get("ci") or {}
            if ci.get("lo") is None or ci.get("hi") is None:
                continue
            width = float(ci["hi"]) - float(ci["lo"])
            coords = rec.get("coords") or {}
            skey = (
                coords.get("strategy"),
                coords.get("p_depolarize"),
                coords.get("p_measure_flip"),
                coords.get("size_l"),
            )
            side = "frontier" if is_frontier(rec, target) else "interior"
            slices.setdefault(skey, {"frontier": [], "interior": []})[
                side
            ].append(width)
        for skey, widths in sorted(slices.items(), key=str):
            fw, iw = widths["frontier"], widths["interior"]
            if fw and iw and max(fw) > max(iw) + 1e-9:
                report.add([
                    _finding(
                        "frontier-widths",
                        f"slice {skey}: widest frontier CI {max(fw):.4f} "
                        f"> widest interior CI {max(iw):.4f} — the "
                        "steering policy promises frontier cells tighten "
                        "first",
                        store.cells_dir,
                    )
                ])
            elif fw:
                report.notes.append(
                    f"slice {skey}: frontier max width {max(fw):.4f}"
                    + (f" <= interior max {max(iw):.4f}" if iw else "")
                )

    report.stats["atlas_cells"] = len(cells)
    report.stats["atlas_certified"] = n_certified
    report.stats["atlas_refused"] = n_refused
    report.notes.append(
        f"atlas store {store_dir}: {len(cells)} ledger cell(s), "
        f"{n_certified} certified, {n_refused} refused, "
        f"digest {store.digest()[:16]}"
    )
    return report
