"""Launch-count accounting: ``pallas_call`` launches per trial.

PERF.md's overhead attribution and the megakernel's one-launch
contract both rest on a number nobody computed generically until now:
how many kernel launches one trial dispatches on a given engine.  The
one-``pallas_call`` jaxpr assertion in tests/test_round_kernel_fused.py
hard-coded it for the fused engine; this module generalizes that into
:func:`launches_per_trial` (a static count over the full ``run_trial``
jaxpr, scan trip counts multiplied through) and a ``qba-tpu lint``
check that PINS each engine to its launch model:

========================  =======================================
engine                    launches per trial
========================  =======================================
``xla``                   0 (no kernels)
``pallas``                ``n_rounds`` (one monolithic call/round)
``pallas_tiled``          ``2 * n_rounds`` (verdict + rebuild)
``pallas_fused``          ``n_rounds`` (one fused call/round)
``pallas_mega``           1 (decode + all rounds + decision reduce)
========================  =======================================

A drift in these counts is a perf regression the runtime would never
surface (everything stays bit-identical), so the pin is a lint
finding, tagged KI-5 with the donation/launch-discipline family.
"""

from __future__ import annotations

import dataclasses
import warnings

from qba_tpu.analysis.findings import Finding, Report
from qba_tpu.config import QBAConfig

#: Engine -> expected launches per trial, as a function of the config.
LAUNCH_MODEL = {
    "xla": lambda cfg: 0,
    "pallas": lambda cfg: cfg.n_rounds,
    "pallas_tiled": lambda cfg: 2 * cfg.n_rounds,
    "pallas_fused": lambda cfg: cfg.n_rounds,
    "pallas_mega": lambda cfg: 1,
}


def count_pallas_launches(jaxpr) -> int:
    """Total ``pallas_call`` launches one evaluation of ``jaxpr``
    performs: scans multiply their body's count by the trip count,
    ``cond`` takes the max over branches, other sub-jaxprs add up.
    Kernel bodies are not descended into (a kernel cannot launch a
    kernel)."""
    from qba_tpu.analysis.effects import _as_jaxprs

    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    total = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "pallas_call":
            total += 1
            continue
        subs = [
            count_pallas_launches(s)
            for p in eqn.params.values()
            for s in _as_jaxprs(p)
        ]
        if not subs:
            continue
        if name == "scan":
            total += eqn.params.get("length", 1) * sum(subs)
        elif name == "cond":
            total += max(subs)
        else:
            total += sum(subs)
    return total


def _trace_trial(cfg: QBAConfig, engine: str | None):
    import jax

    from qba_tpu.rounds.engine import run_trial

    ecfg = (
        dataclasses.replace(cfg, round_engine=engine)
        if engine is not None
        else cfg
    )
    key = jax.random.key(0)
    return jax.make_jaxpr(lambda k: run_trial(ecfg, k))(key)


def launches_per_trial(cfg: QBAConfig, engine: str | None = None) -> int:
    """Kernel launches one trial dispatches, from the full
    ``run_trial`` jaxpr with the round engine forced to ``engine``
    (None = the config's own resolution, demotions and all)."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        closed = _trace_trial(cfg, engine)
    return count_pallas_launches(closed.jaxpr)


def check_launches(cfg: QBAConfig, engines) -> Report:
    """Pin every requested engine's per-trial launch count to
    :data:`LAUNCH_MODEL`.  An engine that records a
    :class:`~qba_tpu.diagnostics.QBADemotionWarning` during the trace
    is noted, not pinned — the demoted engine is pinned under its own
    entry."""
    from qba_tpu.diagnostics import QBADemotionWarning

    report = Report()
    checked = 0
    for engine in LAUNCH_MODEL:
        if engine not in engines:
            continue
        try:
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                closed = _trace_trial(cfg, engine)
        except Exception as exc:
            report.notes.append(
                f"launches/{engine}: trace failed, pin skipped "
                f"({type(exc).__name__}: {exc})"
            )
            continue
        count = count_pallas_launches(closed.jaxpr)
        if any(
            issubclass(w.category, QBADemotionWarning) for w in caught
        ):
            report.notes.append(
                f"launches/{engine}: demotion recorded during trace — "
                f"launch pin skipped (counted {count} on the demoted "
                "path)"
            )
            continue
        checked += 1
        expect = LAUNCH_MODEL[engine](cfg)
        if count != expect:
            report.findings.append(Finding(
                ki="KI-5", check="launches-per-trial",
                path=f"{engine}/run_trial",
                message=(
                    f"{count} pallas_call launch(es) per trial, the "
                    f"engine's launch model says {expect} — either the "
                    "dispatch grew an extra launch (perf regression "
                    "the runtime never surfaces) or the model in "
                    "analysis/launches.py needs a conscious update"
                ),
            ))
        else:
            report.notes.append(
                f"launches/{engine}: {count} launch(es) per trial "
                "(= model)"
            )
    report.stats["launch_engines_checked"] = checked
    return report
