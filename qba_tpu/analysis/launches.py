"""Launch-count accounting: ``pallas_call`` launches per trial.

PERF.md's overhead attribution and the megakernel's one-launch
contract both rest on a number nobody computed generically until now:
how many kernel launches one trial dispatches on a given engine.  The
one-``pallas_call`` jaxpr assertion in tests/test_round_kernel_fused.py
hard-coded it for the fused engine; this module generalizes that into
:func:`launches_per_trial` (a static count over the full ``run_trial``
jaxpr, scan trip counts multiplied through) and a ``qba-tpu lint``
check that PINS each engine to its launch model:

========================  =======================================
engine                    launches per trial
========================  =======================================
``xla``                   0 (no kernels)
``pallas``                ``n_rounds`` (one monolithic call/round)
``pallas_tiled``          ``2 * n_rounds`` (verdict + rebuild)
``pallas_fused``          ``n_rounds`` (one fused call/round)
``pallas_mega``           1 (decode + all rounds + decision reduce;
                          with ``mega_gen="gf2"`` the count INCLUDES
                          step-1 generation — the GF(2) measurement
                          sweep runs in VMEM inside the same launch,
                          proven by the zero-host-scan pin below)
========================  =======================================

A drift in these counts is a perf regression the runtime would never
surface (everything stays bit-identical), so the pin is a lint
finding, tagged KI-5 with the donation/launch-discipline family.
For gen-fused megakernel configs (``mega_gen`` resolving ``"gf2"``)
the launch pin is paired with a host-scan pin: the traced trial must
carry ZERO ``lax.scan``s outside kernel bodies — the host generation
path's measurement sweeps are scans, so a nonzero count means step 1
leaked back to the host even though launches still say 1.

The party-sharded (tp) path has its own rows
(:func:`check_spmd_launches`): per device-program the engine keeps its
single-device launch count, and the comms transport adds

========================  =======================================
tp comms                  extra launches / collectives per trial
========================  =======================================
``ring`` off-TPU          0 launches; ``leaves x n_rounds x (tp-1)``
                          ``ppermute`` hops (the schedule the lint
                          counts and pins)
``ring`` on TPU           ``leaves x n_rounds`` remote-DMA kernel
                          launches (one per pool leaf per round,
                          :mod:`qba_tpu.ops.ring_shuffle`) — the
                          stated model :func:`spmd_launches_per_trial`
                          closes from the counted hop schedule
``all_gather``            0 launches, 0 ``ppermute`` (one XLA
                          collective per leaf per round)
========================  =======================================

``pallas_mega`` under tp is special-cased: on TPU the party-sharded
megakernel moves the ring INSIDE the launch (one
``make_async_remote_copy`` per pool leaf per hop, all inside the
round ``fori_loop``), so its TPU row is ONE launch per trial with
``leaves x n_rounds x (tp - 1)`` in-kernel remote-DMA hops and zero
transport launches.  Off-TPU remote DMA does not exist, so the spmd
path runs the ``pallas_fused`` transport twin; the twin's counted
``ppermute`` schedule is what pins the in-kernel hop count (same
leaves, same hop algebra).
"""

from __future__ import annotations

import warnings

from qba_tpu.analysis.findings import Finding, Report
from qba_tpu.config import QBAConfig

#: Engine -> expected launches per trial, as a function of the config.
LAUNCH_MODEL = {
    "xla": lambda cfg: 0,
    "pallas": lambda cfg: cfg.n_rounds,
    "pallas_tiled": lambda cfg: 2 * cfg.n_rounds,
    "pallas_fused": lambda cfg: cfg.n_rounds,
    "pallas_mega": lambda cfg: 1,
}


def count_primitive(jaxpr, prim_names) -> int:
    """Total evaluations of any primitive in ``prim_names`` one
    evaluation of ``jaxpr`` performs: scans multiply their body's
    count by the trip count, ``cond`` takes the max over branches,
    other sub-jaxprs add up.  Kernel bodies are not descended into
    (a kernel cannot launch a kernel)."""
    from qba_tpu.analysis.effects import _as_jaxprs

    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    total = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in prim_names:
            total += 1
            continue
        subs = [
            count_primitive(s, prim_names)
            for p in eqn.params.values()
            for s in _as_jaxprs(p)
        ]
        if not subs:
            continue
        if name == "scan":
            total += eqn.params.get("length", 1) * sum(subs)
        elif name == "cond":
            total += max(subs)
        else:
            total += sum(subs)
    return total


def count_pallas_launches(jaxpr) -> int:
    """``pallas_call`` launches per evaluation of ``jaxpr``."""
    return count_primitive(jaxpr, ("pallas_call",))


def count_host_scans(jaxpr) -> int:
    """``lax.scan`` eqns OUTSIDE kernel bodies — the host-side loops.

    Unlike :func:`count_primitive` this does NOT descend through a
    ``pallas_call``: a scan inside a kernel (the megakernel's round
    loop, the gen-fused measurement sweep) is exactly what the
    in-kernel contract wants, while a scan outside one is host work.
    Counts eqns, not trips — the pin is existence, not cost."""
    from qba_tpu.analysis.effects import _as_jaxprs

    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    total = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            continue
        if eqn.primitive.name == "scan":
            total += 1
        total += sum(
            count_host_scans(s)
            for p in eqn.params.values()
            for s in _as_jaxprs(p)
        )
    return total


def _trace_trial(cfg: QBAConfig, engine: str | None):
    from qba_tpu.analysis.tracecache import trial_jaxpr

    closed, _caught = trial_jaxpr(cfg, engine)
    return closed


def launches_per_trial(cfg: QBAConfig, engine: str | None = None) -> int:
    """Kernel launches one trial dispatches, from the full
    ``run_trial`` jaxpr with the round engine forced to ``engine``
    (None = the config's own resolution, demotions and all)."""
    return count_pallas_launches(_trace_trial(cfg, engine).jaxpr)


def check_launches(cfg: QBAConfig, engines) -> Report:
    """Pin every requested engine's per-trial launch count to
    :data:`LAUNCH_MODEL`.  An engine that records a
    :class:`~qba_tpu.diagnostics.QBADemotionWarning` during the trace
    is noted, not pinned — the demoted engine is pinned under its own
    entry."""
    from qba_tpu.analysis.tracecache import trial_jaxpr
    from qba_tpu.diagnostics import QBADemotionWarning

    report = Report()
    checked = 0
    for engine in LAUNCH_MODEL:
        if engine not in engines:
            continue
        try:
            closed, caught = trial_jaxpr(cfg, engine)
        except Exception as exc:
            report.notes.append(
                f"launches/{engine}: trace failed, pin skipped "
                f"({type(exc).__name__}: {exc})"
            )
            continue
        count = count_pallas_launches(closed.jaxpr)
        if any(
            issubclass(w.category, QBADemotionWarning) for w in caught
        ):
            report.notes.append(
                f"launches/{engine}: demotion recorded during trace — "
                f"launch pin skipped (counted {count} on the demoted "
                "path)"
            )
            continue
        checked += 1
        expect = LAUNCH_MODEL[engine](cfg)
        if count != expect:
            report.findings.append(Finding(
                ki="KI-5", check="launches-per-trial",
                path=f"{engine}/run_trial",
                message=(
                    f"{count} pallas_call launch(es) per trial, the "
                    f"engine's launch model says {expect} — either the "
                    "dispatch grew an extra launch (perf regression "
                    "the runtime never surfaces) or the model in "
                    "analysis/launches.py needs a conscious update"
                ),
            ))
        else:
            report.notes.append(
                f"launches/{engine}: {count} launch(es) per trial "
                "(= model)"
            )
        if engine == "pallas_mega":
            _pin_mega_gen_in_kernel(cfg, closed, report)
    report.stats["launch_engines_checked"] = checked
    return report


def _pin_mega_gen_in_kernel(cfg: QBAConfig, closed, report: Report) -> None:
    """For a gen-fused megakernel config, prove generation moved
    in-kernel: the traced trial must carry ZERO host-side scans.  The
    host generation path evaluates the GF(2) measurement sweeps as
    ``lax.scan``s outside any kernel, so a nonzero count here means
    step 1 leaked back to the host while the launch count still reads
    1 (the launch pin alone cannot see that regression)."""
    from qba_tpu.ops.round_kernel_tiled import resolve_mega_gen

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        gen = resolve_mega_gen(cfg)
    if gen != "gf2":
        return
    host_scans = count_host_scans(closed.jaxpr)
    if host_scans:
        report.findings.append(Finding(
            ki="KI-5", check="mega-gen-in-kernel",
            path="pallas_mega/run_trial",
            message=(
                f"mega_gen resolved 'gf2' but the trial jaxpr carries "
                f"{host_scans} host-side scan(s) — step-1 generation "
                "(the GF(2) measurement sweep) leaked back outside the "
                "kernel launch"
            ),
        ))
    else:
        report.notes.append(
            "launches/pallas_mega: generation in-kernel PROVEN — "
            "mega_gen='gf2' and 0 host-side scans in the full trial "
            "jaxpr (the host path's measurement sweeps would be scans)"
        )
    report.stats["mega_gen_host_scans"] = host_scans


#: Engines whose party-sharded variants get launch rows.  xla pins the
#: pure-collective path; pallas_fused pins the per-round spmd path;
#: pallas_mega pins the party-sharded megakernel (on TPU the ring runs
#: IN-kernel; off-TPU its trace is the fused transport twin, whose
#: ppermute schedule pins the in-kernel hop count).
SPMD_CHECK_ENGINES = ("xla", "pallas_fused", "pallas_mega")


def spmd_launches_per_trial(
    cfg: QBAConfig,
    engine: str = "xla",
    comms: str = "ring",
    pool_leaves: int = 0,
    tpu: bool = False,
) -> int:
    """The closed launch model for the party-sharded path.

    ``pallas_mega`` on TPU is ONE launch per trial regardless of
    comms: the neighbor ring runs inside the kernel's round loop as
    ``pool_leaves x n_rounds x (tp - 1)`` remote-DMA hops, which are
    DMAs within the launch, not launches.  Off-TPU remote DMA does
    not exist, so the spmd path runs the ``pallas_fused`` transport
    twin and this model returns the twin's counts.

    Every other engine keeps its single-device launches per trial
    plus, on TPU under ``comms="ring"``, one remote-DMA kernel launch
    per gathered pool leaf per round.  Off-TPU the ring is
    ``ppermute`` hops and ``all_gather`` is one XLA collective per
    leaf per round — neither adds a ``pallas_call``.  ``pool_leaves``
    comes from the counted hop schedule (:func:`check_spmd_launches`
    derives it as ``ppermute_hops / (n_rounds * (tp - 1))``)."""
    if engine == "pallas_mega":
        if tpu:
            return LAUNCH_MODEL["pallas_mega"](cfg)
        engine = "pallas_fused"  # off-TPU transport twin
    base = LAUNCH_MODEL[engine](cfg)
    if comms == "ring" and tpu:
        return base + pool_leaves * cfg.n_rounds
    return base


def check_spmd_launches(cfg: QBAConfig, engines, tp: int = 2) -> Report:
    """Pin the party-sharded path's launch + hop schedule on an
    emulated (dp=1, tp) mesh: per device-program the engine keeps its
    single-device launch count for BOTH comms (off-TPU neither
    transport may add a ``pallas_call``), the ring trace carries
    exactly ``leaves x n_rounds x (tp - 1)`` ``ppermute`` hops, and
    the all_gather trace carries none.  The derived leaf count closes
    the TPU ring row of :func:`spmd_launches_per_trial` (noted, since
    remote DMA cannot be traced off-TPU)."""
    import jax

    from qba_tpu.diagnostics import QBADemotionWarning

    report = Report()
    spmd_engines = [e for e in SPMD_CHECK_ENGINES if e in engines]
    if not spmd_engines:
        return report
    if jax.device_count() < tp:
        report.notes.append(
            f"spmd-launches: {jax.device_count()} device(s) < tp={tp} — "
            "pin skipped (the multichip CI job runs it on the emulated "
            "8-device mesh)"
        )
        return report
    if cfg.n_lieutenants % tp != 0:
        report.notes.append(
            f"spmd-launches: tp={tp} does not divide "
            f"n_lieutenants={cfg.n_lieutenants}; pin skipped"
        )
        return report

    from qba_tpu.parallel.mesh import make_mesh
    from qba_tpu.parallel.spmd import _resolve_check_vma, _spmd_batch

    mesh = make_mesh({"dp": 1, "tp": tp}, devices=jax.devices()[:tp])
    keys = jax.random.split(jax.random.key(0), 1)
    checked = 0
    for engine in spmd_engines:
        counts: dict[str, tuple[int, int]] = {}
        demoted = False
        for comms in ("ring", "all_gather"):
            try:
                with warnings.catch_warnings(record=True) as caught:
                    warnings.simplefilter("always")
                    closed = jax.make_jaxpr(
                        lambda k: _spmd_batch(
                            cfg, mesh, k, engine,
                            _resolve_check_vma(engine), comms,
                        )
                    )(keys)
            except Exception as exc:
                report.notes.append(
                    f"spmd-launches[tp={tp}]/{engine}/{comms}: trace "
                    f"failed, pin skipped ({type(exc).__name__}: {exc})"
                )
                break
            if any(
                issubclass(w.category, QBADemotionWarning) for w in caught
            ):
                demoted = True
            counts[comms] = (
                count_pallas_launches(closed.jaxpr),
                count_primitive(closed.jaxpr, ("ppermute",)),
            )
        if len(counts) < 2:
            continue
        if demoted:
            report.notes.append(
                f"spmd-launches[tp={tp}]/{engine}: demotion recorded "
                "during trace — pin skipped (the demoted engine is "
                "pinned under its own entry)"
            )
            continue
        checked += 1
        # Off-TPU the sharded megakernel runs its fused transport
        # twin (remote DMA exists only on hardware), so its traced
        # counts are the twin's; the hop pin below still closes the
        # in-kernel model because both move the same pool leaves on
        # the same schedule.
        twin = "pallas_fused" if engine == "pallas_mega" else engine
        base = LAUNCH_MODEL[twin](cfg)
        for comms, (pallas, _) in counts.items():
            if pallas != base:
                report.findings.append(Finding(
                    ki="KI-5", check="spmd-launches",
                    path=f"spmd[tp={tp}]/{engine}/{comms}",
                    message=(
                        f"{pallas} pallas_call launch(es) per trial "
                        f"off-TPU, the engine's model says {base} — "
                        "the comms path must add zero launches off-TPU "
                        "(remote DMA exists only on hardware)"
                        + (
                            "; pallas_mega traces its pallas_fused "
                            "transport twin here" if twin != engine
                            else ""
                        )
                    ),
                ))
        hops = cfg.n_rounds * (tp - 1)
        ring_hops = counts["ring"][1]
        ag_hops = counts["all_gather"][1]
        if ag_hops != 0:
            report.findings.append(Finding(
                ki="KI-5", check="spmd-launches",
                path=f"spmd[tp={tp}]/{engine}/all_gather",
                message=(
                    f"{ag_hops} ppermute hop(s) in the all_gather "
                    "trace — the escape-hatch path regrew ring traffic"
                ),
            ))
        if ring_hops == 0 or ring_hops % hops != 0:
            report.findings.append(Finding(
                ki="KI-5", check="spmd-launches",
                path=f"spmd[tp={tp}]/{engine}/ring",
                message=(
                    f"{ring_hops} ppermute hop(s) per trial does not "
                    f"match the ring schedule (a multiple of "
                    f"n_rounds x (tp-1) = {hops}): the hop structure "
                    "drifted and the TPU remote-DMA model no longer "
                    "closes"
                ),
            ))
        else:
            leaves = ring_hops // hops
            tpu_model = spmd_launches_per_trial(
                cfg, engine, "ring", leaves, tpu=True
            )
            if engine == "pallas_mega":
                report.notes.append(
                    f"spmd-launches[tp={tp}]/pallas_mega: twin counts "
                    f"{base} launch(es) + {ring_hops} ppermute "
                    f"hops/trial (= {leaves} pool leaves x "
                    f"{cfg.n_rounds} rounds x {tp - 1} hops); on TPU "
                    f"the sharded megakernel closes at {tpu_model} "
                    f"launch/trial with the same {ring_hops} hops as "
                    "IN-KERNEL remote DMAs"
                )
            else:
                report.notes.append(
                    f"spmd-launches[tp={tp}]/{engine}: {base} "
                    f"launch(es) + {ring_hops} ppermute hops/trial "
                    f"(= {leaves} pool leaves x {cfg.n_rounds} rounds "
                    f"x {tp - 1} hops); TPU ring model closes at "
                    f"{tpu_model} launch(es)/trial"
                )
    report.stats["spmd_launch_engines_checked"] = checked
    return report
