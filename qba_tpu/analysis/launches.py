"""Launch-count accounting: ``pallas_call`` launches per trial.

PERF.md's overhead attribution and the megakernel's one-launch
contract both rest on a number nobody computed generically until now:
how many kernel launches one trial dispatches on a given engine.  The
one-``pallas_call`` jaxpr assertion in tests/test_round_kernel_fused.py
hard-coded it for the fused engine; this module generalizes that into
:func:`launches_per_trial` (a static count over the full ``run_trial``
jaxpr, scan trip counts multiplied through) and a ``qba-tpu lint``
check that PINS each engine to its launch model:

========================  =======================================
engine                    launches per trial
========================  =======================================
``xla``                   0 (no kernels)
``pallas``                ``n_rounds`` (one monolithic call/round)
``pallas_tiled``          ``2 * n_rounds`` (verdict + rebuild)
``pallas_fused``          ``n_rounds`` (one fused call/round)
``pallas_mega``           1 (decode + all rounds + decision reduce)
========================  =======================================

A drift in these counts is a perf regression the runtime would never
surface (everything stays bit-identical), so the pin is a lint
finding, tagged KI-5 with the donation/launch-discipline family.

The party-sharded (tp) path has its own rows
(:func:`check_spmd_launches`): per device-program the engine keeps its
single-device launch count, and the comms transport adds

========================  =======================================
tp comms                  extra launches / collectives per trial
========================  =======================================
``ring`` off-TPU          0 launches; ``leaves x n_rounds x (tp-1)``
                          ``ppermute`` hops (the schedule the lint
                          counts and pins)
``ring`` on TPU           ``leaves x n_rounds`` remote-DMA kernel
                          launches (one per pool leaf per round,
                          :mod:`qba_tpu.ops.ring_shuffle`) — the
                          stated model :func:`spmd_launches_per_trial`
                          closes from the counted hop schedule
``all_gather``            0 launches, 0 ``ppermute`` (one XLA
                          collective per leaf per round)
========================  =======================================
"""

from __future__ import annotations

import dataclasses
import warnings

from qba_tpu.analysis.findings import Finding, Report
from qba_tpu.config import QBAConfig

#: Engine -> expected launches per trial, as a function of the config.
LAUNCH_MODEL = {
    "xla": lambda cfg: 0,
    "pallas": lambda cfg: cfg.n_rounds,
    "pallas_tiled": lambda cfg: 2 * cfg.n_rounds,
    "pallas_fused": lambda cfg: cfg.n_rounds,
    "pallas_mega": lambda cfg: 1,
}


def count_primitive(jaxpr, prim_names) -> int:
    """Total evaluations of any primitive in ``prim_names`` one
    evaluation of ``jaxpr`` performs: scans multiply their body's
    count by the trip count, ``cond`` takes the max over branches,
    other sub-jaxprs add up.  Kernel bodies are not descended into
    (a kernel cannot launch a kernel)."""
    from qba_tpu.analysis.effects import _as_jaxprs

    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    total = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in prim_names:
            total += 1
            continue
        subs = [
            count_primitive(s, prim_names)
            for p in eqn.params.values()
            for s in _as_jaxprs(p)
        ]
        if not subs:
            continue
        if name == "scan":
            total += eqn.params.get("length", 1) * sum(subs)
        elif name == "cond":
            total += max(subs)
        else:
            total += sum(subs)
    return total


def count_pallas_launches(jaxpr) -> int:
    """``pallas_call`` launches per evaluation of ``jaxpr``."""
    return count_primitive(jaxpr, ("pallas_call",))


def _trace_trial(cfg: QBAConfig, engine: str | None):
    import jax

    from qba_tpu.rounds.engine import run_trial

    ecfg = (
        dataclasses.replace(cfg, round_engine=engine)
        if engine is not None
        else cfg
    )
    key = jax.random.key(0)
    return jax.make_jaxpr(lambda k: run_trial(ecfg, k))(key)


def launches_per_trial(cfg: QBAConfig, engine: str | None = None) -> int:
    """Kernel launches one trial dispatches, from the full
    ``run_trial`` jaxpr with the round engine forced to ``engine``
    (None = the config's own resolution, demotions and all)."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        closed = _trace_trial(cfg, engine)
    return count_pallas_launches(closed.jaxpr)


def check_launches(cfg: QBAConfig, engines) -> Report:
    """Pin every requested engine's per-trial launch count to
    :data:`LAUNCH_MODEL`.  An engine that records a
    :class:`~qba_tpu.diagnostics.QBADemotionWarning` during the trace
    is noted, not pinned — the demoted engine is pinned under its own
    entry."""
    from qba_tpu.diagnostics import QBADemotionWarning

    report = Report()
    checked = 0
    for engine in LAUNCH_MODEL:
        if engine not in engines:
            continue
        try:
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                closed = _trace_trial(cfg, engine)
        except Exception as exc:
            report.notes.append(
                f"launches/{engine}: trace failed, pin skipped "
                f"({type(exc).__name__}: {exc})"
            )
            continue
        count = count_pallas_launches(closed.jaxpr)
        if any(
            issubclass(w.category, QBADemotionWarning) for w in caught
        ):
            report.notes.append(
                f"launches/{engine}: demotion recorded during trace — "
                f"launch pin skipped (counted {count} on the demoted "
                "path)"
            )
            continue
        checked += 1
        expect = LAUNCH_MODEL[engine](cfg)
        if count != expect:
            report.findings.append(Finding(
                ki="KI-5", check="launches-per-trial",
                path=f"{engine}/run_trial",
                message=(
                    f"{count} pallas_call launch(es) per trial, the "
                    f"engine's launch model says {expect} — either the "
                    "dispatch grew an extra launch (perf regression "
                    "the runtime never surfaces) or the model in "
                    "analysis/launches.py needs a conscious update"
                ),
            ))
        else:
            report.notes.append(
                f"launches/{engine}: {count} launch(es) per trial "
                "(= model)"
            )
    report.stats["launch_engines_checked"] = checked
    return report


#: Engines whose party-sharded variants get launch rows.  xla pins the
#: pure-collective path; pallas_fused pins the spmd hot path (mega has
#: no sharded variant — spmd demotes it to fused, so fused IS its row).
SPMD_CHECK_ENGINES = ("xla", "pallas_fused")


def spmd_launches_per_trial(
    cfg: QBAConfig,
    engine: str = "xla",
    comms: str = "ring",
    pool_leaves: int = 0,
    tpu: bool = False,
) -> int:
    """The closed launch model for the party-sharded path: the
    engine's single-device launches per trial (``pallas_mega`` demotes
    to ``pallas_fused`` under the tp mesh) plus, on TPU under
    ``comms="ring"``, one remote-DMA kernel launch per gathered pool
    leaf per round.  Off-TPU the ring is ``ppermute`` hops and
    ``all_gather`` is one XLA collective per leaf per round — neither
    adds a ``pallas_call``.  ``pool_leaves`` comes from the counted
    hop schedule (:func:`check_spmd_launches` derives it as
    ``ppermute_hops / (n_rounds * (tp - 1))``)."""
    resolved = "pallas_fused" if engine == "pallas_mega" else engine
    base = LAUNCH_MODEL[resolved](cfg)
    if comms == "ring" and tpu:
        return base + pool_leaves * cfg.n_rounds
    return base


def check_spmd_launches(cfg: QBAConfig, engines, tp: int = 2) -> Report:
    """Pin the party-sharded path's launch + hop schedule on an
    emulated (dp=1, tp) mesh: per device-program the engine keeps its
    single-device launch count for BOTH comms (off-TPU neither
    transport may add a ``pallas_call``), the ring trace carries
    exactly ``leaves x n_rounds x (tp - 1)`` ``ppermute`` hops, and
    the all_gather trace carries none.  The derived leaf count closes
    the TPU ring row of :func:`spmd_launches_per_trial` (noted, since
    remote DMA cannot be traced off-TPU)."""
    import jax

    from qba_tpu.diagnostics import QBADemotionWarning

    report = Report()
    spmd_engines = [e for e in SPMD_CHECK_ENGINES if e in engines]
    if not spmd_engines:
        return report
    if jax.device_count() < tp:
        report.notes.append(
            f"spmd-launches: {jax.device_count()} device(s) < tp={tp} — "
            "pin skipped (the multichip CI job runs it on the emulated "
            "8-device mesh)"
        )
        return report
    if cfg.n_lieutenants % tp != 0:
        report.notes.append(
            f"spmd-launches: tp={tp} does not divide "
            f"n_lieutenants={cfg.n_lieutenants}; pin skipped"
        )
        return report

    from qba_tpu.parallel.mesh import make_mesh
    from qba_tpu.parallel.spmd import _resolve_check_vma, _spmd_batch

    mesh = make_mesh({"dp": 1, "tp": tp}, devices=jax.devices()[:tp])
    keys = jax.random.split(jax.random.key(0), 1)
    checked = 0
    for engine in spmd_engines:
        counts: dict[str, tuple[int, int]] = {}
        demoted = False
        for comms in ("ring", "all_gather"):
            try:
                with warnings.catch_warnings(record=True) as caught:
                    warnings.simplefilter("always")
                    closed = jax.make_jaxpr(
                        lambda k: _spmd_batch(
                            cfg, mesh, k, engine,
                            _resolve_check_vma(engine), comms,
                        )
                    )(keys)
            except Exception as exc:
                report.notes.append(
                    f"spmd-launches[tp={tp}]/{engine}/{comms}: trace "
                    f"failed, pin skipped ({type(exc).__name__}: {exc})"
                )
                break
            if any(
                issubclass(w.category, QBADemotionWarning) for w in caught
            ):
                demoted = True
            counts[comms] = (
                count_pallas_launches(closed.jaxpr),
                count_primitive(closed.jaxpr, ("ppermute",)),
            )
        if len(counts) < 2:
            continue
        if demoted:
            report.notes.append(
                f"spmd-launches[tp={tp}]/{engine}: demotion recorded "
                "during trace — pin skipped (the demoted engine is "
                "pinned under its own entry)"
            )
            continue
        checked += 1
        base = LAUNCH_MODEL[engine](cfg)
        for comms, (pallas, _) in counts.items():
            if pallas != base:
                report.findings.append(Finding(
                    ki="KI-5", check="spmd-launches",
                    path=f"spmd[tp={tp}]/{engine}/{comms}",
                    message=(
                        f"{pallas} pallas_call launch(es) per trial "
                        f"off-TPU, the engine's model says {base} — "
                        "the comms path must add zero launches off-TPU "
                        "(remote DMA exists only on hardware)"
                    ),
                ))
        hops = cfg.n_rounds * (tp - 1)
        ring_hops = counts["ring"][1]
        ag_hops = counts["all_gather"][1]
        if ag_hops != 0:
            report.findings.append(Finding(
                ki="KI-5", check="spmd-launches",
                path=f"spmd[tp={tp}]/{engine}/all_gather",
                message=(
                    f"{ag_hops} ppermute hop(s) in the all_gather "
                    "trace — the escape-hatch path regrew ring traffic"
                ),
            ))
        if ring_hops == 0 or ring_hops % hops != 0:
            report.findings.append(Finding(
                ki="KI-5", check="spmd-launches",
                path=f"spmd[tp={tp}]/{engine}/ring",
                message=(
                    f"{ring_hops} ppermute hop(s) per trial does not "
                    f"match the ring schedule (a multiple of "
                    f"n_rounds x (tp-1) = {hops}): the hop structure "
                    "drifted and the TPU remote-DMA model no longer "
                    "closes"
                ),
            ))
        else:
            leaves = ring_hops // hops
            tpu_model = spmd_launches_per_trial(
                cfg, engine, "ring", leaves, tpu=True
            )
            report.notes.append(
                f"spmd-launches[tp={tp}]/{engine}: {base} launch(es) + "
                f"{ring_hops} ppermute hops/trial (= {leaves} pool "
                f"leaves x {cfg.n_rounds} rounds x {tp - 1} hops); "
                f"TPU ring model closes at {tpu_model} launch(es)/trial"
            )
    report.stats["spmd_launch_engines_checked"] = checked
    return report
