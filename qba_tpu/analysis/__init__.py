"""Static invariant checker for the QBA TPU kernels (``qba-tpu lint``).

Turns the Known Issues' hand-enforced conventions into machine-checked
passes over the traced build paths of every round engine:

* :mod:`qba_tpu.analysis.dots` — KI-3 exact-dot checking via interval
  abstract interpretation (:mod:`qba_tpu.analysis.intervals`) of the
  jaxprs in :mod:`qba_tpu.analysis.traces`;
* :mod:`qba_tpu.analysis.vma` — KI-1 ``out_vma`` threading and
  ``check_vma`` policy audits;
* :mod:`qba_tpu.analysis.memory` — KI-2 static VMEM/HBM plan audit;
* :mod:`qba_tpu.analysis.driver` — the lint orchestrator
  (:func:`run_lint`) the CLI and CI gate call.
"""

from qba_tpu.analysis.findings import Finding, Report  # noqa: F401


def run_lint(configs=None, engines=None) -> Report:
    """Lazy forwarder to :func:`qba_tpu.analysis.driver.run_lint` so
    ``import qba_tpu.analysis`` stays jax-import-free."""
    from qba_tpu.analysis.driver import run_lint as _run

    return _run(configs=configs, engines=engines)
