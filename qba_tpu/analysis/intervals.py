"""Interval abstract interpretation over jaxprs.

The KI-3 rule ("any dot whose integer operands can exceed 256 must pass
``Precision.HIGHEST``", docs/KNOWN_ISSUES.md) is a statement about the
*value ranges* flowing into each ``dot_general``.  This module proves
those ranges statically: every array in a traced jaxpr is abstracted to
one interval ``[lo, hi]`` plus an ``integral`` bit ("provably
integer-valued"), seeded at the jaxpr inputs from ``QBAConfig``-derived
bounds (:mod:`qba_tpu.analysis.traces`) and propagated through a
transfer function per primitive.

The domain is a product of the interval with three per-axis structure
facts, because the kernels' central idiom — gather/permute as a one-hot
MXU matmul — is invisible to plain intervals (a sound sum-over-K bound
inflates every gathered value by the contraction size):

* ``onehot``: axes along which at most ONE element per fiber is
  nonzero.  Established by ``eq(iota_d, c)`` where ``c`` is constant
  along ``d``, preserved by 0-masking selects and multiplies.  A dot
  whose contracted axis is onehot on either side sums at most one
  nonzero term, so its bound is the plain product of operand bounds —
  exactly the "one-hot gather is exact while gathered values fit"
  reasoning the kernels are built on.
* ``const``: axes along which the array is constant (what broadcasting
  a ``[n, 1]`` column across lanes produces).
* ``distinct``: axes along which all values differ (``iota``).

Other design points:

* **One interval per array**, not per element — coarse, but the
  protocol's operands are bounded uniformly (ids, flags, counts).
* **Refs** (Pallas kernel operands/outputs/scratch) map to mutable
  :class:`RefCell` s holding the join of everything ever stored;
  ``pallas_call`` bodies run to a *fixpoint* (grid steps carry state
  through revisited output blocks, e.g. the verdict kernel's
  cross-block ``vi`` carry) with widening to TOP after
  :data:`MAX_FIXPOINT_PASSES`.
* **Unknown primitives degrade to TOP with ``integral=False``** — the
  KI-3 checker then *skips* those dots (it flags only provably-integer
  operands), so an unmodeled primitive can cause a false negative but
  never a false positive.  Unmodeled names surface in the report's
  ``unhandled_primitives`` stat so gaps stay visible.
* Every ``dot_general`` encountered (including inside ``pallas_call``
  kernel jaxprs, ``pjit`` bodies, and ``cond`` branches) is recorded
  with its operand abstractions for :mod:`qba_tpu.analysis.dots`.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import numpy as np

INF = math.inf
MAX_FIXPOINT_PASSES = 5
_EMPTY = frozenset()
#: Skip numeric structure detection on constants larger than this.
_CONCRETE_STRUCTURE_CAP = 1 << 22


@dataclasses.dataclass(frozen=True)
class IVal:
    """Abstract value: interval + integrality + per-axis structure."""

    lo: float
    hi: float
    integral: bool
    onehot: frozenset = _EMPTY   # axes with <= 1 nonzero per fiber
    const: frozenset = _EMPTY    # axes the array is constant along
    distinct: frozenset = _EMPTY  # axes with all-distinct values
    #: For rank-2 arrays packing heterogeneous columns (the pool's
    #: ``meta`` ``[cap, 4]`` = count/v/sent/cell), a per-index interval
    #: along the LAST axis — static column slices refine to it.
    cols: tuple | None = None

    @property
    def mag(self) -> float:
        return max(abs(self.lo), abs(self.hi))

    @property
    def bounded(self) -> bool:
        return math.isfinite(self.lo) and math.isfinite(self.hi)

    def plain(self) -> "IVal":
        """The same interval with structure dropped (shape changed)."""
        if not (self.onehot or self.const or self.distinct or self.cols):
            return self
        return IVal(self.lo, self.hi, self.integral)

    def __repr__(self) -> str:  # compact in finding messages
        tag = "int" if self.integral else "real"
        return f"[{self.lo:g}, {self.hi:g}]({tag})"


TOP = IVal(-INF, INF, False)
BOOL = IVal(0.0, 1.0, True)


def join(a: IVal, b: IVal) -> IVal:
    cols = None
    if a.cols and b.cols and len(a.cols) == len(b.cols):
        cols = tuple(join(x, y) for x, y in zip(a.cols, b.cols))
    return IVal(
        min(a.lo, b.lo), max(a.hi, b.hi), a.integral and b.integral,
        a.onehot & b.onehot, a.const & b.const, a.distinct & b.distinct,
        cols,
    )


def join_all(vals) -> IVal:
    out = None
    for v in vals:
        out = v if out is None else join(out, v)
    return out if out is not None else TOP


def from_concrete(value) -> IVal:
    """Interval + structure of a literal / jaxpr constant."""
    try:
        a = np.asarray(value)
        if a.size == 0:
            return IVal(0.0, 0.0, True)
        if a.dtype == bool:
            a = a.astype(np.int32)
        if not np.issubdtype(a.dtype, np.number):
            return TOP
        af = a.astype(np.float64)
        if not np.all(np.isfinite(af)):
            return TOP
        integral = bool(
            np.issubdtype(a.dtype, np.integer)
            or np.all(af == np.floor(af))
        )
        onehot, const, distinct = _concrete_structure(af)
        return IVal(
            float(af.min()), float(af.max()), integral,
            onehot, const, distinct,
        )
    except Exception:
        return TOP


def _concrete_structure(af: np.ndarray):
    """Detect per-axis structure of a constant numerically (captured
    one-hot tables, ``jnp.arange`` index vectors, ...)."""
    if af.ndim == 0 or af.size > _CONCRETE_STRUCTURE_CAP:
        return _EMPTY, _EMPTY, _EMPTY
    onehot, const, distinct = set(), set(), set()
    nz = af != 0.0
    for d in range(af.ndim):
        if af.shape[d] == 1:
            const.add(d)
            distinct.add(d)
            if nz.sum() <= max(
                1, af.size // max(1, af.shape[d])
            ) and np.all(nz.sum(axis=d) <= 1):
                onehot.add(d)
            continue
        if np.all(nz.sum(axis=d) <= 1):
            onehot.add(d)
        fibers = np.moveaxis(af, d, 0).reshape(af.shape[d], -1)
        if np.all(fibers == fibers[0]):
            const.add(d)
        else:
            srt = np.sort(fibers, axis=0)
            if np.all(np.diff(srt, axis=0) != 0):
                distinct.add(d)
    return frozenset(onehot), frozenset(const), frozenset(distinct)


def _mul_bound(x: float, y: float) -> float:
    if x == 0.0 or y == 0.0:
        return 0.0  # inf * 0 convention: arrays of zeros stay zero
    return x * y


def interval_mul(a: IVal, b: IVal) -> IVal:
    corners = [
        _mul_bound(a.lo, b.lo), _mul_bound(a.lo, b.hi),
        _mul_bound(a.hi, b.lo), _mul_bound(a.hi, b.hi),
    ]
    # A product is nonzero only where both factors are, so either
    # factor's onehot axes carry over.
    return IVal(
        min(corners), max(corners), a.integral and b.integral,
        a.onehot | b.onehot, a.const & b.const,
    )


class RefCell:
    """Abstract contents of one mutable ref (kernel operand, output
    block, or scratch buffer).  ``None`` means "never written" — a read
    before any write returns TOP (uninitialized scratch)."""

    __slots__ = ("content",)

    def __init__(self, content: IVal | None = None):
        self.content = content

    def read(self) -> IVal:
        return self.content if self.content is not None else TOP

    def store(self, val: IVal) -> None:
        self.content = val if self.content is None else join(self.content, val)


@dataclasses.dataclass
class DotRecord:
    """One ``dot_general`` site with the operand intervals proven for it."""

    eqn: Any
    lhs: IVal
    rhs: IVal
    path: str
    where: str


def source_location(eqn) -> str:
    try:
        from jax._src import source_info_util as siu

        fr = siu.user_frame(eqn.source_info)
        if fr is not None:
            return f"{fr.file_name}:{fr.start_line}"
    except Exception:
        pass
    return ""


def _is_ref(var) -> bool:
    aval = getattr(var, "aval", None)
    return hasattr(aval, "inner_aval") or type(aval).__name__ in (
        "AbstractRef", "AbstractMemoryRef",
    )


def _aval_size(var) -> int:
    shape = getattr(var.aval, "shape", ())
    n = 1
    for d in shape:
        n *= int(d)
    return n


class IntervalInterpreter:
    """Abstractly interprets one traced build path (a ClosedJaxpr)."""

    def __init__(self, path: str = ""):
        self.path = path
        self.unhandled: set[str] = set()
        # keyed by id(eqn): fixpoint passes overwrite with the widest
        # (final) operand intervals — the join is monotone per pass.
        self.dots: dict[int, DotRecord] = {}

    # -- public entry -----------------------------------------------------

    def run(self, closed_jaxpr, arg_ivals: list[IVal]) -> list[IVal]:
        jaxpr = closed_jaxpr.jaxpr
        consts = closed_jaxpr.consts
        env: dict[Any, Any] = {}
        for var, const in zip(jaxpr.constvars, consts):
            env[var] = from_concrete(const)
        if len(arg_ivals) != len(jaxpr.invars):
            raise ValueError(
                f"{self.path}: seeded {len(arg_ivals)} intervals for "
                f"{len(jaxpr.invars)} jaxpr inputs"
            )
        for var, ival in zip(jaxpr.invars, arg_ivals):
            env[var] = RefCell(ival) if _is_ref(var) else ival
        self._eval_jaxpr(jaxpr, env)
        return [self._read(env, v) for v in jaxpr.outvars]

    # -- environment ------------------------------------------------------

    def _read(self, env, var):
        if type(var).__name__ == "Literal":
            return from_concrete(var.val)
        val = env.get(var, TOP)
        if isinstance(val, RefCell):
            return val.read()
        return val

    def _read_raw(self, env, var):
        """Like _read but refs come back as their RefCell (aliasing)."""
        if type(var).__name__ == "Literal":
            return from_concrete(var.val)
        return env.get(var, TOP)

    # -- interpreter core -------------------------------------------------

    def _eval_jaxpr(self, jaxpr, env) -> None:
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            handler = getattr(self, f"_prim_{name.replace('-', '_')}", None)
            if handler is not None:
                outs = handler(eqn, env)
            elif name in _IDENTITY_PRIMS:
                src = self._read(env, eqn.invars[0])
                if getattr(eqn.invars[0].aval, "shape", None) != getattr(
                    eqn.outvars[0].aval, "shape", None
                ):
                    src = src.plain()  # axes moved; structure is stale
                outs = [src] * len(eqn.outvars)
            elif name in _BOOL_PRIMS:
                outs = [BOOL] * len(eqn.outvars)
            elif name in _CALL_PRIMS or "call_jaxpr" in eqn.params:
                outs = self._call(eqn, env)
            else:
                self.unhandled.add(name)
                outs = [TOP] * len(eqn.outvars)
            for var, out in zip(eqn.outvars, outs):
                if type(var).__name__ != "DropVar":
                    env[var] = out

    def _sub_run(self, sub, env, operands):
        """Run a sub-jaxpr with the given operand objects (IVals and/or
        RefCells — cells alias, so mutations propagate to the caller)."""
        jaxpr = sub.jaxpr if hasattr(sub, "jaxpr") else sub
        consts = list(getattr(sub, "consts", ()))
        sub_env: dict[Any, Any] = {}
        for var, const in zip(jaxpr.constvars, consts):
            sub_env[var] = from_concrete(const)
        for var, op in zip(jaxpr.invars, operands):
            sub_env[var] = op
        self._eval_jaxpr(jaxpr, sub_env)
        return [self._read(sub_env, v) for v in jaxpr.outvars]

    # -- structured / call primitives -------------------------------------

    def _call(self, eqn, env):
        sub = eqn.params.get("call_jaxpr") or eqn.params.get("jaxpr")
        jaxpr = sub.jaxpr if hasattr(sub, "jaxpr") else sub
        ops = [self._read_raw(env, v) for v in eqn.invars]
        # custom_jvp/vjp calls prepend rule closures to invars; align on
        # the trailing operands the sub-jaxpr actually takes.
        ops = ops[len(ops) - len(jaxpr.invars):]
        return self._sub_run(sub, env, ops)

    def _prim_pjit(self, eqn, env):
        return self._call(eqn, env)

    def _prim_closed_call(self, eqn, env):
        return self._call(eqn, env)

    def _prim_custom_jvp_call(self, eqn, env):
        return self._call(eqn, env)

    def _prim_custom_vjp_call(self, eqn, env):
        return self._call(eqn, env)

    def _prim_remat(self, eqn, env):
        return self._call(eqn, env)

    def _prim_checkpoint(self, eqn, env):
        return self._call(eqn, env)

    def _prim_cond(self, eqn, env):
        branches = eqn.params["branches"]
        ops = [self._read_raw(env, v) for v in eqn.invars[1:]]
        outs = None
        for br in branches:
            res = self._sub_run(br, env, ops)
            outs = res if outs is None else [join(a, b) for a, b in zip(outs, res)]
        return outs if outs is not None else [TOP] * len(eqn.outvars)

    def _prim_while(self, eqn, env):
        # Conservative: analyze the body once with TOP carries (collects
        # any dots inside without claiming bounds for them).
        body = eqn.params["body_jaxpr"]
        jaxpr = body.jaxpr if hasattr(body, "jaxpr") else body
        self._sub_run(body, env, [TOP] * len(jaxpr.invars))
        return [TOP] * len(eqn.outvars)

    def _prim_scan(self, eqn, env):
        # Conservative: consts keep their intervals, carries are TOP
        # (they evolve across iterations), xs keep theirs (each
        # iteration sees a slice of the same array).
        sub = eqn.params["jaxpr"]
        n_consts = eqn.params.get("num_consts", 0)
        n_carry = eqn.params.get("num_carry", 0)
        ops = [self._read_raw(env, v) for v in eqn.invars]
        for i in range(n_consts, n_consts + n_carry):
            ops[i] = TOP
        self._sub_run(sub, env, ops)
        return [TOP] * len(eqn.outvars)

    def _prim_pallas_call(self, eqn, env):
        gm = eqn.params["grid_mapping"]
        sub = eqn.params["jaxpr"]
        jaxpr = sub.jaxpr if hasattr(sub, "jaxpr") else sub
        n_in = gm.num_inputs + gm.num_index_operands
        n_out = gm.num_outputs
        in_ivals = [self._read(env, v) for v in eqn.invars]
        aliases = dict(eqn.params.get("input_output_aliases") or ())
        out_cells = [RefCell() for _ in range(n_out)]
        for in_idx, out_idx in aliases.items():
            out_cells[out_idx] = RefCell(in_ivals[in_idx])
        n_scratch = len(jaxpr.invars) - n_in - n_out
        scratch = [RefCell() for _ in range(max(0, n_scratch))]
        operands = (
            [RefCell(iv) for iv in in_ivals] + out_cells + scratch
        )
        # Fixpoint over grid steps: revisited output blocks / scratch
        # carry state between steps, so re-run until contents settle,
        # then widen whatever is still moving and do one final pass.
        cells = [c for c in operands if isinstance(c, RefCell)]
        for _ in range(MAX_FIXPOINT_PASSES):
            before = [c.content for c in cells]
            self._sub_run(sub, env, operands)
            if [c.content for c in cells] == before:
                break
        else:
            for c, b in zip(cells, before):
                if c.content != b:
                    c.content = TOP
            self._sub_run(sub, env, operands)
        return [c.read() for c in out_cells]

    # -- state primitives --------------------------------------------------

    def _prim_get(self, eqn, env):
        cell = self._read_raw(env, eqn.invars[0])
        if not isinstance(cell, RefCell):
            return [TOP]
        val = cell.read()
        ref_shape = tuple(getattr(eqn.invars[0].aval, "shape", ()) or ())
        out_shape = tuple(getattr(eqn.outvars[0].aval, "shape", ()) or ())
        if ref_shape == out_shape:
            return [val]
        # Indexed read: axis identities shift, so drop per-axis facts —
        # but a read that leaves the trailing axis whole (row slicing /
        # leading-index selection, e.g. meta_ref[t, sl]) preserves the
        # column partition.
        out = val.plain()
        if (
            val.cols is not None and ref_shape and out_shape
            and out_shape[-1] == ref_shape[-1]
        ):
            out = dataclasses.replace(out, cols=val.cols)
        return [out]

    def _prim_swap(self, eqn, env):
        cell = self._read_raw(env, eqn.invars[0])
        val = self._read(env, eqn.invars[1])
        if isinstance(cell, RefCell):
            old = cell.read() if cell.content is not None else TOP
            if getattr(eqn.invars[0].aval, "shape", None) != getattr(
                eqn.invars[1].aval, "shape", None
            ):
                val = val.plain()
            cell.store(val)
            return [old]
        return [TOP]

    def _prim_addupdate(self, eqn, env):
        cell = self._read_raw(env, eqn.invars[0])
        val = self._read(env, eqn.invars[1])
        if isinstance(cell, RefCell):
            if cell.content is None:
                cell.content = TOP
            else:
                base = cell.content
                cell.content = IVal(
                    base.lo + min(val.lo, 0.0), base.hi + max(val.hi, 0.0),
                    base.integral and val.integral,
                )
        return []

    # -- arithmetic --------------------------------------------------------

    def _prim_add(self, eqn, env):
        a, b = (self._read(env, v) for v in eqn.invars)
        return [IVal(a.lo + b.lo, a.hi + b.hi, a.integral and b.integral,
                     _EMPTY, a.const & b.const)]

    def _prim_sub(self, eqn, env):
        a, b = (self._read(env, v) for v in eqn.invars)
        return [IVal(a.lo - b.hi, a.hi - b.lo, a.integral and b.integral,
                     _EMPTY, a.const & b.const)]

    def _prim_mul(self, eqn, env):
        a, b = (self._read(env, v) for v in eqn.invars)
        return [interval_mul(a, b)]

    def _prim_div(self, eqn, env):
        a, b = (self._read(env, v) for v in eqn.invars)
        # An unbounded dividend stays unbounded (scan-widened carries
        # inside the megakernel's in-kernel round loop reach here);
        # flooring an infinite corner would raise.
        if a.bounded and b.bounded and (b.lo > 0 or b.hi < 0):
            corners = [a.lo / b.lo, a.lo / b.hi, a.hi / b.lo, a.hi / b.hi]
            is_int = np.issubdtype(eqn.outvars[0].aval.dtype, np.integer)
            if is_int:
                return [IVal(
                    math.floor(min(corners)), math.floor(max(corners)), True
                )]
            return [IVal(min(corners), max(corners), False)]
        return [TOP]

    def _prim_rem(self, eqn, env):
        a, b = (self._read(env, v) for v in eqn.invars)
        if b.bounded and b.lo > 0:
            hi = b.hi - (1 if (a.integral and b.integral) else 0)
            lo = 0.0 if a.lo >= 0 else -hi
            return [IVal(lo, hi, a.integral and b.integral)]
        return [TOP]

    def _prim_neg(self, eqn, env):
        a = self._read(env, eqn.invars[0])
        return [IVal(-a.hi, -a.lo, a.integral, a.onehot, a.const)]

    def _prim_abs(self, eqn, env):
        a = self._read(env, eqn.invars[0])
        lo = 0.0 if a.lo <= 0 <= a.hi else min(abs(a.lo), abs(a.hi))
        return [IVal(lo, a.mag, a.integral, a.onehot, a.const)]

    def _prim_sign(self, eqn, env):
        a = self._read(env, eqn.invars[0])
        return [IVal(-1.0, 1.0, True, a.onehot, a.const)]

    def _prim_max(self, eqn, env):
        a, b = (self._read(env, v) for v in eqn.invars)
        return [IVal(max(a.lo, b.lo), max(a.hi, b.hi),
                     a.integral and b.integral, _EMPTY, a.const & b.const)]

    def _prim_min(self, eqn, env):
        a, b = (self._read(env, v) for v in eqn.invars)
        return [IVal(min(a.lo, b.lo), min(a.hi, b.hi),
                     a.integral and b.integral, _EMPTY, a.const & b.const)]

    def _prim_clamp(self, eqn, env):
        lo_b, x, hi_b = (self._read(env, v) for v in eqn.invars)
        t = IVal(max(x.lo, lo_b.lo), max(x.hi, lo_b.hi),
                 x.integral and lo_b.integral)
        return [IVal(min(t.lo, hi_b.lo), min(t.hi, hi_b.hi),
                     t.integral and hi_b.integral)]

    def _prim_integer_pow(self, eqn, env):
        a = self._read(env, eqn.invars[0])
        k = int(eqn.params["y"])
        if k < 0 or not a.bounded:
            return [TOP]
        corners = [a.lo ** k, a.hi ** k] + ([0.0] if a.lo <= 0 <= a.hi else [])
        return [IVal(min(corners), max(corners), a.integral,
                     a.onehot, a.const)]

    def _prim_floor(self, eqn, env):
        a = self._read(env, eqn.invars[0])
        return [IVal(math.floor(a.lo) if a.bounded else a.lo,
                     math.floor(a.hi) if a.bounded else a.hi, True)]

    def _prim_ceil(self, eqn, env):
        a = self._read(env, eqn.invars[0])
        return [IVal(math.ceil(a.lo) if a.bounded else a.lo,
                     math.ceil(a.hi) if a.bounded else a.hi, True)]

    def _prim_round(self, eqn, env):
        a = self._read(env, eqn.invars[0])
        return [IVal(round(a.lo) if a.bounded else a.lo,
                     round(a.hi) if a.bounded else a.hi, True)]

    def _prim_convert_element_type(self, eqn, env):
        a = self._read(env, eqn.invars[0])
        dt = eqn.params.get("new_dtype")
        if dt is not None and (
            np.issubdtype(dt, np.integer) or dt == np.bool_
        ):
            # float -> int truncates toward zero: stays inside the
            # outward-rounded interval.
            lo = math.floor(a.lo) if math.isfinite(a.lo) else a.lo
            hi = math.ceil(a.hi) if math.isfinite(a.hi) else a.hi
            return [IVal(lo, hi, True, a.onehot, a.const, a.distinct)]
        return [a]

    def _prim_select_n(self, eqn, env):
        pred = self._read(env, eqn.invars[0])
        cases = [self._read(env, v) for v in eqn.invars[1:]]
        out = join_all(cases)
        onehot = frozenset(out.onehot)
        # jnp.where(mask, x, 0): nonzeros of the result are a subset of
        # the mask's trues, so the mask's onehot axes carry over.
        if len(cases) == 2:
            if cases[0].lo == cases[0].hi == 0.0:
                onehot = onehot | pred.onehot | cases[1].onehot
            elif cases[1].lo == cases[1].hi == 0.0:
                onehot = onehot | cases[0].onehot
        return [dataclasses.replace(out, onehot=onehot)]

    # -- bitwise / shifts --------------------------------------------------

    def _prim_eq(self, eqn, env):
        a, b = (self._read(env, v) for v in eqn.invars)
        out_rank = len(getattr(eqn.outvars[0].aval, "shape", ()))

        def const_axes(ival, var):
            # A size-1 axis is trivially constant; rank-0 operands
            # (implicitly broadcast) are constant along every out axis.
            shape = tuple(getattr(var.aval, "shape", ()))
            if not shape:
                return frozenset(range(out_rank))
            return ival.const | frozenset(
                d for d, n in enumerate(shape) if n == 1
            )

        a_const, b_const = const_axes(a, eqn.invars[0]), const_axes(b, eqn.invars[1])
        # eq(iota_d, c) with c constant along d: at most one index along
        # d can match — the one-hot construction idiom.
        onehot = frozenset(
            {d for d in a.distinct if d in b_const}
            | {d for d in b.distinct if d in a_const}
        )
        return [IVal(0.0, 1.0, True, onehot, a.const & b.const)]

    def _bitwise(self, eqn, env, op: str):
        a, b = (self._read(env, v) for v in eqn.invars)
        if eqn.outvars[0].aval.dtype == np.bool_:
            if op == "and":
                # true only where both are: either side's onehot holds.
                return [IVal(0.0, 1.0, True, a.onehot | b.onehot,
                             a.const & b.const)]
            return [IVal(0.0, 1.0, True, _EMPTY, a.const & b.const)]
        if a.bounded and b.bounded and a.lo >= 0 and b.lo >= 0:
            if op == "and":
                return [IVal(0.0, min(a.hi, b.hi), True,
                             a.onehot | b.onehot)]
            bits = max(int(a.hi), int(b.hi)).bit_length()
            return [IVal(0.0, float((1 << bits) - 1), True)]
        return [TOP]

    def _prim_and(self, eqn, env):
        return self._bitwise(eqn, env, "and")

    def _prim_or(self, eqn, env):
        return self._bitwise(eqn, env, "or")

    def _prim_xor(self, eqn, env):
        return self._bitwise(eqn, env, "xor")

    def _prim_not(self, eqn, env):
        if eqn.outvars[0].aval.dtype == np.bool_:
            return [BOOL]
        return [TOP]

    def _prim_shift_left(self, eqn, env):
        a, b = (self._read(env, v) for v in eqn.invars)
        if a.bounded and b.bounded and a.lo >= 0 and b.lo >= 0:
            return [IVal(
                float(int(a.lo) << int(b.lo)),
                float(int(a.hi) << int(b.hi)), True, a.onehot,
            )]
        return [TOP]

    def _shift_right(self, eqn, env):
        a, b = (self._read(env, v) for v in eqn.invars)
        if a.bounded and b.bounded and a.lo >= 0 and b.lo >= 0:
            return [IVal(
                float(int(a.lo) >> int(b.hi)),
                float(int(a.hi) >> int(b.lo)), True,
            )]
        return [TOP]

    def _prim_shift_right_logical(self, eqn, env):
        return self._shift_right(eqn, env)

    def _prim_shift_right_arithmetic(self, eqn, env):
        return self._shift_right(eqn, env)

    def _prim_population_count(self, eqn, env):
        bits = np.dtype(eqn.invars[0].aval.dtype).itemsize * 8
        return [IVal(0.0, float(bits), True)]

    # -- shape / indexing --------------------------------------------------

    def _prim_iota(self, eqn, env):
        dim = eqn.params.get("dimension", 0)
        shape = tuple(eqn.params.get("shape") or eqn.outvars[0].aval.shape)
        n = int(shape[dim]) if shape else 1
        const = frozenset(d for d in range(len(shape)) if d != dim)
        return [IVal(0.0, float(max(0, n - 1)), True,
                     _EMPTY, const, frozenset({dim}))]

    def _prim_broadcast_in_dim(self, eqn, env):
        a = self._read(env, eqn.invars[0])
        bd = tuple(eqn.params["broadcast_dimensions"])
        in_shape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
        out_shape = tuple(eqn.outvars[0].aval.shape)
        mapped = dict(zip(range(len(in_shape)), bd))
        const = {d for d in range(len(out_shape)) if d not in bd}
        onehot, distinct = set(), set()
        for i, d in mapped.items():
            expanded = in_shape[i] == 1 and out_shape[d] > 1
            if expanded or i in a.const:
                const.add(d)
            if not expanded:
                if i in a.onehot:
                    onehot.add(d)
                if i in a.distinct:
                    distinct.add(d)
        return [IVal(a.lo, a.hi, a.integral, frozenset(onehot),
                     frozenset(const), frozenset(distinct))]

    def _prim_transpose(self, eqn, env):
        a = self._read(env, eqn.invars[0])
        perm = tuple(eqn.params["permutation"])

        def remap(axes):
            return frozenset(j for j, i in enumerate(perm) if i in axes)

        return [IVal(a.lo, a.hi, a.integral, remap(a.onehot),
                     remap(a.const), remap(a.distinct))]

    def _prim_concatenate(self, eqn, env):
        return [join_all(
            self._read(env, v) for v in eqn.invars
        ).plain()]

    def _prim_pad(self, eqn, env):
        op, pad_val = (self._read(env, v) for v in eqn.invars)
        return [join(op, pad_val).plain()]

    def _prim_dynamic_update_slice(self, eqn, env):
        op, upd = (self._read(env, v) for v in eqn.invars[:2])
        return [join(op, upd).plain()]

    def _prim_gather(self, eqn, env):
        return [self._read(env, eqn.invars[0]).plain()]

    def _prim_scatter(self, eqn, env):
        op = self._read(env, eqn.invars[0])
        upd = self._read(env, eqn.invars[2])
        return [join(op, upd).plain()]

    def _prim_scatter_add(self, eqn, env):
        op = self._read(env, eqn.invars[0])
        upd = self._read(env, eqn.invars[2])
        n = max(1, _aval_size(eqn.invars[2]))
        return [IVal(
            op.lo + min(0.0, upd.lo * n), op.hi + max(0.0, upd.hi * n),
            op.integral and upd.integral,
        )]

    def _prim_slice(self, eqn, env):
        a = self._read(env, eqn.invars[0])
        start = tuple(eqn.params["start_indices"])
        limit = tuple(eqn.params["limit_indices"])
        strides = eqn.params.get("strides") or (1,) * len(start)
        # Subsetting preserves per-axis structure; a static slice along
        # the column axis of a column-partitioned array refines the
        # interval to the selected columns (meta[:, V:V+1] etc.).
        if (
            a.cols is not None and len(start) == 2 and strides[-1] == 1
            and 0 <= start[1] < limit[1] <= len(a.cols)
        ):
            sel = a.cols[start[1]:limit[1]]
            j = join_all(sel)
            return [IVal(
                j.lo, j.hi, j.integral, a.onehot, a.const, a.distinct,
                sel if len(sel) > 1 else None,
            )]
        return [dataclasses.replace(a, cols=None)]

    def _prim_program_id(self, eqn, env):
        return [IVal(0.0, INF, True)]

    def _prim_num_programs(self, eqn, env):
        return [IVal(1.0, INF, True)]

    # -- reductions --------------------------------------------------------

    def _prim_reduce_sum(self, eqn, env):
        a = self._read(env, eqn.invars[0])
        axes = tuple(eqn.params.get("axes") or ())
        shape = tuple(eqn.invars[0].aval.shape)
        if any(ax in a.onehot for ax in axes):
            # One nonzero per fiber along a onehot axis: the sum over
            # the remaining reduced axes counts at most one term each.
            n = 1
            skipped = False
            for ax in axes:
                if not skipped and ax in a.onehot:
                    skipped = True
                    continue
                n *= int(shape[ax])
        else:
            n = 1
            for ax in axes:
                n *= int(shape[ax])
            if not axes:
                n = max(1, _aval_size(eqn.invars[0])
                        // max(1, _aval_size(eqn.outvars[0])))
        n = max(1, n)
        return [IVal(min(a.lo * n, min(a.lo, 0.0)),
                     max(a.hi * n, max(a.hi, 0.0)), a.integral)]

    def _prim_cumsum(self, eqn, env):
        a = self._read(env, eqn.invars[0])
        axis = eqn.params.get("axis", 0)
        n = int(eqn.invars[0].aval.shape[axis])
        if axis in a.onehot:
            n = 1
        return [IVal(min(a.lo * n, min(a.lo, 0.0)),
                     max(a.hi * n, max(a.hi, 0.0)), a.integral)]

    def _prim_reduce_max(self, eqn, env):
        return [self._read(env, eqn.invars[0]).plain()]

    def _prim_reduce_min(self, eqn, env):
        return [self._read(env, eqn.invars[0]).plain()]

    def _prim_argmax(self, eqn, env):
        axes = eqn.params.get("axes", (0,))
        n = 1
        for ax in axes:
            n *= int(eqn.invars[0].aval.shape[ax])
        return [IVal(0.0, float(max(0, n - 1)), True)]

    def _prim_argmin(self, eqn, env):
        return self._prim_argmax(eqn, env)

    # -- the dot itself ----------------------------------------------------

    def _prim_dot_general(self, eqn, env):
        a, b = (self._read(env, v) for v in eqn.invars[:2])
        self.dots[id(eqn)] = DotRecord(
            eqn=eqn, lhs=a, rhs=b, path=self.path,
            where=source_location(eqn),
        )
        (lhs_contract, rhs_contract), _ = eqn.params["dimension_numbers"]
        # A contracted axis that is onehot on EITHER operand contributes
        # at most one nonzero product to each output sum — the one-hot
        # gather/permute idiom, whose result is bounded by the plain
        # operand product rather than K times it.
        k = 1
        for la, ra in zip(lhs_contract, rhs_contract):
            if la in a.onehot or ra in b.onehot:
                continue
            k *= int(eqn.invars[0].aval.shape[la])
        prod = interval_mul(a, b)
        if not prod.bounded:
            return [TOP]
        return [IVal(
            min(prod.lo * k, min(prod.lo, 0.0)),
            max(prod.hi * k, max(prod.hi, 0.0)),
            a.integral and b.integral,
        )]


_IDENTITY_PRIMS = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "squeeze", "expand_dims",
    "rev", "copy", "dynamic_slice", "reduce_precision",
    "stop_gradient", "device_put", "optimization_barrier", "real",
    "copy_p", "sharding_constraint",
})

_BOOL_PRIMS = frozenset({
    "ne", "lt", "le", "gt", "ge", "reduce_or", "reduce_and",
    "is_finite",
})

_CALL_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "xla_call", "named_call",
})
