"""KI-8 manifest-CI audit: every reported rate carries an interval.

The stats subsystem (docs/STATS.md) makes certified rates cheap — a
rate in a run manifest is a dict with ``rate``/``lo``/``hi`` (see
:class:`qba_tpu.stats.estimators.RateEstimate`), never a bare float.  A
bare number is exactly the anecdotal-evidence failure mode the VALIDITY
study replaced: a point estimate whose precision the reader must guess.
This pass walks manifest JSON recursively and flags every numeric value
under a ``*_rate``-shaped key that is not packaged as an estimate.

Scope notes:

* Keys audited: ``*_rate`` and ``*_ratio`` leaves.  Latency/timing
  totals, counts, and probabilities-as-*inputs* (``p_depolarize`` …)
  are configuration, not measurements, and are not rate-shaped.
* An estimate dict is recognized by carrying ``lo`` and ``hi`` keys
  alongside the point value; its *internal* fields are then exempt.
* ``None`` rates (the uniform zero-trial encoding) are fine — the
  estimate dict around them still carries the vacuous [0, 1] interval.

Findings are tagged ``KI-8`` (docs/KNOWN_ISSUES.md).
"""

from __future__ import annotations

import glob as _glob
import json
import os

from qba_tpu.analysis.findings import Finding, Report

#: Key suffixes that denote a measured proportion.
RATE_SUFFIXES = ("_rate", "_ratio")

#: Keys that prove a dict is a packaged estimate (RateEstimate.to_json).
ESTIMATE_KEYS = frozenset({"lo", "hi"})


def _is_estimate(value) -> bool:
    return isinstance(value, dict) and ESTIMATE_KEYS <= set(value)


def _walk(node, path: str, offenders: list[tuple[str, object]]) -> None:
    if isinstance(node, dict):
        for key, value in node.items():
            child = f"{path}.{key}" if path else str(key)
            if isinstance(key, str) and key.endswith(RATE_SUFFIXES):
                if _is_estimate(value):
                    continue  # certified; don't descend into its fields
                if isinstance(value, (int, float)) and not isinstance(
                    value, bool
                ):
                    offenders.append((child, value))
                    continue
            _walk(value, child, offenders)
    elif isinstance(node, list):
        for i, item in enumerate(node):
            _walk(item, f"{path}[{i}]", offenders)


def check_manifest(manifest: dict, label: str = "<manifest>") -> Report:
    """KI-8 audit of one (already-loaded) manifest dict."""
    report = Report()
    offenders: list[tuple[str, object]] = []
    _walk(manifest, "", offenders)
    report.stats["manifest_rate_keys_flagged"] = len(offenders)
    for key_path, value in offenders:
        report.findings.append(Finding(
            ki="KI-8", check="manifest-ci", path=f"manifest:{label}",
            where=key_path,
            message=(
                f"bare rate {key_path} = {value!r} with no confidence "
                "interval: report rates as estimate objects "
                "(rate/lo/hi, qba_tpu.stats.estimators.RateEstimate) "
                "so the manifest states its own precision"
            ),
        ))
    return report


def check_manifest_files(paths) -> Report:
    """KI-8 audit over manifest files; ``paths`` may contain globs.
    A path that matches nothing, fails to parse, or fails the manifest
    schema is itself a finding — a CI gate that silently skips a
    missing artifact proves nothing."""
    from qba_tpu.obs.manifest import validate_manifest

    report = Report()
    checked = 0
    for pattern in paths:
        matches = sorted(_glob.glob(pattern)) or [pattern]
        for path in matches:
            label = os.path.basename(path)
            if not os.path.exists(path):
                report.findings.append(Finding(
                    ki="KI-8", check="manifest-ci", path=f"manifest:{label}",
                    where=path,
                    message=f"manifest path {path!r} does not exist",
                ))
                continue
            try:
                with open(path) as fh:
                    manifest = json.load(fh)
                validate_manifest(manifest)
            except (ValueError, OSError) as e:
                report.findings.append(Finding(
                    ki="KI-8", check="manifest-ci", path=f"manifest:{label}",
                    where=path,
                    message=f"unreadable/invalid manifest: {e}",
                ))
                continue
            checked += 1
            report.extend(check_manifest(manifest, label=label))
    report.stats["manifests_checked"] = checked
    return report
