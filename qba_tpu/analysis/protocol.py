"""KI-10: exhaustive model check of the fleet's file-queue protocol.

The claim/reclaim/heartbeat/poison/breaker protocol under
``qba_tpu/serve`` is the transport the atlas campaign (ROADMAP item 2)
rides on, and until this pass its invariants were argued in
docstrings and spot-checked by chaos tests — PR 12's reclaim
double-execution race was found by hand.  This module applies the
repo's ByMC bet (PAPERS.md: Konnov–Veith–Widder, POPL 2017) to our own
infrastructure: reduce the protocol's unbounded interleavings to small
bounded configurations, enumerate EVERY schedule by BFS
(:mod:`qba_tpu.analysis.fsm`), and report violations as *minimal
counterexample schedules* instead of flaky repro scripts.

Three layers make this a static-analysis pass, not a free-floating
model:

1. **Extracted semantics** — the model's behavioral switches (does the
   claim re-stamp the mtime?  does the reclaimer emit only at
   dead-letter?  is the stop sentinel checked after the drain?) are
   read from the AST of ``serve/transport.py`` itself, so the model
   checks the code that ships, and the seeded fixtures under
   ``tests/analysis_fixtures/`` are checked by the *same* extraction
   over their bad function bodies.
2. **Conformance** — every filesystem mutation on a queue path
   (``os.replace``/``rename``/``unlink``/``remove``/``utime``
   anywhere under ``serve/``) must carry a ``# qba-protocol:
   <transition>`` annotation binding it to a model transition, and
   every registered code site must still exist.  A future mutation
   that skips registration turns the lint red.
3. **Timing constants** — the model's bounds (reclaim ladder, poison
   threshold) are imported from :mod:`qba_tpu.serve.timing`, the same
   module the shipped code reads, so model and fleet cannot drift.

Timer/crash nondeterminism is abstracted to before/after-timeout
orderings (the ByMC-style reduction): ``age_*`` actions flip a
boolean per file instead of modeling clocks.  One deliberate ordering
assumption is encoded: with the supervisor running, a dead worker's
claim is handled within one poll (0.5 s) — long before the reclaim
timeout (5 s) — so ``age_claim`` on a supervised fleet requires the
death to have been polled first.  The ``release-within-one-poll``
invariant checks the other side of that bargain.
"""

from __future__ import annotations

import ast
import os
import re
from collections import namedtuple
from dataclasses import dataclass
from typing import Iterable

from qba_tpu.analysis.findings import Finding, Report
from qba_tpu.analysis.fsm import (
    Action,
    Invariant,
    explore,
    render_schedule,
)
from qba_tpu.serve.timing import MAX_RECLAIMS, POISON_THRESHOLD

# ---------------------------------------------------------------------------
# Registered mutation sites: (file basename, enclosing function,
# annotation marker).  The conformance sweep fails when a site here is
# missing from the code OR a queue mutation in serve/ is not annotated
# with one of these markers.

PROTOCOL_MARKER = "qba-protocol"

#: marker -> the model action it is part of (documentation + closure:
#: every registered marker must belong to a modeled transition).
MARKER_TO_ACTION = {
    "publish": "enqueue/emit",  # write_json_atomic: temp + rename
    "claim": "claim",
    "restamp": "claim",  # the PR 12 fix: mtime := claim instant
    "settle": "emit",
    "reclaim": "reclaim",
    "dead-letter": "dead-letter",
    "release": "sup_poll",
    "quarantine": "sup_poll",
    "consume": "consume",
}

PROTOCOL_SITES = frozenset(
    {
        ("queuefs.py", "write_json_atomic", "publish"),
        ("transport.py", "serve_file_queue", "claim"),
        ("transport.py", "serve_file_queue", "restamp"),
        ("transport.py", "settle", "settle"),
        ("transport.py", "_reclaim_stale", "reclaim"),
        ("transport.py", "_reclaim_stale", "dead-letter"),
        ("supervisor.py", "_release_claim", "release"),
        ("supervisor.py", "_quarantine", "quarantine"),
        ("frontend.py", "_watch_outbox", "consume"),
    }
)

#: Files where EVERY os-level mutation is a protocol mutation.
_PROTOCOL_MODULES = frozenset(
    {"queuefs.py", "transport.py", "supervisor.py", "pool.py", "frontend.py"}
)

_MUTATORS = frozenset({"replace", "rename", "unlink", "remove", "utime"})

#: Queue-path vocabulary: a mutation in a non-protocol serve/ module is
#: flagged only when its arguments mention the queue layout.
_QUEUE_TOKENS = (
    "inbox",
    "claimed",
    "outbox",
    "consumed",
    "dead",
    "stop",
    "heartbeat",
    "queue_dir",
    "paths[",
)


def _serve_root() -> str:
    import qba_tpu.serve as serve

    return os.path.dirname(os.path.abspath(serve.__file__))


# ---------------------------------------------------------------------------
# Extracted semantics: the behavioral switches the model runs on.


@dataclass(frozen=True)
class ProtocolSemantics:
    """What the claim-loop/reclaim code actually does, per its AST."""

    #: ``os.utime`` re-stamps the claim file to the claim instant right
    #: after the claim rename (the PR 12 fix).  Off = reclaim staleness
    #: is measured from the producer's enqueue mtime.
    restamp_on_claim: bool
    #: The reclaimer writes an outbox result only on the dead-letter
    #: branch (``attempts >= max_reclaims``), never on an ordinary
    #: push-back.  Off = every reclaim also emits (double-emit bug).
    emit_only_at_dead_letter: bool
    #: The stop sentinel is checked AFTER the claimed inbox listing is
    #: drained, so ``stop`` can never overtake queued requests.
    stop_after_drain: bool
    #: Where the claim loop came from (shipped transport.py or a
    #: fixture overlay) — named in findings.
    origin: str


def _functions(tree: ast.Module) -> dict[str, ast.AST]:
    """All function defs in a module, INCLUDING nested ones (the
    transport's ``settle``/``emit`` live inside ``serve_file_queue``)
    and async defs (the frontend's watchers), keyed by bare name;
    outermost wins on duplicates."""
    out: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name not in out
        ):
            out[node.name] = node
    return out


def _calls(fn: ast.AST) -> Iterable[ast.Call]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            yield node


def _is_os_call(call: ast.Call, attr: str) -> bool:
    f = call.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr == attr
        and isinstance(f.value, ast.Name)
        and f.value.id == "os"
    )


def _extract_restamp(fn: ast.FunctionDef) -> bool:
    return any(_is_os_call(c, "utime") for c in _calls(fn))


def _extract_emit_discipline(fn: ast.FunctionDef) -> bool:
    """True iff every ``emit(...)`` in the reclaimer is inside an
    ``if`` whose test mentions the dead-letter bound."""

    def emit_calls_outside_dead_letter(node: ast.AST, guarded: bool) -> int:
        n = 0
        for child in ast.iter_child_nodes(node):
            g = guarded
            if isinstance(child, ast.If) and "max_reclaims" in ast.unparse(
                child.test
            ):
                # Both branches: the else of the dead-letter check is
                # NOT dead-letter-guarded.
                n += sum(
                    emit_calls_outside_dead_letter(s, True)
                    for s in child.body
                )
                n += sum(
                    emit_calls_outside_dead_letter(s, guarded)
                    for s in child.orelse
                )
                continue
            if (
                isinstance(child, ast.Call)
                and isinstance(child.func, ast.Name)
                and child.func.id == "emit"
                and not g
            ):
                n += 1
            n += emit_calls_outside_dead_letter(child, g)
        return n

    return emit_calls_outside_dead_letter(fn, False) == 0


def _extract_stop_after_drain(fn: ast.FunctionDef) -> bool:
    """The inbox-drain ``for`` must precede the stop-sentinel check in
    the claim loop body."""
    drain_line = stop_line = None
    for node in ast.walk(fn):
        if (
            drain_line is None
            and isinstance(node, ast.For)
            and isinstance(node.iter, ast.Name)
            and node.iter.id == "names"
        ):
            drain_line = node.lineno
        if (
            stop_line is None
            and isinstance(node, ast.If)
            and "stop" in ast.unparse(node.test)
        ):
            stop_line = node.lineno
    if drain_line is None or stop_line is None:
        return False  # can't prove the ordering -> treat as violated
    return drain_line < stop_line


def extract_semantics(overlay: str | None = None) -> ProtocolSemantics:
    """Read the behavioral switches from ``serve/transport.py``; when
    ``overlay`` names a fixture module, functions defined there shadow
    the shipped ones (the fixture re-introduces one bad function, the
    rest stays shipped)."""
    shipped = os.path.join(_serve_root(), "transport.py")
    with open(shipped) as f:
        fns = _functions(ast.parse(f.read()))
    origin = "serve/transport.py"
    if overlay is not None:
        with open(overlay) as f:
            for name, fn in _functions(ast.parse(f.read())).items():
                fns[name] = fn
        origin = os.path.basename(overlay)
    claim_loop = fns.get("serve_file_queue")
    reclaimer = fns.get("_reclaim_stale")
    return ProtocolSemantics(
        restamp_on_claim=(
            claim_loop is not None and _extract_restamp(claim_loop)
        ),
        emit_only_at_dead_letter=(
            reclaimer is not None and _extract_emit_discipline(reclaimer)
        ),
        stop_after_drain=(
            claim_loop is not None and _extract_stop_after_drain(claim_loop)
        ),
        origin=origin,
    )


# ---------------------------------------------------------------------------
# The protocol model: state, scenarios, guarded actions, invariants.

# One queue artifact per request:
#   loc      — new | inbox | claimed | done | dead
#   holder   — worker slot index holding the claim file, -1 otherwise
#   aged     — the file's mtime is older than the reclaim timeout
#   attempts — reclaim ladder position (transport's attempts dict)
#   emitted  — outbox results written for this id (capped at 2: the
#              exactly-once invariant fires at 2, higher is the same)
#   blame    — worker deaths the crash ledger charges to this id
#   consumed — the front-end forwarded the result (outbox/->consumed/)
Req = namedtuple(
    "Req", "loc holder aged attempts emitted blame consumed"
)
# One worker slot: st — idle | busy | crashed | exited | benched;
# req — in-flight request index (-1); spawns — respawn count.
Wkr = namedtuple("Wkr", "st req spawns")
St = namedtuple("St", "reqs wkrs stop crashes")


@dataclass(frozen=True)
class Scenario:
    """One bounded configuration the BFS exhausts."""

    name: str
    workers: int = 2
    requests: int = 2
    #: spontaneous worker crashes mid-execution allowed (bounded).
    crashes: bool = False
    max_crashes: int = 0
    #: request indices that kill their claimant (the poison hook).
    poison: tuple[int, ...] = ()
    #: supervisor present (release/quarantine/respawn within one poll).
    supervisor: bool = False
    #: a stop sentinel may be dropped once all requests are enqueued.
    stop: bool = False
    max_respawns: int = 3
    max_reclaims: int = MAX_RECLAIMS
    poison_threshold: int = POISON_THRESHOLD


#: The shipped matrix: every transition of the protocol is live in at
#: least one scenario, and each stays comfortably exhaustive.
DEFAULT_SCENARIOS = (
    # The acceptance-criteria default: crashes under supervision.
    Scenario(
        "2w2r-crash", workers=2, requests=2, crashes=True, max_crashes=2,
        supervisor=True,
    ),
    # Poison quarantine: one request kills every claimant.
    Scenario(
        "2w2r-poison", workers=2, requests=2, poison=(0,), supervisor=True,
    ),
    # Unsupervised chaos: the reclaim ladder is the only recovery, and
    # max_reclaims=1 makes the dead-letter branch reachable in bounds.
    Scenario(
        "3w2r-reclaim", workers=3, requests=2, crashes=True, max_crashes=2,
        supervisor=False, max_reclaims=1,
    ),
    # Clean drain: the stop sentinel must not overtake queued work.
    Scenario("2w2r-stop", workers=2, requests=2, stop=True),
)


def _initial(sc: Scenario) -> St:
    return St(
        reqs=tuple(
            Req("new", -1, False, 0, 0, 0, False)
            for _ in range(sc.requests)
        ),
        wkrs=tuple(Wkr("idle", -1, 0) for _ in range(sc.workers)),
        stop=False,
        crashes=0,
    )


def _set_req(s: St, i: int, r: Req) -> St:
    return s._replace(reqs=s.reqs[:i] + (r,) + s.reqs[i + 1:])


def _set_wkr(s: St, i: int, w: Wkr) -> St:
    return s._replace(wkrs=s.wkrs[:i] + (w,) + s.wkrs[i + 1:])


def build_actions(sem: ProtocolSemantics, sc: Scenario) -> list[Action]:
    """The protocol's guarded transitions under ``sem`` semantics."""

    def enqueue(s: St):
        if s.stop:
            return
        for i, r in enumerate(s.reqs):
            if r.loc == "new":
                yield (
                    f"enqueue(r{i}): frontend drops r{i} into inbox/",
                    _set_req(s, i, r._replace(loc="inbox", aged=False)),
                )

    def age_inbox(s: St):
        for i, r in enumerate(s.reqs):
            if r.loc == "inbox" and not r.aged:
                yield (
                    f"age(r{i}): r{i} waits in the inbox past the "
                    "reclaim timeout (backlog)",
                    _set_req(s, i, r._replace(aged=True)),
                )

    def claim(s: St):
        # sorted(os.listdir(inbox)): workers take the lowest slug first.
        inbox = [i for i, r in enumerate(s.reqs) if r.loc == "inbox"]
        if not inbox:
            return
        i = min(inbox)
        r = s.reqs[i]
        aged = False if sem.restamp_on_claim else r.aged
        stamp = (
            "mtime re-stamped to the claim instant"
            if sem.restamp_on_claim
            else "mtime NOT re-stamped — still the enqueue stamp"
        )
        for wi, w in enumerate(s.wkrs):
            if w.st != "idle":
                continue
            nxt = _set_req(
                s, i, r._replace(loc="claimed", holder=wi, aged=aged)
            )
            if i in sc.poison:
                # The poison hook dies at decode, right after the
                # claim-phase heartbeat named this slug.
                nxt = _set_wkr(nxt, wi, w._replace(st="crashed", req=i))
                yield (
                    f"claim(w{wi},r{i}): w{wi} claims poison r{i} "
                    f"({stamp}) and dies mid-decode",
                    nxt,
                )
            else:
                nxt = _set_wkr(nxt, wi, w._replace(st="busy", req=i))
                yield (
                    f"claim(w{wi},r{i}): w{wi} renames inbox/->claimed/ "
                    f"({stamp})",
                    nxt,
                )

    def emit(s: St):
        for wi, w in enumerate(s.wkrs):
            if w.st != "busy":
                continue
            i = w.req
            r = s.reqs[i]
            nxt = s
            if r.loc == "claimed" and r.holder == wi:
                nxt = _set_req(
                    nxt,
                    i,
                    r._replace(
                        loc="done",
                        holder=-1,
                        emitted=min(r.emitted + 1, 2),
                    ),
                )
                extra = ""
            else:
                # The claim was stolen: settle's rename fails silently
                # ("result wins") but the outbox write still lands.
                nxt = _set_req(
                    nxt, i, r._replace(emitted=min(r.emitted + 1, 2))
                )
                extra = " (claim already stolen; outbox write lands anyway)"
            nxt = _set_wkr(nxt, wi, w._replace(st="idle", req=-1))
            yield (
                f"emit(w{wi},r{i}): w{wi} writes r{i}'s result to "
                f"outbox/ and settles claimed/->done/{extra}",
                nxt,
            )

    def crash(s: St):
        if not sc.crashes or s.crashes >= sc.max_crashes:
            return
        for wi, w in enumerate(s.wkrs):
            if w.st == "busy":
                yield (
                    f"crash(w{wi}): w{wi} dies (SIGKILL/OOM) while "
                    f"executing r{w.req}",
                    _set_wkr(
                        s._replace(crashes=s.crashes + 1),
                        wi,
                        w._replace(st="crashed"),
                    ),
                )

    def age_claim(s: St):
        for i, r in enumerate(s.reqs):
            if r.loc != "claimed" or r.aged or r.holder < 0:
                continue
            holder = s.wkrs[r.holder]
            if holder.st != "crashed":
                # Timer discipline: a live claimant finishes well inside
                # the reclaim timeout (the protocol's stated assumption;
                # enqueue-side aging is modeled separately).
                continue
            if sc.supervisor:
                # Poll period (0.5s) << reclaim timeout (5s): the
                # supervisor always handles a death before the claim
                # ages — sup_poll fires on this state instead.
                continue
            yield (
                f"age(r{i}): r{i}'s claim ages past the reclaim timeout "
                f"(holder w{r.holder} is dead)",
                _set_req(s, i, r._replace(aged=True)),
            )

    def _reclaimable(s: St):
        for i, r in enumerate(s.reqs):
            if r.loc == "claimed" and r.aged:
                for wi, w in enumerate(s.wkrs):
                    if w.st == "idle" and wi != r.holder:
                        yield i, r, wi

    def reclaim(s: St):
        for i, r, wi in _reclaimable(s):
            if r.attempts >= sc.max_reclaims:
                continue  # the dead-letter action owns this case
            emitted = r.emitted
            extra = ""
            if not sem.emit_only_at_dead_letter:
                emitted = min(emitted + 1, 2)
                extra = " AND writes a failure result to outbox/"
            yield (
                f"reclaim(w{wi},r{i}): w{wi} pushes the stale claim "
                f"back claimed/->inbox/ (attempt "
                f"{r.attempts + 1}){extra}",
                _set_req(
                    s,
                    i,
                    r._replace(
                        loc="inbox",
                        holder=-1,
                        aged=False,
                        attempts=r.attempts + 1,
                        emitted=emitted,
                    ),
                ),
            )

    def dead_letter(s: St):
        for i, r, wi in _reclaimable(s):
            if r.attempts < sc.max_reclaims:
                continue
            yield (
                f"dead-letter(w{wi},r{i}): {r.attempts} reclaims burned "
                f"— w{wi} moves r{i} claimed/->dead/ and writes the "
                "failure result",
                _set_req(
                    s,
                    i,
                    r._replace(
                        loc="dead",
                        holder=-1,
                        emitted=min(r.emitted + 1, 2),
                    ),
                ),
            )

    def sup_poll(s: St):
        if not sc.supervisor:
            return
        crashed = [wi for wi, w in enumerate(s.wkrs) if w.st == "crashed"]
        if not crashed:
            return
        nxt = s
        log: list[str] = []
        for wi in crashed:
            w = nxt.wkrs[wi]
            i = w.req
            if i >= 0:
                r = nxt.reqs[i]
                blame = min(r.blame + 1, sc.poison_threshold + 1)
                r = r._replace(blame=blame)
                nxt = _set_req(nxt, i, r)
                if blame >= sc.poison_threshold:
                    # Quarantine: dead-letter NOW with the crash report
                    # (wherever the file sits — claimed or inbox).
                    if r.loc in ("claimed", "inbox"):
                        nxt = _set_req(
                            nxt,
                            i,
                            r._replace(
                                loc="dead",
                                holder=-1,
                                emitted=min(r.emitted + 1, 2),
                            ),
                        )
                        log.append(
                            f"quarantines poison r{i} (blamed for "
                            f"{blame} deaths) -> dead/ + crash report"
                        )
                elif r.loc == "claimed" and r.holder == wi:
                    nxt = _set_req(
                        nxt, i, r._replace(loc="inbox", holder=-1)
                    )
                    log.append(
                        f"blames r{i} for w{wi}'s death and releases "
                        "its claim claimed/->inbox/"
                    )
                else:
                    log.append(f"blames r{i} for w{wi}'s death")
            # Respawn (or bench at the cap) the dead slot.
            if w.spawns >= sc.max_respawns:
                nxt = _set_wkr(nxt, wi, w._replace(st="benched", req=-1))
                log.append(f"benches w{wi} (respawn cap)")
            else:
                nxt = _set_wkr(
                    nxt,
                    wi,
                    w._replace(st="idle", req=-1, spawns=w.spawns + 1),
                )
                log.append(f"respawns w{wi}")
        yield (
            "sup_poll: supervisor " + "; ".join(log),
            nxt,
        )

    def consume(s: St):
        for i, r in enumerate(s.reqs):
            if r.emitted >= 1 and not r.consumed:
                yield (
                    f"consume(r{i}): frontend forwards r{i}'s result "
                    "and moves outbox/->consumed/",
                    _set_req(s, i, r._replace(consumed=True)),
                )

    def drop_stop(s: St):
        if not sc.stop or s.stop:
            return
        if any(r.loc == "new" for r in s.reqs):
            return  # producers stop before pool.stop() drops the sentinel
        yield ("stop: pool.stop() drops the stop sentinel", s._replace(stop=True))

    def wexit(s: St):
        if not s.stop:
            return
        inbox_empty = all(r.loc != "inbox" for r in s.reqs)
        for wi, w in enumerate(s.wkrs):
            if w.st != "idle":
                continue
            if sem.stop_after_drain and not inbox_empty:
                continue  # the claim loop drains its listing first
            note = "" if inbox_empty else " with requests still queued"
            yield (
                f"exit(w{wi}): w{wi} observes the stop sentinel and "
                f"exits{note}",
                _set_wkr(s, wi, w._replace(st="exited")),
            )

    return [
        Action("enqueue", enqueue),
        Action("age_inbox", age_inbox),
        Action("claim", claim),
        Action("emit", emit),
        Action("crash", crash),
        Action("age_claim", age_claim),
        Action("reclaim", reclaim),
        Action("dead-letter", dead_letter),
        Action("sup_poll", sup_poll),
        Action("consume", consume),
        Action("stop", drop_stop),
        Action("exit", wexit),
    ]


def build_invariants(sc: Scenario) -> list[Invariant]:
    def exactly_once(s: St, via: str) -> str | None:
        for i, r in enumerate(s.reqs):
            if r.emitted >= 2:
                return (
                    f"r{i} has {r.emitted} results in the outbox — "
                    "exactly-once settle violated (a client future "
                    "resolves from whichever write raced last)"
                )
        return None

    def single_executor(s: St, via: str) -> str | None:
        for i in range(len(s.reqs)):
            live = [
                wi
                for wi, w in enumerate(s.wkrs)
                if w.st == "busy" and w.req == i
            ]
            if len(live) >= 2:
                pair = " and ".join(f"w{wi}" for wi in live)
                return (
                    f"r{i} is being executed by {pair} concurrently — "
                    "the later claim conflicts with the earlier one "
                    "still live (double execution)"
                )
        return None

    def poison_bound(s: St, via: str) -> str | None:
        for i, r in enumerate(s.reqs):
            if r.blame > sc.poison_threshold:
                return (
                    f"r{i} blamed for {r.blame} worker deaths > "
                    f"poison_threshold={sc.poison_threshold} — "
                    "quarantine failed to bound the blast radius"
                )
        return None

    def release_within_poll(s: St, via: str) -> str | None:
        if via != "sup_poll":
            return None
        for wi, w in enumerate(s.wkrs):
            if w.st == "crashed":
                return (
                    f"w{wi} is still dead-and-unhandled after a "
                    "supervisor poll — release-within-one-poll violated"
                )
        for i, r in enumerate(s.reqs):
            if r.loc == "claimed" and r.holder >= 0:
                h = s.wkrs[r.holder]
                if h.st in ("crashed", "benched") or (
                    h.st == "idle" and h.req != i
                ):
                    return (
                        f"r{i}'s claim is still held by dead slot "
                        f"w{r.holder} after a supervisor poll"
                    )
        return None

    def no_lost_request(s: St, via: str) -> str | None:
        live_slots = [w for w in s.wkrs if w.st not in ("benched",)]
        if not live_slots:
            return None  # fully degraded fleet: admission repriced to 0
        for i, r in enumerate(s.reqs):
            if r.loc != "new" and r.emitted == 0:
                return (
                    f"schedule completed but r{i} (in {r.loc}) never "
                    "produced a result — lost request"
                )
        return None

    return [
        Invariant("exactly-once-settle", exactly_once),
        Invariant("single-executor", single_executor),
        Invariant("poison-bound", poison_bound),
        Invariant("release-within-one-poll", release_within_poll),
        Invariant("no-lost-request", no_lost_request, terminal=True),
    ]


# ---------------------------------------------------------------------------
# Findings assembly.

_CONFLICT_ACTIONS = ("claim", "emit", "reclaim", "dead-letter", "sup_poll")


def _conflict_line(schedule: list[tuple[str, str]]) -> str:
    """Name the two conflicting transitions of a violation: the final
    step plus the last earlier step touching the same request."""
    if not schedule:
        return ""
    last_name, last_detail = schedule[-1]
    m = re.search(r"r\d+", last_detail)
    if m is None:
        return f"conflicting transition: {last_name}"
    token = m.group(0)
    # Prefer the last earlier step that also wrote the outbox (the
    # true partner of an exactly-once violation); fall back to the
    # last protocol transition touching the same request.
    earlier = [
        (name, detail)
        for name, detail in schedule[:-1]
        if name in _CONFLICT_ACTIONS and re.search(rf"\b{token}\b", detail)
    ]
    if "outbox" in last_detail:
        emitters = [s for s in earlier if "outbox" in s[1]]
        earlier = emitters or earlier
    if earlier:
        name, detail = earlier[-1]
        return (
            f"conflicting transitions: [{name}] {detail}  vs  "
            f"[{last_name}] {last_detail}"
        )
    return f"conflicting transition: [{last_name}] {last_detail}"


def check_protocol_model(
    sem: ProtocolSemantics,
    scenarios: Iterable[Scenario] = DEFAULT_SCENARIOS,
    *,
    stop_on_violation: bool = False,
) -> Report:
    """BFS every scenario under ``sem``; violations become KI-10
    findings carrying the minimal counterexample schedule.

    ``stop_on_violation`` (the fixture path) halts each scenario at
    its first — still minimal-depth — counterexample instead of
    exhausting the buggy relation's reachable space; a clean tree
    never halts, so the exhaustiveness note is unaffected there."""
    report = Report()
    states = transitions = 0
    for sc in scenarios:
        ex = explore(
            _initial(sc),
            build_actions(sem, sc),
            build_invariants(sc),
            stop_on_violation=stop_on_violation,
        )
        states += ex.states
        transitions += ex.transitions
        report.notes.append(
            f"protocol/{sc.name}: {ex.states} states, "
            f"{ex.transitions} transitions, diameter {ex.diameter}, "
            f"{ex.terminal_states} terminal state(s) — "
            + (
                "HALTED at first violation"
                if ex.halted
                else ("TRUNCATED" if ex.truncated else "exhaustive")
            )
        )
        if ex.truncated:
            report.findings.append(
                Finding(
                    ki="KI-10",
                    check="protocol-model",
                    path=f"protocol/{sc.name}",
                    message=(
                        "state space truncated before exhaustion — a "
                        "clean result is inconclusive; shrink the "
                        "scenario or raise max_states"
                    ),
                )
            )
        for v in ex.violations:
            report.findings.append(
                Finding(
                    ki="KI-10",
                    check="protocol-model",
                    path=f"protocol/{sc.name}",
                    message=(
                        f"[{sem.origin}] {v.message}\n"
                        f"  minimal counterexample ({v.depth} steps, "
                        f"{sc.workers} workers x {sc.requests} "
                        "requests):\n"
                        + render_schedule(v.schedule, indent="    ")
                        + "\n  " + _conflict_line(v.schedule)
                    ),
                )
            )
    report.stats["protocol_states_explored"] = states
    report.stats["protocol_transitions_explored"] = transitions
    return report


# ---------------------------------------------------------------------------
# Conformance: every queue mutation in serve/ is bound to the model.

_ANNOT_RE = re.compile(rf"#\s*{PROTOCOL_MARKER}:\s*([A-Za-z0-9_-]+)")


def _annotation_near(lines: list[str], lineno: int) -> str | None:
    """The ``# qba-protocol: <marker>`` on the call line or up to two
    lines above it (the repo's annotation idiom)."""
    for ln in range(lineno, max(lineno - 3, 0), -1):
        m = _ANNOT_RE.search(lines[ln - 1])
        if m:
            return m.group(1)
    return None


def _iter_mutations(tree: ast.Module):
    """Yield ``(call, enclosing_function_name)`` for every os-level
    mutation call, tracking the innermost enclosing function."""

    def walk(node: ast.AST, fn: str):
        for child in ast.iter_child_nodes(node):
            f = fn
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                f = child.name
            if isinstance(child, ast.Call):
                func = child.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr in _MUTATORS
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "os"
                ):
                    yield child, f
            yield from walk(child, f)

    yield from walk(tree, "<module>")


def check_protocol_conformance(serve_root: str | None = None) -> Report:
    """AST sweep of ``serve/``: flag any unregistered queue mutation
    and any registered model site that has gone missing."""
    root = serve_root if serve_root is not None else _serve_root()
    report = Report()
    seen_sites: set[tuple[str, str, str]] = set()
    mutations = 0
    for dirpath, _dirnames, filenames in os.walk(root):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            with open(path) as f:
                src = f.read()
            lines = src.splitlines()
            rel = os.path.relpath(path, root)
            try:
                tree = ast.parse(src)
            except SyntaxError:
                continue
            strict = fname in _PROTOCOL_MODULES
            for call, fn_name in _iter_mutations(tree):
                arg_src = " ".join(
                    ast.unparse(a) for a in call.args
                )
                queueish = strict or any(
                    t in arg_src for t in _QUEUE_TOKENS
                )
                if not queueish:
                    continue
                mutations += 1
                marker = _annotation_near(lines, call.lineno)
                where = f"{rel}:{call.lineno}"
                mut = ast.unparse(call.func)
                if marker is None:
                    report.findings.append(
                        Finding(
                            ki="KI-10",
                            check="protocol-conformance",
                            path=f"serve/{rel}",
                            message=(
                                f"unmapped queue mutation {mut}(...) in "
                                f"{fn_name}() — every rename/unlink/"
                                "utime on a queue path must carry a "
                                f"'# {PROTOCOL_MARKER}: <transition>' "
                                "annotation binding it to a transition "
                                "modeled in analysis/protocol.py"
                            ),
                            where=where,
                        )
                    )
                    continue
                if marker not in MARKER_TO_ACTION:
                    report.findings.append(
                        Finding(
                            ki="KI-10",
                            check="protocol-conformance",
                            path=f"serve/{rel}",
                            message=(
                                f"unknown protocol transition "
                                f"{marker!r} on {mut}(...) — known: "
                                f"{sorted(MARKER_TO_ACTION)}"
                            ),
                            where=where,
                        )
                    )
                    continue
                seen_sites.add((fname, fn_name, marker))
    for site in sorted(PROTOCOL_SITES - seen_sites):
        fname, fn_name, marker = site
        report.findings.append(
            Finding(
                ki="KI-10",
                check="protocol-conformance",
                path=f"serve/{fname}",
                message=(
                    f"registered model site lost: the {marker!r} "
                    f"transition ({MARKER_TO_ACTION[marker]}) is bound "
                    f"to {fn_name}() in {fname} but no annotated "
                    "mutation was found there — update the model AND "
                    "PROTOCOL_SITES together"
                ),
            )
        )
    report.stats["protocol_mutations_checked"] = mutations
    report.stats["protocol_sites_bound"] = len(
        seen_sites & PROTOCOL_SITES
    )
    return report


def check_admission_purity(frontend_path: str | None = None) -> Report:
    """The admission-ledger purity invariant, statically: the deferred
    retry loop must poll with ``try_admit(..., record=False)`` and
    record only the resolving decision — otherwise the decision ledger
    becomes a function of settle *timing*, not of the request stream
    and settle points."""
    path = (
        frontend_path
        if frontend_path is not None
        else os.path.join(_serve_root(), "fleet", "frontend.py")
    )
    report = Report()
    with open(path) as f:
        tree = ast.parse(f.read())
    fns = _functions(tree)
    retry = fns.get("_retry_deferred")
    if retry is None:
        report.findings.append(
            Finding(
                ki="KI-10",
                check="admission-purity",
                path="serve/fleet/frontend.py",
                message=(
                    "_retry_deferred() not found — the deferred-retry "
                    "purity proof has no anchor"
                ),
            )
        )
        return report
    ok_poll = records = False
    for call in _calls(retry):
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr == "try_admit":
            ok_poll = any(
                kw.arg == "record"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is False
                for kw in call.keywords
            )
            if not ok_poll:
                report.findings.append(
                    Finding(
                        ki="KI-10",
                        check="admission-purity",
                        path="serve/fleet/frontend.py",
                        message=(
                            "deferred-retry try_admit() without "
                            "record=False — a still-full retry would "
                            "append one DEFER per settle event, making "
                            "the admission ledger a function of settle "
                            "timing (purity violated)"
                        ),
                        where=f"frontend.py:{call.lineno}",
                    )
                )
        if isinstance(f, ast.Attribute) and f.attr == "record":
            records = True
    if ok_poll and not records:
        report.findings.append(
            Finding(
                ki="KI-10",
                check="admission-purity",
                path="serve/fleet/frontend.py",
                message=(
                    "deferred retries poll with record=False but never "
                    "record the resolving decision — resolved retries "
                    "would vanish from the admission ledger"
                ),
            )
        )
    report.stats["admission_purity_checked"] = 1
    return report


# ---------------------------------------------------------------------------
# Entry points.


def check_protocol(
    serve_root: str | None = None,
    scenarios: Iterable[Scenario] = DEFAULT_SCENARIOS,
) -> Report:
    """The full KI-10 pass over the shipped tree: extracted-semantics
    model check + conformance sweep + admission purity.  This is what
    ``qba-tpu lint --protocol`` runs."""
    report = Report()
    sem = extract_semantics()
    report.notes.append(
        f"protocol semantics [{sem.origin}]: restamp_on_claim="
        f"{sem.restamp_on_claim}, emit_only_at_dead_letter="
        f"{sem.emit_only_at_dead_letter}, stop_after_drain="
        f"{sem.stop_after_drain}"
    )
    report.extend(check_protocol_model(sem, scenarios))
    report.extend(check_protocol_conformance(serve_root))
    report.extend(check_admission_purity())
    return report


def check_protocol_fixture(
    fixture_path: str,
    scenarios: Iterable[Scenario] = DEFAULT_SCENARIOS,
) -> Report:
    """Model-check a seeded violation fixture: functions defined in
    ``fixture_path`` shadow the shipped transport's, and the SAME
    scenarios/invariants run over the resulting semantics.  Used by
    tests/test_analysis_protocol.py and the CI fixture gate — the
    checker must kill every fixture with a printed schedule.

    Runs in stop-at-first-counterexample mode: a seeded bug can blow
    the reachable space up ~350x (the no-restamp race reaches 175k
    states under 2w2r-crash vs the clean tree's 495), and the first
    BFS witness is already the minimal schedule we print."""
    sem = extract_semantics(overlay=fixture_path)
    return check_protocol_model(sem, scenarios, stop_on_violation=True)
