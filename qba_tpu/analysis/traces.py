"""Trace catalog: every engine/kernel build path as a jaxpr + seeds.

Each entry pairs ``jax.make_jaxpr`` of one round-step callable — built
exactly the way the engines build it (``rounds/engine.py``,
``parallel/spmd.py``) — with per-operand seed intervals derived from
the protocol's own invariants:

* evidence values live in ``[-1, w-1]`` (SENTINEL plus particle-list
  draws from ``[0, w)``);
* row lengths in ``[0, size_l]``; evidence counts in ``[0, max_l]``;
* order values in ``[0, w]`` (mailbox ``v < w``; the oob test
  tolerates ``<= w``); forged ``rand_v < n_parties + 1 <= w``;
* attack draws are 4-bit actions (``[0, 15]``); honesty/acceptance/
  P-mask/sent columns are 0/1;
* pool meta packs ``(count, v, sent, cell)`` with cell ids below the
  pool capacity ``n_lieutenants * slots``;
* the all-receiver tables carry ``li + 1`` (``[1, w]``) and
  ``li^2 - 1`` (``[-1, (w-1)^2 - 1]``).

Operand arrays are built with the repo's own packing helpers
(``pack_mailbox``, ``empty_pool``, ``make_verdict_tables``, ...) so
the catalog cannot drift from the layouts the kernels define; block
plans and variants come from the same ``resolve_*`` probes the engines
call.  A path whose plan resolves to None (probe demotion) is recorded
as a note, not silently dropped.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from qba_tpu.analysis.intervals import BOOL, IVal
from qba_tpu.config import QBAConfig


@dataclasses.dataclass
class TracedPath:
    """One build path: its closed jaxpr plus input seed intervals."""

    name: str  # e.g. "pallas_tiled/verdict" or "spmd/pallas_fused"
    closed_jaxpr: object
    seeds: list  # IVal per flattened jaxpr input


def _seed_bank(cfg: QBAConfig) -> dict:
    w = cfg.w
    cap = cfg.n_lieutenants * cfg.slots
    return {
        "round": IVal(1, cfg.n_rounds, True),
        "vals": IVal(-1, w, True),
        "lens": IVal(0, cfg.size_l, True),
        "count": IVal(0, cfg.max_l, True),
        "v": IVal(0, w, True),
        "bit": BOOL,
        "li": IVal(0, w - 1, True),
        "attack": IVal(0, 15, True),
        "rand_v": IVal(0, w, True),
        # Pool meta packs heterogeneous columns [cap, 4]; the per-column
        # intervals (ops/round_kernel_tiled.py META_* layout) let the
        # interpreter refine static column slices instead of tainting
        # the v column with the cell-id bound.
        "meta": IVal(
            0, max(cap - 1, w, cfg.max_l), True,
            cols=(
                IVal(0, cfg.max_l, True),   # META_COUNT
                IVal(0, w, True),           # META_V
                BOOL,                       # META_SENT
                IVal(0, cap - 1, True),     # META_CELL
            ),
        ),
        "tables": (
            IVal(1, w, True),                   # t_li1 = li + 1
            IVal(-1, (w - 1) ** 2 - 1, True),   # t_li2 = li^2 - 1
            BOOL, BOOL, BOOL,                   # t_oob, t_lh, t_lh2
        ),
    }


def _flatten_seeds(seeds_tree) -> list:
    return jax.tree_util.tree_leaves(
        seeds_tree, is_leaf=lambda x: isinstance(x, IVal)
    )


def _trace(name: str, fn, args, seeds_tree) -> TracedPath:
    closed = jax.make_jaxpr(fn)(*args)
    seeds = _flatten_seeds(seeds_tree)
    n_in = len(closed.jaxpr.invars)
    if len(seeds) != n_in:
        raise RuntimeError(
            f"{name}: seed tree has {len(seeds)} leaves but the traced "
            f"jaxpr takes {n_in} inputs — the catalog drifted from the "
            "builder's calling convention"
        )
    return TracedPath(name=name, closed_jaxpr=closed, seeds=seeds)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _mailbox_args(cfg: QBAConfig, sb):
    from qba_tpu.rounds.mailbox import empty_mailbox

    mb = empty_mailbox(cfg)
    mb_seeds = type(mb)(
        vals=sb["vals"], lens=sb["lens"], count=sb["count"],
        p_mask=sb["bit"], v=sb["v"], sent=sb["bit"],
    )
    return mb, mb_seeds


def _packed_args(cfg: QBAConfig, sb):
    from qba_tpu.ops.round_kernel import pack_mailbox
    from qba_tpu.rounds.mailbox import empty_mailbox

    n_pk = cfg.n_lieutenants * cfg.slots
    packed = pack_mailbox(empty_mailbox(cfg), n_pk, cfg.max_l, cfg.size_l)
    seeds = (sb["vals"], sb["lens"], sb["count"], sb["bit"], sb["v"],
             sb["bit"])
    return packed, seeds


def _pool_args(cfg: QBAConfig, sb):
    from qba_tpu.ops.round_kernel_tiled import empty_pool

    pool = empty_pool(cfg)
    seeds = (sb["vals"], sb["lens"], sb["bit"], sb["meta"])
    return pool, seeds


def _draws(cfg: QBAConfig, n_rv: int):
    n_pk = cfg.n_lieutenants * cfg.slots
    z = jnp.zeros((n_pk, n_rv), jnp.int32)
    return (z, z, z)


def _li_arg(cfg: QBAConfig, variant: str, sb):
    """The verdict/fused kernels' li operand for ``variant`` plus its
    seed tree: the receiver-table tuple for "allrecv", the li matrix
    for the group family."""
    li = jnp.zeros((cfg.n_lieutenants, cfg.size_l), jnp.int32)
    if variant == "allrecv":
        from qba_tpu.ops.round_kernel_tiled import make_verdict_tables

        return make_verdict_tables(cfg, li), sb["tables"]
    return li, sb["li"]


def trace_xla(cfg: QBAConfig) -> list[TracedPath]:
    """The pure-XLA receiver round (``run_rounds_xla``'s vmapped body)."""
    from qba_tpu.adversary import sample_attacks_round
    from qba_tpu.rounds.engine import receiver_round

    sb = _seed_bank(cfg)
    mb, mb_seeds = _mailbox_args(cfg, sb)
    d = sample_attacks_round(cfg, jax.random.PRNGKey(0))
    draws = tuple(x[:, 0] for x in d)
    args = (
        jnp.asarray(1, jnp.int32),            # round_idx
        draws,
        jnp.asarray(0, jnp.int32),            # receiver_idx
        jnp.zeros((cfg.w,), bool),            # vi_row
        jnp.zeros((cfg.size_l,), jnp.int32),  # li
        mb,
        jnp.ones((cfg.n_parties + 1,), bool),  # honest
    )
    seeds = (
        sb["round"], (sb["attack"], sb["rand_v"], sb["bit"]),
        IVal(0, cfg.n_lieutenants - 1, True), sb["bit"], sb["li"],
        mb_seeds, sb["bit"],
    )
    return [_trace(
        "xla/receiver_round",
        lambda r, dr, ri, vi, li, mb, h: receiver_round(
            cfg, r, dr, ri, vi, li, mb, h
        ),
        args, seeds,
    )]


def trace_pallas(
    cfg: QBAConfig, n_recv: int | None = None, out_vma=None,
) -> list[TracedPath]:
    """The monolithic round-step kernel, global or party-sharded.
    ``out_vma`` is forwarded to the builder so the KI-1 threading audit
    (:mod:`qba_tpu.analysis.vma`) can inject a recorded sentinel."""
    from qba_tpu.ops.round_kernel import build_round_step, honest_packets

    sb = _seed_bank(cfg)
    n_lieu = cfg.n_lieutenants
    n_rv = n_recv if n_recv is not None else n_lieu
    step = build_round_step(
        cfg, interpret=_interpret(), n_recv=n_recv, out_vma=out_vma,
    )
    packed, packed_seeds = _packed_args(cfg, sb)
    honest_pk = honest_packets(jnp.ones((cfg.n_parties + 1,), bool), cfg)
    tail = (
        jnp.zeros((n_rv, cfg.size_l), jnp.int32),  # li block
        jnp.zeros((n_rv, cfg.w), jnp.int32),       # vi block
        honest_pk, *_draws(cfg, n_rv),
    )
    tail_seeds = (sb["li"], sb["bit"], sb["bit"], sb["attack"],
                  sb["rand_v"], sb["bit"])
    r = jnp.asarray(1, jnp.int32)
    if n_recv is None:
        return [_trace(
            "pallas/round_step", step, (r, *packed, *tail),
            (sb["round"], packed_seeds, tail_seeds),
        )]
    off = jnp.asarray(0, jnp.int32)
    off_seed = IVal(0, n_lieu - n_rv, True)
    return [_trace(
        "spmd/pallas/round_step", step, (r, off, *packed, *tail),
        (sb["round"], off_seed, packed_seeds, tail_seeds),
    )]


def trace_tiled(cfg: QBAConfig, n_recv: int | None = None, out_vma=None):
    """The packet-tiled verdict + rebuild kernel pair.  Returns
    ``(paths, notes)`` — a probe-demoted rebuild plan becomes a note."""
    from qba_tpu.ops.round_kernel_tiled import (
        build_rebuild_kernel,
        build_verdict_kernel,
        honest_cells,
        resolve_rebuild_block,
        resolve_tiled_block,
        resolve_verdict_variant,
    )

    sb = _seed_bank(cfg)
    notes: list[str] = []
    n_lieu = cfg.n_lieutenants
    n_rv = n_recv if n_recv is not None else n_lieu
    prefix = "spmd/" if n_recv is not None else ""
    variant = resolve_verdict_variant(cfg, n_recv=n_recv)
    blk = resolve_tiled_block(cfg, n_recv=n_recv)
    if blk is None:
        return [], [f"{prefix}pallas_tiled: no block plan at "
                    f"(n_parties={cfg.n_parties}, size_l={cfg.size_l}); "
                    "path skipped"]
    verdict = build_verdict_kernel(
        cfg, blk, interpret=_interpret(), n_recv=n_recv, variant=variant,
        out_vma=out_vma,
    )
    pool, pool_seeds = _pool_args(cfg, sb)
    hc = honest_cells(jnp.ones((cfg.n_parties + 1,), bool), cfg)
    li_mat = jnp.zeros((n_rv, cfg.size_l), jnp.int32)
    li_arg, li_seed = (
        _li_arg(cfg, variant, sb) if n_recv is None else (li_mat, sb["li"])
    )
    vi = jnp.zeros((n_rv, cfg.w), jnp.int32)
    draws = _draws(cfg, n_rv)
    r = jnp.asarray(1, jnp.int32)
    off = jnp.asarray(0, jnp.int32)
    off_seed = IVal(0, n_lieu - n_rv, True)
    if n_recv is None:
        v_args = (r, *pool, li_arg, vi, hc, *draws)
        v_seeds = (sb["round"], pool_seeds, li_seed, sb["bit"], sb["bit"],
                   sb["attack"], sb["rand_v"], sb["bit"])
    else:
        v_args = (r, off, *pool, li_mat, vi, hc, *draws)
        v_seeds = (sb["round"], off_seed, pool_seeds, sb["li"], sb["bit"],
                   sb["bit"], sb["attack"], sb["rand_v"], sb["bit"])
    paths = [_trace(f"{prefix}pallas_tiled/verdict", verdict, v_args, v_seeds)]

    blk_d = resolve_rebuild_block(cfg, n_recv=n_recv)
    if blk_d is None:
        notes.append(
            f"{prefix}pallas_tiled: rebuild kernel demoted to the XLA "
            f"rebuild at (n_parties={cfg.n_parties}, size_l={cfg.size_l})"
        )
        return paths, notes
    rebuild = build_rebuild_kernel(
        cfg, blk_d, interpret=_interpret(), n_recv=n_recv, out_vma=out_vma,
    )
    acc_aval = jax.eval_shape(verdict, *v_args)[0]
    acc = jnp.zeros(acc_aval.shape, acc_aval.dtype)
    if n_recv is None:
        rb_args = (r, *pool, li_mat, acc, draws[0], draws[1], hc)
        rb_seeds = (sb["round"], pool_seeds, sb["li"], sb["bit"],
                    sb["attack"], sb["rand_v"], sb["bit"])
    else:
        rb_args = (r, off, *pool, li_mat, acc, draws[0], draws[1], hc)
        rb_seeds = (sb["round"], off_seed, pool_seeds, sb["li"], sb["bit"],
                    sb["attack"], sb["rand_v"], sb["bit"])
    paths.append(
        _trace(f"{prefix}pallas_tiled/rebuild", rebuild, rb_args, rb_seeds)
    )
    return paths, notes


def trace_fused(cfg: QBAConfig, n_recv: int | None = None, out_vma=None):
    """The fused single-launch round kernel.  Returns ``(paths, notes)``."""
    from qba_tpu.ops.round_kernel_tiled import (
        build_fused_round_kernel,
        honest_cells,
        resolve_fused_block,
        resolve_tiled_block,
        resolve_verdict_variant,
    )

    sb = _seed_bank(cfg)
    n_lieu = cfg.n_lieutenants
    n_rv = n_recv if n_recv is not None else n_lieu
    prefix = "spmd/" if n_recv is not None else ""
    variant = resolve_verdict_variant(cfg, n_recv=n_recv)
    blk_v = resolve_tiled_block(cfg, n_recv=n_recv)
    blk_d = resolve_fused_block(cfg, n_recv=n_recv)
    if blk_v is None or blk_d is None:
        return [], [
            f"{prefix}pallas_fused: no fused plan at (n_parties="
            f"{cfg.n_parties}, size_l={cfg.size_l}); demotes to the "
            "two-kernel tiled path"
        ]
    fused = build_fused_round_kernel(
        cfg, blk_d, blk_v, interpret=_interpret(), n_recv=n_recv,
        variant=variant, out_vma=out_vma,
    )
    pool, pool_seeds = _pool_args(cfg, sb)
    hc = honest_cells(jnp.ones((cfg.n_parties + 1,), bool), cfg)
    li_mat = jnp.zeros((n_rv, cfg.size_l), jnp.int32)
    vi = jnp.zeros((n_rv, cfg.w), jnp.int32)
    draws = _draws(cfg, n_rv)
    r = jnp.asarray(1, jnp.int32)
    if n_recv is None:
        li_full = jnp.zeros((n_lieu, cfg.size_l), jnp.int32)
        li_arg, li_seed = _li_arg(cfg, variant, sb)
        args = (r, *pool, li_full, li_arg, vi, hc, *draws)
        seeds = (sb["round"], pool_seeds, sb["li"], li_seed, sb["bit"],
                 sb["bit"], sb["attack"], sb["rand_v"], sb["bit"])
    else:
        off = jnp.asarray(0, jnp.int32)
        args = (r, off, *pool, li_mat, li_mat, vi, hc, *draws)
        seeds = (sb["round"], IVal(0, n_lieu - n_rv, True), pool_seeds,
                 sb["li"], sb["li"], sb["bit"], sb["bit"], sb["attack"],
                 sb["rand_v"], sb["bit"])
    return [_trace(f"{prefix}pallas_fused/round", fused, args, seeds)], []


def trace_mega(cfg: QBAConfig, out_vma=None):
    """The trial megakernel: decode + in-kernel round loop + decision
    reduce in one launch.  Returns ``(paths, notes)`` — a missing plan
    (:func:`~qba_tpu.ops.round_kernel_tiled.resolve_mega_block`)
    becomes a note, mirroring the engine's recorded demotion to the
    fused per-round path."""
    from qba_tpu.ops.round_kernel_tiled import (
        honest_cells,
        resolve_mega_block,
        resolve_verdict_variant,
    )
    from qba_tpu.ops.trial_megakernel import build_trial_megakernel

    sb = _seed_bank(cfg)
    n_lieu = cfg.n_lieutenants
    n_pool = n_lieu * cfg.slots
    variant = resolve_verdict_variant(cfg)
    plan = resolve_mega_block(cfg)
    if plan is None:
        return [], [
            f"pallas_mega: no megakernel plan at (n_parties="
            f"{cfg.n_parties}, size_l={cfg.size_l}); demotes to the "
            "fused per-round engine"
        ]
    mega = build_trial_megakernel(
        cfg, *plan, interpret=_interpret(), variant=variant,
        out_vma=out_vma,
    )
    li_full = jnp.zeros((n_lieu, cfg.size_l), jnp.int32)
    li_arg, li_seed = _li_arg(cfg, variant, sb)
    hc = honest_cells(jnp.ones((cfg.n_parties + 1,), bool), cfg)
    z = jnp.zeros((cfg.n_rounds, n_pool, n_lieu), jnp.int32)
    args = (
        jnp.zeros((n_lieu, cfg.size_l), bool),  # p_rows
        li_full,
        li_arg,
        jnp.zeros((n_lieu,), jnp.int32),  # v_sent
        hc,
        z, z, z,  # attack / rand_v / late, round-stacked
    )
    seeds = (
        sb["bit"], sb["li"], li_seed, sb["v"], sb["bit"],
        sb["attack"], sb["rand_v"], sb["bit"],
    )
    return [_trace("pallas_mega/trial", mega, args, seeds)], []


def trace_gf2(cfg: QBAConfig) -> list[TracedPath]:
    """The batched GF(2) symplectic sampler paths — resource generation
    on ``qsim_path="stabilizer"`` (:mod:`qba_tpu.gf2.symplectic`).

    The traced callables are the pure sampler cores: they take the
    pre-drawn measurement coins (``rnds``) and the permutation-bit
    params as *inputs* (the PRNG draw lives outside the core), so both
    seed as 0/1 and every parity dot's operands are interval-proven
    bf16-exact from the seeds alone — the KI-3 acceptance for this
    subsystem is zero ``exact-ok`` allowlist markers.  The third path
    pins the standalone K-tiled parity matmul at a contraction length
    (``2 * total_qubits``) that forces multi-tile accumulation at
    reference scale.
    """
    from qba_tpu.gf2 import build_gf2_sample_core, gf2_matmul
    from qba_tpu.qsim.protocol_circuits import (
        gen_nq_corr_circuit,
        gen_q_corr_circuit,
    )

    n, nq = cfg.n_parties, cfg.n_qubits
    total = (n + 1) * nq
    b = cfg.size_l
    circ_q = gen_q_corr_circuit(n, nq)
    circ_nq = gen_nq_corr_circuit(n, nq)
    core_q = build_gf2_sample_core(total, tuple(circ_q.ops), circ_q.n_params)
    core_nq = build_gf2_sample_core(total, tuple(circ_nq.ops), 0)
    rnds = jnp.zeros((b, total), jnp.int32)
    params = jnp.zeros((b, max(circ_q.n_params, 1)), jnp.int32)
    return [
        _trace("gf2/sampler/qcorr", core_q, (rnds, params), (BOOL, BOOL)),
        _trace("gf2/sampler/nqcorr", lambda r: core_nq(r), (rnds,), (BOOL,)),
        _trace(
            "gf2/matmul",
            gf2_matmul,
            (
                jnp.zeros((b, 2 * total), jnp.int32),
                jnp.zeros((2 * total, total), jnp.int32),
            ),
            (BOOL, BOOL),
        ),
    ]


def trace_paths(cfg: QBAConfig, engines=None):
    """Trace every requested build path.  ``engines`` is an iterable of
    {"xla", "pallas", "pallas_tiled", "pallas_fused", "pallas_mega",
    "spmd", "gf2"}; None traces everything.  Returns
    ``(paths, notes)``."""
    engines = set(engines) if engines is not None else {
        "xla", "pallas", "pallas_tiled", "pallas_fused", "pallas_mega",
        "spmd", "gf2",
    }
    paths: list[TracedPath] = []
    notes: list[str] = []
    if "xla" in engines:
        paths += trace_xla(cfg)
    if "pallas" in engines:
        paths += trace_pallas(cfg)
    if "pallas_tiled" in engines:
        p, n = trace_tiled(cfg)
        paths += p
        notes += n
    if "pallas_fused" in engines:
        p, n = trace_fused(cfg)
        paths += p
        notes += n
    if "pallas_mega" in engines:
        p, n = trace_mega(cfg)
        paths += p
        notes += n
    if "gf2" in engines:
        paths += trace_gf2(cfg)
    if "spmd" in engines:
        n_lieu = cfg.n_lieutenants
        if n_lieu % 2 == 0:
            n_local = n_lieu // 2
            paths += trace_pallas(cfg, n_recv=n_local)
            p, n = trace_tiled(cfg, n_recv=n_local)
            paths += p
            notes += n
            p, n = trace_fused(cfg, n_recv=n_local)
            paths += p
            notes += n
        else:
            notes.append(
                f"spmd: n_lieutenants={n_lieu} not divisible by 2; "
                "party-sharded variants skipped"
            )
    return paths, notes
