"""Memoized ``run_trial`` jaxpr traces for one lint run.

With ``--effects`` the lint matrix traces the same
``jax.make_jaxpr(run_trial)`` path repeatedly: the KI-5 launch pins
(:mod:`qba_tpu.analysis.launches`) trace every engine, then the scan
carry audits and the megakernel one-launch proof
(:mod:`qba_tpu.analysis.effects`) trace the SAME (config, engine)
pairs again.  Tracing is the dominant lint cost, so
:func:`trial_jaxpr` memoizes on the ``(QBAConfig, engine)`` key —
``QBAConfig`` is a frozen dataclass, so the key is exact, and any
config difference (a demotion-relevant flag, a strategy) is a
different entry, never a stale hit.

Warnings are part of the trace's meaning here: the launch pins and
the mega audit decide "pin vs skip" by whether a
``QBADemotionWarning`` was recorded during tracing.  The cache
therefore captures the warning list at trace time and hands the same
list back on every hit (callers inspect, never re-emit).  Exceptions
are cached too — a failing trace fails identically on the retry, and
callers note-and-skip on the first failure already.

The cache is process-global but scoped by convention to one driver
run: :func:`~qba_tpu.analysis.driver.run_lint` calls :func:`reset`
on entry so back-to-back lints (tests, REPL) never see each other's
traces, and reports ``stats()`` in its ``-v`` output.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Any

from qba_tpu.config import QBAConfig

#: (cfg, engine) -> ("ok", closed_jaxpr, warnings) | ("err", exc)
_CACHE: dict[tuple[QBAConfig, str | None], tuple] = {}
_HITS = 0


def trial_jaxpr(
    cfg: QBAConfig, engine: str | None
) -> tuple[Any, list[warnings.WarningMessage]]:
    """The traced ``run_trial`` jaxpr for ``cfg`` with the round
    engine forced to ``engine`` (``None`` = the config's own
    resolution), plus the warnings the trace recorded.

    Returns ``(closed_jaxpr, warning_messages)``; raises the original
    exception (cached) when the trace fails.
    """
    global _HITS
    key = (cfg, engine)
    hit = _CACHE.get(key)
    if hit is not None:
        _HITS += 1
        if hit[0] == "err":
            raise hit[1]
        return hit[1], hit[2]

    import jax

    from qba_tpu.rounds.engine import run_trial

    ecfg = (
        dataclasses.replace(cfg, round_engine=engine)
        if engine is not None
        else cfg
    )
    try:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            closed = jax.make_jaxpr(
                lambda k: run_trial(ecfg, k)
            )(jax.random.key(0))
    except Exception as exc:
        _CACHE[key] = ("err", exc)
        raise
    _CACHE[key] = ("ok", closed, list(caught))
    return closed, list(caught)


def reset() -> None:
    """Drop every cached trace and zero the hit counter (one driver
    run = one cache generation)."""
    global _HITS
    _CACHE.clear()
    _HITS = 0


def stats() -> dict[str, int]:
    return {"trace_cache_entries": len(_CACHE), "trace_cache_hits": _HITS}
