"""Finding/report data model for the static invariant checker.

A *finding* is one violated invariant, tagged with the Known Issue it
mechanizes (``docs/KNOWN_ISSUES.md``):

* ``KI-1`` — ``out_vma`` dead machinery / ``check_vma`` policy drift
  on the party-sharded kernel builders (:mod:`qba_tpu.analysis.vma`).
* ``KI-2`` — a kernel/HBM plan that is statically inconsistent with
  its own budget (:mod:`qba_tpu.analysis.memory`).
* ``KI-3`` — a default-precision float dot whose integer operand bound
  exceeds bf16's exact range of 256
  (:mod:`qba_tpu.analysis.dots`).
* ``KI-5`` — a donation/aliasing claim that does not hold: a scan
  carry that round-trips through a fresh HBM allocation, a
  ``pallas_call`` whose ``input_output_aliases`` are inconsistent or
  missing on a state-shaped operand, or a top-level jit whose
  ``donate_argnums`` claim is unsound
  (:mod:`qba_tpu.analysis.effects`).
* ``KI-6`` — an implicit device→host transfer on a hot module outside
  a ``fenced`` telemetry span and without a ``qba-lint: sync-ok``
  annotation, or a violation of serve's double-buffer dispatch
  ordering (:mod:`qba_tpu.analysis.transfers`).
* ``KI-8`` — an uncertified rate in a run manifest: a bare numeric
  ``*_rate`` value with no accompanying confidence interval
  (:mod:`qba_tpu.analysis.manifests`, docs/STATS.md).
* ``KI-10`` — a file-queue protocol violation: a safety invariant
  (exactly-once settle, single executor, poison blast-radius bound,
  release-within-one-poll, no lost request) falsified by the bounded
  model check, an unregistered queue mutation in ``serve/``, or an
  admission-ledger purity break
  (:mod:`qba_tpu.analysis.protocol`).
* ``KI-11`` — an incomplete atlas campaign: an enumerated cube cell
  with neither a certified store record meeting its target nor an
  explicit refusal/truncation finding, a record/ledger/content-address
  disagreement, or a slice whose frontier CI widths exceed the
  interior's (:mod:`qba_tpu.analysis.atlas`, docs/ATLAS.md).
* ``KI-12`` — dark time in the observability plane: a trace id minted
  outside the registered frontend mint sites (a mid-request re-mint
  orphans every span under it), an emission of an unregistered metric
  name, a queue hop that drops trace context, or request span coverage
  below the floor (:mod:`qba_tpu.analysis.obs`,
  docs/OBSERVABILITY.md).

A *note* is an informational line the report carries alongside the
findings (plan predictions, probe-counter reality checks) — notes
never fail the lint gate; findings always do.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable

KI_TAGS = (
    "KI-1", "KI-2", "KI-3", "KI-5", "KI-6", "KI-8", "KI-10", "KI-11",
    "KI-12",
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violated invariant."""

    ki: str  # one of KI_TAGS
    check: str  # pass name, e.g. "exact-dot", "vma-threading"
    path: str  # traced build path, e.g. "pallas_tiled/rebuild"
    message: str  # human-readable statement of the violation
    where: str = ""  # source location "file:line" when recoverable

    def __post_init__(self) -> None:
        if self.ki not in KI_TAGS:
            raise ValueError(f"unknown KI tag {self.ki!r}")

    def render(self) -> str:
        loc = f" [{self.where}]" if self.where else ""
        return f"{self.ki} {self.check} ({self.path}){loc}: {self.message}"


@dataclasses.dataclass
class Report:
    """Aggregated lint result: findings fail the gate, notes inform."""

    findings: list[Finding] = dataclasses.field(default_factory=list)
    notes: list[str] = dataclasses.field(default_factory=list)
    stats: dict = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings

    def extend(self, other: "Report") -> None:
        self.findings.extend(other.findings)
        self.notes.extend(other.notes)
        for k, v in other.stats.items():
            if isinstance(v, (int, float)) and k in self.stats:
                self.stats[k] += v
            elif isinstance(v, (set, frozenset)):
                self.stats[k] = set(self.stats.get(k, set())) | set(v)
            else:
                self.stats[k] = v

    def add(self, findings: Iterable[Finding]) -> None:
        self.findings.extend(findings)

    def render(self, verbose: bool = False) -> str:
        lines: list[str] = []
        for f in self.findings:
            lines.append("FINDING " + f.render())
        if verbose or not self.findings:
            for n in self.notes:
                lines.append("note: " + n)
        unhandled = self.stats.get("unhandled_primitives")
        if unhandled:
            lines.append(
                "note: interval analysis skipped unmodeled primitives "
                f"(treated as unknown/non-integer): {sorted(unhandled)}"
            )
        lines.append(
            f"{len(self.findings)} finding(s), {len(self.notes)} note(s)"
        )
        return "\n".join(lines)
