"""KI-6 host-sync discipline audit.

On an async-dispatch backend, every implicit device→host transfer —
``np.asarray`` on a device value, ``.item()``, ``bool()``/``float()``
of a traced result, a bare ``block_until_ready`` — is a silent
pipeline stall: it blocks the host until the device drains, and if it
happens *between* a chunk's dispatch and the next chunk's enqueue it
serializes the double buffer the serving and sweep paths are built
around (docs/PERF.md readback-barrier methodology, docs/SERVING.md).
The discipline the tree lives by is: a host sync is legal only

* inside a telemetry span whose body marks ``<span>.fenced = True`` —
  the span *is* the readback barrier and the telemetry attributes the
  stall to the device (``qba_tpu/obs/telemetry.py``); or
* annotated ``# qba-lint: sync-ok (reason)`` at the call site — for
  host-side numpy on data that was never on the device (key
  derivation at intake, wire decoding).

This pass mechanizes it three ways:

* **AST sweep** over the hot modules (``rounds/``, ``ops/``,
  ``serve/``, ``serve/fleet/``, ``sweep.py``, ``benchmark.py``): every
  sync-shaped call
  site must be fenced or annotated.  ``jnp.asarray`` is device-side
  and never flagged.  Zero sites found across the serve/sweep
  pipelines is itself a finding — the audit no longer matches the
  module layout.
* **Dispatch-order proof** over ``QBAServer._dispatch``: statically,
  chunk k+1's ``_in_flight.append`` precedes any drain/sync in the
  method (so chunk k's readback never forces a sync before the next
  dispatch is enqueued), the drain loop is bounded by ``self.depth``,
  the ``serve.dispatch`` span stays enqueue-only (no sync, never
  fenced), and ``_drain_one`` pops FIFO (``pop(0)``) so readback
  order matches dispatch order.
* **Fleet front-half proof** (:func:`check_fleet`): the socket
  front-end never imports jax, no fleet front-half module calls a
  device entry point, and the replica pool spawns the stock
  ``serve --transport file-queue`` loop — so multi-replica dispatch
  ordering inherits the double-buffer proof unchanged.
* **Jaxpr sweep** over the traced build paths: callback primitives
  (``pure_callback`` / ``io_callback`` / ``debug_callback``) inside a
  hot jitted program are implicit host round-trips per grid step and
  are flagged.

Findings are tagged ``KI-6`` (docs/KNOWN_ISSUES.md).
"""

from __future__ import annotations

import ast
import os

from qba_tpu.analysis.effects import annotation_at, iter_eqns
from qba_tpu.analysis.findings import Finding, Report

#: Call-site marker demoting a host-sync finding to a note carrying
#: the justification (docs/ANALYSIS.md annotation grammar).
SYNC_ALLOW_MARKER = "qba-lint: sync-ok"

#: Jaxpr-level primitives that round-trip to the host from inside a
#: jitted program.
_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
})

#: Host-numpy module aliases whose ``asarray``/``array`` force a
#: device readback when fed a device value.
_HOST_NP_NAMES = ("np", "numpy", "onp")


def hot_module_paths(root: str | None = None) -> list[str]:
    """The audited surface: the modules on the dispatch/readback hot
    path.  ``rounds/`` and ``ops/`` are in scope even though today
    they only use device-side ``jnp`` — a future ``np`` leak there
    would sync once per *trace*, the worst place possible."""
    if root is None:
        import qba_tpu

        root = os.path.dirname(qba_tpu.__file__)
    paths: list[str] = []
    for sub in ("rounds", "ops", "serve", os.path.join("serve", "fleet")):
        d = os.path.join(root, sub)
        for fname in sorted(os.listdir(d)):
            if fname.endswith(".py"):
                paths.append(os.path.join(d, fname))
    for fname in ("sweep.py", "benchmark.py"):
        paths.append(os.path.join(root, fname))
    return paths


# ---------------------------------------------------------------------------
# Sync-site detection.


def _contains_traced_call(node) -> bool:
    """True if ``node``'s subtree references ``jnp.*`` / ``jax.*`` —
    the cast argument is (or contains) a device value."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and isinstance(
            sub.value, ast.Name
        ) and sub.value.id in ("jnp", "jax"):
            return True
    return False


def _sync_kind(call: ast.Call) -> str | None:
    """Classify ``call`` as a device→host sync site, or None."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        if (
            isinstance(fn.value, ast.Name)
            and fn.value.id in _HOST_NP_NAMES
            and fn.attr in ("asarray", "array")
        ):
            return f"{fn.value.id}.{fn.attr}"
        if fn.attr == "item" and not call.args and not call.keywords:
            return ".item()"
        if fn.attr == "block_until_ready":
            return ".block_until_ready()"
        if (
            fn.attr == "device_get"
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "jax"
        ):
            return "jax.device_get"
    elif isinstance(fn, ast.Name) and fn.id in ("bool", "int", "float"):
        if len(call.args) == 1 and _contains_traced_call(call.args[0]):
            return f"{fn.id}() on a traced value"
    return None


class _SyncVisitor(ast.NodeVisitor):
    """Collects sync sites with their enclosing-``with`` fence state."""

    def __init__(self):
        self.with_stack: list[bool] = []
        self.sites: list[tuple[ast.Call, str, bool]] = []

    @staticmethod
    def _is_fencing_with(node: ast.With) -> bool:
        spanlike = any(
            isinstance(item.context_expr, ast.Call)
            and isinstance(item.context_expr.func, ast.Attribute)
            and item.context_expr.func.attr in ("span", "time")
            for item in node.items
        )
        if not spanlike:
            return False
        for stmt in ast.walk(ast.Module(body=node.body, type_ignores=[])):
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Attribute)
                and stmt.targets[0].attr == "fenced"
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is True
            ):
                return True
        return False

    def visit_With(self, node: ast.With) -> None:
        self.with_stack.append(self._is_fencing_with(node))
        self.generic_visit(node)
        self.with_stack.pop()

    def visit_Call(self, node: ast.Call) -> None:
        kind = _sync_kind(node)
        if kind is not None:
            self.sites.append((node, kind, any(self.with_stack)))
        self.generic_visit(node)


def audit_module(source_path: str, report: Report, stats: dict) -> None:
    """KI-6 AST sweep over one module."""
    with open(source_path) as fh:
        tree = ast.parse(fh.read(), filename=source_path)
    rel = os.path.basename(source_path)
    visitor = _SyncVisitor()
    visitor.visit(tree)
    for call, kind, fenced in visitor.sites:
        stats["sync_sites_checked"] += 1
        where = f"{source_path}:{call.lineno}"
        if fenced:
            stats["sync_sites_fenced"] += 1
            continue
        justification = annotation_at(where, SYNC_ALLOW_MARKER)
        if justification is not None:
            stats["sync_sites_allowlisted"] += 1
            report.notes.append(
                f"transfers: allowlisted host-sync ({kind}) at "
                f"{rel}:{call.lineno}: {justification}"
            )
            continue
        report.findings.append(Finding(
            ki="KI-6", check="host-sync", path=f"module:{rel}",
            where=where,
            message=(
                f"{kind} outside a fenced telemetry span: an implicit "
                "device→host transfer stalls async dispatch "
                "unattributed — wrap it in a span that sets "
                "`<span>.fenced = True`, or annotate "
                f"'# {SYNC_ALLOW_MARKER} (reason)' if the data never "
                "lives on the device"
            ),
        ))


# ---------------------------------------------------------------------------
# Serve dispatch-order proof.


def _calls_named(node, name: str):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = sub.func
            if (isinstance(fn, ast.Attribute) and fn.attr == name) or (
                isinstance(fn, ast.Name) and fn.id == name
            ):
                yield sub


def _stmt_has_sync(stmt) -> bool:
    return any(
        isinstance(sub, ast.Call) and _sync_kind(sub) is not None
        for sub in ast.walk(stmt)
    )


def _find_method(tree, cls_name: str, meth_name: str):
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            for item in node.body:
                if (
                    isinstance(item, ast.FunctionDef)
                    and item.name == meth_name
                ):
                    return item
    return None


def check_serve_dispatch(source_path: str | None = None) -> Report:
    """Statically prove serve's double-buffer invariant on
    ``QBAServer._dispatch`` / ``_drain_one`` (docs/SERVING.md): chunk
    k's readback never forces a sync before chunk k+1's dispatch is
    enqueued."""
    report = Report()
    if source_path is None:
        import qba_tpu.serve.engine as serve_engine

        source_path = serve_engine.__file__
    rel = os.path.basename(source_path)
    path = f"serve:{rel}"
    with open(source_path) as fh:
        tree = ast.parse(fh.read(), filename=source_path)

    dispatch = _find_method(tree, "QBAServer", "_dispatch")
    drain = _find_method(tree, "QBAServer", "_drain_one")
    if dispatch is None or drain is None:
        report.findings.append(Finding(
            ki="KI-6", check="dispatch-order", path=path,
            message=(
                "QBAServer._dispatch/_drain_one not found — the "
                "double-buffer proof no longer matches the module "
                "layout"
            ),
        ))
        return report

    # 1. Statement order inside _dispatch: the in-flight append (the
    #    enqueue of chunk k+1) must precede any drain or sync.
    append_at = drain_at = sync_at = None
    for i, stmt in enumerate(dispatch.body):
        if append_at is None:
            for call in _calls_named(stmt, "append"):
                fn = call.func
                if (
                    isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Attribute)
                    and fn.value.attr == "_in_flight"
                ):
                    append_at = i
                    break
        if drain_at is None and any(_calls_named(stmt, "_drain_one")):
            drain_at = i
        if sync_at is None and _stmt_has_sync(stmt):
            sync_at = i
    if append_at is None:
        report.findings.append(Finding(
            ki="KI-6", check="dispatch-order", path=path,
            where=f"{source_path}:{dispatch.lineno}",
            message=(
                "_dispatch never appends to _in_flight — the "
                "double-buffer proof no longer matches the code"
            ),
        ))
    else:
        for label, at in (("a drain", drain_at), ("a host sync", sync_at)):
            if at is not None and at < append_at:
                report.findings.append(Finding(
                    ki="KI-6", check="dispatch-order", path=path,
                    where=f"{source_path}:{dispatch.body[at].lineno}",
                    message=(
                        f"_dispatch performs {label} before enqueuing "
                        "the chunk on _in_flight: chunk k's readback "
                        "would block before chunk k+1's dispatch is "
                        "enqueued, serializing the double buffer"
                    ),
                ))

    # 2. The drain loop must be bounded by the configured depth —
    #    an unconditional drain degenerates to depth-1 (no overlap).
    depth_bounded = False
    for stmt in ast.walk(dispatch):
        if isinstance(stmt, ast.While) and any(
            _calls_named(stmt, "_drain_one")
        ):
            depth_bounded = any(
                isinstance(sub, ast.Attribute) and sub.attr == "depth"
                for sub in ast.walk(stmt.test)
            )
    if append_at is not None and not depth_bounded:
        report.findings.append(Finding(
            ki="KI-6", check="dispatch-order", path=path,
            where=f"{source_path}:{dispatch.lineno}",
            message=(
                "_dispatch's drain loop is not bounded by self.depth: "
                "the in-flight window no longer matches the "
                "configured double-buffer depth"
            ),
        ))

    # 3. The dispatch span must stay enqueue-only: fencing it (or
    #    syncing inside it) would time the device, not the enqueue,
    #    and stall the pipeline inside the dispatch phase.
    for node in ast.walk(dispatch):
        if not isinstance(node, ast.With):
            continue
        names = [
            item.context_expr.args[0].value
            for item in node.items
            if isinstance(item.context_expr, ast.Call)
            and isinstance(item.context_expr.func, ast.Attribute)
            and item.context_expr.func.attr == "span"
            and item.context_expr.args
            and isinstance(item.context_expr.args[0], ast.Constant)
        ]
        if "serve.dispatch" not in names:
            continue
        fenced = _SyncVisitor._is_fencing_with(node)
        synced = any(_stmt_has_sync(s) for s in node.body)
        if fenced or synced:
            report.findings.append(Finding(
                ki="KI-6", check="dispatch-order", path=path,
                where=f"{source_path}:{node.lineno}",
                message=(
                    "the serve.dispatch span must stay enqueue-only "
                    "(no host sync, never fenced) — it measures the "
                    "async enqueue, and a sync here serializes "
                    "dispatch against the previous chunk's compute"
                ),
            ))

    # 4. FIFO drain: _drain_one must pop the OLDEST chunk so chunk k
    #    is read back before chunk k+1.
    fifo = any(
        isinstance(call.func, ast.Attribute)
        and call.func.attr == "pop"
        and call.args
        and isinstance(call.args[0], ast.Constant)
        and call.args[0].value == 0
        for call in _calls_named(drain, "pop")
    )
    if not fifo:
        report.findings.append(Finding(
            ki="KI-6", check="dispatch-order", path=path,
            where=f"{source_path}:{drain.lineno}",
            message=(
                "_drain_one does not pop(0) from _in_flight: readback "
                "order would diverge from dispatch order and the "
                "oldest chunk's results could wait behind newer ones"
            ),
        ))
    report.stats["dispatch_proof_obligations"] = 4
    return report


# ---------------------------------------------------------------------------
# Fleet front-half proof.

#: Call names that enter the device path; none may appear in the fleet
#: front half (frontend/pool/admission/summary) — replicas, and only
#: replicas, touch devices.
_DEVICE_ENTRY_NAMES = frozenset({
    "run_trials", "trial_keys", "pallas_call", "device_put",
    "wrap_key_data", "block_until_ready", "serve_batch",
})


def _fleet_dir() -> str:
    import qba_tpu

    return os.path.join(os.path.dirname(qba_tpu.__file__), "serve", "fleet")


def check_fleet(fleet_dir: str | None = None) -> Report:
    """Statically prove the fleet front half does no device work
    (docs/SERVING.md "Fleet"): the asyncio front-end and pool manager
    move JSON between sockets and the file queue, and every device
    byte flows through the replicas' serve loops — whose dispatch
    ordering :func:`check_serve_dispatch` already proves.

    Four obligations:

    1. ``frontend.py`` and ``supervisor.py`` never import jax/jaxlib
       at all — not even lazily — so neither the listener nor the
       self-healing loop can ever trigger a device→host transfer
       (their sync discipline is vacuously clean).
    2. No fleet module calls a device entry point
       (``run_trials`` / ``pallas_call`` / ``serve_batch`` / ...):
       the front half has no dispatch path of its own.
    3. ``ReplicaPool.worker_argv`` spawns the stock
       ``serve --transport file-queue`` loop (the ``"serve"`` and
       ``"file-queue"`` argv constants are present), so pool dispatch
       ordering inherits the double-buffer proof unchanged.
    4. Heartbeat writes stay on the worker side of the KI-6 fence: no
       fleet module constructs a ``HeartbeatWriter`` or calls
       ``.beat()`` (the supervisor only ever *reads* heartbeats),
       while the worker-side transport loop does construct one — the
       observation channel exists and flows one way.
    """
    report = Report()
    fleet_dir = fleet_dir if fleet_dir is not None else _fleet_dir()
    if not os.path.isdir(fleet_dir):
        report.findings.append(Finding(
            ki="KI-6", check="fleet-front", path="fleet:*",
            message=(
                "serve/fleet/ not found — the fleet front-half proof "
                "no longer matches the module layout"
            ),
        ))
        return report

    modules_checked = 0
    for fname in sorted(os.listdir(fleet_dir)):
        if not fname.endswith(".py"):
            continue
        modules_checked += 1
        path = os.path.join(fleet_dir, fname)
        with open(path) as fh:
            tree = ast.parse(fh.read(), filename=path)
        # Obligation 1: neither the frontend nor the supervisor ever
        # imports jax, even lazily.
        if fname in ("frontend.py", "supervisor.py"):
            for node in ast.walk(tree):
                mods = []
                if isinstance(node, ast.Import):
                    mods = [a.name for a in node.names]
                elif isinstance(node, ast.ImportFrom) and node.module:
                    mods = [node.module]
                for mod in mods:
                    top = mod.split(".")[0]
                    if top in ("jax", "jaxlib"):
                        report.findings.append(Finding(
                            ki="KI-6", check="fleet-front",
                            path=f"fleet:{fname}",
                            where=f"{path}:{node.lineno}",
                            message=(
                                f"{fname} imports {mod}: the fleet "
                                "front half must stay jax-free so it "
                                "can never perform a device→host "
                                "transfer"
                            ),
                        ))
        # Obligation 4a: heartbeats flow worker -> supervisor only.  A
        # fleet module writing one would forge the very evidence the
        # watchdog and blame attribution rest on.
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (
                fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else None
            )
            if name in ("HeartbeatWriter", "beat"):
                report.findings.append(Finding(
                    ki="KI-6", check="fleet-front", path=f"fleet:{fname}",
                    where=f"{path}:{node.lineno}",
                    message=(
                        f"fleet front-half module calls {name}(): "
                        "heartbeats are written by workers and only "
                        "read here — a front-half write would forge "
                        "the watchdog's evidence"
                    ),
                ))
        # Obligation 2: no device entry points anywhere in the front
        # half.
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (
                fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else None
            )
            if name in _DEVICE_ENTRY_NAMES:
                report.findings.append(Finding(
                    ki="KI-6", check="fleet-front", path=f"fleet:{fname}",
                    where=f"{path}:{node.lineno}",
                    message=(
                        f"fleet front-half module calls {name}(): "
                        "device work belongs in the replicas' serve "
                        "loops, which the dispatch-order proof covers "
                        "— the front half must stay dispatch-free"
                    ),
                ))

    # Obligation 3: workers run the proven serve loop.
    pool_path = os.path.join(fleet_dir, "pool.py")
    ok_argv = False
    if os.path.isfile(pool_path):
        with open(pool_path) as fh:
            pool_tree = ast.parse(fh.read(), filename=pool_path)
        argv_fn = _find_method(pool_tree, "ReplicaPool", "worker_argv")
        if argv_fn is not None:
            consts = {
                n.value
                for n in ast.walk(argv_fn)
                if isinstance(n, ast.Constant) and isinstance(n.value, str)
            }
            ok_argv = {"serve", "file-queue", "--transport"} <= consts
    if not ok_argv:
        report.findings.append(Finding(
            ki="KI-6", check="fleet-front", path="fleet:pool.py",
            where=pool_path,
            message=(
                "ReplicaPool.worker_argv does not spawn "
                "'serve --transport file-queue': pool dispatch "
                "ordering no longer inherits the serve double-buffer "
                "proof"
            ),
        ))
    # Obligation 4b: the worker-side transport loop actually writes
    # heartbeats (constructs a HeartbeatWriter) — without it the
    # supervisor would watchdog against a channel nobody feeds.
    transport_path = os.path.join(
        os.path.dirname(fleet_dir), "transport.py"
    )
    writes_heartbeat = False
    if os.path.isfile(transport_path):
        with open(transport_path) as fh:
            transport_tree = ast.parse(fh.read(), filename=transport_path)
        writes_heartbeat = any(
            isinstance(node, ast.Call)
            and (
                (isinstance(node.func, ast.Name)
                 and node.func.id == "HeartbeatWriter")
                or (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "HeartbeatWriter")
            )
            for node in ast.walk(transport_tree)
        )
    if not writes_heartbeat:
        report.findings.append(Finding(
            ki="KI-6", check="fleet-front", path="fleet:transport.py",
            where=transport_path,
            message=(
                "serve/transport.py constructs no HeartbeatWriter: "
                "workers have stopped feeding the supervisor's "
                "observation channel — hung workers become "
                "undetectable"
            ),
        ))
    report.stats["fleet_modules_checked"] = modules_checked
    report.stats["fleet_proof_obligations"] = 4
    return report


# ---------------------------------------------------------------------------
# Jaxpr half: host callbacks inside traced programs.


def check_jaxpr_transfers(paths) -> Report:
    """Flag host-callback primitives inside the traced build paths —
    each one is a device→host round trip per invocation, inside code
    that runs once per round per trial."""
    from qba_tpu.analysis.intervals import source_location

    report = Report()
    scanned = 0
    for p in paths:
        for eqn in iter_eqns(p.closed_jaxpr.jaxpr):
            scanned += 1
            if eqn.primitive.name in _CALLBACK_PRIMS:
                where = source_location(eqn)
                justification = (
                    annotation_at(where, SYNC_ALLOW_MARKER)
                    if where else None
                )
                if justification is not None:
                    report.notes.append(
                        f"transfers: allowlisted host callback "
                        f"({eqn.primitive.name}) at {where}: "
                        f"{justification}"
                    )
                    continue
                report.findings.append(Finding(
                    ki="KI-6", check="host-callback", path=p.name,
                    where=where,
                    message=(
                        f"{eqn.primitive.name} inside a hot traced "
                        "program: a host round trip per invocation "
                        "on the round path"
                    ),
                ))
    report.stats["jaxpr_eqns_scanned"] = scanned
    return report


# ---------------------------------------------------------------------------
# Device-resident loop proof (ROADMAP item 3): per-chunk readbacks
# ELIMINATED, not merely fenced.

#: Infeed/outfeed primitives — a host channel inside the loop would be
#: a per-iteration transfer the AST sweep cannot see.
_FEED_PRIMS = frozenset({"infeed", "outfeed"})


def audit_device_loop(closed_jaxpr, path: str) -> Report:
    """Prove a device-resident sequential program has no per-chunk
    host round trip — the ``check_device_loop`` obligations, exposed
    separately so the seeded bad fixture can exercise them
    (tests/analysis_fixtures/bad_device_loop.py):

    1. exactly ONE ``while`` primitive — the stopping predicate is the
       loop condition, not a host-consulted rule between dispatches;
    2. ZERO host-callback primitives and zero infeed/outfeed anywhere
       in the traced program (the loop body especially): the host-loop
       path's per-chunk fenced readback has no device-loop analogue to
       fence — it must not exist at all;
    3. the while body actually carries the engine program (a round
       ``scan`` or a ``pallas_call``) — an empty loop would "pass" the
       transfer obligations while computing nothing.
    """
    report = Report()
    jaxpr = (
        closed_jaxpr.jaxpr
        if hasattr(closed_jaxpr, "jaxpr")
        else closed_jaxpr
    )
    whiles = [
        e for e in iter_eqns(jaxpr) if e.primitive.name == "while"
    ]
    callbacks = [
        e for e in iter_eqns(jaxpr)
        if e.primitive.name in _CALLBACK_PRIMS
        or e.primitive.name in _FEED_PRIMS
    ]
    if len(whiles) != 1:
        report.findings.append(Finding(
            ki="KI-6", check="device-loop", path=path,
            message=(
                f"device-resident program contains {len(whiles)} "
                "while_loop(s), expected exactly 1 — the stopping "
                "predicate is no longer the loop condition of a single "
                "on-device loop"
            ),
        ))
    for eqn in callbacks:
        from qba_tpu.analysis.intervals import source_location

        report.findings.append(Finding(
            ki="KI-6", check="device-loop", path=path,
            where=source_location(eqn),
            message=(
                f"{eqn.primitive.name} inside the device-resident "
                "program: a host round trip per loop iteration — the "
                "single-dispatch contract requires the loop body to be "
                "transfer-free, not transfer-fenced"
            ),
        ))
    body_engine = False
    if len(whiles) == 1:
        body = whiles[0].params.get("body_jaxpr")
        body = body.jaxpr if hasattr(body, "jaxpr") else body
        body_eqns = list(iter_eqns(body)) if body is not None else []
        body_engine = any(
            e.primitive.name in ("scan", "pallas_call") for e in body_eqns
        )
        if not body_engine:
            report.findings.append(Finding(
                ki="KI-6", check="device-loop", path=path,
                message=(
                    "the device loop body contains no round scan and no "
                    "pallas_call — the engine program is not inside the "
                    "loop, so the \"single dispatch\" computes nothing"
                ),
            ))
    if len(whiles) == 1 and not callbacks and body_engine:
        report.notes.append(
            f"transfers/device-loop [{path}]: per-chunk readback PROVEN "
            "eliminated — 1 while_loop with the engine program in its "
            "body, 0 host callbacks, 0 infeed/outfeed in the traced "
            "targeted run"
        )
    report.stats["device_loop_obligations"] = 3
    return report


def check_device_loop(cfg=None) -> Report:
    """Trace the shipped device-resident targeted loop
    (``qba_tpu.sweep._device_loop_foldin``) and run the
    :func:`audit_device_loop` obligations over its jaxpr.  Like
    ``effects._audit_mega`` this is a positive proof: the lint FAILS if
    the loop cannot be traced, rather than silently skipping the
    obligation."""
    import jax
    import jax.numpy as jnp

    from qba_tpu.config import QBAConfig

    report = Report()
    if cfg is None:
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=1)
    n_chunks, chunk_trials = 4, 8
    try:
        from qba_tpu.sweep import _device_carry, _device_loop_foldin

        carry = _device_carry(n_chunks, 0, 0)
        lo = jnp.full(n_chunks + 1, -1, jnp.int32)
        hi = jnp.full(n_chunks + 1, n_chunks * chunk_trials + 1, jnp.int32)
        fn = _device_loop_foldin.__wrapped__
        closed = jax.make_jaxpr(
            lambda c, lo_, hi_: fn(cfg, n_chunks, chunk_trials, c, lo_, hi_)
        )(carry, lo, hi)
    except Exception as exc:
        report.findings.append(Finding(
            ki="KI-6", check="device-loop",
            path="sweep/_device_loop_foldin",
            message=(
                f"device loop trace failed ({type(exc).__name__}: {exc})"
                " — the single-dispatch proof no longer matches the "
                "module layout"
            ),
        ))
        return report
    report.extend(audit_device_loop(closed, "sweep/_device_loop_foldin"))
    return report


# ---------------------------------------------------------------------------
# Entry point.


def check_transfers(module_paths=None) -> Report:
    """Run the sitewide KI-6 audit: the AST sweep over every hot
    module plus the serve dispatch-order proof."""
    report = Report()
    stats = {
        "sync_sites_checked": 0,
        "sync_sites_fenced": 0,
        "sync_sites_allowlisted": 0,
    }
    for path in module_paths or hot_module_paths():
        audit_module(path, report, stats)
    if module_paths is None and stats["sync_sites_checked"] == 0:
        report.findings.append(Finding(
            ki="KI-6", check="host-sync", path="module:*",
            message=(
                "found zero host-sync sites across the hot modules — "
                "the serve/sweep readback pipelines always sync "
                "somewhere, so the audit no longer matches the module "
                "layout"
            ),
        ))
    report.stats.update(stats)
    report.extend(check_serve_dispatch())
    report.extend(check_fleet())
    return report
