"""KI-1 vma-threading pass.

KNOWN_ISSUES KI-1 records the round-4 regression this pass
mechanizes: the party-sharded kernel builders *accepted* an
``out_vma`` argument but hard-coded ``None`` into their
``ShapeDtypeStruct`` s, so shard_map's replication checker either
rejected every sharded build or ran with dead declarations — and
nothing caught it because the machinery failed silent.  Three checks:

1. **Builder threading (dynamic).**  Monkeypatch the two vma plumbing
   helpers (:func:`qba_tpu.ops.round_kernel.vma_struct` /
   ``promote_vma``) with recorders that behave like the checker-off
   path (so the build traces on any backend), build every sharded
   builder through the same call paths :mod:`qba_tpu.analysis.traces`
   uses with a sentinel ``out_vma``, and require the sentinel to reach
   *both* helpers.  A builder that drops, shadows, or defaults its
   ``out_vma`` reverts to the round-4 bug and fails here.

2. **Call-site audit (static AST).**  Every call to a kernel builder
   in ``qba_tpu/parallel/spmd.py`` must pass an ``out_vma=`` keyword
   whose value is not the literal ``None`` — re-introducing
   ``out_vma=None`` at a sharded call site is the exact KI-1 revert.

3. **Policy audit.**  ``check_vma`` resolution must keep its contract:
   ON for every engine on real TPU, OFF in kernel interpret mode,
   ``QBA_TILED_CHECK_VMA=1``/``0`` forcing either way and any other
   value failing loudly (:func:`qba_tpu.parallel.spmd._tiled_check_vma`
   / ``_resolve_check_vma``).
"""

from __future__ import annotations

import ast
import os

import jax
import jax.numpy as jnp

from qba_tpu.analysis.findings import Finding, Report
from qba_tpu.config import QBAConfig

#: Builders that take part in sharded builds and must thread out_vma.
BUILDER_NAMES = (
    "build_round_step",
    "build_verdict_kernel",
    "build_rebuild_kernel",
    "build_fused_round_kernel",
    "build_ring_gather",
)

_SENTINEL = frozenset({"__qba_lint_axis__"})


def _check_builder_threading(cfg: QBAConfig) -> Report:
    """Check 1: a sentinel ``out_vma`` injected at each builder must
    reach both vma plumbing helpers during the build."""
    import qba_tpu.ops.round_kernel as rk
    from qba_tpu.analysis import traces

    report = Report()
    seen: dict[str, list] = {"vma_struct": [], "promote_vma": []}
    orig_struct, orig_promote = rk.vma_struct, rk.promote_vma

    def rec_struct(out_vma, dims, dt=jnp.int32):
        seen["vma_struct"].append(out_vma)
        return jax.ShapeDtypeStruct(dims, dt)

    def rec_promote(out_vma, x):
        seen["promote_vma"].append(out_vma)
        return x

    n_local = cfg.n_lieutenants // 2
    builds = [
        ("spmd/pallas/round_step",
         lambda: traces.trace_pallas(cfg, n_recv=n_local, out_vma=_SENTINEL)),
        ("spmd/pallas_tiled",
         lambda: traces.trace_tiled(cfg, n_recv=n_local, out_vma=_SENTINEL)),
        ("spmd/pallas_fused",
         lambda: traces.trace_fused(cfg, n_recv=n_local, out_vma=_SENTINEL)),
    ]
    rk.vma_struct, rk.promote_vma = rec_struct, rec_promote
    try:
        for path, build in builds:
            seen["vma_struct"].clear()
            seen["promote_vma"].clear()
            build()
            for helper, calls in seen.items():
                if not calls:
                    report.findings.append(Finding(
                        ki="KI-1", check="vma-threading", path=path,
                        message=(
                            f"builder never called {helper}() during a "
                            "sharded build: the output-vma declaration "
                            "machinery is disconnected (round-4 "
                            "regression shape)"
                        ),
                    ))
                elif _SENTINEL not in calls:
                    got = sorted({repr(c) for c in calls})
                    report.findings.append(Finding(
                        ki="KI-1", check="vma-threading", path=path,
                        message=(
                            f"out_vma passed to the builder never reached "
                            f"{helper}() (saw {got}): the declaration is "
                            "dropped or shadowed on the way to pallas_call "
                            "(round-4 regression: out_vma accepted but "
                            "hard-coded None)"
                        ),
                    ))
    finally:
        rk.vma_struct, rk.promote_vma = orig_struct, orig_promote
    report.stats["vma_builds_checked"] = len(builds)
    return report


def _iter_calls(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else getattr(
                fn, "attr", None
            )
            if name in BUILDER_NAMES:
                yield name, node


def check_spmd_call_sites(source_path: str | None = None) -> Report:
    """Check 2: AST audit of the builder call sites in spmd.py."""
    report = Report()
    if source_path is None:
        import qba_tpu.parallel.spmd as spmd_mod

        source_path = spmd_mod.__file__
    with open(source_path, encoding="utf-8") as fh:
        src = fh.read()
    tree = ast.parse(src, filename=source_path)
    n_sites = 0
    for name, call in _iter_calls(tree):
        n_sites += 1
        where = f"{source_path}:{call.lineno}"
        kw = next((k for k in call.keywords if k.arg == "out_vma"), None)
        if kw is None:
            report.findings.append(Finding(
                ki="KI-1", check="vma-call-site", path="parallel/spmd",
                where=where,
                message=(
                    f"{name}(...) called without an out_vma= keyword: the "
                    "sharded build silently loses its output-vma "
                    "declaration"
                ),
            ))
        elif isinstance(kw.value, ast.Constant) and kw.value.value is None:
            report.findings.append(Finding(
                ki="KI-1", check="vma-call-site", path="parallel/spmd",
                where=where,
                message=(
                    f"{name}(..., out_vma=None) hard-codes the declaration "
                    "off — the literal round-4 KI-1 bug; thread the mesh "
                    "axes (vma_axes / tiled_out_vma) instead"
                ),
            ))
    if n_sites == 0:
        report.findings.append(Finding(
            ki="KI-1", check="vma-call-site", path="parallel/spmd",
            where=source_path,
            message=(
                "no kernel-builder call sites found in spmd.py — the AST "
                "audit no longer matches the module layout; update "
                "qba_tpu/analysis/vma.py"
            ),
        ))
    report.stats["vma_call_sites_checked"] = n_sites
    return report


def _check_policy() -> Report:
    """Check 3: the check_vma resolution contract."""
    from qba_tpu.parallel.spmd import _resolve_check_vma, _tiled_check_vma

    report = Report()
    on_tpu = jax.default_backend() == "tpu"
    saved = os.environ.get("QBA_TILED_CHECK_VMA")

    def expect(desc: str, got, want) -> None:
        if got != want:
            report.findings.append(Finding(
                ki="KI-1", check="vma-policy", path="parallel/spmd",
                message=f"{desc}: resolved {got!r}, policy requires {want!r}",
            ))

    try:
        os.environ.pop("QBA_TILED_CHECK_VMA", None)
        expect("QBA_TILED_CHECK_VMA unset (default = on iff real TPU)",
               _tiled_check_vma(), on_tpu)
        for engine in ("pallas_tiled", "pallas_fused"):
            expect(f"_resolve_check_vma({engine!r}) default",
                   _resolve_check_vma(engine), on_tpu)
        expect("_resolve_check_vma('pallas') (on iff real TPU)",
               _resolve_check_vma("pallas"), on_tpu)
        expect("_resolve_check_vma('xla') (always on: plain shard_map body)",
               _resolve_check_vma("xla"), True)

        os.environ["QBA_TILED_CHECK_VMA"] = "1"
        expect("QBA_TILED_CHECK_VMA=1 (force on)", _tiled_check_vma(), True)
        os.environ["QBA_TILED_CHECK_VMA"] = "0"
        expect("QBA_TILED_CHECK_VMA=0 (force off)", _tiled_check_vma(), False)

        os.environ["QBA_TILED_CHECK_VMA"] = "maybe"
        try:
            got = _tiled_check_vma()
        except ValueError:
            pass
        else:
            report.findings.append(Finding(
                ki="KI-1", check="vma-policy", path="parallel/spmd",
                message=(
                    "QBA_TILED_CHECK_VMA='maybe' silently resolved to "
                    f"{got!r}; an escape hatch must fail loudly on junk "
                    "values (ValueError)"
                ),
            ))
    finally:
        if saved is None:
            os.environ.pop("QBA_TILED_CHECK_VMA", None)
        else:
            os.environ["QBA_TILED_CHECK_VMA"] = saved
    return report


def check_vma(cfg: QBAConfig, sitewide: bool = True) -> Report:
    """Run the KI-1 checks for one config.  The builder-threading check
    is config-shaped; the call-site and policy audits are not, so a
    matrix driver passes ``sitewide=False`` after the first config to
    avoid triplicated findings and inflated site counts."""
    report = Report()
    if cfg.n_lieutenants % 2 == 0:
        report.extend(_check_builder_threading(cfg))
    else:
        report.notes.append(
            f"vma-threading: n_lieutenants={cfg.n_lieutenants} has no "
            "2-way sharding; builder threading checked on another config"
        )
    if sitewide:
        report.extend(check_spmd_call_sites())
        report.extend(_check_policy())
    return report
