"""Explicit-state model-checker core for the KI-10 protocol pass.

A deliberately small bounded model checker in the ByMC mold
(PAPERS.md): a protocol is a set of named guarded actions over
hashable states; :func:`explore` runs breadth-first search from the
initial state, checks every safety invariant on every reachable
state (and the terminal-scoped ones on quiescent states), and — the
property ByMC makes a methodology — returns the *minimal* violating
schedule, because BFS reaches every state first along a shortest
path.

The core knows nothing about file queues; the fleet protocol model
lives in :mod:`qba_tpu.analysis.protocol`.  Keeping the search
generic means the seeded violation fixtures and the shipped tree run
through literally identical exploration code — only the transition
semantics differ.

States must be hashable and equality-comparable (the protocol model
uses nested ``namedtuple``s).  Actions are *pure*: they return
successor states and never mutate their argument, so the BFS parent
map stays consistent for schedule reconstruction.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Callable, Hashable, Iterable


@dataclasses.dataclass(frozen=True)
class Action:
    """One named guarded transition family.

    ``fire(state)`` yields ``(detail, next_state)`` pairs — one per
    enabled instantiation (e.g. ``claim`` yields one pair per
    (worker, request) whose guard holds).  ``detail`` is the
    human-readable instantiation ("w1 claims r0") used in printed
    counterexample schedules.
    """

    name: str
    fire: Callable[[Any], Iterable[tuple[str, Any]]]


@dataclasses.dataclass(frozen=True)
class Invariant:
    """One safety property.

    ``check(state, via)`` returns ``None`` when the state is fine or
    a violation message; ``via`` is the name of the action that
    produced the state (empty for the initial state) so post-action
    properties ("after a supervisor poll, no dead claim remains") can
    scope themselves.  ``terminal=True`` invariants run only on
    quiescent states (no action enabled) — liveness-flavored safety
    like "no request is lost on complete schedules".
    """

    name: str
    check: Callable[[Any, str], str | None]
    terminal: bool = False


@dataclasses.dataclass
class Violation:
    """A violated invariant plus its minimal witness schedule."""

    invariant: str
    message: str
    #: ``(action_name, detail)`` steps from the initial state.
    schedule: list[tuple[str, str]]

    @property
    def depth(self) -> int:
        return len(self.schedule)


@dataclasses.dataclass
class Exploration:
    """BFS result: the reached state space plus any violations."""

    states: int = 0
    transitions: int = 0
    diameter: int = 0  # depth of the deepest reached state
    terminal_states: int = 0
    truncated: bool = False  # hit max_states before exhausting
    halted: bool = False  # stopped at the first violation (opt-in)
    violations: list[Violation] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations


def explore(
    initial: Hashable,
    actions: Iterable[Action],
    invariants: Iterable[Invariant],
    *,
    max_states: int = 500_000,
    stop_on_violation: bool = False,
) -> Exploration:
    """Exhaustive BFS from ``initial``; first (= minimal-depth)
    violation per invariant is kept.  ``truncated`` reports a
    ``max_states`` cutoff — callers must treat a truncated clean run
    as *inconclusive*, not verified.

    ``stop_on_violation`` halts the search as soon as any invariant
    has a witness (``halted=True`` in the result).  BFS order makes
    that first witness minimal-depth regardless, so this is the
    classic stop-at-first-counterexample mode — right for seeded
    violation fixtures, where a buggy transition relation can blow
    the reachable space up orders of magnitude past the clean one's.
    A clean protocol never triggers it, so exhaustive verification
    claims are unaffected."""
    actions = list(actions)
    state_checks = [i for i in invariants if not i.terminal]
    terminal_checks = [i for i in invariants if i.terminal]

    result = Exploration()
    # state -> (parent_state, action_name, detail); initial maps to None.
    parents: dict[Hashable, tuple[Hashable, str, str] | None] = {
        initial: None
    }
    depth_of: dict[Hashable, int] = {initial: 0}
    queue: deque[Hashable] = deque([initial])
    violated: set[str] = set()

    def schedule_to(state: Hashable) -> list[tuple[str, str]]:
        steps: list[tuple[str, str]] = []
        cur = state
        while True:
            link = parents[cur]
            if link is None:
                break
            cur, name, detail = link
            steps.append((name, detail))
        steps.reverse()
        return steps

    def note_violation(inv: Invariant, msg: str, state: Hashable) -> None:
        if inv.name in violated:
            return  # BFS order: the first witness is already minimal
        violated.add(inv.name)
        result.violations.append(
            Violation(
                invariant=inv.name,
                message=msg,
                schedule=schedule_to(state),
            )
        )

    while queue:
        state = queue.popleft()
        depth = depth_of[state]
        result.states += 1
        result.diameter = max(result.diameter, depth)
        link = parents[state]
        via = link[1] if link is not None else ""

        for inv in state_checks:
            msg = inv.check(state, via)
            if msg is not None:
                note_violation(inv, msg, state)
        if stop_on_violation and result.violations:
            result.halted = True
            break

        fired = 0
        for action in actions:
            for detail, nxt in action.fire(state):
                fired += 1
                result.transitions += 1
                if nxt in parents:
                    continue
                if len(parents) >= max_states:
                    result.truncated = True
                    continue
                parents[nxt] = (state, action.name, detail)
                depth_of[nxt] = depth + 1
                queue.append(nxt)
        if fired == 0:
            result.terminal_states += 1
            for inv in terminal_checks:
                msg = inv.check(state, via)
                if msg is not None:
                    note_violation(inv, msg, state)
            if stop_on_violation and result.violations:
                result.halted = True
                break
    return result


def render_schedule(
    schedule: list[tuple[str, str]], *, indent: str = "  "
) -> str:
    """The printed minimal counterexample: one numbered line per step."""
    if not schedule:
        return f"{indent}(violated in the initial state)"
    width = len(str(len(schedule)))
    return "\n".join(
        f"{indent}{i + 1:>{width}}. {detail or name}"
        for i, (name, detail) in enumerate(schedule)
    )
