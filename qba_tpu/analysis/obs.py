"""KI-12: the "no dark time" observability-plane audit.

The fleet's tracing story (docs/OBSERVABILITY.md "Fleet tracing and
metrics") rests on three conventions that nothing at runtime enforces
per se — a request whose trace id is re-minted mid-flight still
*works*, its spans just become unattributable orphans; a metric
emitted under a free-hand name still renders, it just silently forks
the name table.  This pass makes the conventions load-bearing:

1. **Mint-site closure.**  ``mint_trace_id()`` may be called ONLY at
   the registered request-origin sites (:data:`MINT_SITES`): the
   frontend's ``_intake`` and the atlas campaign's ``_stamp_trace``.
   Everything downstream must *adopt* the id riding the queue file.
   The closure runs both ways, like KI-10's ``PROTOCOL_SITES``: an
   unregistered call site is a finding, and so is a registered site
   that has gone missing (the model and the code must move together).
2. **One metric name table.**  Every emitter call
   (``.inc``/``.set_gauge``/``.observe``) whose first argument is a
   string literal must name a key of
   :data:`qba_tpu.obs.metrics.METRICS`.  (Dynamic first arguments are
   the statistics rules' ``observe()`` — different protocol, exempt.)
3. **Trace-context propagation.**  The modules a request's identity
   must cross (request/engine/transport/frontend/supervisor/campaign)
   each have to reference ``trace_id``, and the engine's ``submit``
   must both adopt ``req.trace_id`` and stamp the ``t0_epoch``
   wall-clock anchor — without the anchor, spans can never be shifted
   onto the fleet's epoch axis and the whole worker segment goes dark.
4. **Coverage floor** (:func:`check_span_coverage`, needs a real run's
   queue dir): stitched request traces must attribute at least
   ``floor`` of their wall time to child spans, and the orphan-span
   count must be zero.

Seeded violation fixtures under ``tests/analysis_fixtures/`` prove the
checker bites (the CI fixture gate).
"""

from __future__ import annotations

import ast
import os

from qba_tpu.analysis.findings import Finding, Report
from qba_tpu.obs.metrics import METRICS

#: Registered trace-id mint sites: (path relative to the qba_tpu
#: package root, enclosing function).  Both-ways closure: a
#: ``mint_trace_id`` call anywhere else in the package is a finding,
#: and so is a registered site with no call left in it.
MINT_SITES = frozenset(
    {
        ("serve/fleet/frontend.py", "_intake"),
        ("atlas/campaign.py", "_stamp_trace"),
    }
)

#: The module that defines the minting helpers — its own code is not a
#: call site.
_MINT_HOME = "obs/tracing.py"

#: Metric emitter method names whose string-literal first argument must
#: be a registered metric name.
_EMITTERS = frozenset({"inc", "set_gauge", "observe"})

#: Modules a request's trace identity must cross.  Each must reference
#: ``trace_id`` somewhere (attribute, keyword, or literal) — a queue
#: hop that stops mentioning it has dropped the context.
PROPAGATING_MODULES = (
    "serve/request.py",
    "serve/engine.py",
    "serve/fleet/frontend.py",
    "serve/fleet/supervisor.py",
    "atlas/campaign.py",
)

#: Default stitched-trace coverage floor (the acceptance bar).
COVERAGE_FLOOR = 0.8


def _pkg_root() -> str:
    import qba_tpu

    return os.path.dirname(os.path.abspath(qba_tpu.__file__))


def _walk_calls(tree: ast.Module):
    """Yield ``(call, enclosing_function_name)`` tracking the innermost
    enclosing def (same idiom as the KI-10 conformance sweep)."""

    def walk(node: ast.AST, fn: str):
        for child in ast.iter_child_nodes(node):
            f = fn
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                f = child.name
            if isinstance(child, ast.Call):
                yield child, f
            yield from walk(child, f)

    yield from walk(tree, "<module>")


def _call_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _iter_package_sources(pkg_root: str):
    for dirpath, _dirnames, filenames in os.walk(pkg_root):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, pkg_root).replace(os.sep, "/")
            try:
                with open(path) as f:
                    src = f.read()
                tree = ast.parse(src)
            except (OSError, SyntaxError):
                continue
            yield rel, src, tree


def _audit_tree(rel: str, tree: ast.Module, report: Report,
                seen_mints: set[tuple[str, str]]) -> int:
    """The per-module static rules (mint closure + metric names);
    returns the number of emitter calls audited."""
    audited = 0
    for call, fn_name in _walk_calls(tree):
        name = _call_name(call)
        if name == "mint_trace_id" and rel != _MINT_HOME:
            site = (rel, fn_name)
            seen_mints.add(site)
            if site not in MINT_SITES:
                report.findings.append(
                    Finding(
                        ki="KI-12",
                        check="mint-site",
                        path=f"qba_tpu/{rel}",
                        message=(
                            f"mint_trace_id() called in {fn_name}() — "
                            "minting a fresh trace id outside the "
                            "registered request-origin sites orphans "
                            "every span recorded under it; adopt the "
                            "id riding the request instead (or "
                            "register the site in analysis/obs.py "
                            "MINT_SITES)"
                        ),
                        where=f"{rel}:{call.lineno}",
                    )
                )
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in _EMITTERS
            and call.args
            and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)
        ):
            audited += 1
            metric = call.args[0].value
            if metric not in METRICS:
                report.findings.append(
                    Finding(
                        ki="KI-12",
                        check="metric-name",
                        path=f"qba_tpu/{rel}",
                        message=(
                            f"emission of unregistered metric "
                            f"{metric!r} via .{call.func.attr}() — "
                            "every metric name must be a row of "
                            "qba_tpu.obs.metrics.METRICS (one name "
                            "table, no forks)"
                        ),
                        where=f"{rel}:{call.lineno}",
                    )
                )
    return audited


def _references_trace_id(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr == "trace_id":
            return True
        if isinstance(node, ast.Name) and node.id == "trace_id":
            return True
        if isinstance(node, ast.keyword) and node.arg == "trace_id":
            return True
        if (
            isinstance(node, ast.Constant)
            and node.value == "trace_id"
        ):
            return True
    return False


def _check_request_fields(pkg_root: str, report: Report) -> None:
    """Trace context must be real EvalRequest/EvalResult fields — the
    strict ``from_json`` rejects unknown keys, so context smuggled any
    other way would be dropped at the first queue hop."""
    path = os.path.join(pkg_root, "serve", "request.py")
    try:
        with open(path) as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        report.findings.append(
            Finding(
                ki="KI-12",
                check="trace-propagation",
                path="qba_tpu/serve/request.py",
                message="serve/request.py unreadable — no trace fields",
            )
        )
        return
    for cls_name in ("EvalRequest", "EvalResult"):
        cls = next(
            (n for n in ast.walk(tree)
             if isinstance(n, ast.ClassDef) and n.name == cls_name),
            None,
        )
        fields = {
            stmt.target.id
            for stmt in (cls.body if cls else [])
            if isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Name)
        }
        if "trace_id" not in fields:
            report.findings.append(
                Finding(
                    ki="KI-12",
                    check="trace-propagation",
                    path="qba_tpu/serve/request.py",
                    message=(
                        f"{cls_name} has no trace_id field — the "
                        "strict from_json drops unknown keys, so "
                        "trace context cannot ride the queue file"
                    ),
                )
            )


def _check_engine_adoption(pkg_root: str, report: Report) -> None:
    """``submit`` must adopt ``req.trace_id`` into the root span's args
    and stamp ``t0_epoch``; without either, worker spans are dark."""
    path = os.path.join(pkg_root, "serve", "engine.py")
    try:
        with open(path) as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return
    submit = next(
        (n for n in ast.walk(tree)
         if isinstance(n, ast.FunctionDef) and n.name == "submit"),
        None,
    )
    if submit is None:
        report.findings.append(
            Finding(
                ki="KI-12",
                check="trace-adoption",
                path="qba_tpu/serve/engine.py",
                message="engine submit() not found — adoption unproven",
            )
        )
        return
    adopts = any(
        isinstance(n, ast.Attribute)
        and n.attr == "trace_id"
        and isinstance(n.value, ast.Name)
        and n.value.id == "req"
        for n in ast.walk(submit)
    )
    anchors = any(
        (isinstance(n, ast.Constant) and n.value == "t0_epoch")
        or (isinstance(n, ast.keyword) and n.arg == "t0_epoch")
        for n in ast.walk(submit)
    )
    if not adopts:
        report.findings.append(
            Finding(
                ki="KI-12",
                check="trace-adoption",
                path="qba_tpu/serve/engine.py",
                message=(
                    "submit() never reads req.trace_id — the worker "
                    "root span cannot adopt the request's identity "
                    "and its spans will stitch to nothing"
                ),
                where=f"engine.py:{submit.lineno}",
            )
        )
    if not anchors:
        report.findings.append(
            Finding(
                ki="KI-12",
                check="trace-adoption",
                path="qba_tpu/serve/engine.py",
                message=(
                    "submit() never stamps t0_epoch — perf_counter "
                    "spans cannot be shifted onto the wall-clock axis "
                    "and the whole worker segment goes dark"
                ),
                where=f"engine.py:{submit.lineno}",
            )
        )


def check_obs(pkg_root: str | None = None) -> Report:
    """The static KI-12 pass over the shipped package: mint-site
    closure, metric-name registration, trace-context propagation,
    engine adoption.  This is what ``qba-tpu lint --obs`` runs."""
    root = pkg_root if pkg_root is not None else _pkg_root()
    report = Report()
    seen_mints: set[tuple[str, str]] = set()
    audited = 0
    trees: dict[str, ast.Module] = {}
    for rel, _src, tree in _iter_package_sources(root):
        trees[rel] = tree
        audited += _audit_tree(rel, tree, report, seen_mints)
    for site in sorted(MINT_SITES - seen_mints):
        rel, fn_name = site
        report.findings.append(
            Finding(
                ki="KI-12",
                check="mint-site",
                path=f"qba_tpu/{rel}",
                message=(
                    f"registered mint site lost: {fn_name}() in {rel} "
                    "no longer calls mint_trace_id() — requests born "
                    "there would ride the queue with no trace id; "
                    "update the code AND MINT_SITES together"
                ),
            )
        )
    for rel in PROPAGATING_MODULES:
        tree = trees.get(rel)
        if tree is None or not _references_trace_id(tree):
            report.findings.append(
                Finding(
                    ki="KI-12",
                    check="trace-propagation",
                    path=f"qba_tpu/{rel}",
                    message=(
                        f"{rel} never references trace_id — a queue "
                        "hop through it drops the trace context and "
                        "everything downstream orphans"
                    ),
                )
            )
    _check_request_fields(root, report)
    _check_engine_adoption(root, report)
    report.stats["obs_modules_scanned"] = len(trees)
    report.stats["obs_emitter_calls_audited"] = audited
    report.stats["obs_mint_sites_bound"] = len(seen_mints & MINT_SITES)
    report.notes.append(
        f"obs: {len(trees)} modules scanned, {audited} emitter call(s) "
        f"audited, {len(seen_mints & MINT_SITES)}/{len(MINT_SITES)} "
        "mint sites bound"
    )
    return report


def check_obs_fixture(fixture_path: str) -> Report:
    """Run the same static rules over one seeded violation fixture (the
    file is treated as a package module at its basename).  Used by
    tests/test_obs_plane.py and the CI fixture gate — the checker must
    kill every fixture."""
    report = Report()
    with open(fixture_path) as f:
        tree = ast.parse(f.read())
    rel = os.path.basename(fixture_path)
    seen: set[tuple[str, str]] = set()
    audited = _audit_tree(rel, tree, report, seen)
    report.stats["obs_emitter_calls_audited"] = audited
    return report


def check_span_coverage(
    queue_dir: str,
    telemetry_dir: str | None = None,
    *,
    floor: float = COVERAGE_FLOOR,
) -> Report:
    """The dynamic half of KI-12, over a real fleet run's artifacts:
    every closed stitched trace must attribute at least ``floor`` of
    its wall time to child spans, and no worker span may be an orphan."""
    from qba_tpu.obs.tracing import stitch_traces

    report = Report()
    stitched = stitch_traces(queue_dir, telemetry_dir=telemetry_dir)
    if stitched["orphan_spans"]:
        report.findings.append(
            Finding(
                ki="KI-12",
                check="span-coverage",
                path=queue_dir,
                message=(
                    f"{stitched['orphan_spans']} orphan span(s): worker "
                    "span files that stitch to no intaken request — "
                    "their trace id was dropped or re-minted somewhere "
                    "on the queue path"
                ),
            )
        )
    below = 0
    for tid, trace in sorted(stitched["traces"].items()):
        cov = trace["coverage"]
        if not trace["closed"] or cov is None:
            continue
        if cov < floor:
            below += 1
            report.findings.append(
                Finding(
                    ki="KI-12",
                    check="span-coverage",
                    path=queue_dir,
                    message=(
                        f"trace {tid[:12]} (request "
                        f"{trace.get('request_id')}) attributes only "
                        f"{cov:.1%} of its {trace['dur']:.3f}s wall "
                        f"time to child spans (floor {floor:.0%}) — "
                        "dark time the trace cannot explain"
                    ),
                )
            )
    n = len(stitched["traces"])
    report.stats["obs_traces_checked"] = n
    report.notes.append(
        f"obs: {n} stitched trace(s), {stitched['orphan_spans']} "
        f"orphan span(s), {below} below the {floor:.0%} coverage floor"
    )
    return report
