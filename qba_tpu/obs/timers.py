"""Per-phase wall-clock timers and throughput metrics.

The reference contains no timers at all (SURVEY §5 "Tracing/profiling:
Absent"); benchmarking it means re-measuring from scratch (SURVEY §6).
Here every runner can time its phases and report the headline
"protocol rounds/sec" throughput (BASELINE.json).

Since the telemetry layer landed, ``PhaseTimers`` is a *view* over a
:class:`~qba_tpu.obs.telemetry.SpanRecorder`: ``time(phase)`` records a
span named ``phase``, and the totals/counts are per-name aggregates of
the recorded spans.  Passing a shared recorder (``spans=``) makes every
timed phase appear in the run's exported trace for free; the default
constructs a private recorder, preserving the original flat-timer
behavior exactly.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Iterator

from qba_tpu.config import QBAConfig
from qba_tpu.obs.telemetry import Span, SpanRecorder


class PhaseTimers:
    """Accumulating named wall-clock timers over a span recorder.

    ``with timers.time("rounds"): ...`` accumulates into ``total("rounds")``;
    a phase may be entered repeatedly (per chunk / per rep).  Extra
    keyword args to ``time`` become span args in the exported trace.
    """

    def __init__(
        self,
        clock: Callable[[], float] = time.perf_counter,
        spans: SpanRecorder | None = None,
    ) -> None:
        self.spans = spans if spans is not None else SpanRecorder(clock=clock)

    @contextlib.contextmanager
    def time(self, phase: str, **args) -> Iterator["Span"]:
        with self.spans.span(phase, **args) as sp:
            yield sp

    def total(self, phase: str) -> float:
        return sum(
            sp.dur
            for sp in self.spans.spans
            if sp.name == phase and sp.dur is not None
        )

    def count(self, phase: str) -> int:
        return sum(
            1
            for sp in self.spans.spans
            if sp.name == phase and sp.dur is not None
        )

    def summary(self) -> dict[str, dict[str, float]]:
        return self.spans.totals()

    def render(self) -> str:
        rows = [
            f"  {phase:<16} {d['total_s']:.4f}s  (x{int(d['count'])})"
            for phase, d in sorted(self.summary().items())
        ]
        return "phase timings:\n" + "\n".join(rows) if rows else "phase timings: none"


def throughput(cfg: QBAConfig, n_trials: int, seconds: float) -> dict[str, float]:
    """Throughput triple for a completed batch.

    ``rounds_per_sec`` counts protocol voting rounds (``n_rounds`` per
    trial, ``tfg.py:337``) — the BASELINE.json headline metric.
    """
    if seconds <= 0:
        raise ValueError("seconds must be > 0")
    return {
        "trials_per_sec": n_trials / seconds,
        "rounds_per_sec": n_trials * cfg.n_rounds / seconds,
        "positions_per_sec": n_trials * cfg.size_l / seconds,
    }
