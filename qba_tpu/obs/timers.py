"""Per-phase wall-clock timers and throughput metrics.

The reference contains no timers at all (SURVEY §5 "Tracing/profiling:
Absent"); benchmarking it means re-measuring from scratch (SURVEY §6).
Here every runner can time its phases and report the headline
"protocol rounds/sec" throughput (BASELINE.json).
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Callable, Iterator

from qba_tpu.config import QBAConfig


class PhaseTimers:
    """Accumulating named wall-clock timers.

    ``with timers.time("rounds"): ...`` accumulates into ``total("rounds")``;
    a phase may be entered repeatedly (per chunk / per rep).
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter) -> None:
        self._clock = clock
        self._totals: defaultdict[str, float] = defaultdict(float)
        self._counts: defaultdict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def time(self, phase: str) -> Iterator[None]:
        t0 = self._clock()
        try:
            yield
        finally:
            self._totals[phase] += self._clock() - t0
            self._counts[phase] += 1

    def total(self, phase: str) -> float:
        return self._totals[phase]

    def count(self, phase: str) -> int:
        return self._counts[phase]

    def summary(self) -> dict[str, dict[str, float]]:
        return {
            phase: {"total_s": self._totals[phase], "count": self._counts[phase]}
            for phase in self._totals
        }

    def render(self) -> str:
        rows = [
            f"  {phase:<16} {d['total_s']:.4f}s  (x{int(d['count'])})"
            for phase, d in sorted(self.summary().items())
        ]
        return "phase timings:\n" + "\n".join(rows) if rows else "phase timings: none"


def throughput(cfg: QBAConfig, n_trials: int, seconds: float) -> dict[str, float]:
    """Throughput triple for a completed batch.

    ``rounds_per_sec`` counts protocol voting rounds (``n_rounds`` per
    trial, ``tfg.py:337``) — the BASELINE.json headline metric.
    """
    if seconds <= 0:
        raise ValueError("seconds must be > 0")
    return {
        "trials_per_sec": n_trials / seconds,
        "rounds_per_sec": n_trials * cfg.n_rounds / seconds,
        "positions_per_sec": n_trials * cfg.size_l / seconds,
    }
