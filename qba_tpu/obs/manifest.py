"""Run manifest: a structured record of every dispatch decision.

The 4-engine auto-dispatch (fused -> tiled -> monolithic -> XLA), the
verdict-variant and block-plan compile probes, and the trial-pack
resolution all decide *what actually ran* — and until now those
decisions surfaced only as one-shot ``QBADemotionWarning`` /
``QBAProbeWarning`` strings plus per-field accessors scattered across
:mod:`qba_tpu.benchmark`.  The manifest collects them in one validated
JSON document next to the environment (jax version, backend, device
topology) and the config fingerprint, so a benchmark artifact or a bug
report names its execution path machine-readably.

Two complementary sources feed it, by design:

* ``decisions`` — the structured records captured live by
  :func:`qba_tpu.diagnostics.record_decisions` while the run executed.
  Complete for the FIRST resolution of a config shape in a process;
  empty when the resolver memo already held the verdicts (warnings
  fire once per shape per process).
* ``plan`` / ``demotion_chain`` — read back from the memoized
  resolvers afterwards (:func:`qba_tpu.benchmark.kernel_plan`), which
  is exactly the resolution the run used regardless of when it was
  first probed.

Schema id: ``qba-tpu/run-manifest/v1`` (see docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import time
from typing import Any, Iterator

from qba_tpu.config import QBAConfig
from qba_tpu.obs.telemetry import SpanRecorder

MANIFEST_SCHEMA = "qba-tpu/run-manifest/v1"

# Keys validate_manifest requires, with their expected types.
_REQUIRED: dict[str, type | tuple[type, ...]] = {
    "schema": str,
    "environment": dict,
    "config": dict,
    "plan": dict,
    "engine_description": str,
    "demotion_chain": list,
    "decisions": list,
    "probe_stats": dict,
    "counters_enabled": bool,
}

_PLAN_KEYS = (
    "engine", "variant", "verdict_block", "rebuild_block", "fused_block",
    "trial_pack", "launches_per_round",
)


def environment_info() -> dict[str, Any]:
    """jax/backend/device-topology fingerprint of this process."""
    import platform as _platform

    import jax

    devices = jax.devices()
    return {
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "device_count": len(devices),
        "device_kind": devices[0].device_kind if devices else None,
        "python": _platform.python_version(),
        "host": _platform.platform(),
    }


def config_fingerprint(cfg: QBAConfig) -> dict[str, Any]:
    """All explicit fields plus the derived shape parameters the
    engines actually key on — enough to reconstruct the config AND to
    read the manifest without re-deriving w/slots by hand."""
    d = dataclasses.asdict(cfg)
    d["derived"] = {
        "w": cfg.w,
        "slots": cfg.slots,
        "max_l": cfg.max_l,
        "n_rounds": cfg.n_rounds,
        "n_lieutenants": cfg.n_lieutenants,
    }
    return d


def probe_stats_snapshot() -> dict[str, int]:
    """Copy of the resolver/probe counters
    (:data:`qba_tpu.ops.round_kernel_tiled.PROBE_STATS`)."""
    from qba_tpu.ops.round_kernel_tiled import PROBE_STATS

    return dict(PROBE_STATS)


def demotion_chain(cfg: QBAConfig, plan: dict[str, Any]) -> list[str]:
    """requested -> resolved -> actually-run engine names, deduplicated
    in order.  ``auto`` resolution is the first hop; a fused engine
    whose fused block failed to probe runs the tiled path
    (:func:`qba_tpu.rounds.engine.run_rounds_fused`) — the second."""
    chain = [cfg.round_engine]
    engine = plan["engine"]
    if engine != chain[-1]:
        chain.append(engine)
    if engine == "pallas_fused" and plan.get("fused_block") is None:
        chain.append("pallas_tiled")
    return chain


def collect_manifest(
    cfg: QBAConfig,
    *,
    command: str | None = None,
    decisions: list[dict] | None = None,
    probe_stats_before: dict[str, int] | None = None,
    spans: SpanRecorder | None = None,
    extra: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """Assemble the full manifest for ``cfg`` as run in this process.

    ``probe_stats_before`` should be a :func:`probe_stats_snapshot`
    taken before the run so the delta isolates this run's resolver
    traffic; without it the delta equals the absolute counters.
    """
    from qba_tpu.benchmark import engine_description, kernel_plan

    plan = kernel_plan(cfg)
    after = probe_stats_snapshot()
    before = probe_stats_before or {k: 0 for k in after}
    manifest: dict[str, Any] = {
        "schema": MANIFEST_SCHEMA,
        "created_unix_s": time.time(),
        "command": command,
        "environment": environment_info(),
        "config": config_fingerprint(cfg),
        "plan": plan,
        "engine_description": engine_description(cfg),
        "demotion_chain": demotion_chain(cfg, plan),
        "decisions": list(decisions or []),
        "probe_stats": {
            "before": before,
            "after": after,
            "delta": {k: after[k] - before.get(k, 0) for k in after},
        },
        "counters_enabled": bool(cfg.collect_counters),
    }
    if spans is not None:
        manifest["phase_totals"] = spans.totals()
    if extra:
        manifest.update(extra)
    return manifest


def validate_manifest(manifest: dict[str, Any]) -> dict[str, Any]:
    """Schema check (all problems at once); returns the manifest so the
    call composes.  The CI smoke step and the round-trip tests both run
    this — keep it in sync with :func:`collect_manifest`."""
    problems: list[str] = []
    if not isinstance(manifest, dict):
        raise ValueError(f"manifest must be a dict, got {type(manifest)}")
    if manifest.get("schema") != MANIFEST_SCHEMA:
        problems.append(
            f"schema: expected {MANIFEST_SCHEMA!r}, got {manifest.get('schema')!r}"
        )
    for key, typ in _REQUIRED.items():
        if key not in manifest:
            problems.append(f"missing key: {key}")
        elif not isinstance(manifest[key], typ):
            problems.append(
                f"{key}: expected {typ}, got {type(manifest[key]).__name__}"
            )
    plan = manifest.get("plan")
    if isinstance(plan, dict):
        for key in _PLAN_KEYS:
            if key not in plan:
                problems.append(f"plan missing key: {key}")
    chain = manifest.get("demotion_chain")
    if isinstance(chain, list) and not chain:
        problems.append("demotion_chain must name at least the run engine")
    stats = manifest.get("probe_stats")
    if isinstance(stats, dict):
        for key in ("before", "after", "delta"):
            if not isinstance(stats.get(key), dict):
                problems.append(f"probe_stats.{key} must be a dict")
    if problems:
        raise ValueError("invalid run manifest: " + "; ".join(problems))
    return manifest


def write_manifest(path: str, manifest: dict[str, Any]) -> str:
    with open(path, "w") as f:
        json.dump(manifest, f, indent=1, default=str)
    return path


def load_manifest(path: str) -> dict[str, Any]:
    with open(path) as f:
        return validate_manifest(json.load(f))


@dataclasses.dataclass
class TelemetrySession:
    """Live handle yielded by :func:`telemetry_session`: the shared span
    recorder (hand it to ``PhaseTimers(spans=...)``), plus mutable
    ``extra`` merged into the manifest at exit."""

    directory: str
    spans: SpanRecorder
    extra: dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.directory, "run_manifest.json")

    @property
    def trace_path(self) -> str:
        return os.path.join(self.directory, "trace.json")


@contextlib.contextmanager
def telemetry_session(
    directory: str, cfg: QBAConfig, command: str
) -> Iterator[TelemetrySession]:
    """Everything ``--telemetry DIR`` needs in one context manager:

    * opens a :class:`SpanRecorder` with a root span named ``command``,
    * captures dispatch decisions (:func:`~qba_tpu.diagnostics.record_decisions`)
      and the PROBE_STATS delta across the block,
    * on exit writes ``run_manifest.json`` (validated),
      ``trace.json`` (Chrome trace events, Perfetto-loadable), and
      ``spans.jsonl`` into ``directory``.

    Artifacts are written even when the block raises — a failed run's
    partial trace is exactly when you want telemetry.
    """
    from qba_tpu.diagnostics import record_decisions

    os.makedirs(directory, exist_ok=True)
    session = TelemetrySession(directory=directory, spans=SpanRecorder())
    before = probe_stats_snapshot()
    try:
        with record_decisions() as decisions:
            with session.spans.span(command, cat="command"):
                yield session
    finally:
        manifest = collect_manifest(
            cfg,
            command=command,
            decisions=decisions,
            probe_stats_before=before,
            spans=session.spans,
            extra=session.extra,
        )
        write_manifest(session.manifest_path, validate_manifest(manifest))
        session.spans.write_chrome_trace(session.trace_path)
        session.spans.write_jsonl(os.path.join(directory, "spans.jsonl"))
