"""Live fleet metrics: a jax-free registry with Prometheus exposition.

The fleet's metrics plane (docs/OBSERVABILITY.md "Fleet tracing and
metrics") is deliberately small: one registered name table
(:data:`METRICS`), three instrument kinds (counter / gauge /
histogram), and a hand-rolled text renderer compatible with the
Prometheus exposition format plus OpenMetrics-style ``# {...}``
exemplars carrying trace ids.

Two design rules, both machine-checked by ``qba-tpu lint --obs``
(KI-12):

* **One name table.** Every emission site must name a key of
  :data:`METRICS`; the registry raises on anything else, and the lint
  proves statically that every string-literal metric name in the tree
  is registered.
* **No new sockets.** Point-in-time facts (queue depth, heartbeat
  staleness, crash-ledger totals) are *collected* from the queue
  directory at scrape time via :meth:`MetricsRegistry.add_collector`;
  workers never push — the heartbeat and summary files they already
  write are the transport.

This module must stay importable without jax: the frontend serves
``GET /metrics`` and is statically proven jax-free (KI-6 fleet fence).
"""

from __future__ import annotations

import math
import threading

__all__ = [
    "METRICS",
    "MetricsRegistry",
    "default_buckets",
    "validate_exposition",
]

# The single registered metric-name table.  name -> (kind, help text,
# allowed label keys).  Adding a metric means adding a row here first;
# emitting an unregistered name raises at runtime and fails KI-12 lint
# statically.
METRICS: dict[str, tuple[str, str, tuple[str, ...]]] = {
    "qba_intake_requests_total": (
        "counter", "Requests received at the fleet frontend.", ()),
    "qba_admission_decisions_total": (
        "counter", "Admission decisions by action and typed reason.",
        ("action", "reason")),
    "qba_results_forwarded_total": (
        "counter", "Results settled back to clients, by outcome.",
        ("outcome",)),
    "qba_request_latency_seconds": (
        "histogram", "Worker-reported request latency.", ()),
    "qba_request_queue_wait_seconds": (
        "histogram", "Queue wait from producer mtime to claim.", ()),
    "qba_queue_files": (
        "gauge", "Files per queue box (inbox/claimed/outbox/dead/...).",
        ("box",)),
    "qba_queue_reclaims": (
        "gauge", "Stale-claim reclaims summed over replica exit "
        "summaries and the crash ledger.", ()),
    "qba_queue_dead_letters": (
        "gauge", "Dead-lettered requests currently in dead/.", ()),
    "qba_replica_heartbeat_staleness_seconds": (
        "gauge", "Monotonic now minus last heartbeat, per replica.",
        ("replica",)),
    "qba_fleet_replicas": (
        "gauge", "Replicas per supervisor health class.", ("state",)),
    "qba_supervisor_deaths": (
        "gauge", "Worker deaths recorded in the crash ledger.", ()),
    "qba_supervisor_quarantined": (
        "gauge", "Requests quarantined as poison.", ()),
    "qba_atlas_cells_total": (
        "counter", "Atlas campaign cell outcomes by status.",
        ("status",)),
    "qba_atlas_budget_trials_total": (
        "counter", "Trials of budget spent by atlas campaigns.", ()),
}

_KINDS = ("counter", "gauge", "histogram")


def default_buckets() -> tuple[float, ...]:
    """Latency-shaped histogram buckets (seconds)."""
    return (0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
            30.0, 60.0)


def _check_labels(name: str, labels: dict[str, str] | None) -> tuple:
    kind, _, allowed = METRICS[name]
    labels = labels or {}
    if set(labels) != set(allowed):
        raise ValueError(
            f"metric {name} takes labels {sorted(allowed)}, "
            f"got {sorted(labels)}"
        )
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _label_str(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
    return "{" + inner + "}"


def _escape(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


class MetricsRegistry:
    """Counters, gauges and histograms over the registered name table.

    Thread-safe (the frontend's asyncio loop, the supervisor thread and
    scrape-time collectors may all touch it).  Exemplar trace ids are
    kept per series — the most recent one wins — and rendered in
    OpenMetrics ``# {trace_id="..."} value`` form.
    """

    def __init__(self, buckets: tuple[float, ...] | None = None):
        self._lock = threading.Lock()
        self._buckets = tuple(buckets or default_buckets())
        self._counters: dict[tuple, float] = {}
        self._gauges: dict[tuple, float] = {}
        # (name, labelkey) -> [bucket counts..., +Inf count, sum]
        self._hists: dict[tuple, list[float]] = {}
        self._exemplars: dict[tuple, tuple[str, float]] = {}
        self._collectors: list = []

    # -- registration guard ------------------------------------------

    @staticmethod
    def _require(name: str, kind: str) -> None:
        row = METRICS.get(name)
        if row is None:
            raise ValueError(f"unregistered metric name: {name!r} "
                             "(add it to qba_tpu.obs.metrics.METRICS)")
        if row[0] != kind:
            raise ValueError(f"metric {name} is a {row[0]}, not a {kind}")

    # -- instruments -------------------------------------------------

    def inc(self, name: str, value: float = 1.0, *,
            labels: dict[str, str] | None = None,
            exemplar: str | None = None) -> None:
        self._require(name, "counter")
        key = (name, _check_labels(name, labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value
            if exemplar:
                self._exemplars[key] = (exemplar, value)

    def set_gauge(self, name: str, value: float, *,
                  labels: dict[str, str] | None = None) -> None:
        self._require(name, "gauge")
        key = (name, _check_labels(name, labels))
        with self._lock:
            self._gauges[key] = float(value)

    def observe(self, name: str, value: float, *,
                labels: dict[str, str] | None = None,
                exemplar: str | None = None) -> None:
        self._require(name, "histogram")
        key = (name, _check_labels(name, labels))
        with self._lock:
            row = self._hists.setdefault(
                key, [0.0] * (len(self._buckets) + 2))
            for i, edge in enumerate(self._buckets):
                if value <= edge:
                    row[i] += 1
            row[len(self._buckets)] += 1  # +Inf / _count
            row[len(self._buckets) + 1] += value  # _sum
            if exemplar:
                self._exemplars[key] = (exemplar, value)

    # -- scrape-time collection --------------------------------------

    def add_collector(self, fn) -> None:
        """Register ``fn(registry)`` to run at the top of each render.

        Collectors set point-in-time gauges (queue depth, heartbeat
        staleness) so scrapes always reflect the on-disk now rather
        than the last push.
        """
        self._collectors.append(fn)

    # -- exposition --------------------------------------------------

    def render(self) -> str:
        for fn in list(self._collectors):
            try:
                fn(self)
            except Exception:  # a sick collector must not kill /metrics
                pass
        lines: list[str] = []
        with self._lock:
            for name in sorted(METRICS):
                kind, help_text, _ = METRICS[name]
                series = self._series_for(name)
                if not series:
                    continue
                lines.append(f"# HELP {name} {help_text}")
                lines.append(f"# TYPE {name} {kind}")
                lines.extend(series)
        return "\n".join(lines) + "\n" if lines else "\n"

    def _series_for(self, name: str) -> list[str]:
        kind = METRICS[name][0]
        out: list[str] = []
        if kind == "counter":
            store = self._counters
        elif kind == "gauge":
            store = self._gauges
        else:
            store = self._hists
        for (nm, labelkey), val in sorted(store.items()):
            if nm != name:
                continue
            if kind in ("counter", "gauge"):
                line = f"{name}{_label_str(labelkey)} {_fmt(val)}"
                out.append(self._with_exemplar(line, (nm, labelkey),
                                               kind == "counter"))
            else:
                base = dict(labelkey)
                cum = 0.0
                for i, edge in enumerate(self._buckets):
                    cum = val[i]
                    lk = tuple(sorted(
                        {**base, "le": _fmt(edge)}.items()))
                    out.append(f"{name}_bucket{_label_str(lk)} "
                               f"{_fmt(cum)}")
                lk = tuple(sorted({**base, "le": "+Inf"}.items()))
                count = val[len(self._buckets)]
                line = f"{name}_bucket{_label_str(lk)} {_fmt(count)}"
                out.append(self._with_exemplar(line, (nm, labelkey),
                                               True))
                out.append(f"{name}_sum{_label_str(labelkey)} "
                           f"{_fmt(val[len(self._buckets) + 1])}")
                out.append(f"{name}_count{_label_str(labelkey)} "
                           f"{_fmt(count)}")
        return out

    def _with_exemplar(self, line: str, key: tuple,
                       allowed: bool) -> str:
        ex = self._exemplars.get(key)
        if not (allowed and ex):
            return line
        trace_id, value = ex
        return f'{line} # {{trace_id="{_escape(trace_id)}"}} {_fmt(value)}'

    # -- snapshots (tests, summaries) --------------------------------

    def counter_value(self, name: str,
                      labels: dict[str, str] | None = None) -> float:
        self._require(name, "counter")
        key = (name, _check_labels(name, labels))
        with self._lock:
            return self._counters.get(key, 0.0)


def validate_exposition(text: str) -> list[str]:
    """Check Prometheus-text well-formedness; return problems found.

    Used by the CI fleet job on the mid-run ``GET /metrics`` scrape and
    by the tests — an empty return means the exposition parsed clean.
    """
    problems: list[str] = []
    typed: dict[str, str] = {}
    for i, raw in enumerate(text.splitlines(), start=1):
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            if len(parts) < 4 or parts[3] not in _KINDS:
                problems.append(f"line {i}: malformed TYPE: {line!r}")
            else:
                typed[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            problems.append(f"line {i}: unknown comment: {line!r}")
            continue
        sample, _, exemplar = line.partition(" # ")
        if exemplar and not exemplar.startswith("{"):
            problems.append(f"line {i}: malformed exemplar: {line!r}")
        name = sample.split("{", 1)[0].split(" ", 1)[0]
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in typed:
                base = name[: -len(suffix)]
        if base not in typed:
            problems.append(f"line {i}: sample before TYPE: {name}")
        if base not in METRICS:
            problems.append(f"line {i}: unregistered metric: {base}")
        fields = sample.rsplit(" ", 1)
        if len(fields) != 2:
            problems.append(f"line {i}: no value: {line!r}")
            continue
        value = fields[1]
        if value != "+Inf":
            try:
                float(value)
            except ValueError:
                problems.append(f"line {i}: bad value {value!r}")
    return problems
