"""Optional Monte-Carlo plotting utilities (matplotlib-gated).

The reference imports matplotlib and never uses it (``tfg.py:2``,
SURVEY §2.18 "none needed (optionally a plotting util for Monte-Carlo
results)").  Here the optional plotting layer earns its keep with the two
plots a protocol study actually needs:

* convergence of the Monte-Carlo success-rate estimate over trials, and
* success rate vs a swept protocol parameter (the security-parameter
  study: how fast agreement probability approaches 1 in ``size_l``).

Both are single-series line charts: one hue, no legend (the title names
the series), recessive grid, a ±2σ binomial uncertainty band instead of
per-point labels.  Import of matplotlib is deferred and failure-gated so
the framework never requires it.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

_HUE = "#2563eb"  # single categorical hue; band/grid stay neutral
_INK = "#374151"
_GRID = "#d1d5db"


class PlottingUnavailableError(RuntimeError):
    """matplotlib is not installed (it is an optional dependency).

    A dedicated type so the CLI can turn exactly this condition into a
    clean usage error while letting every other ``RuntimeError`` (XLA
    failures, native runtime errors) propagate with a traceback.
    """


def _require_pyplot():
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError as e:  # pragma: no cover - matplotlib is optional
        raise PlottingUnavailableError(
            "plotting requires matplotlib, which is not installed; "
            "qba_tpu works without it everywhere else"
        ) from e
    return plt


def _style(ax) -> None:
    ax.spines["top"].set_visible(False)
    ax.spines["right"].set_visible(False)
    for spine in ("left", "bottom"):
        ax.spines[spine].set_color(_GRID)
    ax.tick_params(colors=_INK, labelsize=9)
    ax.grid(axis="y", color=_GRID, linewidth=0.6, alpha=0.6)
    ax.set_axisbelow(True)


def _band(n: np.ndarray, rate: np.ndarray) -> np.ndarray:
    """±2σ binomial standard error of the rate estimate."""
    with np.errstate(divide="ignore", invalid="ignore"):
        se = np.sqrt(rate * (1.0 - rate) / np.maximum(n, 1))
    return 2.0 * se


def plot_convergence(sweep, path: str) -> str:
    """Cumulative success-rate vs trials from a
    :class:`qba_tpu.sweep.SweepResult`; writes a PNG to ``path``."""
    plt = _require_pyplot()
    chunks = sorted(sweep.chunks, key=lambda c: c.chunk)
    n = np.cumsum([c.trials for c in chunks])
    s = np.cumsum([c.successes for c in chunks])
    rate = s / n
    band = _band(n, rate)

    fig, ax = plt.subplots(figsize=(6.4, 3.6), dpi=150)
    _style(ax)
    ax.fill_between(n, rate - band, rate + band, color=_HUE, alpha=0.15, lw=0)
    ax.plot(n, rate, color=_HUE, lw=2)
    ax.set_xlabel("trials", color=_INK)
    ax.set_ylabel("success rate", color=_INK)
    cfg = sweep.cfg
    ax.set_title(
        f"Monte-Carlo convergence — n={cfg.n_parties}, sizeL={cfg.size_l}, "
        f"d={cfg.n_dishonest}",
        color=_INK,
        fontsize=10,
    )
    ax.set_ylim(0.0, 1.05)
    fig.tight_layout()
    fig.savefig(path)
    plt.close(fig)
    return path


def plot_param_study(
    values: Sequence[float],
    rates: Sequence[float],
    trials: int,
    xlabel: str,
    path: str,
    title: str | None = None,
    log_x: bool = False,
) -> str:
    """Success rate vs a swept parameter; writes a PNG to ``path``."""
    plt = _require_pyplot()
    x = np.asarray(values, dtype=float)
    y = np.asarray(rates, dtype=float)
    # Sort by x so an unordered --values list still draws a monotone line
    # (unsorted points would zigzag and self-overlap the band).
    order = np.argsort(x, kind="stable")
    x, y = x[order], y[order]
    band = _band(np.full_like(y, trials), y)

    fig, ax = plt.subplots(figsize=(6.4, 3.6), dpi=150)
    _style(ax)
    ax.fill_between(x, y - band, y + band, color=_HUE, alpha=0.15, lw=0)
    ax.plot(x, y, color=_HUE, lw=2, marker="o", markersize=5)
    if log_x:
        ax.set_xscale("log", base=2)
    ax.set_xlabel(xlabel, color=_INK)
    ax.set_ylabel("success rate", color=_INK)
    ax.set_title(
        title or f"success rate vs {xlabel} ({trials} trials/point)",
        color=_INK,
        fontsize=10,
    )
    ax.set_ylim(0.0, 1.05)
    fig.tight_layout()
    fig.savefig(path)
    plt.close(fig)
    return path
