"""Interval statistics for Monte-Carlo study results.

The reference only ever eyeballed single runs (``log_d_11.txt``); the
``study`` subcommand quantifies the protocol's guarantees, which needs
honest uncertainty: Wilson score intervals (well-behaved near rates of
0/1, where the normal approximation the plots' shaded band uses breaks
down) and the success/validity decomposition.

Terminology (docs/VALIDITY.md): the built-in oracle
(:func:`qba_tpu.core.decide.success_oracle`, ``tfg.py:359-363``) checks
AGREEMENT — all honest parties decide one value.  Because an honest
commander decides its own order (``tfg.py:303-305``), agreement
*conditional on an honest commander* is exactly VALIDITY — honest
lieutenants decide the commander's order.  Under a dishonest commander
validity is vacuous and agreement is the whole guarantee.
"""

from __future__ import annotations

import numpy as np

from qba_tpu.stats.estimators import wilson_ci_z


def wilson_interval(k: int, n: int, z: float = 1.96) -> tuple[float, float]:
    """Wilson score interval for ``k`` successes in ``n`` Bernoulli
    trials (default 95%).  ``n == 0`` returns the uninformative (0, 1).

    Thin wrapper over :func:`qba_tpu.stats.estimators.wilson_ci_z` —
    the statistics engine owns the formula now; this name stays for the
    study scripts and their JSON consumers.
    """
    return wilson_ci_z(k, n, z)


def _rate(k: int, n: int) -> dict:
    lo, hi = wilson_interval(k, n)
    return {
        "k": int(k),
        "n": int(n),
        "rate": (k / n) if n else None,
        "lo": lo,
        "hi": hi,
    }


def decision_profile(decisions, honest, v_comm, w: int) -> dict:
    """Outcome classes among honest-commander trials — the detectable-QBA
    decomposition a bare success bit hides.

    A lieutenant decides ``min(Vi)``, or the sentinel ``w`` (abort, D2)
    on an empty accepted-set, so "success | honest commander" conflates
    three different failures.  Per honest-commander trial, over the
    HONEST lieutenants only:

    * ``valid`` — all decide the commander's order (strict validity).
    * ``abort_all`` — all decide the sentinel: unanimous detection.
    * ``mixed_valid_abort`` — every decision is the order or the
      sentinel, both occur.  Detection split the honest set.
    * ``corrupted`` — some honest lieutenant decided a DIFFERENT order
      (a forged value below the commander's won its ``min(Vi)``).

    ``decisions``: int32[trials, n_parties] (index 0 = commander);
    ``honest``: bool[trials, n_parties]; ``v_comm``: int32[trials].
    Returns the four Wilson-bounded rates, conditional on an honest
    commander with >= 1 honest lieutenant.
    """
    dec = np.asarray(decisions)
    hon = np.asarray(honest, dtype=bool)
    vc = np.asarray(v_comm)
    ch = hon[:, 0] & hon[:, 1:].any(axis=1)
    lieu_h = hon[:, 1:]
    d_l = dec[:, 1:]
    is_v = d_l == vc[:, None]
    is_abort = d_l == w
    all_v = np.where(lieu_h, is_v, True).all(axis=1)
    all_abort = np.where(lieu_h, is_abort, True).all(axis=1)
    in_pair = np.where(lieu_h, is_v | is_abort, True).all(axis=1)
    valid = ch & all_v
    abort_all = ch & all_abort & ~all_v
    mixed = ch & in_pair & ~all_v & ~all_abort
    corrupted = ch & ~in_pair
    n = int(ch.sum())
    return {
        "n_honest_commander": n,
        "valid": _rate(int(valid.sum()), n),
        "abort_all": _rate(int(abort_all.sum()), n),
        "mixed_valid_abort": _rate(int(mixed.sum()), n),
        "corrupted": _rate(int(corrupted.sum()), n),
    }


def study_breakdown(success, commander_honest) -> dict:
    """Success decomposed over the commander's honesty.

    ``success``: bool[trials] from the oracle; ``commander_honest``:
    bool[trials] (``trials.honest[:, 0]``).  Returns ``overall``,
    ``validity`` (success | honest commander — the protocol's validity
    property), and ``agreement_dishonest_c`` (success | dishonest
    commander), each with Wilson 95% bounds.
    """
    s = np.asarray(success, dtype=bool)
    ch = np.asarray(commander_honest, dtype=bool)
    return {
        "overall": _rate(int(s.sum()), s.size),
        "validity": _rate(int(s[ch].sum()), int(ch.sum())),
        "agreement_dishonest_c": _rate(int(s[~ch].sum()), int((~ch).sum())),
    }
