"""Observability: structured events, phase timers, reports, profiling.

The reference's entire observability surface is ``mpi_print`` — an
unconditional ``print`` + flush (``tfg.py:10-12``) called at every protocol
event, with commented-out calls as the de-facto verbosity knob
(``tfg.py:32,70,183,208-225,236-262,292,300``) and a final summary triple
``Decisions / Dishonests / Success`` (``tfg.py:360-363``).  SURVEY §5 lists
tracing/profiling as absent.

Here observability is a first-class subsystem:

* :mod:`qba_tpu.obs.events` — leveled, structured event log (JSONL-able)
  replacing ``mpi_print``.
* :mod:`qba_tpu.obs.timers` — per-phase wall-clock timers and throughput
  metrics (the BASELINE.json "protocol rounds/sec" headline).
* :mod:`qba_tpu.obs.report` — human-readable run reports, including the
  reference's closing ``Decisions / Dishonests / Success`` triple.
* :mod:`qba_tpu.obs.profiling` — optional JAX profiler trace hook.
* :mod:`qba_tpu.obs.telemetry` — hierarchical spans with fenced
  device-time attribution; JSONL + Chrome trace (Perfetto) export.
* :mod:`qba_tpu.obs.manifest` — the run manifest: engine/demotion/
  probe decisions, environment, and config fingerprint as one
  validated JSON document (docs/OBSERVABILITY.md).
"""

from qba_tpu.obs.events import Event, EventLog, Level
from qba_tpu.obs.manifest import (
    collect_manifest,
    load_manifest,
    telemetry_session,
    validate_manifest,
)
from qba_tpu.obs.profiling import profile_trace
from qba_tpu.obs.report import render_sweep, render_verdict
from qba_tpu.obs.telemetry import Span, SpanRecorder
from qba_tpu.obs.timers import PhaseTimers, throughput

__all__ = [
    "Event",
    "EventLog",
    "Level",
    "PhaseTimers",
    "Span",
    "SpanRecorder",
    "collect_manifest",
    "load_manifest",
    "profile_trace",
    "render_sweep",
    "render_verdict",
    "telemetry_session",
    "throughput",
    "validate_manifest",
]
