"""Optional JAX profiler hook (SURVEY §5: absent in the reference).

``profile_trace(dir)`` wraps a block in ``jax.profiler.trace`` when a
directory is given, and is a no-op otherwise — so runners can thread a
``--profile-dir`` flag through unconditionally.
"""

from __future__ import annotations

import contextlib
from typing import Iterator


@contextlib.contextmanager
def profile_trace(log_dir: str | None) -> Iterator[None]:
    if log_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
