"""Run reports: the reference's closing verdict triple, plus sweep summaries.

``render_verdict`` reproduces the rank-0 summary format of the reference
(``tfg.py:360-363``)::

    Decisions:  [3, 3, 3]
    Dishonests: [3]
    Success:    True

``Dishonests`` lists the reference's *ranks* (1 = commander, 2.. =
lieutenants, ``tfg.py:105``), matching the captured logs
(``logs tests/log_d_3.txt``).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from qba_tpu.config import QBAConfig


def _dishonest_ranks(honest) -> list[int]:
    """Reference ranks (1..n_parties) of the dishonest parties; index 0 of
    ``honest`` is the commander = rank 1 (TrialResult.honest layout)."""
    return [i + 1 for i, h in enumerate(np.asarray(honest)) if not bool(h)]


def render_verdict(cfg: QBAConfig, trial: Any, index: int | None = None) -> str:
    """One trial's verdict block from TrialResult-shaped fields.

    ``trial`` needs ``decisions``, ``honest``, ``success`` (and optionally
    ``overflow``); pass one element of a batched result via
    ``jax.tree.map(lambda x: x[i], batch)`` or index arrays directly.
    """
    decisions = [int(x) for x in np.asarray(trial.decisions)]
    shown = [d if d != cfg.no_decision else None for d in decisions]
    lines = []
    if index is not None:
        lines.append(f"trial {index}:")
    lines += [
        f"Decisions:  {shown}",
        f"Dishonests: {_dishonest_ranks(trial.honest)}",
        f"Success:    {bool(np.asarray(trial.success))}",
    ]
    if bool(np.asarray(getattr(trial, "overflow", False))):
        lines.append("(mailbox slot overflow occurred — see QBAConfig.slots)")
    return "\n".join(lines)


def render_sweep(
    cfg: QBAConfig,
    success_rate: float,
    n_trials: int,
    seconds: float | None = None,
) -> str:
    """Monte-Carlo aggregate summary (the capability the reference lacks:
    it can only run one trial per ``mpiexec`` invocation)."""
    lines = [
        f"config: n_parties={cfg.n_parties} size_l={cfg.size_l} "
        f"n_dishonest={cfg.n_dishonest} w={cfg.w}",
        f"trials: {n_trials}",
        f"success rate: {success_rate:.4f}",
    ]
    if seconds is not None and seconds > 0:
        rps = n_trials * cfg.n_rounds / seconds
        lines.append(f"throughput: {rps:.1f} protocol rounds/s ({seconds:.3f}s)")
    return "\n".join(lines)
