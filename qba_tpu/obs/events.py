"""Leveled, structured event log — the replacement for ``mpi_print``.

The reference logs by unconditional stdout prints (``tfg.py:10-12``); its
only verbosity control is commenting calls out (SURVEY §5).  Here events
are structured records with a level; sinks decide rendering (stdout for
interactive runs, JSONL for machine consumption).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import sys
import time
from typing import Any, Callable, TextIO


class Level(enum.IntEnum):
    DEBUG = 10
    INFO = 20
    WARNING = 30
    ERROR = 40


@dataclasses.dataclass(frozen=True)
class Event:
    """One structured protocol event.

    ``phase`` names the protocol phase (dishonesty / particles / step2 /
    round / decision — the reference's step comments, ``tfg.py:101-363``);
    ``fields`` carries the event payload.
    """

    ts: float
    level: Level
    phase: str
    message: str
    fields: dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(
            {
                "ts": round(self.ts, 6),
                "level": self.level.name,
                "phase": self.phase,
                "message": self.message,
                **self.fields,
            },
            default=str,
        )

    def render(self) -> str:
        extra = (
            " " + " ".join(f"{k}={v}" for k, v in self.fields.items())
            if self.fields
            else ""
        )
        return f"[{self.phase}] {self.message}{extra}"


class EventLog:
    """Append-only event collector with a minimum level and optional
    live stream (the ``mpi_print`` role, but leveled and structured)."""

    def __init__(
        self,
        min_level: Level = Level.INFO,
        stream: TextIO | None = None,
        clock: Callable[[], float] = time.monotonic,
        stream_level: Level | None = None,
    ) -> None:
        self.min_level = min_level
        self.stream = stream
        # Collection and live streaming can have different thresholds:
        # ``--jsonl`` without ``-v`` collects the DEBUG trail for export
        # without flooding stdout.
        self.stream_level = min_level if stream_level is None else stream_level
        self.events: list[Event] = []
        self._clock = clock

    def emit(
        self, level: Level, phase: str, message: str, **fields: Any
    ) -> None:
        if level < self.min_level:
            return
        ev = Event(self._clock(), level, phase, message, fields)
        self.events.append(ev)
        if self.stream is not None and level >= self.stream_level:
            # print + flush, as the reference's mpi_print does (tfg.py:10-12)
            print(ev.render(), file=self.stream, flush=True)

    def debug(self, phase: str, message: str, **fields: Any) -> None:
        self.emit(Level.DEBUG, phase, message, **fields)

    def info(self, phase: str, message: str, **fields: Any) -> None:
        self.emit(Level.INFO, phase, message, **fields)

    def warning(self, phase: str, message: str, **fields: Any) -> None:
        self.emit(Level.WARNING, phase, message, **fields)

    def error(self, phase: str, message: str, **fields: Any) -> None:
        self.emit(Level.ERROR, phase, message, **fields)

    def to_jsonl(self) -> str:
        return "\n".join(ev.to_json() for ev in self.events)

    def write_jsonl(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_jsonl() + ("\n" if self.events else ""))


def stdout_log(min_level: Level = Level.INFO) -> EventLog:
    """An EventLog that also prints live to stdout."""
    return EventLog(min_level=min_level, stream=sys.stdout)
