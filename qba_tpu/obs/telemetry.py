"""Hierarchical run telemetry: nested spans with device-time attribution.

The flat :class:`~qba_tpu.obs.timers.PhaseTimers` answer "how long did
phase X take in total"; they cannot express *structure* (which chunk's
readback, nested inside which command) and they cannot say whether a
wall-clock interval is trustworthy as device time.  Spans fix both:

* A span is a named wall-clock interval with a parent (spans nest via a
  context-manager stack), free-form key/value args, and a ``fenced``
  flag.
* ``fenced`` carries docs/PERF.md's core measurement lesson: on a
  remote-tunnel backend, async dispatch returns immediately and only a
  host readback is a barrier — so a span's duration is attributable to
  device execution ONLY if the span fetched a result before closing.
  :meth:`SpanRecorder.fence` does exactly that (it defers to
  :func:`qba_tpu.backends.jax_backend.fence`) and marks the span, so
  every exported interval is labeled host-wall vs fenced-device.
* Exports: JSONL (one span per line, for machine diffing) and Chrome
  trace-event JSON (``ph: "X"`` complete events) loadable in Perfetto
  (https://ui.perfetto.dev) or ``chrome://tracing`` — see
  docs/OBSERVABILITY.md for the how-to.

No module-level jax import: recording spans must stay usable from the
pure-Python backends and from tests that never touch jax.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import time
from typing import Any, Callable, Iterable, Iterator


def _jsonable(v: Any) -> Any:
    """Span args are free-form; exports must never crash on a numpy
    scalar or a config object — degrade to ``str`` past the JSON types."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    try:  # numpy / jax scalars
        return v.item()
    except (AttributeError, ValueError, TypeError):
        return str(v)


@dataclasses.dataclass
class Span:
    """One named interval.  ``t0``/``dur`` are in the recorder's clock
    units (seconds); ``dur`` is None while the span is still open."""

    name: str
    index: int  # position in the recorder's span list
    parent: int | None  # index of the enclosing span, None at top level
    depth: int  # nesting depth (0 = top level)
    t0: float
    dur: float | None = None
    cat: str = "host"
    fenced: bool = False  # closed after a host readback => device time
    args: dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "index": self.index,
            "parent": self.parent,
            "depth": self.depth,
            "t0_s": self.t0,
            "dur_s": self.dur,
            "cat": self.cat,
            "fenced": self.fenced,
            "args": {k: _jsonable(v) for k, v in self.args.items()},
        }


class SpanRecorder:
    """Appending span collector with a nesting stack.

    ``with rec.span("trials", cat="device") as sp: ...`` opens a child
    of the innermost open span; closing it (normally or via exception)
    stamps the duration.  Thread-unsafe by design — one recorder per
    run, like the EventLog.
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter) -> None:
        self._clock = clock
        self.spans: list[Span] = []
        self._stack: list[int] = []

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "host", **args: Any) -> Iterator[Span]:
        sp = Span(
            name=name,
            index=len(self.spans),
            parent=self._stack[-1] if self._stack else None,
            depth=len(self._stack),
            t0=self._clock(),
            cat=cat,
            args=dict(args),
        )
        self.spans.append(sp)
        self._stack.append(sp.index)
        try:
            yield sp
        finally:
            self._stack.pop()
            sp.dur = self._clock() - sp.t0

    def fence(self, res: Any, span: Span | None = None) -> Any:
        """Block until ``res`` is host-readable and mark the innermost
        open span (or ``span``) as device-fenced.

        This is THE way to make a span's duration mean device time on a
        tunneled backend (docs/PERF.md): without the readback the span
        only measures async-dispatch enqueue.  Lazy jax import so
        recorders stay importable jax-free."""
        from qba_tpu.backends.jax_backend import fence as _fence

        _fence(res)
        target = span if span is not None else (
            self.spans[self._stack[-1]] if self._stack else None
        )
        if target is not None:
            target.fenced = True
        return res

    # ---- aggregation -------------------------------------------------
    def totals(self) -> dict[str, dict[str, float]]:
        """Per-name aggregate over CLOSED spans — the PhaseTimers view."""
        agg: dict[str, dict[str, float]] = {}
        for sp in self.spans:
            if sp.dur is None:
                continue
            d = agg.setdefault(sp.name, {"total_s": 0.0, "count": 0})
            d["total_s"] += sp.dur
            d["count"] += 1
        return agg

    # ---- exports -----------------------------------------------------
    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(sp.to_dict()) for sp in self.spans)

    def write_jsonl(self, path: str) -> str:
        with open(path, "w") as f:
            content = self.to_jsonl()
            f.write(content + ("\n" if content else ""))
        return path

    def chrome_trace(self) -> dict[str, Any]:
        """Chrome trace-event JSON: one complete (``ph: "X"``) event per
        span, microsecond timestamps, all on one pid/tid so Perfetto
        nests them by time containment (the recorder's stack discipline
        guarantees proper containment).  A still-open span is exported
        with its duration up to now — a crash mid-run still yields a
        loadable trace."""
        pid = os.getpid()
        now = self._clock()
        events: list[dict[str, Any]] = [
            {
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "name": "process_name",
                "args": {"name": "qba_tpu"},
            }
        ]
        for sp in self.spans:
            dur = sp.dur if sp.dur is not None else now - sp.t0
            args = {k: _jsonable(v) for k, v in sp.args.items()}
            args["fenced"] = sp.fenced
            events.append(
                {
                    "name": sp.name,
                    "cat": sp.cat + (",fenced" if sp.fenced else ""),
                    "ph": "X",
                    "ts": round(sp.t0 * 1e6, 3),
                    "dur": round(dur * 1e6, 3),
                    "pid": pid,
                    "tid": 0,
                    "args": args,
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def write_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f, indent=1)
        return path


def spans_from_jsonl(path: str) -> list[Span]:
    """Reconstruct :class:`Span` objects from a ``write_jsonl`` export.

    The inverse of :meth:`SpanRecorder.to_jsonl`, for cross-process
    aggregation: each fleet replica exports its own span file, and the
    fleet summary merges them back into one list (``index``/``parent``
    stay file-local — only name/dur/args matter to aggregation).
    Malformed lines are skipped: a replica killed mid-write must not
    take the fleet summary down with it."""
    spans: list[Span] = []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return spans
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            d = json.loads(line)
            spans.append(
                Span(
                    name=d["name"],
                    index=int(d.get("index", len(spans))),
                    parent=d.get("parent"),
                    depth=int(d.get("depth", 0)),
                    t0=float(d.get("t0_s", 0.0)),
                    dur=(
                        float(d["dur_s"]) if d.get("dur_s") is not None else None
                    ),
                    cat=d.get("cat", "host"),
                    fenced=bool(d.get("fenced", False)),
                    args=dict(d.get("args") or {}),
                )
            )
        except (json.JSONDecodeError, KeyError, TypeError, ValueError):
            continue
    return spans


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Linear-interpolation percentile over pre-sorted values (numpy's
    default method, reimplemented so latency summaries stay jax/numpy
    free like the rest of this module)."""
    if not sorted_vals:
        raise ValueError("percentile of empty sequence")
    pos = (len(sorted_vals) - 1) * (q / 100.0)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


def span_latency_summary(
    spans: Iterable[Span],
    name: str,
    percentiles: tuple[float, ...] = (50.0, 99.0),
) -> dict[str, Any]:
    """Latency distribution of every closed span named ``name``.

    This is the serving subsystem's p50/p99 instrument: the span tree
    already records one ``request`` span per served request, so the
    latency report is *derived from* the telemetry rather than a second
    bookkeeping path (Dapper's leave-it-on design point — see
    docs/SERVING.md).  Keys: ``count``, ``mean_s``, ``min_s``,
    ``max_s``, and one ``p<q>_s`` per requested percentile."""
    durs = sorted(
        sp.dur for sp in spans if sp.name == name and sp.dur is not None
    )
    summary: dict[str, Any] = {"name": name, "count": len(durs)}
    if not durs:
        return summary
    summary["mean_s"] = sum(durs) / len(durs)
    summary["min_s"] = durs[0]
    summary["max_s"] = durs[-1]
    for q in percentiles:
        summary[f"p{q:g}_s"] = _percentile(durs, q)
    return summary
