"""Cross-process causal tracing: one stitched trace per fleet request.

A request's life crosses process boundaries — frontend intake →
admission → file queue → worker → (sometimes supervisor) → settle —
and each hop keeps time on its own clock.  This module is the glue
(docs/OBSERVABILITY.md "Fleet tracing and metrics"):

* **Identity travels with the trace** (the Dapper lesson, PAPERS.md):
  :func:`mint_trace_id` runs at exactly the registered minting sites
  (the frontend's ``_intake``, the atlas campaign's ``_stamp_trace`` —
  ``qba-tpu lint --obs`` / KI-12 proves there are no others), the id
  rides the queue-file JSON as ``EvalRequest.trace_id``, the worker's
  root span *adopts* it, and supervisor lifecycle events stamp it.
* **Wall-clock anchoring**: :class:`~qba_tpu.obs.telemetry.SpanRecorder`
  timestamps are ``perf_counter`` seconds, meaningless across
  processes.  The serve engine stamps ``t0_epoch`` (``time.time()`` at
  submit) into the root span's args; the stitcher shifts each span
  file onto the epoch axis by ``t0_epoch - root.t0``.
* **No dark time**: the queue wait is *synthesized* from the measured
  ``queue_wait_s`` (producer/claim mtimes, see serve/transport.py) as
  a span ending at the worker's anchor, and the settle-side wait (the
  result sitting in outbox/ until the frontend forwards it) is
  synthesized from the worker end and the settle event — so the union
  of child spans covers the root and coverage below the floor is a
  lint finding, not a shrug (Coz's causal framing: unattributed time
  is time we cannot prove matters).

Everything here is stdlib-only — the frontend imports it and is
statically proven jax-free (KI-6 fleet fence).
"""

from __future__ import annotations

import json
import os
import time
import uuid

from .telemetry import Span, _percentile, spans_from_jsonl

__all__ = [
    "TRACE_CONTEXT_SCHEMA",
    "TRACE_EVENTS_NAME",
    "TraceEventLog",
    "mint_span_id",
    "mint_trace_id",
    "read_trace_events",
    "stitch_traces",
    "stitched_chrome_trace",
    "trace_summary",
]

TRACE_CONTEXT_SCHEMA = "qba-tpu/trace-context/v1"
TRACE_EVENTS_NAME = "trace-events.jsonl"

# Mirrors qba_tpu.serve.engine.REQUEST_SPAN without importing the
# (jax-loading) engine module.
ROOT_SPAN_NAME = "request"

# Lifecycle events a stitched trace understands.  "settle" closes the
# trace; supervisor events render as instants on the lifecycle track.
LIFECYCLE_EVENTS = (
    "intake", "admit", "defer", "reject", "settle",
    "kill", "death", "release", "quarantine",
)


def mint_trace_id() -> str:
    """Mint a fresh trace id.

    Called ONLY at registered request-origin sites (KI-12): everything
    downstream of intake must adopt the id riding the queue file, or
    its spans can never stitch back to the request.
    """
    return uuid.uuid4().hex


def mint_span_id() -> str:
    """A short span id for the intake span (the worker root's parent)."""
    return uuid.uuid4().hex[:16]


# ---------------------------------------------------------------------------
# event log: append-only JSONL beside the queue boxes


class TraceEventLog:
    """Append-only lifecycle event log in the fleet queue directory.

    One line per event, O_APPEND semantics: the frontend and the
    supervisor (threads or processes) interleave whole lines safely.
    Events are wall-clock (``time.time()``) — the same axis the
    stitcher anchors worker spans onto.
    """

    def __init__(self, queue_dir: str):
        self.path = os.path.join(queue_dir, TRACE_EVENTS_NAME)

    def emit(self, event: str, trace_id: str | None,
             request_id: str | None, **fields) -> dict:
        rec = {
            "schema": TRACE_CONTEXT_SCHEMA,
            "event": event,
            "trace_id": trace_id,
            "request_id": request_id,
            "t": time.time(),
        }
        rec.update(fields)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(rec, sort_keys=True) + "\n")
        return rec


def read_trace_events(queue_dir: str) -> list[dict]:
    """All lifecycle events, in emission order; malformed lines skipped."""
    path = os.path.join(queue_dir, TRACE_EVENTS_NAME)
    events: list[dict] = []
    try:
        with open(path, encoding="utf-8") as fh:
            lines = fh.readlines()
    except OSError:
        return events
    for line in lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and rec.get("event"):
            events.append(rec)
    return events


# ---------------------------------------------------------------------------
# stitching


def _worker_segments(telemetry_dir: str | None):
    """Yield (trace_id | None, segment) per exported spans.jsonl.

    A segment is the one worker-side execution of a request: its spans
    shifted onto the epoch axis via the root's ``t0_epoch`` anchor.
    Files without a root span or without an anchor yield trace_id None
    — the caller counts their spans as orphans.
    """
    if not telemetry_dir or not os.path.isdir(telemetry_dir):
        return
    for entry in sorted(os.listdir(telemetry_dir)):
        path = os.path.join(telemetry_dir, entry, "spans.jsonl")
        spans = spans_from_jsonl(path)
        if not spans:
            continue
        root = next(
            (s for s in spans
             if s.name == ROOT_SPAN_NAME and s.parent is None), None)
        anchor = (root.args.get("t0_epoch")
                  if root is not None else None)
        trace_id = (root.args.get("trace_id")
                    if root is not None else None)
        if root is None or anchor is None or root.dur is None:
            yield None, {"entry": entry, "spans": spans}
            continue
        offset = float(anchor) - root.t0
        shifted = [
            Span(name=s.name, index=s.index, parent=s.parent,
                 depth=s.depth, t0=s.t0 + offset, dur=s.dur,
                 cat=s.cat, fenced=s.fenced, args=s.args)
            for s in spans if s.dur is not None
        ]
        yield trace_id, {
            "entry": entry,
            "spans": shifted,
            "root_t0": root.t0 + offset,
            "root_end": root.t0 + offset + root.dur,
            "replica_id": root.args.get("replica_id"),
            "queue_wait_s": root.args.get("queue_wait_s"),
            "request_id": root.args.get("request_id"),
        }


def _union_length(intervals: list[tuple[float, float]]) -> float:
    total = 0.0
    end = -float("inf")
    for lo, hi in sorted(intervals):
        if hi <= end:
            continue
        total += hi - max(lo, end)
        end = hi
    return total


def stitch_traces(queue_dir: str,
                  telemetry_dir: str | None = None) -> dict:
    """Stitch lifecycle events + worker span files into causal traces.

    Returns ``{"traces": {trace_id: trace}, "orphan_spans": int}``.
    Each trace holds wall-clock ``spans`` (dicts: name/t0/dur/track/
    args), instant ``events``, ``closed`` (a settle event exists), and
    ``coverage`` (union of child spans over the root interval) when
    computable.  Orphans are worker spans that cannot be attributed to
    any intaken request — the fleet-summary ``traces`` block asserts
    this count is zero.
    """
    events = read_trace_events(queue_dir)
    by_trace: dict[str, list[dict]] = {}
    for ev in events:
        tid = ev.get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(ev)

    segments: dict[str, list[dict]] = {}
    orphan_spans = 0
    for tid, seg in _worker_segments(telemetry_dir):
        if tid is None or tid not in by_trace:
            orphan_spans += len(seg["spans"])
            continue
        segments.setdefault(tid, []).append(seg)

    traces: dict[str, dict] = {}
    for tid, evs in by_trace.items():
        evs = sorted(evs, key=lambda e: e.get("t", 0.0))
        intake = next((e for e in evs if e["event"] == "intake"), None)
        settle = next((e for e in evs if e["event"] == "settle"), None)
        segs = sorted(segments.get(tid, []),
                      key=lambda s: s["root_t0"])
        request_id = (intake or (evs and evs[0]) or {}).get("request_id")
        t_in = intake["t"] if intake else (
            segs[0]["root_t0"] if segs else evs[0]["t"])
        ends = [e["t"] for e in evs] + [s["root_end"] for s in segs]
        t_out = settle["t"] if settle else max(ends)
        t_out = max(t_out, t_in)

        spans: list[dict] = [{
            "name": ROOT_SPAN_NAME, "t0": t_in,
            "dur": t_out - t_in, "track": "lifecycle",
            "args": {"trace_id": tid, "request_id": request_id},
        }]
        children: list[tuple[float, float]] = []

        decision = next(
            (e for e in evs if e["event"] in ("admit", "defer", "reject")),
            None)
        if intake and decision and decision["t"] >= intake["t"]:
            spans.append({
                "name": "frontend.admission", "t0": intake["t"],
                "dur": decision["t"] - intake["t"],
                "track": "lifecycle",
                "args": {k: decision.get(k)
                         for k in ("event", "reason") if k in decision},
            })
            children.append((intake["t"], decision["t"]))

        for seg in segs:
            track = seg.get("replica_id") or seg["entry"]
            qw = seg.get("queue_wait_s")
            if qw is not None:
                spans.append({
                    "name": "queue.wait",
                    "t0": seg["root_t0"] - float(qw),
                    "dur": float(qw), "track": "lifecycle",
                    "args": {"queue_wait_s": qw},
                })
                children.append(
                    (seg["root_t0"] - float(qw), seg["root_t0"]))
            for s in seg["spans"]:
                spans.append({
                    "name": s.name, "t0": s.t0, "dur": s.dur,
                    "track": track, "depth": s.depth, "cat": s.cat,
                    "args": s.args,
                })
            children.append((seg["root_t0"], seg["root_end"]))

        if segs and settle and settle["t"] > segs[-1]["root_end"]:
            spans.append({
                "name": "queue.result_wait",
                "t0": segs[-1]["root_end"],
                "dur": settle["t"] - segs[-1]["root_end"],
                "track": "lifecycle", "args": {},
            })
            children.append((segs[-1]["root_end"], settle["t"]))

        coverage = None
        if t_out > t_in and children:
            clipped = [(max(lo, t_in), min(hi, t_out))
                       for lo, hi in children
                       if min(hi, t_out) > max(lo, t_in)]
            coverage = _union_length(clipped) / (t_out - t_in)

        traces[tid] = {
            "trace_id": tid,
            "request_id": request_id,
            "t0": t_in,
            "dur": t_out - t_in,
            "closed": settle is not None,
            "coverage": coverage,
            "spans": spans,
            "events": evs,
            "segments": len(segs),
        }
    return {"traces": traces, "orphan_spans": orphan_spans}


def trace_summary(stitched: dict) -> dict:
    """The fleet-summary ``traces`` block, from stitched traces."""
    traces = stitched["traces"]
    coverages = sorted(
        t["coverage"] for t in traces.values()
        if t["coverage"] is not None)
    block = {
        "count": len(traces),
        "closed": sum(1 for t in traces.values() if t["closed"]),
        "open": sum(1 for t in traces.values() if not t["closed"]),
        "orphan_spans": stitched["orphan_spans"],
        "coverage": None,
    }
    if coverages:
        block["coverage"] = {
            "count": len(coverages),
            "p50": _percentile(coverages, 50.0),
            "p99": _percentile(coverages, 99.0),
            "min": coverages[0],
        }
    return block


# ---------------------------------------------------------------------------
# Perfetto / Chrome trace-event export


def stitched_chrome_trace(stitched: dict,
                          trace_ids: list[str] | None = None) -> dict:
    """Chrome trace-event JSON for Perfetto: one process per trace,
    one thread per track (lifecycle + each worker segment), instant
    events for supervisor lifecycle stamps."""
    events: list[dict] = []
    traces = stitched["traces"]
    ids = trace_ids if trace_ids is not None else sorted(traces)
    for pid, tid in enumerate(ids, start=1):
        trace = traces[tid]
        label = trace.get("request_id") or tid
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"request {label} [{tid[:8]}]"},
        })
        tracks: dict[str, int] = {}

        def _tid(track: str, tracks=tracks, pid=pid,
                 events=events) -> int:
            if track not in tracks:
                tracks[track] = len(tracks)
                events.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": tracks[track], "args": {"name": track},
                })
            return tracks[track]

        for span in trace["spans"]:
            events.append({
                "ph": "X", "name": span["name"],
                "cat": span.get("cat", "lifecycle"),
                "pid": pid, "tid": _tid(span["track"]),
                "ts": round(span["t0"] * 1e6, 3),
                "dur": round(max(span["dur"], 0.0) * 1e6, 3),
                "args": span.get("args", {}),
            })
        for ev in trace["events"]:
            events.append({
                "ph": "i", "s": "p", "name": f"fleet.{ev['event']}",
                "cat": "lifecycle", "pid": pid, "tid": _tid("lifecycle"),
                "ts": round(ev["t"] * 1e6, 3),
                "args": {k: v for k, v in ev.items()
                         if k not in ("t", "schema")},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
