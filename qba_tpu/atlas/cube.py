"""Campaign spec + 4-D cube enumeration.

A campaign is the cube (parties × dishonest × strategy × noise) at one
protocol depth ``size_l``, one seed, and one precision target.  This
module turns a :class:`CampaignSpec` into the deterministic, deduped
list of :class:`AtlasCell`\\ s the driver admits — each cell carrying
the validated :class:`~qba_tpu.config.QBAConfig`, its sweep-dialect
config fingerprint, and the content-address key the store files it
under.

Determinism contract: ``enumerate_cells`` is a pure function of the
spec — same spec, same cell list in the same order, with the same
keys.  Campaign resume depends on this: a restarted driver re-derives
the cube and reconciles it against the ledger instead of trusting any
in-memory state.
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
from typing import Any, Sequence

from qba_tpu.atlas.store import canonical_json, cell_key
from qba_tpu.serve.request import EvalRequest

CAMPAIGN_SPEC_SCHEMA = "qba-tpu/atlas-spec/v1"


@dataclasses.dataclass(frozen=True)
class CampaignSpec:
    """The four axes plus execution policy for one atlas campaign.

    ``dishonest`` entries are either absolute traitor counts (integral
    values) or fractions of ``n_parties`` (values in (0, 1), floored
    per party count — ``1/3`` enumerates the paper's resilience
    boundary at every n).  Entries exceeding a given ``n`` are skipped
    for that n; duplicates collapsing to the same (n, d) are deduped.

    ``budget_trials`` is the wave-0 per-cell trial budget; a cell whose
    stopping rule is still unresolved at budget exhaustion escalates:
    its budget multiplies by ``escalation`` up to ``max_escalations``
    times before the campaign records an explicit truncation refusal.
    Frontier cells are exactly the ones that escalate — the allocator's
    straddling tier ranks them first (see :mod:`qba_tpu.atlas.steer`).
    """

    parties: tuple[int, ...]
    dishonest: tuple[float, ...]
    strategies: tuple[str, ...] = ("reference",)
    noise_points: tuple[tuple[float, float], ...] = ((0.0, 0.0),)
    size_l: int = 4
    seed: int = 0
    chunk_trials: int = 256
    budget_trials: int = 1024
    escalation: float = 4.0
    max_escalations: int = 2
    target: str = "decide vs 1/3 @ 95%"
    qsim_path: str = "factorized"
    round_engine: str = "auto"

    def __post_init__(self) -> None:
        if not self.parties:
            raise ValueError("campaign needs at least one party count")
        if not self.dishonest:
            raise ValueError("campaign needs at least one dishonest value")
        if self.budget_trials < 1:
            raise ValueError(f"budget_trials must be >= 1, got {self.budget_trials}")
        if self.escalation < 1.0:
            raise ValueError(f"escalation must be >= 1, got {self.escalation}")
        if self.max_escalations < 0:
            raise ValueError(
                f"max_escalations must be >= 0, got {self.max_escalations}"
            )
        # Parse eagerly so an unparseable target fails at spec build,
        # not mid-campaign on the first admission.
        from qba_tpu.stats.targets import parse_target

        parse_target(self.target)

    def to_json(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["schema"] = CAMPAIGN_SPEC_SCHEMA
        return d

    @classmethod
    def from_json(cls, payload: dict[str, Any]) -> "CampaignSpec":
        data = dict(payload)
        schema = data.pop("schema", CAMPAIGN_SPEC_SCHEMA)
        if schema != CAMPAIGN_SPEC_SCHEMA:
            raise ValueError(
                f"bad campaign spec schema {schema!r}; "
                f"expected {CAMPAIGN_SPEC_SCHEMA}"
            )
        for key in ("parties", "dishonest", "strategies"):
            if key in data:
                data[key] = tuple(data[key])
        if "noise_points" in data:
            data["noise_points"] = tuple(
                (float(p), float(q)) for p, q in data["noise_points"]
            )
        return cls(**data)

    def campaign_key(self) -> str:
        """Identity of the campaign itself (ledger ownership check): a
        short hash of the canonicalized spec.  A ledger written by a
        different spec must not be resumed into — same refusal
        discipline as ``QBACheckpointMismatch`` in the sweep layer."""
        return hashlib.sha256(
            canonical_json(self.to_json()).encode()
        ).hexdigest()[:16]


@dataclasses.dataclass(frozen=True)
class AtlasCell:
    """One enumerated cube cell: content-address key, human-facing
    coordinates, the validated base config, and its fingerprint."""

    key: str
    coords: dict[str, Any]
    config: Any  # QBAConfig — typed loosely to keep this module light
    fingerprint: dict[str, Any]


def parse_dishonest(tokens: Sequence[str]) -> tuple[float, ...]:
    """CLI-side parse of the dishonest axis: ``"0" "2" "1/3" "0.4"`` —
    integral values are counts, fractions/floats in (0, 1) scale with
    the party count."""
    out: list[float] = []
    for tok in tokens:
        text = str(tok).strip()
        if "/" in text:
            num, _, den = text.partition("/")
            try:
                val = float(num) / float(den)
            except (ValueError, ZeroDivisionError):
                raise ValueError(f"bad dishonest value {tok!r}") from None
        else:
            try:
                val = float(text)
            except ValueError:
                raise ValueError(f"bad dishonest value {tok!r}") from None
        if val < 0:
            raise ValueError(f"dishonest value must be >= 0, got {tok!r}")
        out.append(val)
    return tuple(out)


def resolve_dishonest(n_parties: int, dishonest: Sequence[float]) -> list[int]:
    """Concrete traitor counts for one party count: counts pass
    through, fractions floor, out-of-range values drop, duplicates
    dedup — ascending order."""
    counts: set[int] = set()
    for d in dishonest:
        if 0 < float(d) < 1:
            c = int(math.floor(n_parties * float(d)))
        else:
            c = int(d)
            if c != d:
                raise ValueError(
                    f"dishonest value {d!r} is neither a count nor a "
                    "fraction in (0, 1)"
                )
        if 0 <= c <= n_parties:
            counts.add(c)
    return sorted(counts)


def enumerate_cells(spec: CampaignSpec) -> list[AtlasCell]:
    """The deduped cube, in deterministic (parties, dishonest,
    strategy, noise) lexicographic order.  Each cell's config is
    validated at enumeration time — an invalid combination fails the
    whole campaign here, before anything is admitted."""
    from qba_tpu.config import QBAConfig

    cells: list[AtlasCell] = []
    seen: set[str] = set()
    for n in spec.parties:
        for d in resolve_dishonest(n, spec.dishonest):
            for strat in spec.strategies:
                for p_dep, p_mf in spec.noise_points:
                    cfg = QBAConfig(
                        n_parties=n,
                        size_l=spec.size_l,
                        n_dishonest=d,
                        trials=spec.budget_trials,
                        seed=spec.seed,
                        qsim_path=spec.qsim_path,
                        round_engine=spec.round_engine,
                        strategy=strat,
                        p_depolarize=p_dep,
                        p_measure_flip=p_mf,
                    )
                    fp = dataclasses.asdict(cfg)
                    fp.pop("trials", None)
                    key = cell_key(fp)
                    if key in seen:
                        continue
                    seen.add(key)
                    cells.append(
                        AtlasCell(
                            key=key,
                            coords={
                                "n_parties": n,
                                "n_dishonest": d,
                                "strategy": strat,
                                "p_depolarize": p_dep,
                                "p_measure_flip": p_mf,
                                "size_l": spec.size_l,
                            },
                            config=cfg,
                            fingerprint=fp,
                        )
                    )
    return cells


def attempt_trials(spec: CampaignSpec, attempt: int) -> int:
    """Trial budget for escalation wave ``attempt`` (0-based):
    ``budget_trials * escalation**attempt``, rounded up to a whole
    number of chunks so the device chunk ladder stays aligned across
    waves."""
    raw = spec.budget_trials * (spec.escalation ** attempt)
    chunks = max(1, math.ceil(raw / spec.chunk_trials))
    return chunks * spec.chunk_trials


def request_id_for(cell_key_: str, attempt: int) -> str:
    """Deterministic, slug-safe request id for one cell attempt — a
    resumed driver re-derives the id and recognizes in-flight or
    already-landed results for it."""
    return f"atlas-{cell_key_}-a{attempt}"


def build_request(
    cell: AtlasCell, spec: CampaignSpec, attempt: int
) -> EvalRequest:
    """The targeted :class:`EvalRequest` for one cell attempt.  The
    request's trial count is the attempt's budget ceiling; its target
    makes the server stop early once the rule fires — admission prices
    the *target* (``Target.planning_trials``), not the ceiling."""
    return EvalRequest(
        request_id=request_id_for(cell.key, attempt),
        n_parties=cell.coords["n_parties"],
        size_l=spec.size_l,
        n_dishonest=cell.coords["n_dishonest"],
        trials=attempt_trials(spec, attempt),
        seed=spec.seed,
        round_engine=spec.round_engine,
        qsim_path=spec.qsim_path,
        strategy=cell.coords["strategy"],
        p_depolarize=cell.coords["p_depolarize"],
        p_measure_flip=cell.coords["p_measure_flip"],
        target=spec.target,
    )
