"""Content-addressed atlas store + campaign ledger (jax-free by design).

The atlas store is the artifact ROADMAP items 3 and 4 consume: one
validated record per (config, strategy, noise) cell, **keyed by the
cell's config fingerprint** (the Dapper lesson from PAPERS.md —
identity travels from request through manifest to the rendered atlas).
Filenames are derived from the fingerprint hash and pass through the
hardened :func:`qba_tpu.serve.queuefs.request_slug`, so cell records
produced by independent campaigns (or by independent ``run_surface``
runs) merge into one store directory without renames: identical
configs land on identical filenames, distinct configs cannot collide
(sha256 content addressing under an injective slug).

Two schemas live here:

* ``qba-tpu/atlas-cell/v1`` — one cell's certified (or explicitly
  refused) estimate: coords, config fingerprint, target, stop
  decision, anytime-valid CI, attempts, refusal evidence, plus a
  *provenance* block (replica attribution, latencies, wall time) and
  the full run manifest.  Provenance and manifest are excluded from
  the store digest — the digest covers exactly the identity-bearing
  content (cell set, configs, stop decisions, estimates), which is
  what the campaign resume differential pins bit-identical.
* ``qba-tpu/atlas-campaign/v1`` — the campaign ledger: the campaign
  spec, per-cell status (pending/submitted/certified/refused),
  attempt + budget state, the last admission decision per cell, and
  the frontier-steering trace.  The driver rewrites it atomically
  after every state change; a ``kill -9`` of the driver resumes from
  it, re-admitting only uncertified cells.

No jax anywhere in this module: the campaign driver, the KI-11 lint,
and the examples' cache-read path must all be importable without
touching a device (same discipline as :mod:`qba_tpu.serve.queuefs`).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Iterator

from qba_tpu.serve.queuefs import request_slug, write_json_atomic

CELL_SCHEMA = "qba-tpu/atlas-cell/v1"
LEDGER_SCHEMA = "qba-tpu/atlas-campaign/v1"

#: Cell record statuses.  ``certified`` — the stopping rule met the
#: target; ``refused`` — an explicit refusal/truncation finding
#: (admission reject, engine error, quarantine, or budget exhausted
#: after every escalation) with the evidence attached; ``uncertified``
#: — a fixed-budget estimate with a CI but no target (``run_surface``
#: without ``target=`` writes these; a campaign never does).
CELL_STATUSES = ("certified", "refused", "uncertified")

#: Cell-ledger statuses a campaign moves through, in order.
LEDGER_STATUSES = ("pending", "submitted", "certified", "refused")

#: Keys of a cell record that carry identity (everything the resume
#: differential compares); the rest — ``manifest``, ``provenance`` —
#: is attribution and may legitimately differ between two runs that
#: produced the same science.
IDENTITY_KEYS = (
    "schema",
    "cell_key",
    "coords",
    "config",
    "target",
    "chunk_trials",
    "status",
    "stop",
    "ci",
    "successes",
    "n_trials",
    "attempts",
    "refusal",
)


def canonical_json(obj: Any) -> str:
    """Deterministic serialization: sorted keys, no whitespace — the
    single recipe behind every hash in this module."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def _normalize_fingerprint(fingerprint: dict[str, Any]) -> dict[str, Any]:
    """Drop the non-identity keys both fingerprint dialects may carry:
    ``trials`` is chunk sizing (sweep's checkpoint rule), ``derived``
    is recomputable shape arithmetic (the manifest dialect)."""
    fp = dict(fingerprint)
    fp.pop("trials", None)
    fp.pop("derived", None)
    return fp


def cell_key(fingerprint: dict[str, Any]) -> str:
    """The content address of one cell: a short sha256 of the
    canonicalized config fingerprint (minus ``trials``/``derived``).
    Accepts both the sweep fingerprint (``dataclasses.asdict`` minus
    trials) and the manifest fingerprint (same plus ``derived``) and
    maps them to the same key — a request and its manifest agree on
    identity by construction."""
    return hashlib.sha256(
        canonical_json(_normalize_fingerprint(fingerprint)).encode()
    ).hexdigest()[:16]


def cell_slug(fingerprint: dict[str, Any]) -> str:
    """Filesystem name stem for one cell: ``cell-<key>`` passed through
    the hardened injective :func:`request_slug` (NAME_MAX-safe,
    collision-checked sanitization) — shared by the store, the
    ``run_surface`` checkpoint layout, and campaign request ids."""
    return request_slug(f"cell-{cell_key(fingerprint)}")


def identity_view(record: dict[str, Any]) -> dict[str, Any]:
    """The identity-bearing subset of a cell record (see
    :data:`IDENTITY_KEYS`)."""
    return {k: record.get(k) for k in IDENTITY_KEYS}


def validate_cell_record(record: dict[str, Any]) -> dict[str, Any]:
    """Schema-check one cell record; returns it on success, raises
    ``ValueError`` naming the defect otherwise (the KI-11 lint turns
    these into findings)."""
    if not isinstance(record, dict):
        raise ValueError(f"cell record must be an object, got {type(record)}")
    if record.get("schema") != CELL_SCHEMA:
        raise ValueError(
            f"bad cell schema {record.get('schema')!r}; expected {CELL_SCHEMA}"
        )
    missing = [k for k in IDENTITY_KEYS if k not in record]
    if missing:
        raise ValueError(f"cell record missing keys {missing}")
    status = record["status"]
    if status not in CELL_STATUSES:
        raise ValueError(
            f"unknown cell status {status!r}; one of {CELL_STATUSES}"
        )
    if not isinstance(record["config"], dict):
        raise ValueError("cell 'config' must be the config fingerprint dict")
    want = cell_key(record["config"])
    if record["cell_key"] != want:
        raise ValueError(
            f"content-address violation: cell_key {record['cell_key']!r} "
            f"!= fingerprint key {want!r} — the record does not describe "
            "the config it is filed under"
        )
    if status == "certified":
        stop = record.get("stop")
        if not isinstance(stop, dict):
            raise ValueError("certified cell carries no stop decision")
        if stop.get("reason") not in ("decided_above", "decided_below", "ci_width"):
            raise ValueError(
                f"certified cell stopped with {stop.get('reason')!r} — "
                "only decided_above/decided_below/ci_width certify a target"
            )
    if status == "refused":
        refusal = record.get("refusal")
        if not isinstance(refusal, dict) or not refusal.get("reason"):
            raise ValueError(
                "refused cell carries no refusal evidence (need at least "
                "{'reason': ...})"
            )
    ci = record.get("ci")
    if ci is not None and not {"lo", "hi"} <= set(ci):
        raise ValueError(
            "cell 'ci' lacks lo/hi — uncertified rates are the KI-8 "
            "failure mode the atlas exists to prevent"
        )
    return record


def record_satisfies(record: dict[str, Any], target) -> bool:
    """Does a certified record answer a query at ``target`` (a
    :class:`qba_tpu.stats.Target` or the grammar string)?  This is the
    item-3 cache-hit predicate: an estimate certified at >= the
    queried confidence answers any *weaker* question for free —
    a decide query is answered when the CI excludes its threshold, a
    width query when the CI is at least as tight."""
    if record.get("status") != "certified":
        return False
    ci = record.get("ci")
    if not isinstance(ci, dict) or not {"lo", "hi"} <= set(ci):
        return False
    from qba_tpu.stats.targets import parse_target

    want = parse_target(target) if isinstance(target, str) else target
    have_conf = float(ci.get("confidence", 0.0))
    if have_conf + 1e-12 < want.confidence:
        return False
    lo, hi = float(ci["lo"]), float(ci["hi"])
    if want.kind == "decide":
        # The stop decision is the certificate: an e-value rule can
        # decide against a threshold before the (conservative) anytime
        # CI excludes it, so a decided stop at the same threshold
        # answers the question even when the CI straddles it.
        stop = record.get("stop") or {}
        if (
            stop.get("reason") in ("decided_above", "decided_below")
            and abs(float(stop.get("threshold", -1.0)) - want.threshold)
            <= 1e-9
        ):
            return True
        return lo > want.threshold or hi < want.threshold
    return (hi - lo) <= want.width + 1e-12


class AtlasCollision(ValueError):
    """Two distinct config fingerprints mapped to one cell filename —
    content addressing refuses to overwrite one with the other."""


class AtlasStore:
    """One atlas store directory: ``cells/`` of content-addressed
    records, ``ledger.json`` (campaign state), ``atlas.json`` (the
    rendered phase diagram)."""

    def __init__(self, root: str) -> None:
        self.root = root
        self.cells_dir = os.path.join(root, "cells")
        self.ledger_path = os.path.join(root, "ledger.json")
        self.atlas_path = os.path.join(root, "atlas.json")
        os.makedirs(self.cells_dir, exist_ok=True)

    # ---- cells -------------------------------------------------------
    def cell_path(self, key: str) -> str:
        return os.path.join(
            self.cells_dir, request_slug(f"cell-{key}") + ".json"
        )

    def write_cell(self, record: dict[str, Any]) -> str:
        """Validate + atomically publish one cell record; returns the
        path.  Collision-checked: an existing record under the same
        filename must describe the same config fingerprint (same
        campaign re-certifying a cell overwrites it; a *different*
        config under the same name is refused loudly)."""
        validate_cell_record(record)
        path = self.cell_path(record["cell_key"])
        existing = self._read(path)
        if existing is not None:
            theirs = _normalize_fingerprint(existing.get("config") or {})
            ours = _normalize_fingerprint(record["config"])
            if theirs != ours:
                raise AtlasCollision(
                    f"{path} already holds a record for a different config "
                    f"({canonical_json(theirs)[:120]} != "
                    f"{canonical_json(ours)[:120]}) — refusing to overwrite"
                )
        write_json_atomic(path, record)
        return path

    def load_cell(self, key: str) -> dict[str, Any] | None:
        return self._read(self.cell_path(key))

    def lookup(self, fingerprint: dict[str, Any], target=None):
        """The cache-read path (seed of the ROADMAP item-3 tier): the
        certified record answering this config fingerprint at
        ``target``, else None.  With no target any certified record
        for the config hits; with one, :func:`record_satisfies`
        decides — a stronger certificate answers a weaker question."""
        rec = self.load_cell(cell_key(fingerprint))
        if rec is None or rec.get("status") != "certified":
            return None
        if target is not None and not record_satisfies(rec, target):
            return None
        return rec

    def iter_cells(self) -> Iterator[tuple[str, dict[str, Any]]]:
        """(filename, record) for every readable cell file, sorted by
        name — deterministic iteration order for digests and renders."""
        if not os.path.isdir(self.cells_dir):
            return
        for name in sorted(os.listdir(self.cells_dir)):
            if not name.endswith(".json"):
                continue
            rec = self._read(os.path.join(self.cells_dir, name))
            if rec is not None:
                yield name, rec

    def digest(self) -> str:
        """sha256 over the identity view of every cell, in filename
        order.  Two stores with the same digest agree on the cell set,
        per-cell configs, stop decisions, and estimates — the
        bit-identity the campaign resume differential asserts.
        Provenance (timestamps, replica attribution, environment
        blocks) is excluded by construction."""
        h = hashlib.sha256()
        for name, rec in self.iter_cells():
            h.update(name.encode())
            h.update(canonical_json(identity_view(rec)).encode())
        return h.hexdigest()

    # ---- ledger ------------------------------------------------------
    def load_ledger(self) -> dict[str, Any] | None:
        led = self._read(self.ledger_path)
        if led is None:
            return None
        if led.get("schema") != LEDGER_SCHEMA:
            raise ValueError(
                f"{self.ledger_path}: bad ledger schema "
                f"{led.get('schema')!r}; expected {LEDGER_SCHEMA}"
            )
        return led

    def save_ledger(self, ledger: dict[str, Any]) -> None:
        assert ledger.get("schema") == LEDGER_SCHEMA, ledger.get("schema")
        write_json_atomic(self.ledger_path, ledger)

    # ---- plumbing ----------------------------------------------------
    @staticmethod
    def _read(path: str) -> dict[str, Any] | None:
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        return payload if isinstance(payload, dict) else None


def record_from_surface_cell(
    cell, target, chunk_trials: int
) -> dict[str, Any]:
    """Build a store record from one :class:`qba_tpu.sweep.SurfaceCell`
    — the merge path for independently produced ``run_surface`` cells
    (satellite of ISSUE 19): targeted cells certify or refuse exactly
    like campaign cells; untargeted cells land as ``uncertified``
    fixed-budget estimates."""
    res = cell.result
    cfg = res.cfg
    import dataclasses as _dc

    fp = _dc.asdict(cfg)
    fp.pop("trials", None)
    stop = res.stop.to_json() if res.stop is not None else None
    est = res.estimators().success.estimate()
    status = "uncertified"
    refusal = None
    target_spec = None
    if target is not None:
        target_spec = target if isinstance(target, str) else target.spec
        if stop is not None and stop["reason"] in (
            "decided_above", "decided_below", "ci_width"
        ):
            status = "certified"
            est_json = stop["estimate"] or est.to_json()
        else:
            status = "refused"
            refusal = {
                "reason": "budget_exhausted",
                "detail": (
                    f"stopping rule unresolved after {res.n_trials} trials"
                ),
            }
            est_json = (stop or {}).get("estimate") or est.to_json()
    else:
        est_json = est.to_json()
    return {
        "schema": CELL_SCHEMA,
        "cell_key": cell_key(fp),
        "coords": {
            "n_parties": cfg.n_parties,
            "n_dishonest": cfg.n_dishonest,
            "strategy": cell.strategy,
            "p_depolarize": cell.p_depolarize,
            "p_measure_flip": cell.p_measure_flip,
            "size_l": cell.size_l,
        },
        "config": fp,
        "target": target_spec,
        "chunk_trials": chunk_trials,
        "status": status,
        "stop": stop,
        "ci": est_json,
        "successes": res.successes,
        "n_trials": res.n_trials,
        "attempts": 1,
        "refusal": refusal,
        "provenance": {"producer": "run_surface"},
        "manifest": cell.manifest,
    }
