"""Render the atlas: per-slice validity surfaces + the measured KI-7 fence.

The rendered atlas (``atlas.json``, schema ``qba-tpu/atlas/v1``) is the
phase diagram the campaign exists to produce: for every (strategy,
noise, size_l) slice, a (n_parties × n_dishonest) grid of certified
success rates with their anytime-valid CI bands, each point flagged
frontier/interior; plus the KI-7 noise-detectability fence as a
**measured curve**: the all-honest (d = 0) false-abort rate across the
noise axis with confidence bands, against the documented per-bit flip
probability ``pflip = (2p/3)(1 − q) + q(1 − 2p/3)``.  KI-7's claim —
detection is unsound off the zero-noise slice — stops being a
documented estimate and becomes data.

Per-slice width accounting backs the frontier-steering acceptance
check: frontier cells (CI straddling the threshold, or refused on
budget) escalate until they resolve or exhaust, so their CI widths end
at or below the interior cells that certified on a coarse wave-0 CI.
``render_atlas`` computes both maxima per slice; the KI-11 lint turns
a violation into a finding.

Plotting is optional and import-gated (matplotlib is not a
dependency): :func:`plot_slices` writes one PNG per slice plus the
fence when matplotlib is importable, and reports cleanly when not.
"""

from __future__ import annotations

from typing import Any

from qba_tpu.atlas.steer import is_frontier
from qba_tpu.atlas.store import AtlasStore
from qba_tpu.serve.queuefs import write_json_atomic

ATLAS_SCHEMA = "qba-tpu/atlas/v1"


def measured_pflip(p_depolarize: float, p_measure_flip: float) -> float:
    """Per-measured-bit flip probability under both channels — the
    KI-7 composition (docs/KNOWN_ISSUES.md)."""
    p, q = p_depolarize, p_measure_flip
    return (2.0 * p / 3.0) * (1.0 - q) + q * (1.0 - 2.0 * p / 3.0)


def _slice_key(coords: dict[str, Any]) -> tuple:
    return (
        str(coords.get("strategy")),
        float(coords.get("p_depolarize", 0.0)),
        float(coords.get("p_measure_flip", 0.0)),
        int(coords.get("size_l", 0)),
    )


def render_atlas(
    store: AtlasStore, target: str | None = None
) -> dict[str, Any]:
    """Build (and atomically write) ``atlas.json`` from every cell in
    the store.  ``target`` defaults to the store ledger's campaign
    target; without either, frontier classification is skipped (every
    cell renders as interior)."""
    if target is None:
        led = store.load_ledger()
        if led is not None:
            target = (led.get("campaign") or {}).get("target")
    slices: dict[tuple, dict[str, Any]] = {}
    fence_points: dict[tuple, list[dict[str, Any]]] = {}
    total = 0
    for _name, rec in store.iter_cells():
        total += 1
        coords = rec.get("coords") or {}
        skey = _slice_key(coords)
        sl = slices.setdefault(
            skey,
            {
                "strategy": skey[0],
                "p_depolarize": skey[1],
                "p_measure_flip": skey[2],
                "size_l": skey[3],
                "points": [],
            },
        )
        ci = rec.get("ci") or {}
        lo = ci.get("lo")
        hi = ci.get("hi")
        width = (
            float(hi) - float(lo)
            if lo is not None and hi is not None
            else None
        )
        frontier = bool(target) and is_frontier(rec, target)
        sl["points"].append(
            {
                "n_parties": coords.get("n_parties"),
                "n_dishonest": coords.get("n_dishonest"),
                "status": rec.get("status"),
                "rate": ci.get("rate"),
                "lo": lo,
                "hi": hi,
                "ci_width": width,
                "n_trials": rec.get("n_trials"),
                "attempts": rec.get("attempts"),
                "frontier": frontier,
                "refusal": (rec.get("refusal") or {}).get("reason"),
            }
        )
        # KI-7 fence: the all-honest column, across noise.  The fence
        # is about *false aborts* — agreement failing with zero
        # traitors — so the y-axis is 1 - success with flipped bands.
        if coords.get("n_dishonest") == 0 and rec.get("status") != "refused":
            fkey = (
                str(coords.get("strategy")),
                int(coords.get("size_l", 0)),
                int(coords.get("n_parties", 0)),
            )
            point = {
                "p_depolarize": skey[1],
                "p_measure_flip": skey[2],
                "pflip": measured_pflip(skey[1], skey[2]),
                "n_trials": rec.get("n_trials"),
            }
            if lo is not None and hi is not None and ci.get("rate") is not None:
                point["false_abort_rate"] = 1.0 - float(ci["rate"])
                point["lo"] = 1.0 - float(hi)
                point["hi"] = 1.0 - float(lo)
            fence_points.setdefault(fkey, []).append(point)
    out_slices = []
    for skey in sorted(slices):
        sl = slices[skey]
        sl["points"].sort(
            key=lambda p: (p["n_parties"] or 0, p["n_dishonest"] or 0)
        )
        fw = [
            p["ci_width"] for p in sl["points"]
            if p["frontier"] and p["ci_width"] is not None
        ]
        iw = [
            p["ci_width"] for p in sl["points"]
            if not p["frontier"] and p["ci_width"] is not None
        ]
        sl["frontier_cells"] = sum(1 for p in sl["points"] if p["frontier"])
        sl["frontier_max_width"] = max(fw) if fw else None
        sl["interior_max_width"] = max(iw) if iw else None
        sl["widths_ok"] = (
            sl["frontier_max_width"] <= sl["interior_max_width"] + 1e-12
            if fw and iw
            else True
        )
        out_slices.append(sl)
    fences = []
    for fkey in sorted(fence_points):
        pts = sorted(fence_points[fkey], key=lambda p: p["pflip"])
        fences.append(
            {
                "strategy": fkey[0],
                "size_l": fkey[1],
                "n_parties": fkey[2],
                "points": pts,
            }
        )
    atlas = {
        "schema": ATLAS_SCHEMA,
        "target": target,
        "cells": total,
        "store_digest": store.digest(),
        "slices": out_slices,
        "ki7_fence": fences,
    }
    write_json_atomic(store.atlas_path, atlas)
    return atlas


def plot_slices(store: AtlasStore, out_dir: str) -> list[str]:
    """PNG renders (one heatmap per slice + one fence figure); returns
    the written paths, or [] when matplotlib is unavailable."""
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        return []
    import os

    atlas = render_atlas(store)
    os.makedirs(out_dir, exist_ok=True)
    written: list[str] = []
    for i, sl in enumerate(atlas["slices"]):
        parties = sorted({p["n_parties"] for p in sl["points"]})
        dish = sorted({p["n_dishonest"] for p in sl["points"]})
        grid = [[float("nan")] * len(dish) for _ in parties]
        for p in sl["points"]:
            if p["rate"] is not None:
                grid[parties.index(p["n_parties"])][
                    dish.index(p["n_dishonest"])
                ] = p["rate"]
        fig, ax = plt.subplots(figsize=(6, 4))
        im = ax.imshow(
            grid, origin="lower", aspect="auto", vmin=0.0, vmax=1.0,
            cmap="viridis",
        )
        ax.set_xticks(range(len(dish)), [str(d) for d in dish])
        ax.set_yticks(range(len(parties)), [str(n) for n in parties])
        ax.set_xlabel("n_dishonest")
        ax.set_ylabel("n_parties")
        ax.set_title(
            f"{sl['strategy']} p={sl['p_depolarize']} "
            f"q={sl['p_measure_flip']} L={sl['size_l']}"
        )
        for p in sl["points"]:
            if p["frontier"]:
                ax.plot(
                    dish.index(p["n_dishonest"]),
                    parties.index(p["n_parties"]),
                    "r+", markersize=12,
                )
        fig.colorbar(im, label="agreement success rate")
        path = os.path.join(out_dir, f"slice_{i:02d}.png")
        fig.savefig(path, dpi=120, bbox_inches="tight")
        plt.close(fig)
        written.append(path)
    if atlas["ki7_fence"]:
        fig, ax = plt.subplots(figsize=(6, 4))
        for fence in atlas["ki7_fence"]:
            pts = [p for p in fence["points"] if "false_abort_rate" in p]
            if not pts:
                continue
            xs = [p["pflip"] for p in pts]
            ys = [p["false_abort_rate"] for p in pts]
            los = [p["lo"] for p in pts]
            his = [p["hi"] for p in pts]
            label = (
                f"{fence['strategy']} n={fence['n_parties']} "
                f"L={fence['size_l']}"
            )
            ax.plot(xs, ys, "o-", label=label)
            ax.fill_between(xs, los, his, alpha=0.2)
        ax.set_xlabel("pflip = (2p/3)(1-q) + q(1-2p/3)")
        ax.set_ylabel("all-honest false-abort rate")
        ax.set_title("KI-7 noise-detectability fence (measured)")
        ax.legend(fontsize=7)
        path = os.path.join(out_dir, "ki7_fence.png")
        fig.savefig(path, dpi=120, bbox_inches="tight")
        plt.close(fig)
        written.append(path)
    return written
