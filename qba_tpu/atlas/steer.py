"""Cross-cell frontier steering — the campaign's submission order.

The per-cell stopping rule decides *when a cell is done*; this module
decides *which cell's budget is spent next*, reusing the
:class:`~qba_tpu.stats.AdaptiveAllocator`'s tiering across the whole
cube: cells whose running CI still straddles the validity threshold
(the phase-transition **frontier**) outrank cells whose answer is
already clearly on one side (the **interior**).  Frontier cells get
submitted — and, on budget exhaustion, escalated — first; interior
cells certify at whatever coarse CI their first wave produced.

The plan is a pure function of the observed per-cell counts: the
allocator is rebuilt from scratch each round, fed one aggregate
``preload`` per observed cell, and its ``_priority`` tuple orders the
open cells.  No RNG, no timing input — a resumed driver derives the
same plan from the same ledger, which the resume differential test
pins.  The allocator's summary (with its trace) is stored in the
campaign ledger's ``steering`` block, so the rendered atlas can show
*why* each cell got the budget it did.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

from qba_tpu.stats import AdaptiveAllocator
from qba_tpu.stats.targets import Target, parse_target

#: Tier names in allocator priority order (allocate.py's trace reasons).
TIERS = ("bootstrap", "straddling", "undecided")


def frontier_plan(
    cell_keys: Sequence[str],
    observed: Mapping[str, tuple[int, int]],
    open_keys: Sequence[str],
    target: Target | str,
    budget_chunks: int = 1,
) -> tuple[list[str], dict[str, Any]]:
    """Rank the open cells by frontier priority.

    ``cell_keys`` is the full enumerated cube in enumeration order
    (ties break by this index, mirroring the allocator), ``observed``
    maps cell key -> aggregate ``(successes, trials)`` seen so far
    (certified, refused, and escalated-away attempts all count — the
    evidence exists regardless of what the ledger did with it), and
    ``open_keys`` is the subset still needing work.  Returns the open
    keys most-urgent-first plus the allocator summary (tier + CI width
    per cell, trace) for the ledger's ``steering`` block.
    """
    want = parse_target(target) if isinstance(target, str) else target
    # budget_chunks only gates next_cell(), which this planner never
    # calls — pass something valid and let _priority do the ranking.
    alloc = AdaptiveAllocator(
        list(cell_keys), want, budget_chunks=max(1, budget_chunks)
    )
    index_of = {key: i for i, key in enumerate(cell_keys)}
    for key, (k, n) in sorted(observed.items(), key=lambda kv: index_of.get(kv[0], 0)):
        if key in index_of and n > 0:
            alloc.preload(index_of[key], int(k), int(n))
    ranked = sorted(
        (key for key in open_keys if key in index_of),
        key=lambda key: alloc._priority(alloc.cells[index_of[key]]),
    )
    tiers: dict[str, str] = {}
    widths: dict[str, float | None] = {}
    for key in open_keys:
        if key not in index_of:
            continue
        cell = alloc.cells[index_of[key]]
        prio = alloc._priority(cell)
        tiers[key] = TIERS[prio[0]]
        widths[key] = (
            float(cell.rule.estimate().width) if cell.chunks_run else None
        )
    plan = {
        "target": want.to_json(),
        "open": list(ranked),
        "tiers": tiers,
        "ci_widths": widths,
        "allocator": alloc.summary(),
    }
    return ranked, plan


def is_frontier(record: Mapping[str, Any], target: Target | str) -> bool:
    """Is a finished cell on the validity frontier?  Yes when its final
    CI still contains the decide threshold (a ``ci_width`` certification
    that never excluded it, or a truncation refusal), or when it
    escalated past wave 0 before resolving — both mean the allocator's
    straddling tier kept feeding it.  ``decide``-certified cells are
    interior by definition: their CI cleared the threshold."""
    want = parse_target(target) if isinstance(target, str) else target
    if want.kind != "decide":
        return False
    ci = record.get("ci")
    if isinstance(ci, dict) and ci.get("lo") is not None:
        lo, hi = float(ci["lo"]), float(ci["hi"])
        if lo <= want.threshold <= hi:
            return True
    refusal = record.get("refusal")
    if isinstance(refusal, dict) and refusal.get("reason") == "budget_exhausted":
        return True
    return False
