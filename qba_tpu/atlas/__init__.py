"""Fleet-scale atlas campaigns: the 4-D QBA validity phase diagram.

The campaign driver (:mod:`~qba_tpu.atlas.campaign`) enumerates the
(parties × dishonest × strategy × noise) cube
(:mod:`~qba_tpu.atlas.cube`), prices every cell through the fleet
admission controller, steers trial budget toward the validity
threshold frontier (:mod:`~qba_tpu.atlas.steer`), and materializes a
content-addressed store of certified per-cell records
(:mod:`~qba_tpu.atlas.store`) that
:func:`~qba_tpu.atlas.render.render_atlas` turns into the phase
diagram — validity surfaces with CI bands per (strategy, noise) slice
and the measured KI-7 noise-detectability fence.  docs/ATLAS.md is
the operator guide; the KI-11 completeness lint lives in
:mod:`qba_tpu.analysis.atlas`.
"""

from qba_tpu.atlas.campaign import (
    CampaignDriver,
    FleetExecutor,
    LocalExecutor,
)
from qba_tpu.atlas.cube import AtlasCell, CampaignSpec, enumerate_cells
from qba_tpu.atlas.render import plot_slices, render_atlas
from qba_tpu.atlas.steer import frontier_plan, is_frontier
from qba_tpu.atlas.store import (
    AtlasStore,
    cell_key,
    cell_slug,
    record_satisfies,
)

__all__ = [
    "AtlasCell",
    "AtlasStore",
    "CampaignDriver",
    "CampaignSpec",
    "FleetExecutor",
    "LocalExecutor",
    "cell_key",
    "cell_slug",
    "enumerate_cells",
    "frontier_plan",
    "is_frontier",
    "plot_slices",
    "record_satisfies",
    "render_atlas",
]
