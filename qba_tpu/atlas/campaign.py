"""The campaign driver: enumerate → admit → steer → certify → render.

One :class:`CampaignDriver` owns one campaign over one atlas store.
Its loop is deliberately boring — the correctness story is in the
invariants, not the control flow:

* **At-least-once delivery, exactly-once effect.**  Results are
  processed (ledger persisted, store record written) *before* they are
  acknowledged to the executor.  A ``kill -9`` of the driver between
  persist and ack makes the result arrive again on resume; the handler
  recognizes the finalized cell and drops the duplicate.  Zero lost,
  zero duplicated cells — the file-queue's model-checked claim
  guarantees (KI-10), observed at campaign level.
* **The ledger is the only state.**  A restarted driver re-derives the
  cube from the spec (``enumerate_cells`` is pure), reconciles it
  against the store (certified/refused cells are never re-admitted),
  recovers in-flight request ids through the executor, and continues.
  Nothing in memory matters.
* **Determinism.**  Per-cell results are pure functions of
  ``(config, seed, chunk index)`` (the sweep layer's chunk-key
  discipline), escalation is driven only by per-cell budget
  exhaustion, and steering order never changes what any cell computes
  — so an interrupted-and-resumed campaign produces a store with the
  same identity digest as an uninterrupted one (the resume
  differential in tests/test_atlas.py).

Back-pressure: every submission goes through
``AdmissionController.try_admit(req, batch=True)``.  ``defer`` stops
this round's submissions — the driver drains results (which settle
capacity) and re-offers next round, per the batch retry contract in
docs/SERVING.md.  ``reject`` becomes an explicit refusal record: the
KI-11 lint treats a silently missing cell as a finding, so every
enumerated cell must end certified or refused.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable

from qba_tpu.atlas.cube import (
    AtlasCell,
    CampaignSpec,
    build_request,
    enumerate_cells,
    request_id_for,
)
from qba_tpu.atlas.steer import frontier_plan
from qba_tpu.atlas.store import (
    CELL_SCHEMA,
    LEDGER_SCHEMA,
    AtlasStore,
    record_satisfies,
)
from qba_tpu.obs.metrics import MetricsRegistry
from qba_tpu.obs.tracing import mint_span_id, mint_trace_id
from qba_tpu.serve.fleet.admission import ADMIT, DEFER, AdmissionController
from qba_tpu.serve.queuefs import drop_request, queue_paths, request_slug
from qba_tpu.serve.request import EvalRequest, EvalResult


def _stamp_trace(req: EvalRequest) -> EvalRequest:
    """Mint trace context for one atlas cell request.

    The campaign driver is this request's frontend — no fleet intake
    ever sees it before the queue file — so the trace id is born here
    and only *adopted* downstream (KI-12 registered mint site; see
    qba_tpu/analysis/obs.py MINT_SITES)."""
    if req.trace_id:
        return req
    return dataclasses.replace(
        req, trace_id=mint_trace_id(), parent_span_id=mint_span_id()
    )


class LocalExecutor:
    """In-process executor: one :class:`~qba_tpu.serve.engine.QBAServer`
    behind the same submit/poll/ack/recover surface as the fleet.  The
    test and quick-CI path — synchronous, deterministic, no queue dir.
    Nothing survives the process, so :meth:`recover` always answers
    ``gone`` and a restarted driver simply re-submits."""

    def __init__(self, server=None, **server_kw) -> None:
        self._server = server
        self._server_kw = server_kw
        self._pending: list[EvalRequest] = []

    def submit(self, req: EvalRequest) -> None:
        self._pending.append(req)

    def poll(self) -> list[dict[str, Any]]:
        if not self._pending:
            return []
        from qba_tpu.serve.engine import QBAServer, serve_batch

        if self._server is None:
            self._server = QBAServer(**self._server_kw)
        reqs, self._pending = self._pending, []
        return [r.to_json() for r in serve_batch(self._server, reqs)]

    def recover(self, request_id: str) -> tuple[str, dict[str, Any] | None]:
        return ("gone", None)

    def ack(self, request_id: str) -> None:
        pass

    def stop(self) -> None:
        pass


class FleetExecutor:
    """File-queue executor: requests dropped into a fleet ``inbox/``,
    results read from ``outbox/`` and moved to ``consumed/`` only on
    :meth:`ack` — i.e. only after the driver has persisted their
    effect, which is what makes driver kills loss-free.  The pool and
    supervisor run elsewhere (CLI or test harness); this class touches
    nothing but the queue directory, and stays jax-free like the rest
    of the fleet's front half."""

    def __init__(self, queue_dir: str) -> None:
        self.paths = queue_paths(queue_dir)
        for key in ("inbox", "claimed", "done", "dead", "outbox", "consumed"):
            os.makedirs(self.paths[key], exist_ok=True)

    def submit(self, req: EvalRequest) -> None:
        drop_request(self.paths["inbox"], req.to_json(), req.request_id)

    def poll(self) -> list[dict[str, Any]]:
        import json

        out: list[dict[str, Any]] = []
        outbox = self.paths["outbox"]
        try:
            names = sorted(os.listdir(outbox))
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(outbox, name)) as f:
                    payload = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue  # mid-rename or torn teardown; next poll
            if isinstance(payload, dict):
                out.append(payload)
        return out

    def recover(self, request_id: str) -> tuple[str, dict[str, Any] | None]:
        """Where is an in-flight request after a driver restart?
        ``result`` — its result is in the outbox (unacked; the caller
        processes it normally); ``pending`` — still queued or claimed
        by a worker; ``gone`` — no trace (e.g. submitted to a queue
        that was since recreated): re-submit."""
        import json

        name = request_slug(request_id) + ".json"
        res = os.path.join(self.paths["outbox"], name)
        if os.path.exists(res):
            try:
                with open(res) as f:
                    payload = json.load(f)
                if isinstance(payload, dict):
                    return ("result", payload)
            except (OSError, json.JSONDecodeError):
                return ("pending", None)  # mid-rename: poll will see it
        for box in ("inbox", "claimed", "dead"):
            if os.path.exists(os.path.join(self.paths[box], name)):
                return ("pending", None)
        return ("gone", None)

    def ack(self, request_id: str) -> None:
        """Move a processed result out of the outbox.  Crash-safe in
        both directions: ack-after-persist means a missed ack only
        re-delivers (handled idempotently), never loses."""
        name = request_slug(request_id) + ".json"
        src = os.path.join(self.paths["outbox"], name)
        try:
            os.replace(src, os.path.join(self.paths["consumed"], name))
        except OSError:
            pass  # already acked, or outbox torn down

    def stop(self) -> None:
        pass


class CampaignDriver:
    """Runs one campaign spec against one store through one executor.

    ``max_results`` interrupts the driver after processing that many
    results (the test harness's stand-in for ``kill -9`` — the ledger
    on disk at that point is exactly what a real kill would leave);
    ``on_result(count, payload)`` fires after each processed result
    (the CLI's chaos-kill hook).
    """

    def __init__(
        self,
        store: AtlasStore,
        spec: CampaignSpec,
        executor,
        *,
        admission: AdmissionController | None = None,
        log: Callable[[str], None] = lambda s: None,
        poll_s: float = 0.05,
        idle_timeout_s: float = 180.0,
        max_results: int | None = None,
        on_result: Callable[[int, dict[str, Any]], None] | None = None,
        metrics: MetricsRegistry | None = None,
    ) -> None:
        self.store = store
        self.spec = spec
        self.executor = executor
        # Driver-owned metrics plane: campaign outcomes and budget spend
        # land in the same registered-name table the fleet exposes.
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.admission = admission or AdmissionController(
            chunk_trials=spec.chunk_trials
        )
        self.log = log
        self.poll_s = poll_s
        self.idle_timeout_s = idle_timeout_s
        self.max_results = max_results
        self.on_result = on_result
        self.cells: dict[str, AtlasCell] = {
            c.key: c for c in enumerate_cells(spec)
        }
        self.order: list[str] = list(self.cells)
        self.results_processed = 0

    # ---- ledger ------------------------------------------------------
    def _fresh_ledger(self) -> dict[str, Any]:
        return {
            "schema": LEDGER_SCHEMA,
            "campaign": self.spec.to_json(),
            "campaign_key": self.spec.campaign_key(),
            "cells": {
                key: {
                    "coords": cell.coords,
                    "status": "pending",
                    "attempt": 0,
                    "request_id": None,
                    "successes": 0,
                    "n_trials": 0,
                    "admission": None,
                    "refusal": None,
                }
                for key, cell in self.cells.items()
            },
            "steering": None,
        }

    def _load_ledger(self) -> dict[str, Any]:
        led = self.store.load_ledger()
        if led is None:
            return self._fresh_ledger()
        if led.get("campaign_key") != self.spec.campaign_key():
            raise ValueError(
                f"ledger at {self.store.ledger_path} belongs to campaign "
                f"{led.get('campaign_key')!r}, not {self.spec.campaign_key()!r}"
                " — refusing to resume a different campaign's ledger"
            )
        # The cube is re-derived, never trusted from disk: a ledger cell
        # set differing from the enumeration is a corruption finding.
        missing = set(self.cells) - set(led.get("cells", {}))
        if missing:
            raise ValueError(
                f"ledger is missing {len(missing)} enumerated cell(s), "
                f"e.g. {sorted(missing)[:3]} — corrupt ledger"
            )
        return led

    def _reconcile_store(self, ledger: dict[str, Any]) -> int:
        """Cells the store already answers are never re-admitted: a
        certified record satisfying this campaign's target (or any
        record finalized by this same campaign target) closes the
        ledger cell.  Returns how many cells were closed this way."""
        closed = 0
        for key, entry in ledger["cells"].items():
            if entry["status"] in ("certified", "refused"):
                continue
            rec = self.store.load_cell(key)
            if rec is None:
                continue
            same_target = rec.get("target") == self.spec.target
            if rec.get("status") == "certified" and (
                same_target or record_satisfies(rec, self.spec.target)
            ):
                entry.update(
                    status="certified",
                    successes=rec.get("successes", 0),
                    n_trials=rec.get("n_trials", 0),
                    attempt=max(0, int(rec.get("attempts", 1)) - 1),
                )
                closed += 1
            elif rec.get("status") == "refused" and same_target:
                entry.update(
                    status="refused",
                    successes=rec.get("successes", 0),
                    n_trials=rec.get("n_trials", 0),
                    refusal=rec.get("refusal"),
                    attempt=max(0, int(rec.get("attempts", 1)) - 1),
                )
                closed += 1
        return closed

    def _recover_inflight(self, ledger: dict[str, Any]) -> None:
        """Driver-restart path: every ``submitted`` cell's request id is
        located through the executor — landed results get processed,
        queued/claimed work is left to arrive, vanished requests go
        back to pending for re-admission."""
        for key, entry in list(ledger["cells"].items()):
            if entry["status"] != "submitted":
                continue
            rid = entry["request_id"] or request_id_for(key, entry["attempt"])
            state, payload = self.executor.recover(rid)
            if state == "result" and payload is not None:
                self._handle(ledger, payload)
            elif state == "gone":
                entry["status"] = "pending"
                entry["request_id"] = None
                self.log(f"atlas: {rid} lost in flight; re-admitting")

    # ---- result handling --------------------------------------------
    @staticmethod
    def _cell_key_of(request_id: str) -> str | None:
        if not request_id.startswith("atlas-"):
            return None
        body = request_id[len("atlas-"):]
        key, sep, _ = body.rpartition("-a")
        return key if sep else None

    def _handle(self, ledger: dict[str, Any], payload: dict[str, Any]) -> bool:
        """Process one result payload; returns True if it advanced the
        campaign (False for stale/foreign/duplicate payloads, which are
        acked and dropped)."""
        try:
            res = EvalResult.from_json(payload)
        except (TypeError, ValueError):
            rid = payload.get("request_id")
            if isinstance(rid, str):
                self.executor.ack(rid)
            return False
        rid = res.request_id
        key = self._cell_key_of(rid)
        entry = ledger["cells"].get(key) if key else None
        if (
            entry is None
            or entry["status"] != "submitted"
            or entry["request_id"] != rid
        ):
            self.executor.ack(rid)  # duplicate delivery or stale attempt
            return False
        self.admission.settle(rid, res.n_trials)
        if res.n_trials:
            self.metrics.inc(
                "qba_atlas_budget_trials_total",
                float(res.n_trials),
                exemplar=res.trace_id,
            )
        if res.error:
            refusal = {
                "reason": (
                    "crash_quarantine" if res.crash_report else "error"
                ),
                "detail": res.error,
            }
            if res.crash_report:
                refusal["crash_report"] = res.crash_report
            self._finalize(ledger, key, res, status="refused", refusal=refusal)
        else:
            entry["successes"] = res.successes
            entry["n_trials"] = res.n_trials
            reason = (res.stop or {}).get("reason")
            if reason in ("decided_above", "decided_below", "ci_width"):
                self._finalize(ledger, key, res, status="certified")
            elif entry["attempt"] < self.spec.max_escalations:
                entry["attempt"] += 1
                entry["status"] = "pending"
                entry["request_id"] = None
                self.metrics.inc(
                    "qba_atlas_cells_total",
                    labels={"status": "escalated"},
                    exemplar=res.trace_id,
                )
                self.log(
                    f"atlas: {key} unresolved at {res.n_trials} trials; "
                    f"escalating to wave {entry['attempt']}"
                )
            else:
                self._finalize(
                    ledger, key, res, status="refused",
                    refusal={
                        "reason": "budget_exhausted",
                        "detail": (
                            f"target unresolved after {res.n_trials} trials "
                            f"over {entry['attempt'] + 1} wave(s)"
                        ),
                    },
                )
        self._save(ledger)
        self.executor.ack(rid)  # persist-then-ack: kills re-deliver, never lose
        return True

    def _finalize(
        self,
        ledger: dict[str, Any],
        key: str,
        res: EvalResult,
        *,
        status: str,
        refusal: dict[str, Any] | None = None,
    ) -> None:
        cell = self.cells[key]
        entry = ledger["cells"][key]
        ci = res.ci
        if ci is None and res.n_trials > 0:
            from qba_tpu.stats.estimators import rate_estimate

            ci = rate_estimate(res.successes, res.n_trials).to_json()
        record = {
            "schema": CELL_SCHEMA,
            "cell_key": key,
            "coords": cell.coords,
            "config": cell.fingerprint,
            "target": self.spec.target,
            "chunk_trials": self.spec.chunk_trials,
            "status": status,
            "stop": res.stop,
            "ci": ci,
            "successes": res.successes,
            "n_trials": res.n_trials,
            "attempts": entry["attempt"] + 1,
            "refusal": refusal,
            "provenance": {
                "producer": "campaign",
                "campaign_key": self.spec.campaign_key(),
                "request_id": res.request_id,
                "replica_id": res.replica_id,
                "engine": res.engine,
                "bucket": res.bucket,
                "latency_s": res.latency_s,
                "queue_wait_s": res.queue_wait_s,
                "admission": entry.get("admission"),
            },
            "manifest": res.manifest,
        }
        self.store.write_cell(record)
        entry["status"] = status
        entry["refusal"] = refusal
        entry["successes"] = res.successes
        entry["n_trials"] = res.n_trials
        self.metrics.inc(
            "qba_atlas_cells_total",
            labels={"status": status},
            exemplar=res.trace_id,
        )

    def _refuse_admission(
        self, ledger: dict[str, Any], key: str, decision
    ) -> None:
        """An admission REJECT is a final, explicit refusal — the cell
        can never be served by this fleet, and KI-11 wants the evidence
        on disk, not a silent gap."""
        cell = self.cells[key]
        entry = ledger["cells"][key]
        refusal = {
            "reason": f"admission_{decision.reason}",
            "detail": decision.detail,
            "admission": decision.to_json(),
        }
        record = {
            "schema": CELL_SCHEMA,
            "cell_key": key,
            "coords": cell.coords,
            "config": cell.fingerprint,
            "target": self.spec.target,
            "chunk_trials": self.spec.chunk_trials,
            "status": "refused",
            "stop": None,
            "ci": None,
            "successes": 0,
            "n_trials": 0,
            "attempts": entry["attempt"] + 1,
            "refusal": refusal,
            "provenance": {
                "producer": "campaign",
                "campaign_key": self.spec.campaign_key(),
            },
            "manifest": None,
        }
        self.store.write_cell(record)
        entry["status"] = "refused"
        entry["refusal"] = refusal
        self.metrics.inc(
            "qba_atlas_cells_total", labels={"status": "refused"}
        )

    def _save(self, ledger: dict[str, Any]) -> None:
        self.store.save_ledger(ledger)

    # ---- the loop ----------------------------------------------------
    def run(self) -> dict[str, Any]:
        ledger = self._load_ledger()
        reused = self._reconcile_store(ledger)
        if reused:
            self.log(f"atlas: {reused} cell(s) already answered by the store")
        self._recover_inflight(ledger)
        self._save(ledger)
        last_progress = time.monotonic()
        interrupted = False
        while True:
            pending = [
                k for k in self.order
                if ledger["cells"][k]["status"] == "pending"
            ]
            submitted = [
                k for k in self.order
                if ledger["cells"][k]["status"] == "submitted"
            ]
            if not pending and not submitted:
                break
            if pending:
                observed = {
                    k: (e["successes"], e["n_trials"])
                    for k, e in ledger["cells"].items()
                    if e["n_trials"] > 0
                }
                ranked, plan = frontier_plan(
                    self.order, observed, pending, self.spec.target
                )
                ledger["steering"] = plan
                for key in ranked:
                    entry = ledger["cells"][key]
                    req = _stamp_trace(build_request(
                        self.cells[key], self.spec, entry["attempt"]
                    ))
                    dec = self.admission.try_admit(req, batch=True)
                    entry["admission"] = dec.to_json()
                    if dec.action == ADMIT:
                        entry["status"] = "submitted"
                        entry["request_id"] = req.request_id
                        self.executor.submit(req)
                        last_progress = time.monotonic()
                    elif dec.action == DEFER:
                        # Back-pressure: stop offering, drain settles,
                        # re-offer next round (docs/SERVING.md).
                        break
                    else:
                        self._refuse_admission(ledger, key, dec)
                self._save(ledger)
                submitted = [
                    k for k in self.order
                    if ledger["cells"][k]["status"] == "submitted"
                ]
            progressed = False
            for payload in self.executor.poll():
                if self._handle(ledger, payload):
                    progressed = True
                    last_progress = time.monotonic()
                    self.results_processed += 1
                    if self.on_result is not None:
                        self.on_result(self.results_processed, payload)
                    if (
                        self.max_results is not None
                        and self.results_processed >= self.max_results
                    ):
                        interrupted = True
                        break
            if interrupted:
                break
            if not progressed and submitted:
                if time.monotonic() - last_progress > self.idle_timeout_s:
                    stuck = [
                        ledger["cells"][k]["request_id"] for k in submitted
                    ]
                    raise RuntimeError(
                        f"campaign stalled: no result for "
                        f"{self.idle_timeout_s:.0f}s with {len(stuck)} "
                        f"request(s) in flight, e.g. {stuck[:3]}"
                    )
                time.sleep(self.poll_s)
        summary = self.summary(ledger)
        summary["interrupted"] = interrupted
        if not interrupted:
            from qba_tpu.atlas.render import render_atlas

            atlas = render_atlas(self.store, self.spec.target)
            summary["atlas"] = {
                "slices": len(atlas.get("slices", [])),
                "path": self.store.atlas_path,
            }
        self.log(
            f"atlas: campaign {'interrupted' if interrupted else 'complete'}"
            f" — {summary['certified']} certified, "
            f"{summary['refused']} refused, "
            f"{summary['open']} open of {summary['cells']}"
        )
        return summary

    def summary(self, ledger: dict[str, Any]) -> dict[str, Any]:
        by_status: dict[str, int] = {}
        for entry in ledger["cells"].values():
            by_status[entry["status"]] = by_status.get(entry["status"], 0) + 1
        return {
            "campaign_key": self.spec.campaign_key(),
            "cells": len(ledger["cells"]),
            "certified": by_status.get("certified", 0),
            "refused": by_status.get("refused", 0),
            "open": by_status.get("pending", 0) + by_status.get("submitted", 0),
            "by_status": by_status,
            "results_processed": self.results_processed,
            "admission": self.admission.summary(),
            "store_digest": self.store.digest(),
            "metrics": {
                "escalated": self.metrics.counter_value(
                    "qba_atlas_cells_total", {"status": "escalated"}
                ),
                "budget_trials": self.metrics.counter_value(
                    "qba_atlas_budget_trials_total"
                ),
            },
        }
