"""qba_tpu — TPU-native framework for detectable Quantum Byzantine Agreement.

A ground-up JAX/XLA re-design of the capabilities of the reference simulator
``Carl0sGV/TFG---Quantum-Byzantine-Agreement`` (``tfg.py``): an MPI
process-per-party Byzantine-agreement protocol driven by simulated quantum
resources.  Here the message-passing design inverts into array programming:

* all parties' protocol state lives in fixed-shape arrays carrying a party
  axis (replacing MPI ranks, ``tfg.py:310-314``),
* the quantum resource generation is a batched JAX sampler / dense
  statevector engine (replacing the qsimov native engine, ``tfg.py:68-84``),
* voting rounds are a synchronous ``lax.scan`` over a dense mailbox tensor
  (replacing tagged ``Isend``/``Irecv``/``Iprobe`` traffic,
  ``tfg.py:199-263,337-348``),
* Byzantine fault injection is a vectorized adversary model
  (replacing ``tfg.py:101-125,169-181,271-284``),
* Monte-Carlo trials are ``vmap``-batched and sharded over a TPU device
  mesh via ``shard_map`` with XLA collectives.
"""

from qba_tpu.config import QBAConfig


def run_trials(cfg, keys=None):
    """Convenience re-export of
    :func:`qba_tpu.backends.jax_backend.run_trials` (lazy import so
    ``import qba_tpu`` stays light)."""
    from qba_tpu.backends.jax_backend import run_trials as _run

    return _run(cfg, keys)


__all__ = ["QBAConfig", "run_trials"]
__version__ = "0.1.0"
