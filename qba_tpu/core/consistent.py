"""Vectorized consistency predicate and evidence append.

Re-designs ``consistent(v, L, w)`` (``tfg.py:87-98``) and the
``L.add(tuple(Li[j] for j in P))`` append (``tfg.py:189,291``) over the
compacted tuple-ordered :class:`~qba_tpu.core.types.Evidence` layout:

Condition 1 — all tuples in L have the same length (``tfg.py:88-92``):
  recorded per-row lengths agree over valid rows.
Condition 2 — every element is in ``[0, w] \\ {v}`` (``tfg.py:93-94``; the
  reference's ``x <= w`` off-by-one is preserved — protocol values are < w
  anyway): no in-tuple entry equals v, exceeds w, or is negative.
Condition 3 — every pair of tuples differs at every index (``tfg.py:96-98``):
  no pair of valid rows agrees at any jointly-in-range tuple index.  Because
  rows are compacted in tuple order, this is elementwise comparison — the
  exact reference semantics, for any combination of P masks.
"""

from __future__ import annotations

import jax.numpy as jnp

from qba_tpu.core.types import SENTINEL, Evidence


def consistent(v: jnp.ndarray, ev: Evidence, w: int) -> jnp.ndarray:
    """bool scalar: is (v, L) consistent? Vacuously true for empty L
    (the reference only ever calls ``consistent`` with |L| >= 1)."""
    max_l = ev.vals.shape[0]
    valid = jnp.arange(max_l) < ev.count  # bool[max_l]
    in_tuple = ev.vals != SENTINEL  # bool[max_l, size_l]

    # Cond 1: lengths agree over valid rows (row 0 is valid whenever any is).
    cond1 = jnp.all(jnp.where(valid, ev.lens == ev.lens[0], True))

    # Cond 2: tuple entries of valid rows avoid v, stay in [0, w].
    bad = in_tuple & ((ev.vals == v) | (ev.vals > w) | (ev.vals < 0))
    cond2 = ~jnp.any(bad & valid[:, None])

    # Cond 3: no tuple index where two valid rows agree.
    eq = (
        (ev.vals[:, None, :] == ev.vals[None, :, :])
        & in_tuple[:, None, :]
        & in_tuple[None, :, :]
    )
    collide = jnp.any(eq, axis=-1)  # bool[max_l, max_l]
    pair = valid[:, None] & valid[None, :] & (
        jnp.arange(max_l)[:, None] < jnp.arange(max_l)[None, :]
    )
    cond3 = ~jnp.any(collide & pair)

    return cond1 & cond2 & cond3


def compact_tuple(p_mask: jnp.ndarray, li: jnp.ndarray) -> jnp.ndarray:
    """``tuple(Li[j] for j in P)`` as a SENTINEL-padded row: the values of
    ``li`` at True positions of ``p_mask``, left-justified in ascending
    position order.  The reference iterates the int-set ``P`` in CPython
    hash-table order, which need not be sorted; any single ordering shared
    by all rows yields identical ``consistent`` verdicts, and sorted order
    is the one we fix (docs/DIVERGENCES.md D10)."""
    size_l = p_mask.shape[0]
    # Stable argsort puts selected positions first, preserving position order.
    order = jnp.argsort(~p_mask, stable=True)
    n_sel = jnp.sum(p_mask.astype(jnp.int32))
    gathered = li[order].astype(jnp.int32)
    return jnp.where(jnp.arange(size_l) < n_sel, gathered, SENTINEL)


def append_own(ev: Evidence, p_mask: jnp.ndarray, li: jnp.ndarray) -> Evidence:
    """Add this party's sub-list ``tuple(Li[j] for j in P)`` to L
    (``tfg.py:189,291``) with set semantics (no-op if an identical row
    exists)."""
    max_l = ev.vals.shape[0]
    own_vals = compact_tuple(p_mask, li)
    own_len = jnp.sum(p_mask.astype(jnp.int32))

    valid = jnp.arange(max_l) < ev.count
    same_vals = jnp.all(ev.vals == own_vals[None, :], axis=-1)
    dup = jnp.any(valid & same_vals)

    # Scatter the new row at index `count` (guarded against overflow, which
    # is unreachable by the |L| <= n_dishonest+2 bound — SURVEY §7).
    slot = jnp.minimum(ev.count, max_l - 1)
    at = jnp.arange(max_l) == slot
    write = (~dup) & at
    new_vals = jnp.where(write[:, None], own_vals[None, :], ev.vals)
    new_lens = jnp.where(write, own_len, ev.lens)
    new_count = jnp.where(dup, ev.count, jnp.minimum(ev.count + 1, max_l))
    return Evidence(vals=new_vals, lens=new_lens, count=new_count)
