"""Vectorized consistency predicate and evidence append.

Re-designs ``consistent(v, L, w)`` (``tfg.py:87-98``) and the
``L.add(tuple(Li[j] for j in P))`` append (``tfg.py:189,291``) over the
position-expanded :class:`~qba_tpu.core.types.Evidence` layout:

Condition 1 — all tuples in L have the same length (``tfg.py:88-92``):
  recorded per-row lengths agree over valid rows.
Condition 2 — every element is in ``[0, w] \\ {v}`` (``tfg.py:93-94``; the
  reference's ``x <= w`` off-by-one is preserved — protocol values are < w
  anyway): no in-tuple entry equals v, exceeds w, or is negative.
Condition 3 — every pair of tuples differs at every index (``tfg.py:96-98``):
  no pair of valid rows agrees at any jointly-populated list position.
  Equal-length rows in a protocol-reachable L always share the same P
  (docs/DIVERGENCES.md D10), so position-wise comparison is exactly the
  reference's tuple-index comparison.
"""

from __future__ import annotations

import jax.numpy as jnp

from qba_tpu.core.types import SENTINEL, Evidence


def consistent(v: jnp.ndarray, ev: Evidence, w: int) -> jnp.ndarray:
    """bool scalar: is (v, L) consistent? Vacuously true for empty L
    (the reference only ever calls ``consistent`` with |L| >= 1)."""
    max_l = ev.vals.shape[0]
    valid = jnp.arange(max_l) < ev.count  # bool[max_l]
    in_tuple = ev.vals != SENTINEL  # bool[max_l, size_l]

    # Cond 1: lengths agree over valid rows (row 0 is valid whenever any is).
    cond1 = jnp.all(jnp.where(valid, ev.lens == ev.lens[0], True))

    # Cond 2: tuple entries of valid rows avoid v, stay in [0, w].
    bad = in_tuple & ((ev.vals == v) | (ev.vals > w) | (ev.vals < 0))
    cond2 = ~jnp.any(bad & valid[:, None])

    # Cond 3: no tuple index where two valid rows agree.
    eq = (
        (ev.vals[:, None, :] == ev.vals[None, :, :])
        & in_tuple[:, None, :]
        & in_tuple[None, :, :]
    )
    collide = jnp.any(eq, axis=-1)  # bool[max_l, max_l]
    pair = valid[:, None] & valid[None, :] & (
        jnp.arange(max_l)[:, None] < jnp.arange(max_l)[None, :]
    )
    cond3 = ~jnp.any(collide & pair)

    return cond1 & cond2 & cond3


def sublist_row(p_mask: jnp.ndarray, li: jnp.ndarray) -> jnp.ndarray:
    """``tuple(Li[j] for j in P)`` stored *position-expanded*: ``li``'s
    value at each True position of ``p_mask``, SENTINEL elsewhere.

    A pure elementwise select — no sort, no gather (both are serial-slow
    on the TPU VPU; a left-justified compaction here cost ~10x the whole
    round loop).  Comparing rows at shared non-SENTINEL positions is
    exactly the reference's compare-by-tuple-index (``tfg.py:96-98``)
    whenever the rows were built from the same ``P`` — and every
    protocol-reachable evidence set has that property, because the only
    attack that mutates ``P`` (clear-P, ``tfg.py:281``) changes the tuple
    length to 0, which the length condition already rejects against
    non-empty rows.  See docs/DIVERGENCES.md D10 for the full argument.
    """
    return jnp.where(p_mask, li.astype(jnp.int32), SENTINEL)


def append_own(ev: Evidence, p_mask: jnp.ndarray, li: jnp.ndarray) -> Evidence:
    """Add this party's sub-list ``tuple(Li[j] for j in P)`` to L
    (``tfg.py:189,291``) with set semantics (no-op if an identical row
    exists)."""
    max_l = ev.vals.shape[0]
    own_vals = sublist_row(p_mask, li)
    own_len = jnp.sum(p_mask.astype(jnp.int32))

    valid = jnp.arange(max_l) < ev.count
    same_vals = jnp.all(ev.vals == own_vals[None, :], axis=-1)
    dup = jnp.any(valid & same_vals)

    # Scatter the new row at index `count` (guarded against overflow, which
    # is unreachable by the |L| <= n_dishonest+2 bound — SURVEY §7).
    slot = jnp.minimum(ev.count, max_l - 1)
    at = jnp.arange(max_l) == slot
    write = (~dup) & at
    new_vals = jnp.where(write[:, None], own_vals[None, :], ev.vals)
    new_lens = jnp.where(write, own_len, ev.lens)
    new_count = jnp.where(dup, ev.count, jnp.minimum(ev.count + 1, max_l))
    return Evidence(vals=new_vals, lens=new_lens, count=new_count)


def consistent_after_append(
    v: jnp.ndarray,
    ev: Evidence,
    p_mask: jnp.ndarray,
    li: jnp.ndarray,
    w: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """``(consistent(v, L'), |L'|)`` for ``L' = append_own(ev, p_mask, li)``
    — without materializing ``L'``.

    Executable specification of the verdict algebra the round engines
    inline in batched form (:mod:`qba_tpu.rounds.engine` for XLA,
    :mod:`qba_tpu.ops.round_kernel` for the Pallas kernel): materializing
    the appended evidence per (receiver, packet) costs a
    ``[trials, receivers, packets, max_l, size_l]`` tensor (~2 GB/round
    at the headline config), so both engines compute these conditions
    from the un-appended cell evidence plus the would-be own row.  This
    function is the single-packet reference the property tests check the
    composition against (tests/test_core.py); it is not on the hot path.

    Decomposition — the own row's conditions apply only when it actually
    enters ``L'``, i.e. it is not a set-duplicate (then ``L'`` equals the
    cell rows, whose checks subsume the own row's) and the evidence is
    not already full (``append_own`` drops the row then):

    * cond1 — valid cell rows share one length, and (if appended) the
      own row's length matches it.
    * cond2 — no valid cell row (nor, if appended, the own row) touches
      ``{v}`` or leaves ``[0, w]``.
    * cond3 — no valid cell pair collides, and (if appended) the own row
      collides with no valid cell row.
    """
    max_l = ev.vals.shape[0]
    valid = jnp.arange(max_l) < ev.count  # bool[max_l]
    in_tuple = ev.vals != SENTINEL  # bool[max_l, size_l]

    own = sublist_row(p_mask, li)  # [size_l]
    own_len = jnp.sum(p_mask.astype(jnp.int32))

    dup = jnp.any(valid & jnp.all(ev.vals == own[None, :], axis=-1))
    appended = ~dup & (ev.count < max_l)
    new_count = jnp.where(appended, ev.count + 1, ev.count)

    # Cond 1 (tfg.py:88-92).
    cell_lens_ok = jnp.all(jnp.where(valid, ev.lens == ev.lens[0], True))
    own_len_ok = ~appended | (ev.count == 0) | (own_len == ev.lens[0])
    cond1 = cell_lens_ok & own_len_ok

    # Cond 2 (tfg.py:93-94; the reference's `<= w` off-by-one preserved).
    bad_cell = jnp.any(
        in_tuple
        & ((ev.vals == v) | (ev.vals > w) | (ev.vals < 0))
        & valid[:, None]
    )
    bad_own = appended & jnp.any(p_mask & ((own == v) | (own > w) | (own < 0)))
    cond2 = ~(bad_cell | bad_own)

    # Cond 3 (tfg.py:96-98) over jointly-populated positions.
    eq = (
        (ev.vals[:, None, :] == ev.vals[None, :, :])
        & in_tuple[:, None, :]
        & in_tuple[None, :, :]
    )
    collide = jnp.any(eq, axis=-1)
    pair = valid[:, None] & valid[None, :] & (
        jnp.arange(max_l)[:, None] < jnp.arange(max_l)[None, :]
    )
    cells_ok = ~jnp.any(collide & pair)
    own_hits = jnp.any(
        p_mask[None, :] & in_tuple & (ev.vals == own[None, :]) & valid[:, None],
        axis=-1,
    )
    own_ok = ~appended | ~jnp.any(own_hits)
    cond3 = cells_ok & own_ok

    return cond1 & cond2 & cond3, new_count
