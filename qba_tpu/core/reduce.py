"""Position-axis reductions, optionally crossing a sharded mesh axis.

The security-parameter axis ``size_l`` is the structural analog of sequence
length (SURVEY §5 "Long-context"): positions are i.i.d. and every protocol
reduction over them is a plain any/sum.  When ``size_l`` is sharded over a
mesh axis under ``shard_map`` (sequence parallelism), these helpers finish
the reduction with a ``psum`` over that axis; single-device callers pass
``axis_name=None`` and get pure ``jnp`` reductions that XLA fuses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def psum_positions(x: jnp.ndarray, axis_name: str | None) -> jnp.ndarray:
    """Sum over the trailing (positions) axis, then over the mesh axis."""
    s = jnp.sum(x, axis=-1)
    if axis_name is not None:
        s = jax.lax.psum(s, axis_name)
    return s


def pany_positions(x: jnp.ndarray, axis_name: str | None) -> jnp.ndarray:
    """Logical any over the trailing (positions) axis + mesh axis."""
    return psum_positions(x.astype(jnp.int32), axis_name) > 0


def pall_positions(x: jnp.ndarray, axis_name: str | None) -> jnp.ndarray:
    """Logical all over the trailing (positions) axis + mesh axis."""
    return ~pany_positions(~x, axis_name)
