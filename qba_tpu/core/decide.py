"""Decision rule and end-to-end success oracle.

Re-designs ``decide_order`` (``tfg.py:303-306``) and the verdict computed by
rank 0 (``tfg.py:359-363``).  Divergence: the reference crashes on an empty
accepted-set (``min(set())``, ``tfg.py:306``); here an empty ``Vi`` decides
the sentinel ``w`` (an impossible order value) — see docs/DIVERGENCES.md.
"""

from __future__ import annotations

import jax.numpy as jnp


def decide_order(
    vi_mask: jnp.ndarray,
    v: jnp.ndarray,
    is_comm: jnp.ndarray,
    w: int,
) -> jnp.ndarray:
    """``tfg.py:303-306``: the commander decides its own order ``v``; a
    lieutenant decides ``min(Vi)`` over the accepted-set mask ``[w]`` — or
    the sentinel ``w`` when ``Vi`` is empty (divergence D2)."""
    candidates = jnp.where(vi_mask, jnp.arange(w, dtype=jnp.int32), w)
    lieu = jnp.min(candidates).astype(jnp.int32)
    return jnp.where(is_comm, jnp.asarray(v, jnp.int32), lieu)


def success_oracle(decisions: jnp.ndarray, honest: jnp.ndarray) -> jnp.ndarray:
    """The built-in Byzantine-agreement check (``tfg.py:359-363``).

    ``decisions``: int32[n_parties] — index 0 is the commander (rank 1).
    ``honest``: bool[n_parties] — same indexing.
    Success iff the honest parties' decisions form a singleton set; all
    parties dishonest -> empty set -> False, as in the reference.
    """
    first_idx = jnp.argmax(honest)  # index of first honest party
    ref = decisions[first_idx]
    agree = jnp.all(jnp.where(honest, decisions == ref, True))
    return jnp.any(honest) & agree
