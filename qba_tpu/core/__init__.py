"""Pure functional protocol kernel — no I/O, no communication.

TPU-native re-design of the reference's pure layer (SURVEY §1 L4):
``consistent`` (``tfg.py:87-98``), ``measure_to_ints`` (``tfg.py:128-129``),
``decide_order`` (``tfg.py:303-306``) and the success oracle
(``tfg.py:359-363``) — all as fixed-shape masked-array functions that are
jit/vmap/shard_map friendly.
"""

from qba_tpu.core.types import Evidence, Packet, empty_evidence, empty_packet
from qba_tpu.core.consistent import (
    append_own,
    consistent,
    consistent_after_append,
    sublist_row,
)
from qba_tpu.core.decode import measure_to_ints
from qba_tpu.core.decide import decide_order, success_oracle

__all__ = [
    "Evidence",
    "Packet",
    "empty_evidence",
    "empty_packet",
    "consistent",
    "consistent_after_append",
    "append_own",
    "sublist_row",
    "measure_to_ints",
    "decide_order",
    "success_oracle",
]
