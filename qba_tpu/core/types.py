"""Fixed-shape array encodings of the reference's variable-size sets.

The reference moves Python sets over the wire: a packet is
``(P: set[int], v: int, L: set[tuple[int]])`` (``tfg.py:199-263``).  Under
XLA everything must be static-shape, so (SURVEY §5 "Distributed communication
backend"):

* ``P``  -> bool mask ``[size_l]``
* ``v``  -> int32 scalar
* ``L``  -> an :class:`Evidence` matrix: up to ``max_l`` rows, each holding
  one tuple **position-expanded** — row ``i``'s entry at list position
  ``j`` is that tuple's value drawn from position ``j`` (i.e. ``Li[j]``
  for ``j`` in the packet's ``P``), with sentinel ``-1`` at positions
  outside ``P``.  Condition 3 of ``consistent`` compares elements at
  jointly-populated positions, and tuple equality (the ``set`` dedup of
  ``tfg.py:189,291``) is elementwise equality — both exactly the
  reference's by-tuple-index semantics for every protocol-reachable
  evidence set (docs/DIVERGENCES.md D10).  Per-row lengths are stored
  explicitly so the length condition (``tfg.py:88-92``) survives the
  clear-P attack (``tfg.py:281``).
* accepted-set ``Vi`` -> bool mask ``[w]``.

Tuple elements are order values in ``[0, w)``; ``-1`` never collides with a
representable element (``docs/DIVERGENCES.md`` D4).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct

SENTINEL = -1  # "past the end of this row's tuple"


@struct.dataclass
class Evidence:
    """The set L of sub-list tuples carried by a packet (``tfg.py:189,291``)."""

    vals: jnp.ndarray  # int32[max_l, size_l], position-expanded, SENTINEL-padded
    lens: jnp.ndarray  # int32[max_l], tuple length per row
    count: jnp.ndarray  # int32 scalar, number of valid rows


@struct.dataclass
class Packet:
    """One (P, v, L) protocol message (``tfg.py:199-263``)."""

    p_mask: jnp.ndarray  # bool[size_l]
    v: jnp.ndarray  # int32 scalar
    evidence: Evidence


def empty_evidence(max_l: int, size_l: int) -> Evidence:
    return Evidence(
        vals=jnp.full((max_l, size_l), SENTINEL, dtype=jnp.int32),
        lens=jnp.zeros((max_l,), dtype=jnp.int32),
        count=jnp.zeros((), dtype=jnp.int32),
    )


def empty_packet(max_l: int, size_l: int) -> Packet:
    return Packet(
        p_mask=jnp.zeros((size_l,), dtype=bool),
        v=jnp.zeros((), dtype=jnp.int32),
        evidence=empty_evidence(max_l, size_l),
    )
