"""Measurement-bit decoding.

Re-designs ``measure_to_ints`` (``tfg.py:128-129``): the reference joins
``n_qubits`` bit characters big-endian per list position and parses base-2.
Here: one reshape + dot with powers of two, batched over any leading axes.
"""

from __future__ import annotations

import jax.numpy as jnp


def measure_to_ints(raw: jnp.ndarray, size_l: int, n_qubits: int) -> jnp.ndarray:
    """``raw``: int bits ``[..., size_l * n_qubits]`` -> ints ``[..., size_l]``.

    Big-endian within each group of ``n_qubits`` bits, matching the string
    concatenation order of ``tfg.py:129``.
    """
    bits = raw.reshape(raw.shape[:-1] + (size_l, n_qubits))
    weights = 2 ** jnp.arange(n_qubits - 1, -1, -1, dtype=jnp.int32)
    return jnp.sum(bits.astype(jnp.int32) * weights, axis=-1)
