"""Typed, validated experiment configuration.

The reference's entire config surface is two positional CLI ints plus the MPI
world size (``tfg.py:366-367``, ``README.md:3-4``): ``sizeL``, ``nDishonest``,
and ``nParties = world_size - 1``.  Everything else is derived
(``tfg.py:316-318``).  There is no validation in the reference (e.g.
``nDishonest > nParties`` crashes ``np.random.choice`` at ``tfg.py:105``).

Here the config is an explicit frozen dataclass with derived properties and
validation, plus the knobs the TPU design adds (trials, seed, backend,
mailbox slot bound, qsim path).
"""

from __future__ import annotations

import dataclasses
import math

# Joint-statevector feasibility bound: 2**20 complex64 amplitudes ≈ 8 MB
# per state, the practical dense-path ceiling under vmap over list
# positions.  Single source of truth for the config validator and
# Drewom's auto engine switch (qsim/compat.py).
DENSE_QUBIT_CAP = 20


@dataclasses.dataclass(frozen=True)
class QBAConfig:
    """Static (compile-time) parameters of one QBA experiment.

    Attributes:
      n_parties: number of generals including the commander (MPI world size
        minus the QSD rank in the reference, ``tfg.py:314``).
      size_l: security parameter — length of each party's particle list
        (``sizeL``, ``tfg.py:366``).
      n_dishonest: number of Byzantine parties, sampled from ranks
        ``1..n_parties`` (the commander may be dishonest, ``tfg.py:105``).
      trials: Monte-Carlo batch size (new axis; the reference runs a single
        trial per mpiexec invocation).
      seed: PRNG seed (the reference uses the global NumPy MT19937; here an
        explicit threefry key tree).
      qsim_path: "factorized" (closed-form sampler, any size — SURVEY §2.6),
        "dense" (full joint statevector, validation only, <= ~20 qubits),
        "dense_pallas" (dense path on the fused single-kernel Pallas
        executor, :mod:`qba_tpu.ops.fused_circuit`), or "stabilizer"
        (vectorized Clifford tableau, :mod:`qba_tpu.qsim.stabilizer` —
        executes the actual joint circuits at ANY party count, incl.
        the reference's 48-qubit 11-party construction).
      max_accepts_per_round: static bound on mailbox slots per (sender,
        round). A lieutenant accepts each order value at most once
        (``v not in Vi``, ``tfg.py:294``), so ``w`` is a universal bound;
        smaller values trade memory for a recorded overflow flag.
      round_engine: "auto" (default — the fastest engine that compiles
        for this config: the packet-tiled kernel first (after the
        round-4 pool work it wins at every measured scale, 12-110% —
        docs/PERF.md), the fused monolithic Pallas round kernel
        second, pure XLA as the final fallback — see
        :func:`qba_tpu.rounds.engine.resolve_round_engine`), "xla",
        "pallas" (forces the monolithic kernel; interpreter mode
        off-TPU), "pallas_tiled" (forces the tiled engine —
        lossless at scales the monolithic kernel cannot compile,
        :mod:`qba_tpu.ops.round_kernel_tiled`), or "pallas_fused"
        (forces the fused single-launch round kernel — verdict +
        rebuild in one ``pallas_call`` per round, optionally
        trial-packed; demotes to the two-kernel tiled path with a
        warning where it doesn't compile), or "pallas_mega" (forces
        the trial megakernel — decode + the whole in-kernel round
        loop + decision reduce in ONE ``pallas_call`` per trial
        batch, :mod:`qba_tpu.ops.trial_megakernel`; demotes to the
        fused per-round engine with a warning where the VMEM budget
        refuses it or when ``collect_counters`` needs the host
        scan).  All engines are bit-identical
        (tests/test_round_kernel.py,
        tests/test_round_kernel_tiled.py,
        tests/test_round_kernel_fused.py,
        tests/test_trial_megakernel.py).
      tp_comms: per-round communication path of the party-sharded
        (dp × tp) engine (:mod:`qba_tpu.parallel.spmd`): "auto"
        (default — the double-buffered neighbor-ring shuffle, the
        KI-2-friendly hot path since round 9), "ring" (force the ring:
        ``pltpu.make_async_remote_copy`` remote DMA on TPU, a masked
        ``lax.ppermute`` ring off-TPU — bit-identical by construction),
        or "all_gather" (force the legacy one-collective w-wide gather
        — the escape hatch, and the bit-identity reference the ring is
        pinned against in tests/test_parallel.py).  Ignored outside
        ``run_trials_spmd``.
      tiled_block: explicit packet-block size for the tiled engine
        (must divide ``n_lieutenants * slots``); None = probe-chosen.
      trial_pack: explicit trial-pack factor ``k`` for the fused round
        kernel (``k`` trials folded into one kernel grid — must be
        >= 1 and divide ``trials`` to take effect); None =
        probe-chosen on TPU, 1 off-TPU.
      max_evidence_rows: static bound on |L| (``max_l``); None = the
        derived ``n_dishonest + 2``.  Validated ``>= n_rounds + 1`` —
        the batched engines compute the own-row consistency terms under
        the invariant that ``append_own`` never drops a row for
        fullness (``len(L) == round+1`` at acceptance, ``tfg.py:294``),
        so a smaller bound would silently split them from the
        ``consistent_after_append`` spec.
      delivery: "sync" (race-free idealization, default) or "racy" —
        model the reference's barrier race (a packet missing its round's
        ``Iprobe`` drain is silently lost, ``tfg.py:294,341``) as an
        independent per-(packet, receiver) loss with probability
        ``p_late``.  See docs/DIVERGENCES.md D1.
      p_late: per-delivery lateness probability under ``delivery="racy"``.
      attack_scope: "delivery" (default) — each dishonest delivery draws
        an independent attack action, the intended per-recipient law; or
        "broadcast" — reproduce the reference's *actual* shared-object
        mutation semantics (``tfg.py:271-284``): ``P.clear()`` /
        ``L.clear()`` at one recipient of a broadcast leak into every
        later recipient, and a forged ``v`` persists until re-forged.
        Only meaningful for ``strategy="reference"`` (the leak chain
        models the reference's mutation accident; the zoo strategies
        define per-delivery laws).  See docs/DIVERGENCES.md D3.
      strategy: adversary strategy (the zoo,
        :mod:`qba_tpu.adversary.model`): "reference" (default — the
        reference's random 4-action attack, bit-identical to historical
        outputs), "collude" (all traitors forge one shared per-trial
        target value), "adaptive" (drop-heavy reconnaissance in early
        rounds, forge-heavy in late rounds, forged values conditioned
        on the value the sender received), or "split" (commander
        parity-equivocation + lieutenant worst-case P-set forgery that
        fabricates maximal evidence masks).  Every strategy is
        expressed as the same effective-edit arrays from
        :func:`~qba_tpu.adversary.sample_attacks_round`, so all round
        engines/backends consume it unchanged and bit-identically.
      p_depolarize: per-qubit depolarizing probability applied to the
        quantum resource state before measurement (uniform X/Y/Z Pauli
        with probability ``p``; keeps the stabilizer tableau Clifford).
        0.0 (default) leaves every qsim path byte-identical to the
        noiseless sampler.
      p_measure_flip: classical per-bit measurement flip probability
        applied to every measured qubit.  0.0 (default) = noiseless.
      racy_mode: under ``delivery="racy"``: "loss" (default) — a late
        packet is silently lost, the *effect* of the reference's barrier
        race; or "defer" — the *mechanism*: the packet is delivered in
        the next round's drain, where ``len(L) == round+1``
        (``tfg.py:294``) necessarily rejects it.  Provably
        decision-equivalent (a once-deferred packet can never satisfy
        the evidence-length check).  BOTH message-level engines (local
        Python and the C++ runtime) execute the mechanism — deferred
        queues, next-round re-drain, the deferred deliveries in the
        event trail; the vectorized jax engines realize it through the
        equivalence (computing the always-rejected re-deliveries would
        be dead code), and ``run -v`` on the jax backend replays
        displayed trials through the local backend so the trail still
        shows the mechanism.  ``tests/test_racy.py`` pins the
        cross-mode and cross-backend decision match.  See
        docs/DIVERGENCES.md D1.
      mega_gen: where the trial megakernel generates the step-1
        particle pool: "auto" (default — fuse the PR 7 bit-packed
        GF(2) stabilizer sampler into the megakernel's entry whenever
        ``qsim_path="stabilizer"`` and the tableau fits the megakernel
        VMEM budget, otherwise generate on the host exactly as every
        other engine does), "gf2" (force the in-VMEM generation —
        requires ``qsim_path="stabilizer"``; demotes to the host path
        with a recorded warning when the tableau busts the VMEM budget
        or the gen-fused plan refuses to compile), or "host" (force
        host-side generation).  Bit-identical either way by
        construction: both paths share the GF(2) measurement-sweep
        algebra (:func:`qba_tpu.gf2.symplectic.gf2_measure_sweep`)
        under the same key tree.  Ignored by every non-mega engine.
      collect_counters: emit on-device protocol counters
        (:class:`qba_tpu.rounds.engine.ProtocolCounters`) as an
        auxiliary per-trial output of the round engines:
        rounds-to-first-acceptance per (receiver, value), per-value
        accept counts, per-round accept totals, the per-receiver slot
        high-water mark, and per-round overflow flags.  Computed purely
        from the accepted-set deltas the round scan already carries, so
        the PRIMARY outputs (decisions/success/vi/overflow) are
        bit-identical with counters on or off
        (tests/test_telemetry.py), and no extra dots enter the traced
        paths (the ``qba-tpu lint`` KI-3 gate stays clean).  Default
        off: the counters add scan-carry state and host readback bytes.
    """

    n_parties: int
    size_l: int
    n_dishonest: int = 0
    trials: int = 1
    seed: int = 0
    qsim_path: str = "factorized"
    max_accepts_per_round: int | None = None
    delivery: str = "sync"
    p_late: float = 0.0
    round_engine: str = "auto"
    attack_scope: str = "delivery"
    strategy: str = "reference"
    p_depolarize: float = 0.0
    p_measure_flip: float = 0.0
    racy_mode: str = "loss"
    tp_comms: str = "auto"
    tiled_block: int | None = None
    trial_pack: int | None = None
    max_evidence_rows: int | None = None
    collect_counters: bool = False
    mega_gen: str = "auto"

    def __post_init__(self) -> None:
        if self.n_parties < 2:
            raise ValueError("n_parties must be >= 2 (commander + >=1 lieutenant)")
        if self.size_l < 1:
            raise ValueError("size_l must be >= 1")
        if not 0 <= self.n_dishonest <= self.n_parties:
            raise ValueError(
                f"n_dishonest must be in [0, n_parties]; got {self.n_dishonest}"
            )
        if self.trials < 1:
            raise ValueError("trials must be >= 1")
        if self.qsim_path not in (
            "factorized", "dense", "dense_pallas", "stabilizer"
        ):
            raise ValueError(f"unknown qsim_path {self.qsim_path!r}")
        if self.qsim_path.startswith("dense") and (
            self.total_qubits > DENSE_QUBIT_CAP
        ):
            raise ValueError(
                f"dense qsim path infeasible at {self.total_qubits} qubits; "
                "use qsim_path='factorized'"
            )
        if self.max_accepts_per_round is not None and self.max_accepts_per_round < 1:
            raise ValueError("max_accepts_per_round must be >= 1")
        if self.delivery not in ("sync", "racy"):
            raise ValueError(f"unknown delivery model {self.delivery!r}")
        if not 0.0 <= self.p_late <= 1.0:
            raise ValueError("p_late must be in [0, 1]")
        if self.p_late > 0.0 and self.delivery != "racy":
            raise ValueError("p_late > 0 requires delivery='racy'")
        if self.round_engine not in (
            "auto", "xla", "pallas", "pallas_tiled", "pallas_fused",
            "pallas_mega",
        ):
            raise ValueError(f"unknown round_engine {self.round_engine!r}")
        if self.tp_comms not in ("auto", "ring", "all_gather"):
            raise ValueError(
                f"unknown tp_comms {self.tp_comms!r}; expected 'auto', "
                "'ring', or 'all_gather'"
            )
        if self.tiled_block is not None:
            n_pool = self.n_lieutenants * self.slots
            if self.tiled_block < 1 or n_pool % self.tiled_block:
                raise ValueError(
                    f"tiled_block={self.tiled_block} must divide "
                    f"n_lieutenants * slots = {n_pool}"
                )
        if self.trial_pack is not None and self.trial_pack < 1:
            raise ValueError(
                f"trial_pack={self.trial_pack} must be >= 1"
            )
        if self.max_evidence_rows is not None and (
            self.max_evidence_rows < self.n_rounds + 1
        ):
            raise ValueError(
                f"max_evidence_rows={self.max_evidence_rows} < n_rounds + 1 "
                f"= {self.n_rounds + 1}: every engine relies on |L| <= "
                "round+1 <= max_l (the append_own fullness guard must be "
                "unreachable, see consistent_after_append); a smaller "
                "bound would drop evidence rows mid-protocol"
            )
        if self.attack_scope not in ("delivery", "broadcast"):
            raise ValueError(f"unknown attack_scope {self.attack_scope!r}")
        # Strategy-zoo membership is validated against the single source
        # of truth in qba_tpu.adversary.model (imported lazily: config is
        # imported by the adversary module).
        from qba_tpu.adversary.model import STRATEGIES

        if self.strategy not in STRATEGIES:
            raise ValueError(
                f"unknown strategy {self.strategy!r}; "
                f"expected one of {sorted(STRATEGIES)}"
            )
        if self.attack_scope == "broadcast" and self.strategy != "reference":
            raise ValueError(
                "attack_scope='broadcast' models the reference's "
                "shared-object mutation accident and is only defined for "
                f"strategy='reference'; got strategy={self.strategy!r}"
            )
        if not 0.0 <= self.p_depolarize <= 1.0:
            raise ValueError(
                f"p_depolarize must be in [0, 1]; got {self.p_depolarize}"
            )
        if not 0.0 <= self.p_measure_flip <= 1.0:
            raise ValueError(
                f"p_measure_flip must be in [0, 1]; got {self.p_measure_flip}"
            )
        if self.mega_gen not in ("auto", "gf2", "host"):
            raise ValueError(
                f"unknown mega_gen {self.mega_gen!r}; expected 'auto', "
                "'gf2', or 'host'"
            )
        if self.mega_gen == "gf2" and self.qsim_path != "stabilizer":
            raise ValueError(
                "mega_gen='gf2' fuses the GF(2) stabilizer sampler into "
                "the trial megakernel and is only defined for "
                f"qsim_path='stabilizer'; got qsim_path={self.qsim_path!r}"
            )
        if self.racy_mode not in ("loss", "defer"):
            raise ValueError(f"unknown racy_mode {self.racy_mode!r}")
        if self.racy_mode == "defer" and self.delivery != "racy":
            raise ValueError("racy_mode='defer' requires delivery='racy'")

    # Derived parameters (``tfg.py:316-318``).
    @property
    def n_qubits(self) -> int:
        """Qubits per party group: ceil(log2(n_parties + 1))."""
        return max(1, math.ceil(math.log2(self.n_parties + 1)))

    @property
    def w(self) -> int:
        """Number of possible order values, 2**n_qubits."""
        return 2 ** self.n_qubits

    @property
    def total_qubits(self) -> int:
        """Joint circuit width: (n_parties + 1) * n_qubits (``tfg.py:16``)."""
        return (self.n_parties + 1) * self.n_qubits

    @property
    def n_lieutenants(self) -> int:
        """Ranks 2..n_parties of the reference."""
        return self.n_parties - 1

    @property
    def n_rounds(self) -> int:
        """Voting rounds 1..n_dishonest+1 (``tfg.py:337``)."""
        return self.n_dishonest + 1

    @property
    def max_l(self) -> int:
        """Static bound on |L|: len(L) == round+1 at acceptance
        (``tfg.py:294``), round <= n_dishonest+1, so |L| <= n_dishonest+2.
        Overridable upward via ``max_evidence_rows`` (validated
        ``>= n_rounds + 1`` in ``__post_init__``)."""
        if self.max_evidence_rows is not None:
            return self.max_evidence_rows
        return self.n_dishonest + 2

    @property
    def slots(self) -> int:
        """Mailbox slots per (sender, round)."""
        if self.max_accepts_per_round is not None:
            return min(self.max_accepts_per_round, self.w)
        return self.w

    @property
    def no_decision(self) -> int:
        """Sentinel decision for an empty accepted-set Vi.

        Divergence from the reference, which raises ``ValueError`` on
        ``min(set())`` at ``tfg.py:306``; we return ``w`` (an impossible
        order value) and keep the trial alive.
        """
        return self.w
