"""Persistent warm-start artifacts: XLA executables + resolver plans.

The protocol programs take tens of seconds to compile (remote TPU
compiles especially); caching compiled executables on disk makes
repeated CLI/bench invocations of the same config start in seconds.
Library imports never enable this — only the tool entry points call it —
so embedding applications keep full control of JAX global config.

The serving subsystem (:mod:`qba_tpu.serve`) promotes this module from
the CLI's opt-in convenience to a first-class artifact layout: a cache
directory holds the XLA compilation cache (``xla/``) next to the saved
resolver-plan file (``plans.json`` — every memoized block/variant/pack
verdict, :func:`qba_tpu.ops.round_kernel_tiled.export_resolver_state`),
so a server boot restores BOTH halves of warm start: compiled
executables from the XLA cache, dispatch decisions from the plan file —
zero compile probes on the second boot (tests/test_serve.py).
"""

from __future__ import annotations

import os


def default_cache_root() -> str:
    """The per-user artifact root (override with ``QBA_CACHE_ROOT``)."""
    return os.environ.get(
        "QBA_CACHE_ROOT",
        os.path.join(os.path.expanduser("~"), ".cache", "qba_tpu"),
    )


def xla_cache_dir(cache_dir: str | None = None) -> str:
    """The XLA compilation-cache directory inside ``cache_dir`` (default:
    the per-user root).  The legacy env override ``QBA_COMPILE_CACHE``
    keeps working when no explicit directory is given."""
    if cache_dir is not None:
        return os.path.join(cache_dir, "xla")
    return os.environ.get(
        "QBA_COMPILE_CACHE", os.path.join(default_cache_root(), "jax")
    )


def plans_path(cache_dir: str | None = None) -> str:
    """The saved resolver-plan artifact inside ``cache_dir`` (default:
    the per-user root) — see :mod:`qba_tpu.serve.persist`."""
    return os.path.join(cache_dir or default_cache_root(), "plans.json")


def plans_lock_path(cache_dir: str | None = None) -> str:
    """The advisory lock file guarding ``plans.json`` reads/writes.

    A fleet boot starts N replicas against one cache directory; the
    lock serializes their save/load so no replica ever observes a torn
    artifact and no replica's flush clobbers another's freshly merged
    plans (:mod:`qba_tpu.serve.persist`)."""
    return plans_path(cache_dir) + ".lock"


def enable_compile_cache(path: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``path`` (default:
    :func:`xla_cache_dir`, whose ``QBA_COMPILE_CACHE`` env override can
    be set empty to disable).  Harmless if the directory is unwritable
    (jax warns and continues).  Returns the directory actually set, or
    None when disabled."""
    import jax

    path = xla_cache_dir() if path is None else path
    if path:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        return path
    return None
