"""Persistent XLA compilation cache for the command-line tools.

The protocol programs take tens of seconds to compile (remote TPU
compiles especially); caching compiled executables on disk makes
repeated CLI/bench invocations of the same config start in seconds.
Library imports never enable this — only the tool entry points call it —
so embedding applications keep full control of JAX global config.
"""

from __future__ import annotations

import os


def enable_compile_cache() -> None:
    """Point JAX's persistent compilation cache at a per-user directory
    (override with ``QBA_COMPILE_CACHE``; set it empty to disable).
    Harmless if the directory is unwritable (jax warns and continues)."""
    import jax

    path = os.environ.get(
        "QBA_COMPILE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "qba_tpu", "jax"),
    )
    if path:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
