"""Native host runtime: ctypes bindings to ``libqba_native.so``.

The reference's host runtime is native by dependency — an MPI C library
for transport and qsimov's C core for simulation (SURVEY §2.15-2.16).
Here TPU compute stays in XLA; the native layer provides the host-side
message-level engine + PvL wire codec (``src/qba_native.cc``), built on
demand with ``make`` (g++, no dependencies) and cached by source mtime.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libqba_native.so")
_SRC = os.path.join(_DIR, "src", "qba_native.cc")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None

_i32p = ctypes.POINTER(ctypes.c_int32)
_u8p = ctypes.POINTER(ctypes.c_uint8)


class NativeUnavailableError(RuntimeError):
    """The native library could not be built (missing toolchain, compile
    failure).  A dedicated type so the CLI can report exactly this
    optional-dependency condition cleanly while other RuntimeErrors keep
    their tracebacks."""


def _build() -> None:
    proc = subprocess.run(
        ["make", "-C", _DIR],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        raise NativeUnavailableError(
            f"native build failed:\n{proc.stdout}\n{proc.stderr}"
        )


def load() -> ctypes.CDLL:
    """Build (if stale) and load the native library; thread-safe, cached."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(
            _SRC
        ):
            _build()
        lib = ctypes.CDLL(_SO)

        lib.qba_consistent.restype = ctypes.c_int
        lib.qba_consistent.argtypes = [
            ctypes.c_int32, _i32p, _i32p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int32,
        ]
        lib.qba_encode_pvl.restype = ctypes.c_int
        lib.qba_encode_pvl.argtypes = [
            _i32p, ctypes.c_int, ctypes.c_int32, _i32p, _i32p, ctypes.c_int,
            ctypes.c_int, _i32p, ctypes.c_int,
        ]
        lib.qba_decode_pvl.restype = ctypes.c_int
        lib.qba_decode_pvl.argtypes = [
            _i32p, ctypes.c_int, _i32p, ctypes.c_int, _i32p, _i32p,
            ctypes.c_int, ctypes.c_int, _i32p,
        ]
        lib.qba_run_trial.restype = ctypes.c_int
        lib.qba_run_trial.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int32,
            ctypes.c_int, ctypes.c_int, _u8p, _i32p, _i32p, ctypes.c_int32,
            _i32p, _i32p, _u8p, _i32p, _i32p, ctypes.c_int32, _i32p,
        ]
        lib.qba_run_trials.restype = ctypes.c_int
        lib.qba_run_trials.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int32, ctypes.c_int, ctypes.c_int, _u8p,
            _i32p, _i32p, _i32p, _i32p, _i32p, _u8p, _i32p,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    """True if the native library can be built/loaded on this host."""
    try:
        load()
        return True
    except (RuntimeError, OSError):
        return False
