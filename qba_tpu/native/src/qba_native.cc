// qba_native — C++ host runtime for the QBA protocol.
//
// The reference delegates its entire host runtime to native dependencies:
// an MPI C library for transport (tfg.py:199-263,310-363) and qsimov's C
// core for circuit simulation (tfg.py:68-84).  This framework keeps TPU
// compute in XLA (qba_tpu/qsim, qba_tpu/rounds) and provides the native
// host-side runtime here: a tagged PvL wire codec (the send_pvl/recv_pvl
// format, tfg.py:199-263) and a message-level protocol engine that runs a
// full trial over per-party mailboxes (tfg.py:166-363).
//
// Randomness is pre-sampled by the caller (honesty mask, particle lists,
// commander orders, per-cell attack/late-loss triples) so the engine is a
// deterministic function — bit-compatible with both Python backends for
// the same key tree; tests/test_native.py enforces the three-way match.
//
// Build: make -C qba_tpu/native  (g++ -O2 -shared; no dependencies).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <set>
#include <thread>
#include <vector>

namespace {

using Tuple = std::vector<int32_t>;

// ---------------------------------------------------------------------------
// Consistency predicate (tfg.py:87-98): (1) all tuples the same length,
// (2) every element in [0, w] and != v, (3) every pair of tuples differs
// at every index.  Empty L is consistent.
bool consistent(int32_t v, const std::set<Tuple>& L, int32_t w) {
  if (L.empty()) return true;
  const size_t n = L.begin()->size();
  for (const Tuple& t : L) {
    if (t.size() != n) return false;
    for (int32_t x : t) {
      if (x < 0 || x > w || x == v) return false;
    }
  }
  for (auto a = L.begin(); a != L.end(); ++a) {
    for (auto b = std::next(a); b != L.end(); ++b) {
      for (size_t k = 0; k < n; ++k) {
        if ((*a)[k] == (*b)[k]) return false;
      }
    }
  }
  return true;
}

// ---------------------------------------------------------------------------
// PvL wire codec.  Flat int32 layout mirroring the reference's tag
// sequence (tfg.py:199-263): |P|, P..., v, |L|, then per tuple: len,
// elements.  Returns the number of int32 words written, or -1 on
// insufficient capacity.
int encode_pvl(const std::vector<int32_t>& p, int32_t v,
               const std::set<Tuple>& L, int32_t* out, int cap) {
  std::vector<int32_t> buf;
  buf.push_back(static_cast<int32_t>(p.size()));
  buf.insert(buf.end(), p.begin(), p.end());
  buf.push_back(v);
  buf.push_back(static_cast<int32_t>(L.size()));
  for (const Tuple& t : L) {
    buf.push_back(static_cast<int32_t>(t.size()));
    buf.insert(buf.end(), t.begin(), t.end());
  }
  if (static_cast<int>(buf.size()) > cap) return -1;
  std::copy(buf.begin(), buf.end(), out);
  return static_cast<int>(buf.size());
}

// Returns words consumed, or -1 on a malformed buffer.
int decode_pvl(const int32_t* buf, int len, std::vector<int32_t>* p,
               int32_t* v, std::set<Tuple>* L) {
  int i = 0;
  if (i >= len) return -1;
  int32_t np = buf[i++];
  if (np < 0 || i + np > len) return -1;
  p->assign(buf + i, buf + i + np);
  i += np;
  if (i >= len) return -1;
  *v = buf[i++];
  if (i >= len) return -1;
  int32_t nt = buf[i++];
  if (nt < 0) return -1;
  L->clear();
  for (int32_t t = 0; t < nt; ++t) {
    if (i >= len) return -1;
    int32_t tl = buf[i++];
    if (tl < 0 || i + tl > len) return -1;
    L->insert(Tuple(buf + i, buf + i + tl));
    i += tl;
  }
  return i;
}

struct Packet {
  std::vector<int32_t> p;
  int32_t v;
  std::set<Tuple> L;
};

}  // namespace

extern "C" {

// consistent() over a flat [n_tuples, max_len] tuple matrix with per-tuple
// lengths; exposed for differential tests against the Python/JAX versions.
int qba_consistent(int32_t v, const int32_t* tuples, const int32_t* lens,
                   int n_tuples, int max_len, int32_t w) {
  std::set<Tuple> L;
  for (int t = 0; t < n_tuples; ++t) {
    L.insert(Tuple(tuples + t * max_len, tuples + t * max_len + lens[t]));
  }
  return consistent(v, L, w) ? 1 : 0;
}

int qba_encode_pvl(const int32_t* p, int np, int32_t v, const int32_t* tuples,
                   const int32_t* lens, int n_tuples, int max_len,
                   int32_t* out, int cap) {
  std::vector<int32_t> pv(p, p + np);
  std::set<Tuple> L;
  for (int t = 0; t < n_tuples; ++t) {
    L.insert(Tuple(tuples + t * max_len, tuples + t * max_len + lens[t]));
  }
  return encode_pvl(pv, v, L, out, cap);
}

// Decode into flat buffers: p_out (cap np_cap), tuple matrix
// [nt_cap, max_len] + lens.  Writes (np, v, nt) into header_out[0..2].
// Returns words consumed or -1.
int qba_decode_pvl(const int32_t* buf, int len, int32_t* p_out, int np_cap,
                   int32_t* tuples_out, int32_t* lens_out, int nt_cap,
                   int max_len, int32_t* header_out) {
  std::vector<int32_t> p;
  int32_t v;
  std::set<Tuple> L;
  int used = decode_pvl(buf, len, &p, &v, &L);
  if (used < 0) return -1;
  if (static_cast<int>(p.size()) > np_cap ||
      static_cast<int>(L.size()) > nt_cap)
    return -1;
  std::copy(p.begin(), p.end(), p_out);
  int t = 0;
  for (const Tuple& tup : L) {
    if (static_cast<int>(tup.size()) > max_len) return -1;
    lens_out[t] = static_cast<int32_t>(tup.size());
    std::copy(tup.begin(), tup.end(), tuples_out + t * max_len);
    ++t;
  }
  header_out[0] = static_cast<int32_t>(p.size());
  header_out[1] = v;
  header_out[2] = static_cast<int32_t>(L.size());
  return used;
}

// Full message-level trial (tfg.py:166-363) over pre-sampled randomness.
//
//   honest   : uint8[n_parties+1], rank-indexed (rank 0 = QSD)
//   lists    : int32[(n_parties+1) * size_l], row-major
//   v_sent   : int32[n_lieu] per-lieutenant commander order (equivocation
//              already applied, tfg.py:169-181)
//   attacks  : int32[n_rounds * n_lieu * n_lieu * slots * 3] — per
//              (round-1, receiver, sender*slots+slot) triples
//              (attack, rand_v, late): the sample_attacks_round layout.
//              `attack` is the effective edit bitmask (bit0 drop, bit1
//              forge-v, bit2 clear-P, bit3 clear-L, bit4 forge-P: the
//              fabricated all-positions evidence mask, applied after the
//              clears so forgery wins) with the configured attack scope
//              and strategy already folded in, so this engine is
//              scope- and strategy-agnostic; `late` = 1 -> the delivery is silently
//              late: under racy_defer=0 the delivery is silently lost
//              before any corruption; under racy_defer=1 the corrupted
//              packet is instead delivered at the start of the NEXT
//              round's drain, where the evidence-length check
//              necessarily rejects it — the reference's actual race
//              mechanism (the barrier-race model of
//              docs/DIVERGENCES.md D1; late is all 0 under
//              delivery="sync")
//   decisions_out : int32[n_parties] (index 0 = commander)
//   vi_out   : uint8[n_lieu * w] accepted-set masks
//   flags_out: int32[2] = {success, overflow}
//   trace_out/trace_cap/trace_len : optional protocol event trail — the
//              in-engine analog of the reference's mpi_print sites
//              (tfg.py:190,203,229,275-284,294).  When trace_out is
//              non-null, fixed 7-int32 records {kind, round, sender_rank,
//              recv_rank, v, a, b} are appended (capacity trace_cap
//              records; excess events are dropped and *trace_len saturates
//              at trace_cap so the caller can detect truncation):
//                kind 1 step2 send       (a=|P|, b=0)          tfg.py:203
//                kind 2 step3a receive   (a=accepted, b=reason) tfg.py:190
//                kind 3 racy late loss                      DIVERGENCES D1
//                kind 4 attack           (a=edit bitmask)  tfg.py:275-284
//                kind 5 round receive    (a=accepted, b=reason) tfg.py:294
//                kind 6 rebroadcast      (a=|P|, b=|L|)        tfg.py:229
//                kind 9 deferred receive (a=accepted, b=reason) — a
//                       kind-5 delivery that arrived one round late
//                       (racy_defer)                      DIVERGENCES D1
//                kind 10 late defer      — the packet was queued for
//                       the next round                    DIVERGENCES D1
//                kind 7 vi snapshot header (a=|Vi|), followed by |Vi|
//                       kind 8 records {8, round, rank, 0, value, 0, 0}
//                       — value list form, exact for any w
//              reason codes: 0 accepted, 1 inconsistent, 2 duplicate-v,
//              3 wrong-evidence-len (the lieu_receive condition order,
//              tfg.py:294).
//
// Packets move between parties through the PvL codec (encode on send,
// decode on delivery) — the in-process analog of the reference's tagged
// MPI transport.  Returns 0, or -1 on a codec capacity/format error.
int qba_run_trial(int n_parties, int size_l, int n_dishonest, int32_t w,
                  int slots, int racy_defer, const uint8_t* honest,
                  const int32_t* lists,
                  const int32_t* v_sent, int32_t v_comm,
                  const int32_t* attacks, int32_t* decisions_out,
                  uint8_t* vi_out, int32_t* flags_out,
                  int32_t* trace_out, int32_t trace_cap,
                  int32_t* trace_len) {
  const int n_lieu = n_parties - 1;
  const int n_rounds = n_dishonest + 1;
  const int max_l = n_dishonest + 2;
  const int cap = 3 + size_l + max_l * (1 + size_l);

  int32_t n_trace = 0;
  auto trace = [&](int32_t kind, int32_t rnd, int32_t sender, int32_t recv,
                   int32_t v, int32_t a, int32_t b) {
    if (trace_out == nullptr || n_trace >= trace_cap) return;
    int32_t* rec = trace_out + static_cast<size_t>(n_trace) * 7;
    rec[0] = kind; rec[1] = rnd; rec[2] = sender; rec[3] = recv;
    rec[4] = v; rec[5] = a; rec[6] = b;
    ++n_trace;
  };

  auto list_row = [&](int rank) { return lists + rank * size_l; };

  // Step 1b (tfg.py:325-328): positions where the QSD copy differs from
  // the commander's own list are exactly the Q-correlated ones.
  std::vector<int32_t> isq;
  for (int k = 0; k < size_l; ++k) {
    if (list_row(0)[k] != list_row(1)[k]) isq.push_back(k);
  }

  std::vector<std::set<int32_t>> vi(n_lieu);
  bool overflow = false;

  // Mailboxes hold encoded packets; slot index = append order (the dense
  // mailbox tensor numbering shared with the JAX engine).
  using Wire = std::vector<int32_t>;
  std::vector<std::vector<Wire>> mailbox(n_lieu);

  auto own_sublist = [&](int lieu, const std::vector<int32_t>& p) {
    Tuple t;
    t.reserve(p.size());
    for (int32_t j : p) t.push_back(list_row(lieu + 2)[j]);
    return t;
  };

  auto push = [&](std::vector<Wire>* box, const Packet& pk) -> int {
    Wire wire(cap);
    int n = encode_pvl(pk.p, pk.v, pk.L, wire.data(), cap);
    if (n < 0) return -1;
    wire.resize(n);
    box->push_back(std::move(wire));
    return 0;
  };

  // Step 2 + 3a (tfg.py:166-196).
  for (int i = 0; i < n_lieu; ++i) {
    Packet pk;
    pk.v = v_sent[i];
    for (int32_t k : isq) {
      if (list_row(1)[k] == pk.v) pk.p.push_back(k);
    }
    trace(1, 0, 1, i + 2, pk.v, static_cast<int32_t>(pk.p.size()), 0);
    pk.L.insert(own_sublist(i, pk.p));
    const bool ok3a = consistent(pk.v, pk.L, w);
    trace(2, 0, 1, i + 2, pk.v, ok3a ? 1 : 0, ok3a ? 0 : 1);
    if (ok3a) {
      vi[i].insert(pk.v);
      if (push(&mailbox[i], pk) < 0) return -1;
    }
  }

  // Step 3b (tfg.py:337-348): synchronous rounds.  Under racy_defer,
  // late packets carry over one round (corrupted with the ORIGINAL
  // round's draws — the reference corrupts at send time, before the
  // race) and are drained first, where the evidence-length check
  // necessarily rejects them (docs/DIVERGENCES.md D1).
  struct Late { int sender_rank; Packet pk; };
  std::vector<std::vector<Late>> deferred(n_lieu);
  for (int rnd = 1; rnd <= n_rounds; ++rnd) {
    std::vector<std::vector<Wire>> out(n_lieu);
    std::vector<std::vector<Late>> next_deferred(n_lieu);
    // lieu_receive (tfg.py:289-300), shared by deferred + fresh traffic.
    auto lieu_receive = [&](int recv, int sender_rank, Packet& pk,
                            bool was_deferred) -> int {
      pk.L.insert(own_sublist(recv, pk.p));
      int32_t reason;
      if (!consistent(pk.v, pk.L, w)) reason = 1;
      else if (vi[recv].count(pk.v)) reason = 2;
      else if (static_cast<int>(pk.L.size()) != rnd + 1) reason = 3;
      else reason = 0;
      trace(was_deferred ? 9 : 5, rnd, sender_rank, recv + 2, pk.v,
            reason == 0 ? 1 : 0, reason);
      if (reason == 0) {
        vi[recv].insert(pk.v);
        if (rnd <= n_dishonest) {
          if (static_cast<int>(out[recv].size()) < slots) {
            trace(6, rnd, recv + 2, 0, pk.v,
                  static_cast<int32_t>(pk.p.size()),
                  static_cast<int32_t>(pk.L.size()));
            if (push(&out[recv], pk) < 0) return -1;
          } else {
            overflow = true;
          }
        }
      }
      return 0;
    };
    // Deferred arrivals from the previous round drain first (they were
    // in the queue before this round's traffic; deterministic order).
    for (int recv = 0; recv < n_lieu; ++recv) {
      for (Late& d : deferred[recv]) {
        if (lieu_receive(recv, d.sender_rank, d.pk, true) < 0) return -1;
      }
    }
    for (int recv = 0; recv < n_lieu; ++recv) {
      for (int sender = 0; sender < n_lieu; ++sender) {
        int n_slots = std::min<int>(slots, mailbox[sender].size());
        for (int slot = 0; slot < n_slots; ++slot) {
          if (sender == recv) continue;
          const Wire& wire = mailbox[sender][slot];
          Packet pk;
          if (decode_pvl(wire.data(), static_cast<int>(wire.size()), &pk.p,
                         &pk.v, &pk.L) < 0)
            return -1;
          const int32_t* a =
              attacks + (((rnd - 1) * n_lieu + recv) * n_lieu * slots +
                         sender * slots + slot) *
                            3;
          if (a[2] && !racy_defer) {  // racy late loss (DIVERGENCES.md D1)
            trace(3, rnd, sender + 2, recv + 2, 0, 0, 0);
            continue;
          }
          if (!honest[sender + 2]) {  // tfg.py:271-284
            trace(4, rnd, sender + 2, recv + 2, 0, a[0], 0);
            if (a[0] & 1) continue;       // drop
            if (a[0] & 2) pk.v = a[1];    // forged v
            if (a[0] & 4) pk.p.clear();   // clear P
            if (a[0] & 8) pk.L.clear();   // clear L
            if (a[0] & 16) {              // forge-P: full mask wins
              pk.p.resize(size_l);
              for (int32_t k = 0; k < size_l; ++k) pk.p[k] = k;
            }
          }
          if (a[2]) {  // racy_defer: queue for the next round's drain
            trace(10, rnd, sender + 2, recv + 2, 0, 0, 0);
            next_deferred[recv].push_back(Late{sender + 2, std::move(pk)});
            continue;
          }
          if (lieu_receive(recv, sender + 2, pk, false) < 0) return -1;
        }
      }
    }
    for (int i = 0; i < n_lieu; ++i) {
      trace(7, rnd, i + 2, 0, 0, static_cast<int32_t>(vi[i].size()), 0);
      for (int32_t x : vi[i]) trace(8, rnd, i + 2, 0, x, 0, 0);
    }
    mailbox = std::move(out);
    deferred = std::move(next_deferred);
  }

  // Decision + verdict (tfg.py:303-306,351-363; empty-Vi sentinel = w,
  // docs/DIVERGENCES.md D2).
  decisions_out[0] = v_comm;
  for (int i = 0; i < n_lieu; ++i) {
    decisions_out[i + 1] = vi[i].empty() ? w : *vi[i].begin();
    for (int32_t x = 0; x < w; ++x) {
      vi_out[i * w + x] = vi[i].count(x) ? 1 : 0;
    }
  }
  std::set<int32_t> filtered;
  for (int i = 0; i < n_parties; ++i) {
    if (honest[i + 1]) filtered.insert(decisions_out[i]);
  }
  flags_out[0] = filtered.size() == 1 ? 1 : 0;
  flags_out[1] = overflow ? 1 : 0;
  if (trace_len) *trace_len = n_trace;
  return 0;
}

// Batched Monte-Carlo executor: runs n_trials independent trials across a
// host thread pool (work-stealing via an atomic cursor).  qba_run_trial is
// a pure function of its per-trial inputs, so trials parallelize with no
// shared state beyond the cursor.  All arrays are the single-trial layouts
// stacked along a leading n_trials axis; v_comm becomes int32[n_trials].
//
//   n_threads <= 0 -> std::thread::hardware_concurrency().
//
// Returns 0, or one failing trial's nonzero error code (the first store
// wins; which trial that is depends on thread scheduling).
int qba_run_trials(int n_trials, int n_threads, int n_parties, int size_l,
                   int n_dishonest, int32_t w, int slots, int racy_defer,
                   const uint8_t* honest, const int32_t* lists,
                   const int32_t* v_sent, const int32_t* v_comm,
                   const int32_t* attacks, int32_t* decisions_out,
                   uint8_t* vi_out, int32_t* flags_out) {
  const int n_lieu = n_parties - 1;
  const int n_rounds = n_dishonest + 1;
  const size_t honest_s = static_cast<size_t>(n_parties) + 1;
  const size_t lists_s = honest_s * size_l;
  const size_t vsent_s = n_lieu;
  const size_t att_s = static_cast<size_t>(n_rounds) * n_lieu * n_lieu *
                       slots * 3;
  const size_t dec_s = n_parties;
  const size_t vi_s = static_cast<size_t>(n_lieu) * w;

  if (n_threads <= 0) {
    n_threads = static_cast<int>(std::thread::hardware_concurrency());
    if (n_threads <= 0) n_threads = 1;
  }
  n_threads = std::min(n_threads, n_trials);

  std::atomic<int> cursor(0);
  std::atomic<int> rc(0);
  auto worker = [&]() {
    for (;;) {
      const int t = cursor.fetch_add(1);
      if (t >= n_trials) return;
      const int r = qba_run_trial(
          n_parties, size_l, n_dishonest, w, slots, racy_defer,
          honest + t * honest_s,
          lists + t * lists_s, v_sent + t * vsent_s, v_comm[t],
          attacks + t * att_s, decisions_out + t * dec_s, vi_out + t * vi_s,
          flags_out + t * 2, nullptr, 0, nullptr);
      if (r != 0) {
        int expected = 0;  // first error wins (deterministic reporting)
        rc.compare_exchange_strong(expected, r);
      }
    }
  };

  if (n_threads == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(n_threads);
    for (int i = 0; i < n_threads; ++i) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return rc.load();
}

}  // extern "C"
