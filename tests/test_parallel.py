"""Mesh-sharded execution is placement, not semantics: every parallel
path must reproduce the single-device engine's results bit-for-bit for
the same trial keys (the corruption key tree is indexed by global
(trial, round, receiver, cell), so sharding cannot shift randomness)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qba_tpu.backends.jax_backend import run_trials, trial_keys
from qba_tpu.config import QBAConfig
from qba_tpu.parallel import (
    default_mesh_shape,
    make_mesh,
    run_trials_sharded,
    run_trials_spmd,
)


def assert_trials_equal(a, b):
    np.testing.assert_array_equal(np.asarray(a.trials.success), np.asarray(b.trials.success))
    np.testing.assert_array_equal(np.asarray(a.trials.decisions), np.asarray(b.trials.decisions))
    np.testing.assert_array_equal(np.asarray(a.trials.honest), np.asarray(b.trials.honest))
    np.testing.assert_array_equal(np.asarray(a.trials.vi), np.asarray(b.trials.vi))
    np.testing.assert_array_equal(np.asarray(a.trials.overflow), np.asarray(b.trials.overflow))
    assert float(a.success_rate) == float(b.success_rate)


@pytest.fixture(scope="module")
def n_devices():
    n = len(jax.devices())
    if n < 2 or n % 2 != 0:
        pytest.skip("mesh tests need an even multi-device environment "
                    "(conftest forces an 8-device virtual CPU mesh)")
    return n


class TestDpSharded:
    def test_dp_matches_single_device(self, n_devices):
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=1, trials=n_devices * 2, seed=7)
        mesh = make_mesh({"dp": n_devices})
        ref = run_trials(cfg)
        sharded = run_trials_sharded(cfg, mesh)
        assert_trials_equal(sharded, ref)

    def test_dp_sp_matches_single_device(self, n_devices):
        cfg = QBAConfig(n_parties=5, size_l=8, n_dishonest=2, trials=n_devices, seed=3)
        mesh = make_mesh({"dp": n_devices // 2, "sp": 2})
        ref = run_trials(cfg)
        sharded = run_trials_sharded(cfg, mesh)
        assert_trials_equal(sharded, ref)

    def test_output_sharding_is_dp(self, n_devices):
        cfg = QBAConfig(n_parties=3, size_l=4, trials=n_devices, seed=0)
        mesh = make_mesh({"dp": n_devices})
        out = run_trials_sharded(cfg, mesh)
        # Per-trial outputs stay distributed — no implicit host gather.
        assert len(out.trials.success.sharding.device_set) == n_devices

    def test_indivisible_trials_rejected(self, n_devices):
        cfg = QBAConfig(n_parties=3, size_l=4, trials=n_devices + 1)
        mesh = make_mesh({"dp": n_devices})
        with pytest.raises(ValueError, match="not divisible"):
            run_trials_sharded(cfg, mesh)

    def test_sp_only_mesh(self, n_devices):
        # Pure position sharding, no trial axis in the mesh.
        cfg = QBAConfig(n_parties=3, size_l=8 * n_devices, trials=2, seed=1)
        mesh = make_mesh({"sp": n_devices})
        ref = run_trials(cfg)
        sharded = run_trials_sharded(cfg, mesh)
        assert_trials_equal(sharded, ref)


class TestPartySharded:
    def test_tp_matches_single_device(self, n_devices):
        # n_parties=5 -> 4 lieutenants, shardable over tp=2.
        cfg = QBAConfig(n_parties=5, size_l=8, n_dishonest=2, trials=4, seed=11)
        mesh = make_mesh({"dp": n_devices // 2, "tp": 2})
        ref = run_trials(cfg)
        spmd = run_trials_spmd(cfg, mesh)
        assert_trials_equal(spmd, ref)

    def test_tp4_dishonest_commander_heavy(self, n_devices):
        if n_devices < 4:
            pytest.skip("needs >= 4 devices")
        cfg = QBAConfig(n_parties=5, size_l=8, n_dishonest=3, trials=2, seed=5)
        mesh = make_mesh({"dp": n_devices // 4, "tp": 4})
        ref = run_trials(cfg)
        spmd = run_trials_spmd(cfg, mesh)
        assert_trials_equal(spmd, ref)

    def test_indivisible_lieutenants_rejected(self, n_devices):
        cfg = QBAConfig(n_parties=4, size_l=4, trials=n_devices)  # 3 lieutenants
        mesh = make_mesh({"dp": n_devices // 2, "tp": 2})
        with pytest.raises(ValueError, match="n_lieutenants"):
            run_trials_spmd(cfg, mesh)

    def test_mesh_without_tp_rejected(self, n_devices):
        cfg = QBAConfig(n_parties=5, size_l=4, trials=n_devices)
        mesh = make_mesh({"dp": n_devices})
        with pytest.raises(ValueError, match="'tp' mesh axis"):
            run_trials_spmd(cfg, mesh)


class TestMeshHelpers:
    def test_make_mesh_validates_device_count(self):
        with pytest.raises(ValueError, match="devices"):
            make_mesh({"dp": 3, "tp": 5})

    def test_default_shape_factors(self):
        assert default_mesh_shape(8) == {"dp": 4, "sp": 2}
        assert default_mesh_shape(8, want_tp=True) == {"dp": 4, "tp": 2}
        shape = default_mesh_shape(1)
        assert shape["dp"] == 1

    def test_hybrid_mesh_single_slice_fallback(self, n_devices):
        from qba_tpu.parallel import make_hybrid_mesh

        mesh = make_hybrid_mesh({"dp": n_devices // 2, "tp": 2})
        assert mesh.axis_names == ("dp", "tp")
        assert mesh.devices.shape == (n_devices // 2, 2)

    def test_hybrid_mesh_explicit_slices(self, n_devices):
        import pytest

        from qba_tpu.parallel import make_hybrid_mesh

        mesh = make_hybrid_mesh({"dp": n_devices // 4, "tp": 2}, n_slices=2)
        assert mesh.axis_names == ("dp", "tp")
        assert mesh.devices.shape == (n_devices // 2, 2)
        # Each slice's block stays contiguous along the non-dcn axis.
        assert len(set(d.id for d in mesh.devices.flat)) == n_devices
        with pytest.raises(ValueError, match="dcn_axis"):
            make_hybrid_mesh({"tp": n_devices}, dcn_axis="dp")
        with pytest.raises(ValueError, match="devices"):
            make_hybrid_mesh({"dp": n_devices}, n_slices=3)
