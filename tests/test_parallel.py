"""Mesh-sharded execution is placement, not semantics: every parallel
path must reproduce the single-device engine's results bit-for-bit for
the same trial keys (the corruption key tree is indexed by global
(trial, round, receiver, cell), so sharding cannot shift randomness)."""

import jax
import numpy as np
import pytest

from qba_tpu.backends.jax_backend import run_trials
from qba_tpu.config import QBAConfig
from qba_tpu.parallel import (
    default_mesh_shape,
    make_mesh,
    run_trials_sharded,
    run_trials_spmd,
)


def assert_trials_equal(a, b):
    np.testing.assert_array_equal(np.asarray(a.trials.success), np.asarray(b.trials.success))
    np.testing.assert_array_equal(np.asarray(a.trials.decisions), np.asarray(b.trials.decisions))
    np.testing.assert_array_equal(np.asarray(a.trials.honest), np.asarray(b.trials.honest))
    np.testing.assert_array_equal(np.asarray(a.trials.vi), np.asarray(b.trials.vi))
    np.testing.assert_array_equal(np.asarray(a.trials.overflow), np.asarray(b.trials.overflow))
    assert float(a.success_rate) == float(b.success_rate)


@pytest.fixture(scope="module")
def n_devices():
    n = len(jax.devices())
    if n < 2 or n % 2 != 0:
        pytest.skip("mesh tests need an even multi-device environment "
                    "(conftest forces an 8-device virtual CPU mesh)")
    return n


class TestDpSharded:
    def test_dp_matches_single_device(self, n_devices):
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=1, trials=n_devices * 2, seed=7)
        mesh = make_mesh({"dp": n_devices})
        ref = run_trials(cfg)
        sharded = run_trials_sharded(cfg, mesh)
        assert_trials_equal(sharded, ref)

    def test_dp_sp_matches_single_device(self, n_devices):
        cfg = QBAConfig(n_parties=5, size_l=8, n_dishonest=2, trials=n_devices, seed=3)
        mesh = make_mesh({"dp": n_devices // 2, "sp": 2})
        ref = run_trials(cfg)
        sharded = run_trials_sharded(cfg, mesh)
        assert_trials_equal(sharded, ref)

    def test_output_sharding_is_dp(self, n_devices):
        cfg = QBAConfig(n_parties=3, size_l=4, trials=n_devices, seed=0)
        mesh = make_mesh({"dp": n_devices})
        out = run_trials_sharded(cfg, mesh)
        # Per-trial outputs stay distributed — no implicit host gather.
        assert len(out.trials.success.sharding.device_set) == n_devices

    def test_indivisible_trials_rejected(self, n_devices):
        cfg = QBAConfig(n_parties=3, size_l=4, trials=n_devices + 1)
        mesh = make_mesh({"dp": n_devices})
        with pytest.raises(ValueError, match="not divisible"):
            run_trials_sharded(cfg, mesh)

    def test_sp_only_mesh(self, n_devices):
        # Pure position sharding, no trial axis in the mesh.
        cfg = QBAConfig(n_parties=3, size_l=8 * n_devices, trials=2, seed=1)
        mesh = make_mesh({"sp": n_devices})
        ref = run_trials(cfg)
        sharded = run_trials_sharded(cfg, mesh)
        assert_trials_equal(sharded, ref)


class TestPartySharded:
    def test_tp_matches_single_device(self, n_devices):
        # n_parties=5 -> 4 lieutenants, shardable over tp=2.
        cfg = QBAConfig(n_parties=5, size_l=8, n_dishonest=2, trials=4, seed=11)
        mesh = make_mesh({"dp": n_devices // 2, "tp": 2})
        ref = run_trials(cfg)
        spmd = run_trials_spmd(cfg, mesh)
        assert_trials_equal(spmd, ref)

    def test_tp_broadcast_scope_and_racy(self, n_devices):
        # The scope/racy semantics are folded into the shared draw arrays
        # BEFORE the per-receiver slicing, so placement cannot change
        # them; pin it for the non-default modes too.
        cfg = QBAConfig(
            n_parties=5, size_l=8, n_dishonest=2, trials=4, seed=12,
            attack_scope="broadcast", delivery="racy", p_late=0.4,
        )
        mesh = make_mesh({"dp": n_devices // 2, "tp": 2})
        assert_trials_equal(run_trials_spmd(cfg, mesh), run_trials(cfg))

    def test_tp_pallas_kernel_matches_xla(self, n_devices):
        # The party-sharded Pallas round-kernel variant (each device's
        # kernel drains its receiver block against the gathered global
        # mailbox, block offset as a runtime operand) must be
        # bit-identical to the single-device XLA engine.  Interpret mode
        # on the virtual CPU mesh; the same build runs Mosaic on TPU.
        import dataclasses

        cfg = QBAConfig(
            n_parties=5, size_l=8, n_dishonest=2, trials=4, seed=11,
            round_engine="pallas",
        )
        mesh = make_mesh({"dp": n_devices // 2, "tp": 2})
        ref = run_trials(dataclasses.replace(cfg, round_engine="xla"))
        spmd = run_trials_spmd(cfg, mesh)
        assert_trials_equal(spmd, ref)

    def test_tp_pallas_kernel_broadcast_scope(self, n_devices):
        import dataclasses

        cfg = QBAConfig(
            n_parties=5, size_l=8, n_dishonest=3, trials=4, seed=3,
            round_engine="pallas", attack_scope="broadcast",
        )
        mesh = make_mesh({"dp": n_devices // 2, "tp": 2})
        ref = run_trials(dataclasses.replace(cfg, round_engine="xla"))
        spmd = run_trials_spmd(cfg, mesh)
        assert_trials_equal(spmd, ref)

    def test_tp4_dishonest_commander_heavy(self, n_devices):
        if n_devices < 4:
            pytest.skip("needs >= 4 devices")
        cfg = QBAConfig(n_parties=5, size_l=8, n_dishonest=3, trials=2, seed=5)
        mesh = make_mesh({"dp": n_devices // 4, "tp": 4})
        ref = run_trials(cfg)
        spmd = run_trials_spmd(cfg, mesh)
        assert_trials_equal(spmd, ref)

    def test_spmd_auto_engine_failure_degrades_to_xla(self, n_devices, monkeypatch):
        # The probe-context gap (ADVICE r2 item 1 residual): a kernel
        # engine that passed its standalone compile probe can still fail
        # under the real shard_map context.  Auto-selected engines must
        # degrade loudly to the XLA branch; forced engines must raise.
        import warnings as _warnings

        import qba_tpu.parallel.spmd as spmd_mod

        cfg = QBAConfig(n_parties=5, size_l=8, n_dishonest=2, trials=4, seed=11)
        mesh = make_mesh({"dp": n_devices // 2, "tp": 2})
        ref = run_trials(cfg)

        real_batch = spmd_mod._spmd_batch
        attempts = []

        def failing_batch(
            cfg_, mesh_, keys_, engine="xla", check_vma=True,
            comms="all_gather",
        ):
            attempts.append((engine, comms))
            if engine != "xla":
                raise RuntimeError("forced shard_map compile failure")
            return real_batch(cfg_, mesh_, keys_, engine, check_vma, comms)

        monkeypatch.setattr(spmd_mod, "_spmd_batch", failing_batch)
        # Auto path: force the resolver to pick a kernel engine.
        monkeypatch.setattr(
            spmd_mod, "_resolve_spmd_engine", lambda c, n: "pallas_tiled"
        )
        with _warnings.catch_warnings(record=True) as caught:
            _warnings.simplefilter("always")
            out = spmd_mod.run_trials_spmd(cfg, mesh)
        # BOTH auto knobs degrade in the single fallback step: the
        # engine to xla AND the comms to the all_gather escape hatch.
        assert attempts == [("pallas_tiled", "ring"), ("xla", "all_gather")]
        assert any("falling back" in str(w.message) for w in caught)
        assert_trials_equal(out, ref)

        # Forced path: the explicit knob must raise, never downgrade.
        import dataclasses

        cfg_forced = dataclasses.replace(cfg, round_engine="pallas_tiled")
        attempts.clear()
        with pytest.raises(RuntimeError, match="forced shard_map"):
            spmd_mod.run_trials_spmd(cfg_forced, mesh)
        # Forced engine + auto comms: one retry with the comms knob
        # degraded, then the engine failure re-raises.
        assert attempts == [("pallas_tiled", "ring"),
                            ("pallas_tiled", "all_gather")]

    def test_indivisible_lieutenants_rejected(self, n_devices):
        cfg = QBAConfig(n_parties=4, size_l=4, trials=n_devices)  # 3 lieutenants
        mesh = make_mesh({"dp": n_devices // 2, "tp": 2})
        with pytest.raises(ValueError, match="n_lieutenants"):
            run_trials_spmd(cfg, mesh)

    def test_mesh_without_tp_rejected(self, n_devices):
        cfg = QBAConfig(n_parties=5, size_l=4, trials=n_devices)
        mesh = make_mesh({"dp": n_devices})
        with pytest.raises(ValueError, match="'tp' mesh axis"):
            run_trials_spmd(cfg, mesh)


class TestPartyShardedTiled:
    """The packet-tiled engine's party-sharded variant (round 4,
    VERDICT r3 item 1): per-device local pools with global cell ids,
    one tp all_gather per round, local-receiver verdict + rebuild
    kernels.  Must be bit-identical to the single-device XLA engine —
    placement is never semantics."""

    def _cfg(self, **kw):
        base = dict(
            n_parties=5, size_l=8, n_dishonest=2, trials=4, seed=11,
            round_engine="pallas_tiled", tiled_block=8,
        )
        base.update(kw)
        return QBAConfig(**base)

    def _ref(self, cfg):
        import dataclasses

        return run_trials(
            dataclasses.replace(cfg, round_engine="xla", tiled_block=None)
        )

    def test_tp_tiled_matches_xla(self, n_devices):
        cfg = self._cfg()
        mesh = make_mesh({"dp": n_devices // 2, "tp": 2})
        assert_trials_equal(run_trials_spmd(cfg, mesh), self._ref(cfg))

    def test_tp_tiled_matches_single_device_tiled(self, n_devices):
        # Transitivity check straight against the single-device TILED
        # engine (not just XLA): same pool algebra, different sharding.
        cfg = self._cfg(seed=3, n_dishonest=3)
        mesh = make_mesh({"dp": n_devices // 2, "tp": 2})
        assert_trials_equal(run_trials_spmd(cfg, mesh), run_trials(cfg))

    def test_tp_tiled_broadcast_scope_and_racy(self, n_devices):
        cfg = self._cfg(
            attack_scope="broadcast", delivery="racy", p_late=0.4,
            seed=12,
        )
        mesh = make_mesh({"dp": n_devices // 2, "tp": 2})
        assert_trials_equal(run_trials_spmd(cfg, mesh), self._ref(cfg))

    def test_tp4_single_receiver_blocks(self, n_devices):
        # n_local = 1: one receiver per device — the lane-group and
        # prefix-sum edge cases of the local kernel variants.
        if n_devices < 4:
            pytest.skip("needs >= 4 devices")
        cfg = self._cfg(n_dishonest=3, trials=2, seed=5)
        mesh = make_mesh({"dp": n_devices // 4, "tp": 4})
        assert_trials_equal(run_trials_spmd(cfg, mesh), self._ref(cfg))

    def test_northstar_scale_tp4_matches_single_device(self, n_devices):
        # THE round-4 acceptance criterion (VERDICT r3 item 1): the
        # flagship 33p/64/10 lossless config, lieutenants sharded 4-way,
        # bit-identical to the single-device tiled engine.  2 trials
        # keep the interpret-mode kernels tractable on CPU.
        if n_devices < 4:
            pytest.skip("needs >= 4 devices")
        cfg = QBAConfig(
            n_parties=33, size_l=64, n_dishonest=10, trials=2, seed=3,
            round_engine="pallas_tiled", tiled_block=256,
        )
        mesh = make_mesh({"dp": n_devices // 4, "tp": 4})
        spmd = run_trials_spmd(cfg, mesh)
        ref = run_trials(cfg)
        assert_trials_equal(spmd, ref)
        assert not bool(np.asarray(ref.trials.overflow).any())  # lossless

    def test_tp_tiled_xla_rebuild_fallback(self, n_devices, monkeypatch):
        # Forcing the Pallas rebuild plan away exercises the local
        # XLA rebuild_pool variant under shard_map.
        import qba_tpu.parallel.spmd as spmd_mod

        monkeypatch.setattr(
            spmd_mod, "_resolve_spmd_engine", lambda c, n: "pallas_tiled"
        )
        import qba_tpu.ops.round_kernel_tiled as rkt

        monkeypatch.setattr(
            rkt, "resolve_rebuild_block", lambda c, n_recv=None: None
        )
        cfg = self._cfg(round_engine="auto")
        mesh = make_mesh({"dp": n_devices // 2, "tp": 2})
        import warnings as _warnings

        with _warnings.catch_warnings(record=True) as caught:
            _warnings.simplefilter("always")
            out = run_trials_spmd(cfg, mesh)
        # The XLA-rebuild path itself must succeed — a silent engine
        # downgrade through the exception fallback would make this
        # equivalence vacuous.
        assert not any("falling back" in str(w.message) for w in caught)
        assert_trials_equal(out, self._ref(cfg))


class TestRingComms:
    """Round 9 (KI-2 memory wall): the neighbor-ring comms schedule
    that replaces the broadcast all_gather must be *placement, not
    semantics* — bit-identical to the all_gather escape hatch AND to
    the single-device engine at every tp width, shape, strategy, and
    noise knob.  The ring is what makes the per-device footprint
    constant in tp (docs/KNOWN_ISSUES.md KI-2)."""

    def _triple(self, cfg, tp, n_devices):
        """spmd(auto->ring) == spmd(all_gather) == single-device."""
        import dataclasses

        if n_devices < tp:
            pytest.skip(f"needs >= {tp} devices")
        mesh = make_mesh({"dp": n_devices // tp, "tp": tp})
        ring = run_trials_spmd(cfg, mesh)
        ag = run_trials_spmd(
            dataclasses.replace(cfg, tp_comms="all_gather"), mesh
        )
        assert_trials_equal(ring, ag)
        assert_trials_equal(ring, run_trials(cfg))

    @pytest.mark.parametrize("tp", [2, 4])
    def test_ring_matches_all_gather_17p(self, n_devices, tp):
        cfg = QBAConfig(
            n_parties=17, size_l=8, n_dishonest=4, trials=4, seed=21
        )
        self._triple(cfg, tp, n_devices)

    @pytest.mark.parametrize("tp", [2, 4])
    def test_ring_matches_all_gather_33p(self, n_devices, tp):
        cfg = QBAConfig(
            n_parties=33, size_l=8, n_dishonest=2, trials=4, seed=22
        )
        self._triple(cfg, tp, n_devices)

    def test_ring_split_strategy(self, n_devices):
        # The split strategy's worst-case forgery masks ride the same
        # shared draw arrays, so the ring shuffle cannot perturb them.
        cfg = QBAConfig(
            n_parties=17, size_l=8, n_dishonest=4, trials=4, seed=23,
            strategy="split",
        )
        self._triple(cfg, 4, n_devices)

    def test_ring_with_noise(self, n_devices):
        # Noise keys are indexed by global (trial, qubit) coordinates —
        # party sharding must not shift the noise stream either.
        cfg = QBAConfig(
            n_parties=17, size_l=8, n_dishonest=4, trials=4, seed=24,
            p_depolarize=0.05, p_measure_flip=0.02,
        )
        self._triple(cfg, 2, n_devices)

    def test_ring_path_check_vma_replication(self, n_devices):
        # Replication pin: the ring gather declares its output
        # tp-varying (out_vma) and recombination is psum-only, so the
        # static replication checker must PROVE the per-trial outputs
        # tp-replicated with check_vma=True — tracing is where an
        # under-replicated output would error out.
        import qba_tpu.parallel.spmd as spmd_mod

        assert spmd_mod._resolve_check_vma("xla") is True
        mesh = make_mesh({"dp": n_devices // 2, "tp": 2})
        cfg = QBAConfig(
            n_parties=5, size_l=8, n_dishonest=2, trials=n_devices // 2,
            seed=1,
        )
        keys = jax.random.split(jax.random.key(cfg.seed), cfg.trials)
        jax.make_jaxpr(
            lambda k: spmd_mod._spmd_batch(cfg, mesh, k, "xla", True, "ring")
        )(keys)

    def test_ring_gather_unit_matches_all_gather(self, n_devices):
        # The schedule itself, outside the protocol: hop k delivers the
        # shard of device (i-k-1) mod tp at that owner's global offset,
        # so the assembled array equals the tiled all_gather exactly.
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from qba_tpu.parallel.ring import ring_gather
        from qba_tpu.parallel.spmd import _shard_map

        tp = 4
        if n_devices < tp:
            pytest.skip("needs >= 4 devices")
        mesh = make_mesh({"dp": n_devices // tp, "tp": tp})
        x = jnp.arange(tp * 6, dtype=jnp.int32).reshape(tp * 3, 2)

        def body(xs):
            ring = ring_gather(xs, tp)
            gathered = jax.lax.all_gather(xs, "tp", axis=0, tiled=True)
            return ring, gathered

        ring, gathered = _shard_map(
            body, mesh=mesh,
            in_specs=P("tp"), out_specs=(P(), P()),
            check_vma=False,  # gather equality, not replication proof
        )(x)
        np.testing.assert_array_equal(np.asarray(ring), np.asarray(gathered))
        np.testing.assert_array_equal(np.asarray(ring), np.asarray(x))

    @pytest.mark.slow
    def test_65p_beyond_single_chip_budget(self, n_devices):
        # THE round-9 acceptance shape: a 65-party (w=128) pool the
        # KI-2 model PROVES cannot fit one emulated chip (ceiling 0 at
        # a reserve+16MiB budget) completes party-sharded over tp=8,
        # where the ring model prices >= 2 trials/device — the memory
        # wall broken by placement alone, bit-identically.
        if n_devices < 8:
            pytest.skip("needs >= 8 devices")
        from qba_tpu.analysis.memory import (
            HBM_RESERVE,
            sharded_trial_ceiling,
            trial_ceiling,
        )

        cfg = QBAConfig(
            n_parties=65, size_l=32, n_dishonest=2, trials=2, seed=9,
            round_engine="xla",
        )
        emu_hbm = HBM_RESERVE + (16 << 20)
        assert trial_ceiling(cfg, hbm_bytes=emu_hbm) == 0
        sc = sharded_trial_ceiling(
            cfg, dp=1, tp=8, hbm_bytes=emu_hbm, comms="ring"
        )
        assert sc["per_device_trials"] >= cfg.trials
        mesh = make_mesh({"dp": 1, "tp": 8})
        spmd = run_trials_spmd(cfg, mesh)
        assert_trials_equal(spmd, run_trials(cfg))


class TestShardedMega:
    """Round 11 tentpole (b): the party-sharded trial megakernel.  On
    TPU its neighbor ring runs INSIDE the one launch as double-buffered
    remote DMAs; off-TPU (this mesh) the spmd dispatch runs the fused
    transport twin over the identical hop schedule, so equality here
    pins the semantics and :mod:`qba_tpu.analysis.launches` pins the
    in-kernel schedule.  Placement, never semantics: forced
    ``pallas_mega`` under tp must match the single-device megakernel
    and the all_gather escape hatch bit for bit."""

    def _mega_triple(self, cfg, tp, n_devices):
        """spmd(mega, ring) == spmd(mega, all_gather) == single-device
        mega — with NO demotion recorded on the spmd path."""
        import dataclasses
        import warnings as _warnings

        from qba_tpu.diagnostics import QBADemotionWarning

        if n_devices < tp:
            pytest.skip(f"needs >= {tp} devices")
        mcfg = dataclasses.replace(cfg, round_engine="pallas_mega")
        mesh = make_mesh({"dp": n_devices // tp, "tp": tp})
        with _warnings.catch_warnings(record=True) as caught:
            _warnings.simplefilter("always")
            ring = run_trials_spmd(mcfg, mesh)
            ag = run_trials_spmd(
                dataclasses.replace(mcfg, tp_comms="all_gather"), mesh
            )
        assert not any(
            issubclass(w.category, QBADemotionWarning) for w in caught
        ), [str(w.message) for w in caught]
        assert_trials_equal(ring, ag)
        assert_trials_equal(ring, run_trials(mcfg))

    @pytest.mark.parametrize("tp", [2, 4])
    def test_sharded_mega_matches_single_device_17p(self, n_devices, tp):
        cfg = QBAConfig(
            n_parties=17, size_l=8, n_dishonest=4, trials=4, seed=41
        )
        self._mega_triple(cfg, tp, n_devices)

    @pytest.mark.parametrize("tp", [2, 4])
    def test_sharded_mega_matches_single_device_9p(self, n_devices, tp):
        cfg = QBAConfig(
            n_parties=9, size_l=16, n_dishonest=2, trials=4, seed=42
        )
        self._mega_triple(cfg, tp, n_devices)

    def test_sharded_mega_split_strategy(self, n_devices):
        cfg = QBAConfig(
            n_parties=17, size_l=8, n_dishonest=4, trials=4, seed=43,
            strategy="split",
        )
        self._mega_triple(cfg, 4, n_devices)

    def test_sharded_mega_with_noise(self, n_devices):
        cfg = QBAConfig(
            n_parties=17, size_l=8, n_dishonest=4, trials=4, seed=44,
            p_depolarize=0.05, p_measure_flip=0.02,
        )
        self._mega_triple(cfg, 2, n_devices)

    def test_sharded_mega_counters_demote_recorded(self, n_devices):
        # The megakernel has no host round scan for the counters
        # wrapper under tp either — a forced mega with counters must
        # RECORD its demotion to the fused engine and stay
        # bit-identical (the same contract as single-device).
        import dataclasses
        import warnings as _warnings

        from qba_tpu.diagnostics import QBADemotionWarning

        cfg = QBAConfig(
            n_parties=9, size_l=16, n_dishonest=2, trials=4, seed=45,
            collect_counters=True, round_engine="pallas_mega",
        )
        mesh = make_mesh({"dp": n_devices // 2, "tp": 2})
        with pytest.warns(QBADemotionWarning, match="counters"):
            spmd = run_trials_spmd(cfg, mesh)
        fused = dataclasses.replace(cfg, round_engine="pallas_fused")
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            ref = run_trials(fused)
        assert_trials_equal(spmd, ref)

    def test_sharded_mega_gen_stays_on_host_bit_identical(self, n_devices):
        # mega_gen='gf2' under tp records a generation demotion (no
        # party-sharded gen-fused prologue) but the sharded megakernel
        # still runs — and, generation being bit-identical by
        # construction, it must match the single-device GEN-FUSED
        # megakernel exactly.
        import dataclasses
        import warnings as _warnings

        from qba_tpu.diagnostics import QBADemotionWarning

        cfg = QBAConfig(
            n_parties=9, size_l=16, n_dishonest=2, trials=4, seed=46,
            qsim_path="stabilizer", mega_gen="gf2",
            round_engine="pallas_mega",
        )
        mesh = make_mesh({"dp": n_devices // 2, "tp": 2})
        with pytest.warns(
            QBADemotionWarning, match="gen-fused prologue"
        ):
            spmd = run_trials_spmd(cfg, mesh)
        with _warnings.catch_warnings():
            _warnings.simplefilter("error")
            ref = run_trials(cfg)  # single-device: gen fuses for real
        assert_trials_equal(spmd, ref)

    @pytest.mark.slow
    def test_65p_sharded_mega_one_launch(self, n_devices):
        # THE round-11 acceptance shape: the 65-party (w=128) pool
        # that breaks the single-chip KI-2 budget completes under the
        # party-sharded MEGAKERNEL at dp x tp = 1 x 8 — and the launch
        # model machine-proves ONE launch per trial on TPU, ring hops
        # and all.
        if n_devices < 8:
            pytest.skip("needs >= 8 devices")
        import dataclasses

        from qba_tpu.analysis.launches import spmd_launches_per_trial
        from qba_tpu.ops.round_kernel_tiled import sharded_mega_plan

        cfg = QBAConfig(
            n_parties=65, size_l=32, n_dishonest=2, trials=2, seed=9,
        )
        assert sharded_mega_plan(cfg, 8) is not None
        assert spmd_launches_per_trial(
            cfg, "pallas_mega", "ring", 4, tpu=True
        ) == 1
        mcfg = dataclasses.replace(cfg, round_engine="pallas_mega")
        mesh = make_mesh({"dp": 1, "tp": 8})
        spmd = run_trials_spmd(mcfg, mesh)
        ref = run_trials(
            dataclasses.replace(cfg, round_engine="xla")
        )
        assert_trials_equal(spmd, ref)


class TestMeshHelpers:
    def test_make_mesh_validates_device_count(self):
        with pytest.raises(ValueError, match="devices"):
            make_mesh({"dp": 3, "tp": 5})

    def test_default_shape_factors(self):
        assert default_mesh_shape(8) == {"dp": 4, "sp": 2}
        assert default_mesh_shape(8, want_tp=True) == {"dp": 4, "tp": 2}
        shape = default_mesh_shape(1)
        assert shape["dp"] == 1

    def test_hybrid_mesh_single_slice_fallback(self, n_devices):
        from qba_tpu.parallel import make_hybrid_mesh

        mesh = make_hybrid_mesh({"dp": n_devices // 2, "tp": 2})
        assert mesh.axis_names == ("dp", "tp")
        assert mesh.devices.shape == (n_devices // 2, 2)

    def test_hybrid_mesh_explicit_slices(self, n_devices):
        import pytest

        from qba_tpu.parallel import make_hybrid_mesh

        mesh = make_hybrid_mesh({"dp": n_devices // 4, "tp": 2}, n_slices=2)
        assert mesh.axis_names == ("dp", "tp")
        assert mesh.devices.shape == (n_devices // 2, 2)
        # Each slice's block stays contiguous along the non-dcn axis.
        assert len(set(d.id for d in mesh.devices.flat)) == n_devices
        with pytest.raises(ValueError, match="dcn_axis"):
            make_hybrid_mesh({"tp": n_devices}, dcn_axis="dp")
        with pytest.raises(ValueError, match="devices"):
            make_hybrid_mesh({"dp": n_devices}, n_slices=3)


class _FakeSliceDev:
    """Mock device carrying the multi-slice ``slice_index`` attribute
    (real multi-slice TPU hardware is unavailable in CI; VERDICT r1 asked
    for the create_hybrid_device_mesh branch to be exercised anyway)."""

    def __init__(self, id, slice_index):
        self.id = id
        self.slice_index = slice_index
        self.platform = "cpu"
        self.device_kind = "fake"

    def __repr__(self):
        return f"_FakeSliceDev({self.id}, slice={self.slice_index})"


class TestHybridMultiSlice:
    """The true multi-slice branch of make_hybrid_mesh
    (mesh_utils.create_hybrid_device_mesh), driven with mock devices."""

    def _devs(self, n, per_slice):
        return [_FakeSliceDev(i, i // per_slice) for i in range(n)]

    def test_dcn_axis_carries_slice_boundary(self):
        import random

        from qba_tpu.parallel.mesh import hybrid_device_array

        devs = self._devs(8, 4)
        shuffled = devs[:]
        random.Random(0).shuffle(shuffled)  # granules must sort by slice
        arr = hybrid_device_array(
            {"dp": 2, "tp": 2}, dcn_axis="dp", n_slices=2, devices=shuffled
        )
        assert arr.shape == (4, 2)
        # dp rows 0-1 = slice 0, rows 2-3 = slice 1: the DCN hop only
        # crosses the dp axis; tp neighbors always share a slice (ICI).
        for row in range(4):
            slices = {d.slice_index for d in arr[row]}
            assert slices == {row // 2}, (row, arr[row])
        assert {d.id for d in arr.flat} == set(range(8))

    def test_four_slices(self):
        from qba_tpu.parallel.mesh import hybrid_device_array

        arr = hybrid_device_array(
            {"dp": 1, "tp": 2}, dcn_axis="dp", n_slices=4,
            devices=self._devs(8, 2),
        )
        assert arr.shape == (4, 2)
        for row in range(4):
            assert {d.slice_index for d in arr[row]} == {row}

    def test_slice_count_inferred_from_devices(self):
        from qba_tpu.parallel import make_hybrid_mesh

        mesh = make_hybrid_mesh(
            {"dp": 2, "tp": 2}, devices=self._devs(8, 4)
        )
        assert mesh.devices.shape == (4, 2)
        assert mesh.axis_names == ("dp", "tp")

    def test_device_count_mismatch_rejected(self):
        from qba_tpu.parallel.mesh import hybrid_device_array

        with pytest.raises(ValueError, match="devices"):
            hybrid_device_array(
                {"dp": 2, "tp": 2}, dcn_axis="dp", n_slices=3,
                devices=self._devs(8, 4),
            )


_DIST_SMOKE = """
import os, sys
proc_id, port = int(sys.argv[1]), sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    f"localhost:{port}", num_processes=2, process_id=proc_id
)
import numpy as np
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from qba_tpu.parallel import make_mesh
devs = jax.devices()
assert len(devs) == 4, devs
mesh = make_mesh({"dp": 4}, devices=devs)
try:  # older jax: only jax.experimental.shard_map (jax.shard_map raises)
    shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map
out = jax.jit(
    shard_map(
        lambda x: jax.lax.psum(x, "dp"),
        mesh=mesh, in_specs=P("dp"), out_specs=P(),
    )
)(jnp.arange(4.0))
print("DIST_SMOKE_RESULT", proc_id, float(np.asarray(jax.device_get(out))[0]))
"""


def test_two_process_distributed_cpu_smoke(tmp_path):
    """Multi-host smoke: two OS processes, jax.distributed.initialize,
    one global 4-device CPU mesh, a psum collective crossing the process
    boundary — the minimal in-CI stand-in for the reference's multi-host
    mpiexec launch (README.md:4).  Skips only on environmental failures
    (no free port / distributed service unavailable); wrong numerics
    fail."""
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "dist_smoke.py"
    script.write_text(_DIST_SMOKE)
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=repo, env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append((p.returncode, out))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("distributed CPU smoke timed out (environment)")
    for rc, out in outs:
        if rc != 0 and "DIST_SMOKE_RESULT" not in out:
            if (
                "Connection refused" in out
                or "UNAVAILABLE" in out
                or "aren't implemented on the CPU backend" in out
            ):
                pytest.skip(f"distributed service unavailable: {out[-200:]}")
            pytest.fail(f"distributed smoke rc={rc}:\n{out[-2000:]}")
        assert f"DIST_SMOKE_RESULT {outs.index((rc, out))} 6.0" in out, out


class TestCheckVmaFlag:
    def test_bad_flag_value_raises(self, monkeypatch):
        # The escape hatch must fail loudly on unrecognized values, not
        # silently fall back to the backend default (review r5).
        import qba_tpu.parallel.spmd as spmd_mod

        monkeypatch.setenv("QBA_TILED_CHECK_VMA", "true")
        with pytest.raises(ValueError, match="QBA_TILED_CHECK_VMA"):
            spmd_mod._tiled_check_vma()

    def test_flag_values(self, monkeypatch):
        import qba_tpu.parallel.spmd as spmd_mod

        monkeypatch.setenv("QBA_TILED_CHECK_VMA", "1")
        assert spmd_mod._tiled_check_vma() is True
        monkeypatch.setenv("QBA_TILED_CHECK_VMA", "0")
        assert spmd_mod._tiled_check_vma() is False
        monkeypatch.delenv("QBA_TILED_CHECK_VMA")
        assert spmd_mod._tiled_check_vma() is (
            __import__("jax").default_backend() == "tpu"
        )
