"""One-launch trial megakernel vs the fused per-round + XLA engines.

The trial megakernel (:func:`qba_tpu.ops.trial_megakernel
.build_trial_megakernel`) runs the ENTIRE trial — step-1 particle
decode, the ``fori_loop`` over all ``n_dishonest + 1`` voting rounds,
and the per-trial decision reduce — in ONE ``pallas_call``, with the
vi/acc/pool/mailbox state held in VMEM scratch.  Round state never
round-trips HBM and no per-round launch exists (the KI-5 lint proves
the host scan disappeared; :mod:`qba_tpu.analysis.launches` pins the
launch count to 1).  It must stay bit-identical to the fused per-round
engine and the XLA oracle for the same trial keys, and every refusal
(VMEM budget, counters, spmd) must be a RECORDED demotion, never a
silent one.  Runs in interpreter mode on the CPU test mesh; the same
kernel compiles for real on TPU (``auto`` prefers it wherever the
one-launch plan fits the megakernel VMEM budget).
"""

import dataclasses
import warnings

import jax
import jax.numpy as jnp
import pytest

from qba_tpu.config import QBAConfig
from qba_tpu.diagnostics import QBADemotionWarning
from qba_tpu.rounds import run_trial


def batch(cfg, engine, seed, n, strict=True):
    """A trial batch on a forced engine; warnings are errors unless the
    engine is expected to demote (strict=False)."""
    keys = jax.random.split(jax.random.key(seed), n)
    ecfg = dataclasses.replace(cfg, round_engine=engine)
    with warnings.catch_warnings():
        if strict:
            warnings.simplefilter("error")
        else:
            warnings.simplefilter("ignore")
        return jax.jit(jax.vmap(lambda k: run_trial(ecfg, k)))(keys)


def assert_equal(a, b):
    assert a.vi.tolist() == b.vi.tolist()
    assert a.decisions.tolist() == b.decisions.tolist()
    assert a.success.tolist() == b.success.tolist()
    assert a.overflow.tolist() == b.overflow.tolist()


def triad(cfg, seed=0, n=2, strict=True):
    xla = batch(cfg, "xla", seed, n)
    fused = batch(cfg, "pallas_fused", seed, n)
    mega = batch(cfg, "pallas_mega", seed, n, strict=strict)
    assert_equal(xla, mega)
    assert_equal(fused, mega)


class TestMegaEquivalence:
    def test_headline_shape(self):
        # 11p/64 — the headline benchmark config (BASELINE.json).
        triad(QBAConfig(n_parties=11, size_l=64, n_dishonest=3))

    def test_grp1_window(self):
        # sizeL >= 128 pushes the verdict algebra into grp == 1.
        triad(QBAConfig(n_parties=4, size_l=128, n_dishonest=1))

    def test_wide_group_demotes_recorded(self):
        # 33p/L8: the fused per-round working set alone crowds the
        # 64 MiB megakernel VMEM budget, so the one-launch plan does
        # not exist and the forced megakernel must RECORD its demotion
        # to the fused engine — and still be bit-identical.
        cfg = QBAConfig(n_parties=33, size_l=8, n_dishonest=10)
        ecfg = dataclasses.replace(cfg, round_engine="pallas_mega")
        keys = jax.random.split(jax.random.key(3), 2)
        with pytest.warns(QBADemotionWarning, match="megakernel unavailable"):
            mega = jax.vmap(lambda k: run_trial(ecfg, k))(keys)
        assert_equal(batch(cfg, "pallas_fused", 3, 2), mega)

    @pytest.mark.slow
    def test_north_star_shape(self):
        # 33p/64/10 (BASELINE.md config 5).  The megakernel estimate
        # fits or demotes per machine; either way the verdicts must
        # match the oracle bit for bit.
        triad(
            QBAConfig(n_parties=33, size_l=64, n_dishonest=10),
            strict=False,
        )

    def test_racy_delivery(self):
        # p_late > 0 exercises the late-delivery mask inside the
        # in-kernel round loop (the `late` draw plane is indexed from
        # the stacked round-major tables, not a fresh host draw).
        triad(
            QBAConfig(
                n_parties=5, size_l=16, n_dishonest=1,
                delivery="racy", p_late=0.25,
            ),
            seed=5,
        )

    def test_split_strategy(self):
        # The forge-P flag algebra is the only strategy-gated extra
        # math inside the verdict block; it must survive the move
        # into the in-kernel round loop.
        triad(
            QBAConfig(
                n_parties=11, size_l=16, n_dishonest=3, strategy="split"
            )
        )


class TestMegaPacking:
    def test_packed_matches_unpacked(self):
        from qba_tpu.rounds.engine import run_trials_mega_packed

        cfg = QBAConfig(
            n_parties=11, size_l=64, n_dishonest=3,
            round_engine="pallas_mega",
        )
        keys = jax.random.split(jax.random.key(7), 4)
        packed = run_trials_mega_packed(cfg, keys, pack=2)
        unpacked = jax.vmap(lambda k: run_trial(cfg, k))(keys)
        assert_equal(unpacked, packed)

    def test_pack_of_one_falls_back(self):
        from qba_tpu.rounds.engine import run_trials_mega_packed

        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=1,
            round_engine="pallas_mega",
        )
        keys = jax.random.split(jax.random.key(9), 2)
        assert_equal(
            jax.vmap(lambda k: run_trial(cfg, k))(keys),
            run_trials_mega_packed(cfg, keys, pack=1),
        )


class TestCountersSeam:
    def test_counters_demote_recorded_and_bit_identical(self):
        # The scan_rounds(collect=True) contract on a scan-free
        # engine: requesting counters IS a recorded demotion to the
        # fused per-round engine, and everything — counters included —
        # is bit-identical to running that engine directly.
        cfg = QBAConfig(
            n_parties=11, size_l=16, n_dishonest=3,
            collect_counters=True,
        )
        keys = jax.random.split(jax.random.key(11), 2)
        mcfg = dataclasses.replace(cfg, round_engine="pallas_mega")
        with pytest.warns(
            QBADemotionWarning, match="counters"
        ):
            mega = jax.vmap(lambda k: run_trial(mcfg, k))(keys)
        fused = batch(cfg, "pallas_fused", 11, 2)
        assert_equal(fused, mega)
        assert mega.counters is not None
        for got, want in zip(
            jax.tree_util.tree_leaves(mega.counters),
            jax.tree_util.tree_leaves(fused.counters),
        ):
            assert got.tolist() == want.tolist()

    def test_counters_off_identity(self):
        # Without counters the megakernel runs for real — same
        # primaries as the fused engine (counters stay None).
        cfg = QBAConfig(n_parties=11, size_l=16, n_dishonest=3)
        mega = batch(cfg, "pallas_mega", 13, 2)
        fused = batch(cfg, "pallas_fused", 13, 2)
        assert_equal(fused, mega)
        assert mega.counters is None

    def test_auto_engine_never_picks_mega_with_counters(self):
        from qba_tpu.rounds.engine import resolve_round_engine

        cfg = QBAConfig(
            n_parties=11, size_l=16, n_dishonest=3,
            collect_counters=True,
        )
        assert resolve_round_engine(cfg) != "pallas_mega"


class TestDemotions:
    def test_over_budget_shape_warns_once_per_trace(self):
        from qba_tpu.rounds.engine import _demote_mega

        cfg = QBAConfig(
            n_parties=33, size_l=8, n_dishonest=10,
            round_engine="pallas_mega",
        )
        with pytest.warns(QBADemotionWarning) as rec:
            assert _demote_mega(cfg) == "pallas_fused"
        [w] = rec.list
        assert "VMEM" in str(w.message) or "unavailable" in str(w.message)

    def test_spmd_resolves_party_sharded_mega(self):
        # Round 11 inverts the round-9 pin: the megakernel HAS a
        # party-sharded variant (the in-kernel neighbor ring), so a
        # forced mega under the tp mesh resolves to itself with no
        # demotion wherever the sharded plan is admitted.
        from qba_tpu.parallel.spmd import _resolve_spmd_engine

        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=1,
            round_engine="pallas_mega",
        )
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            assert (
                _resolve_spmd_engine(cfg, cfg.n_lieutenants // 2)
                == "pallas_mega"
            )

    def test_spmd_mega_without_plan_demotes_recorded(self, monkeypatch):
        # When the sharded plan is refused (VMEM screen or probe), the
        # tp-mesh resolver must still record its demotion to the fused
        # engine — never a silent fallback.
        from qba_tpu.ops import round_kernel_tiled as rkt
        from qba_tpu.parallel.spmd import _resolve_spmd_engine

        monkeypatch.setattr(
            rkt, "sharded_mega_plan", lambda cfg, n_tp: None
        )
        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=1,
            round_engine="pallas_mega",
        )
        with pytest.warns(
            QBADemotionWarning, match="party-sharded"
        ):
            assert (
                _resolve_spmd_engine(cfg, cfg.n_lieutenants // 2)
                == "pallas_fused"
            )


class TestLaunchModel:
    def test_launches_per_trial(self):
        from qba_tpu.analysis.launches import launches_per_trial

        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=1)
        assert launches_per_trial(cfg, "xla") == 0
        assert launches_per_trial(cfg, "pallas") == cfg.n_rounds
        assert launches_per_trial(cfg, "pallas_tiled") == 2 * cfg.n_rounds
        assert launches_per_trial(cfg, "pallas_fused") == cfg.n_rounds
        assert launches_per_trial(cfg, "pallas_mega") == 1

    def test_lint_launch_pin(self):
        from qba_tpu.analysis.launches import check_launches

        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=1)
        report = check_launches(
            cfg, {"xla", "pallas_fused", "pallas_mega"}
        )
        assert report.ok
        assert any("pallas_mega" in n for n in report.notes)

    def test_kernel_plan_attribution(self):
        from qba_tpu.benchmark import engine_description, kernel_plan

        cfg = QBAConfig(
            n_parties=11, size_l=64, n_dishonest=3,
            round_engine="pallas_mega",
        )
        plan = kernel_plan(cfg)
        assert plan["launches_per_trial"] == 1
        assert plan["launches_per_round"] is None
        assert plan["mega_block"] is not None
        assert engine_description(cfg).startswith("pallas_mega/")

    def test_kernel_plan_tp_attribution(self):
        # Round 9: on a tp mesh the plan names the SPMD engine and the
        # comms transport, and a mega demotion (the in-kernel round
        # loop cannot drain a sharded mailbox) is attributed, never
        # silent.
        import dataclasses

        from qba_tpu.benchmark import engine_description, kernel_plan

        cfg = QBAConfig(n_parties=17, size_l=16, n_dishonest=4)
        plan = kernel_plan(cfg, tp=4)
        assert plan["tp"] == 4
        assert plan["tp_comms"] == "ring"
        assert plan["tp_demoted_from"] is None
        desc = engine_description(cfg, tp=4)
        assert desc.startswith("spmd[tp=4]/")
        assert desc.endswith("/ring")

        # Round 11: the sharded megakernel survives the tp mesh — the
        # plan attributes it (and its ring) with no demotion.
        cfg_mega = dataclasses.replace(cfg, round_engine="pallas_mega")
        plan_mega = kernel_plan(cfg_mega, tp=4)
        assert plan_mega["tp_engine"] == "pallas_mega"
        assert plan_mega["tp_demoted_from"] is None
        desc_mega = engine_description(cfg_mega, tp=4)
        assert "/pallas_mega/" in desc_mega
        assert desc_mega.endswith("/ring")

        # ... but counters still demote under tp, and the demotion is
        # attributed in the plan, never silent.
        cfg_ctr = dataclasses.replace(cfg_mega, collect_counters=True)
        plan_ctr = kernel_plan(cfg_ctr, tp=4)
        assert plan_ctr["tp_engine"] == "pallas_fused"
        assert plan_ctr["tp_demoted_from"] == "pallas_mega"
        assert "(from mega)" in engine_description(cfg_ctr, tp=4)

        cfg_ag = dataclasses.replace(cfg, tp_comms="all_gather")
        assert kernel_plan(cfg_ag, tp=2)["tp_comms"] == "all_gather"
        # tp=None keeps the single-device attribution unchanged.
        assert "tp" not in kernel_plan(cfg)


def gen_triad(cfg, seed=0, n=2):
    """Bit-identity across the generation seam: host-gen XLA, host-gen
    fused, host-gen megakernel, and the gen-fused (in-VMEM GF(2))
    megakernel must all agree for the same trial keys.  ``cfg`` must
    ride the stabilizer sampler (the gen-fused prologue exists only
    there)."""
    assert cfg.qsim_path == "stabilizer"
    host = dataclasses.replace(cfg, mega_gen="host")
    gf2 = dataclasses.replace(cfg, mega_gen="gf2")
    mega_gf2 = batch(gf2, "pallas_mega", seed, n)
    assert_equal(batch(host, "xla", seed, n), mega_gf2)
    assert_equal(batch(host, "pallas_fused", seed, n), mega_gf2)
    assert_equal(batch(host, "pallas_mega", seed, n), mega_gf2)


class TestMegaGen:
    """Round 11 tentpole (a): step-1 generation folded into the one
    launch.  The GF(2) sweep inside VMEM replays the HOST sampler's
    exact bit algebra over the same packed tables and key-derived
    draws, so equivalence is by construction — these triads prove the
    construction held through the kernel move."""

    def test_headline_gen_fused(self):
        cfg = QBAConfig(
            n_parties=11, size_l=64, n_dishonest=3,
            qsim_path="stabilizer",
        )
        from qba_tpu.ops.round_kernel_tiled import resolve_mega_gen

        assert resolve_mega_gen(
            dataclasses.replace(cfg, mega_gen="gf2")
        ) == "gf2"
        gen_triad(cfg)

    def test_wide_group_gen_fused(self):
        # 33p/L8 — the second pinned shape (single chip, wide group).
        gen_triad(
            QBAConfig(
                n_parties=33, size_l=8, n_dishonest=2,
                qsim_path="stabilizer",
            ),
            seed=17,
        )

    def test_split_strategy_gen_fused(self):
        gen_triad(
            QBAConfig(
                n_parties=11, size_l=16, n_dishonest=3,
                strategy="split", qsim_path="stabilizer",
            ),
            seed=19,
        )

    def test_noisy_gen_fused(self):
        # Depolarizing + measurement-flip noise folds into the
        # generation draws; the in-VMEM sweep must consume the same
        # key-derived planes as the host sampler.
        gen_triad(
            QBAConfig(
                n_parties=5, size_l=16, n_dishonest=1,
                qsim_path="stabilizer",
                p_depolarize=0.05, p_measure_flip=0.02,
            ),
            seed=23,
        )

    def test_packed_matches_unpacked_gen_fused(self):
        from qba_tpu.rounds.engine import run_trials_mega_packed

        cfg = QBAConfig(
            n_parties=11, size_l=64, n_dishonest=3,
            qsim_path="stabilizer", mega_gen="gf2",
            round_engine="pallas_mega",
        )
        keys = jax.random.split(jax.random.key(29), 4)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            packed = run_trials_mega_packed(cfg, keys, pack=2)
            unpacked = jax.vmap(lambda k: run_trial(cfg, k))(keys)
        assert_equal(unpacked, packed)

    def test_gf2_requires_stabilizer(self):
        with pytest.raises(ValueError, match="stabilizer"):
            QBAConfig(
                n_parties=5, size_l=16, n_dishonest=1,
                qsim_path="factorized", mega_gen="gf2",
            )

    def test_forced_gf2_refused_records_demotion(self):
        # A forced gen-fused prologue whose plan is refused must
        # RECORD the generation demotion (host sampler, megakernel
        # still runs) — and stay bit-identical.  The gen working set
        # is small, so no natural shape refuses only the gen plan;
        # pre-seed the plan memo with a refusal instead.
        from qba_tpu.ops.round_kernel_tiled import (
            _memo,
            _resolve_key,
            clear_resolve_caches,
        )

        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=1,
            qsim_path="stabilizer", mega_gen="gf2",
            round_engine="pallas_mega",
        )
        clear_resolve_caches()
        try:
            _memo(
                _resolve_key("mega", cfg, None, (1, True)),
                lambda: None,
            )
            keys = jax.random.split(jax.random.key(31), 2)
            with pytest.warns(
                QBADemotionWarning,
                match="gen-fused megakernel plan",
            ):
                mega = jax.vmap(lambda k: run_trial(cfg, k))(keys)
        finally:
            clear_resolve_caches()
        host = batch(
            dataclasses.replace(cfg, mega_gen="host"),
            "pallas_mega", 31, 2,
        )
        assert_equal(host, mega)

    def test_spmd_gf2_stays_on_host_recorded(self):
        # The sharded megakernel has no gen-fused prologue: a forced
        # gf2 under the tp mesh records a generation demotion but the
        # sharded megakernel itself still runs.
        from qba_tpu.parallel.spmd import _resolve_spmd_engine

        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=1,
            qsim_path="stabilizer", mega_gen="gf2",
            round_engine="pallas_mega",
        )
        with pytest.warns(
            QBADemotionWarning, match="gen-fused prologue"
        ):
            assert (
                _resolve_spmd_engine(cfg, cfg.n_lieutenants // 2)
                == "pallas_mega"
            )


class TestGenLaunchPin:
    """Satellite 1: machine proof that generation moved in-kernel.
    Host generation necessarily carries its measurement sweeps as
    host-side ``scan``s; the gen-fused trace must carry ZERO scans
    outside the one ``pallas_call``."""

    def test_gen_fused_proves_zero_host_scans(self):
        from qba_tpu.analysis.launches import check_launches

        cfg = QBAConfig(
            n_parties=11, size_l=64, n_dishonest=3,
            qsim_path="stabilizer", mega_gen="gf2",
        )
        report = check_launches(cfg, {"pallas_mega"})
        assert report.ok
        assert report.stats.get("mega_gen_host_scans") == 0
        assert any("PROVEN" in n for n in report.notes)

    def test_host_gen_carries_host_scans(self):
        from qba_tpu.analysis.launches import (
            _trace_trial,
            count_host_scans,
        )

        cfg = QBAConfig(
            n_parties=11, size_l=64, n_dishonest=3,
            qsim_path="stabilizer", mega_gen="host",
        )
        closed = _trace_trial(cfg, "pallas_mega")
        assert count_host_scans(closed.jaxpr) > 0

    def test_effects_audit_proves_gen_in_kernel(self):
        from qba_tpu.analysis.effects import _audit_mega
        from qba_tpu.analysis.findings import Report

        cfg = QBAConfig(
            n_parties=11, size_l=64, n_dishonest=3,
            qsim_path="stabilizer", mega_gen="gf2",
        )
        report = Report()
        stats = {"mega_demotions_recorded": 0}
        _audit_mega(cfg, report, stats)
        assert not report.findings
        assert stats["mega_gen_host_scans"] == 0
        assert any("PROVEN" in n for n in report.notes)

    def test_spmd_mega_launch_row(self):
        from qba_tpu.analysis.launches import (
            check_spmd_launches,
            spmd_launches_per_trial,
        )

        cfg = QBAConfig(n_parties=9, size_l=16, n_dishonest=2)
        # TPU model: ONE launch per trial regardless of comms — the
        # ring hops are in-kernel remote DMAs, not launches.
        assert spmd_launches_per_trial(
            cfg, "pallas_mega", "ring", 4, tpu=True
        ) == 1
        # Off-TPU model: the fused transport twin's counts.
        assert spmd_launches_per_trial(
            cfg, "pallas_mega", "ring", 4, tpu=False
        ) == cfg.n_rounds
        report = check_spmd_launches(
            dataclasses.replace(cfg, round_engine="pallas_mega"),
            {"pallas_mega"}, tp=2,
        )
        assert report.ok
        assert report.stats["spmd_launch_engines_checked"] == 1
        assert any("IN-KERNEL" in n for n in report.notes)


class TestServeWarmStart:
    def test_mega_plan_round_trips_zero_probe(self):
        # A mega plan resolved once must ride the resolver-state
        # artifact: a fresh process that imports it re-resolves the
        # same shape with ZERO new probes or misses (the serve
        # warm-start contract, tests/test_serve.py).
        from qba_tpu.ops.round_kernel_tiled import (
            PROBE_STATS,
            clear_resolve_caches,
            export_resolver_state,
            import_resolver_state,
            resolve_mega_block,
        )

        cfg = QBAConfig(n_parties=11, size_l=64, n_dishonest=3)
        clear_resolve_caches()
        try:
            plan = resolve_mega_block(cfg)
            assert plan is not None
            state = export_resolver_state()
            assert any(
                k[0] == "mega" for k, _ in state["resolve"]
            )
            clear_resolve_caches()  # simulate a fresh process
            assert import_resolver_state(state) > 0
            assert resolve_mega_block(cfg) == plan
            assert PROBE_STATS["compile_probes"] == 0
            assert PROBE_STATS["resolve_misses"] == 0
            assert PROBE_STATS["resolve_hits"] > 0
        finally:
            clear_resolve_caches()

    def test_gen_fused_plan_round_trips_zero_probe(self):
        # Round 11: the gen-fused probe results (the "+gen" mega plan
        # and the megagen resolution) ride the same resolver-state
        # artifact — a warm-started serve process answers the
        # generation question with ZERO new probes.
        from qba_tpu.ops.round_kernel_tiled import (
            PROBE_STATS,
            clear_resolve_caches,
            export_resolver_state,
            import_resolver_state,
            resolve_mega_block,
            resolve_mega_gen,
        )

        cfg = QBAConfig(
            n_parties=11, size_l=64, n_dishonest=3,
            qsim_path="stabilizer", mega_gen="gf2",
        )
        clear_resolve_caches()
        try:
            assert resolve_mega_gen(cfg) == "gf2"
            plan = resolve_mega_block(cfg)
            assert plan is not None
            state = export_resolver_state()
            kinds = {k[0] for k, _ in state["resolve"]}
            assert "megagen" in kinds
            assert "mega" in kinds
            clear_resolve_caches()  # simulate a fresh process
            assert import_resolver_state(state) > 0
            assert resolve_mega_gen(cfg) == "gf2"
            assert resolve_mega_block(cfg) == plan
            assert PROBE_STATS["compile_probes"] == 0
            assert PROBE_STATS["resolve_misses"] == 0
            assert PROBE_STATS["resolve_hits"] > 0
        finally:
            clear_resolve_caches()

    def test_sharded_mega_plan_round_trips_zero_probe(self):
        from qba_tpu.ops.round_kernel_tiled import (
            PROBE_STATS,
            clear_resolve_caches,
            export_resolver_state,
            import_resolver_state,
            sharded_mega_plan,
        )

        cfg = QBAConfig(n_parties=9, size_l=16, n_dishonest=2)
        clear_resolve_caches()
        try:
            plan = sharded_mega_plan(cfg, 2)
            assert plan is not None
            state = export_resolver_state()
            assert any(k[0] == "megash" for k, _ in state["resolve"])
            clear_resolve_caches()  # simulate a fresh process
            assert import_resolver_state(state) > 0
            assert sharded_mega_plan(cfg, 2) == plan
            assert PROBE_STATS["compile_probes"] == 0
            assert PROBE_STATS["resolve_misses"] == 0
            assert PROBE_STATS["resolve_hits"] > 0
        finally:
            clear_resolve_caches()
