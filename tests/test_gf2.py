"""qba_tpu.gf2 tests: the batched bit-packed GF(2) engine.

Three layers of contract, mirroring the subsystem's structure:

* **linalg/bitops unit tests** — pack/unpack roundtrips, parity matmul
  vs numpy mod-2 (including K-tiling past :data:`GF2_TILE_K`), the
  packed rank-1 update, and the triangular-parity reduction vs the
  direct strict-upper-triangle formulation it replaces.
* **bit-identity differentials** — the batched symplectic sampler must
  be *bit-identical* to the per-shot tableau engine
  (:mod:`qba_tpu.qsim.stabilizer`) for the same keys: random Clifford
  circuits (with and without runtime params) and both protocol circuit
  families.  Bitwise equality is the strongest possible check — any
  drift in the aggregate-transform compilation, the coin-draw
  discipline, or the masked measurement sweep breaks it.
* **statistical cross-checks** — outcome laws vs the dense statevector
  at small n (chi-square) and the closed-form sampler's §2.6 marginals
  at protocol scale, so the engine is validated against physics, not
  just against another tableau implementation.

Scale tests (65-party protocol trial, 129/257-party resource
generation) are ``slow``-marked; tier-1 keeps a small-n stabilizer
smoke.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qba_tpu.config import DENSE_QUBIT_CAP, QBAConfig
from qba_tpu.diagnostics import QBADemotionWarning, record_decisions
from qba_tpu.gf2 import (
    GF2_TILE_K,
    WORD,
    build_gf2_tableau_run_batch,
    build_gf2_tableau_run_shots,
    compile_symplectic,
    get_bit,
    gf2_matmul,
    gf2_matvec,
    mask_words,
    n_words,
    pack_bits,
    parity_words,
    prefix_xor_exclusive,
    rank1_update_packed,
    triangular_parity,
    unit_words,
    unpack_bits,
)
from qba_tpu.qsim import (
    generate_lists,
    generate_lists_dense,
    generate_lists_for,
    generate_lists_stabilizer,
)
from qba_tpu.qsim.circuit import Circuit, Gate, Op
from qba_tpu.qsim.stabilizer import build_tableau_run_shots
from qba_tpu.rounds import run_trial
from tests.test_qsim import check_closed_form_properties


# ---------------------------------------------------------------------------
# bitops: packing, extraction, parity.


class TestBitops:
    @pytest.mark.parametrize("n", [1, 31, 32, 33, 100])
    def test_pack_unpack_roundtrip(self, n):
        rng = np.random.default_rng(n)
        bits = rng.integers(0, 2, size=(5, n)).astype(np.int32)
        words = pack_bits(jnp.asarray(bits))
        assert words.shape == (5, n_words(n))
        assert words.dtype == jnp.uint32
        np.testing.assert_array_equal(np.asarray(unpack_bits(words, n)), bits)

    def test_get_bit_matches_unpacked_traced_index(self):
        rng = np.random.default_rng(7)
        bits = rng.integers(0, 2, size=(3, 70)).astype(np.int32)
        words = pack_bits(jnp.asarray(bits))
        extract = jax.jit(get_bit)
        for j in (0, 31, 32, 69):
            np.testing.assert_array_equal(
                np.asarray(extract(words, jnp.asarray(j))), bits[:, j]
            )

    def test_unit_words(self):
        for j in (0, 31, 32, 40):
            e = unit_words(70, jnp.asarray(j))
            np.testing.assert_array_equal(
                np.asarray(unpack_bits(e, 70)),
                np.eye(70, dtype=np.int32)[j],
            )

    def test_parity_words(self):
        rng = np.random.default_rng(11)
        bits = rng.integers(0, 2, size=(4, 90)).astype(np.int32)
        words = pack_bits(jnp.asarray(bits))
        np.testing.assert_array_equal(
            np.asarray(parity_words(words)), bits.sum(axis=-1) % 2
        )
        # tuple-axis form (the triangular-parity reduction uses (-2, -1))
        assert int(parity_words(words, axis=(-2, -1))) == bits.sum() % 2

    def test_mask_words(self):
        m = mask_words(jnp.asarray([0, 1, 1, 0]))
        assert m.tolist() == [0, 0xFFFFFFFF, 0xFFFFFFFF, 0]

    def test_prefix_xor_exclusive(self):
        rng = np.random.default_rng(13)
        bits = rng.integers(0, 2, size=(6, 40)).astype(np.int32)
        words = pack_bits(jnp.asarray(bits))
        out = unpack_bits(prefix_xor_exclusive(words, axis=-2), 40)
        expect = np.zeros_like(bits)
        for i in range(1, 6):
            expect[i] = expect[i - 1] ^ bits[i - 1]
        np.testing.assert_array_equal(np.asarray(out), expect)


# ---------------------------------------------------------------------------
# linalg: the KI-3-provable parity matmul and packed reductions.


class TestLinalg:
    @pytest.mark.parametrize("k", [1, 17, GF2_TILE_K, GF2_TILE_K + 1, 600])
    def test_matmul_vs_numpy_mod2(self, k):
        # k > GF2_TILE_K exercises the multi-tile XOR-combine path.
        rng = np.random.default_rng(k)
        a = rng.integers(0, 2, size=(9, k)).astype(np.int32)
        b = rng.integers(0, 2, size=(k, 13)).astype(np.int32)
        got = np.asarray(gf2_matmul(jnp.asarray(a), jnp.asarray(b)))
        np.testing.assert_array_equal(got, (a @ b) % 2)

    def test_matmul_batched(self):
        rng = np.random.default_rng(3)
        a = rng.integers(0, 2, size=(4, 6, 300)).astype(np.int32)
        b = rng.integers(0, 2, size=(300, 5)).astype(np.int32)
        got = np.asarray(gf2_matmul(jnp.asarray(a), jnp.asarray(b)))
        np.testing.assert_array_equal(got, (a @ b) % 2)

    def test_matmul_empty_contraction_is_zero(self):
        out = gf2_matmul(
            jnp.zeros((3, 0), jnp.int32), jnp.zeros((0, 4), jnp.int32)
        )
        assert out.shape == (3, 4)
        assert not np.asarray(out).any()

    def test_matmul_rejects_bad_shapes_and_tiles(self):
        a = jnp.zeros((2, 3), jnp.int32)
        with pytest.raises(ValueError, match="contraction mismatch"):
            gf2_matmul(a, jnp.zeros((4, 2), jnp.int32))
        with pytest.raises(ValueError, match="bf16"):
            gf2_matmul(a, jnp.zeros((3, 2), jnp.int32), tile_k=GF2_TILE_K + 1)

    def test_matvec(self):
        rng = np.random.default_rng(5)
        m = rng.integers(0, 2, size=(7, 40)).astype(np.int32)
        v = rng.integers(0, 2, size=(40,)).astype(np.int32)
        np.testing.assert_array_equal(
            np.asarray(gf2_matvec(jnp.asarray(m), jnp.asarray(v))),
            (m @ v) % 2,
        )

    def test_rank1_update_packed(self):
        rng = np.random.default_rng(9)
        m = rng.integers(0, 2, size=(8, 50)).astype(np.int32)
        mask = rng.integers(0, 2, size=(8,)).astype(np.int32)
        row = rng.integers(0, 2, size=(50,)).astype(np.int32)
        got = unpack_bits(
            rank1_update_packed(
                pack_bits(jnp.asarray(m)),
                jnp.asarray(mask),
                pack_bits(jnp.asarray(row)),
            ),
            50,
        )
        np.testing.assert_array_equal(
            np.asarray(got), m ^ (mask[:, None] & row[None, :])
        )

    def test_triangular_parity_vs_direct(self):
        # Direct strict-upper-triangle formulation: parity of
        # sum_{a<b} <z_a, x_b> — the O(R^2) form the prefix-XOR replaces.
        rng = np.random.default_rng(21)
        z = rng.integers(0, 2, size=(10, 64)).astype(np.int32)
        x = rng.integers(0, 2, size=(10, 64)).astype(np.int32)
        direct = 0
        for a in range(10):
            for b in range(a + 1, 10):
                direct ^= int(z[a] @ x[b]) & 1
        got = triangular_parity(pack_bits(jnp.asarray(z)),
                                pack_bits(jnp.asarray(x)))
        assert int(got) == direct


# ---------------------------------------------------------------------------
# symplectic compilation: static op list -> aggregate GF(2) transform.


class TestSymplecticCompile:
    def test_empty_circuit_is_identity(self):
        prog = compile_symplectic(4, (), 0)
        eye = np.eye(4, dtype=np.int32)
        zero = np.zeros((4, 4), np.int32)
        np.testing.assert_array_equal(prog.x, np.concatenate([eye, zero]))
        np.testing.assert_array_equal(prog.z, np.concatenate([zero, eye]))
        assert not prog.r.any()
        # n_params is padded to >= 1 column; all-zero = no phase deps.
        assert prog.l.shape[0] == 8 and not prog.l.any()

    def test_rejects_non_clifford(self):
        with pytest.raises(ValueError):
            compile_symplectic(2, (Op("T", 0),), 0)


def _random_clifford_ops(seed, n, n_ops, n_params):
    """A random op list over the stabilizer engine's gate set."""
    rng = random.Random(seed)
    ops = []
    for _ in range(n_ops):
        kind = rng.choice(["H", "X", "Y", "Z", "CNOT", "CZ", "XPOW"])
        t = rng.randrange(n)
        if kind in ("CNOT", "CZ"):
            c = rng.choice([q for q in range(n) if q != t])
            ops.append(Op("X" if kind == "CNOT" else "Z", t, (c,)))
        elif kind == "XPOW":
            ops.append(Op("XPOW", t, (), rng.randrange(n_params)))
        else:
            ops.append(Op(kind, t))
    return tuple(ops)


# ---------------------------------------------------------------------------
# Bit-identity: batched symplectic vs per-shot tableau, identical keys.


class TestBitIdentity:
    N, N_PARAMS, SHOTS = 6, 4, 16

    @pytest.mark.parametrize("seed", range(6))
    def test_random_cliffords_with_params(self, seed):
        ops = _random_clifford_ops(seed, self.N, 40, self.N_PARAMS)
        params = jnp.asarray(
            np.random.default_rng(seed).integers(0, 2, self.N_PARAMS),
            jnp.int32,
        )
        key = jax.random.key(100 + seed)
        ref = build_tableau_run_shots(self.N, ops, self.N_PARAMS)(
            key, self.SHOTS, params
        )
        got = build_gf2_tableau_run_shots(self.N, ops, self.N_PARAMS)(
            key, self.SHOTS, params
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    @pytest.mark.parametrize("seed", range(2))
    def test_random_cliffords_no_params(self, seed):
        ops = tuple(
            op for op in _random_clifford_ops(seed + 50, self.N, 40, 1)
            if op.kind != "XPOW"
        )
        key = jax.random.key(200 + seed)
        ref = build_tableau_run_shots(self.N, ops, 0)(key, self.SHOTS)
        got = build_gf2_tableau_run_shots(self.N, ops, 0)(key, self.SHOTS)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_run_batch_per_shot_params(self):
        # [B, P] per-shot param rows, not just one broadcast [P] vector:
        # row i of the batch must match a solo tableau run with
        # (keys[i], params[i]).
        from qba_tpu.qsim.stabilizer import build_tableau_run

        ops = _random_clifford_ops(99, self.N, 30, self.N_PARAMS)
        keys = jax.random.split(jax.random.key(42), 8)
        params = jnp.asarray(
            np.random.default_rng(0).integers(0, 2, (8, self.N_PARAMS)),
            jnp.int32,
        )
        run1 = build_tableau_run(self.N, ops, self.N_PARAMS)
        ref = np.stack(
            [np.asarray(run1(keys[i], params[i])) for i in range(8)]
        )
        run = build_gf2_tableau_run_batch(self.N, ops, self.N_PARAMS)
        np.testing.assert_array_equal(np.asarray(run(keys, params)), ref)

    @pytest.mark.parametrize("n_parties", [5, 11])
    def test_protocol_families_bit_identical(self, n_parties):
        # The acceptance-criterion differential: generate_lists on the
        # batched GF(2) path == the per-position tableau reference,
        # same key, bitwise.
        cfg = QBAConfig(
            n_parties=n_parties, size_l=16,
            n_dishonest=min(3, n_parties - 2), qsim_path="stabilizer",
        )
        key = jax.random.key(n_parties)
        lists_b, qcorr_b = generate_lists_stabilizer(cfg, key)
        lists_r, qcorr_r = generate_lists_dense(cfg, key, impl="stabilizer")
        np.testing.assert_array_equal(np.asarray(qcorr_b), np.asarray(qcorr_r))
        np.testing.assert_array_equal(np.asarray(lists_b), np.asarray(lists_r))

    def test_generate_lists_for_dispatch(self):
        cfg = QBAConfig(
            n_parties=5, size_l=8, n_dishonest=1, qsim_path="stabilizer"
        )
        key = jax.random.key(3)
        lists_a, qcorr_a = generate_lists_for(cfg, key)
        lists_b, qcorr_b = generate_lists_stabilizer(cfg, key)
        np.testing.assert_array_equal(np.asarray(lists_a), np.asarray(lists_b))
        np.testing.assert_array_equal(np.asarray(qcorr_a), np.asarray(qcorr_b))


# ---------------------------------------------------------------------------
# impl="auto" chooser: dense under the cap, stabilizer handoff past it.


class TestAutoHandoff:
    def test_under_cap_stays_dense(self):
        c = Circuit(3).add_operation(Gate(3).add_operation("H", targets=0))
        assert c.resolve_auto_impl() in ("pallas", "pallas_interpret")

    def test_past_cap_clifford_demotes_with_record(self):
        n = DENSE_QUBIT_CAP + 5
        g = Gate(n)
        for q in range(n):
            g.add_operation("H", targets=q)
        c = Circuit(n).add_operation(g)
        with record_decisions() as decisions:
            with pytest.warns(QBADemotionWarning, match="dense cap"):
                assert c.resolve_auto_impl() == "stabilizer"
        assert any(
            d["kind"] == "demotion" and d["engine_to"] == "stabilizer"
            and d["reason"] == "dense_qubit_cap"
            for d in decisions
        )

    def test_past_cap_non_clifford_raises(self):
        n = DENSE_QUBIT_CAP + 1
        c = Circuit(n).add_operation(Gate(n).add_operation("T", targets=0))
        with pytest.raises(ValueError, match="Clifford gate set"):
            c.resolve_auto_impl()

    def test_generate_lists_auto_handoff_matches_stabilizer(self):
        # 11 parties = 48 joint qubits: past the dense cap, so
        # impl="auto" must route to (and bit-match) the batched engine.
        cfg = QBAConfig(n_parties=11, size_l=8, n_dishonest=3)
        key = jax.random.key(8)
        with pytest.warns(QBADemotionWarning, match="dense cap"):
            lists_a, qcorr_a = generate_lists_dense(cfg, key, impl="auto")
        lists_s, qcorr_s = generate_lists_stabilizer(cfg, key)
        np.testing.assert_array_equal(np.asarray(lists_a), np.asarray(lists_s))
        np.testing.assert_array_equal(np.asarray(qcorr_a), np.asarray(qcorr_s))


# ---------------------------------------------------------------------------
# Statistical cross-checks: vs the dense statevector at small n, and vs
# the closed-form sampler's marginal laws at protocol shape.


class TestStatistical:
    def test_outcome_law_vs_statevector_chi_square(self):
        # GHZ-flavored 3-qubit Clifford with a phase kickback: compare
        # full 8-outcome distributions, chi-square at significance 1e-4.
        from scipy import stats

        g = (
            Gate(3)
            .add_operation("H", targets=0)
            .add_operation("X", targets=1, controls=0)
            .add_operation("Z", targets=2, controls=1)
            .add_operation("H", targets=2)
            .add_operation("X", targets=2, controls=0)
        )
        c = Circuit(3).add_operation(g)
        shots = 4096
        dense_run = c.compile("xla")
        keys = jax.random.split(jax.random.key(1), shots)
        dense = np.asarray(jax.jit(jax.vmap(dense_run))(keys))
        gf2 = np.asarray(
            build_gf2_tableau_run_shots(3, tuple(c.ops), 0)(
                jax.random.key(2), shots
            )
        )
        weights = np.asarray([4, 2, 1])
        table = np.stack([
            np.bincount(dense @ weights, minlength=8),
            np.bincount(gf2 @ weights, minlength=8),
        ])
        # drop never-hit outcomes (zero columns break the contingency test)
        table = table[:, table.sum(axis=0) > 0]
        assert stats.chi2_contingency(table).pvalue > 1e-4

    def test_closed_form_marginals_at_protocol_shape(self):
        # The §2.6 invariants + full value laws on the batched engine,
        # mirroring TestFactorizedSampler — validates against the
        # closed-form sampler's marginals, not another tableau.
        from scipy import stats

        cfg = QBAConfig(n_parties=3, size_l=2048, qsim_path="stabilizer")
        lists, qcorr = generate_lists_stabilizer(cfg, jax.random.key(6))
        lists, qcorr = np.asarray(lists), np.asarray(qcorr)
        check_closed_form_properties(lists, qcorr, cfg.w)
        r = lists[0][qcorr]
        assert stats.chisquare(np.bincount(r, minlength=cfg.w)).pvalue > 1e-4
        for row in lists:
            obs = np.bincount(row, minlength=cfg.w)
            assert stats.chisquare(obs).pvalue > 1e-4
        xors = lists[1:, qcorr] ^ lists[0:1, qcorr]
        for i in range(cfg.n_parties):
            obs = np.bincount(xors[i], minlength=cfg.n_parties + 1)[1:]
            assert stats.chisquare(obs).pvalue > 1e-4
        # qcorr stays Bernoulli(1/2) on this path too.
        k = int(qcorr.sum())
        assert stats.binomtest(k, cfg.size_l, 0.5).pvalue > 1e-4

    def test_cross_validates_factorized_sampler(self):
        cfg = QBAConfig(n_parties=3, size_l=1024, qsim_path="stabilizer")
        ls, qs = generate_lists_stabilizer(cfg, jax.random.key(7))
        lf, qf = generate_lists(cfg, jax.random.key(8))
        from scipy import stats

        for lists, qcorr in ((ls, qs), (lf, qf)):
            check_closed_form_properties(
                np.asarray(lists), np.asarray(qcorr), cfg.w
            )
        for lists in (ls, lf):
            for row in np.asarray(lists):
                obs = np.bincount(row, minlength=cfg.w)
                assert stats.chisquare(obs).pvalue > 1e-4


# ---------------------------------------------------------------------------
# Protocol smoke (tier-1) and reference-scale runs (slow).


class TestProtocolSmoke:
    def test_small_n_stabilizer_trial(self):
        # Tier-1 smoke: the full protocol through the batched GF(2)
        # resource path at 5 parties, all honest -> unanimous on v.
        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=0, qsim_path="stabilizer"
        )
        keys = jax.random.split(jax.random.key(0), 8)
        r = jax.jit(jax.vmap(lambda k: run_trial(cfg, k)))(keys)
        assert float(jnp.mean(r.success)) == 1.0
        assert bool(jnp.all(r.decisions == r.v_comm[:, None]))


@pytest.mark.slow
class TestReferenceScale:
    def test_65_party_protocol_trial(self):
        # 66 groups x 7 qubits = 462 joint qubits (w=128): far past any
        # dense engine; the batched GF(2) path runs it end to end.  All
        # honest, so validity is deterministic (with dishonest parties
        # success at size_l=8 is probabilistic — the forgery window,
        # docs/VALIDITY.md / tests/test_e2e.py).
        cfg = QBAConfig(
            n_parties=65, size_l=8, n_dishonest=0, qsim_path="stabilizer"
        )
        r = jax.jit(lambda k: run_trial(cfg, k))(jax.random.key(0))
        assert bool(jnp.all(jnp.asarray(r.success)))
        assert bool(jnp.all(r.decisions == r.v_comm))

    @pytest.mark.parametrize(
        "n_parties,total,w", [(129, 1040, 256), (257, 2322, 512)]
    )
    def test_large_party_resource_generation(self, n_parties, total, w):
        cfg = QBAConfig(
            n_parties=n_parties, size_l=4, n_dishonest=1,
            qsim_path="stabilizer",
        )
        assert cfg.total_qubits == total and cfg.w == w
        lists, qcorr = generate_lists_stabilizer(cfg, jax.random.key(1))
        assert lists.shape == (n_parties + 1, 4)
        check_closed_form_properties(
            np.asarray(lists), np.asarray(qcorr), cfg.w
        )
