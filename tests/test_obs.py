"""Observability subsystem tests: events, timers, reports."""

import io
import json

import jax
import numpy as np

from qba_tpu.config import QBAConfig
from qba_tpu.obs import EventLog, Level, PhaseTimers, render_sweep, render_verdict, throughput
from qba_tpu.rounds import run_trial


class TestEventLog:
    def test_levels_filter(self):
        log = EventLog(min_level=Level.INFO)
        log.debug("round", "dropped")
        log.info("round", "kept", round=1)
        assert len(log.events) == 1
        assert log.events[0].fields == {"round": 1}

    def test_stream_renders(self):
        buf = io.StringIO()
        log = EventLog(stream=buf)
        log.info("particles", "distributed", n=3)
        assert buf.getvalue() == "[particles] distributed n=3\n"

    def test_jsonl_roundtrip(self, tmp_path):
        log = EventLog()
        log.info("decision", "verdict", success=True)
        log.warning("round", "overflow")
        path = tmp_path / "events.jsonl"
        log.write_jsonl(str(path))
        lines = path.read_text().strip().splitlines()
        assert len(lines) == 2
        rec = json.loads(lines[0])
        assert rec["phase"] == "decision" and rec["success"] is True
        assert json.loads(lines[1])["level"] == "WARNING"


class TestTimers:
    def test_accumulates(self):
        t = {"now": 0.0}

        def clock():
            return t["now"]

        timers = PhaseTimers(clock=clock)
        for _ in range(2):
            with timers.time("rounds"):
                t["now"] += 1.5
        assert timers.total("rounds") == 3.0
        assert timers.count("rounds") == 2
        assert timers.summary()["rounds"] == {"total_s": 3.0, "count": 2}
        assert "rounds" in timers.render()

    def test_throughput(self):
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=1)
        th = throughput(cfg, n_trials=10, seconds=2.0)
        assert th["trials_per_sec"] == 5.0
        # n_rounds = n_dishonest + 1 = 2 (tfg.py:337)
        assert th["rounds_per_sec"] == 10.0


class TestReports:
    def test_verdict_matches_trial(self):
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=0)
        res = jax.jit(lambda k: run_trial(cfg, k))(jax.random.key(0))
        text = render_verdict(cfg, res)
        v = int(np.asarray(res.v_comm))
        assert f"Decisions:  [{v}, {v}, {v}]" in text
        assert "Dishonests: []" in text
        assert "Success:    True" in text

    def test_verdict_no_decision_sentinel(self):
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=1)

        class T:
            decisions = np.array([2, cfg.no_decision, 1])
            honest = np.array([False, True, True])
            success = np.array(False)
            overflow = np.array(False)

        text = render_verdict(cfg, T(), index=7)
        assert "trial 7:" in text
        assert "[2, None, 1]" in text
        assert "Dishonests: [1]" in text  # commander rank 1 dishonest

    def test_sweep_summary(self):
        cfg = QBAConfig(n_parties=11, size_l=16, n_dishonest=3)
        text = render_sweep(cfg, success_rate=0.975, n_trials=400, seconds=2.0)
        assert "success rate: 0.9750" in text
        # 400 trials * 4 rounds / 2 s = 800 rounds/s
        assert "800.0 protocol rounds/s" in text


class TestStudyStats:
    def test_wilson_interval_basics(self):
        from qba_tpu.obs.stats import wilson_interval

        lo, hi = wilson_interval(0, 0)
        assert (lo, hi) == (0.0, 1.0)
        lo, hi = wilson_interval(50, 100)
        assert 0.40 < lo < 0.5 < hi < 0.60
        lo, hi = wilson_interval(100, 100)
        assert lo > 0.95 and hi > 0.9999
        lo, hi = wilson_interval(0, 100)
        assert lo == 0.0 and hi < 0.05

    def test_study_breakdown_conditions_on_commander(self):
        import numpy as np

        from qba_tpu.obs.stats import study_breakdown

        success = np.array([True, False, True, True])
        ch = np.array([True, True, False, False])
        b = study_breakdown(success, ch)
        assert b["overall"]["k"] == 3 and b["overall"]["n"] == 4
        assert b["validity"]["k"] == 1 and b["validity"]["n"] == 2
        assert b["agreement_dishonest_c"]["k"] == 2

    def test_decision_profile_classes(self):
        import numpy as np

        from qba_tpu.obs.stats import decision_profile

        w = 8
        # 5 trials, 4 parties (commander + 3 lieutenants), all honest
        # except trial 4's commander (excluded from conditioning).
        v_comm = np.array([3, 3, 3, 3, 3])
        honest = np.ones((5, 4), dtype=bool)
        honest[4, 0] = False
        decisions = np.array([
            [3, 3, 3, 3],   # valid
            [3, w, w, w],   # abort_all
            [3, 3, w, 3],   # mixed valid/abort
            [3, 3, 1, 3],   # corrupted (forged 1 < 3 won a min(Vi))
            [3, 3, 3, 3],   # dishonest commander: not conditioned on
        ])
        p = decision_profile(decisions, honest, v_comm, w)
        assert p["n_honest_commander"] == 4
        assert p["valid"]["k"] == 1
        assert p["abort_all"]["k"] == 1
        assert p["mixed_valid_abort"]["k"] == 1
        assert p["corrupted"]["k"] == 1

    def test_decision_profile_ignores_dishonest_lieutenants(self):
        import numpy as np

        from qba_tpu.obs.stats import decision_profile

        w = 8
        v_comm = np.array([2])
        honest = np.array([[True, True, False, True]])
        decisions = np.array([[2, 2, 0, 2]])  # dishonest lieu's 0 ignored
        p = decision_profile(decisions, honest, v_comm, w)
        assert p["valid"]["k"] == 1 and p["corrupted"]["k"] == 0
