"""Differential test: vectorized jax engine vs message-level local backend.

Both backends consume the same keyed randomness, so per-trial outcomes
must match *exactly* — decisions, accepted-sets, verdict.  This is the
strongest fidelity check available without the reference's runtime
(mpi4py/qsimov are not installable here): two independently written
implementations of the protocol semantics checking each other, per trial.
"""

import jax
import jax.numpy as jnp
import pytest

from qba_tpu.backends import run_trial_local, run_trials
from qba_tpu.config import QBAConfig

CONFIGS = [
    QBAConfig(n_parties=3, size_l=8, n_dishonest=0, trials=16, seed=10),
    QBAConfig(n_parties=3, size_l=16, n_dishonest=1, trials=24, seed=11),
    QBAConfig(n_parties=5, size_l=16, n_dishonest=2, trials=16, seed=12),
    QBAConfig(n_parties=11, size_l=16, n_dishonest=3, trials=6, seed=13),
    # reduced slot bound exercises the overflow path in both backends
    QBAConfig(
        n_parties=5, size_l=8, n_dishonest=2, trials=12, seed=14,
        max_accepts_per_round=1,
    ),
]


@pytest.mark.parametrize("cfg", CONFIGS, ids=lambda c: f"p{c.n_parties}_d{c.n_dishonest}_s{c.size_l}")
def test_backends_agree_per_trial(cfg):
    keys = jax.random.split(jax.random.key(cfg.seed), cfg.trials)
    mc = run_trials(cfg, keys)
    for t in range(cfg.trials):
        local = run_trial_local(cfg, keys[t])
        jax_decisions = mc.trials.decisions[t].tolist()
        assert jax_decisions == local["decisions"], (
            f"trial {t}: jax {jax_decisions} vs local {local['decisions']} "
            f"(honest={local['honest']})"
        )
        assert bool(mc.trials.success[t]) == local["success"], f"trial {t}"
        assert bool(mc.trials.overflow[t]) == local["overflow"], f"trial {t}"
        # accepted-sets match too (Vi mask vs set)
        for i in range(cfg.n_lieutenants):
            mask = mc.trials.vi[t, i]
            got = {int(v) for v in jnp.nonzero(mask)[0]}
            assert got == local["vi"][i], f"trial {t} lieu {i}"
