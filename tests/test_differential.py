"""Differential test: vectorized jax engine vs message-level local backend.

Both backends consume the same keyed randomness, so per-trial outcomes
must match *exactly* — decisions, accepted-sets, verdict.  This is the
strongest fidelity check available without the reference's runtime
(mpi4py/qsimov are not installable here): two independently written
implementations of the protocol semantics checking each other, per trial.
"""

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from qba_tpu.backends import run_trial_local, run_trials
from qba_tpu.backends.jax_backend import batched_trials
from qba_tpu.config import QBAConfig

CONFIGS = [
    QBAConfig(n_parties=3, size_l=8, n_dishonest=0, trials=16, seed=10),
    QBAConfig(n_parties=3, size_l=16, n_dishonest=1, trials=24, seed=11),
    QBAConfig(n_parties=5, size_l=16, n_dishonest=2, trials=16, seed=12),
    QBAConfig(n_parties=11, size_l=16, n_dishonest=3, trials=6, seed=13),
    # reduced slot bound exercises the overflow path in both backends
    QBAConfig(
        n_parties=5, size_l=8, n_dishonest=2, trials=12, seed=14,
        max_accepts_per_round=1,
    ),
    # reference-faithful mutation-leak attack semantics (DIVERGENCES D3)
    QBAConfig(
        n_parties=5, size_l=16, n_dishonest=2, trials=16, seed=15,
        attack_scope="broadcast",
    ),
    QBAConfig(
        n_parties=7, size_l=8, n_dishonest=4, trials=8, seed=16,
        attack_scope="broadcast",
    ),
]


@pytest.mark.parametrize("cfg", CONFIGS, ids=lambda c: f"p{c.n_parties}_d{c.n_dishonest}_s{c.size_l}")
def test_backends_agree_per_trial(cfg):
    keys = jax.random.split(jax.random.key(cfg.seed), cfg.trials)
    mc = run_trials(cfg, keys)
    for t in range(cfg.trials):
        local = run_trial_local(cfg, keys[t])
        jax_decisions = mc.trials.decisions[t].tolist()
        assert jax_decisions == local["decisions"], (
            f"trial {t}: jax {jax_decisions} vs local {local['decisions']} "
            f"(honest={local['honest']})"
        )
        assert bool(mc.trials.success[t]) == local["success"], f"trial {t}"
        assert bool(mc.trials.overflow[t]) == local["overflow"], f"trial {t}"
        # accepted-sets match too (Vi mask vs set)
        for i in range(cfg.n_lieutenants):
            mask = mc.trials.vi[t, i]
            got = {int(v) for v in jnp.nonzero(mask)[0]}
            assert got == local["vi"][i], f"trial {t} lieu {i}"


def test_randomized_config_fuzz_three_way():
    """Differential fuzz: random small configs, all three backends must
    agree trial by trial (the strongest correctness check we have — three
    independent implementations of the full protocol)."""
    from qba_tpu.backends.native_backend import run_trials_native
    from qba_tpu.native import available

    if not available():
        pytest.skip("native toolchain unavailable; three-way fuzz needs it")
    rng = np.random.default_rng(123)
    for case in range(6):
        n_parties = int(rng.integers(2, 7))
        racy = rng.random() < 0.3
        cfg = QBAConfig(
            n_parties=n_parties,
            size_l=int(rng.integers(1, 24)),
            n_dishonest=int(rng.integers(0, n_parties + 1)),
            trials=4,
            seed=int(rng.integers(0, 1000)),
            max_accepts_per_round=(
                int(rng.integers(1, 4)) if rng.random() < 0.3 else None
            ),
            delivery="racy" if racy else "sync",
            p_late=0.4 if racy else 0.0,
            attack_scope="broadcast" if rng.random() < 0.5 else "delivery",
        )
        keys = jax.random.split(jax.random.key(cfg.seed), cfg.trials)
        a = batched_trials(cfg, keys)
        nat = run_trials_native(cfg, keys)
        if cfg.max_accepts_per_round is None:
            # D9: slots = w is a lossless bound; overflow must be impossible.
            assert not bool(jnp.any(a.overflow)), f"case={case} cfg={cfg}"
        for i in range(cfg.trials):
            b = run_trial_local(cfg, keys[i])
            ctx = f"case={case} cfg={cfg} trial={i}"
            assert [int(x) for x in a.decisions[i]] == b["decisions"], ctx
            assert bool(a.success[i]) == b["success"], ctx
            assert nat["decisions"][i].tolist() == b["decisions"], ctx
