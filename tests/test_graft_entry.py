"""Driver integration points (`__graft_entry__`).

Round 1's multi-chip gate failed because ``dryrun_multichip`` assumed the
ambient process already had ``n`` devices (MULTICHIP_r01.json: rc=1 on the
1-chip axon platform).  These tests pin both acquisition paths:

* in-process when enough devices exist (conftest provisions 8 CPU devices),
* the self-provisioning subprocess used when they don't.
"""

import jax
import pytest

import __graft_entry__ as graft


def test_entry_returns_jittable_fn():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.success.shape == (8,)


def test_dryrun_multichip_inprocess():
    # conftest forces 8 virtual CPU devices, so this takes the in-process
    # branch and exercises all three sharded stages.
    assert len(jax.devices()) >= 8
    graft.dryrun_multichip(8)


def test_dryrun_subprocess_provisions_devices():
    # The subprocess path must work even though THIS process also could —
    # it is the path the driver hits when JAX sits on a 1-chip platform.
    graft._dryrun_in_subprocess(2)


def test_dryrun_subprocess_failure_raises():
    # A child failure must surface, not pass silently; 0 devices cannot
    # ever provision a mesh.
    with pytest.raises(RuntimeError, match="subprocess failed"):
        graft._dryrun_in_subprocess(0)
