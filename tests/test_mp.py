"""Multi-process backend: the reference's runtime shape, differentially
pinned (VERDICT r2 item 3).

One OS process per party over a Unix-socket mesh, every packet through
the C++ PvL wire codec — and for any config and trial key the decisions,
accepted-sets, overflow and the full event trail must match the
in-process backends exactly (the four-way differential: mp / local /
native / jax).
"""


import jax
import pytest

from qba_tpu.backends.jax_backend import trial_keys
from qba_tpu.backends.local_backend import run_trial_local
from qba_tpu.backends.mp_backend import run_trial_mp
from qba_tpu.config import QBAConfig

CONFIGS = [
    QBAConfig(n_parties=3, size_l=8),
    QBAConfig(n_parties=5, size_l=16, n_dishonest=2),
    QBAConfig(
        n_parties=5, size_l=16, n_dishonest=2, attack_scope="broadcast"
    ),
    QBAConfig(
        n_parties=4, size_l=8, n_dishonest=1, delivery="racy", p_late=0.4
    ),
    QBAConfig(
        n_parties=4, size_l=8, n_dishonest=1, delivery="racy",
        p_late=0.5, racy_mode="defer",
    ),
]
_IDS = [
    f"p{c.n_parties}_d{c.n_dishonest}_{c.attack_scope[:5]}_{c.delivery}"
    f"_{c.racy_mode}"
    for c in CONFIGS
]


class TestMpDifferential:
    @pytest.mark.parametrize("cfg", CONFIGS, ids=_IDS)
    def test_matches_local_backend(self, cfg):
        for seed in range(2):
            k = jax.random.key(seed)
            a = run_trial_local(cfg, k)
            b = run_trial_mp(cfg, k)
            assert a["decisions"] == b["decisions"]
            assert a["vi"] == b["vi"]
            assert a["overflow"] == b["overflow"]
            assert a["success"] == b["success"]

    def test_four_way_differential(self):
        # mp == local == native == jax on one adversarial batch.
        from qba_tpu.backends.native_backend import run_trial_native
        from qba_tpu.rounds import run_trial

        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2, trials=3)
        keys = trial_keys(cfg)
        for i in range(cfg.trials):
            m = run_trial_mp(cfg, keys[i])
            l = run_trial_local(cfg, keys[i])
            n = run_trial_native(cfg, keys[i])
            j = run_trial(cfg, keys[i])
            assert m["decisions"] == l["decisions"] == n["decisions"]
            assert m["decisions"] == [int(x) for x in j.decisions]
            assert m["vi"] == l["vi"] == n["vi"]

    def test_batch_mode_one_mesh_many_trials(self):
        # Round 4 (VERDICT r3 item 4): one persistent party mesh serves
        # a whole batch — per-trial results must equal the local
        # backend's AND the per-trial-spawn path's (run_trial_mp with
        # the same keys), trial for trial.
        from qba_tpu.backends.mp_backend import run_trials_mp

        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2, trials=4)
        keys = trial_keys(cfg)
        batch = run_trials_mp(cfg, [keys[i] for i in range(cfg.trials)])
        assert len(batch) == cfg.trials
        for i in range(cfg.trials):
            ref = run_trial_local(cfg, keys[i])
            assert batch[i]["decisions"] == ref["decisions"]
            assert batch[i]["vi"] == ref["vi"]
            assert batch[i]["overflow"] == ref["overflow"]
            assert batch[i]["success"] == ref["success"]

    def test_eleven_party_differential(self):
        # Scale proof past the round-3 five-party ceiling: a full
        # 11-party mesh (the reference's own largest captured config,
        # logs tests/log_d_11.txt) with dishonest parties, batch mode.
        from qba_tpu.backends.mp_backend import run_trials_mp

        cfg = QBAConfig(n_parties=11, size_l=16, n_dishonest=5)
        keys = [jax.random.key(3), jax.random.key(4)]
        batch = run_trials_mp(cfg, keys)
        for key, got in zip(keys, batch):
            ref = run_trial_local(cfg, key)
            assert got["decisions"] == ref["decisions"]
            assert got["vi"] == ref["vi"]
            assert got["success"] == ref["success"]

    def test_batch_trail_parity_per_trial(self):
        # The event trail of trial i in a batch must match the local
        # backend's trail for that trial (same trial index, same order).
        from qba_tpu.backends.mp_backend import run_trials_mp
        from qba_tpu.obs import EventLog, Level

        cfg = QBAConfig(n_parties=4, size_l=8, n_dishonest=1)
        keys = [jax.random.key(7), jax.random.key(8)]
        log_m = EventLog(Level.DEBUG)
        run_trials_mp(cfg, keys, log=log_m)
        log_l = EventLog(Level.DEBUG)
        for i, k in enumerate(keys):
            run_trial_local(cfg, k, log=log_l, trial=i)
        assert [
            (e.phase, e.message, e.fields) for e in log_m.events
        ] == [
            (e.phase, e.message, e.fields) for e in log_l.events
        ]

    def test_tight_slot_overflow(self):
        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=2, max_accepts_per_round=1
        )
        # Find seeds where the bound binds with the fast local backend,
        # then pin the mp backend on one overflowing and one clean seed.
        seeds = {True: None, False: None}
        for seed in range(32):
            r = run_trial_local(cfg, jax.random.key(seed))
            if seeds[r["overflow"]] is None:
                seeds[r["overflow"]] = seed
            if None not in seeds.values():
                break
        assert seeds[True] is not None, "no seed exercised the bound"
        for seed in (s for s in seeds.values() if s is not None):
            k = jax.random.key(seed)
            a = run_trial_local(cfg, k)
            b = run_trial_mp(cfg, k)
            assert a["overflow"] == b["overflow"]
            assert a["decisions"] == b["decisions"]


class TestMpTrail:
    @pytest.mark.parametrize(
        "cfg",
        [
            QBAConfig(n_parties=5, size_l=16, n_dishonest=2),
            QBAConfig(
                n_parties=4, size_l=8, n_dishonest=1, delivery="racy",
                p_late=0.5, racy_mode="defer",
            ),
        ],
        ids=("adversarial", "defer"),
    )
    def test_trail_matches_local_backend(self, cfg):
        from qba_tpu.obs import EventLog, Level

        k = jax.random.key(1)
        log_l, log_m = EventLog(Level.DEBUG), EventLog(Level.DEBUG)
        run_trial_local(cfg, k, log=log_l)
        run_trial_mp(cfg, k, log=log_m)
        a = [(e.phase, e.message, e.fields) for e in log_l.events]
        b = [(e.phase, e.message, e.fields) for e in log_m.events]
        assert len(a) == len(b), (len(a), len(b))
        for i, (x, y) in enumerate(zip(a, b)):
            assert x == y, f"event {i}: local={x} mp={y}"


class TestWireBoundary:
    def test_party_codec_roundtrip_and_malformed_rejection(self):
        # The exact codec object the party processes run: C-encoded wire
        # bytes round-trip, and a truncated buffer is rejected (the wire
        # format is load-bearing across the socket, not Python pickling).
        import qba_tpu.backends.mp_party as mp_party
        from qba_tpu import native

        native.load()
        codec = mp_party._Codec(native._SO, 8, 3)
        wire = codec.encode({1, 3}, 2, {(0, 5), (4, 1)})
        p, v, L = codec.decode(wire)
        assert p == {1, 3} and v == 2 and L == {(0, 5), (4, 1)}
        with pytest.raises(RuntimeError, match="malformed"):
            codec.decode(wire[:4])

    def test_mp_matches_at_reference_scale_params(self):
        # 11 parties (the reference's larger demo scale), small sizeL
        # for CI: eleven real OS processes, one mesh.
        cfg = QBAConfig(n_parties=11, size_l=16, n_dishonest=3)
        k = jax.random.key(5)
        a = run_trial_local(cfg, k)
        b = run_trial_mp(cfg, k)
        assert a["decisions"] == b["decisions"]
        assert a["vi"] == b["vi"]
        assert a["success"] == b["success"]


class TestDeadlineHazards:
    """The recv/send deadline helpers abandon daemon threads still
    blocked on the Connection; cleanup must never close (or write) a
    connection such a thread still owns (ADVICE r4 + review r5)."""

    def test_recv_deadline_poisons_wedged_conn(self):
        import multiprocessing as mp

        from qba_tpu.backends.mp_backend import _recv_deadline

        parent, child = mp.Pipe(duplex=True)
        try:
            with pytest.raises(RuntimeError, match="recv deadline"):
                _recv_deadline(parent, 0.05)  # nothing ever written
            assert getattr(parent, "_qba_poisoned", False)
        finally:
            child.close()  # EOFs the abandoned reader thread

    def test_recv_deadline_grace_recovers_readable_pipe(self):
        # remaining <= 0 with the report already sitting in the pipe
        # (budget consumed by a sibling recv in the same wait batch):
        # the grace join must deliver it instead of poisoning a healthy
        # party out of its graceful stop.
        import multiprocessing as mp

        from qba_tpu.backends.mp_backend import _recv_deadline

        parent, child = mp.Pipe(duplex=True)
        try:
            child.send(("ok", 42))
            assert _recv_deadline(parent, 0.0) == ("ok", 42)
            assert not getattr(parent, "_qba_poisoned", False)
        finally:
            parent.close()
            child.close()

    def test_send_deadline_poisons_inflight_conn(self):
        import threading

        from qba_tpu.backends.mp_backend import _send_with_deadline

        ev = threading.Event()

        class WedgedConn:
            def send(self, msg):
                ev.wait()  # blocked "in the OS write" forever

        class FineConn:
            def __init__(self):
                self.sent = []

            def send(self, msg):
                self.sent.append(msg)

        pipes = {1: FineConn(), 2: WedgedConn()}
        try:
            with pytest.raises(RuntimeError, match="dispatch timed out"):
                _send_with_deadline(
                    pipes, [(1, ("work",)), (2, ("work",))], 0.1
                )
            assert pipes[1].sent == [("work",)]
            assert getattr(pipes[2], "_qba_poisoned", False)
            assert not getattr(pipes[1], "_qba_poisoned", False)
        finally:
            ev.set()  # release the abandoned sender thread
