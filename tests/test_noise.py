"""Imperfect quantum resources (qba_tpu.qsim.noise, ISSUE PR 9).

Contract layers, mirroring tests/test_gf2.py:

* **Zero-noise gating** — ``p_depolarize = p_measure_flip = 0.0`` is
  *byte-identical* to the pre-noise sampler on every path (the noise
  branch is statically gated on Python floats and never traced).
* **Bit-identity differentials** — the two stabilizer engines (per-shot
  tableau and batched GF(2)) share one ``noise_draws`` stream per shot
  key, so their noisy outputs must match bit for bit; likewise the two
  protocol list-generation paths on the stabilizer impl.
* **Statistical cross-checks** — the classical reduction's flip rate
  matches the closed-form channel rate, and dense-vs-stabilizer outcome
  distributions agree under noise (chi-square; the classical-reduction
  and phase-injection implementations are exact realizations of the
  SAME channel, so only sampling noise separates them).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy import stats

from qba_tpu.config import QBAConfig
from qba_tpu.gf2 import build_gf2_tableau_run_batch
from qba_tpu.qsim.noise import (
    classical_flip_ints,
    classical_flips,
    classical_flips_shots,
    noise_draws,
)
from qba_tpu.qsim.protocol_circuits import (
    gen_q_corr_circuit,
    generate_lists_dense,
    generate_lists_stabilizer,
)
from qba_tpu.qsim.sampler import generate_lists
from qba_tpu.qsim.stabilizer import build_tableau_run

P, Q = 0.08, 0.03  # channel strengths shared by the tests below


def pflip(p=P, q=Q):
    """Closed-form outcome-bit flip rate: X/Y component (2p/3) XOR the
    readout flip (q)."""
    px = 2.0 * p / 3.0
    return px * (1 - q) + q * (1 - px)


class TestZeroNoiseGating:
    def test_factorized_sampler_unchanged_at_zero(self):
        cfg = QBAConfig(n_parties=5, size_l=64, n_dishonest=1)
        cfg0 = dataclasses.replace(cfg, p_depolarize=0.0, p_measure_flip=0.0)
        key = jax.random.key(9)
        a, qa = generate_lists(cfg, key)
        b, qb = generate_lists(cfg0, key)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(qa), np.asarray(qb))

    def test_tableau_build_at_zero_is_noiseless_build(self):
        circ = gen_q_corr_circuit(3, 2)
        run0 = build_tableau_run(circ.n_qubits, tuple(circ.ops), circ.n_params)
        runz = build_tableau_run(
            circ.n_qubits, tuple(circ.ops), circ.n_params, 0.0, 0.0
        )
        params = jnp.zeros((circ.n_params,), jnp.int32)
        for seed in range(4):
            k = jax.random.key(seed)
            np.testing.assert_array_equal(
                np.asarray(run0(k, params)), np.asarray(runz(k, params))
            )


class TestStabilizerBitIdentity:
    def test_gf2_batch_matches_per_shot_tableau_under_noise(self):
        # The two stabilizer engines consume the same noise_draws per
        # shot key — their bit-identity contract extends to noisy runs.
        circ = gen_q_corr_circuit(3, 2)
        n = circ.n_qubits
        run1 = build_tableau_run(n, tuple(circ.ops), circ.n_params, P, Q)
        runb = build_gf2_tableau_run_batch(
            n, tuple(circ.ops), circ.n_params, P, Q
        )
        keys = jax.random.split(jax.random.key(17), 32)
        params = jax.random.randint(
            jax.random.key(18), (32, circ.n_params), 0, 2, dtype=jnp.int32
        )
        batch = runb(keys, params)
        single = jax.vmap(run1)(keys, params)
        np.testing.assert_array_equal(np.asarray(batch), np.asarray(single))

    def test_protocol_list_paths_bit_identical_under_noise(self):
        cfg = QBAConfig(
            n_parties=3, size_l=16, n_dishonest=1,
            p_depolarize=P, p_measure_flip=Q,
        )
        key = jax.random.key(4)
        la, qa = generate_lists_stabilizer(cfg, key)
        lb, qb = generate_lists_dense(cfg, key, impl="stabilizer")
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        np.testing.assert_array_equal(np.asarray(qa), np.asarray(qb))


class TestChannelLaws:
    def test_noise_perturbs_but_stays_in_value_domain(self):
        cfg = QBAConfig(
            n_parties=5, size_l=256, n_dishonest=1,
            p_depolarize=P, p_measure_flip=Q,
        )
        key = jax.random.key(12)
        noisy, _ = generate_lists(cfg, key)
        clean, _ = generate_lists(
            dataclasses.replace(cfg, p_depolarize=0.0, p_measure_flip=0.0),
            key,
        )
        noisy, clean = np.asarray(noisy), np.asarray(clean)
        assert ((noisy >= 0) & (noisy < cfg.w)).all()
        assert (noisy != clean).any()

    def test_classical_reduction_flip_rate(self):
        flips = np.asarray(
            classical_flips_shots(jax.random.key(3), 4000, 16, P, Q)
        )
        rate = flips.mean()
        exp = pflip()
        # Bernoulli CI at 64k draws: ~4 sigma half-width below.
        assert abs(rate - exp) < 4.5 * np.sqrt(exp * (1 - exp) / flips.size)

    def test_flip_ints_consistent_with_flip_vector(self):
        # The packed-int form is exactly the bit-vector form of the same
        # key, big-endian — the factorized sampler and the dense engines
        # realize one channel, not two.
        key = jax.random.key(5)
        ints = np.asarray(classical_flip_ints(key, (), 8, P, Q))
        vec = np.asarray(classical_flips(key, 8, P, Q))
        assert ints == int("".join(map(str, vec)), 2)

    def test_noise_draw_components_are_valid_paulis(self):
        bx, bz, mflip = noise_draws(jax.random.key(1), 5000, P, Q)
        bx, bz, mflip = (np.asarray(v) for v in (bx, bz, mflip))
        assert set(np.unique(bx)) <= {0, 1}
        assert set(np.unique(bz)) <= {0, 1}
        # P(any Pauli) = p, split uniformly over X/Y/Z.
        any_pauli = (bx | bz).mean()
        assert abs(any_pauli - P) < 4.5 * np.sqrt(P * (1 - P) / bx.size)
        assert abs(mflip.mean() - Q) < 4.5 * np.sqrt(Q * (1 - Q) / bx.size)

    @pytest.mark.slow
    def test_dense_vs_stabilizer_distributional_under_noise(self):
        # Classical reduction (dense) vs tableau-phase injection
        # (stabilizer): exact realizations of the same channel, so the
        # outcome-pattern histograms must agree up to sampling noise
        # (two-sample chi-square at significance 1e-4).
        circ = gen_q_corr_circuit(2, 2)  # 6 qubits, 64 patterns
        shots = 4096
        params = jnp.asarray([0, 1, 1, 0], jnp.int32)
        run_d = circ.compile_shots("xla", P, Q)
        run_s = circ.compile_shots("stabilizer", P, Q)
        bits_d = np.asarray(run_d(jax.random.key(40), shots, params))
        bits_s = np.asarray(run_s(jax.random.key(41), shots, params))
        weights = 1 << np.arange(circ.n_qubits - 1, -1, -1)
        pats_d = bits_d @ weights
        pats_s = bits_s @ weights
        table = np.stack([
            np.bincount(pats_d, minlength=64),
            np.bincount(pats_s, minlength=64),
        ])
        table = table[:, table.sum(axis=0) > 0]
        assert stats.chi2_contingency(table).pvalue > 1e-4


class TestEndToEnd:
    def test_noise_flows_through_trial_and_degrades_agreement(self):
        # All-honest runs succeed deterministically when noiseless; under
        # heavy readout noise the parties' lists decohere and the
        # success rate must drop measurably.
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=0, trials=32,
                        seed=2)
        from qba_tpu.backends.jax_backend import run_trials, trial_keys

        clean = run_trials(cfg, trial_keys(cfg))
        assert float(clean.success_rate) == 1.0
        noisy_cfg = dataclasses.replace(
            cfg, p_depolarize=0.3, p_measure_flip=0.2
        )
        noisy = run_trials(noisy_cfg, trial_keys(noisy_cfg))
        assert float(noisy.success_rate) < 1.0
