"""qsimov-shaped compat API (qba_tpu.qsim.compat).

Builds the protocol's circuits through the reference's call shapes
(``QGate(size, 0, name)`` / ``QCircuit`` / ``MEASURE`` with outputs /
``Drewom().execute(circ)[0]``, ``tfg.py:17-80``) and checks the §2.6
closed-form output properties on the results.
"""

import pytest

from qba_tpu.qsim import Drewom, QCircuit, QGate


def build_nq_circuit(n_parties: int, n_qubits: int) -> QCircuit:
    """Reference-style construction of the not-Q-correlated circuit
    (H on every qubit of groups 1..n, then CNOT copying group 1 onto
    group 0; tfg.py:15-22,56-65) via the compat API."""
    size = (n_parties + 1) * n_qubits
    g = QGate(size, 0, "notQCorr")
    for q in range(n_qubits, size):
        g.add_operation("H", targets=q)
    for b in range(n_qubits):
        g.add_operation("X", targets=b, controls=n_qubits + b)
    c = QCircuit(size, size, "NQCorrCircuit")
    c.add_operation(g)
    for i in range(size):
        c.add_operation("MEASURE", targets=i, outputs=i)
    return c


def group_values(bits, n_parties: int, n_qubits: int) -> list[int]:
    """Decode each party group's bits (big-endian) into an int."""
    vals = []
    for p in range(n_parties + 1):
        v = 0
        for b in bits[p * n_qubits:(p + 1) * n_qubits]:
            v = (v << 1) | b
        vals.append(v)
    return vals


class TestCompatAPI:
    def test_nq_circuit_closed_form(self):
        # Not-Q-correlated: group 0 == group 1 in every shot (§2.6).
        n_parties, n_qubits = 3, 2
        circ = build_nq_circuit(n_parties, n_qubits)
        shots = Drewom(seed=1).execute(circ, shots=16)
        assert len(shots) == 16
        groups = [group_values(s, n_parties, n_qubits) for s in shots]
        assert all(g[0] == g[1] for g in groups)
        # Other groups are i.i.d. uniform; 16 shots of 3 values in [0,4)
        # are essentially never all identical.
        assert len({tuple(g) for g in groups}) > 1

    def test_q_circuit_closed_form(self):
        # Q-correlated with a fixed permutation: H on group 0, X-encode
        # perm[i-1] into group i, CNOT group 0 onto all (tfg.py:25-40).
        n_parties, n_qubits = 3, 2
        size = (n_parties + 1) * n_qubits
        perm = [2, 3, 1]
        g = QGate(size, 0, "qCorr")
        for b in range(n_qubits):
            g.add_operation("H", targets=b)
        for i in range(1, n_parties + 1):
            for b in range(n_qubits):
                if (perm[i - 1] >> (n_qubits - 1 - b)) & 1:
                    g.add_operation("X", targets=i * n_qubits + b)
        for i in range(1, n_parties + 1):
            for b in range(n_qubits):
                g.add_operation("X", targets=i * n_qubits + b, controls=b)
        circ = QCircuit(size, size, "QCorrCircuit")
        circ.add_operation(g)
        for i in range(size):
            circ.add_operation("MEASURE", targets=i, outputs=i)

        for bits in Drewom(seed=2).execute(circ, shots=8):
            vals = group_values(bits, n_parties, n_qubits)
            # group i = r XOR perm[i-1]: all four values pairwise distinct.
            assert len(set(vals)) == n_parties + 1
            r = vals[0]
            assert vals[1:] == [r ^ p for p in perm]

    def test_measure_subset_and_output_order(self):
        c = QCircuit(2, 2, "sub")
        c.add_operation("X", targets=1)
        c.add_operation("MEASURE", targets=1, outputs=0)
        [bits] = Drewom().execute(c)
        assert bits == [1]

    def test_program_cache_reused(self):
        d = Drewom(seed=0)
        c = build_nq_circuit(2, 1)
        d.execute(c, shots=2)
        d.execute(build_nq_circuit(2, 1), shots=2)
        assert len(d._programs) == 1

    def test_rng_advances_between_calls(self):
        # Stateful executor RNG: repeated executes draw fresh samples.
        d = Drewom(seed=3)
        c = build_nq_circuit(3, 2)
        seen = {tuple(d.execute(c)[0]) for _ in range(12)}
        assert len(seen) > 1

    def test_api_validation(self):
        with pytest.raises(ValueError):
            QGate(4, 1)  # ancilla unsupported
        c = QCircuit(2)
        with pytest.raises(ValueError):
            c.add_operation("MEASURE")  # no targets
        c.add_operation("MEASURE", targets=0, outputs=0)
        with pytest.raises(ValueError):
            c.add_operation("MEASURE", targets=1, outputs=0)  # slot reuse
        with pytest.raises(ValueError, match="after MEASURE"):
            c.add_operation("X", targets=1)  # mid-circuit measurement
        with pytest.raises(TypeError):
            Drewom().execute("not a circuit")
